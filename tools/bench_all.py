#!/usr/bin/env python
"""Round-over-round benchmark recorder: every north-star config from
BASELINE.md as one JSON line each (bench.py's format), plus a combined
JSON file.

Configs (BASELINE.md "North-star target" reproduction list):
  - resnet50_infer   bench.py headline (bs32 inference, vs K80 baseline)
  - resnet50_train   bf16 bs128 NHWC train via Module._step_scan
  - lstm_ptb         word-LM tokens/s train (example/rnn/word_lm)
  - sparse_fm        factorization machine samples/s (example/sparse)
  - wide_deep        wide&deep samples/s (example/sparse)
  - multichip        SPMD weak-scaling efficiency on a forced 8-device
                     CPU mesh, with the shardprof collective inventory
                     (bytes/step by kind), overlap_fraction, and the
                     sharding-audit summary attached to the record

Usage:
    python tools/bench_all.py                 # all configs, TPU default
    python tools/bench_all.py --only lstm_ptb
    python tools/bench_all.py --out BENCH_EXTRA.json

The driver's contract (ONE line from bench.py) is untouched — this tool
is the per-round regression record the VERDICT asked to keep."""
from __future__ import annotations

import argparse
import json
import os
import re
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# A100-class targets from BASELINE.md / driver metadata where defined;
# otherwise the round-3 recorded numbers act as the regression floor.
BASELINES = {
    "resnet50_infer": 109.0,       # K80 img/s (BASELINE.md)
    "resnet50_train": 2900.0,      # A100-class img/s/chip target
    "lstm_ptb": 14400.0,           # reference 4x K80 tokens/s word_lm
    # Round-3 recorded bf16 = regression floor. Config note (ADVICE r4):
    # recorded BEFORE round 4 added elementwise clip_gradient=0.25 to the
    # measured update path (the reference recipe clips global norm); the
    # clipped config re-measured 405k tokens/s, so the floor is
    # conservative and ratios vs it remain meaningful.
    "lstm_ptb_bf16": 87104.0,
    "sparse_fm": None,
    "wide_deep": None,
}


def _run(cmd, timeout=3600):
    t0 = time.time()
    r = subprocess.run(cmd, cwd=REPO, capture_output=True, text=True,
                       timeout=timeout)
    return r, time.time() - t0


def bench_resnet50_infer():
    # --infer-only: bench.py's full run now appends the TRAIN line last
    # (the driver's north-star record); this config wants just inference
    r, _ = _run([sys.executable, "bench.py", "--infer-only"])
    lines = [json.loads(l) for l in r.stdout.splitlines()
             if l.startswith("{")]
    for rec in lines:
        if rec.get("metric") == "resnet50_infer_imgs_per_sec_bs32":
            return rec
    raise RuntimeError("bench.py produced no inference record:\n"
                       + r.stdout[-2000:] + r.stderr[-2000:])


def _parse_phase_breakdown(stdout):
    """The last ``train_phase_breakdown`` JSON line a benchmark printed
    (stepprof attribution pass), or None."""
    found = None
    for line in stdout.splitlines():
        line = line.strip()
        if not line.startswith("{"):
            continue
        try:
            rec = json.loads(line)
        except ValueError:
            continue
        if isinstance(rec, dict) and \
                rec.get("metric") == "train_phase_breakdown":
            found = rec
    return found


def bench_resnet50_train():
    r, _ = _run([sys.executable,
                 "examples/image-classification/benchmark.py",
                 "--model", "resnet50_v1", "--batch-size", "128",
                 "--dtype", "bfloat16", "--layout", "NHWC",
                 "--batches-per-dispatch", "30", "--num-calls", "15",
                 "--scan-unroll", "3", "--donate", "--prestack"])
    m = re.search(r"([\d.]+) img/s train", r.stdout)
    if not m:
        raise RuntimeError("train benchmark produced no rate:\n"
                           + r.stdout[-2000:] + r.stderr[-2000:])
    v = float(m.group(1))
    rec = {"metric": "resnet50_train_imgs_per_sec_bf16_bs128",
           "value": v, "unit": "img/s",
           "vs_baseline": round(v / BASELINES["resnet50_train"], 3)}
    # step-time anatomy: p50 share per phase + verdict, so the BENCH
    # history (and bench_gate failures) carry attribution with the rate
    pb = _parse_phase_breakdown(r.stdout)
    if pb:
        rec["phases"] = pb.get("phases") or {}
        rec["verdict"] = pb.get("verdict")
        # run anatomy: goodput fraction + run-state seconds over the
        # attribution window, gated by bench_gate as
        # train_goodput_fraction (higher is better) with a state-
        # seconds delta line on regression
        if isinstance(pb.get("goodput_fraction"), (int, float)):
            rec["goodput_fraction"] = pb["goodput_fraction"]
        if isinstance(pb.get("run_states"), dict):
            rec["run_states"] = pb["run_states"]
        # memory anatomy: worst-device peak + scope waterfall, gated by
        # bench_gate as peak_hbm_bytes (lower-better ceiling) with a
        # bench_gate_memory per-scope delta line on regression
        if isinstance(pb.get("peak_hbm_bytes"), (int, float)):
            rec["peak_hbm_bytes"] = pb["peak_hbm_bytes"]
        if isinstance(pb.get("memory_scopes"), dict):
            rec["memory_scopes"] = pb["memory_scopes"]
    return rec


def _bench_lstm(dtype):
    r, _ = _run([sys.executable, "examples/rnn/word_lm/benchmark.py",
                 "--dtype", dtype, "--num-calls", "25"])
    m = re.search(r"([\d.]+) tokens/s train", r.stdout)
    if not m:
        raise RuntimeError("lstm benchmark produced no rate:\n"
                           + r.stdout[-2000:] + r.stderr[-2000:])
    v = float(m.group(1))
    suffix = "" if dtype == "float32" else "_bf16"
    base = BASELINES["lstm_ptb" if dtype == "float32" else "lstm_ptb_bf16"]
    return {"metric": "lstm_ptb_tokens_per_sec_bs32" + suffix,
            "value": v, "unit": "tokens/s",
            "vs_baseline": round(v / base, 3)}


def bench_lstm_ptb():
    return _bench_lstm("float32")


def bench_lstm_ptb_bf16():
    return _bench_lstm("bfloat16")


def _bench_sparse(name, script, examples, epochs, extra):
    cmd = [sys.executable, script, "--num-epochs", str(epochs),
           "--num-examples", str(examples)] + extra
    r, dt = _run(cmd)
    m = re.search(r"final val accuracy: ([\d.]+)", r.stdout)
    if r.returncode != 0 or not m:
        raise RuntimeError("%s failed:\n%s" % (name, r.stdout[-1500:]
                                               + r.stderr[-1500:]))
    rate = examples * epochs / dt  # end-to-end incl. compile: a regression
    return {"metric": "%s_samples_per_sec" % name,  # signal, not a peak
            "value": round(rate, 1), "unit": "samples/s",
            "vs_baseline": None, "accuracy": float(m.group(1))}


def bench_sparse_fm():
    return _bench_sparse("sparse_fm",
                         "examples/sparse/factorization_machine/train.py",
                         24000, 3, ["--num-features", "1000"])


def bench_wide_deep():
    return _bench_sparse("wide_deep", "examples/sparse/wide_deep/train.py",
                         12000, 2, ["--num-sparse", "1000"])


def bench_multichip(n_devices=8):
    """The `multichip_scaling_efficiency` record on a forced N-device
    CPU mesh (a subprocess: the device count must be set before jax
    initializes a backend). Carries the communication anatomy —
    collective bytes/step by kind, overlap_fraction, sharding-audit
    summary — so MULTICHIP history gates with attribution."""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    flags = env.get("XLA_FLAGS", "")
    if "host_platform_device_count" not in flags:
        env["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_"
                            "count=%d" % n_devices).strip()
    # the axon TPU sitecustomize overrides JAX_PLATFORMS at interpreter
    # startup, so the child must ALSO drop the plugin's backend factory
    # before any backend initializes (same trick as dryrun_multichip /
    # tests/conftest.py) — the env var alone is too late on a TPU host
    code = ("import jax\n"
            "try:\n"
            "    jax.config.update('jax_platforms', 'cpu')\n"
            "    from jax._src import xla_bridge as _xb\n"
            "    _xb._backend_factories.pop('axon', None)\n"
            "except Exception:\n"
            "    pass\n"
            "import json, __graft_entry__ as g\n"
            "print(json.dumps(g.scaling_efficiency_record(%d)))\n"
            % n_devices)
    r = subprocess.run([sys.executable, "-c", code], cwd=REPO, env=env,
                       capture_output=True, text=True, timeout=1200)
    for line in reversed(r.stdout.splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if rec.get("metric") == "multichip_scaling_efficiency":
                return rec
    raise RuntimeError("multichip bench produced no record:\n"
                       + r.stdout[-1500:] + r.stderr[-1500:])


CONFIGS = {
    "resnet50_infer": bench_resnet50_infer,
    "resnet50_train": bench_resnet50_train,
    "lstm_ptb": bench_lstm_ptb,
    "lstm_ptb_bf16": bench_lstm_ptb_bf16,
    "sparse_fm": bench_sparse_fm,
    "wide_deep": bench_wide_deep,
    "multichip": bench_multichip,
}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--only", choices=sorted(CONFIGS), default=None)
    ap.add_argument("--out", default=None,
                    help="also write the combined records to this JSON file")
    ap.add_argument("--round", type=int, default=None,
                    help="build-round stamp recorded with the results so "
                         "BENCH_EXTRA history stays diffable")
    args = ap.parse_args()
    names = [args.only] if args.only else list(CONFIGS)
    records = []
    for name in names:
        try:
            rec = CONFIGS[name]()
        except Exception as e:  # record the failure, keep benching
            rec = {"metric": name, "value": None, "unit": None,
                   "vs_baseline": None, "error": str(e)[:500]}
        if args.round is not None:
            rec["round"] = args.round
        print(json.dumps(rec), flush=True)
        records.append(rec)
    if args.out:
        with open(args.out, "w") as fh:
            json.dump(records, fh, indent=1)


if __name__ == "__main__":
    main()
