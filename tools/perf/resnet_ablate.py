#!/usr/bin/env python
"""Ablate full ResNet-50 bf16 bs128 train throughput on the chip.

Variants:
  base      — NCHW, BN stats in f32 (matches framework path; sanity vs
              examples/image-classification/benchmark.py)
  bnbf16    — BN stats computed in bf16
  s2d       — space-to-depth stem: 7x7s2 conv on 3 channels replaced by an
              equivalent 4x4 conv on a (N,56,56,48) space-to-depth input
              (the MLPerf-TPU trick: packs the 3-channel stem onto the MXU)
  fwdonly   — inference forward only (locates fwd:bwd split)

Sync discipline: K steps in fori_loop, calls chained through the carry,
one scalar read at the end (bench.py rationale).
"""
import sys
import time

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

LAYERS = [3, 4, 6, 3]
CMID = [64, 128, 256, 512]
COUT = [256, 512, 1024, 2048]


def build(variant):
    bn_f32 = variant not in ("bnbf16",)
    bn_mixed = variant in ("bnmixed", "combo")
    s2d = variant in ("s2d", "combo")
    rng = np.random.RandomState(0)

    def mk(shape):
        return jnp.asarray(rng.randn(*shape).astype(np.float32) * 0.05,
                           jnp.bfloat16)

    params = []

    def add_conv(k, cin, cout):
        params.append(mk((cout, cin, k, k)))
        params.append(jnp.ones((cout,), jnp.bfloat16))
        params.append(jnp.zeros((cout,), jnp.bfloat16))
        return len(params) - 3

    def conv(x, w, stride=1):
        dn = lax.conv_dimension_numbers(x.shape, w.shape,
                                        ("NCHW", "OIHW", "NCHW"))
        p = (w.shape[2] - 1) // 2
        return lax.conv_general_dilated(x, w, (stride, stride),
                                        [(p, p), (p, p)],
                                        dimension_numbers=dn)

    def bn(x, g, b, relu=True):
        if bn_mixed:
            # stats accumulate in f32 (cast fuses into the reductions,
            # no f32 copy of x materializes); elementwise stays bf16 as a
            # single scale/shift multiply-add
            m = jnp.mean(x, (0, 2, 3), dtype=jnp.float32)
            m2 = jnp.mean(jnp.square(x.astype(jnp.float32)), (0, 2, 3))
            v = m2 - m * m
            scale = g.astype(jnp.float32) * lax.rsqrt(v + 1e-5)
            shift = b.astype(jnp.float32) - m * scale
            y = x * scale.astype(x.dtype).reshape(1, -1, 1, 1) \
                + shift.astype(x.dtype).reshape(1, -1, 1, 1)
        else:
            x32 = x.astype(jnp.float32) if bn_f32 else x
            m = jnp.mean(x32, (0, 2, 3))
            v = jnp.var(x32, (0, 2, 3))
            y = (x32 - m.reshape(1, -1, 1, 1)) * lax.rsqrt(
                v.reshape(1, -1, 1, 1) + 1e-5)
            y = y.astype(x.dtype) * g.reshape(1, -1, 1, 1) \
                + b.reshape(1, -1, 1, 1)
        return jax.nn.relu(y) if relu else y

    if s2d:
        stem = add_conv(4, 48, 64)  # 4x4 on space-to-depth(4) input, stride 1
    else:
        stem = add_conv(7, 3, 64)
    blocks = []
    cin = 64
    for st in range(4):
        stage = []
        for i in range(LAYERS[st]):
            stride = (1 if st == 0 else 2) if i == 0 else 1
            blk = dict(c1=add_conv(1, cin, CMID[st]),
                       c2=add_conv(3, CMID[st], CMID[st]),
                       c3=add_conv(1, CMID[st], COUT[st]),
                       proj=add_conv(1, cin, COUT[st]) if i == 0 else None,
                       stride=stride)
            stage.append(blk)
            cin = COUT[st]
        blocks.append(stage)
    params.append(mk((2048, 1000)))

    def ap(x, idx, stride=1, relu=True, pv=None):
        return bn(conv(x, pv[idx], stride), pv[idx + 1], pv[idx + 2],
                  relu=relu)

    def forward(pv, x):
        if s2d:
            # (N,3,224,224) -> (N,48,56,56): 4x4 blocks into channels
            n = x.shape[0]
            x = x.reshape(n, 3, 56, 4, 56, 4).transpose(0, 1, 3, 5, 2, 4)
            x = x.reshape(n, 48, 56, 56)
            y = ap(x, stem, stride=1, pv=pv)
        else:
            y = ap(x, stem, stride=2, pv=pv)
            y = lax.reduce_window(y, -jnp.inf, lax.max, (1, 1, 3, 3),
                                  (1, 1, 2, 2),
                                  ((0, 0), (0, 0), (1, 1), (1, 1)))
        for stage in blocks:
            for b in stage:
                sc = y if b["proj"] is None else \
                    ap(y, b["proj"], stride=b["stride"], relu=False, pv=pv)
                z = ap(y, b["c1"], pv=pv)
                z = ap(z, b["c2"], stride=b["stride"], pv=pv)
                z = ap(z, b["c3"], relu=False, pv=pv)
                y = jax.nn.relu(z + sc)
        y = jnp.mean(y.astype(jnp.float32), (2, 3)).astype(y.dtype)
        return jnp.dot(y, pv[-1])

    return params, forward


def run(variant, batch=128, k=10, calls=3):
    params, forward = build(variant)
    rng = np.random.RandomState(1)
    x = jnp.asarray(rng.rand(batch, 3, 224, 224).astype(np.float32),
                    jnp.bfloat16)
    yl = jnp.asarray(rng.randint(0, 1000, batch).astype(np.int32))

    if variant == "fwdonly":
        @jax.jit
        def loop(pv, xv, acc0):
            def body(i, acc):
                xi = jnp.roll(xv, i, axis=0)
                return acc + forward(pv, xi).astype(jnp.float32).sum()
            return lax.fori_loop(0, k, body, acc0)

        t0 = time.time()
        float(loop(params, x, jnp.float32(0)))
        print("%s: compiled %.1fs" % (variant, time.time() - t0), flush=True)
        t0 = time.time()
        acc = jnp.float32(0)
        for _ in range(calls):
            acc = loop(params, x, acc)
        float(acc)
        dt = time.time() - t0
        print("%s: %.1f img/s" % (variant, calls * k * batch / dt), flush=True)
        return

    def loss_fn(pv, xv, yv):
        logits = forward(pv, xv).astype(jnp.float32)
        logp = jax.nn.log_softmax(logits, -1)
        return -jnp.mean(jnp.take_along_axis(logp, yv[:, None], 1))

    @jax.jit
    def k_steps(pv, sv, xv, yv):
        def body(i, carry):
            pv, sv, _ = carry
            xi = jnp.roll(xv, i, axis=0)
            loss, g = jax.value_and_grad(loss_fn)(pv, xi, yv)
            sv = [0.9 * s + gg.astype(s.dtype) for s, gg in zip(sv, g)]
            pv = [p - 0.05 * s.astype(p.dtype) for p, s in zip(pv, sv)]
            return pv, sv, loss
        return lax.fori_loop(0, k, body, (pv, sv, jnp.float32(0)))

    momenta = [jnp.zeros_like(p) for p in params]
    t0 = time.time()
    params, momenta, loss = k_steps(params, momenta, x, yl)
    float(loss)
    print("%s: compiled %.1fs" % (variant, time.time() - t0), flush=True)
    t0 = time.time()
    for _ in range(calls):
        params, momenta, loss = k_steps(params, momenta, x, yl)
    float(loss)
    dt = time.time() - t0
    print("%s: %.1f img/s (train bf16 bs%d)"
          % (variant, calls * k * batch / dt, batch), flush=True)


if __name__ == "__main__":
    variants = sys.argv[1:] or ["base", "fwdonly", "bnbf16", "s2d"]
    print(jax.devices(), flush=True)
    for v in variants:
        run(v)
