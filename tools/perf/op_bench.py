#!/usr/bin/env python
"""Per-op performance harness (reference `tests/cpp/operator/coreop_perf.cc`
+ `python/mxnet/test_utils.py:1133 check_speed`): sweeps the hot operator
families at benchmark shapes and prints a per-op microsecond table, plus
one JSON line per op for regression diffing.

Run on the chip (plain `python tools/perf/op_bench.py`) for real numbers,
or `--preset tiny` on CPU for a smoke sweep. Measurement discipline: each
op compiles once (warmup), then N timed iterations end with ONE fence
(`test_utils.check_speed` semantics).

Relay caveat: behind the axon tunnel every dispatch costs ~20ms host-side,
which floors per-iter numbers — read the table RELATIVELY (subtract the
cheapest op's time as the dispatch floor) or run on a directly-attached
chip for absolute microseconds.
"""
from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np


def sweep(preset):
    """(name, symbol-factory, shape-kwargs) per hot op family."""
    import mxnet_tpu as mx
    sym = mx.sym
    t = preset == "tiny"
    B = 4 if t else 32
    C = 8 if t else 64
    HW = 16 if t else 56
    H = 64 if t else 1024
    T = 8 if t else 128
    V = 100 if t else 10000

    d = sym.Variable("data")
    cases = [
        ("Convolution3x3", sym.Convolution(
            d, kernel=(3, 3), num_filter=C, pad=(1, 1), name="conv"),
            {"data": (B, C, HW, HW)}),
        ("Convolution1x1", sym.Convolution(
            d, kernel=(1, 1), num_filter=C, name="conv1"),
            {"data": (B, C, HW, HW)}),
        ("FullyConnected", sym.FullyConnected(d, num_hidden=H, name="fc"),
            {"data": (B, H)}),
        ("BatchNorm", sym.BatchNorm(d, fix_gamma=False, name="bn"),
            {"data": (B, C, HW, HW)}),
        ("Pooling_max", sym.Pooling(d, kernel=(2, 2), stride=(2, 2),
                                    pool_type="max"),
            {"data": (B, C, HW, HW)}),
        ("Activation_relu", sym.Activation(d, act_type="relu"),
            {"data": (B, C, HW, HW)}),
        ("SoftmaxOutput", sym.SoftmaxOutput(d, name="softmax"),
            {"data": (B, V)}),
        ("elemwise_add", d + d * 2.0, {"data": (B, C, HW, HW)}),
        ("sum_reduce", sym.sum(d, axis=(1, 2, 3)), {"data": (B, C, HW, HW)}),
        ("dot", sym.dot(d, sym.Variable("rhs")),
            {"data": (H, H), "rhs": (H, H)}),
        ("Embedding", sym.Embedding(d, sym.Variable("weight"),
                                    input_dim=V, output_dim=C),
            {"data": (B, T), "weight": (V, C)}),
        ("LayerNorm", sym.LayerNorm(d, sym.Variable("gamma"),
                                    sym.Variable("beta")),
            {"data": (B, T, H), "gamma": (H,), "beta": (H,)}),
        ("Dropout", sym.Dropout(d, p=0.5), {"data": (B, T, H)}),
        ("transpose", sym.transpose(d, axes=(0, 2, 1)),
            {"data": (B, T, H)}),
    ]
    return cases


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--preset", choices=["tiny", "bench"], default="bench")
    p.add_argument("-N", type=int, default=20, help="timed iters per op")
    p.add_argument("--typ", choices=["whole", "forward"], default="whole")
    p.add_argument("--json-out", type=str, default=None)
    args = p.parse_args()

    import mxnet_tpu as mx
    from mxnet_tpu.test_utils import check_speed

    ctx = mx.tpu() if mx.context.num_tpus() else mx.cpu()
    rows = []
    hdr = "%-20s %-28s %12s" % ("Op", "Shapes", "us/iter")
    print(hdr)
    print("-" * len(hdr))
    for name, sym, shapes in sweep(args.preset):
        try:
            sec = check_speed(sym, ctx=ctx, N=args.N, typ=args.typ, **shapes)
        except Exception as e:  # keep sweeping; report the failure
            print("%-20s %-28s %12s (%s)" % (name, shapes, "FAIL", e))
            rows.append({"op": name, "error": str(e)})
            continue
        us = sec * 1e6
        print("%-20s %-28s %12.1f"
              % (name, ",".join(str(s) for s in shapes.values()), us))
        rows.append({"op": name, "us_per_iter": round(us, 2),
                     "typ": args.typ, "shapes": {k: list(v)
                                                 for k, v in shapes.items()}})
    for r in rows:
        print(json.dumps({"metric": "op_us", **r}))
    if args.json_out:
        with open(args.json_out, "w") as f:
            json.dump(rows, f, indent=1)


if __name__ == "__main__":
    main()
