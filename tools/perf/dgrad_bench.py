#!/usr/bin/env python
"""Microbenchmark: 1x1-conv input-gradient formulations at ResNet-50
bs128 NHWC shapes (the round-4 attribution table's weak spot — stage-entry
stride-2 dgrads at 6-12 TF/s, 56x56-stage dgrads at 10-23 TF/s).

Per shape, times three formulations of the SAME contraction:
  xla     — jax.vjp through lax.conv_general_dilated (the default path:
            XLA's lhs-dilated conv-transpose emitter)
  pad_dot — interior-pad(dy @ W^T) (round-4's rejected matmul form:
            extra materialized intermediate)
  pallas  — ops.conv_kernels.conv1x1_s2_dgrad (compact matmul + fused
            interleaved store; stride-2 shapes only)
  dot     — dy @ W^T reshaped (stride-1 shapes only)

Measurement: K iterations chained inside ONE jitted lax.scan — the weight
is scaled by a carried scalar that depends on the previous output, so
iterations serialize and CSE can't collapse them; the ~40 ms tunnel
dispatch cost is paid once per timed call, not per iteration.  Best of R
timed calls (the tunnel's bimodal timing, see
docs/perf/resnet50_train_attribution.md).
"""
from __future__ import annotations

import argparse
import functools
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

# (name, Ho/Wo, K=Cout, C=Cin, stride) — bs128 NHWC ResNet-50 dgrad shapes
SHAPES = [
    ("c3_entry_1x1s2", 28, 128, 256, 2),
    ("c3_down_1x1s2", 28, 512, 256, 2),
    ("c4_entry_1x1s2", 14, 256, 512, 2),
    ("c4_down_1x1s2", 14, 1024, 512, 2),
    ("c5_entry_1x1s2", 7, 512, 1024, 2),
    ("c5_down_1x1s2", 7, 2048, 1024, 2),
    ("c2_conv1_1x1s1", 56, 64, 256, 1),
    ("c2_conv3_1x1s1", 56, 256, 64, 1),
    ("c3_conv3_1x1s1", 28, 512, 128, 1),
]


def make_fns(Ho, K, C, stride, dtype):
    """name -> fn(dy, w2) computing dx for this shape."""
    H = stride * Ho
    N = 128

    def conv_fwd(x, w2):
        w4 = w2.reshape(K, 1, 1, C)
        dn = lax.conv_dimension_numbers((N, H, H, C), w4.shape,
                                        ("NHWC", "OHWI", "NHWC"))
        return lax.conv_general_dilated(
            x, w4, window_strides=(stride, stride),
            padding=[(0, 0), (0, 0)], dimension_numbers=dn)

    def xla(dy, w2):
        x = jnp.zeros((N, H, H, C), dtype)
        _, vjp = jax.vjp(lambda d: conv_fwd(d, w2), x)
        return vjp(dy)[0]

    def pad_dot(dy, w2):
        dz = lax.dot_general(dy, w2, (((3,), (0,)), ((), ())),
                             preferred_element_type=jnp.float32).astype(dtype)
        if stride == 1:
            return dz
        pads = [(0, 0, 0),
                (0, H - (2 * (Ho - 1) + 1), 1),
                (0, H - (2 * (Ho - 1) + 1), 1),
                (0, 0, 0)]
        return lax.pad(dz, jnp.zeros((), dtype), pads)

    fns = {"xla": xla, "pad_dot": pad_dot}
    if stride == 2:
        from mxnet_tpu.ops.conv_kernels import conv1x1_s2_dgrad
        fns["pallas"] = lambda dy, w2: conv1x1_s2_dgrad(dy, w2, H, H)
    else:
        fns["dot"] = pad_dot
        del fns["pad_dot"]
    return fns


def time_fn(fn, dy, w2, iters, rounds, calls=6):
    """Per-op seconds: `calls` chained scan dispatches of `iters`
    iterations each, ONE scalar readback at the end — the ~90 ms tunnel
    sync cost amortizes over iters*calls executions (same discipline as
    bench.py; at 30 iters/1 call it floored every op at ~3 ms/iter)."""
    @jax.jit
    def run(c, dy, w2):
        # dy/w2 as ARGUMENTS: closing over them bakes multi-MB constants
        # into the MLIR payload (25 MB for the c3 shapes), which the
        # remote compile helper rejects
        def body(c, _):
            dx = fn(dy, (w2 * c).astype(w2.dtype))
            # the carry must consume ALL of dx: a single-element read
            # lets XLA slice straight through the conv/dot (slice-of-conv
            # -> tiny conv) and the "measurement" times dead code.  The
            # full-array sum costs one extra dx read — identical across
            # variants of the same shape.
            return 1.0 + jnp.sum(dx.astype(jnp.float32)) * 1e-30, ()
        return lax.scan(body, c, None, length=iters)[0]

    float(run(jnp.float32(1.0), dy, w2))  # compile + warm
    best = float("inf")
    for _ in range(rounds):
        t0 = time.perf_counter()
        c = jnp.float32(1.0)
        for _ in range(calls):
            c = run(c, dy, w2)
        float(c)
        best = min(best, time.perf_counter() - t0)
    return best / (iters * calls)


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--iters", type=int, default=100)
    p.add_argument("--rounds", type=int, default=2)
    p.add_argument("--calls", type=int, default=6)
    p.add_argument("--dtype", default="bfloat16")
    p.add_argument("--only", default=None, help="substring filter on shape")
    p.add_argument("--variants", default=None,
                   help="comma list: xla,pad_dot,pallas,dot")
    args = p.parse_args()

    dtype = jnp.dtype(args.dtype)
    rng = np.random.RandomState(0)
    N = 128
    for name, Ho, K, C, stride in SHAPES:
        if args.only and args.only not in name:
            continue
        dy = jnp.asarray(rng.randn(N, Ho, Ho, K), dtype)
        w2 = jnp.asarray(rng.randn(K, C), dtype)
        gflop = 2.0 * N * Ho * Ho * K * C / 1e9
        for vname, fn in make_fns(Ho, K, C, stride, dtype).items():
            if args.variants and vname not in args.variants.split(","):
                continue
            try:
                sec = time_fn(fn, dy, w2, args.iters, args.rounds,
                              args.calls)
            except Exception as e:
                print(json.dumps({"shape": name, "variant": vname,
                                  "error": str(e)[:2000]}), flush=True)
                continue
            print(json.dumps({
                "shape": name, "variant": vname,
                "us": round(sec * 1e6, 1),
                "tf_s": round(gflop / sec / 1e3, 1)}), flush=True)


if __name__ == "__main__":
    main()
