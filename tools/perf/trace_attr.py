#!/usr/bin/env python
"""Per-step conv/op attribution from an XPlane trace: joins each XLA-Ops
event's metadata (flops, bytes_accessed, output shape, jax tf_op path)
into a per-step table with achieved TF/s and GB/s — the instrument behind
docs/perf/resnet50_train_attribution.md, automated (round 4 did this join
by hand against the compiled HLO).

Usage:
    python tools/perf/trace_attr.py TRACE_DIR --steps 150 [--top 40]
            [--filter conv] [--json out.json]

--steps: total train steps the trace covers (calls x batches-per-dispatch);
per-step ms = sum over an op's unroll siblings / steps.  Ops are grouped by
(tf_op, output shape): unroll copies of the same logical op land together.
"""
from __future__ import annotations

import argparse
import collections
import json
import os
import re
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

from mxnet_tpu.xplane import find_xplane_files, parse_xspace


def collect(logdir, line_name="XLA Ops"):
    rows = []
    for path in find_xplane_files(logdir):
        for plane in parse_xspace(path):
            if "TPU" not in plane.name and "Device" not in plane.name:
                continue
            for line in plane.lines:
                if line.name != line_name:
                    continue
                for ev in line.events:
                    rows.append(ev)
    return rows


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("logdir")
    ap.add_argument("--steps", type=int, required=True,
                    help="total train steps covered by the trace")
    ap.add_argument("--top", type=int, default=40)
    ap.add_argument("--filter", default=None,
                    help="substring filter on the tf_op path")
    ap.add_argument("--json", default=None)
    args = ap.parse_args()

    groups = collections.defaultdict(
        lambda: {"ms": 0.0, "count": 0, "flops": 0, "bytes": 0,
                 "names": set(), "source": ""})
    for ev in collect(args.logdir):
        if ev.name.startswith("while"):
            continue  # container; its body ops are separate events
        st = ev.stats
        tf_op = str(st.get("tf_op", ev.name))
        shape = str(st.get("shape_with_layout", ""))
        shape = re.sub(r"\{[^}]*\}", "", shape)     # drop layout annotations
        key = (tf_op, shape)
        g = groups[key]
        g["ms"] += ev.duration_ps / 1e9
        g["count"] += 1
        g["flops"] += int(st.get("flops", 0) or 0)
        g["bytes"] += int(st.get("bytes_accessed", 0) or 0)
        g["names"].add(re.sub(r"\.\d+$", "", ev.name))
        g["source"] = str(st.get("source", ""))

    rows = []
    for (tf_op, shape), g in groups.items():
        if args.filter and args.filter not in tf_op:
            continue
        ms_step = g["ms"] / args.steps
        sec = g["ms"] / 1e3
        rows.append({
            "tf_op": tf_op.split("/")[-1].rstrip(":"),
            "path": tf_op,
            "shape": shape,
            "fusion": "+".join(sorted(g["names"])),
            "ms_per_step": round(ms_step, 3),
            "tf_s": round(g["flops"] / sec / 1e12, 1) if sec else 0.0,
            "gb_s": round(g["bytes"] / sec / 1e9, 0) if sec else 0.0,
            "count": g["count"],
        })
    rows.sort(key=lambda r: -r["ms_per_step"])

    total = sum(r["ms_per_step"] for r in rows)
    hdr = "%-34s %-36s %9s %7s %7s" % ("op", "out shape", "ms/step",
                                       "TF/s", "GB/s")
    print(hdr)
    print("-" * len(hdr))
    for r in rows[:args.top]:
        name = ("bwd:" if "transpose(jvp" in r["path"] else "") + r["tf_op"]
        print("%-34s %-36s %9.3f %7.1f %7.0f"
              % (name[:34], r["shape"][:36], r["ms_per_step"],
                 r["tf_s"], r["gb_s"]))
    print("-" * len(hdr))
    print("%-34s %45.3f ms/step over %d rows" % ("TOTAL (excl. while)",
                                                 total, len(rows)))
    if args.json:
        with open(args.json, "w") as f:
            json.dump(rows, f, indent=1)


if __name__ == "__main__":
    main()
