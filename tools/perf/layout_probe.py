#!/usr/bin/env python
"""Probe: does NHWC beat NCHW for a ResNet-style conv stack on this chip?

Runs a reduced-depth bottleneck ResNet (stem + one bottleneck block per
stage, same shapes as ResNet-50's stages) fwd+bwd+SGD in bf16 at batch 128
under both layouts, plus a bf16 matmul peak-FLOPs sanity line. Reduced depth
keeps tunnel compile time tolerable while preserving the layout question.

Sync discipline (see bench.py): chain K steps in a fori_loop, chain calls
through the params carry, one scalar read at the end.
"""
import time

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax


def matmul_peak():
    n = 8192
    a = jnp.zeros((n, n), jnp.bfloat16)
    b = jnp.zeros((n, n), jnp.bfloat16)

    @jax.jit
    def loop(a, b):
        def body(i, acc):
            return jnp.dot(acc, b, preferred_element_type=jnp.bfloat16)
        return lax.fori_loop(0, 20, body, a)

    r = loop(a, b)
    float(r[0, 0].astype(jnp.float32))
    t0 = time.time()
    r = loop(a, b)
    float(r[0, 0].astype(jnp.float32))
    dt = time.time() - t0
    tflops = 20 * 2 * n**3 / dt / 1e12
    print("matmul bf16 %dx%d: %.1f TFLOP/s" % (n, n, tflops), flush=True)


def make_stack(layout):
    """Reduced ResNet-50: stem + 1 bottleneck per stage (4 stages)."""
    nhwc = layout == "NHWC"
    dn_l = ("NHWC", "HWIO", "NHWC") if nhwc else ("NCHW", "OIHW", "NCHW")
    caxis = 3 if nhwc else 1

    def conv(x, w, stride=1):
        dn = lax.conv_dimension_numbers(x.shape, w.shape, dn_l)
        k = w.shape[0] if nhwc else w.shape[2]
        p = (k - 1) // 2
        return lax.conv_general_dilated(
            x, w, (stride, stride), [(p, p), (p, p)], dimension_numbers=dn)

    def bn_relu(x, g, b):
        red = tuple(i for i in range(4) if i != caxis)
        sh = tuple(-1 if i == caxis else 1 for i in range(4))
        x32 = x.astype(jnp.float32)
        m = jnp.mean(x32, red)
        v = jnp.var(x32, red)
        y = (x32 - m.reshape(sh)) * lax.rsqrt(v.reshape(sh) + 1e-5)
        return jax.nn.relu(y.astype(x.dtype) * g.reshape(sh) + b.reshape(sh))

    def wshape(k, cin, cout):
        return (k, k, cin, cout) if nhwc else (cout, cin, k, k)

    rng = np.random.RandomState(0)

    def mk(shape):
        return jnp.asarray(rng.randn(*shape).astype(np.float32) * 0.05,
                           jnp.bfloat16)

    params = []

    def add_conv(k, cin, cout):
        params.append(mk(wshape(k, cin, cout)))
        params.append(jnp.ones((cout,), jnp.bfloat16))
        params.append(jnp.zeros((cout,), jnp.bfloat16))
        return len(params) - 3

    stem = add_conv(7, 3, 64)
    blocks = []
    cin = 64
    for stage, (cmid, cout, stride) in enumerate(
            [(64, 256, 1), (128, 512, 2), (256, 1024, 2), (512, 2048, 2)]):
        b = dict(c1=add_conv(1, cin, cmid), c2=add_conv(3, cmid, cmid),
                 c3=add_conv(1, cmid, cout), proj=add_conv(1, cin, cout),
                 stride=stride)
        blocks.append(b)
        cin = cout
    fc = mk((2048, 1000))
    params.append(fc)

    def apply_conv(x, pv, idx, stride=1, relu=True):
        y = conv(x, pv[idx], stride)
        g, b = pv[idx + 1], pv[idx + 2]
        if relu:
            return bn_relu(y, g, b)
        red = tuple(i for i in range(4) if i != caxis)
        sh = tuple(-1 if i == caxis else 1 for i in range(4))
        x32 = y.astype(jnp.float32)
        m = jnp.mean(x32, red)
        v = jnp.var(x32, red)
        out = (x32 - m.reshape(sh)) * lax.rsqrt(v.reshape(sh) + 1e-5)
        return out.astype(y.dtype) * g.reshape(sh) + b.reshape(sh)

    def forward(pv, x):
        y = apply_conv(x, pv, stem, stride=2)
        window = (1, 3, 3, 1) if nhwc else (1, 1, 3, 3)
        strides = (1, 2, 2, 1) if nhwc else (1, 1, 2, 2)
        pad = ((0, 0), (1, 1), (1, 1), (0, 0)) if nhwc else \
            ((0, 0), (0, 0), (1, 1), (1, 1))
        y = lax.reduce_window(y, -jnp.inf, lax.max, window, strides, pad)
        for b in blocks:
            sc = apply_conv(y, pv, b["proj"], stride=b["stride"], relu=False)
            y = apply_conv(y, pv, b["c1"])
            y = apply_conv(y, pv, b["c2"], stride=b["stride"])
            y = apply_conv(y, pv, b["c3"], relu=False)
            y = jax.nn.relu(y + sc)
        red = (1, 2) if nhwc else (2, 3)
        y = jnp.mean(y.astype(jnp.float32), red).astype(y.dtype)
        return jnp.dot(y, pv[-1])

    return params, forward


def bench_layout(layout, batch=128, k=10, calls=3):
    params, forward = make_stack(layout)
    shape = (batch, 224, 224, 3) if layout == "NHWC" else (batch, 3, 224, 224)
    rng = np.random.RandomState(1)
    x = jnp.asarray(rng.rand(*shape).astype(np.float32), jnp.bfloat16)
    y = jnp.asarray(rng.randint(0, 1000, batch).astype(np.int32))

    def loss_fn(pv, xv, yv):
        logits = forward(pv, xv).astype(jnp.float32)
        logp = jax.nn.log_softmax(logits, -1)
        return -jnp.mean(jnp.take_along_axis(logp, yv[:, None], 1))

    @jax.jit
    def k_steps(pv, xv, yv):
        def body(i, carry):
            pv, _ = carry
            xi = jnp.roll(xv, i, axis=0)
            loss, g = jax.value_and_grad(loss_fn)(pv, xi, yv)
            pv = [p - 0.01 * gg.astype(p.dtype) for p, gg in zip(pv, g)]
            return pv, loss
        return lax.fori_loop(0, k, body, (pv, jnp.float32(0)))

    t0 = time.time()
    params, loss = k_steps(params, x, y)
    float(loss)
    print("%s: compiled in %.1fs" % (layout, time.time() - t0), flush=True)
    t0 = time.time()
    for _ in range(calls):
        params, loss = k_steps(params, x, y)
    float(loss)
    dt = time.time() - t0
    rate = calls * k * batch / dt
    print("%s: %.1f img/s (reduced-depth resnet bf16 bs%d)"
          % (layout, rate, batch), flush=True)
    return rate


if __name__ == "__main__":
    print(jax.devices(), flush=True)
    matmul_peak()
    r_nchw = bench_layout("NCHW")
    r_nhwc = bench_layout("NHWC")
    print("NHWC/NCHW speedup: %.3f" % (r_nhwc / r_nchw), flush=True)
