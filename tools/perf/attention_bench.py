#!/usr/bin/env python
"""Long-context attention benchmark: the Pallas flash-attention kernel
(`contrib.flash_attention`, ops/pallas_kernels.py) at sequence lengths the
reference cannot express (its attention materializes the full T x T score
matrix; 32k x 32k f32 scores = 4 GB per head — OOM long before this).

Reports sustained attention TFLOP/s per sequence length with the chained
single-readback discipline (bench.py rationale). FLOPs = 4*B*H*T^2*D
(QK^T + PV, 2 FLOPs/MAC each); causal halves it."""
from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--seq-lens", nargs="+", type=int,
                   default=[4096, 16384, 32768])
    p.add_argument("--heads", type=int, default=8)
    p.add_argument("--head-dim", type=int, default=128)
    p.add_argument("--batch", type=int, default=1)
    p.add_argument("--dtype", default="bfloat16",
                   choices=["float32", "bfloat16"])
    p.add_argument("--causal", action=argparse.BooleanOptionalAction,
                   default=True)
    p.add_argument("--iters", type=int, default=8)
    args = p.parse_args()

    import jax
    import jax.numpy as jnp
    from jax import lax

    import mxnet_tpu as mx
    from mxnet_tpu.ops.pallas_kernels import flash_attention

    ctx = mx.tpu() if mx.context.num_tpus() else mx.cpu()
    dev = ctx.jax_device()
    B, H, D = args.batch, args.heads, args.head_dim

    for T in args.seq_lens:
        rng = np.random.RandomState(0)
        q, k, v = (jax.device_put(
            (rng.randn(B, T, H, D) * 0.05).astype(args.dtype), dev)
            for _ in range(3))

        iters = args.iters

        @jax.jit
        def loop(q, k, v, acc0):
            def body(i, acc):
                qi = jnp.roll(q, i, axis=1)  # data-dependent on i
                o = flash_attention(qi, k, v, causal=args.causal)
                return acc + o.ravel()[0].astype(jnp.float32)
            return lax.fori_loop(0, iters, body, acc0)

        # warm both accumulator placements (see benchmark_score.py)
        acc = loop(q, k, v, jnp.float32(0))
        float(loop(q, k, v, acc))
        t0 = time.time()
        acc = jnp.float32(0)
        for _ in range(2):
            acc = loop(q, k, v, acc)
        float(acc)
        dt_s = time.time() - t0
        n = 2 * iters
        flops = 4.0 * B * H * T * T * D * (0.5 if args.causal else 1.0)
        tflops = flops * n / dt_s / 1e12
        ms = dt_s / n * 1e3
        print("T=%6d  %s  causal=%s: %7.2f ms/attention  %6.1f TFLOP/s"
              % (T, args.dtype, args.causal, ms, tflops), flush=True)


if __name__ == "__main__":
    main()
