#!/usr/bin/env python
"""Serving load generator: closed- and open-loop benchmarks against the
dynamic-batching inference engine (`mxnet_tpu/serving/`).

Two load models, because they answer different questions:

- **closed loop** (``--mode closed``): N client threads, each holding at
  most one request in flight (submit, block on the result, repeat).
  Measures sustainable throughput under coordinated omission-free
  latency — the classic "how fast can K users go" number.
- **open loop** (``--mode open``): requests fire at a fixed arrival rate
  regardless of completions (``--qps``), the way real traffic arrives.
  Latency percentiles under an open load expose queueing delay the
  closed loop hides; shed counts expose where backpressure engages.

Reports throughput + p50/p95/p99 and writes BENCH-style JSON metric
lines ({"metric", "value", "unit", ...}) — the same shape bench.py
emits, so ``python bench.py --serve`` embeds these records and
``tools/bench_gate.py`` can gate them (``--metric
serving_closed_rps``, and the lower-is-better p99 latency gate on
``serving_closed_p99_ms``).

Every generated request carries a trace id (``rid=`` into the engine,
``X-Request-Id`` over HTTP), so a bench run's tail is attributable:
the in-process paths reset `serving.reqtrace` per loop and attach the
p99 phase-share breakdown (+ verdict) to the p99 metric line — the
serving analog of the TRAIN record's ``"phases"`` field that
`bench_gate` prints as a delta on regression.

Default target is a built-in small MLP engine (CPU-friendly, no files);
point it at an exported model with ``--symbol/--params/--input`` or at
a RUNNING server with ``--url http://host:port`` (closed loop only —
open-loop HTTP would measure the client's connection churn, not the
engine).
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)


def build_demo_engine(config=None, ctx=None):
    """A small MLP engine over random weights: enough compute to batch
    meaningfully, small enough to warm-compile in seconds on CPU.
    Returns ``(engine, input_name, example_shape)``."""
    import mxnet_tpu as mx
    from mxnet_tpu.serving import InferenceEngine

    data = mx.sym.Variable("data")
    h = mx.sym.FullyConnected(data, num_hidden=64, name="fc1")
    h = mx.sym.Activation(h, act_type="relu", name="relu1")
    net = mx.sym.FullyConnected(h, num_hidden=10, name="fc2")
    exe = net.simple_bind(mx.cpu(), data=(2, 32))
    rng = np.random.RandomState(0)
    params = {}
    for name, arr in exe.arg_dict.items():
        if name != "data":
            arr[:] = (rng.randn(*arr.shape) * 0.1).astype(np.float32)
            params[name] = arr
    engine = InferenceEngine(net.tojson(), params, {"data": (32,)},
                             ctx=ctx, config=config)
    return engine, "data", (32,)


def build_file_engine(symbol_path, params_path, input_specs, config=None):
    from mxnet_tpu.serving import InferenceEngine
    from mxnet_tpu.serving.server import _parse_input_spec
    with open(symbol_path, "r", encoding="utf-8") as fh:
        symbol_json = fh.read()
    shapes = _parse_input_spec(input_specs)
    engine = InferenceEngine(symbol_json, params_path, shapes,
                             config=config)
    name, shape = next(iter(shapes.items()))
    return engine, name, shape


def _percentile(sorted_vals, q):
    if not sorted_vals:
        return 0.0
    pos = q * (len(sorted_vals) - 1)
    lo = int(pos)
    hi = min(lo + 1, len(sorted_vals) - 1)
    return sorted_vals[lo] + (sorted_vals[hi] - sorted_vals[lo]) \
        * (pos - lo)


class _Tally:
    """Thread-safe latency/status accumulator."""

    def __init__(self):
        self.lock = threading.Lock()
        self.latencies = []      # seconds, completed requests only
        self.statuses = {}       # status -> count
        self.rows_done = 0

    def ok(self, latency, rows):
        with self.lock:
            self.latencies.append(latency)
            self.statuses["ok"] = self.statuses.get("ok", 0) + 1
            self.rows_done += rows

    def fail(self, status):
        with self.lock:
            self.statuses[status] = self.statuses.get(status, 0) + 1

    def records(self, mode, elapsed):
        lats = sorted(self.latencies)
        done = len(lats)
        recs = [
            {"metric": "serving_%s_rps" % mode,
             "value": round(done / elapsed, 2) if elapsed else 0.0,
             "unit": "req/s", "mode": mode},
            {"metric": "serving_%s_rows_per_sec" % mode,
             "value": round(self.rows_done / elapsed, 2) if elapsed
             else 0.0,
             "unit": "rows/s", "mode": mode},
        ]
        for q, label in ((0.5, "p50"), (0.95, "p95"), (0.99, "p99")):
            recs.append({"metric": "serving_%s_%s_ms" % (mode, label),
                         "value": round(_percentile(lats, q) * 1e3, 3),
                         "unit": "ms", "mode": mode})
        for status, count in sorted(self.statuses.items()):
            if status != "ok":
                recs.append({"metric": "serving_%s_%s_total"
                             % (mode, status),
                             "value": count, "unit": "requests",
                             "mode": mode})
        return recs


def _status_of(exc):
    return getattr(exc, "status", "error")


def run_closed(submit_and_wait, clients, requests_per_client, sizes,
               make_input):
    """Closed loop: ``clients`` threads each issue
    ``requests_per_client`` blocking requests of rotating ``sizes``.
    ``submit_and_wait(inputs, rid) -> rows`` raises on
    rejection/error; ``rid`` is the per-request trace id the submitter
    must propagate (engine ``rid=`` / HTTP ``X-Request-Id``)."""
    tally = _Tally()

    def client(cid):
        rng = np.random.RandomState(cid)
        for i in range(requests_per_client):
            n = sizes[(cid + i) % len(sizes)]
            inputs = make_input(n, rng)
            t0 = time.monotonic()
            try:
                rows = submit_and_wait(inputs, "bench-c%d-%d" % (cid, i))
            except Exception as exc:   # noqa: BLE001 - tallied
                tally.fail(_status_of(exc))
                continue
            tally.ok(time.monotonic() - t0, rows)

    threads = [threading.Thread(target=client, args=(c,), daemon=True)
               for c in range(clients)]
    t0 = time.monotonic()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    return tally, time.monotonic() - t0


def run_open(engine, qps, seconds, sizes, make_input):
    """Open loop: fire ``engine.submit`` at a fixed ``qps`` for
    ``seconds`` without waiting; latencies land via future callbacks
    (arrival-time anchored, so queueing delay is IN the number)."""
    from mxnet_tpu.serving import RequestRejected

    if qps <= 0:
        raise ValueError("open-loop qps must be > 0, got %g" % qps)
    tally = _Tally()
    rng = np.random.RandomState(0)
    interval = 1.0 / qps
    futures = []
    t0 = time.monotonic()
    i = 0
    while time.monotonic() - t0 < seconds:
        target = t0 + i * interval
        delay = target - time.monotonic()
        if delay > 0:
            time.sleep(delay)
        n = sizes[i % len(sizes)]
        sent = time.monotonic()
        try:
            fut = engine.submit(make_input(n, rng), rid="bench-o%d" % i)
        except RequestRejected as exc:
            tally.fail(exc.status)
        else:
            def _done(f, sent=sent, n=n):
                exc = f.exception()
                if exc is None:
                    tally.ok(time.monotonic() - sent, n)
                else:
                    tally.fail(_status_of(exc))
            fut.add_done_callback(_done)
            futures.append(fut)
        i += 1
    for fut in futures:
        try:
            fut.result(timeout=30)
        except Exception:   # noqa: BLE001 - already tallied by callback
            pass
    return tally, time.monotonic() - t0


def http_submit_and_wait(host, port, input_name, timeout=30):
    """Closed-loop submitter over HTTP (one connection per client
    thread, stdlib only)."""
    import http.client
    local = threading.local()

    def call(inputs, rid=None):
        conn = getattr(local, "conn", None)
        if conn is None:
            conn = http.client.HTTPConnection(host, port, timeout=timeout)
            local.conn = conn
        body = json.dumps({"inputs": {k: v.tolist()
                                      for k, v in inputs.items()}})
        headers = {"Content-Type": "application/json"}
        if rid:
            headers["X-Request-Id"] = rid
        try:
            conn.request("POST", "/predict", body, headers)
            resp = conn.getresponse()
            doc = json.loads(resp.read())
        except Exception:
            local.conn = None   # poisoned connection: rebuild next call
            raise
        if resp.status != 200:
            err = RuntimeError(doc.get("error", "HTTP %d" % resp.status))
            err.status = doc.get("status", "error")
            raise err
        return len(inputs[input_name])

    return call


def _attach_anatomy(records, mode):
    """Fold the reqtrace window's tail attribution into this loop's
    records: the p99 metric line carries the p99 phase shares + verdict
    (the serving analog of the TRAIN record's ``"phases"`` field, so a
    p99 regression gates pre-diagnosed), plus a pad-waste line."""
    from mxnet_tpu.serving import reqtrace
    att = reqtrace.tracer.attribution()
    if not att["requests"]:
        return
    verdict, _hint = reqtrace.classify(
        att["p99_shares"], shed_fraction=att["shed_fraction"],
        pad_waste=att["pad"].get("waste_ratio"))
    for rec in records:
        if rec.get("metric") == "serving_%s_p99_ms" % mode:
            rec["phases"] = {k: round(v, 4)
                             for k, v in att["p99_shares"].items()}
            rec["verdict"] = verdict
    records.append({"metric": "serving_%s_pad_waste_ratio" % mode,
                    "value": round(att["pad"].get("waste_ratio", 0.0), 4),
                    "unit": "ratio", "mode": mode})


def bench_records(clients=8, requests_per_client=25, qps=150.0,
                  seconds=2.0, sizes=(1, 2, 3, 5), config=None,
                  mode="both", engine_factory=None):
    """The ONE in-process bench path (bench.py --serve and the CLI's
    non-URL branch both land here): closed and/or open loop against
    ``engine_factory()`` (default: the demo engine); returns the metric
    records (engine is shut down)."""
    from mxnet_tpu.serving import reqtrace
    make = engine_factory or (lambda: build_demo_engine(config=config))
    engine, name, shape = make()
    records = [{"metric": "serving_warmup_compiles",
                "value": engine.warmup_compiles, "unit": "compiles",
                "buckets": engine.buckets}]

    def make_input(n, rng):
        return {name: rng.rand(n, *shape).astype(np.float32)}

    def submit_and_wait(inputs, rid=None):
        engine.predict(inputs, timeout=30, rid=rid)
        return len(inputs[name])

    try:
        if mode in ("closed", "both"):
            reqtrace.reset()   # this loop's window, not warmup's
            tally, elapsed = run_closed(submit_and_wait, clients,
                                        requests_per_client, list(sizes),
                                        make_input)
            recs = tally.records("closed", elapsed)
            _attach_anatomy(recs, "closed")
            records.extend(recs)
        if mode in ("open", "both"):
            reqtrace.reset()
            tally, elapsed = run_open(engine, qps, seconds, list(sizes),
                                      make_input)
            recs = tally.records("open", elapsed)
            _attach_anatomy(recs, "open")
            records.extend(recs)
        records.append({"metric": "serving_cold_compiles",
                        "value": engine.cold_compiles(),
                        "unit": "compiles"})
        # memory anatomy: peak bytes ride alongside p99, so a latency
        # regression and a memory regression read from the same run
        try:
            from mxnet_tpu import memprof
            records.append({"metric": "serving_peak_hbm_bytes",
                            "value": memprof.peak_hbm_bytes(),
                            "unit": "bytes"})
        except Exception as e:
            records.append({"metric": "serving_peak_hbm_bytes",
                            "value": None, "unit": "bytes",
                            "error": str(e)[:200]})
    finally:
        engine.shutdown()
    return records


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--mode", choices=["closed", "open", "both"],
                    default="both")
    ap.add_argument("--clients", type=int, default=8,
                    help="closed-loop client threads")
    ap.add_argument("--requests", type=int, default=25,
                    help="closed-loop requests per client")
    ap.add_argument("--qps", type=float, default=150.0,
                    help="open-loop arrival rate")
    ap.add_argument("--seconds", type=float, default=2.0,
                    help="open-loop duration")
    ap.add_argument("--sizes", default="1,2,3,5",
                    help="rotating request row counts")
    ap.add_argument("--symbol", default=None)
    ap.add_argument("--params", default=None)
    ap.add_argument("--input", action="append", default=None,
                    help="name:d1,d2,... per-example shape (with "
                         "--symbol)")
    ap.add_argument("--url", default=None,
                    help="benchmark a RUNNING server (host:port or "
                         "http://host:port; closed loop only)")
    ap.add_argument("--out", default=None,
                    help="also append the JSON metric lines to a file")
    args = ap.parse_args(argv)
    if args.mode in ("open", "both") and args.qps <= 0 and not args.url:
        ap.error("--qps must be > 0 for open-loop mode")
    sizes = [int(s) for s in args.sizes.split(",") if s]

    records = []
    if args.url:
        target = args.url.split("//")[-1].rstrip("/")
        host, _, port = target.partition(":")
        call = http_submit_and_wait(host, int(port or 80), "data")
        input_name, shape = "data", (32,)
        if args.input:
            from mxnet_tpu.serving.server import _parse_input_spec
            input_name, shape = next(iter(
                _parse_input_spec(args.input).items()))
            call = http_submit_and_wait(host, int(port or 80), input_name)

        def make_input(n, rng):
            return {input_name: rng.rand(n, *shape).astype(np.float32)}

        tally, elapsed = run_closed(call, args.clients, args.requests,
                                    sizes, make_input)
        records.extend(tally.records("closed", elapsed))
    else:
        factory = None
        if args.symbol:
            factory = lambda: build_file_engine(  # noqa: E731
                args.symbol, args.params, args.input)
        records = bench_records(
            clients=args.clients, requests_per_client=args.requests,
            qps=args.qps, seconds=args.seconds, sizes=sizes,
            mode=args.mode, engine_factory=factory)

    for rec in records:
        print(json.dumps(rec), flush=True)
    if args.out:
        with open(args.out, "a", encoding="utf-8") as fh:
            for rec in records:
                fh.write(json.dumps(rec) + "\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())
