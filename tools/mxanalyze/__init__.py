"""mxanalyze: JAX-aware static analysis for the mxnet_tpu tree.

AST-level (stdlib ``ast``, no third-party deps) checks for the
invariants the runtime can only count after the fact — jit purity,
retrace hazards, lock discipline, swallowed exceptions, env-var drift —
run as a repo gate next to ``tools/bench_gate.py``.

CLI::

    python -m tools.mxanalyze [--strict] [--update-baseline] [paths...]

Design note: ``docs/architecture/static_analysis.md``.
"""
from .core import (Finding, Project, SourceModule, RULES, SEVERITY,
                   analyze_paths, repo_root)
from .baseline import load_baseline, save_baseline, diff_baseline

__all__ = ["Finding", "Project", "SourceModule", "RULES", "SEVERITY",
           "analyze_paths", "repo_root", "load_baseline", "save_baseline",
           "diff_baseline"]
