"""jit-purity: Python side effects lexically inside a traced function.

``jax.jit`` (and ``tracked_jit``, ``pl.pallas_call``, ``jax.checkpoint``)
executes the Python body ONCE per signature, at trace time. Any side
effect in that body — a clock read, an env-var read, a telemetry bump, a
log line, stdlib randomness, mutation of enclosing state — silently
bakes its trace-time value into the compiled program or fires once
instead of per step. This is the discipline JAX's omnistaging enforces
dynamically (by erroring on some of it) moved to a lexical check.
"""
from __future__ import annotations

import ast

from ..core import Finding
from .common import (dotted_parts, import_aliases, jit_index,
                     local_bindings)

RULE = "jit-purity"

#: dotted prefixes (after alias resolution) that are side effects /
#: trace-time-only values. ``jax.random`` is pure and never matches —
#: alias resolution turns ``from jax import random`` into "jax.random".
_DENY = (
    ("time.", "the clock is read once, at trace time"),
    ("datetime.", "the clock is read once, at trace time"),
    ("random.", "stdlib randomness is drawn once at trace time (use "
                "jax.random with an explicit key)"),
    ("numpy.random.", "numpy randomness is drawn once at trace time "
                      "(use jax.random)"),
    ("np.random.", "numpy randomness is drawn once at trace time "
                   "(use jax.random)"),
    ("os.environ", "the environment is read once, at trace time"),
    ("os.getenv", "the environment is read once, at trace time"),
    ("os.putenv", "the environment is read once, at trace time"),
    ("logging.", "logs fire once per compile, not once per step"),
    ("warnings.", "warnings fire once per compile, not once per step"),
    ("logger.", "logs fire once per compile, not once per step"),
    ("log.", "logs fire once per compile, not once per step"),
    ("telemetry.", "registry mutations run at trace time, not per step"),
    ("mxnet_tpu.telemetry.",
     "registry mutations run at trace time, not per step"),
)

#: bare builtins that are I/O at trace time.
_DENY_BUILTINS = {"print", "open", "input"}


def _deny_reason(parts, aliases):
    target = aliases.get(parts[0])
    if target:
        parts = target.split(".") + parts[1:]
    full = ".".join(parts)
    for prefix, why in _DENY:
        if full.startswith(prefix):
            return why
    if len(parts) == 1 and parts[0] in _DENY_BUILTINS:
        return "I/O executes at trace time only"
    return None


def _fn_label(fn):
    return getattr(fn, "name", "<lambda>")


class Pass:
    rule = RULE

    def run(self, project):
        findings = []
        for mod in project.modules:
            if mod.tree is None:
                continue
            index = jit_index(mod)
            aliases = import_aliases(mod.tree)
            for fn in index.jitted_defs:
                findings.extend(self._check_fn(mod, fn, aliases))
        return findings

    def _check_fn(self, mod, fn, aliases):
        out = []
        label = _fn_label(fn)
        locals_ = local_bindings(fn)
        body = fn.body if isinstance(fn.body, list) else [fn.body]
        for stmt in body:
            for node in ast.walk(stmt):
                if isinstance(node, ast.Call):
                    parts = dotted_parts(node.func)
                    if not parts:
                        continue
                    why = _deny_reason(parts, aliases)
                    if why:
                        out.append(Finding(
                            RULE, mod.relpath, node.lineno,
                            node.col_offset,
                            "call to %s() inside jit-wrapped '%s': %s"
                            % (".".join(parts), label, why),
                            hint="hoist it out of the traced function "
                                 "or pass the value in as an argument"))
                elif isinstance(node, ast.Global):
                    out.append(Finding(
                        RULE, mod.relpath, node.lineno, node.col_offset,
                        "`global %s` inside jit-wrapped '%s': the "
                        "mutation happens at trace time only"
                        % (", ".join(node.names), label),
                        hint="thread the value through the function's "
                             "arguments and return value"))
                elif isinstance(node, (ast.Assign, ast.AugAssign)):
                    tgts = node.targets if isinstance(node, ast.Assign) \
                        else [node.target]
                    for tgt in tgts:
                        root = _subscript_attr_root(tgt)
                        if root and root not in locals_ \
                                and root != "self":
                            out.append(Finding(
                                RULE, mod.relpath, node.lineno,
                                node.col_offset,
                                "jit-wrapped '%s' mutates enclosing-"
                                "scope state '%s': the write happens at "
                                "trace time only" % (label, root),
                                hint="return the new value instead of "
                                     "mutating closed-over state"))
        return out


def _subscript_attr_root(tgt):
    """Root Name of an attribute/subscript write target (``cache[k]``,
    ``obj.field``); None for plain-name targets (local rebinding is
    fine)."""
    node = tgt
    if not isinstance(node, (ast.Subscript, ast.Attribute)):
        return None
    while isinstance(node, (ast.Subscript, ast.Attribute)):
        node = node.value
    return node.id if isinstance(node, ast.Name) else None


PASS = Pass()
