"""swallowed-exception: broad handlers that hide failures.

Flags ``except Exception`` / ``except BaseException`` / bare ``except``
handlers whose body neither re-raises, logs (``logging``/``logger``/
``warnings``/``traceback``), nor bumps telemetry (any ``telemetry.*``
call — ``telemetry.swallowed(site, exc)`` is the one-line idiom).
Narrow handlers (``except OSError``) are out of scope: catching a named
failure mode silently is a choice the narrow type documents; catching
EVERYTHING silently is how real bugs disappear.

Deliberate swallows (exit paths, "never break the caller" guards) get
``# mxanalyze: allow(swallowed-exception): <reason>`` on the ``except``
line.
"""
from __future__ import annotations

import ast

from ..core import Finding
from .common import dotted_parts

RULE = "swallowed-exception"

_BROAD = {"Exception", "BaseException"}
_LOG_ROOTS = {"logging", "logger", "warnings", "traceback", "telemetry",
              "log"}
_LOG_METHODS = {"debug", "info", "warning", "warn", "error", "exception",
                "critical", "print_exc", "log", "swallowed"}


def _is_broad(handler):
    t = handler.type
    if t is None:
        return True
    names = []
    if isinstance(t, ast.Tuple):
        for e in t.elts:
            parts = dotted_parts(e)
            if parts:
                names.append(parts[-1])
    else:
        parts = dotted_parts(t)
        if parts:
            names.append(parts[-1])
    return any(n in _BROAD for n in names)


def _observes(handler):
    for node in ast.walk(handler):
        if isinstance(node, ast.Raise):
            return True
        if isinstance(node, ast.Call):
            parts = dotted_parts(node.func)
            if not parts:
                continue
            if parts[0] in _LOG_ROOTS or parts[-1] in _LOG_METHODS:
                return True
    return False


class Pass:
    rule = RULE

    def run(self, project):
        findings = []
        for mod in project.modules:
            if mod.tree is None:
                continue
            for node in ast.walk(mod.tree):
                if not isinstance(node, ast.ExceptHandler):
                    continue
                if not _is_broad(node) or _observes(node):
                    continue
                findings.append(Finding(
                    RULE, mod.relpath, node.lineno, node.col_offset,
                    "broad except swallows the failure without logging "
                    "or counting it",
                    hint="log at debug, call telemetry.swallowed("
                         "site, exc), or annotate `# mxanalyze: "
                         "allow(swallowed-exception): <reason>`"))
        return findings


PASS = Pass()
