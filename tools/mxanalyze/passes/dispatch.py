"""dispatch-amplification: per-layer/per-param Python loops that
multiply dispatches.

ROADMAP item 1's dispatch-bound verdict has two canonical source
shapes, and this pass names both:

1. a Python ``for`` loop over layers/params LEXICALLY INSIDE a jitted
   (or CompiledProgram-dispatched) step function whose body makes
   calls — each iteration is unrolled into the HLO, so compile time
   and program size scale with depth where ``lax.scan`` would keep
   them constant.
2. a per-param optimizer update OUTSIDE the compiled step: a host-side
   ``for`` over params whose body calls an updater — N param tensors
   become N dispatches per step where a fused (stacked) applier or an
   in-step optimizer would be one.

Both shapes are sometimes deliberate (heterogeneous shapes cannot
scan; the per-param path is the documented fallback when fusion is
off) — those sites carry
``# mxanalyze: allow(dispatch-amplification): <reason>``.
"""
from __future__ import annotations

import ast
import re

from ..core import Finding
from .common import dotted_parts, jit_index

RULE = "dispatch-amplification"

#: iterable names that look like a parameter/layer collection
_PARAMISH_RE = re.compile(
    r"param|weight|layer|grad|live|expert|stage|block|cell")
_PARAMISH_EXACT = {"ws", "gs", "sv", "weights", "grads", "states",
                   "params"}

#: callee tails that apply one param's update (host-side loop check)
_UPDATER_RE = re.compile(r"^_?updaters?\d*$|^upd$|^update_multi_precision$")


def _iter_names(node):
    """Name identifiers mentioned anywhere in a loop's iterable."""
    out = set()
    for sub in ast.walk(node):
        if isinstance(sub, ast.Name):
            out.add(sub.id)
        elif isinstance(sub, ast.Attribute):
            out.add(sub.attr)
    return out


def _paramish(names):
    return any(n in _PARAMISH_EXACT or _PARAMISH_RE.search(n)
               for n in names)


def _has_call(body):
    for stmt in body:
        for sub in ast.walk(stmt):
            if isinstance(sub, ast.Call):
                return True
    return False


def _functions(tree):
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node


class Pass:
    rule = RULE

    def run(self, project):
        findings = []
        for mod in project.modules:
            if mod.tree is None:
                continue
            if not mod.relpath.startswith("mxnet_tpu/"):
                continue
            index = jit_index(mod)
            jitted_ids = set()
            for d in index.jitted_defs:
                for sub in ast.walk(d):
                    jitted_ids.add(id(sub))
            findings.extend(self._check_traced_loops(mod, index))
            findings.extend(self._check_host_updates(mod, jitted_ids))
        return findings

    # (1) unrolled for-loops inside traced bodies
    def _check_traced_loops(self, mod, index):
        out = []
        seen = set()
        for d in index.jitted_defs:
            for node in ast.walk(d):
                if not isinstance(node, ast.For) or id(node) in seen:
                    continue
                seen.add(id(node))
                names = _iter_names(node.iter)
                if not _paramish(names) or not _has_call(node.body):
                    continue
                out.append(Finding(
                    RULE, mod.relpath, node.lineno, node.col_offset,
                    "Python for over a param/layer collection inside a "
                    "traced function: the loop unrolls into the HLO, "
                    "so program size and compile time scale with depth",
                    hint="restructure as lax.scan over stacked leaves, "
                         "or annotate why unrolling is required "
                         "(`# mxanalyze: allow("
                         "dispatch-amplification): <reason>`)"))
        return out

    # (2) host-side per-param updater loops
    def _check_host_updates(self, mod, jitted_ids):
        out = []
        seen = set()
        for fn in _functions(mod.tree):
            for node in ast.walk(fn):
                if not isinstance(node, ast.For) \
                        or id(node) in jitted_ids \
                        or id(node) in seen:
                    continue
                seen.add(id(node))
                if not _paramish(_iter_names(node.iter)):
                    continue
                upd = self._updater_call(node.body)
                if upd is None:
                    continue
                out.append(Finding(
                    RULE, mod.relpath, upd.lineno, upd.col_offset,
                    "per-param optimizer update in a host loop: one "
                    "dispatch per parameter per step instead of one "
                    "fused apply",
                    hint="route through the fused applier (stacked "
                         "same-shape groups) or move the update into "
                         "the compiled step; annotate deliberate "
                         "fallback paths"))
        return out

    @staticmethod
    def _updater_call(body):
        for stmt in body:
            for sub in ast.walk(stmt):
                if not isinstance(sub, ast.Call):
                    continue
                parts = dotted_parts(sub.func)
                if parts and _UPDATER_RE.match(parts[-1]):
                    return sub
        return None


PASS = Pass()
