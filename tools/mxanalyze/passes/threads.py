"""cross-thread-state: state shared between thread roots without a lock.

The thread-rooted upgrade the lexical lock table can't express: the
``lock-discipline`` pass flags *mixed-guard* writes (some under a lock,
some not), but a symbol written consistently with NO lock from two
different threads never mixes and sails through. This pass first
computes **thread entry roots** per module:

- targets of ``threading.Thread(target=...)`` (module functions and
  ``self.method`` bound targets),
- ``run()`` of ``threading.Thread`` subclasses,
- everything else seeds from public entry points as the ``main`` root,

then propagates roots through the module's direct call graph (a helper
called only from a worker loop runs on the worker root; one called from
both runs on both). A module-global or ``self.attr`` written from >= 2
distinct roots where at least one write happens outside any recognized
``with <lock>`` is flagged at the unguarded site(s).

Construction is exempt (``__init__``/``__new__`` — single-threaded by
convention), as is module top level (import lock).

Also in this pass (low severity, same rule): a bare ``Condition.wait()``
outside any ``while`` loop — the predicate must be re-checked on wakeup
(spurious wakeups, stolen wakeups), so ``wait()`` belongs inside
``while not predicate:`` or should be ``wait_for(predicate)``.

Runtime join: ``mxanalyze --witness <dir>`` (tools/mxanalyze/witness.py)
merges the acquisition-order edges a live ``MXNET_THREADSAN=1`` run
recorded into the static inversion check and escalates findings of this
rule that a witness hazard report confirms.
"""
from __future__ import annotations

import ast

from ..core import Finding
from .common import dotted_parts, import_aliases, module_globals
from .locks import (_EXEMPT_FNS, _LockTable, _symbol_of, _write_targets)

RULE = "cross-thread-state"


def _is_thread_ctor(call, aliases):
    """True when ``call`` constructs a ``threading.Thread``."""
    parts = dotted_parts(call.func)
    if not parts or parts[-1] != "Thread":
        return False
    if len(parts) == 1:
        return aliases.get("Thread") == "threading.Thread"
    base = parts[-2]
    return base == "threading" or aliases.get(base) == "threading"


def _is_thread_base(base, aliases):
    parts = dotted_parts(base)
    if parts == ["Thread"]:
        return aliases.get("Thread") == "threading.Thread"
    return parts[-2:] == ["threading", "Thread"]


def _root_label(key):
    return key[1] if not key[0] else "%s.%s" % key


class _ModuleIndex:
    """Function defs, call edges, and thread roots of one module.

    Function keys are ``(class_name_or_empty, fn_name)``; the call graph
    only follows edges it can resolve lexically (bare names to module
    functions, ``self.m`` to methods of the same class) — a deliberate
    under-approximation that keeps root attribution sound for the
    worker-loop idiom this codebase uses."""

    def __init__(self, mod, aliases):
        self.mod = mod
        self.aliases = aliases
        self.fns = {}        # (cls, name) -> FunctionDef
        self.callees = {}    # (cls, name) -> set of callee keys
        self.thread_roots = {}   # fn key -> root label
        self._collect()

    def _collect(self):
        tree = self.mod.tree
        for node in tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.fns[("", node.name)] = node
            elif isinstance(node, ast.ClassDef):
                is_thread_cls = any(_is_thread_base(b, self.aliases)
                                    for b in node.bases)
                for sub in node.body:
                    if isinstance(sub, (ast.FunctionDef,
                                        ast.AsyncFunctionDef)):
                        key = (node.name, sub.name)
                        self.fns[key] = sub
                        if is_thread_cls and sub.name == "run":
                            self.thread_roots[key] = _root_label(key)
        # call edges + Thread(target=...) roots, attributed to the
        # enclosing function (or "main" for module/class top level)
        for key, fn in self.fns.items():
            self.callees[key] = set()
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                self._note_call(key, node)
        for node in ast.walk(tree):
            if isinstance(node, ast.Call) \
                    and _is_thread_ctor(node, self.aliases):
                self._note_thread_target(node)

    def _note_call(self, caller, call):
        if isinstance(call.func, ast.Name):
            key = ("", call.func.id)
            if key in self.fns:
                self.callees[caller].add(key)
        elif isinstance(call.func, ast.Attribute) \
                and isinstance(call.func.value, ast.Name) \
                and call.func.value.id == "self" and caller[0]:
            key = (caller[0], call.func.attr)
            if key in self.fns:
                self.callees[caller].add(key)

    def _enclosing_class(self, target):
        """Class name owning a ``self.X`` thread target: the class that
        defines method ``X`` (unique in this module, else unresolved)."""
        owners = [cls for (cls, name) in self.fns
                  if cls and name == target]
        return owners[0] if len(owners) == 1 else None

    def _note_thread_target(self, call):
        for kw in call.keywords:
            if kw.arg != "target":
                continue
            v = kw.value
            if isinstance(v, ast.Name):
                key = ("", v.id)
                if key in self.fns:
                    self.thread_roots[key] = _root_label(key)
            elif isinstance(v, ast.Attribute) \
                    and isinstance(v.value, ast.Name) \
                    and v.value.id == "self":
                cls = self._enclosing_class(v.attr)
                if cls is not None:
                    key = (cls, v.attr)
                    self.thread_roots[key] = _root_label(key)

    def roots(self):
        """fn key -> sorted tuple of thread-root labels ("main" and/or
        worker roots), via propagation over the call graph."""
        labels = {key: set() for key in self.fns}
        # worker roots flow down from each spawn target
        for root_key, label in self.thread_roots.items():
            stack = [root_key]
            seen = set()
            while stack:
                key = stack.pop()
                if key in seen:
                    continue
                seen.add(key)
                labels[key].add(label)
                stack.extend(self.callees.get(key, ()))
        # "main" flows from every entry point that is NOT a thread
        # target: public API with no intra-module caller (plus anything
        # those reach)
        callers = {}
        for caller, callees in self.callees.items():
            for c in callees:
                callers.setdefault(c, set()).add(caller)
        main_seeds = [key for key in self.fns
                      if key not in self.thread_roots
                      and not callers.get(key)]
        stack = list(main_seeds)
        seen = set()
        while stack:
            key = stack.pop()
            if key in seen:
                continue
            seen.add(key)
            labels[key].add("main")
            stack.extend(self.callees.get(key, ()))
        return {key: tuple(sorted(v)) for key, v in labels.items()}


class _AccessWalker(ast.NodeVisitor):
    """Walk one function with a with-lock stack and a while-loop depth,
    collecting writes (symbol, locked?) and bare Condition waits."""

    def __init__(self, pass_, mod, aliases, class_name, fn, fn_roots):
        self.p = pass_
        self.mod = mod
        self.aliases = aliases
        self.class_name = class_name
        self.fn = fn
        self.fn_roots = fn_roots
        self.stack = []
        self.while_depth = 0

    def visit_With(self, node):
        acquired = 0
        for item in node.items:
            lid = self.p.table.resolve(self.mod, self.aliases,
                                       self.class_name,
                                       item.context_expr)
            if lid is not None:
                self.stack.append(lid)
                acquired += 1
        self.generic_visit(node)
        for _ in range(acquired):
            self.stack.pop()

    visit_AsyncWith = visit_With

    def visit_While(self, node):
        self.while_depth += 1
        self.generic_visit(node)
        self.while_depth -= 1

    def _check_bare_wait(self, node):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "wait"):
            return
        lid = self.p.table.resolve(self.mod, self.aliases,
                                   self.class_name, node.func.value)
        if lid is None or self.p.table.kinds.get(lid) != "Condition":
            return
        if self.while_depth == 0:
            self.p.findings.append(Finding(
                RULE, self.mod.relpath, node.lineno, node.col_offset,
                "bare Condition.wait() outside a while loop — the "
                "predicate is not re-checked on wakeup (spurious/stolen "
                "wakeups)",
                hint="wrap in `while not predicate: cond.wait()` or use "
                     "cond.wait_for(predicate)"))

    def generic_visit(self, node):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            return   # nested defs do not run under this lock stack
        self._check_bare_wait(node)
        for tgt in _write_targets(node):
            sym = _symbol_of(tgt, self.p.globals_by_mod.get(
                self.mod.relpath, set()), self.class_name)
            if sym is not None and self.fn.name not in _EXEMPT_FNS:
                key = (self.mod.relpath,) + sym
                self.p.writes.setdefault(key, []).append(
                    (self.mod.relpath, node.lineno, node.col_offset,
                     tuple(self.stack), self.fn_roots))
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef,
                                  ast.AsyncFunctionDef, ast.Lambda)):
                continue
            self.visit(child)


class Pass:
    rule = RULE

    def run(self, project):
        self.table = _LockTable()
        self.findings = []
        self.writes = {}   # symbol key -> [(path, line, col, locks, roots)]
        self.globals_by_mod = {}
        for mod in project.modules:
            self.table.collect(mod)
            if mod.tree is not None:
                self.globals_by_mod[mod.relpath] = \
                    module_globals(mod.tree)
        for mod in project.modules:
            if mod.tree is None:
                continue
            self._walk_module(mod)
        self._report()
        return self.findings

    def _walk_module(self, mod):
        aliases = import_aliases(mod.tree)
        index = _ModuleIndex(mod, aliases)
        if not index.thread_roots:
            # a module that never spawns a thread has ONE root: nothing
            # here can be cross-thread (waits are still worth checking
            # when a Condition exists, but with no second thread there
            # is no waker — skip entirely)
            return
        roots = index.roots()
        for key, fn in index.fns.items():
            cls = key[0] or None
            w = _AccessWalker(self, mod, aliases, cls, fn,
                              roots.get(key, ("main",)))
            for stmt in fn.body:
                w.visit(stmt)
            # nested defs run with their own empty lock stack but the
            # same thread roots as their definer (closures handed to
            # callbacks — conservative)
            for sub in ast.walk(fn):
                if sub is not fn and isinstance(
                        sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    wn = _AccessWalker(self, mod, aliases, cls, sub,
                                       roots.get(key, ("main",)))
                    for stmt in sub.body:
                        wn.visit(stmt)

    def _report(self):
        for key, sites in sorted(self.writes.items()):
            all_roots = sorted({r for s in sites for r in s[4]})
            if len(all_roots) < 2:
                continue
            unlocked = [s for s in sites if not s[3]]
            if not unlocked:
                continue
            sym = key[1:]
            label = ("%s.%s" % (sym[1], sym[2]) if sym[0] == "attr"
                     else sym[1])
            for path, line, col, _, _ in unlocked:
                self.findings.append(Finding(
                    RULE, path, line, col,
                    "'%s' is written from multiple thread roots (%s) "
                    "and this write is outside any lock"
                    % (label, ", ".join(all_roots)),
                    hint="guard the write with the owning lock, or "
                         "document the ordering contract (queue/Event "
                         "handoff, single-writer) and allow() it"))


PASS = Pass()
