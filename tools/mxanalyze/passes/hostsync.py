"""host-sync-hazard: device->host synchronization in hot loops.

The async-dispatch pipeline (stepprof's whole premise) dies the moment a
hot-path statement forces a device value back to the host: every queued
step drains, dispatch serializes, and the profiler books the stall as
``host_block``. This pass finds the source patterns BEFORE a profile
run does:

1. ``.asnumpy()`` / ``.item()`` / ``np.asarray(x)`` / ``float(x)`` /
   ``int(x)`` on a device-tainted value inside a designated hot
   function (fit/step/update/serving loops). ``asnumpy``/``item`` are
   unconditional sinks in hot scope — on this codebase they only exist
   on NDArray; the scalar coercions and ``np.asarray`` flag only when
   taint says the operand came off a device (result of a jitted
   callable, ``forward``/``get_outputs``-style producer, or ``.outputs``
   read), so ``float(cfg["lr"])`` stays silent.
2. branching (``if``/``while``) on a device-tainted value — a hidden
   sync plus a trace-invalidation hazard in one.
3. ``block_until_ready`` in a hot function OUTSIDE a
   ``stepprof.should_sync()`` bracket — the sampled-sync discipline
   (MXNET_STEPPROF_SYNC_EVERY) exists precisely so full-fence syncs are
   paid on 1/N steps; an unguarded fence pays it every step.

Scope is deliberately narrow: only the hot-path modules and function
names below. ``metric.py`` is excluded on purpose — metric readback is
booked as ``device_compute`` by design (see stepprof docs), and
update_metric sits outside the dispatch hot window.

Legitimate syncs (API boundaries returning numpy, final-loss readback)
get ``# mxanalyze: allow(host-sync-hazard): <reason>``.
"""
from __future__ import annotations

import ast

from ..core import Finding
from .common import dotted_parts, jit_index
from .retrace import _expr_walk, _stmts_in_order

RULE = "host-sync-hazard"

#: module prefixes whose hot functions are in scope
HOT_PREFIXES = (
    "mxnet_tpu/module/",
    "mxnet_tpu/gluon/trainer.py",
    "mxnet_tpu/serving/",
    "mxnet_tpu/executor.py",
    "mxnet_tpu/executor_manager.py",
    "mxnet_tpu/model.py",
    "mxnet_tpu/parallel/data_parallel.py",
)

#: function names that constitute the step/fit/serving hot loops
HOT_FUNCTIONS = {
    "fit", "_fit_loop", "score", "predict", "iter_predict",
    "forward", "backward", "forward_backward", "update", "_update",
    "_update_impl", "_allreduce_grads", "step", "_step", "_step_scan",
    "train_step", "__call__", "stack_batches", "_stack", "_load_batch",
    "_batch_loop", "submit", "run_batch", "_run_batch", "_dispatch",
}

#: unconditional sinks in hot scope — these methods only exist on
#: device arrays in this codebase
_SYNC_METHODS = {"asnumpy", "item"}

#: coercions that sync ONLY when the operand is device-tainted
_COERCIONS = {"float", "int", "bool"}

#: callables whose RESULT is device data (taint sources), beyond
#: jitted names from the module's JitIndex
_DEVICE_PRODUCER_TAILS = {"forward", "get_outputs", "forward_backward",
                          "output_dict", "outputs"}


def _functions(tree):
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node


def _mentions_should_sync(test):
    for node in ast.walk(test):
        if isinstance(node, ast.Call):
            parts = dotted_parts(node.func)
            if parts and parts[-1] == "should_sync":
                return True
    return False


class _DeviceTaint:
    """Forward taint: which local names hold device values."""

    def __init__(self, jitted_names):
        self.tainted = set()
        self.jitted_names = jitted_names

    def expr_tainted(self, node):
        if isinstance(node, ast.Name):
            return node.id in self.tainted
        if isinstance(node, ast.Attribute):
            # module.get_outputs() style producers handled in Call;
            # `exec.outputs` / `self.outputs` reads are device lists
            return node.attr == "outputs"
        if isinstance(node, ast.Subscript):
            return self.expr_tainted(node.value)
        if isinstance(node, ast.Call):
            parts = dotted_parts(node.func)
            if not parts:
                return False
            dotted = ".".join(parts)
            if dotted in self.jitted_names:
                return True
            if parts[-1] in _DEVICE_PRODUCER_TAILS:
                return True
            return False
        if isinstance(node, (ast.Tuple, ast.List)):
            return any(self.expr_tainted(e) for e in node.elts)
        if isinstance(node, ast.BinOp):
            return self.expr_tainted(node.left) \
                or self.expr_tainted(node.right)
        if isinstance(node, ast.UnaryOp):
            return self.expr_tainted(node.operand)
        if isinstance(node, ast.Compare):
            return self.expr_tainted(node.left) \
                or any(self.expr_tainted(c) for c in node.comparators)
        if isinstance(node, ast.IfExp):
            return self.expr_tainted(node.body) \
                or self.expr_tainted(node.orelse)
        return False

    def note_assign(self, node):
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            tgt = node.targets[0]
            if isinstance(tgt, ast.Name):
                if self.expr_tainted(node.value):
                    self.tainted.add(tgt.id)
                else:
                    self.tainted.discard(tgt.id)
            elif isinstance(tgt, ast.Tuple) \
                    and self.expr_tainted(node.value):
                for e in tgt.elts:
                    if isinstance(e, ast.Name):
                        self.tainted.add(e.id)


def _np_asarray(call):
    parts = dotted_parts(call.func)
    return len(parts) >= 2 and parts[-1] in ("asarray", "array") \
        and parts[-2] in ("np", "numpy", "_np", "onp")


class Pass:
    rule = RULE

    def run(self, project):
        findings = []
        for mod in project.modules:
            if mod.tree is None:
                continue
            if not any(mod.relpath == p or mod.relpath.startswith(p)
                       for p in HOT_PREFIXES):
                continue
            index = jit_index(mod)
            jitted = set(index.jitted_names)
            jitted_defs = {id(d) for d in index.jitted_defs}
            for fn in _functions(mod.tree):
                if fn.name not in HOT_FUNCTIONS:
                    continue
                if id(fn) in jitted_defs:
                    continue   # traced bodies never sync at step time
                findings.extend(self._check_fn(mod, fn, jitted))
        return findings

    def _check_fn(self, mod, fn, jitted_names):
        out = []
        taint = _DeviceTaint(jitted_names)

        def check_expr(node, guarded):
            if isinstance(node, ast.Call):
                parts = dotted_parts(node.func)
                tail = parts[-1] if parts else ""
                if tail in _SYNC_METHODS \
                        and isinstance(node.func, ast.Attribute):
                    out.append(Finding(
                        RULE, mod.relpath, node.lineno, node.col_offset,
                        "device->host sync: .%s() inside hot function "
                        "'%s' drains the dispatch pipeline every call"
                        % (tail, fn.name),
                        hint="keep the value on device (jnp ops), batch "
                             "the readback outside the loop, or annotate "
                             "`# mxanalyze: allow(host-sync-hazard): "
                             "<reason>`"))
                    return
                if tail == "block_until_ready":
                    if not guarded:
                        out.append(Finding(
                            RULE, mod.relpath, node.lineno,
                            node.col_offset,
                            "unsampled block_until_ready in hot "
                            "function '%s': full fence every step "
                            "instead of 1/SYNC_EVERY" % fn.name,
                            hint="guard with `if stepprof."
                                 "should_sync():` or annotate the "
                                 "deliberate fence"))
                    return
                if (tail in _COERCIONS and len(parts) == 1
                        and node.args
                        and taint.expr_tainted(node.args[0])):
                    out.append(Finding(
                        RULE, mod.relpath, node.lineno, node.col_offset,
                        "%s() on a device value inside hot function "
                        "'%s' forces a blocking transfer" % (tail,
                                                             fn.name),
                        hint="compute on device and read back once per "
                             "SYNC_EVERY steps"))
                    return
                if _np_asarray(node) and node.args \
                        and taint.expr_tainted(node.args[0]):
                    out.append(Finding(
                        RULE, mod.relpath, node.lineno, node.col_offset,
                        "np.asarray on a device value inside hot "
                        "function '%s' copies device->host every call"
                        % fn.name,
                        hint="stay in jnp, or move the conversion out "
                             "of the hot loop"))
                    return

        def walk_stmts(body, guarded):
            for stmt in body:
                if isinstance(stmt, (ast.FunctionDef,
                                     ast.AsyncFunctionDef,
                                     ast.ClassDef)):
                    continue
                g = guarded
                if isinstance(stmt, (ast.If, ast.While)):
                    if _mentions_should_sync(stmt.test):
                        g = True
                    elif taint.expr_tainted(stmt.test):
                        out.append(Finding(
                            RULE, mod.relpath, stmt.test.lineno,
                            stmt.test.col_offset,
                            "branch on a device value inside hot "
                            "function '%s': the comparison blocks on "
                            "the transfer" % fn.name,
                            hint="branch on host metadata, or use "
                                 "lax.cond inside the compiled step"))
                for node in _expr_walk(stmt):
                    check_expr(node, g)
                taint.note_assign(stmt)
                for _field, value in ast.iter_fields(stmt):
                    if isinstance(value, list) and value and isinstance(
                            value[0], (ast.stmt, ast.ExceptHandler)):
                        inner = []
                        for v in value:
                            if isinstance(v, ast.ExceptHandler):
                                inner.extend(v.body)
                            else:
                                inner.append(v)
                        walk_stmts(inner, g)

        walk_stmts(fn.body, False)
        # dedupe: nested statement walk can visit an expr twice when a
        # compound statement holds both test and body exprs
        seen, uniq = set(), []
        for f in out:
            key = (f.line, f.col, f.message)
            if key not in seen:
                seen.add(key)
                uniq.append(f)
        return uniq


PASS = Pass()
