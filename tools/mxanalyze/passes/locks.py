"""lock-discipline: mixed-guard writes, acquisition-order cycles,
non-reentrant self-nesting.

Builds a project-wide lock table (module-level ``X = threading.Lock()``
/ ``RLock`` / ``Condition`` / ``Semaphore``, and ``self.X = ...`` in
methods), then walks every function with a lexical with-lock stack:

- **mixed-guard**: a symbol (``self.attr`` keyed by class, or a
  module-level global / its subscripts) written at least once under a
  recognized lock AND at least once outside any lock — the unguarded
  write sites are flagged. ``__init__``/``__new__`` bodies are exempt
  (construction is single-threaded by convention), as is module top
  level (import lock).
- **order**: acquiring lock B while holding lock A records edge A->B;
  a pair with edges both ways across the project is an inversion
  (deadlock when the two paths interleave).
- **reentry**: ``with`` on a lock already on the stack when the lock
  was created by ``threading.Lock()`` (non-reentrant: self-deadlock).

Mutating method calls (``.append``/``.clear``/``.update``/...) on a
tracked symbol count as writes.
"""
from __future__ import annotations

import ast

from ..core import Finding
from .common import dotted_parts, import_aliases, module_globals

RULE = "lock-discipline"

_LOCK_CTORS = {"Lock", "RLock", "Condition", "Semaphore",
               "BoundedSemaphore"}
_MUTATORS = {"append", "extend", "clear", "update", "pop", "popitem",
             "setdefault", "remove", "discard", "add", "insert"}
_EXEMPT_FNS = {"__init__", "__new__", "__init_subclass__"}


def _lock_ctor_kind(call, aliases):
    """'Lock'/'RLock'/... when ``call`` constructs a threading primitive
    (``threading.Lock()``, an aliased module, or a bare ``Lock()`` from
    ``from threading import Lock``), else None.

    Sees through ``threadsan.register("label", threading.Lock())`` —
    the witness wrapper hands back either the original lock (off) or a
    proxy with identical semantics (armed), so the wrapped ctor is
    still the lock's identity for discipline purposes."""
    parts = dotted_parts(call.func) if isinstance(call, ast.Call) else []
    if parts and parts[-1] == "register" and "threadsan" in parts[:-1] \
            and isinstance(call, ast.Call) and len(call.args) == 2:
        return _lock_ctor_kind(call.args[1], aliases)
    if not parts or parts[-1] not in _LOCK_CTORS:
        return None
    if len(parts) >= 2:
        base = parts[-2]
        if base != "threading" and aliases.get(base) != "threading":
            return None
    return parts[-1]


class _LockTable:
    """lock id -> ctor kind. Ids are keyed by the module's RELPATH
    (stems collide — the repo has several ``engine.py``/``io.py``):
    ("mod", <relpath>, <name>)  module-level lock
    ("cls", <relpath>, <Class>, <attr>)  instance lock
    ``by_stem`` maps a module stem to the relpaths holding locks, for
    cross-module ``with telemetry._lock`` resolution (skipped when the
    stem is ambiguous).
    """

    def __init__(self):
        self.kinds = {}
        self.by_stem = {}

    def collect(self, mod):
        if mod.tree is None:
            return
        aliases = import_aliases(mod.tree)
        for node in mod.tree.body:
            if isinstance(node, ast.Assign):
                kind = _lock_ctor_kind(node.value, aliases)
                if kind:
                    for tgt in node.targets:
                        if isinstance(tgt, ast.Name):
                            self.kinds[("mod", mod.relpath,
                                        tgt.id)] = kind
                            self.by_stem.setdefault(mod.stem,
                                                    set()).add(mod.relpath)
            elif isinstance(node, ast.ClassDef):
                for sub in ast.walk(node):
                    if isinstance(sub, ast.Assign):
                        kind = _lock_ctor_kind(sub.value, aliases)
                        if not kind:
                            continue
                        for tgt in sub.targets:
                            if isinstance(tgt, ast.Attribute) \
                                    and isinstance(tgt.value, ast.Name) \
                                    and tgt.value.id == "self":
                                self.kinds[("cls", mod.relpath,
                                            node.name,
                                            tgt.attr)] = kind

    def resolve(self, mod, aliases, class_name, expr):
        """Lock id for a with-item expression, or None."""
        if isinstance(expr, ast.Name):
            lid = ("mod", mod.relpath, expr.id)
            return lid if lid in self.kinds else None
        if isinstance(expr, ast.Attribute) \
                and isinstance(expr.value, ast.Name):
            base, attr = expr.value.id, expr.attr
            if base == "self" and class_name:
                lid = ("cls", mod.relpath, class_name, attr)
                if lid in self.kinds:
                    return lid
                return None
            tail = aliases.get(base)
            if tail:
                owners = [rp for rp in self.by_stem.get(
                    tail.split(".")[-1], ())
                    if ("mod", rp, attr) in self.kinds]
                if len(owners) == 1:   # ambiguous stems: no resolution
                    return ("mod", owners[0], attr)
        return None


def _lock_label(lid):
    stem = lid[1].rsplit("/", 1)[-1].rsplit(".", 1)[0]
    if lid[0] == "mod":
        return "%s.%s" % (stem, lid[2])
    return "%s.%s.self.%s" % (stem, lid[2], lid[3])


def _write_targets(node):
    """(target_expr, is_write) pairs for assignments and mutator
    calls."""
    if isinstance(node, ast.Assign):
        return list(node.targets)
    if isinstance(node, (ast.AugAssign, ast.AnnAssign)):
        return [node.target]
    if isinstance(node, ast.Call) and isinstance(node.func,
                                                 ast.Attribute) \
            and node.func.attr in _MUTATORS:
        return [node.func.value]
    return []


def _symbol_of(expr, globals_, class_name):
    """Tracked symbol for a write target: peel subscripts, then match
    ``self.attr`` (class symbol) or a module-global name."""
    node = expr
    while isinstance(node, ast.Subscript):
        node = node.value
    if isinstance(node, ast.Attribute) and isinstance(node.value,
                                                      ast.Name) \
            and node.value.id == "self" and class_name:
        return ("attr", class_name, node.attr)
    if isinstance(node, ast.Name) and node.id in globals_:
        return ("global", node.id)
    return None


class _FnWalker(ast.NodeVisitor):
    """Walk one function body with a with-lock stack."""

    def __init__(self, pass_, mod, aliases, class_name, fn):
        self.p = pass_
        self.mod = mod
        self.aliases = aliases
        self.class_name = class_name
        self.fn = fn
        self.stack = []

    def visit_With(self, node):
        acquired = []
        for item in node.items:
            lid = self.p.table.resolve(self.mod, self.aliases,
                                       self.class_name,
                                       item.context_expr)
            if lid is None:
                continue
            for held in self.stack:
                if held == lid:
                    if self.p.table.kinds.get(lid) == "Lock":
                        self.p.findings.append(Finding(
                            RULE, self.mod.relpath, node.lineno,
                            node.col_offset,
                            "nested acquisition of non-reentrant lock "
                            "%s: self-deadlock" % _lock_label(lid),
                            hint="use threading.RLock or restructure"))
                else:
                    self.p.edges.setdefault(
                        (held, lid), []).append(
                            (self.mod.relpath, node.lineno))
            acquired.append(lid)
            self.stack.append(lid)
        self.generic_visit(node)
        for _ in acquired:
            self.stack.pop()

    visit_AsyncWith = visit_With

    def generic_visit(self, node):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            # nested defs are walked as their own functions: a def
            # CREATED under a lock does not RUN under it
            return
        for tgt in _write_targets(node):
            sym = _symbol_of(tgt,
                             self.p.globals_by_mod[self.mod.relpath],
                             self.class_name)
            if sym is not None and self.fn.name not in _EXEMPT_FNS:
                key = (self.mod.relpath,) + sym
                self.p.writes.setdefault(key, []).append(
                    (self.mod.relpath, node.lineno, node.col_offset,
                     tuple(self.stack)))
        # do not descend into nested defs here; they are walked as their
        # own functions (the lock stack is runtime state, but a nested
        # def defined under a lock does NOT run under it)
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef,
                                  ast.AsyncFunctionDef, ast.Lambda)):
                continue
            self.visit(child)


class Pass:
    rule = RULE

    def run(self, project):
        self.table = _LockTable()
        self.findings = []
        self.edges = {}      # (outer, inner) -> [(path, line)]
        self.writes = {}     # symbol key -> [(path, line, col, locks)]
        self.globals_by_mod = {}
        for mod in project.modules:
            self.table.collect(mod)
            if mod.tree is not None:
                self.globals_by_mod[mod.relpath] = \
                    module_globals(mod.tree)
        for mod in project.modules:
            if mod.tree is None:
                continue
            aliases = import_aliases(mod.tree)
            self._walk_module(mod, aliases)
        self._report_mixed()
        self._report_inversions()
        return self.findings

    def _walk_module(self, mod, aliases):
        def walk_body(nodes, class_name):
            for node in nodes:
                if isinstance(node, ast.ClassDef):
                    walk_body(node.body, node.name)
                elif isinstance(node, (ast.FunctionDef,
                                       ast.AsyncFunctionDef)):
                    w = _FnWalker(self, mod, aliases, class_name, node)
                    for stmt in node.body:
                        w.visit(stmt)
                    # nested defs run with their own (empty) stack
                    for sub in ast.walk(node):
                        if sub is not node and isinstance(
                                sub, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)):
                            wn = _FnWalker(self, mod, aliases,
                                           class_name, sub)
                            for stmt in sub.body:
                                wn.visit(stmt)

        walk_body(mod.tree.body, None)

    def _report_mixed(self):
        for key, sites in sorted(self.writes.items()):
            locked = [s for s in sites if s[3]]
            unlocked = [s for s in sites if not s[3]]
            if not locked or not unlocked:
                continue
            lock_names = sorted({_lock_label(l) for s in locked
                                 for l in s[3]})
            sym = key[1:]
            label = ("%s.%s" % (sym[1], sym[2]) if sym[0] == "attr"
                     else sym[1])
            for path, line, col, _ in unlocked:
                # the example guarded site goes in the HINT: messages are
                # baseline fingerprints and must stay line-independent
                self.findings.append(Finding(
                    RULE, path, line, col,
                    "'%s' is written under %s elsewhere but written "
                    "without the lock here"
                    % (label, "/".join(lock_names)),
                    hint="take the lock (guarded write at %s:%d), or "
                         "document why this site is single-threaded "
                         "and allow() it"
                         % (locked[0][0], locked[0][1])))

    def _report_inversions(self):
        seen = set()
        for (a, b), sites in sorted(self.edges.items()):
            if (b, a) not in self.edges or (b, a) in seen:
                continue
            seen.add((a, b))
            other = self.edges[(b, a)]
            path, line = sites[0]
            self.findings.append(Finding(
                RULE, path, line, 0,
                "lock order inversion: %s -> %s here but %s -> %s "
                "elsewhere — concurrent paths can deadlock"
                % (_lock_label(a), _lock_label(b), _lock_label(b),
                   _lock_label(a)),
                hint="pick one global order and document it in the "
                     "module docstring (opposite order at %s:%d)"
                     % (other[0][0], other[0][1])))


PASS = Pass()
