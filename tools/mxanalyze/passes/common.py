"""Shared AST utilities for the passes: dotted-name flattening, literal
extraction, a per-module jit index (who is jit-wrapped, which argument
positions are static), and scope helpers."""
from __future__ import annotations

import ast

#: callables that wrap a Python function into a compiled/traced one.
JIT_WRAPPER_TAILS = ("jit", "tracked_jit", "pallas_call", "TrackedJit",
                    "checkpoint", "remat")


def dotted_parts(node):
    """Flatten a Name/Attribute chain into its name parts, unwrapping
    intermediate calls: ``telemetry.counter(...).inc`` ->
    ``["telemetry", "counter", "inc"]``. Returns [] when the base is not
    name-like (e.g. a subscript)."""
    parts = []
    while True:
        if isinstance(node, ast.Attribute):
            parts.append(node.attr)
            node = node.value
        elif isinstance(node, ast.Call):
            node = node.func
        elif isinstance(node, ast.Name):
            parts.append(node.id)
            return list(reversed(parts))
        else:
            return []


def dotted_str(node):
    return ".".join(dotted_parts(node))


def const_int(node):
    """The int value of a literal (allowing unary minus), else None."""
    if isinstance(node, ast.UnaryOp) and isinstance(node.op, ast.USub):
        inner = const_int(node.operand)
        return None if inner is None else -inner
    if isinstance(node, ast.Constant) and isinstance(node.value, int) \
            and not isinstance(node.value, bool):
        return node.value
    return None


def literal_int_seq(node):
    """ints of a literal int / tuple-or-list of literal ints; None when
    the expression is anything else (i.e. dynamically constructed)."""
    v = const_int(node)
    if v is not None:
        return [v]
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for elt in node.elts:
            v = const_int(elt)
            if v is None:
                return None
            out.append(v)
        return out
    return None


def is_jit_wrap_call(call):
    """True when ``call`` wraps a function for tracing: jax.jit(f),
    tracked_jit(f, site), pl.pallas_call(kernel, ...), TrackedJit(f, s),
    functools.partial(jax.jit, ...)(f) is NOT handled here (see
    partial_jit_target)."""
    parts = dotted_parts(call.func)
    if not parts:
        return False
    if parts[-1] in JIT_WRAPPER_TAILS:
        # `self.jit(...)` etc. still counts; a bare `jit` must not be a
        # local variable named jit — acceptable over-approximation.
        return True
    return False


def partial_jit_inner(call):
    """For ``partial(jax.jit, static_argnums=...)``: the inner jit call
    node-ish (returns the partial call itself when its first arg is a
    jit wrapper reference), else None."""
    parts = dotted_parts(call.func)
    if parts and parts[-1] == "partial" and call.args:
        first = dotted_parts(call.args[0])
        if first and first[-1] in JIT_WRAPPER_TAILS:
            return call
    return None


def wrapped_function_ref(call):
    """The AST node of the function being wrapped by a jit-wrap call:
    a Name (resolve against module defs), Lambda, or an inline def via
    decorator handled elsewhere. None when not identifiable."""
    if not call.args:
        return None
    arg0 = call.args[0]
    if isinstance(arg0, ast.Call) \
            and dotted_parts(arg0.func)[-1:] == ["partial"] \
            and arg0.args:
        arg0 = arg0.args[0]   # pallas_call(partial(kernel, ...), ...)
    if isinstance(arg0, (ast.Name, ast.Lambda)):
        return arg0
    if isinstance(arg0, ast.Attribute) \
            and isinstance(arg0.value, ast.Name) \
            and arg0.value.id == "self":
        return arg0   # self.method — resolved against the class
    return None


def static_positions(call):
    """Static argument positions declared on a jit-wrap call, and
    whether the declaration is a clean literal. Returns
    ``(positions or None, dynamic_node or None)`` — ``dynamic_node`` is
    the offending expression when static_argnums is not a literal."""
    for kw in call.keywords:
        if kw.arg == "static_argnums":
            seq = literal_int_seq(kw.value)
            if seq is None:
                return None, kw.value
            return set(seq), None
    return set(), None


class JitIndex:
    """Per-module map of jit-wrapped functions and jitted callables.

    - ``jitted_defs``: FunctionDef/AsyncFunctionDef/Lambda nodes whose
      bodies are traced (decorator or first-arg reference).
    - ``jitted_names``: dotted name (as written at the assignment, e.g.
      ``self._fwd`` or ``step``) -> set of static positions (None when
      unknown/dynamic), for call-site checks.
    - ``wrap_calls``: every jit-wrap Call node (for static_argnums
      linting).
    """

    def __init__(self, module):
        self.jitted_defs = []
        self.jitted_names = {}
        self.wrap_calls = []
        if module.tree is None:
            return
        defs = {}
        for node in ast.walk(module.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                defs.setdefault(node.name, []).append(node)
        # class -> {method name: def}, for `tracked_jit(self.method, ..)`
        methods = {}
        for node in ast.walk(module.tree):
            if isinstance(node, ast.ClassDef):
                methods[node.name] = {
                    m.name: m for m in node.body
                    if isinstance(m, (ast.FunctionDef,
                                      ast.AsyncFunctionDef))}
        # `kern = functools.partial(_kernel, ...)` indirection: resolve
        # the alias to the underlying def (the Pallas idiom)
        partial_alias = {}
        for node in ast.walk(module.tree):
            if isinstance(node, ast.Assign) \
                    and isinstance(node.value, ast.Call) \
                    and dotted_parts(node.value.func)[-1:] == ["partial"] \
                    and node.value.args \
                    and isinstance(node.value.args[0], ast.Name):
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        partial_alias.setdefault(tgt.id, set()).add(
                            node.value.args[0].id)
        # call node -> enclosing class name (for self.method resolution)
        call_class = {}
        for cls in ast.walk(module.tree):
            if isinstance(cls, ast.ClassDef):
                for sub in ast.walk(cls):
                    if isinstance(sub, ast.Call):
                        call_class[id(sub)] = cls.name
        seen = set()
        wrap_seen = set()

        def mark(node):
            if id(node) not in seen:
                seen.add(id(node))
                self.jitted_defs.append(node)

        def add_wrap(call):
            # a decorator Call is ALSO reached by ast.walk — record each
            # wrap site once or static_argnums lints double-count
            if id(call) not in wrap_seen:
                wrap_seen.add(id(call))
                self.wrap_calls.append(call)

        for node in ast.walk(module.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for dec in node.decorator_list:
                    target = dec.func if isinstance(dec, ast.Call) else dec
                    parts = dotted_parts(target)
                    inner = None
                    if isinstance(dec, ast.Call):
                        inner = partial_jit_inner(dec)
                    if (parts and parts[-1] in JIT_WRAPPER_TAILS) or inner:
                        mark(node)
                        if isinstance(dec, ast.Call):
                            add_wrap(dec)
            if not isinstance(node, ast.Call):
                continue
            call = node
            if partial_jit_inner(call) is not None:
                add_wrap(call)
                continue
            if not is_jit_wrap_call(call):
                continue
            add_wrap(call)
            ref = wrapped_function_ref(call)
            if isinstance(ref, ast.Lambda):
                mark(ref)
            elif isinstance(ref, ast.Name):
                names = {ref.id} if ref.id in defs \
                    else partial_alias.get(ref.id, set())
                for name in names:
                    for d in defs.get(name, ()):
                        mark(d)
            elif isinstance(ref, ast.Attribute):   # self.method
                cls = call_class.get(id(call))
                target = methods.get(cls, {}).get(ref.attr)
                if target is not None:
                    mark(target)

        # names bound to jit-wrapped callables: `f = jax.jit(g, ...)`,
        # `self._fwd = tracked_jit(step, "site")`
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Assign) \
                    or not isinstance(node.value, ast.Call):
                continue
            call = node.value
            if not (is_jit_wrap_call(call)
                    or partial_jit_inner(call) is not None):
                continue
            pos, dyn = static_positions(call)
            for tgt in node.targets:
                name = dotted_str(tgt)
                if name:
                    self.jitted_names[name] = (None if dyn is not None
                                               else pos)


def jit_index(module):
    """Memoized :class:`JitIndex` for a module — the jit-purity and
    retrace-hazard passes share one instance instead of each paying the
    multi-traversal construction (and risking divergent views)."""
    ix = getattr(module, "_jit_index", None)
    if ix is None:
        ix = JitIndex(module)
        module._jit_index = ix
    return ix


def local_bindings(fn):
    """Over-approximate set of names bound inside ``fn`` (params,
    assignments, loop/with/except/comprehension targets, inner defs),
    nested scopes included."""
    names = set()
    args = fn.args
    for a in (args.posonlyargs + args.args + args.kwonlyargs
              + ([args.vararg] if args.vararg else [])
              + ([args.kwarg] if args.kwarg else [])):
        names.add(a.arg)
    for node in ast.walk(fn):
        if isinstance(node, ast.Name) and isinstance(
                node.ctx, (ast.Store, ast.Del)):
            names.add(node.id)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.ClassDef)):
            names.add(node.name)
        elif isinstance(node, (ast.Import, ast.ImportFrom)):
            for alias in node.names:
                names.add((alias.asname or alias.name).split(".")[0])
    return names


def module_globals(tree):
    """Names assigned at module top level."""
    names = set()
    for node in tree.body:
        if isinstance(node, ast.Assign):
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    names.add(tgt.id)
                elif isinstance(tgt, ast.Tuple):
                    for e in tgt.elts:
                        if isinstance(e, ast.Name):
                            names.add(e.id)
        elif isinstance(node, ast.AnnAssign) \
                and isinstance(node.target, ast.Name):
            names.add(node.target.id)
    return names


def import_aliases(tree):
    """bound name -> full dotted import target (relative dots dropped):
    ``import time as _t`` -> {"_t": "time"};
    ``from jax import random`` -> {"random": "jax.random"};
    ``from .. import telemetry`` -> {"telemetry": "telemetry"}."""
    out = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                if alias.asname:
                    out[alias.asname] = alias.name
                else:
                    root = alias.name.split(".")[0]
                    out[root] = root
        elif isinstance(node, ast.ImportFrom):
            for alias in node.names:
                target = alias.name if not node.module \
                    else "%s.%s" % (node.module, alias.name)
                if node.level:   # relative: inside this package, never a
                    # stdlib module — anchor it so `from .. import random`
                    # cannot shadow stdlib deny prefixes
                    target = "mxnet_tpu." + target
                out[alias.asname or alias.name] = target
    return out
