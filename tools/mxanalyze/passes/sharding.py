"""sharding-reachability: specs that never constrain anything, and
parallel modules no frontend can reach.

The runtime complement is shardprof's placement audit (flagged
replicated params, bad_rows); this pass catches the same
silent-replication class before a run:

1. dead spec: a name assigned from ``PartitionSpec(...)`` / ``P(...)``
   / ``policy.param_spec(...)`` that is never read afterwards — the
   spec was constructed but reaches no placement sink (NamedSharding /
   device_put / in_shardings / with_sharding_constraint), so the
   parameter it described stays replicated without a word.
2. dead public surface: a module under ``mxnet_tpu/parallel/`` whose
   public names are referenced by NOTHING in the analyzed tree except
   the package ``__init__`` re-export — a parallelism feature that no
   frontend (module/gluon/serving) can reach is integration debt
   (ROADMAP item 2), surfaced here so it is either wired up or
   annotated, not silently shipped.

The dead-surface rule only fires when the analyzed project actually
contains frontend modules (something under ``mxnet_tpu/`` outside
``parallel/``) — a single-file or ``--changed-only`` run must not call
everything dead for lack of visible callers.
"""
from __future__ import annotations

import ast

from ..core import Finding
from .common import dotted_parts, import_aliases

RULE = "sharding-reachability"

_SPEC_TAILS = {"PartitionSpec", "param_spec", "batch_spec"}
_PKG = "mxnet_tpu/parallel/"


def _functions(tree):
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node


def _public_names(mod):
    """__all__ when declared, else top-level public defs/classes."""
    for node in mod.tree.body:
        if isinstance(node, ast.Assign):
            for tgt in node.targets:
                if isinstance(tgt, ast.Name) and tgt.id == "__all__" \
                        and isinstance(node.value, (ast.List, ast.Tuple)):
                    return {e.value for e in node.value.elts
                            if isinstance(e, ast.Constant)
                            and isinstance(e.value, str)}
    return {n.name for n in mod.tree.body
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.ClassDef))
            and not n.name.startswith("_")}


def _referenced_tokens(mod):
    """Every identifier ``mod`` could be reaching another module by:
    import target segments, attribute names, bare names."""
    toks = set()
    for target in import_aliases(mod.tree).values():
        toks.update(target.split("."))
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Attribute):
            toks.add(node.attr)
        elif isinstance(node, ast.Name):
            toks.add(node.id)
    return toks


class Pass:
    rule = RULE

    def run(self, project):
        findings = []
        for mod in project.modules:
            if mod.tree is None:
                continue
            if mod.relpath.startswith("mxnet_tpu/"):
                findings.extend(self._check_dead_specs(mod))
        findings.extend(self._check_dead_surface(project))
        return findings

    # (1) spec constructed but never read
    def _check_dead_specs(self, mod):
        out = []
        aliases = import_aliases(mod.tree)
        spec_ctors = set(_SPEC_TAILS)
        for name, target in aliases.items():
            if target.split(".")[-1] in ("PartitionSpec",):
                spec_ctors.add(name)   # `from jax.sharding import
                # PartitionSpec as P` makes bare P(...) a spec ctor
        for fn in _functions(mod.tree):
            assigns = []   # (name, assign node)
            for node in ast.walk(fn):
                if isinstance(node, ast.Assign) \
                        and len(node.targets) == 1 \
                        and isinstance(node.targets[0], ast.Name) \
                        and isinstance(node.value, ast.Call):
                    parts = dotted_parts(node.value.func)
                    if parts and parts[-1] in spec_ctors:
                        assigns.append((node.targets[0].id, node))
            for name, node in assigns:
                used = any(
                    isinstance(sub, ast.Name) and sub.id == name
                    and isinstance(sub.ctx, ast.Load)
                    and (sub.lineno, sub.col_offset)
                    > (node.lineno, node.col_offset)
                    for sub in ast.walk(fn))
                if not used:
                    out.append(Finding(
                        RULE, mod.relpath, node.lineno, node.col_offset,
                        "sharding spec '%s' is constructed but never "
                        "reaches a placement sink — the array it "
                        "describes stays silently replicated" % name,
                        hint="apply it (NamedSharding/device_put/"
                             "in_shardings/with_sharding_constraint) "
                             "or delete it"))
        return out

    # (2) parallel module unreachable from any frontend
    def _check_dead_surface(self, project):
        out = []
        candidates, referencers, frontends = [], [], 0
        for mod in project.modules:
            if mod.tree is None:
                continue
            if mod.relpath.startswith(_PKG):
                if mod.stem != "__init__" \
                        and not mod.stem.startswith("_"):
                    candidates.append(mod)
                if mod.stem != "__init__":
                    referencers.append(mod)
            elif mod.relpath.startswith("mxnet_tpu/"):
                referencers.append(mod)
                frontends += 1
        if not candidates or not frontends:
            return out
        for mod in candidates:
            public = _public_names(mod)
            reach = public | {mod.stem}
            reached = False
            for other in referencers:
                if other is mod:
                    continue
                if reach & _referenced_tokens(other):
                    reached = True
                    break
            if not reached:
                line = 1
                for node in mod.tree.body:   # anchor on __all__ if any
                    if isinstance(node, ast.Assign) and any(
                            isinstance(t, ast.Name)
                            and t.id == "__all__"
                            for t in node.targets):
                        line = node.lineno
                        break
                out.append(Finding(
                    RULE, mod.relpath, line, 0,
                    "public surface (%s) is unreachable from any "
                    "frontend: only the package __init__ re-exports it"
                    % ", ".join(sorted(public)[:4] + (
                        ["..."] if len(public) > 4 else [])),
                    hint="wire it into a frontend path (ROADMAP item "
                         "2) or annotate the integration debt"))
        return out


PASS = Pass()
