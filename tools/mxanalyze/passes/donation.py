"""donation-hazard: buffer donation without the policy point, and
use-after-donation.

``compiled.donate_argnums_for(ctx, argnums)`` is the repo's SINGLE
donation decision point: it strips the donation set on backends without
donation (CPU) so the same step code runs everywhere, and it is where
MXNET_SPMD_DONATE-style policy lands. Two hazards around it:

1. a jit/tracked_jit/CompiledProgram wrap site passing a NON-EMPTY
   ``donate_argnums`` that did not route through
   ``donate_argnums_for`` — on CPU the raw set either errors or
   silently no-ops depending on jax version, and policy knobs stop
   applying. The literal empty tuple ``()`` is fine (no donation).
2. use-after-donation: after calling a jitted callable whose wrap site
   donates argument position ``i``, the OLD buffer passed at ``i`` is
   dead — a later read of that name observes a deleted array on real
   backends (and version-dependent behavior elsewhere). Donated
   positions are resolved from the wrap site (literal tuple, either
   branch of a conditional, or the second argument of
   ``donate_argnums_for``) and joined to call sites through the
   assigned callable name.

``mxnet_tpu/compiled.py`` itself is exempt: it DEFINES the policy point
and forwards the already-decided set into ``jax.jit``.
"""
from __future__ import annotations

import ast

from ..core import Finding
from .common import dotted_parts, dotted_str, jit_index, literal_int_seq
from .retrace import _expr_walk, _stmts_in_order

RULE = "donation-hazard"

_ROUTER = "donate_argnums_for"


def _functions(tree):
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node


def _enclosing_fn_map(tree):
    """node id -> nearest enclosing FunctionDef."""
    out = {}
    for fn in _functions(tree):
        for sub in ast.walk(fn):
            out[id(sub)] = fn   # later (inner) fns overwrite — nearest
    return out


def _is_router_call(node):
    return isinstance(node, ast.Call) \
        and dotted_parts(node.func)[-1:] == [_ROUTER]


def _assigns_to(fn, name):
    """Value expressions assigned to ``name`` inside ``fn`` (or the
    whole module when fn is None)."""
    vals = []
    scope = fn if fn is not None else None
    if scope is None:
        return vals
    for node in ast.walk(scope):
        if isinstance(node, ast.Assign):
            for tgt in node.targets:
                if isinstance(tgt, ast.Name) and tgt.id == name:
                    vals.append(node.value)
    return vals


def _donated_positions(value, fn, seen=None):
    """Union of argument positions ``value`` can donate; None when the
    expression is unresolvable (dynamic). Resolves literals, both arms
    of an IfExp, the router's second argument, and local Name
    assignments. ``seen`` breaks `donate = router(ctx, donate)`
    self-reference cycles; an unresolvable VARIANT of a name is skipped
    rather than poisoning the union (linter over-approximation)."""
    seen = frozenset() if seen is None else seen
    seq = literal_int_seq(value)
    if seq is not None:
        return set(seq)
    if isinstance(value, ast.IfExp):
        a = _donated_positions(value.body, fn, seen)
        b = _donated_positions(value.orelse, fn, seen)
        if a is None or b is None:
            return None
        return a | b
    if _is_router_call(value):
        if len(value.args) >= 2:
            return _donated_positions(value.args[1], fn, seen)
        return None
    if isinstance(value, ast.Name) and fn is not None:
        if value.id in seen:
            return None
        pos, resolved = set(), False
        for v in _assigns_to(fn, value.id):
            p = _donated_positions(v, fn, seen | {value.id})
            if p is None:
                continue
            resolved = True
            pos |= p
        return pos if resolved else None
    return None


def _routed(value, fn, depth=0):
    """True when the donate_argnums expression went through the
    policy router: a router call, the empty tuple (explicit
    no-donation), a conditional whose every arm is one of those, or a
    name assigned from one."""
    if depth > 4:
        return False
    if _is_router_call(value):
        return True
    if literal_int_seq(value) == []:
        return True   # `router(...) if cond else ()` arms
    if isinstance(value, ast.IfExp):
        return _routed(value.body, fn, depth + 1) \
            and _routed(value.orelse, fn, depth + 1)
    if isinstance(value, ast.Name) and fn is not None:
        return any(_routed(v, fn, depth + 1)
                   for v in _assigns_to(fn, value.id))
    return False


class Pass:
    rule = RULE

    def run(self, project):
        findings = []
        for mod in project.modules:
            if mod.tree is None:
                continue
            if not mod.relpath.startswith("mxnet_tpu/"):
                continue
            if mod.relpath == "mxnet_tpu/compiled.py":
                continue   # defines the router; forwards decided sets
            index = jit_index(mod)
            enclosing = _enclosing_fn_map(mod.tree)
            donating_names = {}
            for call in index.wrap_calls:
                findings.extend(self._check_wrap(
                    mod, call, enclosing, donating_names))
            findings.extend(self._check_use_after(
                mod, donating_names))
        return findings

    # (1) unrouted donation at the wrap site
    def _check_wrap(self, mod, call, enclosing, donating_names):
        out = []
        for kw in call.keywords:
            if kw.arg != "donate_argnums":
                continue
            fn = enclosing.get(id(call))
            seq = literal_int_seq(kw.value)
            if seq == []:
                continue   # donate_argnums=() — explicit no-donation
            if not _routed(kw.value, fn):
                out.append(Finding(
                    RULE, mod.relpath, kw.value.lineno,
                    kw.value.col_offset,
                    "donate_argnums bypasses donate_argnums_for: the "
                    "donation set is not stripped on CPU backends and "
                    "ignores the repo-wide donation policy",
                    hint="wrap the set: donate_argnums="
                         "compiled.donate_argnums_for(ctx, <set>)"))
            # even an unrouted site participates in use-after checks
            pos = _donated_positions(kw.value, fn)
            if pos:
                self._note_donating_name(call, mod, pos, donating_names)
        return out

    @staticmethod
    def _note_donating_name(call, mod, pos, donating_names):
        """Record ``name -> donated positions`` for every name the wrap
        result is assigned to (`step_fn = tracked_jit(..., donate...)`)."""
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Assign) and node.value is call:
                for tgt in node.targets:
                    name = dotted_str(tgt)
                    if name:
                        donating_names.setdefault(name, set()).update(pos)

    # (2) reads of a donated buffer after the donating call
    def _check_use_after(self, mod, donating_names):
        out = []
        if not donating_names:
            return out
        # names flow through containers (fused_plan tuples); track by
        # BARE tail too: `step_fn = tracked_jit(...)` rebound via
        # `..., step_fn, _ = self._fused_plan` keeps the name
        tails = {}
        for name, pos in donating_names.items():
            tails.setdefault(name.split(".")[-1], set()).update(pos)
        for fn in _functions(mod.tree):
            donated_vars = {}   # var name -> (callee, lineno)
            for stmt in _stmts_in_order(fn.body):
                for node in _expr_walk(stmt):
                    if isinstance(node, ast.Name) \
                            and isinstance(node.ctx, ast.Load) \
                            and node.id in donated_vars:
                        callee, _ln = donated_vars[node.id]
                        out.append(Finding(
                            RULE, mod.relpath, node.lineno,
                            node.col_offset,
                            "use after donation: '%s' was donated to "
                            "'%s' — the old buffer is deleted once the "
                            "dispatch runs with donation enabled"
                            % (node.id, callee),
                            hint="read the value BEFORE the donating "
                                 "call, or use the program's returned "
                                 "buffer"))
                        del donated_vars[node.id]   # one report per use
                for node in _expr_walk(stmt):
                    if not isinstance(node, ast.Call):
                        continue
                    name = dotted_str(node.func)
                    pos = donating_names.get(name)
                    if pos is None and isinstance(node.func, ast.Name):
                        pos = tails.get(node.func.id)
                    if not pos:
                        continue
                    for i in pos:
                        if i < len(node.args) and isinstance(
                                node.args[i], ast.Name):
                            donated_vars[node.args[i].id] = (
                                name or node.func.id, node.lineno)
                if isinstance(stmt, ast.Assign):
                    for tgt in stmt.targets:
                        for sub in ast.walk(tgt):
                            if isinstance(sub, ast.Name):
                                donated_vars.pop(sub.id, None)
        return out


PASS = Pass()
