"""retrace-hazard: lexical patterns that unbound the XLA signature set.

Complements the runtime retrace EXPLAINER in ``xla_stats.py`` (which
names the changed dimension AFTER a retrace happened) with the checks
that prevent the hazard from landing:

1. ``static_argnums``/``static_argnames`` built dynamically (not an int
   literal / literal tuple of ints) — the static set silently varies
   between wrap sites, so signatures multiply.
2. an unhashable literal (list/dict/set) passed at a call site in a
   position the same-module jit wrap declared static — hash() raises at
   dispatch, or worse, a tuple-ified copy compiles per value.
3. a Python scalar derived from ``.shape`` / ``len()`` passed as a
   TRACED argument to a known-jitted callable — shape-like values want
   to be static (or re-derived inside the trace); traced, they turn a
   shape change into a silent weak-typed constant or a per-call device
   transfer.
4. raw (unbucketed) batch shapes reaching the serving engine: in
   ``mxnet_tpu/serving/`` (outside ``batching.py``'s ladder) a row
   count derived from request data (``.n`` / ``len()`` / ``.shape`` /
   ``sum()``) must flow through ``pick_bucket`` before it shapes an
   array, or the bounded-signature guarantee (warm-compiled buckets,
   ``cold_compiles() == 0``) silently breaks.
"""
from __future__ import annotations

import ast

from ..core import Finding
from .common import (dotted_parts, dotted_str, jit_index,
                     static_positions)

RULE = "retrace-hazard"

_SHAPE_FNS = {"len"}
_NP_SHAPE_CTORS = {"zeros", "ones", "empty", "full"}


def _is_shape_read(node):
    """``x.shape`` or ``x.shape[i]``."""
    if isinstance(node, ast.Subscript):
        node = node.value
    return isinstance(node, ast.Attribute) and node.attr == "shape"


class _Taint:
    """Forward single-pass taint over one function body."""

    def __init__(self, sources_attrs=(), sanitizers=("pick_bucket",)):
        self.tainted = set()
        self.sources_attrs = set(sources_attrs)   # attr names like "n"
        self.sanitizers = set(sanitizers)

    def expr_tainted(self, node):
        if isinstance(node, ast.Name):
            return node.id in self.tainted
        if _is_shape_read(node):
            return True
        if isinstance(node, ast.Attribute):
            return node.attr in self.sources_attrs
        if isinstance(node, ast.Call):
            parts = dotted_parts(node.func)
            if parts and parts[-1] in self.sanitizers:
                return False
            if parts and parts[-1] in _SHAPE_FNS | {"sum"} \
                    and ("sum" in self.sources_attrs or
                         parts[-1] in _SHAPE_FNS):
                return True
            if parts == ["int"] and node.args:
                return self.expr_tainted(node.args[0])
            return False
        if isinstance(node, ast.BinOp):
            return self.expr_tainted(node.left) \
                or self.expr_tainted(node.right)
        if isinstance(node, ast.UnaryOp):
            return self.expr_tainted(node.operand)
        return False

    def note_assign(self, node):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name):
            name = node.targets[0].id
            if self.expr_tainted(node.value):
                self.tainted.add(name)
            else:
                self.tainted.discard(name)
        elif isinstance(node, ast.AugAssign) \
                and isinstance(node.target, ast.Name):
            if self.expr_tainted(node.value):
                self.tainted.add(node.target.id)


def _functions(tree):
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node


_OWN_SCOPE = (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)


def _stmts_in_order(body):
    """Statements in source/execution order, recursing into compound
    bodies but NOT into nested defs/classes (their own scope — they are
    analyzed as their own functions)."""
    for node in body:
        if isinstance(node, ast.ExceptHandler):
            yield from _stmts_in_order(node.body)
            continue
        if isinstance(node, _OWN_SCOPE):
            continue
        yield node
        for _field, value in ast.iter_fields(node):
            if isinstance(value, list) and value and isinstance(
                    value[0], (ast.stmt, ast.ExceptHandler)):
                yield from _stmts_in_order(value)


def _expr_walk(stmt):
    """Expression nodes of one statement, pruning child statements
    (yielded separately by ``_stmts_in_order``) and nested scopes."""
    for child in ast.iter_child_nodes(stmt):
        if isinstance(child, (ast.stmt, ast.ExceptHandler, ast.Lambda)) \
                or isinstance(child, _OWN_SCOPE):
            continue
        yield child
        yield from _expr_walk(child)


def _ordered_exprs(fn, taint):
    """Single forward pass: yield each statement's expression nodes for
    sink checks, THEN fold its assignment into the taint state — a later
    rebinding can neither taint nor sanitize an earlier call site."""
    for stmt in _stmts_in_order(fn.body):
        yield from _expr_walk(stmt)
        taint.note_assign(stmt)


class Pass:
    rule = RULE

    def run(self, project):
        findings = []
        for mod in project.modules:
            if mod.tree is None:
                continue
            index = jit_index(mod)
            findings.extend(self._check_static_argnums(mod, index))
            findings.extend(self._check_call_sites(mod, index))
            if mod.relpath.startswith("mxnet_tpu/serving/") \
                    and mod.stem != "batching":
                findings.extend(self._check_serving(mod))
        return findings

    # (1) dynamically-constructed static_argnums / names
    def _check_static_argnums(self, mod, index):
        out = []
        for call in index.wrap_calls:
            for kw in call.keywords:
                if kw.arg not in ("static_argnums", "static_argnames"):
                    continue
                if kw.arg == "static_argnames":
                    ok = isinstance(kw.value, ast.Constant) or (
                        isinstance(kw.value, (ast.Tuple, ast.List))
                        and all(isinstance(e, ast.Constant)
                                for e in kw.value.elts))
                else:
                    _, dyn = static_positions(call)
                    ok = dyn is None
                if not ok:
                    out.append(Finding(
                        RULE, mod.relpath, kw.value.lineno,
                        kw.value.col_offset,
                        "%s is not a literal (dynamically constructed "
                        "static set): every construction variant is a "
                        "distinct jit signature" % kw.arg,
                        hint="spell the static positions as an int/"
                             "tuple literal at the wrap site"))
        return out

    # (2) unhashable static values + (3) shape-derived traced scalars
    def _check_call_sites(self, mod, index):
        out = []
        if not index.jitted_names:
            return out
        for fn in _functions(mod.tree):
            taint = _Taint()
            for node in _ordered_exprs(fn, taint):
                if not isinstance(node, ast.Call):
                    continue
                name = dotted_str(node.func)
                if name not in index.jitted_names:
                    continue
                static = index.jitted_names[name]
                for i, arg in enumerate(node.args):
                    is_static = static is not None and i in static
                    if is_static and isinstance(
                            arg, (ast.List, ast.Dict, ast.Set,
                                  ast.ListComp, ast.DictComp,
                                  ast.SetComp)):
                        out.append(Finding(
                            RULE, mod.relpath, arg.lineno,
                            arg.col_offset,
                            "unhashable %s passed for static arg %d of "
                            "jitted '%s': static args must hash to hit "
                            "the jit cache"
                            % (type(arg).__name__.lower(), i, name),
                            hint="pass a tuple / frozen value"))
                    elif not is_static and taint.expr_tainted(arg):
                        out.append(Finding(
                            RULE, mod.relpath, arg.lineno,
                            arg.col_offset,
                            "Python scalar derived from .shape/len() "
                            "passed as traced arg %d of jitted '%s'"
                            % (i, name),
                            hint="mark the position static, or derive "
                                 "the value inside the traced function"))
        return out

    # (4) unbucketed batch shapes in serving code
    def _check_serving(self, mod):
        out = []
        for fn in _functions(mod.tree):
            taint = _Taint(sources_attrs={"n", "sum"})
            for node in _ordered_exprs(fn, taint):
                sink = self._serving_sink(node, taint)
                if sink is not None:
                    out.append(Finding(
                        RULE, mod.relpath, sink.lineno, sink.col_offset,
                        "request-derived row count shapes an array "
                        "outside the bucket ladder: signatures become "
                        "unbounded and steady-state serving recompiles",
                        hint="route the count through "
                             "batching.pick_bucket() first"))
        return out

    @staticmethod
    def _serving_sink(node, taint):
        # (n,) + shape  — shape-tuple construction with tainted head
        if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Add) \
                and isinstance(node.left, ast.Tuple) and node.left.elts:
            if taint.expr_tainted(node.left.elts[0]):
                return node.left.elts[0]
        if isinstance(node, ast.Call):
            parts = dotted_parts(node.func)
            if parts and parts[-1] == "pad_rows" and len(node.args) >= 2 \
                    and taint.expr_tainted(node.args[1]):
                return node.args[1]
            if parts and parts[-1] in _NP_SHAPE_CTORS and node.args \
                    and isinstance(node.args[0], ast.Tuple) \
                    and node.args[0].elts \
                    and taint.expr_tainted(node.args[0].elts[0]):
                return node.args[0].elts[0]
        return None


PASS = Pass()
