"""Pass registry: one module per rule, each exporting ``PASS``."""
from . import (dispatch, donation, envvars, hostsync, jit_purity, locks,
               retrace, sharding, swallowed, threads)

#: run order is reporting order for ties; findings are re-sorted anyway.
ALL_PASSES = [jit_purity.PASS, retrace.PASS, locks.PASS, swallowed.PASS,
              envvars.PASS, hostsync.PASS, dispatch.PASS, donation.PASS,
              sharding.PASS, threads.PASS]

__all__ = ["ALL_PASSES"]
