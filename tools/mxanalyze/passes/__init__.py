"""Pass registry: one module per rule, each exporting ``PASS``."""
from . import envvars, jit_purity, locks, retrace, swallowed

#: run order is reporting order for ties; findings are re-sorted anyway.
ALL_PASSES = [jit_purity.PASS, retrace.PASS, locks.PASS, swallowed.PASS,
              envvars.PASS]

__all__ = ["ALL_PASSES"]
