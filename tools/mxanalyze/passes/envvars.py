"""env-var-drift: every ``MXNET_*`` read in code must have a row in
``docs/env_var.md``.

Readers recognized: ``os.environ.get/setdefault``, ``os.environ[...]``,
``os.getenv``, the serving helper ``_env_num(name, ...)``, and
``RetryPolicy.from_env(prefix)`` — the latter expands to the three
``<PREFIX>_MAX_ATTEMPTS`` / ``<PREFIX>_BASE_DELAY`` /
``<PREFIX>_MAX_DELAY`` knobs it actually reads. Doc tokens ending in
``*`` match as prefixes, so one wildcard row can cover a family.

Mentions in comments/docstrings do NOT count as reads (only AST call /
subscript sites do), and only string literals are checked — a
dynamically built name is the caller's responsibility.
"""
from __future__ import annotations

import ast
import os
import re

from ..core import Finding
from .common import dotted_parts

RULE = "env-var-drift"

_ENV_NAME = re.compile(r"^MXNET_[A-Z0-9_]+$")
_DOC_TOKEN = re.compile(r"MXNET_[A-Z0-9_]+\*?")
_FROM_ENV_SUFFIXES = ("_MAX_ATTEMPTS", "_BASE_DELAY", "_MAX_DELAY")


def _literal_str(node):
    return node.value if isinstance(node, ast.Constant) \
        and isinstance(node.value, str) else None


def _env_reads(tree):
    """Yield (env_name, node) for every recognized read site."""
    for node in ast.walk(tree):
        if isinstance(node, ast.Subscript):
            parts = dotted_parts(node.value)
            if parts[-1:] == ["environ"]:
                name = _literal_str(node.slice)
                if name:
                    yield name, node
        elif isinstance(node, ast.Call):
            parts = dotted_parts(node.func)
            if not parts:
                continue
            tail = parts[-1]
            name = _literal_str(node.args[0]) if node.args else None
            if name is None:
                continue
            if tail in ("get", "setdefault") \
                    and parts[-2:-1] == ["environ"]:
                yield name, node
            elif tail == "getenv" and parts[:1] == ["os"]:
                yield name, node
            elif tail == "_env_num":
                yield name, node
            elif tail == "from_env" and "RetryPolicy" in parts:
                # only RetryPolicy.from_env has the *_MAX_ATTEMPTS /
                # *_BASE_DELAY / *_MAX_DELAY expansion; an unrelated
                # from_env classmethod must not create phantom rows
                for suffix in _FROM_ENV_SUFFIXES:
                    yield name + suffix, node


def _documented(doc_path):
    """(exact names, prefix names) found in TABLE ROWS of the doc. A
    prose mention ("no analog: MXNET_FOO") is deliberately not a row —
    the gate's contract is a row with default + meaning, and prose must
    not be able to satisfy it."""
    try:
        with open(doc_path, "r", encoding="utf-8") as fh:
            lines = fh.read().splitlines()
    except OSError:
        return set(), set()
    exact, prefixes = set(), set()
    for line in lines:
        if not line.lstrip().startswith("|"):
            continue
        for tok in _DOC_TOKEN.findall(line):
            if tok.endswith("*"):
                prefixes.add(tok[:-1])
            else:
                exact.add(tok)
    return exact, prefixes


class Pass:
    rule = RULE

    def run(self, project):
        exact, prefixes = _documented(project.env_doc)
        doc_rel = os.path.relpath(project.env_doc,
                                  project.root).replace(os.sep, "/")
        findings = []
        for mod in project.modules:
            if mod.tree is None:
                continue
            seen = set()   # one finding per (file, var)
            for name, node in _env_reads(mod.tree):
                if not _ENV_NAME.match(name) or name in seen:
                    continue
                seen.add(name)
                if name in exact or any(name.startswith(p)
                                        for p in prefixes):
                    continue
                findings.append(Finding(
                    RULE, mod.relpath, node.lineno, node.col_offset,
                    "env var %s is read here but has no row in %s"
                    % (name, doc_rel),
                    hint="add a row (variable, default, meaning) to "
                         "the doc, or rename the knob"))
        return findings


PASS = Pass()
