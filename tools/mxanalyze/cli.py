"""CLI: ``python -m tools.mxanalyze [--strict] [--update-baseline]
[--changed-only] [--profile DIR] [--witness DIR] [paths...]``.

Exit codes follow ``tools/bench_gate.py``: 0 = gate passes, 1 = gate
fails, 2 = usage error; the last stdout line is a BENCH-style JSON
record (``{"metric": "mxanalyze_gate", "status": ...}``) so the same
log-scraping that gates perf regressions gates analysis regressions.

``--changed-only`` scopes the run to the files git says changed
(worktree vs HEAD, plus untracked) — same rules, same exit codes, a
fast incremental gate. ``--profile <telemetry-dir>`` additionally joins
the findings with stepprof/shardprof/runprof runtime verdicts: findings
a verdict explains are escalated to error (baseline amnesty does not
apply) and a second ``mxanalyze_perf_gate`` line is emitted.
``--witness <telemetry-dir>`` does the same join against a live
``MXNET_THREADSAN=1`` lock witness: runtime acquisition-order edges
merge into the static inversion check, hazard reports escalate their
explaining rules, and an ``mxanalyze_threads_gate`` line is emitted
whose failure detail names the worst contended lock.
"""
from __future__ import annotations

import argparse
import json
import os
import sys

from .baseline import (default_baseline_path, diff_baseline,
                       load_baseline, save_baseline)
from .core import (RULES, analyze_paths, repo_root,
                   scope_prefixes)

DEFAULT_PATHS = ["mxnet_tpu"]


def gate_line(status, detail, out=None, metric="mxanalyze_gate",
              **extra):
    # out resolves to the CURRENT sys.stdout per call (same lesson as
    # bench_gate.gate_records): a module-level default would bind
    # whatever capture stream was live at first import and break every
    # later redirected caller
    out = out if out is not None else sys.stdout
    rec = dict({"metric": metric, "status": status,
                "detail": detail}, **extra)
    out.write(json.dumps(rec) + "\n")


def changed_files(root, scope):
    """Repo-relative .py files git reports changed (worktree vs HEAD,
    plus untracked), filtered to the requested ``scope`` prefixes.
    Raises OSError when git itself fails — the gate must not silently
    pass because the diff could not be computed."""
    import subprocess
    names = set()
    for cmd in (["git", "-C", root, "diff", "--name-only", "HEAD"],
                ["git", "-C", root, "ls-files", "--others",
                 "--exclude-standard"]):
        try:
            proc = subprocess.run(cmd, capture_output=True, text=True,
                                  timeout=30)
        except (OSError, subprocess.SubprocessError) as exc:
            raise OSError("git diff failed: %s" % exc)
        if proc.returncode != 0:
            raise OSError("git diff failed: %s"
                          % proc.stderr.strip().splitlines()[-1:]
                          or proc.returncode)
        names.update(ln.strip() for ln in proc.stdout.splitlines()
                     if ln.strip())
    out = []
    for rel in sorted(names):
        if not rel.endswith(".py"):
            continue
        if not any(rel == p or rel.startswith(p) for p in scope):
            continue
        if os.path.exists(os.path.join(root, rel)):   # deletions drop
            out.append(rel)
    return out


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="mxanalyze",
        description="JAX-aware static analysis gate (rules: %s)"
                    % ", ".join(sorted(RULES)))
    ap.add_argument("paths", nargs="*", default=None,
                    help="files/dirs to analyze (default: mxnet_tpu/)")
    ap.add_argument("--strict", action="store_true",
                    help="also fail on stale baseline entries")
    ap.add_argument("--changed-only", action="store_true",
                    help="analyze only files git reports changed "
                         "(within the given paths); same exit codes")
    ap.add_argument("--profile", default=None, metavar="DIR",
                    help="telemetry dir of stepprof/shardprof/runprof "
                         "host snapshots: escalate findings matching "
                         "runtime verdicts and emit an "
                         "mxanalyze_perf_gate line")
    ap.add_argument("--witness", default=None, metavar="DIR",
                    help="telemetry dir (or one file) of threadsan "
                         "lock-witness snapshots: merge runtime lock-"
                         "order edges into the inversion check, "
                         "escalate findings witness hazards confirm, "
                         "and emit an mxanalyze_threads_gate line")
    ap.add_argument("--update-baseline", action="store_true",
                    help="rewrite the baseline from this run and exit 0")
    ap.add_argument("--baseline", default=None,
                    help="baseline file (default: tools/mxanalyze/"
                         "baseline.json)")
    ap.add_argument("--env-doc", default=None,
                    help="env-var doc the drift pass checks against "
                         "(default: docs/env_var.md)")
    ap.add_argument("--format", choices=("text", "json"), default="text",
                    help="findings output format")
    ap.add_argument("--all", action="store_true",
                    help="print baselined findings too, not just new")
    args = ap.parse_args(argv)

    root = repo_root()
    paths = args.paths or DEFAULT_PATHS
    if args.changed_only:
        try:
            paths = changed_files(root, scope_prefixes(paths, root))
        except OSError as exc:
            print("mxanalyze: %s" % exc, file=sys.stderr)
            return 2
        if not paths:
            gate_line("pass", "changed-only: no changed files in scope",
                      new=0, baselined=0, stale=0)
            return 0
    try:
        findings = analyze_paths(paths, root=root, env_doc=args.env_doc)
    except OSError as exc:
        print("mxanalyze: %s" % exc, file=sys.stderr)
        return 2

    # every run is scoped — the default run to DEFAULT_PATHS — and
    # baseline entries OUTSIDE the scope are invisible to it: an update
    # must preserve them and --strict must not call them stale
    scope = scope_prefixes(paths, root)

    def in_scope(fp):
        return any(fp[1] == p or fp[1].startswith(p) for p in scope)

    baseline_path = args.baseline or default_baseline_path()
    if args.update_baseline:
        try:
            old = load_baseline(baseline_path)
        except ValueError as exc:
            print("mxanalyze: %s (a scoped --update-baseline needs "
                  "the existing entries to merge)" % exc,
                  file=sys.stderr)
            return 2
        keep = {fp: n for fp, n in old.items() if not in_scope(fp)}
        n = save_baseline(baseline_path, findings, keep=keep)
        gate_line("pass", "baseline rewritten: %d entries (%d findings, "
                  "%d kept out-of-scope) -> %s"
                  % (n, len(findings), sum(keep.values()), baseline_path),
                  findings=len(findings), entries=n)
        return 0

    try:
        baseline = load_baseline(baseline_path)
    except ValueError as exc:
        print("mxanalyze: %s" % exc, file=sys.stderr)
        return 2
    new, baselined, stale = diff_baseline(findings, baseline)
    stale = {fp: n for fp, n in stale.items() if in_scope(fp)}

    # --profile: escalation runs over ALL findings (baselined included)
    # BEFORE printing, so escalated findings render with their tag and
    # surface even when the baseline would have hidden them
    verdicts, escalated = [], []
    if args.profile is not None:
        from . import profiles
        verdicts = profiles.read_verdicts(args.profile)
        escalated = profiles.escalate(findings, verdicts)

    # --witness: same placement as --profile — escalation must precede
    # printing so witness-confirmed findings surface with their tag
    wit_docs, wit_reports, wit_inversions, wit_escalated = [], [], [], []
    if args.witness is not None:
        from . import witness
        wit_docs = witness.read(args.witness)
        wit_reports = witness.runtime_reports(wit_docs)
        wit_inversions = witness.merged_inversions(
            witness.runtime_edges(wit_docs),
            witness.static_edge_labels())
        wit_escalated = witness.escalate(findings, wit_reports)

    shown = findings if args.all else sorted(
        set(new) | set(escalated) | set(wit_escalated),
        key=lambda f: f.sort_key())
    if args.format == "json":
        doc = {"findings": [f.to_dict() for f in shown],
               "new": len(new), "baselined": len(baselined),
               "stale": sum(stale.values())}
        if args.profile is not None:
            doc["verdicts"] = verdicts
            doc["escalated"] = len(escalated)
        if args.witness is not None:
            doc["witness_reports"] = wit_reports
            doc["witness_inversions"] = wit_inversions
            doc["witness_escalated"] = len(wit_escalated)
        print(json.dumps(doc, indent=1))
    else:
        for v in verdicts:
            print("runtime verdict [%s, %s]: %s%s"
                  % (v["verdict"], v["source"], v["file"],
                     " -- " + v["detail"] if v["detail"] else ""))
        if args.witness is not None:
            from . import witness
            for rep in wit_reports:
                print(witness.render_report(rep))
            for inv in wit_inversions:
                print("witness inversion: %s (%s)"
                      % (inv["pair"], "; ".join(inv["sources"])))
        new_set = set(new)
        for f in shown:
            tag = "" if f in new_set else " [baselined]"
            print(f.render() + tag)
        for fp, n in sorted(stale.items()):
            print("stale baseline entry (finding fixed -- run "
                  "--update-baseline): [%s] %s: %s (x%d)"
                  % (fp[0], fp[1], fp[2], n))

    failed = bool(new) or (args.strict and stale)
    detail = ("%d new finding(s)" % len(new) if new else
              "%d stale baseline entr(ies)" % sum(stale.values())
              if args.strict and stale else
              "clean: %d finding(s), all baselined" % len(baselined))
    gate_line("fail" if failed else "pass", detail, new=len(new),
              baselined=len(baselined), stale=sum(stale.values()))

    if args.profile is not None:
        perf_failed = bool(escalated)
        if not verdicts:
            perf_detail = "no profiler verdicts under %s" % args.profile
        elif escalated:
            perf_detail = ("%d finding(s) escalated by runtime "
                           "verdict(s) %s"
                           % (len(escalated), ", ".join(
                               sorted({f.escalated for f in escalated}))))
        else:
            perf_detail = ("%d verdict(s), no matching findings"
                           % len(verdicts))
        gate_line("fail" if perf_failed else "pass", perf_detail,
                  metric="mxanalyze_perf_gate",
                  verdicts=[v["verdict"] for v in verdicts],
                  escalated=len(escalated))
        failed = failed or perf_failed

    if args.witness is not None:
        from . import witness
        threads_failed = bool(wit_reports or wit_inversions
                              or wit_escalated)
        worst_name, worst = witness.worst_contended(
            witness.lock_stats(wit_docs))
        if not wit_docs:
            threads_detail = "no witness files under %s" % args.witness
        elif threads_failed:
            threads_detail = ("%d hazard report(s), %d inversion(s), "
                              "%d escalated"
                              % (len(wit_reports), len(wit_inversions),
                                 len(wit_escalated)))
            if worst_name:
                threads_detail += (
                    "; worst contended lock: %s (%.3fs waited over %d "
                    "contended acquires)"
                    % (worst_name, worst["wait_total"],
                       worst["contended"]))
        else:
            threads_detail = ("witness clean: %d lock(s), %d edge(s), "
                              "no hazards"
                              % (len(witness.lock_stats(wit_docs)),
                                 len(witness.runtime_edges(wit_docs))))
        gate_line("fail" if threads_failed else "pass", threads_detail,
                  metric="mxanalyze_threads_gate",
                  reports=len(wit_reports),
                  inversions=len(wit_inversions),
                  escalated=len(wit_escalated),
                  worst_contended=worst_name)
        failed = failed or threads_failed
    return 1 if failed else 0
