"""CLI: ``python -m tools.mxanalyze [--strict] [--update-baseline]
[paths...]``.

Exit codes follow ``tools/bench_gate.py``: 0 = gate passes, 1 = gate
fails, 2 = usage error; the last stdout line is a BENCH-style JSON
record (``{"metric": "mxanalyze_gate", "status": ...}``) so the same
log-scraping that gates perf regressions gates analysis regressions.
"""
from __future__ import annotations

import argparse
import json
import sys

from .baseline import (default_baseline_path, diff_baseline,
                       load_baseline, save_baseline)
from .core import (RULES, analyze_paths, repo_root,
                   scope_prefixes)

DEFAULT_PATHS = ["mxnet_tpu"]


def gate_line(status, detail, out=None, **extra):
    # out resolves to the CURRENT sys.stdout per call (same lesson as
    # bench_gate.gate_records): a module-level default would bind
    # whatever capture stream was live at first import and break every
    # later redirected caller
    out = out if out is not None else sys.stdout
    rec = dict({"metric": "mxanalyze_gate", "status": status,
                "detail": detail}, **extra)
    out.write(json.dumps(rec) + "\n")


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="mxanalyze",
        description="JAX-aware static analysis gate (rules: %s)"
                    % ", ".join(sorted(RULES)))
    ap.add_argument("paths", nargs="*", default=None,
                    help="files/dirs to analyze (default: mxnet_tpu/)")
    ap.add_argument("--strict", action="store_true",
                    help="also fail on stale baseline entries")
    ap.add_argument("--update-baseline", action="store_true",
                    help="rewrite the baseline from this run and exit 0")
    ap.add_argument("--baseline", default=None,
                    help="baseline file (default: tools/mxanalyze/"
                         "baseline.json)")
    ap.add_argument("--env-doc", default=None,
                    help="env-var doc the drift pass checks against "
                         "(default: docs/env_var.md)")
    ap.add_argument("--format", choices=("text", "json"), default="text",
                    help="findings output format")
    ap.add_argument("--all", action="store_true",
                    help="print baselined findings too, not just new")
    args = ap.parse_args(argv)

    root = repo_root()
    paths = args.paths or DEFAULT_PATHS
    try:
        findings = analyze_paths(paths, root=root, env_doc=args.env_doc)
    except OSError as exc:
        print("mxanalyze: %s" % exc, file=sys.stderr)
        return 2

    # every run is scoped — the default run to DEFAULT_PATHS — and
    # baseline entries OUTSIDE the scope are invisible to it: an update
    # must preserve them and --strict must not call them stale
    scope = scope_prefixes(paths, root)

    def in_scope(fp):
        return any(fp[1] == p or fp[1].startswith(p) for p in scope)

    baseline_path = args.baseline or default_baseline_path()
    if args.update_baseline:
        try:
            old = load_baseline(baseline_path)
        except ValueError as exc:
            print("mxanalyze: %s (a scoped --update-baseline needs "
                  "the existing entries to merge)" % exc,
                  file=sys.stderr)
            return 2
        keep = {fp: n for fp, n in old.items() if not in_scope(fp)}
        n = save_baseline(baseline_path, findings, keep=keep)
        gate_line("pass", "baseline rewritten: %d entries (%d findings, "
                  "%d kept out-of-scope) -> %s"
                  % (n, len(findings), sum(keep.values()), baseline_path),
                  findings=len(findings), entries=n)
        return 0

    try:
        baseline = load_baseline(baseline_path)
    except ValueError as exc:
        print("mxanalyze: %s" % exc, file=sys.stderr)
        return 2
    new, baselined, stale = diff_baseline(findings, baseline)
    stale = {fp: n for fp, n in stale.items() if in_scope(fp)}

    shown = findings if args.all else new
    if args.format == "json":
        print(json.dumps({
            "findings": [f.to_dict() for f in shown],
            "new": len(new), "baselined": len(baselined),
            "stale": sum(stale.values())}, indent=1))
    else:
        for f in shown:
            tag = "" if f in new else " [baselined]"
            print(f.render() + tag)
        for fp, n in sorted(stale.items()):
            print("stale baseline entry (finding fixed -- run "
                  "--update-baseline): [%s] %s: %s (x%d)"
                  % (fp[0], fp[1], fp[2], n))

    failed = bool(new) or (args.strict and stale)
    detail = ("%d new finding(s)" % len(new) if new else
              "%d stale baseline entr(ies)" % sum(stale.values())
              if args.strict and stale else
              "clean: %d finding(s), all baselined" % len(baselined))
    gate_line("fail" if failed else "pass", detail, new=len(new),
              baselined=len(baselined), stale=sum(stale.values()))
    return 1 if failed else 0
