"""Checked-in baseline for incremental adoption.

``baseline.json`` records the findings the tree is ALLOWED to have —
pre-existing debt, adopted without a flag day. The gate then fails only
on findings beyond the baseline ("new"), and ``--strict`` additionally
fails on *stale* entries (baselined findings that no longer exist —
somebody fixed debt and must shrink the baseline with
``--update-baseline``, so the recorded debt only ever goes down).

Fingerprints are ``(rule, path, message)`` with a count per
fingerprint — line numbers are excluded so edits above a baselined
finding do not churn the file.
"""
from __future__ import annotations

import json
from collections import Counter


def default_baseline_path():
    import os
    return os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "baseline.json")


def load_baseline(path):
    """fingerprint -> allowed count. Absent file = empty baseline; a
    CORRUPT file (conflict markers, hand-edit damage) raises ValueError
    with the path named — the gate must fail loudly as a usage error,
    not silently treat recorded debt as gone."""
    try:
        with open(path, "r", encoding="utf-8") as fh:
            doc = json.load(fh)
    except OSError:
        return Counter()
    except ValueError as exc:
        raise ValueError("baseline %s is not valid JSON (%s) — fix it "
                         "or regenerate with --update-baseline"
                         % (path, exc)) from exc
    out = Counter()
    try:
        for rec in doc.get("entries", []):
            fp = (rec["rule"], rec["path"], rec["message"])
            out[fp] += int(rec.get("count", 1))
    except (AttributeError, KeyError, TypeError, ValueError) as exc:
        raise ValueError("baseline %s is malformed (%s: %s) — "
                         "regenerate with --update-baseline"
                         % (path, type(exc).__name__, exc)) from exc
    return out


def save_baseline(path, findings, keep=None):
    """Write the baseline from ``findings``; ``keep`` (fingerprint ->
    count) carries entries OUTSIDE the analyzed scope that a subset
    update must preserve rather than silently drop."""
    counts = Counter(f.fingerprint() for f in findings)
    for fp, n in (keep or {}).items():
        counts[fp] += n
    entries = [{"rule": fp[0], "path": fp[1], "message": fp[2],
                "count": n}
               for fp, n in sorted(counts.items())]
    doc = {"version": 1, "tool": "mxanalyze", "entries": entries}
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(doc, fh, indent=1, sort_keys=True)
        fh.write("\n")
    return len(entries)


def diff_baseline(findings, baseline):
    """Split findings into (new, baselined) and report stale entries.

    Per fingerprint, the first ``allowed`` instances (line order) are
    baselined; any beyond that are new. Returns
    ``(new, baselined, stale)`` where ``stale`` is a dict
    fingerprint -> count of baseline entries with no live finding.
    """
    new, baselined = [], []
    used = Counter()
    for f in sorted(findings, key=lambda f: f.sort_key()):
        fp = f.fingerprint()
        if used[fp] < baseline.get(fp, 0):
            used[fp] += 1
            baselined.append(f)
        else:
            new.append(f)
    stale = {}
    for fp, allowed in baseline.items():
        if used[fp] < allowed:
            stale[fp] = allowed - used[fp]
    return new, baselined, stale
