"""``--witness``: join static findings with a runtime lock witness.

Reads the ``threadsan_host<h>_pid<p>.json`` snapshots a live
``MXNET_THREADSAN=1`` run drops into the telemetry dir (same
``write_host_json`` transport as the profiler snapshots; parsed here
with ``json`` only — the analyzer never imports the analyzed code):

- **edges**: acquisition-order edges actually witnessed at runtime.
  Merged into the static inversion check: a runtime ``A -> B`` paired
  with a static or runtime ``B -> A`` is an inversion even when one
  side was invisible to the lexical walker (callback indirection,
  locks passed through queues).
- **reports**: hazards the witness filed (``potential_deadlock``,
  ``held_across_dispatch``, ``blocked_too_long``). Each kind ESCALATES
  the static findings that explain it — a live deadlock witness means
  the baseline's amnesty for ``lock-discipline`` /
  ``cross-thread-state`` findings no longer applies.
- **stats**: per-lock wait/hold aggregates; the gate's failure detail
  names the worst contended lock so the log line alone says where to
  look.

The CLI emits a BENCH-style ``mxanalyze_threads_gate`` line that fails
on any hazard report, merged inversion, or escalation.
"""
from __future__ import annotations

import fnmatch
import json
import os

from .passes import locks

#: witness report kind -> static rules it escalates (all under
#: mxnet_tpu/ — the witness only ever wraps project locks)
ESCALATIONS = {
    "potential_deadlock": ("lock-discipline", "cross-thread-state"),
    "held_across_dispatch": ("cross-thread-state", "host-sync-hazard"),
    "blocked_too_long": ("lock-discipline",),
}
_PREFIX = "mxnet_tpu/"


def witness_files(dirpath):
    try:
        names = sorted(os.listdir(dirpath))
    except OSError:
        return []
    return [os.path.join(dirpath, fn) for fn in names
            if fnmatch.fnmatch(fn, "threadsan_host*.json")]


def has_witness(dirpath):
    return bool(witness_files(dirpath))


def read(path_or_dir):
    """Witness docs: one file -> ``[doc]``; a dir -> the freshest doc
    per host (same freshest-wins rule as the telemetry merge, mirrored
    not imported)."""
    if os.path.isfile(path_or_dir):
        doc = _read_json(path_or_dir)
        return [doc] if isinstance(doc, dict) else []
    by_host = {}
    for path in witness_files(path_or_dir):
        doc = _read_json(path)
        if not isinstance(doc, dict):
            continue
        host = doc.get("host", 0)
        kept = by_host.get(host)
        if kept is None or doc.get("updated", 0) > kept.get("updated", 0):
            by_host[host] = doc
    return [by_host[h] for h in sorted(by_host)]


def _read_json(path):
    try:
        with open(path, "r", encoding="utf-8") as fh:
            return json.load(fh)
    except (OSError, ValueError):
        return None


def runtime_edges(docs):
    """(outer, inner) -> summed witnessed count across hosts."""
    out = {}
    for doc in docs:
        for e in doc.get("edges") or []:
            key = (e.get("outer"), e.get("inner"))
            if None in key:
                continue
            out[key] = out.get(key, 0) + int(e.get("count", 1))
    return out


def runtime_reports(docs):
    """Hazard reports across hosts, deduplicated by (kind, cycle/lock)
    so N hosts hitting the same hazard read as one verdict."""
    out, seen = [], set()
    for doc in docs:
        for rep in doc.get("reports") or []:
            kind = rep.get("kind")
            key = (kind, json.dumps(rep.get("cycle")
                                    or rep.get("lock")
                                    or rep.get("locks"), sort_keys=True))
            if kind is None or key in seen:
                continue
            seen.add(key)
            out.append(rep)
    return out


def lock_stats(docs):
    """name -> merged wait/hold aggregates (sums summed, maxes maxed)."""
    out = {}
    for doc in docs:
        for name, st in (doc.get("locks") or {}).items():
            agg = out.setdefault(name, {
                "acquires": 0, "contended": 0, "wait_total": 0.0,
                "wait_max": 0.0, "hold_total": 0.0, "hold_max": 0.0})
            for k in ("acquires", "contended"):
                agg[k] += int(st.get(k, 0))
            for k in ("wait_total", "hold_total"):
                agg[k] += float(st.get(k, 0.0))
            for k in ("wait_max", "hold_max"):
                agg[k] = max(agg[k], float(st.get(k, 0.0)))
    return out


def worst_contended(stats):
    """(name, stats) of the contended lock threads waited on longest;
    (None, None) when no lock ever contended."""
    ranked = sorted(
        ((name, st) for name, st in stats.items() if st["contended"]),
        key=lambda kv: kv[1]["wait_total"])
    return ranked[-1] if ranked else (None, None)


def static_edge_labels():
    """The lock-order edges the last ``locks`` pass run recorded, as
    normalized ``stem.Class.attr`` / ``stem.name`` labels matching the
    witness's registration names (``.self.`` collapsed)."""
    out = {}
    for (a, b), sites in getattr(locks.PASS, "edges", {}).items():
        key = (_norm(locks._lock_label(a)), _norm(locks._lock_label(b)))
        out.setdefault(key, []).extend(sites)
    return out


def _norm(label):
    return label.replace(".self.", ".")


def merged_inversions(rt_edges, st_edges):
    """Inversions only the runtime witness can prove: a witnessed
    ``A -> B`` whose reverse edge exists at runtime or statically.
    Pure static-static inversions are already lock-discipline findings.
    Returns ``[{"pair", "sources"}]`` sorted, each pair once."""
    out, seen = [], set()
    for (a, b) in sorted(rt_edges):
        pair = tuple(sorted((a, b)))
        if pair in seen:
            continue
        sources = []
        if (b, a) in rt_edges:
            sources.append("runtime both ways")
        if (b, a) in st_edges:
            sources.append("static %s -> %s at %s:%d"
                           % ((b, a) + st_edges[(b, a)][0]))
        if sources:
            seen.add(pair)
            out.append({"pair": "%s -> %s" % (a, b),
                        "sources": sources})
    return out


def escalate(findings, reports):
    """Mark every static finding a witness hazard explains as escalated
    (severity becomes error; baseline amnesty overridden). Run over the
    FULL finding list, baselined included."""
    escalated = []
    for rep in reports:
        rules = ESCALATIONS.get(rep.get("kind"))
        if rules is None:
            continue
        for f in findings:
            if f.escalated or f.rule not in rules:
                continue
            if f.path.startswith(_PREFIX):
                f.escalated = "witness:%s" % rep["kind"]
                escalated.append(f)
    escalated.sort(key=lambda f: f.sort_key())
    return escalated


def render_report(rep):
    """One human line per hazard report (stacks summarized)."""
    kind = rep.get("kind", "?")
    if kind == "potential_deadlock":
        body = " -> ".join(rep.get("cycle") or [])
    elif kind == "held_across_dispatch":
        body = "%s held entering %s" % (
            "/".join(rep.get("locks") or []), rep.get("site", "?"))
    elif kind == "blocked_too_long":
        body = "%s blocked %.1fs" % (rep.get("lock", "?"),
                                     rep.get("waited_seconds", 0.0))
    else:
        body = json.dumps({k: v for k, v in rep.items()
                           if k not in ("kind", "stacks", "time")},
                          sort_keys=True)
    return "witness hazard [%s]: %s" % (kind, body)
