"""Framework spine: findings model, suppression comments, source model,
pass registry, and the analysis driver.

Everything operates on parsed ``ast`` trees plus the raw source lines —
no imports of the analyzed code, so the analyzer runs in milliseconds
and cannot be affected by (or affect) runtime state.
"""
from __future__ import annotations

import ast
import os
import re
import tokenize

#: rule id -> one-line description (the registry the CLI prints)
RULES = {
    "jit-purity": "Python side effects lexically inside jit/Pallas-"
                  "wrapped functions run at trace time only",
    "retrace-hazard": "patterns that unbound the XLA signature set "
                      "(dynamic static_argnums, shape-derived scalars as "
                      "traced args, unbucketed serving shapes)",
    "lock-discipline": "state written both under and outside a lock, "
                       "inconsistent lock acquisition order, nested "
                       "non-reentrant locks",
    "swallowed-exception": "broad except handlers that neither raise, "
                           "log, nor bump a telemetry counter",
    "env-var-drift": "MXNET_* env var read in code but undocumented in "
                     "docs/env_var.md",
    "host-sync-hazard": "device->host synchronization inside step/fit/"
                        "serving hot loops (asnumpy/item/float/branching "
                        "on device values, unsampled block_until_ready)",
    "dispatch-amplification": "per-layer/per-param Python loops that "
                              "multiply dispatches (scan-over-layers and "
                              "fused-optimizer candidates)",
    "donation-hazard": "jit/CompiledProgram sites replacing param/"
                       "optimizer buffers without donate_argnums_for, "
                       "or reading a buffer after donating it",
    "sharding-reachability": "sharding specs with no in-program "
                             "constraint path, and parallel modules "
                             "unreachable from any frontend",
    "cross-thread-state": "state written from >=2 thread entry roots "
                          "with at least one write outside any lock; "
                          "bare Condition.wait() without a while-"
                          "predicate loop",
    "bad-suppression": "malformed mxanalyze suppression comment",
    "parse-error": "file could not be parsed",
}

SEVERITY = {
    "jit-purity": "error",
    "retrace-hazard": "warning",
    "lock-discipline": "warning",
    "swallowed-exception": "warning",
    "env-var-drift": "error",
    "host-sync-hazard": "warning",
    "dispatch-amplification": "warning",
    "donation-hazard": "error",
    "sharding-reachability": "warning",
    "cross-thread-state": "warning",
    "bad-suppression": "warning",
    "parse-error": "error",
}


def repo_root():
    """The repository root (two levels above this package)."""
    return os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))


class Finding:
    """One diagnostic: rule id, severity, location, message, fix hint.

    The baseline fingerprint is ``(rule, path, message)`` — line numbers
    are deliberately excluded so unrelated edits above a baselined
    finding do not churn ``baseline.json``.
    """

    __slots__ = ("rule", "path", "line", "col", "message", "hint",
                 "escalated")

    def __init__(self, rule, path, line, col, message, hint=""):
        self.rule = rule
        self.path = path.replace(os.sep, "/")
        self.line = int(line)
        self.col = int(col)
        self.message = message
        self.hint = hint
        #: runtime-verdict name when --profile promoted this finding
        #: to error (e.g. "dispatch-bound"), else None
        self.escalated = None

    @property
    def severity(self):
        if self.escalated:
            return "error"
        return SEVERITY.get(self.rule, "warning")

    def fingerprint(self):
        return (self.rule, self.path, self.message)

    def sort_key(self):
        return (self.path, self.line, self.col, self.rule, self.message)

    def to_dict(self):
        d = {"rule": self.rule, "severity": self.severity,
             "path": self.path, "line": self.line, "col": self.col,
             "message": self.message, "hint": self.hint}
        if self.escalated:
            d["escalated_by"] = self.escalated
        return d

    def render(self):
        out = "%s:%d:%d: [%s] %s: %s" % (
            self.path, self.line, self.col, self.rule, self.severity,
            self.message)
        if self.escalated:
            out += " [escalated by runtime verdict: %s]" % self.escalated
        if self.hint:
            out += " (hint: %s)" % self.hint
        return out

    def __repr__(self):
        return "Finding(%s)" % self.render()


# ---------------------------------------------------------------------------
# Suppression comments
# ---------------------------------------------------------------------------
#
#   x = eval(s)  # mxanalyze: allow(jit-purity): trace-time by design
#
# applies to its own physical line; a comment-only line also covers the
# next line. Multiple rules: allow(rule-a, rule-b); allow(*) covers all.
# The ": <reason>" is REQUIRED — a reasonless allow() does not suppress
# and is itself reported as `bad-suppression`.

_SUPPRESS_RE = re.compile(
    r"#\s*mxanalyze:\s*allow\(\s*([^)]*)\s*\)\s*(?::\s*(\S.*))?")


def _parse_suppressions(text, path):
    """(line -> set(rule)), plus bad-suppression findings.

    Parsed from the tokenizer's COMMENT tokens, not raw lines — an
    ``allow(...)`` inside a string literal (help text, test fixture)
    must neither suppress anything nor be flagged as malformed."""
    supp = {}
    findings = []
    import io
    try:
        tokens = [t for t in tokenize.generate_tokens(
            io.StringIO(text).readline) if t.type == tokenize.COMMENT]
    except (tokenize.TokenError, IndentationError, SyntaxError):
        return supp, findings   # parse-error finding covers the file
    for tok in tokens:
        m = _SUPPRESS_RE.search(tok.string)
        if not m:
            continue
        i, col = tok.start
        rules_raw, reason = m.group(1), m.group(2)
        rules = {r.strip() for r in rules_raw.split(",") if r.strip()}
        bad = [r for r in rules if r != "*" and r not in RULES]
        if not reason or not rules or bad:
            detail = ("unknown rule(s) %s" % ", ".join(sorted(bad))
                      if bad else "missing ': <reason>'"
                      if not reason else "empty rule list")
            findings.append(Finding(
                "bad-suppression", path, i, col,
                "suppression comment is malformed (%s) and does not "
                "suppress anything" % detail,
                hint="write `# mxanalyze: allow(<rule>): <reason>`"))
            continue
        targets = [i]
        if tok.line[:col].strip() == "":
            targets.append(i + 1)   # standalone comment covers next line
        for ln in targets:
            supp.setdefault(ln, set()).update(rules)
    return supp, findings


# ---------------------------------------------------------------------------
# Source model
# ---------------------------------------------------------------------------

class SourceModule:
    """One parsed file: tree + lines + suppression map."""

    def __init__(self, path, relpath, text):
        self.path = path
        self.relpath = relpath.replace(os.sep, "/")
        self.text = text
        self.lines = text.splitlines()
        self.suppressions, self.own_findings = _parse_suppressions(
            text, self.relpath)
        try:
            self.tree = ast.parse(text, filename=path)
        except SyntaxError as exc:
            self.tree = None
            self.own_findings.append(Finding(
                "parse-error", self.relpath, exc.lineno or 1, 0,
                "syntax error: %s" % exc.msg))

    @property
    def stem(self):
        return os.path.splitext(os.path.basename(self.relpath))[0]

    def suppressed(self, line, rule):
        rules = self.suppressions.get(line)
        return bool(rules) and ("*" in rules or rule in rules)


class Project:
    """All modules under analysis plus repo-level context."""

    def __init__(self, modules, root=None, env_doc=None):
        self.modules = modules
        self.root = root or repo_root()
        self.env_doc = env_doc or os.path.join(self.root, "docs",
                                               "env_var.md")


def iter_py_files(paths, root):
    """Sorted .py files under ``paths`` (files or directories),
    __pycache__ pruned."""
    out = []
    for p in paths:
        ap = resolve_path(p, root)
        if os.path.isfile(ap):
            out.append(ap)
            continue
        if not os.path.isdir(ap):
            # a typo'd CI path must not silently gate zero files as pass
            raise OSError("path %r does not exist (resolved to %s)"
                          % (p, ap))
        for dirpath, dirnames, filenames in os.walk(ap):
            dirnames[:] = sorted(d for d in dirnames
                                 if d != "__pycache__"
                                 and not d.startswith("."))
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    out.append(os.path.join(dirpath, fn))
    return sorted(set(out))


def resolve_path(p, root):
    """Resolve one CLI path argument: absolute as-is; relative against
    the cwd first (normal CLI convention), then the repo root (so
    ``-m tools.mxanalyze mxnet_tpu/`` works from anywhere, e.g. a CI
    step with its own cwd). The ONE resolution rule — analysis scope
    and baseline-update scope must never disagree."""
    if os.path.isabs(p):
        return p
    ap = os.path.abspath(p)
    return ap if os.path.exists(ap) else os.path.join(root, p)


def scope_prefixes(paths, root):
    """Repo-relative coverage of ``paths``: exact relpaths for files,
    ``<relpath>/`` prefixes for directories — so a scoped
    ``--update-baseline`` / ``--strict`` knows which baseline entries
    the run can actually see."""
    out = []
    for p in paths:
        ap = resolve_path(p, root)
        rel = os.path.relpath(ap, root).replace(os.sep, "/")
        if rel == ".":
            out.append("")   # the repo root: matches every entry
        elif os.path.isfile(ap):
            out.append(rel)
        else:
            out.append(rel.rstrip("/") + "/")
    return out


def load_modules(paths, root):
    mods = []
    for path in iter_py_files(paths, root):
        rel = os.path.relpath(path, root)
        try:
            with tokenize.open(path) as fh:   # honors coding cookies
                text = fh.read()
        except (OSError, UnicodeDecodeError, SyntaxError) as exc:
            mods.append(SourceModule.__new__(SourceModule))
            m = mods[-1]
            m.path, m.relpath, m.text, m.lines = path, rel, "", []
            m.suppressions, m.tree = {}, None
            m.own_findings = [Finding("parse-error", rel, 1, 0,
                                      "unreadable: %s" % exc)]
            continue
        mods.append(SourceModule(path, rel, text))
    return mods


def analyze_paths(paths, root=None, env_doc=None, passes=None):
    """Run every registered pass over ``paths``; returns the sorted,
    suppression-filtered finding list."""
    from .passes import ALL_PASSES
    root = root or repo_root()
    project = Project(load_modules(paths, root), root=root,
                      env_doc=env_doc)
    findings = []
    for mod in project.modules:
        findings.extend(mod.own_findings)
    for ps in (passes if passes is not None else ALL_PASSES):
        findings.extend(ps.run(project))
    by_rel = {m.relpath: m for m in project.modules}
    kept = []
    for f in findings:
        mod = by_rel.get(f.path)
        if mod is not None and f.rule != "bad-suppression" \
                and mod.suppressed(f.line, f.rule):
            continue
        kept.append(f)
    kept.sort(key=Finding.sort_key)
    return kept
