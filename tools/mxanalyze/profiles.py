"""``--profile``: join static findings with runtime profiler verdicts.

Reads the host snapshots the observability stack drops into a
telemetry dir (``telemetry.write_host_json`` transport):

- ``stepprof_host<h>_pid<p>.json``  — step anatomy; carries a
  ``verdict`` (input-bound / dispatch-bound / sync-bound /
  compute-bound / comm-bound) and ``hint``.
- ``shardprof_host<h>_pid<p>.json`` — sharding anatomy; the placement
  ``audit`` (flagged replicated params) and predicted ``comm``
  (overlap_fraction) synthesize verdicts here.
- ``runprof_i<r>_host<h>_pid<p>.json`` — run anatomy; the verdict is
  re-derived from ``states``/``goodput_fraction`` with the same
  dominant-badput rule as ``runprof.classify`` (re-implemented on
  purpose: the analyzer never imports the analyzed code).

Each verdict then ESCALATES the static findings that explain it — a
dispatch-bound step promotes ``dispatch-amplification`` findings in the
hot path to error severity, even when they are baselined (runtime
evidence says that debt is THE bottleneck now, so the baseline's
amnesty no longer applies). The CLI emits a BENCH-style
``mxanalyze_perf_gate`` line and fails when anything escalated.

Pure stdlib; snapshots are read with ``json`` only.
"""
from __future__ import annotations

import fnmatch
import json
import os

#: runprof's healthy-goodput floor and badput-state verdict names,
#: mirrored (NOT imported — see module docstring)
_HEALTHY_GOODPUT = 0.9
_STATE_VERDICT = {
    "init": "init-heavy",
    "compile": "compile-heavy",
    "checkpoint_save": "checkpoint-heavy",
    "checkpoint_restore": "checkpoint-heavy",
    "recovery": "recovery-heavy",
    "input_stall": "input-bound",
    "idle": "idle-heavy",
}

#: overlap below this fraction reads "collectives exposed on the step
#: critical path" (matches shardprof's overlap guidance)
_LOW_OVERLAP = 0.5

#: verdict -> (rules to escalate, repo-path prefixes the finding must
#: sit under). The prefixes keep a dispatch-bound verdict from
#: promoting, say, a serving-only finding.
_STEP_PATHS = ("mxnet_tpu/module/", "mxnet_tpu/executor",
               "mxnet_tpu/optimizer.py", "mxnet_tpu/gluon/trainer.py",
               "mxnet_tpu/parallel/")
_ANY = ("mxnet_tpu/",)
ESCALATIONS = {
    "dispatch-bound": (("dispatch-amplification",), _STEP_PATHS),
    "sync-bound": (("host-sync-hazard",), _ANY),
    "input-bound": (("host-sync-hazard",), _ANY),
    "comm-bound": (("donation-hazard", "sharding-reachability"), _ANY),
    "replicated-params": (("sharding-reachability",), _ANY),
    "unoverlapped-comm": (("donation-hazard",
                           "sharding-reachability"), _ANY),
    "compile-heavy": (("retrace-hazard",), _ANY),
}


def _read_json(path):
    try:
        with open(path, "r", encoding="utf-8") as fh:
            return json.load(fh)
    except (OSError, ValueError):
        return None


def snapshot_files(dirpath):
    """The profiler host snapshots present under ``dirpath``, by kind."""
    out = {"stepprof": [], "shardprof": [], "runprof": []}
    try:
        names = sorted(os.listdir(dirpath))
    except OSError:
        return out
    for fn in names:
        if not fn.endswith(".json"):
            continue
        if fnmatch.fnmatch(fn, "stepprof_host*.json"):
            out["stepprof"].append(os.path.join(dirpath, fn))
        elif fnmatch.fnmatch(fn, "shardprof_host*.json"):
            out["shardprof"].append(os.path.join(dirpath, fn))
        elif fnmatch.fnmatch(fn, "runprof*_host*.json") \
                and "progress" not in fn:
            out["runprof"].append(os.path.join(dirpath, fn))
    return out


def has_snapshots(dirpath):
    return any(snapshot_files(dirpath).values())


def read_verdicts(dirpath):
    """Every runtime verdict found in ``dirpath``'s snapshots, as
    ``{"verdict", "source", "file", "detail"}`` dicts (deduplicated by
    verdict name, first source wins)."""
    files = snapshot_files(dirpath)
    verdicts = []

    def add(verdict, source, path, detail=""):
        if verdict and not any(v["verdict"] == verdict
                               for v in verdicts):
            verdicts.append({"verdict": verdict, "source": source,
                             "file": os.path.basename(path),
                             "detail": detail})

    for path in files["stepprof"]:
        doc = _read_json(path)
        if not isinstance(doc, dict):
            continue
        add(doc.get("verdict"), "stepprof", path,
            detail=doc.get("hint", ""))
    for path in files["shardprof"]:
        doc = _read_json(path)
        if not isinstance(doc, dict):
            continue
        audit = doc.get("audit") or {}
        if audit.get("flagged"):
            add("replicated-params", "shardprof", path,
                detail="%s param(s) flagged replicated by the "
                       "placement audit" % audit.get("flagged"))
        comm = doc.get("comm") or {}
        ov = comm.get("overlap_fraction")
        if isinstance(ov, (int, float)) and ov < _LOW_OVERLAP:
            add("unoverlapped-comm", "shardprof", path,
                detail="overlap_fraction %.2f: predicted collectives "
                       "sit exposed on the step path" % ov)
    for path in files["runprof"]:
        doc = _read_json(path)
        if not isinstance(doc, dict):
            continue
        states = doc.get("states") or {}
        goodput = doc.get("goodput_fraction")
        total = sum(v for v in states.values()
                    if isinstance(v, (int, float)) and v > 0)
        if total <= 0:
            continue
        if goodput is None:
            goodput = states.get("train_productive", 0.0) / total
        if goodput >= _HEALTHY_GOODPUT:
            continue
        badput = {s: v for s, v in states.items()
                  if s != "train_productive"
                  and isinstance(v, (int, float))}
        if not badput:
            continue
        dominant = max(badput, key=lambda s: badput[s])
        if badput[dominant] <= 0:
            continue
        add(_STATE_VERDICT.get(dominant), "runprof", path,
            detail="goodput %.2f, dominant badput state '%s'"
                   % (goodput, dominant))
    return verdicts


def escalate(findings, verdicts):
    """Mark every finding a runtime verdict explains as escalated
    (severity becomes error). Returns the escalated findings, sorted.
    ``findings`` should be the FULL finding list (baselined included):
    runtime evidence overrides the baseline's amnesty."""
    escalated = []
    for v in verdicts:
        rule_paths = ESCALATIONS.get(v["verdict"])
        if rule_paths is None:
            continue
        rules, prefixes = rule_paths
        for f in findings:
            if f.escalated or f.rule not in rules:
                continue
            if any(f.path == p or f.path.startswith(p)
                   for p in prefixes):
                f.escalated = v["verdict"]
                escalated.append(f)
    escalated.sort(key=lambda f: f.sort_key())
    return escalated
