#!/usr/bin/env python
"""Stitch per-host telemetry JSONL event logs into one chrome-trace file.

Every process of a multi-host run writes its own
``events_host<h>_pid<p>.jsonl`` under ``MXNET_TELEMETRY_DIR``; this CLI
(`mxnet_tpu.telemetry.merge`) aligns them on wall-clock into ONE timeline
viewable in perfetto.dev or chrome://tracing, one trace-process row per
host/pid::

    python tools/merge_traces.py /tmp/run_telemetry -o run_trace.json
    python tools/merge_traces.py hostA.jsonl hostB.jsonl -o trace.json

Stdlib-only (imports just the telemetry module, which itself has no jax
dependency), so it runs on a machine with nothing but the repo checkout.
"""
import argparse
import importlib.util
import os


def _load_telemetry():
    """Load mxnet_tpu/telemetry.py as a standalone module: importing the
    mxnet_tpu PACKAGE would pull in jax, which this CLI must not need."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    path = os.path.join(repo, "mxnet_tpu", "telemetry.py")
    spec = importlib.util.spec_from_file_location("_mxt_telemetry", path)
    mod = importlib.util.module_from_spec(spec)
    saved = os.environ.pop("MXNET_TELEMETRY_DIR", None)
    try:
        # the merger must only READ the dir, not arm its own event log
        spec.loader.exec_module(mod)
    finally:
        if saved is not None:
            os.environ["MXNET_TELEMETRY_DIR"] = saved
    return mod


telemetry = _load_telemetry()


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("src", nargs="+",
                    help="telemetry dir(s) or .jsonl event file(s)")
    ap.add_argument("-o", "--out", default="merged_trace.json",
                    help="chrome-trace JSON output path")
    args = ap.parse_args(argv)
    paths = []
    for src in args.src:
        if not os.path.exists(src):
            ap.error("no such file or directory: %s" % src)
        paths.extend(telemetry._event_files(src))
    if not paths:
        ap.error("no .jsonl event files under %s" % (args.src,))
    trace = telemetry.merge(paths, out=args.out)
    n_procs = sum(1 for e in trace["traceEvents"]
                  if e.get("ph") == "M" and e.get("name") == "process_name")
    print("merged %d events from %d file(s) / %d process(es) -> %s"
          % (len(trace["traceEvents"]), len(paths), n_procs, args.out))


if __name__ == "__main__":
    main()
