#!/usr/bin/env python
"""Parse training logs produced by Module.fit / Speedometer
(reference tools/parse_log.py): extracts per-epoch train/validation
metrics and epoch time, printed as markdown or TSV.
"""
from __future__ import print_function

import argparse
import re
import sys


def parse_log(lines, metric_names):
    res = [re.compile(r".*Epoch\[(\d+)\] Train-" + s + r".*=([.\d]+)")
           for s in metric_names] \
        + [re.compile(r".*Epoch\[(\d+)\] Validation-" + s + r".*=([.\d]+)")
           for s in metric_names] \
        + [re.compile(r".*Epoch\[(\d+)\] Time.*=([.\d]+)")]
    data = {}
    for l in lines:
        m = None
        i = 0
        for r in res:
            m = r.match(l)
            if m is not None:
                break
            i += 1
        if m is None:
            continue
        epoch = int(m.groups()[0])
        val = float(m.groups()[1])
        if epoch not in data:
            data[epoch] = [0] * len(res) * 2
        data[epoch][i * 2] += val
        data[epoch][i * 2 + 1] += 1
    return data


def format_markdown(data, metric_names):
    lines = []
    n = len(metric_names)
    lines.append("| epoch | "
                 + " | ".join(["train-" + s for s in metric_names])
                 + " | " + " | ".join(["val-" + s for s in metric_names])
                 + " | time |")
    lines.append("| --- " * (2 * n + 2) + "|")
    for k, v in sorted(data.items()):
        cells = []
        for j in range(2 * n):
            cells.append("%f" % (v[2 * j] / v[2 * j + 1])
                         if v[2 * j + 1] else "-")
        t = "%.1f" % (v[-2] / v[-1]) if v[-1] else "-"
        lines.append("| %2d | " % (k + 1) + " | ".join(cells)
                     + " | %s |" % t)
    return "\n".join(lines)


def format_tsv(data, metric_names):
    n = len(metric_names)
    lines = ["\t".join(["epoch"]
                       + ["train-" + s for s in metric_names]
                       + ["val-" + s for s in metric_names] + ["time"])]
    for k, v in sorted(data.items()):
        cells = ["%2d" % (k + 1)]
        for j in range(2 * n):
            cells.append("%f" % (v[2 * j] / v[2 * j + 1])
                         if v[2 * j + 1] else "-")
        cells.append("%.1f" % (v[-2] / v[-1]) if v[-1] else "-")
        lines.append("\t".join(cells))
    return "\n".join(lines)


def main():
    parser = argparse.ArgumentParser(
        formatter_class=argparse.ArgumentDefaultsHelpFormatter,
        description="Parse mxnet output log")
    parser.add_argument("logfile", nargs=1, type=str,
                        help="the log file for parsing")
    parser.add_argument("--format", type=str, default="markdown",
                        choices=["markdown", "none"],
                        help="the format of the parsed output")
    parser.add_argument("--metric-names", type=str, nargs="+",
                        default=["accuracy"],
                        help="names of metrics in log which should be parsed")
    args = parser.parse_args()
    with open(args.logfile[0]) as f:
        lines = f.readlines()
    data = parse_log(lines, args.metric_names)
    if args.format == "markdown":
        print(format_markdown(data, args.metric_names))
    else:
        print(format_tsv(data, args.metric_names))


if __name__ == "__main__":
    main()
