#!/usr/bin/env python
"""Rebuild the .idx for an existing .rec file (reference tools/rec2idx.py).

Scans the RecordIO stream, recording each record's byte offset keyed by
the record's packed header id.
"""
from __future__ import print_function

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))


def make_index(rec_path, idx_path):
    from mxnet_tpu import recordio

    reader = recordio.MXRecordIO(rec_path, "r")
    with open(idx_path, "w") as fout:
        counter = 0
        while True:
            pos = reader.tell()
            item = reader.read()
            if item is None:
                break
            try:
                header, _ = recordio.unpack(item)
                key = header.id
            except Exception:
                key = counter
            fout.write("%s\t%d\n" % (str(key), pos))
            counter += 1
    reader.close()
    print("wrote %d index entries to %s" % (counter, idx_path))


def main():
    parser = argparse.ArgumentParser(
        description="Make index file from a RecordIO file")
    parser.add_argument("record", help="path to the .rec file")
    parser.add_argument("index", help="path to the output .idx file")
    args = parser.parse_args()
    make_index(args.record, args.index)


if __name__ == "__main__":
    main()
