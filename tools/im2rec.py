#!/usr/bin/env python
"""Create RecordIO packs from image folders (reference tools/im2rec.py,
tools/im2rec.cc). Two modes, same CLI contract as the reference:

  --list  : walk an image root, write a .lst file (index\\tlabel\\tpath)
  default : read a .lst, encode/augment images into .rec (+ .idx)

Decode/encode rides the framework's native codec (src/image_codec.cc)
with cv2/PIL fallbacks; records are written through MXIndexedRecordIO so
the .idx is produced in the same pass.
"""
from __future__ import print_function

import argparse
import os
import random
import sys
import time
import traceback

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))

import numpy as np  # noqa: E402


def list_image(root, recursive, exts):
    """Yield (index, relpath, label) tuples; label = folder id when
    recursive (reference im2rec.py list_image)."""
    i = 0
    if recursive:
        cat = {}
        for path, dirs, files in os.walk(root, followlinks=True):
            dirs.sort()
            files.sort()
            for fname in files:
                fpath = os.path.join(path, fname)
                suffix = os.path.splitext(fname)[1].lower()
                if os.path.isfile(fpath) and (suffix in exts):
                    if path not in cat:
                        cat[path] = len(cat)
                    yield (i, os.path.relpath(fpath, root), cat[path])
                    i += 1
        for k, v in sorted(cat.items(), key=lambda x: x[1]):
            print(os.path.relpath(k, root), v)
    else:
        for fname in sorted(os.listdir(root)):
            fpath = os.path.join(root, fname)
            suffix = os.path.splitext(fname)[1].lower()
            if os.path.isfile(fpath) and (suffix in exts):
                yield (i, os.path.relpath(fpath, root), 0)
                i += 1


def write_list(path_out, image_list):
    with open(path_out, "w") as fout:
        for i, item in enumerate(image_list):
            line = "%d\t" % item[0]
            for j in item[2:]:
                line += "%f\t" % j
            line += "%s\n" % item[1]
            fout.write(line)


def make_list(args):
    image_list = list(list_image(args.root, args.recursive, args.exts))
    if args.shuffle:
        random.seed(100)
        random.shuffle(image_list)
    N = len(image_list)
    chunk_size = (N + args.chunks - 1) // args.chunks
    for i in range(args.chunks):
        chunk = image_list[i * chunk_size:(i + 1) * chunk_size]
        if args.chunks > 1:
            str_chunk = ".part%03d" % i
        else:
            str_chunk = ""
        sep = int(chunk_size * args.train_ratio)
        sep_test = int(chunk_size * args.test_ratio)
        if args.train_ratio == 1.0:
            write_list(args.prefix + str_chunk + ".lst", chunk)
        else:
            if args.test_ratio:
                write_list(args.prefix + str_chunk + "_test.lst",
                           chunk[:sep_test])
            if args.train_ratio + args.test_ratio < 1.0:
                write_list(args.prefix + str_chunk + "_val.lst",
                           chunk[sep_test + sep:])
            write_list(args.prefix + str_chunk + "_train.lst",
                       chunk[sep_test:sep_test + sep])


def read_list(path_in):
    """Yield (index, path, *labels) from a .lst file."""
    with open(path_in) as fin:
        while True:
            line = fin.readline()
            if not line:
                break
            line = [i.strip() for i in line.strip().split("\t")]
            line_len = len(line)
            if line_len < 3:
                print("lst should have at least has three parts, but only "
                      "has %s parts for %s" % (line_len, line))
                continue
            try:
                item = [int(line[0])] + [line[-1]] \
                    + [float(i) for i in line[1:-1]]
            except Exception as e:
                print("Parsing lst met error for %s, detail: %s"
                      % (line, e))
                continue
            yield item


def image_encode(args, i, item, q_out):
    from mxnet_tpu import recordio
    from mxnet_tpu.image import codec, imresize
    from mxnet_tpu import ndarray as nd

    fullpath = os.path.join(args.root, item[1])
    if len(item) > 3 and args.pack_label:
        header = recordio.IRHeader(0, np.asarray(item[2:], "float32"),
                                   item[0], 0)
    else:
        header = recordio.IRHeader(0, item[2], item[0], 0)

    if args.pass_through:
        try:
            with open(fullpath, "rb") as fin:
                img = fin.read()
            s = recordio.pack(header, img)
            q_out.append((i, s, item))
        except Exception as e:
            traceback.print_exc()
            print("pack_img error:", item[1], e)
            q_out.append((i, None, item))
        return

    try:
        with open(fullpath, "rb") as fin:
            buf = fin.read()
        img = codec.imdecode_np(buf, iscolor=args.color)
    except Exception as e:
        traceback.print_exc()
        print("imdecode error:", item[1], e)
        q_out.append((i, None, item))
        return
    if img is None:
        print("imdecode read blank image for file: %s" % fullpath)
        q_out.append((i, None, item))
        return
    if args.center_crop:
        if img.shape[0] > img.shape[1]:
            margin = (img.shape[0] - img.shape[1]) // 2
            img = img[margin:margin + img.shape[1], :]
        else:
            margin = (img.shape[1] - img.shape[0]) // 2
            img = img[:, margin:margin + img.shape[0]]
    if args.resize:
        if img.shape[0] > img.shape[1]:
            newsize = (args.resize,
                       img.shape[0] * args.resize // img.shape[1])
        else:
            newsize = (img.shape[1] * args.resize // img.shape[0],
                       args.resize)
        img = imresize(nd.array(np.ascontiguousarray(img)),
                       newsize[0], newsize[1]).asnumpy().astype("uint8")

    try:
        s = recordio.pack_img(header, img, quality=args.quality,
                              img_fmt=args.encoding)
        q_out.append((i, s, item))
    except Exception as e:
        traceback.print_exc()
        print("pack_img error on file: %s" % fullpath, e)
        q_out.append((i, None, item))


def make_record(args, path_in):
    from mxnet_tpu import recordio

    fname = os.path.basename(path_in)
    fname_rec = os.path.splitext(fname)[0] + ".rec"
    fname_idx = os.path.splitext(fname)[0] + ".idx"
    record = recordio.MXIndexedRecordIO(
        os.path.join(args.prefix_dir, fname_idx),
        os.path.join(args.prefix_dir, fname_rec), "w")
    image_list = list(read_list(path_in))
    tic = time.time()
    cnt = written = 0
    for i, item in enumerate(image_list):
        out = []
        image_encode(args, i, item, out)
        _, s, it = out[0]
        if s is not None:
            record.write_idx(it[0], s)
            written += 1
        if cnt % 1000 == 0 and cnt > 0:
            print("time:", time.time() - tic, " count:", cnt)
            tic = time.time()
        cnt += 1
    record.close()
    print("wrote %d records to %s (%d of %d inputs)"
          % (written, fname_rec, written, cnt))


def parse_args():
    parser = argparse.ArgumentParser(
        formatter_class=argparse.ArgumentDefaultsHelpFormatter,
        description="Create an image list or make a record database by "
                    "reading from an image list")
    parser.add_argument("prefix", help="prefix of input/output lst and "
                                       "rec files.")
    parser.add_argument("root", help="path to folder containing images.")
    cgroup = parser.add_argument_group("Options for creating image lists")
    cgroup.add_argument("--list", action="store_true",
                        help="If this is set im2rec will create image list(s) "
                             "by traversing root folder and output to <prefix>.lst. "
                             "Otherwise im2rec will read <prefix>.lst and create a database at <prefix>.rec")
    cgroup.add_argument("--exts", nargs="+", default=[".jpeg", ".jpg", ".png"],
                        help="list of acceptable image extensions.")
    cgroup.add_argument("--chunks", type=int, default=1,
                        help="number of chunks.")
    cgroup.add_argument("--train-ratio", type=float, default=1.0,
                        help="Ratio of images to use for training.")
    cgroup.add_argument("--test-ratio", type=float, default=0,
                        help="Ratio of images to use for testing.")
    cgroup.add_argument("--recursive", action="store_true",
                        help="If true recursively walk through subdirs and "
                             "assign an unique label to images in each folder.")
    cgroup.add_argument("--no-shuffle", dest="shuffle", action="store_false",
                        help="If this is passed, im2rec will not randomize "
                             "the image order in <prefix>.lst")
    rgroup = parser.add_argument_group("Options for creating database")
    rgroup.add_argument("--pass-through", action="store_true",
                        help="whether to skip transformation and save image as is")
    rgroup.add_argument("--resize", type=int, default=0,
                        help="resize the shorter edge of image to the newsize, "
                             "original images will be packed by default.")
    rgroup.add_argument("--center-crop", action="store_true",
                        help="specify whether to crop the center image to make it rectangular.")
    rgroup.add_argument("--quality", type=int, default=95,
                        help="JPEG quality for encoding, 1-100; or PNG compression for encoding, 1-9")
    rgroup.add_argument("--color", type=int, default=1,
                        choices=[-1, 0, 1],
                        help="specify the color mode of the loaded image.")
    rgroup.add_argument("--encoding", type=str, default=".jpg",
                        choices=[".jpg", ".png"],
                        help="specify the encoding of the images.")
    rgroup.add_argument("--pack-label", action="store_true",
                        help="Whether to also pack multi dimensional label in the record file")
    args = parser.parse_args()
    args.prefix = os.path.abspath(args.prefix)
    args.root = os.path.abspath(args.root)
    return args


def main():
    args = parse_args()
    if args.list:
        make_list(args)
        return
    args.prefix_dir = os.path.dirname(args.prefix)
    files = [os.path.join(args.prefix_dir, f)
             for f in os.listdir(args.prefix_dir or ".")
             if f.startswith(os.path.basename(args.prefix))
             and f.endswith(".lst")]
    print("Creating .rec file from", files)
    for f in files:
        make_record(args, f)


if __name__ == "__main__":
    main()
