#!/usr/bin/env python
"""Launch distributed training jobs (reference tools/launch.py, which
drives dmlc-tracker over ssh/mpi/sge/yarn).

TPU-native model: there are no scheduler/server roles — every process is
a worker in a `jax.distributed` cluster (mxnet_tpu/parallel/dist.py).
This launcher covers the reference's `--launcher local` CI path: spawn N
worker processes on this host with the DMLC-compatible env contract

    MX_COORDINATOR   coordinator ip:port (process 0)
    DMLC_NUM_WORKER  number of workers
    DMLC_WORKER_ID   this worker's rank

`dist_sync` kvstores created inside the workers then allreduce over the
cluster. For multi-host, run the same command per host with --host-rank /
--coordinator pointing at host 0.
"""
from __future__ import print_function

import argparse
import os
import signal
import subprocess
import sys


def launch_local(args, command):
    procs = []
    env_base = dict(os.environ)
    coordinator = args.coordinator or "127.0.0.1:%d" % args.port
    total = args.num_workers * args.num_hosts
    for r in range(args.num_workers):
        env = dict(env_base)
        env["MX_COORDINATOR"] = coordinator
        env["DMLC_NUM_WORKER"] = str(total)
        env["DMLC_WORKER_ID"] = str(args.host_rank * args.num_workers + r)
        env["DMLC_ROLE"] = "worker"
        # each local worker needs its own devices; a single-client TPU
        # tunnel cannot be shared, so local mode forces CPU unless
        # overridden with --platform
        if args.platform:
            env["JAX_PLATFORMS"] = args.platform
        else:
            env["JAX_PLATFORMS"] = "cpu"
            env.setdefault("PALLAS_AXON_POOL_IPS", "")
        procs.append(subprocess.Popen(command, shell=True, env=env))
    code = 0
    try:
        for p in procs:
            p.wait()
            code = code or p.returncode
    except KeyboardInterrupt:
        for p in procs:
            p.send_signal(signal.SIGINT)
        for p in procs:
            p.wait()
        code = 1
    return code


def main():
    parser = argparse.ArgumentParser(
        description="Launch a distributed job",
        formatter_class=argparse.ArgumentDefaultsHelpFormatter)
    parser.add_argument("-n", "--num-workers", required=True, type=int,
                        help="number of worker processes to launch")
    parser.add_argument("--launcher", type=str, default="local",
                        choices=["local"],
                        help="cluster launcher mode; the reference's "
                             "ssh/mpi/sge/yarn modes are replaced by "
                             "running this command once per host")
    parser.add_argument("--port", type=int, default=9327,
                        help="coordinator port (process 0)")
    parser.add_argument("--coordinator", type=str, default=None,
                        help="ip:port of the rank-0 host for multi-host")
    parser.add_argument("--platform", type=str, default=None,
                        help="JAX_PLATFORMS for workers (default cpu; "
                             "local workers cannot share one TPU tunnel)")
    parser.add_argument("--num-hosts", type=int, default=1,
                        help="total hosts running this command")
    parser.add_argument("--host-rank", type=int, default=0,
                        help="this host's index in [0, num-hosts); worker "
                             "ranks are offset by host-rank * num-workers")
    parser.add_argument("command", nargs="+",
                        help="command for launching the program")
    args, unknown = parser.parse_known_args()
    command = " ".join(args.command + unknown)
    sys.exit(launch_local(args, command))


if __name__ == "__main__":
    main()
