#!/usr/bin/env python
"""Combined repo gate: static analysis + (optional) benchmark regression.

Runs the two gates that share exit-code conventions (0 = pass,
1 = regression) and BENCH-style one-line JSON summaries:

- ``tools/mxanalyze --strict`` over ``mxnet_tpu/`` against the checked-in
  ``tools/mxanalyze/baseline.json`` — a NEW finding of any rule
  (jit-purity, retrace-hazard, lock-discipline, swallowed-exception,
  env-var-drift) fails the gate the same way a perf regression does;
- ``tools/bench_gate.py`` over a bench run file, when one is given —
  the TRAIN/INFER headline as before, PLUS the serving-latency gate
  (lower-is-better ``serving_closed_p99_ms``) whenever the run carries
  serving records, so ``bench.py --serve`` output gates its tail
  latency through the same entry point, PLUS the multichip comm gate
  (``multichip_scaling_efficiency`` vs MULTICHIP_*.json history, a
  ``bench_gate_comm`` bytes-by-kind delta line on regression) whenever
  the run carries MULTICHIP records, PLUS the run-anatomy goodput gate
  (``train_goodput_fraction``, higher is better, a
  ``bench_gate_states`` state-seconds delta line on regression)
  whenever the run carries a ``goodput_fraction``, PLUS the memory
  gate (``peak_hbm_bytes``, a lower-better ceiling, a
  ``bench_gate_memory`` per-scope byte delta line on regression)
  whenever the run carries a ``peak_hbm_bytes``.

``--threads`` additionally runs the launched concurrency tests under
``MXNET_THREADSAN=1`` with a scratch witness dir
(``MXNET_THREADSAN_DIR``), then feeds the lock witness those runs
wrote back into ``mxanalyze --witness`` — runtime
acquisition-order edges join the static inversion check and any hazard
report (potential deadlock, lock held across dispatch, blocked too
long) fails the ``mxanalyze_threads_gate`` line, naming the worst
contended lock.

Usage:
    python tools/repo_gate.py                     # analysis only
    python tools/repo_gate.py --bench run.jsonl   # analysis + perf
    python tools/repo_gate.py --threads           # analysis + witness
    python bench.py | python tools/repo_gate.py --bench -

Exit status: 0 when every gate passed, 1 when any failed. Every gate
emits its own BENCH-style one-line JSON summary.
"""
from __future__ import annotations

import argparse
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

#: tests that actually spin up threads against the registered locks —
#: the witness only learns from code that runs, so the --threads gate
#: drives the serving engine, prefetch iterators, ps_async, and the
#: sanitizer's own fixtures rather than the whole suite
THREAD_TESTS = ["tests/test_threadsan.py", "tests/test_serving.py",
                "tests/test_io_iterators.py", "tests/test_dist_async.py"]


def run_thread_witness(paths=None, tests=None, timeout=600):
    """Run the concurrency tests armed (``MXNET_THREADSAN=1``) with a
    scratch witness dir, then join the witness they wrote back into
    the static analysis via ``mxanalyze --witness``. The scratch dir
    rides ``MXNET_THREADSAN_DIR`` (witness-only), NOT
    ``MXNET_TELEMETRY_DIR`` — several of these tests monkeypatch the
    telemetry dir themselves and a gate-level preset would shadow it.
    Returns the gate rc (test failures fail the gate too — an
    unexercised witness must not read as clean)."""
    import subprocess
    import tempfile
    from tools.mxanalyze.cli import gate_line
    from tools.mxanalyze.cli import main as mxanalyze_main

    tests = [t for t in (tests or THREAD_TESTS)
             if os.path.exists(os.path.join(REPO, t))]
    if not tests:
        gate_line("fail", "no concurrency tests found to arm",
                  metric="mxanalyze_threads_gate")
        return 1
    with tempfile.TemporaryDirectory(prefix="threadsan_gate_") as tmp:
        env = dict(os.environ, MXNET_THREADSAN="1",
                   MXNET_THREADSAN_DIR=tmp, JAX_PLATFORMS="cpu")
        env.pop("MXNET_TELEMETRY_DIR", None)
        proc = subprocess.run(
            [sys.executable, "-m", "pytest", "-q", "-x",
             "-m", "not slow", "-p", "no:cacheprovider"] + tests,
            cwd=REPO, env=env, capture_output=True, text=True,
            timeout=timeout)
        if proc.returncode != 0:
            tail = "\n".join(proc.stdout.splitlines()[-15:])
            print(tail, file=sys.stderr)
            gate_line("fail",
                      "armed concurrency tests failed (rc %d)"
                      % proc.returncode,
                      metric="mxanalyze_threads_gate")
            return 1
        return mxanalyze_main(["--witness", tmp] + (paths or []))


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--bench", default=None, metavar="RUN",
                    help="bench output (JSON lines; '-' = stdin) to gate "
                         "via tools/bench_gate.py")
    ap.add_argument("--threshold", type=float, default=None,
                    help="bench_gate regression threshold override")
    ap.add_argument("--paths", nargs="*", default=None,
                    help="paths for mxanalyze (default: mxnet_tpu/)")
    ap.add_argument("--changed-only", action="store_true",
                    help="scope mxanalyze to files git reports changed "
                         "(fast incremental gate, same exit codes)")
    ap.add_argument("--threads", action="store_true",
                    help="run the launched concurrency tests under "
                         "MXNET_THREADSAN=1 and join the lock witness "
                         "back via mxanalyze --witness")
    args = ap.parse_args(argv)

    sys.path.insert(0, REPO)
    from tools.mxanalyze.cli import main as mxanalyze_main

    mx_args = ["--strict"] + (["--changed-only"] if args.changed_only
                              else []) + (args.paths or [])
    rc = mxanalyze_main(mx_args)

    if args.threads:
        rc = max(rc, run_thread_witness(paths=args.paths))

    if args.bench is not None:
        sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
        import bench_gate
        if args.bench == "-":
            lines = sys.stdin.read().splitlines()
        else:
            with open(args.bench, "r", encoding="utf-8") as fh:
                lines = fh.read().splitlines()
            # MXNET_TELEMETRY_DIR-style snapshots sitting next to the
            # bench records carry runtime verdicts: cross-check the
            # static findings against them (mxanalyze_perf_gate)
            from tools.mxanalyze import profiles
            bench_dir = os.path.dirname(os.path.abspath(args.bench))
            if profiles.has_snapshots(bench_dir):
                rc = max(rc, mxanalyze_main(
                    ["--profile", bench_dir] + (args.paths or [])))
        records = bench_gate.parse_lines(lines)
        kwargs = {}
        if args.threshold is not None:
            kwargs["threshold"] = args.threshold
        rc = max(rc, bench_gate.gate_records(records, **kwargs))
        if any(rec.get("metric") == bench_gate.SERVE_METRIC
               for rec in records):
            # a serving run also gates its p99 tail (lower is better)
            rc = max(rc, bench_gate.gate_records(
                records, metric=bench_gate.SERVE_METRIC, **kwargs))
        if any(rec.get("metric") == bench_gate.MULTICHIP_METRIC
               for rec in records):
            # a multichip run also gates its scaling efficiency
            # (higher is better, vs MULTICHIP_r*.json history)
            rc = max(rc, bench_gate.gate_records(
                records, metric=bench_gate.MULTICHIP_METRIC, **kwargs))
        if any(rec.get("metric") == bench_gate.GOODPUT_METRIC
               or isinstance(rec.get("goodput_fraction"), (int, float))
               for rec in records):
            # a run carrying run-anatomy goodput also gates it (higher
            # is better; a regression prints the state-seconds deltas)
            rc = max(rc, bench_gate.gate_records(
                records, metric=bench_gate.GOODPUT_METRIC, **kwargs))
        if any(rec.get("metric") == bench_gate.MEMORY_METRIC
               or isinstance(rec.get("peak_hbm_bytes"), (int, float))
               for rec in records):
            # a run carrying memory-anatomy peak bytes also gates it
            # (lower-better ceiling; a regression prints the per-scope
            # byte deltas via bench_gate_memory)
            rc = max(rc, bench_gate.gate_records(
                records, metric=bench_gate.MEMORY_METRIC, **kwargs))

    return rc


if __name__ == "__main__":
    sys.exit(main())
