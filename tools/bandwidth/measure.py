#!/usr/bin/env python
"""Measure kvstore push/pull bandwidth (reference
tools/bandwidth/measure.py): creates ResNet-sized gradient arrays on each
device and times aggregate push+pull rounds.
"""
from __future__ import print_function

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))


def measure(kv_type, data_mb, num_keys, iters):
    import numpy as np
    import mxnet_tpu as mx

    kv = mx.kv.create(kv_type)
    per_key = int(data_mb * 1024 * 1024 / 4 / num_keys)
    shapes = [(per_key,) for _ in range(num_keys)]
    grads = [mx.nd.ones(s) for s in shapes]
    outs = [mx.nd.zeros(s) for s in shapes]
    for i, g in enumerate(grads):
        kv.init(i, g)
    # warmup
    for i, g in enumerate(grads):
        kv.push(i, g)
        kv.pull(i, out=outs[i])
    for o in outs:
        o.wait_to_read()

    tic = time.time()
    for _ in range(iters):
        for i, g in enumerate(grads):
            kv.push(i, g)
        for i in range(num_keys):
            kv.pull(i, out=outs[i])
    for o in outs:
        o.wait_to_read()
    total = time.time() - tic
    nbytes = data_mb * 1024 * 1024 * 2 * iters  # push + pull
    print("kvstore=%s keys=%d total=%.1f MB x %d iters" % (
        kv_type, num_keys, data_mb, iters))
    print("time %.3f s, goodput %.2f GB/s" % (
        total, nbytes / total / 1e9))
    return nbytes / total


def main():
    parser = argparse.ArgumentParser(
        description="measure kvstore bandwidth",
        formatter_class=argparse.ArgumentDefaultsHelpFormatter)
    parser.add_argument("--kv-store", type=str, default="local")
    parser.add_argument("--data-mb", type=float, default=100.0,
                        help="total payload size in MB (~ResNet-50 grads)")
    parser.add_argument("--num-keys", type=int, default=20)
    parser.add_argument("--iters", type=int, default=10)
    args = parser.parse_args()
    measure(args.kv_store, args.data_mb, args.num_keys, args.iters)


if __name__ == "__main__":
    main()
