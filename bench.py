"""Headline benchmark: ResNet-50 inference throughput, batch 32.

Matches the reference's benchmark_score.py configuration
(`/root/reference/example/image-classification/README.md:147-156`:
ResNet-50, batch 32, 1 chip — reference scores 109 img/s on a K80).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""
from __future__ import annotations

import json
import time

BASELINE_IMG_S = 109.0  # K80 ResNet-50 batch-32 inference (BASELINE.md)


def main():
    import mxnet_tpu as mx
    from mxnet_tpu.gluon.model_zoo import vision

    batch = 32
    ctx = mx.tpu() if mx.context.num_tpus() else mx.cpu()
    net = vision.resnet50_v1()
    net.initialize(ctx=ctx)
    net.hybridize()

    x = mx.nd.random.uniform(shape=(batch, 3, 224, 224), ctx=ctx)
    net(x).asnumpy()  # compile + warm cache

    # time a fixed iteration budget, syncing only at the end (the engine is
    # async-dispatch; per-call sync would measure host latency, not device
    # throughput — same reason benchmark_score.py uses wait_to_read once)
    iters = 20
    t0 = time.time()
    out = None
    for _ in range(iters):
        out = net(x)
    out.asnumpy()
    dt = time.time() - t0
    img_s = batch * iters / dt

    print(json.dumps({
        "metric": "resnet50_infer_imgs_per_sec_bs32",
        "value": round(img_s, 2),
        "unit": "img/s",
        "vs_baseline": round(img_s / BASELINE_IMG_S, 3),
    }))


if __name__ == "__main__":
    main()
