"""Headline benchmarks: ResNet-50 train (bf16 bs128, the north-star
metric) and ResNet-50 inference (bs32).

Inference matches the reference's benchmark_score.py configuration
(`/root/reference/example/image-classification/README.md:147-156`:
ResNet-50, batch 32, 1 chip — reference scores 109 img/s on a K80).
Train is the driver-defined A100-class target (BASELINE.md: 2,900
img/s/chip) measured through the framework's own Module._step_scan path
(`examples/image-classification/benchmark.py`, the bench_all.py config).

Measures DEVICE throughput: the timed iterations run inside one compiled
program (lax.fori_loop over the hybridized forward) and each timed round
chains several program invocations through a data dependency, syncing
once with a host scalar read at the end. Rationale: the chip sits behind
a network tunnel with ~40 ms/call dispatch latency and a
block_until_ready that does not actually block, so per-call host timing
measures the relay, not the chip (0.7k img/s per-call vs ~10k img/s
sustained on-device).

Prints one JSON line per metric ({"metric", "value", "unit",
"vs_baseline"}); the TRAIN line prints last — it is the north-star
number the driver records.
"""
from __future__ import annotations

import json
import os
import sys
import time

BASELINE_IMG_S = 109.0  # K80 ResNet-50 batch-32 inference (BASELINE.md)
TRAIN_TARGET_IMG_S = 2900.0  # A100-class train target (BASELINE.md)


RECORDS = []  # every JSON metric line this run printed (for --gate)


def emit(rec):
    RECORDS.append(rec)
    print(json.dumps(rec), flush=True)


def bench_train():
    """ResNet-50 bf16 bs128 NHWC train img/s via Module._step_scan.

    The config lives in ONE place — tools/bench_all.py's
    bench_resnet50_train (a subprocess, so its jit cache/compile state
    can't skew the inference measurement above).  Any failure degrades to
    a stderr note; the inference line already printed.
    """
    # a previous round's anatomy must never masquerade as this run's:
    # drop the stale file up front, rewrite it only on a run that
    # actually produced a phase breakdown
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "bench_stepprof.json")
    if os.path.exists(path):
        os.remove(path)
    try:
        rec = tools_import("bench_all").bench_resnet50_train()
    except Exception as e:
        sys.stderr.write("train benchmark failed: %r\n" % (e,))
        return
    emit(rec)
    if rec.get("phases"):
        # leave the anatomy where `python -m mxnet_tpu.stepprof report`
        # finds it with no arguments (next to bench_telemetry.prom)
        with open(path, "w", encoding="utf-8") as fh:
            json.dump({"metric": "train_phase_breakdown",
                       "phases": rec["phases"],
                       "verdict": rec.get("verdict"),
                       "source_metric": rec["metric"],
                       "updated": time.time()}, fh)


def tools_import(name):
    """Import a module out of the repo's tools/ dir (idempotent path
    setup shared by the train/serve gate paths)."""
    import importlib
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "tools")
    if path not in sys.path:
        sys.path.insert(0, path)
    return importlib.import_module(name)


def run_gate(*metrics):
    """Gate this run's RECORDS against the repo history (one
    gate_records pass per metric; default metric selection when none
    given); exits with the worst result."""
    gate = tools_import("bench_gate")
    if not metrics:
        raise SystemExit(gate.gate_records(RECORDS))
    raise SystemExit(max(gate.gate_records(RECORDS, metric=m)
                         for m in metrics))


def bench_serve():
    """--serve mode: closed+open-loop load against the dynamic-batching
    inference engine (`tools/serve_bench.py`), emitted as the same JSON
    metric lines as the train/infer benches so `--gate` and the BENCH
    history tooling parse them unchanged."""
    for rec in tools_import("serve_bench").bench_records():
        emit(rec)


def main():
    if "--serve" in sys.argv:
        bench_serve()
        write_telemetry_snapshot()
        if "--gate" in sys.argv:
            # gate the serving headlines, not the TRAIN metric this run
            # never emitted (which would skip-pass unconditionally):
            # throughput down OR p99 latency up both fail the round
            run_gate("serving_closed_rps", "serving_closed_p99_ms")
        return
    import jax
    import jax.numpy as jnp
    from jax import lax

    import mxnet_tpu as mx
    from mxnet_tpu.gluon.model_zoo import vision

    batch, iters = 32, 100
    ctx = mx.tpu() if mx.context.num_tpus() else mx.cpu()
    # NCHW measured FASTER than NHWC for bs32 fp32 inference (10,033 vs
    # 9,956 img/s): the space-to-depth stem rewrite is NCHW-only and
    # outweighs the channel-minor layout win at this batch size
    layout = os.environ.get("MXNET_BENCH_LAYOUT", "NCHW")
    if layout not in ("NCHW", "NHWC"):
        raise SystemExit("MXNET_BENCH_LAYOUT must be NCHW or NHWC, got %r"
                         % layout)
    kwargs = {"layout": layout} if layout != "NCHW" else {}
    net = vision.resnet50_v1(**kwargs)
    net.initialize(ctx=ctx)
    net.hybridize()

    shape = (batch, 3, 224, 224) if layout == "NCHW" \
        else (batch, 224, 224, 3)
    x = mx.nd.random.uniform(shape=shape, ctx=ctx)
    net(x).asnumpy()  # build + warm the cached jit

    cached = net._cached_jit
    params = tuple(net.collect_params()[n].data()._data
                   for n in net._param_order)
    key = jax.random.PRNGKey(0)

    @jax.jit
    def loop(pv, xv, acc0):
        # roll the batch each iteration so the forward depends on the loop
        # counter — otherwise XLA's invariant code motion hoists the whole
        # network out of the loop and we'd time ONE forward, not `iters`.
        # (Tried: feeding the dependence through the accumulator instead —
        # the roll's 0.083 ms of slice traffic disappears from the trace
        # but measured THROUGHPUT drops ~0.7%: the roll depends only on
        # `i`, so consecutive forwards overlap; an acc-dependent input
        # strictly serializes them.)
        def body(i, acc):
            xi = jnp.roll(xv, i, axis=0)
            return acc + cached(pv, key, False, xi)[0][0].sum()
        return lax.fori_loop(0, iters, body, acc0)

    xv = x._data
    # Sync discipline: block_until_ready is a fast-path no-op on relayed
    # PJRT backends, and the only barrier that provably waits is READING a
    # result scalar (~90ms through the tunnel). One read per timed call
    # would bias the rate, so each timed round chains `calls` loop
    # invocations through the accumulator (a data dependency, so the device
    # must run them back-to-back) and reads once: bias ~= 90ms over the
    # whole round, ~2-3% at the rates measured here.
    calls = 8
    # AOT-compile the timed loop: one compile (same executable the timed
    # calls run) and its cost_analysis gives the MFU/goodput numerator
    from mxnet_tpu import xla_stats
    compiled, info = xla_stats.aot_compile(loop, params, xv,
                                           jnp.float32(0))
    run = compiled if compiled is not None else loop
    float(run(params, xv, jnp.float32(0)))  # compile / warm
    best = 0.0
    best_dt = None
    for _ in range(2):
        t0 = time.time()
        acc = jnp.float32(0)
        for _ in range(calls):
            acc = run(params, xv, acc)
        float(acc)
        dt = time.time() - t0
        if batch * iters * calls / dt > best:
            best = batch * iters * calls / dt
            best_dt = dt

    emit({
        "metric": "resnet50_infer_imgs_per_sec_bs32",
        "value": round(best, 2),
        "unit": "img/s",
        "vs_baseline": round(best / BASELINE_IMG_S, 3),
    })
    write_goodput(info, calls, best_dt)
    if "--infer-only" not in sys.argv:
        bench_train()
    write_telemetry_snapshot()
    if "--gate" in sys.argv:
        run_gate()


def write_goodput(info, calls, dt):
    """`model_flops_per_second` and `mfu` metric lines for the measured
    inference loop (flops from the compiled executable's cost_analysis;
    peak table / MXNET_PEAK_FLOPS from `xla_stats`). Degrades to zeros
    when the backend reports no cost analysis."""
    import jax
    from mxnet_tpu import xla_stats
    flops = (info or {}).get("flops") or 0.0
    mfps = flops * calls / dt if dt else 0.0
    peak = xla_stats.peak_flops_total()
    platform = jax.devices()[0].platform
    g = xla_stats.publish_goodput(mfps)  # the one gauge publisher
    emit({"metric": "model_flops_per_second", "value": round(mfps, 3),
          "unit": "FLOP/s", "platform": platform})
    emit({"metric": "mfu", "value": round(g["mfu"], 5),
          "unit": "ratio", "platform": platform,
          "peak_flops_total": peak})


def write_telemetry_snapshot():
    """Drop the run's telemetry registry (Prometheus text) next to the
    JSON metric lines, so a bench round leaves machine-readable runtime
    series (kvstore traffic, dispatch timings, fit phases) behind, not
    just the headline numbers."""
    from mxnet_tpu import telemetry
    path = telemetry.write_snapshot(
        None if telemetry.configured_dir()
        else os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "bench_telemetry.prom"))
    print(json.dumps({"metric": "telemetry_snapshot", "value": path,
                      "unit": "path"}), flush=True)


if __name__ == "__main__":
    main()
