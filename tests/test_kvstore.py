"""KVStore single-process semantics (reference
tests/python/unittest/test_kvstore.py): init/push/pull aggregation over
device lists, updater hooks, string keys, row_sparse_pull, optimizer
state save/load."""
import numpy as np
import pytest

import mxnet_tpu as mx

SHAPE = (4, 4)
KEYS = [5, 7, 11]


def init_kv(kv_type="local"):
    kv = mx.kv.create(kv_type)
    kv.init(3, mx.nd.zeros(SHAPE))
    kv.init(KEYS, [mx.nd.zeros(SHAPE)] * len(KEYS))
    return kv


def check_diff_to_scalar(A, x):
    np.testing.assert_allclose(A.asnumpy(), x, rtol=1e-5)


def test_single_kv_pair():
    kv = init_kv()
    kv.push(3, mx.nd.ones(SHAPE))
    val = mx.nd.empty(SHAPE)
    kv.pull(3, out=val)
    check_diff_to_scalar(val, 1)


def test_list_kv_pair():
    kv = init_kv()
    kv.push(KEYS, [mx.nd.ones(SHAPE) * 4] * len(KEYS))
    val = [mx.nd.empty(SHAPE)] * len(KEYS)
    kv.pull(KEYS, out=val)
    for v in val:
        check_diff_to_scalar(v, 4)


def test_aggregator_device_list():
    """Pushing a list of values for one key sums them (the reference's
    multi-device aggregation, comm.h:103)."""
    kv = init_kv()
    num_devs = 4
    devs = [mx.cpu(0)] * num_devs
    vals = [mx.nd.ones(SHAPE, ctx=d) for d in devs]
    kv.push(3, vals)
    out = mx.nd.empty(SHAPE)
    kv.pull(3, out=out)
    check_diff_to_scalar(out, num_devs)

    kv.push(KEYS, [[mx.nd.ones(SHAPE, ctx=d) * 2.0 for d in devs]] * len(KEYS))
    outs = [mx.nd.empty(SHAPE) for _ in KEYS]
    kv.pull(KEYS, out=outs)
    for o in outs:
        check_diff_to_scalar(o, num_devs * 2.0)


def test_updater():
    kv = init_kv()

    def updater(key, recv, local):
        local += recv
    kv._set_updater(updater)
    kv.push(3, mx.nd.ones(SHAPE))
    kv.push(3, mx.nd.ones(SHAPE))
    out = mx.nd.empty(SHAPE)
    kv.pull(3, out=out)
    check_diff_to_scalar(out, 2)


def test_string_keys():
    kv = mx.kv.create("local")
    kv.init("w0", mx.nd.ones(SHAPE))
    kv.push("w0", mx.nd.ones(SHAPE) * 3)
    out = mx.nd.empty(SHAPE)
    kv.pull("w0", out=out)
    check_diff_to_scalar(out, 3)
    # mixing int keys after string keys is an error (reference semantics)
    with pytest.raises(mx.MXNetError):
        kv.init(9, mx.nd.ones(SHAPE))


def test_row_sparse_pull():
    kv = mx.kv.create("local")
    w = mx.nd.array(np.arange(12, dtype="f").reshape(6, 2))
    kv.init("emb", w)
    kv.push("emb", w)
    out = mx.nd.zeros((6, 2))
    rid = mx.nd.array(np.array([1, 4], "f"))
    kv.row_sparse_pull("emb", out=out, row_ids=rid)
    got = out.asnumpy()
    np.testing.assert_allclose(got[1], w.asnumpy()[1])
    np.testing.assert_allclose(got[4], w.asnumpy()[4])
    np.testing.assert_allclose(got[0], 0)


def test_set_optimizer_and_states_roundtrip(tmp_path):
    kv = init_kv()
    kv.set_optimizer(mx.optimizer.SGD(learning_rate=0.1, momentum=0.9))
    kv.push(3, mx.nd.ones(SHAPE))
    fname = str(tmp_path / "states")
    kv.save_optimizer_states(fname)
    kv.load_optimizer_states(fname)
    out = mx.nd.empty(SHAPE)
    kv.pull(3, out=out)
    assert np.isfinite(out.asnumpy()).all()


def test_invalid_kvstore_type():
    with pytest.raises(mx.MXNetError):
        mx.kv.create("no_such_store")


def test_double_init_errors():
    kv = init_kv()
    with pytest.raises(mx.MXNetError):
        kv.init(3, mx.nd.zeros(SHAPE))
