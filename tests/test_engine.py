"""Engine fence semantics (reference Engine::WaitForAll,
include/mxnet/engine.h:219). The fence must not recompile per live-array
*population*: its jit cache is keyed on per-array (platform, shape, dtype)
signatures, so waitall() across training steps with a shifting live set
reuses a bounded set of compiled probes (ADVICE r2 medium finding)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import engine


@pytest.fixture
def force_readback(monkeypatch):
    """Make fence() treat CPU buffers as relay-TPU buffers so the probe
    path runs under the test's virtual-CPU environment."""
    monkeypatch.setattr(engine, "_needs_readback", lambda a: True)
    saved = dict(engine._FENCE_JIT)
    engine._FENCE_JIT.clear()
    yield
    engine._FENCE_JIT.clear()
    engine._FENCE_JIT.update(saved)


def test_fence_cache_keyed_on_signature_not_population(force_readback):
    # many "steps", each with a different live-array population drawn from
    # the same two tensor signatures: the cache must be bounded by
    # signatures x pow2-count-buckets, never by the population/grouping
    a = jnp.ones((4, 3), jnp.float32)
    b = jnp.ones((8,), jnp.float32)
    for step in range(10):
        pop = [a] * (1 + step % 3) + [b] * (step % 4)
        engine.fence(pop)
    # sig_a in buckets {1, 2}, sig_b in buckets {1, 2} -> at most 4 probes
    assert len(engine._FENCE_JIT) <= 4


def test_fence_distinct_dtypes_get_distinct_probes(force_readback):
    engine.fence([jnp.ones((4,), jnp.float32), jnp.ones((4,), jnp.bfloat16)])
    assert len(engine._FENCE_JIT) == 2


def test_fence_handles_empty_and_int_arrays(force_readback):
    engine.fence([jnp.zeros((0,), jnp.float32), jnp.arange(3),
                  jnp.ones((2, 2), bool)])


def test_waitall_is_idempotent_across_steps(force_readback):
    sizes = []
    for step in range(3):
        x = mx.nd.ones((4, 4)) * (step + 1)
        y = (x * 2).sum()
        mx.nd.waitall()
        assert float(y.asnumpy()) == 32.0 * (step + 1)
        sizes.append(len(engine._FENCE_JIT))
    # probes accumulate per signature, not per waitall: after the first
    # pass over the live set, repeat steps add (at most) one new probe for
    # the one new signature introduced per iteration
    assert sizes[2] - sizes[0] <= 2


def test_fence_mixed_single_and_sharded(force_readback):
    """waitall over a live set mixing single-device and mesh-sharded arrays
    (SPMD module training) must fence both without a placement clash."""
    import numpy as onp
    from jax.sharding import NamedSharding, PartitionSpec as P
    from mxnet_tpu.parallel.mesh import make_mesh
    mesh = make_mesh({"dp": 8})
    sharded = jax.device_put(onp.ones((16, 4), onp.float32),
                             NamedSharding(mesh, P("dp")))
    repl = jax.device_put(onp.ones((4,), onp.float32),
                          NamedSharding(mesh, P()))
    single = jnp.ones((4, 4), jnp.float32)
    engine.fence([sharded, repl, single, sharded])
