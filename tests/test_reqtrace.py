"""Request anatomy (`mxnet_tpu/serving/reqtrace.py`): trace telescoping,
SLO burn-rate math, pad-waste accounting, tail classification, the
report CLI's verdict fixtures, and the serving-latency bench gate."""
import io
import json
import os
import sys

import pytest

from mxnet_tpu.serving import reqtrace
from mxnet_tpu.serving.batching import PadLedger
from mxnet_tpu.serving.reqtrace import (PHASES, RequestTracer, SLOTracker,
                                        Trace, classify, clean_request_id,
                                        new_request_id)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TOOLS = os.path.join(REPO, "tools")


# ---------------------------------------------------------------------------
# trace ids
# ---------------------------------------------------------------------------

def test_request_ids():
    a, b = new_request_id(), new_request_id()
    assert a != b and len(a) == 16
    assert clean_request_id("abc-123.X:ok") == "abc-123.X:ok"
    # header injection is stripped; empty/None regenerate
    assert "\n" not in clean_request_id("evil\nSet-Cookie: x")
    assert clean_request_id("\n\r ") != ""
    assert clean_request_id(None)
    assert len(clean_request_id("x" * 500)) <= 128


# ---------------------------------------------------------------------------
# trace telescoping
# ---------------------------------------------------------------------------

def _full_trace(rid="r1", t0=100.0):
    tr = Trace(rid, wall0=0.0)
    marks = {"enqueued": t0, "picked": t0 + 0.010,
             "pad_start": t0 + 0.015, "pad_end": t0 + 0.016,
             "forward_end": t0 + 0.030, "outputs_end": t0 + 0.090,
             "split_end": t0 + 0.091}
    for name, t in marks.items():
        tr.mark(name, t)
    return tr, t0 + 0.095


def test_trace_phases_telescope_exactly():
    tr, end = _full_trace()
    phases = tr.phases(end)
    assert set(phases) == set(PHASES)
    assert sum(phases.values()) == pytest.approx(end - 100.0, abs=1e-12)
    assert phases["queue_wait"] == pytest.approx(0.010)
    assert phases["batch_wait"] == pytest.approx(0.005)
    assert phases["device_compute"] == pytest.approx(0.060)
    assert phases["respond"] == pytest.approx(0.004)


def test_partial_trace_attributes_remainder_to_stalled_phase():
    # expired while queued: only 'enqueued' is marked -> pure queue_wait
    tr = Trace("r2")
    tr.mark("enqueued", 10.0)
    assert tr.phases(10.5) == {"queue_wait": pytest.approx(0.5)}
    # died between pickup and pad: remainder lands in batch_wait
    tr.mark("picked", 10.1)
    phases = tr.phases(10.5)
    assert phases["queue_wait"] == pytest.approx(0.1)
    assert phases["batch_wait"] == pytest.approx(0.4)
    with pytest.raises(ValueError):
        tr.mark("not_a_mark")


# ---------------------------------------------------------------------------
# SLO burn-rate math (deterministic clock)
# ---------------------------------------------------------------------------

class _Clock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t


def test_slo_burn_rate_math():
    clk = _Clock()
    slo = SLOTracker(target_ms=100.0, availability=0.99,
                     windows=[60, 600], clock=clk)
    assert slo.error_budget == pytest.approx(0.01)
    for i in range(95):
        slo.record(True, 0.010)
    for i in range(5):
        slo.record(False)
    # 5% bad over a 1% budget: burning 5x in both windows
    assert slo.burn_rate(60) == pytest.approx(5.0)
    assert slo.burn_rate(600) == pytest.approx(5.0)
    snap = slo.snapshot()
    assert snap["good_total"] == 95 and snap["bad_total"] == 5
    assert snap["burn_rate"]["60"] == pytest.approx(5.0)


def test_slo_slow_success_burns_budget():
    slo = SLOTracker(target_ms=100.0, availability=0.9, windows=[60],
                     clock=_Clock())
    slo.record(True, 0.250)   # ok but 2.5x the target: bad
    slo.record(True, 0.050)
    assert slo.window_counts(60) == (2, 1)
    assert slo.burn_rate(60) == pytest.approx(0.5 / 0.1)


def test_slo_windows_age_out_independently():
    clk = _Clock(1000.0)
    slo = SLOTracker(target_ms=100.0, availability=0.99,
                     windows=[60, 3600], clock=clk)
    for _ in range(10):
        slo.record(False)
    clk.t += 120.0            # past the short window, inside the long
    slo.record(True, 0.010)
    assert slo.window_counts(60) == (1, 0)
    assert slo.burn_rate(60) == 0.0
    total, bad = slo.window_counts(3600)
    assert (total, bad) == (11, 10)
    assert slo.burn_rate(3600) > 1.0


def test_slo_idle_is_not_an_alert_and_validation():
    slo = SLOTracker(target_ms=50.0, availability=0.999, windows=[60],
                     clock=_Clock())
    assert slo.burn_rate(60) == 0.0
    with pytest.raises(ValueError):
        SLOTracker(target_ms=0, availability=0.9, windows=[60])
    with pytest.raises(ValueError):
        SLOTracker(target_ms=50, availability=1.5, windows=[60])
    with pytest.raises(ValueError):
        SLOTracker(target_ms=50, availability=0.9, windows=[])


def test_slo_env_defaults(monkeypatch):
    monkeypatch.setenv("MXNET_SLO_LATENCY_MS", "75")
    monkeypatch.setenv("MXNET_SLO_AVAILABILITY", "0.95")
    monkeypatch.setenv("MXNET_SLO_WINDOWS", "30,90")
    slo = SLOTracker()
    assert slo.target_ms == 75.0
    assert slo.availability == 0.95
    assert slo.windows == (30, 90)


# ---------------------------------------------------------------------------
# pad-waste accounting
# ---------------------------------------------------------------------------

def test_pad_ledger_per_bucket():
    led = PadLedger()
    assert led.waste_ratio() == 0.0
    assert led.occupancy(4) is None
    led.note(3, 4)
    led.note(4, 4)
    led.note(1, 8)
    # dispatched rows: 4+4+8=16, real: 3+4+1=8
    assert led.waste_ratio() == pytest.approx(0.5)
    assert led.occupancy(4) == pytest.approx(7 / 8.0)
    assert led.occupancy(8) == pytest.approx(1 / 8.0)
    snap = led.snapshot()
    assert snap["waste_ratio"] == pytest.approx(0.5)
    assert snap["buckets"]["4"] == {"batches": 2, "real_rows": 7,
                                    "occupancy": 0.875}
    with pytest.raises(ValueError):
        led.note(5, 4)
    with pytest.raises(ValueError):
        led.note(0, 4)
    led.reset()
    assert led.waste_ratio() == 0.0


def test_tracer_note_batch_publishes_metrics():
    from mxnet_tpu import telemetry
    tr = RequestTracer(window=64)
    tr.note_batch(2, 4)
    assert telemetry.get_metric("serving_real_rows_total",
                                bucket="4").value == 2
    assert telemetry.get_metric("serving_pad_rows_total",
                                bucket="4").value == 2
    assert telemetry.get_metric("serving_pad_waste_ratio").read() \
        == pytest.approx(0.5)
    assert telemetry.get_metric("serving_bucket_occupancy",
                                bucket="4").read() == pytest.approx(0.5)


# ---------------------------------------------------------------------------
# classification: one fixture per verdict class
# ---------------------------------------------------------------------------

def _shares(**kv):
    total = sum(kv.values())
    return {p: kv.get(p, 0.0) / total for p in PHASES}


def test_classify_queue_bound():
    v, hint = classify(_shares(queue_wait=0.5, batch_wait=0.2,
                               device_compute=0.3))
    assert v == "queue-bound"
    assert "MXNET_SERVING_REPLICAS" in hint
    assert "MXNET_SERVING_MAX_DELAY_MS" in hint


def test_classify_compute_bound():
    v, hint = classify(_shares(device_compute=0.7, dispatch=0.1,
                               queue_wait=0.2), pad_waste=0.05)
    assert v == "compute-bound"
    assert "replicas" in hint


def test_classify_padding_bound():
    shares = _shares(device_compute=0.7, dispatch=0.1, queue_wait=0.2)
    v, hint = classify(shares, pad_waste=0.6)
    assert v == "padding-bound"
    assert "bucket ladder" in hint or "bucket_sizes" in hint
    # padding only matters when the tail actually computes
    assert classify(_shares(queue_wait=0.9, device_compute=0.1),
                    pad_waste=0.6)[0] == "queue-bound"


def test_classify_shed_heavy_and_unknown():
    v, hint = classify(_shares(device_compute=1.0), shed_fraction=0.2)
    assert v == "shed-heavy"
    assert "MXNET_SERVING_QUEUE_DEPTH" in hint
    assert classify({})[0] == "unknown"
    assert classify(_shares(device_compute=1.0),
                    shed_fraction=0.01)[0] == "compute-bound"


# ---------------------------------------------------------------------------
# tracer attribution + slow ring
# ---------------------------------------------------------------------------

def _feed(tracer, rid, total, queue_frac=0.1, t0=0.0, status="ok"):
    """Record one synthetic request: queue_frac of `total` in the
    queue, the rest split across the compute-side phases."""
    tr = Trace(rid, wall0=t0)
    q = total * queue_frac
    rest = total - q
    tr.mark("enqueued", t0)
    tr.mark("picked", t0 + q * 0.7)
    tr.mark("pad_start", t0 + q)
    tr.mark("pad_end", t0 + q + rest * 0.05)
    tr.mark("forward_end", t0 + q + rest * 0.15)
    tr.mark("outputs_end", t0 + q + rest * 0.9)
    tr.mark("split_end", t0 + q + rest * 0.95)
    tr.bucket, tr.batch = 4, 1
    return tracer.record(tr, t0 + total, status=status)


def test_attribution_contrasts_p50_and_tail():
    tracer = RequestTracer(window=256, slow_keep=4)
    # bulk: fast compute-ish requests; tail: queue-dominated stragglers
    for i in range(100):
        _feed(tracer, "fast-%d" % i, total=0.010, queue_frac=0.1)
    for i in range(2):
        _feed(tracer, "slow-%d" % i, total=0.500, queue_frac=0.9)
    att = tracer.attribution()
    assert att["requests"] == 102
    assert att["latency"]["p99"] > att["latency"]["p50"]
    qtail = att["p99_shares"]["queue_wait"] + att["p99_shares"]["batch_wait"]
    qhead = att["p50_shares"]["queue_wait"] + att["p50_shares"]["batch_wait"]
    assert qtail > 0.8 > qhead
    # the slow ring kept the stragglers, slowest first
    slow = tracer.slowest()
    assert [r["rid"] for r in slow[:2]] == ["slow-0", "slow-1"] \
        or [r["rid"] for r in slow[:2]] == ["slow-1", "slow-0"]
    assert slow[0]["total"] == pytest.approx(0.5)
    snap = tracer.snapshot()
    assert snap["verdict"] == "queue-bound"
    # record() returns the folded record and phases tile the total
    rec = _feed(tracer, "one", total=0.020)
    assert sum(rec["phases"].values()) == pytest.approx(rec["total"])


def test_tracer_counts_rejects_toward_shed_fraction():
    tracer = RequestTracer(window=64)
    for i in range(9):
        _feed(tracer, "ok-%d" % i, total=0.010)
    for _ in range(6):
        tracer.note_reject("shed")
    att = tracer.attribution()
    assert att["shed_fraction"] == pytest.approx(6 / 15.0)
    v, _ = classify(att["p99_shares"], shed_fraction=att["shed_fraction"])
    assert v == "shed-heavy"


# ---------------------------------------------------------------------------
# report CLI: verdict fixtures, one per class
# ---------------------------------------------------------------------------

def _snapshot_doc(p99_shares, shed_fraction=0.0, waste=0.0):
    return {"host": 0, "pid": 1, "updated": 123.0, "requests": 100,
            "counts": {"ok": 100}, "shed_fraction": shed_fraction,
            "latency": {"p50": 0.002, "p95": 0.008, "p99": 0.02,
                        "count": 100, "max": 0.03},
            "p50_shares": _shares(device_compute=1.0),
            "p99_shares": p99_shares,
            "pad": {"waste_ratio": waste, "buckets": {}},
            "slowest": [{"rid": "slow-1", "total": 0.03,
                         "phases": {"queue_wait": 0.02,
                                    "device_compute": 0.01}}]}


@pytest.mark.parametrize("doc,verdict", [
    (_snapshot_doc(_shares(queue_wait=0.7, device_compute=0.3)),
     "queue-bound"),
    (_snapshot_doc(_shares(device_compute=0.8, dispatch=0.2)),
     "compute-bound"),
    (_snapshot_doc(_shares(device_compute=0.8, dispatch=0.2), waste=0.5),
     "padding-bound"),
    (_snapshot_doc(_shares(device_compute=1.0), shed_fraction=0.3),
     "shed-heavy"),
])
def test_report_cli_verdict_fixtures(tmp_path, doc, verdict):
    path = tmp_path / "reqtrace_host0_pid1.json"
    path.write_text(json.dumps(doc))
    out = io.StringIO()
    assert reqtrace.report(str(path), out=out) == 0
    text = out.getvalue()
    assert "verdict: %s" % verdict in text
    machine = json.loads(text.strip().splitlines()[-1])
    assert machine["metric"] == "reqtrace_report"
    assert machine["verdict"] == verdict
    assert "slow exemplar slow-1" in text


def test_report_names_dominant_p99_phase_on_queue_delay(tmp_path):
    """THE acceptance fixture: a synthetic queue-delay tail must be
    attributed to queue_wait by name."""
    doc = _snapshot_doc(_shares(queue_wait=0.62, batch_wait=0.2,
                                device_compute=0.18))
    path = tmp_path / "snap.json"
    path.write_text(json.dumps(doc))
    out = io.StringIO()
    assert reqtrace.report(str(path), out=out) == 0
    text = out.getvalue()
    assert "dominant p99 phase: queue_wait" in text
    machine = json.loads(text.strip().splitlines()[-1])
    assert machine["dominant_p99_phase"] == "queue_wait"
    assert machine["verdict"] == "queue-bound"


def test_report_merges_host_snapshot_dir(tmp_path):
    for host, shares in ((0, _shares(queue_wait=1.0)),
                         (1, _shares(queue_wait=1.0))):
        doc = _snapshot_doc(shares)
        doc["host"] = host
        (tmp_path / ("reqtrace_host%d_pid1.json" % host)).write_text(
            json.dumps(doc))
    out = io.StringIO()
    assert reqtrace.report(str(tmp_path), out=out) == 0
    assert "2 host snapshot(s)" in out.getvalue()
    assert "verdict: queue-bound" in out.getvalue()


def test_report_no_data_exits_1(tmp_path):
    out = io.StringIO()
    tracer_backup = reqtrace.tracer
    try:
        reqtrace.tracer = RequestTracer(window=16)
        assert reqtrace.report(out=out) == 1
        assert "unknown" in out.getvalue()
    finally:
        reqtrace.tracer = tracer_backup


def test_report_main_cli(tmp_path, capsys):
    doc = _snapshot_doc(_shares(device_compute=1.0))
    path = tmp_path / "snap.json"
    path.write_text(json.dumps(doc))
    assert reqtrace.main(["report", str(path), "--json"]) == 0
    line = capsys.readouterr().out.strip()
    assert json.loads(line)["verdict"] == "compute-bound"


def test_write_host_snapshot_roundtrip(tmp_path):
    tracer = RequestTracer(window=32)
    _feed(tracer, "r1", total=0.050)
    path = tracer.write_host_snapshot(dir=str(tmp_path))
    assert path and os.path.exists(path)
    doc = json.load(open(path))
    assert doc["requests"] == 1 and doc["verdict"] != "unknown"
    # unconfigured + no dir -> no-op
    empty = RequestTracer(window=16)
    assert empty.write_host_snapshot(dir=str(tmp_path)) is None


# ---------------------------------------------------------------------------
# serving-latency bench gate (lower is better) + repo_gate wiring
# ---------------------------------------------------------------------------

def _bench_gate():
    if TOOLS not in sys.path:
        sys.path.insert(0, TOOLS)
    import bench_gate
    return bench_gate


def test_serving_gate_lower_is_better(tmp_path):
    bench_gate = _bench_gate()
    assert bench_gate.lower_is_better(bench_gate.SERVE_METRIC)
    assert not bench_gate.lower_is_better("serving_closed_rps")
    hist = tmp_path / "BENCH_serve.json"
    hist.write_text(json.dumps(
        [{"metric": bench_gate.SERVE_METRIC, "value": 20.0},
         {"metric": bench_gate.SERVE_METRIC, "value": 10.0}]))
    out = io.StringIO()
    # best history value is the MIN (10); +10% ceiling = 11
    ok = [{"metric": bench_gate.SERVE_METRIC, "value": 10.9}]
    bad = [{"metric": bench_gate.SERVE_METRIC, "value": 12.0,
            "phases": {"queue_wait": 0.8}}]
    assert bench_gate.gate_records(ok, history_dir=str(tmp_path),
                                   metric=bench_gate.SERVE_METRIC,
                                   out=out) == 0
    assert bench_gate.gate_records(bad, history_dir=str(tmp_path),
                                   metric=bench_gate.SERVE_METRIC,
                                   out=out) == 1
    lines = [json.loads(ln) for ln in out.getvalue().splitlines()]
    fail = [ln for ln in lines if ln.get("status") == "fail"]
    assert fail and "ceiling" in fail[0]["detail"]
    # the anatomy delta line rides along on the regression
    assert any(ln.get("metric") == "bench_gate_phases" for ln in lines)


def test_serving_gate_improvement_passes(tmp_path):
    bench_gate = _bench_gate()
    hist = tmp_path / "BENCH_serve.json"
    hist.write_text(json.dumps(
        [{"metric": bench_gate.SERVE_METRIC, "value": 10.0}]))
    better = [{"metric": bench_gate.SERVE_METRIC, "value": 5.0}]
    assert bench_gate.gate_records(better, history_dir=str(tmp_path),
                                   metric=bench_gate.SERVE_METRIC,
                                   out=io.StringIO()) == 0


def test_repo_gate_runs_serving_gate(tmp_path, capfd):
    """repo_gate --bench gates the serving p99 alongside mxanalyze when
    the run carries serving records (shared exit-code + JSON lines).
    capfd, not capsys: bench_gate binds ``out=sys.stdout`` at import."""
    if REPO not in sys.path:
        sys.path.insert(0, REPO)
    from tools import repo_gate
    bench_gate = _bench_gate()
    run = tmp_path / "run.jsonl"
    run.write_text("\n".join([
        json.dumps({"metric": bench_gate.SERVE_METRIC, "value": 1e9}),
        json.dumps({"metric": "serving_closed_rps", "value": 1.0}),
    ]))
    rc = repo_gate.main(["--bench", str(run)])
    out = capfd.readouterr().out
    # serving history exists in the repo only once BENCH rounds record
    # it; either way the serving gate RAN and said so on its own line
    gate_lines = [json.loads(ln) for ln in out.splitlines()
                  if ln.startswith("{") and '"bench_gate"' in ln]
    serve_lines = [ln for ln in gate_lines
                   if bench_gate.SERVE_METRIC in ln.get("detail", "")]
    assert serve_lines, out
    if serve_lines[0]["status"] == "fail":
        assert rc == 1
