"""Native library (libmxtpu.so) tests: recordio framing, image codec,
threaded pipeline, COCO masks.

Mirrors the reference coverage of tests/python/unittest/test_recordio.py
and the COCO mask semantics used by proposal_mask_target.
"""
import os

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import recordio, coco_mask
from mxnet_tpu._native import lib as native_lib

HAVE_NATIVE = native_lib() is not None


def test_recordio_roundtrip(tmp_path):
    path = str(tmp_path / "test.rec")
    w = recordio.MXRecordIO(path, "w")
    records = [b"x" * n for n in (1, 3, 4, 5, 100, 0)]
    for r in records:
        w.write(r)
    w.close()
    r = recordio.MXRecordIO(path, "r")
    for expect in records:
        assert r.read() == expect
    assert r.read() is None
    r.close()


@pytest.mark.skipif(not HAVE_NATIVE, reason="native lib required")
def test_recordio_magic_payload(tmp_path):
    # payloads containing the magic word exercise multi-chunk framing,
    # which only the native path implements (dmlc recordio parity)
    magic = (0xced7230a).to_bytes(4, "little")
    payloads = [
        magic,
        b"abcd" + magic + b"efgh",
        magic + magic + magic,
        b"ab" + magic + b"cd",  # unaligned magic: must NOT split
        b"abc" + magic * 2 + b"defg1234",
    ]
    path = str(tmp_path / "magic.rec")
    w = recordio.MXRecordIO(path, "w")
    for p in payloads:
        w.write(p)
    w.close()
    r = recordio.MXRecordIO(path, "r")
    for expect in payloads:
        assert r.read() == expect
    r.close()


def test_indexed_recordio(tmp_path):
    rec_path = str(tmp_path / "test.rec")
    idx_path = str(tmp_path / "test.idx")
    w = recordio.MXIndexedRecordIO(idx_path, rec_path, "w")
    for i in range(20):
        w.write_idx(i, b"record_%d" % i)
    w.close()
    r = recordio.MXIndexedRecordIO(idx_path, rec_path, "r")
    for i in [13, 2, 19, 0, 7]:
        assert r.read_idx(i) == b"record_%d" % i
    assert r.keys == list(range(20))
    r.close()


@pytest.mark.skipif(not HAVE_NATIVE, reason="native lib required")
def test_image_codec_roundtrip():
    from mxnet_tpu.image.codec import imencode, imdecode_np
    img = np.zeros((32, 48, 3), np.uint8)
    img[:16] = [255, 0, 0]     # BGR blue-ish block
    img[16:] = [0, 255, 0]
    buf = imencode(img, ".jpg", quality=95)
    assert buf[:2] == b"\xff\xd8"
    dec = imdecode_np(buf, iscolor=1)
    assert dec.shape == (32, 48, 3)
    # JPEG is lossy; block colors should survive approximately
    assert np.abs(dec[:14].astype(int) - img[:14].astype(int)).mean() < 12
    gray = imdecode_np(buf, iscolor=0)
    assert gray.shape == (32, 48)


@pytest.mark.skipif(not HAVE_NATIVE, reason="native lib required")
def test_native_resize():
    import ctypes
    lib = native_lib()
    src = np.arange(16 * 16 * 3, dtype=np.uint8).reshape(16, 16, 3)
    dst = np.empty((8, 8, 3), np.uint8)
    u8p = ctypes.POINTER(ctypes.c_ubyte)
    assert lib.MXTImageResize(src.ctypes.data_as(u8p), 16, 16, 3,
                              dst.ctypes.data_as(u8p), 8, 8) == 0
    # downscale of a gradient stays a gradient
    assert dst[0, 0, 0] < dst[7, 7, 0]


def _make_rec(tmp_path, n=24, h=40, w=40):
    from mxnet_tpu.image.codec import imencode
    rec_path = str(tmp_path / "imgs.rec")
    writer = recordio.MXRecordIO(rec_path, "w")
    rng = np.random.RandomState(0)
    for i in range(n):
        img = rng.randint(0, 255, (h, w, 3), dtype=np.uint8)
        header = recordio.IRHeader(0, float(i % 10), i, 0)
        writer.write(recordio.pack(header, imencode(img, ".jpg")))
    writer.close()
    return rec_path


@pytest.mark.skipif(not HAVE_NATIVE, reason="native lib required")
def test_native_image_pipeline(tmp_path):
    rec_path = _make_rec(tmp_path, n=24)
    it = mx.io.ImageRecordIter(path_imgrec=rec_path, data_shape=(3, 32, 32),
                               batch_size=8, shuffle=True, rand_crop=True,
                               preprocess_threads=3, seed=7)
    from mxnet_tpu.image.record_iter import NativeImageRecordIter
    assert isinstance(it, NativeImageRecordIter)
    seen = 0
    labels = []
    for batch in it:
        assert batch.data[0].shape == (8, 3, 32, 32)
        assert batch.label[0].shape == (8,)
        labels.append(batch.label[0].asnumpy())
        seen += 8 - batch.pad
    assert seen == 24
    # labels are the class ids we packed
    all_labels = np.concatenate(labels)
    assert set(all_labels.astype(int)) <= set(range(10))
    # second epoch after reset
    it.reset()
    seen2 = sum(8 - b.pad for b in it)
    assert seen2 == 24


@pytest.mark.skipif(not HAVE_NATIVE, reason="native lib required")
def test_native_pipeline_partial_batch_pad(tmp_path):
    rec_path = _make_rec(tmp_path, n=10)
    it = mx.io.ImageRecordIter(path_imgrec=rec_path, data_shape=(3, 32, 32),
                               batch_size=8)
    batches = list(it)
    assert len(batches) == 2
    assert batches[0].pad == 0
    assert batches[1].pad == 6  # 10 = 8 + 2, final batch wraps 6


@pytest.mark.skipif(not HAVE_NATIVE, reason="native lib required")
def test_native_pipeline_sticky_eof_and_tiny_shard(tmp_path):
    # batch much larger than the record count exercises modulo wrap,
    # and a second exhausted iteration must re-raise StopIteration
    # instead of deadlocking on the native coordinator
    rec_path = _make_rec(tmp_path, n=3)
    it = mx.io.ImageRecordIter(path_imgrec=rec_path, data_shape=(3, 32, 32),
                               batch_size=8)
    batches = list(it)
    assert len(batches) == 1
    assert batches[0].pad == 5
    assert list(it) == []
    it.reset()
    assert len(list(it)) == 1


def test_python_fallback_round_batch(tmp_path):
    # fallback iterator must match native round_batch semantics
    import mxnet_tpu._native as nat
    from mxnet_tpu.image.record_iter import ImageRecordIterImpl
    rec_path = _make_rec(tmp_path, n=10)
    it = ImageRecordIterImpl(path_imgrec=rec_path, data_shape=(3, 32, 32),
                             batch_size=8)
    batches = list(it)
    assert [b.pad for b in batches] == [0, 6]


@pytest.mark.skipif(not HAVE_NATIVE, reason="native lib required")
def test_python_fallback_reads_native_multichunk(tmp_path):
    # records containing the aligned magic are written multi-chunk by the
    # native writer; the pure-Python reader must reassemble them
    magic = (0xced7230a).to_bytes(4, "little")
    payload = b"head" + magic + b"tail"
    path = str(tmp_path / "mc.rec")
    w = recordio.MXRecordIO(path, "w")
    assert w.handle is not None
    w.write(payload)
    w.close()
    import mxnet_tpu._native as nat
    saved = nat._LIB
    try:
        nat._LIB = None
        r = recordio.MXRecordIO(path, "r")
        assert r.handle is None
        assert r.read() == payload
        assert r.read() is None
        r.close()
    finally:
        nat._LIB = saved


@pytest.mark.skipif(not HAVE_NATIVE, reason="native lib required")
def test_native_pipeline_sharding(tmp_path):
    rec_path = _make_rec(tmp_path, n=24)
    counts = []
    for part in range(3):
        it = mx.io.ImageRecordIter(path_imgrec=rec_path,
                                   data_shape=(3, 32, 32), batch_size=4,
                                   part_index=part, num_parts=3)
        counts.append(sum(4 - b.pad for b in it))
    assert counts == [8, 8, 8]


def test_mask_encode_decode_roundtrip():
    rng = np.random.RandomState(3)
    mask = (rng.rand(17, 23) > 0.5).astype(np.uint8)
    rle = coco_mask.encode(mask)
    assert rle["size"] == [17, 23]
    back = coco_mask.decode(rle)
    np.testing.assert_array_equal(mask, back)
    assert coco_mask.area(rle) == int(mask.sum())


def test_mask_merge_and_iou():
    a = np.zeros((10, 10), np.uint8)
    a[2:6, 2:6] = 1  # 16 px
    b = np.zeros((10, 10), np.uint8)
    b[4:8, 4:8] = 1  # 16 px, overlap 2x2=4
    ra, rb = coco_mask.encode(a), coco_mask.encode(b)
    union = coco_mask.merge([ra, rb])
    inter = coco_mask.merge([ra, rb], intersect=True)
    assert coco_mask.area(union) == 28
    assert coco_mask.area(inter) == 4
    got = coco_mask.iou([ra], [rb])
    np.testing.assert_allclose(got, [[4.0 / 28.0]], rtol=1e-9)
    crowd = coco_mask.iou([ra], [rb], iscrowd=[1])
    np.testing.assert_allclose(crowd, [[4.0 / 16.0]], rtol=1e-9)


def test_mask_frpoly():
    # axis-aligned square covering pixel centers [2..6] x [2..6]
    rle = coco_mask.frPoly([2, 2, 7, 2, 7, 7, 2, 7], 10, 10)
    mask = coco_mask.decode(rle)
    assert coco_mask.area(rle) == mask.sum()
    assert mask.sum() == 25
    assert mask[4, 4] == 1 and mask[0, 0] == 0


@pytest.mark.skipif(not HAVE_NATIVE, reason="native lib required")
def test_mask_native_matches_numpy_fallback():
    import mxnet_tpu._native as nat
    rng = np.random.RandomState(11)
    masks = (rng.rand(13, 9, 4) > 0.6).astype(np.uint8)
    native_rles = coco_mask.encode(masks)
    saved = nat._LIB
    try:
        nat._LIB = None  # force the pure-NumPy fallback
        py_rles = coco_mask.encode(masks)
        for nr, pr in zip(native_rles, py_rles):
            np.testing.assert_array_equal(nr["counts"], pr["counts"])
        np.testing.assert_array_equal(coco_mask.decode(py_rles), masks)
    finally:
        nat._LIB = saved
