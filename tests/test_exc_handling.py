"""Exception propagation (reference
tests/python/unittest/test_exc_handling.py): errors from ops/executors
must surface as Python exceptions at the call or sync point, and the
session must stay usable afterwards (the reference rethrows captured
exceptions at WaitToRead, threaded_engine.cc:465)."""
import numpy as np
import pytest

import mxnet_tpu as mx


def test_imperative_shape_error_raises():
    a = mx.nd.ones((2, 3))
    b = mx.nd.ones((4, 5))
    with pytest.raises(Exception):
        (a + b).asnumpy()
    # session still usable after the failure
    out = (a * 2).asnumpy()
    np.testing.assert_allclose(out, 2.0)


def test_executor_bind_shape_mismatch():
    x = mx.sym.Variable("x")
    y = mx.sym.FullyConnected(x, num_hidden=4, name="fc")
    with pytest.raises(Exception):
        y.simple_bind(mx.cpu(), x=(2,))  # 1-D data into FC weight infer


def test_invalid_op_param():
    data = mx.nd.ones((2, 3, 8, 8))
    with pytest.raises(Exception):
        mx.nd.Pooling(data, kernel=(99, 99), pool_type="max",
                      pooling_convention="valid").asnumpy()


def test_bad_reshape_raises():
    a = mx.nd.ones((6,))
    with pytest.raises(Exception):
        mx.nd.Reshape(a, shape=(4, 2)).asnumpy()


def test_autograd_error_leaves_clean_state():
    a = mx.nd.ones((2, 2))
    a.attach_grad()
    try:
        with mx.autograd.record():
            bad = mx.nd.dot(a, mx.nd.ones((3, 3)))  # shape mismatch
            bad.asnumpy()
    except Exception:
        pass
    # recording state must not leak
    with mx.autograd.record():
        y = (a * a).sum()
    y.backward()
    np.testing.assert_allclose(a.grad.asnumpy(), 2.0)


def test_waitall_after_error():
    a = mx.nd.ones((2, 3))
    try:
        (a + mx.nd.ones((5, 7))).asnumpy()
    except Exception:
        pass
    mx.nd.waitall()  # must not hang or rethrow stale errors
