"""Communication anatomy (`mxnet_tpu/shardprof.py`): HLO collective
extraction, the compile-hook ledger, the sharding audit, the overlap /
comm-bound verdict, the report CLI, and the bench_gate comm delta.

Runs on the forced 8-device CPU mesh from conftest. The acceptance
assertions live here: a non-empty collective inventory for an FSDP
`Module` train step (all-gather + a reduction collective, bytes > 0), a
deliberately mis-replicated param flagged by the audit, a `comm-bound`
verdict out of `stepprof.classify`, and `xla_stats.compile_counts()`
proving the instrumentation itself adds zero compiles/retraces.
"""
import io
import json
import os
import sys

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import shardprof, stepprof, telemetry, xla_stats
from mxnet_tpu.parallel import spmd

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))

import bench_gate  # noqa: E402


@pytest.fixture
def fresh():
    """Clean registries: telemetry, stepprof, and the shardprof program
    ledger (compile-accounting state untouched — tests diff it)."""
    telemetry.reset()
    stepprof.reset()
    stepprof.disable()
    shardprof.reset()
    yield
    shardprof.reset()
    stepprof.disable()
    stepprof.reset()
    telemetry.reset()


# ---------------------------------------------------------------------------
# HLO-text extractor fixtures (one line per collective kind + edge cases)
# ---------------------------------------------------------------------------

_HLO_FIXTURE = """\
HloModule jit_step, entry_computation_layout={...}

%fused_computation (param_0: f32[16,8]) -> f32[2,8] {
  ROOT %slice = f32[2,8]{1,0} slice(f32[16,8]{1,0} %param_0)
}

ENTRY %main.42 {
  %ar = f32[16,8]{1,0} all-reduce(f32[16,8]{1,0} %dot), channel_id=1, \
replica_groups=[1,8]<=[8], use_global_device_ids=true, \
metadata={op_name="jit(step)/all_reduce_thing"}
  %ag = bf16[24,16]{1,0} all-gather(bf16[3,16]{1,0} %p0), channel_id=2, \
replica_groups={{0,1,2,3},{4,5,6,7}}, dimensions={0}
  %rs = f32[2,8]{1,0} reduce-scatter(f32[16,8]{1,0} %g), channel_id=3, \
replica_groups=[1,8]<=[8], dimensions={0}
  %cp = f32[4]{0} collective-permute(f32[4]{0} %x), channel_id=4, \
source_target_pairs={{0,1},{1,0}}
  %a2a = f32[8,4]{1,0} all-to-all(f32[8,4]{1,0} %y), channel_id=5, \
replica_groups=[2,4]<=[8], dimensions={0}
  %ags = (f32[3,16]{1,0}, f32[24,16]{1,0}) all-gather-start(\
f32[3,16]{1,0} %p1), channel_id=6, replica_groups=[1,8]<=[8]
  %agd = f32[24,16]{1,0} all-gather-done((f32[3,16]{1,0}, \
f32[24,16]{1,0}) %ags)
  %scalar = f32[] all-reduce(f32[] %loss), channel_id=7, \
replica_groups=[1,8]<=[8], to_apply=%add
  %renamed = f32[4]{0} add(f32[4]{0} %cp, f32[4]{0} %cp), \
metadata={op_name="looks like all-gather in a name only"}
}
"""


def test_parse_hlo_every_kind_counts_and_bytes():
    colls = shardprof.parse_hlo_collectives(_HLO_FIXTURE)
    by_kind = {}
    for c in colls:
        by_kind.setdefault(c["kind"], []).append(c)
    # one of each kind, plus the async all-gather-start and the scalar
    # all-reduce; the -done half and the metadata mention never count
    assert len(by_kind["all-reduce"]) == 2
    assert len(by_kind["all-gather"]) == 2
    assert len(by_kind["reduce-scatter"]) == 1
    assert len(by_kind["collective-permute"]) == 1
    assert len(by_kind["all-to-all"]) == 1
    # bytes: result-shape payload (bf16 = 2 bytes/elem)
    assert by_kind["all-reduce"][0]["bytes"] == 16 * 8 * 4
    assert by_kind["all-reduce"][1]["bytes"] == 4          # f32[] scalar
    assert by_kind["all-gather"][0]["bytes"] == 24 * 16 * 2  # bf16
    assert by_kind["reduce-scatter"][0]["bytes"] == 2 * 8 * 4
    # async start: only the OUTPUT half of the tuple is the wire
    assert by_kind["all-gather"][1]["async"]
    assert by_kind["all-gather"][1]["bytes"] == 24 * 16 * 4
    # replica groups: iota and explicit-list syntaxes both parse
    assert by_kind["all-reduce"][0]["replica_groups"] == (1, 8)
    assert by_kind["all-gather"][0]["replica_groups"] == (2, 4)
    assert by_kind["all-to-all"][0]["replica_groups"] == (2, 4)


def test_inventory_aggregation():
    inv = shardprof.inventory_of(_HLO_FIXTURE)
    assert inv["all-reduce"]["count"] == 2
    assert inv["all-reduce"]["bytes"] == 16 * 8 * 4 + 4
    assert (1, 8) in inv["all-reduce"]["replica_groups"]
    assert inv["all-gather"]["count"] == 2
    total = sum(d["bytes"] for d in inv.values())
    assert total > 0


# ---------------------------------------------------------------------------
# The compile-hook ledger (note_program) + counters + fallback
# ---------------------------------------------------------------------------

class _FakeCompiled:
    def __init__(self, text=None):
        self._text = text

    def as_text(self):
        if self._text is None:
            raise NotImplementedError("no HLO on this backend")
        return self._text

    def cost_analysis(self):
        return {"flops": 100.0, "bytes accessed": 4096.0}


def test_note_program_ledger_and_counters(fresh):
    c0 = telemetry.counter("spmd_collectives_total").value
    b0 = telemetry.counter("spmd_collective_bytes_total").value
    entry = shardprof.note_program("test.site", ("test.site", 1),
                                   _FakeCompiled(_HLO_FIXTURE))
    assert entry["source"] == "hlo" and entry["bytes"] > 0
    assert shardprof.site_inventory("test.site")["collectives"]
    assert telemetry.counter("spmd_collectives_total").value == c0 + 7
    assert telemetry.counter("spmd_collective_bytes_total").value > b0
    per_kind = telemetry.counter("spmd_collective_bytes_total",
                                 kind="all-reduce").value
    assert per_kind == 16 * 8 * 4 + 4
    # a second compile of the same signature key replaces, not stacks
    entry2 = shardprof.note_program("test.site", ("test.site", 1),
                                    _FakeCompiled(_HLO_FIXTURE))
    assert entry2["compiles"] == 2
    assert len([k for k in shardprof.programs() if k[0] == "test.site"]) \
        == 1


def test_note_program_cost_analysis_fallback(fresh):
    s0 = telemetry.counter("errors_swallowed_total",
                           site="shardprof.hlo_text").value
    entry = shardprof.note_program("test.fallback", ("test.fallback", 1),
                                   _FakeCompiled(None))
    assert entry["source"] == "cost_analysis"
    assert entry["collectives"] == {}
    assert entry["cost"] == {"bytes_accessed": 4096.0}
    # the guarded parse failure is counted, not silent
    assert telemetry.counter("errors_swallowed_total",
                             site="shardprof.hlo_text").value == s0 + 1


def test_disabled_records_nothing(fresh, monkeypatch):
    monkeypatch.setenv("MXNET_SHARDPROF", "0")
    assert shardprof.note_program("x", ("x", 1),
                                  _FakeCompiled(_HLO_FIXTURE)) is None
    assert shardprof.programs() == {}


# ---------------------------------------------------------------------------
# Acceptance: FSDP Module step inventory + zero instrumentation compiles
# ---------------------------------------------------------------------------

def _mlp():
    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data, num_hidden=16, name="fc1")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.FullyConnected(net, num_hidden=4, name="fc2")
    return mx.sym.SoftmaxOutput(net, name="softmax")


def _fsdp_module(spmd_arg="fsdp", n=64, d=24):
    X = np.random.RandomState(0).randn(n, d).astype(np.float32)
    y = (np.random.RandomState(1).rand(n) * 4).astype(np.float32)
    it = mx.io.NDArrayIter(X, y, batch_size=32, label_name="softmax_label")
    mod = mx.mod.Module(_mlp(), context=mx.cpu())
    mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label,
             spmd=spmd_arg)
    mod.init_params()
    mod.init_optimizer(kvstore="tpu", optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.1),
                                         ("momentum", 0.9)))
    return mod, list(it)


def test_fsdp_fit_step_inventory_nonempty_and_instrumentation_free(fresh):
    mod, batches = _fsdp_module()
    for b in batches:
        mod._step(b)
    inv = shardprof.site_inventory("module.fused_step")
    assert inv is not None and inv["collectives"], \
        "FSDP train step compiled with no collective inventory"
    # the fsdp weight gather and a gradient reduction are both on the
    # wire (the CPU SPMD partitioner lowers the reduce-scatter as
    # all-reduce + slice, so accept either reduction form)
    assert "all-gather" in inv["collectives"]
    assert inv["collectives"]["all-gather"]["bytes"] > 0
    assert ("reduce-scatter" in inv["collectives"]
            or "all-reduce" in inv["collectives"])
    assert inv["bytes"] > 0
    assert shardprof.train_step_inventory()["site"] == "module.fused_step"

    # the instrumentation itself adds ZERO compiles/retraces: query
    # every surface, then keep training on the warm cache
    c0 = xla_stats.compile_counts()
    shardprof.audit(mod)
    shardprof.comm_stats(gbps=8.0)
    shardprof.snapshot()
    buf = io.StringIO()
    shardprof.report(out=buf)
    for b in batches:
        mod._step(b)
    c1 = xla_stats.compile_counts()
    assert c1["compiles"] == c0["compiles"], \
        "communication instrumentation triggered a compile"
    assert c1["retraces"] == c0["retraces"], \
        "communication instrumentation triggered a retrace"


# ---------------------------------------------------------------------------
# Sharding audit: DP / FSDP / tensor fixtures + the mis-replication flag
# ---------------------------------------------------------------------------

def test_audit_ok_per_policy(fresh):
    for spmd_arg in ("data_parallel", "fsdp",
                     {"policy": "tensor", "model_axis": 2}):
        mod, batches = _fsdp_module(spmd_arg=spmd_arg)
        mod._step(batches[0])
        aud = shardprof.audit(mod)
        assert aud["flagged"] == [], \
            "%s audit flagged %s" % (spmd_arg, aud["flagged"])
        kinds = {r["kind"] for r in aud["rows"]}
        assert {"param", "grad", "opt_state"} <= kinds
        if spmd_arg == "data_parallel":
            assert aud["sharded_bytes"] == 0
            assert aud["replicated_bytes"] > 0
        else:
            assert aud["sharded_bytes"] > 0
        assert aud["param_bytes_global"] > 0
        g = telemetry.gauge("spmd_sharded_param_bytes").value
        assert g == aud["sharded_bytes"]


def test_audit_flags_misreplicated_param(fresh):
    """The init_params bias-bug class: a param the policy shards that
    silently ends up replicated must be named by the audit."""
    import jax
    mod, _batches = _fsdp_module()
    pol = mod._spmd
    w = mod._exec.arg_dict["fc1_weight"]
    w._data = jax.device_put(np.asarray(w.asnumpy()), pol.replicated())
    aud = shardprof.audit(mod)
    flagged = {r["name"]: r for r in aud["rows"] if r["status"] != "ok"}
    assert "fc1_weight" in flagged
    assert flagged["fc1_weight"]["status"] == "replicated"
    assert flagged["fc1_weight"]["kind"] == "param"
    assert "fc1_weight" in aud["flagged"]
    assert telemetry.gauge("spmd_replicated_param_bytes").value >= \
        w._data.nbytes


def test_audit_gluon_trainer(fresh):
    from mxnet_tpu.gluon import nn, Trainer
    net = nn.Dense(16, in_units=24)
    net.initialize()
    net.hybridize()
    trainer = Trainer(net.collect_params(), "sgd",
                      {"learning_rate": 0.1}, spmd="fsdp")
    aud = shardprof.audit(trainer)
    assert aud["policy"] == "fsdp"
    assert aud["flagged"] == []
    assert aud["sharded_bytes"] > 0


def test_audit_plain_dict_with_policy(fresh):
    import jax
    import jax.numpy as jnp
    pol = spmd.make_policy("fsdp")
    good = jax.device_put(jnp.zeros((16, 8), jnp.float32),
                          pol.param_sharding("w", (16, 8)))
    bad = jax.device_put(jnp.zeros((16, 8), jnp.float32),
                         pol.replicated())
    aud = shardprof.audit({"good": good, "bad": bad}, policy=pol)
    by_name = {r["name"]: r for r in aud["rows"]}
    assert by_name["good"]["status"] == "ok"
    assert by_name["bad"]["status"] == "replicated"


# ---------------------------------------------------------------------------
# Overlap / comm verdict
# ---------------------------------------------------------------------------

def test_comm_stats_prediction_and_overlap(fresh, monkeypatch):
    shardprof.note_program("module.fused_step", ("module.fused_step", 1),
                           _FakeCompiled(_HLO_FIXTURE))
    # 10 steps of 10ms wall, 8ms sampled device time each
    for _ in range(10):
        stepprof.record_step({"device_compute": 0.002,
                              "dispatch": 0.001}, 0.010)
    stepprof.note_device_sample(0.008)
    monkeypatch.setenv("MXNET_SHARDPROF_LINK_GBPS", "0.001")  # 1 MB/s
    comm = shardprof.comm_stats()
    assert comm is not None
    assert comm["site"] == "module.fused_step"
    assert comm["bytes_per_step"] == shardprof.site_inventory(
        "module.fused_step")["bytes"]
    expect_c = comm["bytes_per_step"] / 1e6
    assert comm["predicted_comm_seconds"] == pytest.approx(expect_c)
    assert 0.0 < comm["comm_fraction"] <= 1.0
    assert comm["overlap_fraction"] is not None
    assert 0.0 <= comm["overlap_fraction"] <= 1.0
    assert telemetry.gauge("spmd_predicted_comm_seconds").value == \
        pytest.approx(expect_c)
    # explicit bandwidth argument wins over the env table
    c2 = shardprof.comm_stats(gbps=2e-3)
    assert c2["predicted_comm_seconds"] == pytest.approx(expect_c / 2)


def test_comm_stats_none_without_inventory_or_bandwidth(fresh,
                                                        monkeypatch):
    assert shardprof.comm_stats() is None        # no inventory at all
    shardprof.note_program("module.fused_step", ("module.fused_step", 1),
                           _FakeCompiled(_HLO_FIXTURE))
    monkeypatch.setenv("MXNET_SHARDPROF_LINK_GBPS", "0")
    assert shardprof.comm_stats() is None        # no bandwidth figure


def test_classify_comm_bound_fsdp_hint():
    shares = {"device_compute": 0.7, "dispatch": 0.2, "data_wait": 0.1}
    comm = {"comm_fraction": 0.6, "overlap_fraction": 0.1,
            "dominant_kind": "all-gather", "param_gather_ratio": 1.05}
    v, hint = stepprof.classify(shares, comm=comm)
    assert v == "comm-bound"
    assert "fsdp weight gather" in hint and "donation" in hint
    assert "10%" in hint  # the overlap figure is in the hint


def test_classify_comm_bound_allreduce_hint_and_threshold():
    shares = {"device_compute": 0.9, "dispatch": 0.1}
    # all-reduce-dominant inventory -> dp gradient-sync hint
    comm = {"comm_fraction": 0.5, "dominant_kind": "all-reduce"}
    v, hint = stepprof.classify(shares, comm=comm)
    assert v == "comm-bound"
    assert "gradient_compression" in hint
    # small predicted comm never flips the verdict
    v2, _ = stepprof.classify(shares, comm={"comm_fraction": 0.05,
                                            "dominant_kind": "all-reduce"})
    assert v2 == "compute-bound"
    # no shares at all: a dominant comm figure still names the wire
    v3, _ = stepprof.classify({}, comm={"comm_fraction": 0.8})
    assert v3 == "comm-bound"
    assert stepprof.classify({}, comm=None)[0] == "unknown"


def test_live_verdict_is_comm_aware(fresh, monkeypatch):
    shardprof.note_program("module.fused_step", ("module.fused_step", 1),
                           _FakeCompiled(_HLO_FIXTURE))
    for _ in range(4):
        stepprof.record_step({"device_compute": 0.004}, 0.005)
    # a wire so slow the predicted comm dwarfs the step -> comm-bound
    monkeypatch.setenv("MXNET_SHARDPROF_LINK_GBPS", "1e-6")
    v, _ = stepprof.verdict()
    assert v == "comm-bound"


# ---------------------------------------------------------------------------
# Snapshots, cross-host merge, report CLI
# ---------------------------------------------------------------------------

def _fake_snapshot(host, comm_seconds, flagged=()):
    return {"host": host, "pid": 1000 + host, "updated": 1e9 + host,
            "sites": {}, "steps": 4,
            "totals": {"all-gather": {"count": 2,
                                      "bytes": 1024 * (host + 1)}},
            "comm": {"site": "module.fused_step",
                     "bytes_per_step": 1024 * (host + 1),
                     "by_kind": {"all-gather": 1024 * (host + 1)},
                     "dominant_kind": "all-gather",
                     "predicted_comm_seconds": comm_seconds,
                     "link_gbps": 8.0, "step_seconds": 0.01,
                     "comm_fraction": 0.5, "overlap_fraction": 0.25,
                     "param_gather_ratio": 1.0},
            "audit": {"policy": "fsdp", "flagged": list(flagged),
                      "replicated_bytes": 64, "sharded_bytes": 4096,
                      "rows": 6, "bad_rows": []}}


def test_write_and_merge_host_snapshots(fresh, tmp_path):
    shardprof.note_program("test.site", ("test.site", 1),
                           _FakeCompiled(_HLO_FIXTURE))
    path = shardprof.write_host_snapshot(str(tmp_path), force=True)
    assert path and os.path.exists(path)
    merged = shardprof.merge_host_snapshots(str(tmp_path))
    assert telemetry.host_id() in merged
    doc = merged[telemetry.host_id()]
    assert doc["totals"]["all-reduce"]["bytes"] > 0


def test_report_cli_host_dir_roundtrip(fresh, tmp_path, capsys):
    for host, secs in ((0, 0.001), (1, 0.004)):
        with open(os.path.join(str(tmp_path),
                               "shardprof_host%d_pid1.json" % host),
                  "w") as fh:
            json.dump(_fake_snapshot(host, secs,
                                     flagged=["fc1_bias"] if host else []),
                      fh)
    rc = shardprof.main(["report", str(tmp_path)])
    out = capsys.readouterr().out
    assert rc == 0
    assert "all-gather" in out
    assert "comm skew" in out and "slow host 1" in out
    assert "audit[fsdp]" in out and "fc1_bias" in out
    rec = json.loads(out.strip().splitlines()[-1])
    assert rec["metric"] == "shardprof_report"
    assert rec["comm_skew_seconds"] == pytest.approx(0.003)
    assert rec["audit_flagged"] == 1
    # the skew helper names the slow host and publishes the gauge
    sk = shardprof.comm_skew(str(tmp_path))
    assert sk["slow_host"] == 1
    assert sk["skew_seconds"] == pytest.approx(0.003)
    assert telemetry.gauge("spmd_comm_skew_seconds").value == \
        pytest.approx(0.003)


def test_report_cli_no_data_exits_1(fresh, tmp_path, capsys):
    rc = shardprof.main(["report", str(tmp_path), "--json"])
    out = capsys.readouterr().out
    assert rc == 1
    rec = json.loads(out.strip().splitlines()[-1])
    assert rec["collectives"] == {}


def test_report_single_snapshot_file(fresh, tmp_path, capsys):
    p = tmp_path / "snap.json"
    p.write_text(json.dumps(_fake_snapshot(3, 0.002)))
    rc = shardprof.main(["report", str(p)])
    out = capsys.readouterr().out
    assert rc == 0
    assert "comm share: 50%" in out and "overlap 25%" in out
    assert "verdict: comm-bound" in out


# ---------------------------------------------------------------------------
# Speedometer comm suffix (gated like the phase summary)
# ---------------------------------------------------------------------------

def test_speedometer_comm_suffix_gated(fresh, monkeypatch):
    sp = mx.callback.Speedometer(batch_size=16, frequent=4)
    shardprof.note_program("module.fused_step", ("module.fused_step", 1),
                           _FakeCompiled(_HLO_FIXTURE))
    for _ in range(4):
        stepprof.record_step({"device_compute": 0.004}, 0.005)
    monkeypatch.setenv("MXNET_SHARDPROF_LINK_GBPS", "0.001")
    assert sp._comm_suffix() == ""          # disabled: no suffix
    stepprof.enable()
    try:
        suffix = sp._comm_suffix()
        assert "comm" in suffix and "%" in suffix
    finally:
        stepprof.disable()


# ---------------------------------------------------------------------------
# Bench wiring: scaling record attribution + bench_gate comm delta
# ---------------------------------------------------------------------------

def test_scaling_record_carries_comm_attribution(fresh):
    sys.path.insert(0, REPO)
    import __graft_entry__ as graft
    rec = graft.scaling_efficiency_record(8, batch_per_device=8, steps=2)
    assert rec["metric"] == "multichip_scaling_efficiency"
    assert rec["value"] > 0
    assert rec["collectives"], "scaling record carries no collectives"
    assert all(d["bytes"] >= 0 for d in rec["collectives"].values())
    assert rec["comm_bytes_per_step"] > 0
    assert rec["audit"]["policy"] == "data_parallel"
    assert rec["audit"]["flagged"] == 0


def test_bench_gate_comm_delta_line(tmp_path):
    d = str(tmp_path)
    hist = {"metric": bench_gate.MULTICHIP_METRIC, "value": 0.9,
            "n_devices": 8,
            "collectives": {"all-reduce": {"count": 4, "bytes": 4096},
                            "all-gather": {"count": 3, "bytes": 1024}}}
    with open(os.path.join(d, "MULTICHIP_r01.json"), "w") as fh:
        json.dump({"n_devices": 8, "ok": True,
                   "tail": json.dumps(hist) + "\n"}, fh)
    run = [{"metric": bench_gate.MULTICHIP_METRIC, "value": 0.5,
            "collectives": {"all-reduce": {"count": 4, "bytes": 4096},
                            "all-gather": {"count": 6, "bytes": 9216}}}]
    out = io.StringIO()
    rc = bench_gate.gate_records(run, history_dir=d,
                                 metric=bench_gate.MULTICHIP_METRIC,
                                 out=out)
    assert rc == 1
    lines = [json.loads(l) for l in out.getvalue().splitlines()]
    comm_lines = [l for l in lines if l["metric"] == "bench_gate_comm"]
    assert len(comm_lines) == 1
    cl = comm_lines[0]
    assert cl["delta"]["all-gather"] == pytest.approx(8192)
    assert cl["delta"]["all-reduce"] == pytest.approx(0)
    assert "all-gather +8192 B/step" in cl["detail"]
    # a passing run prints no delta line
    out2 = io.StringIO()
    ok = [{"metric": bench_gate.MULTICHIP_METRIC, "value": 0.88}]
    assert bench_gate.gate_records(
        ok, history_dir=d, metric=bench_gate.MULTICHIP_METRIC,
        out=out2) == 0
    assert "bench_gate_comm" not in out2.getvalue()


def test_bench_gate_comm_delta_without_run_inventory(tmp_path):
    d = str(tmp_path)
    hist = {"metric": bench_gate.MULTICHIP_METRIC, "value": 0.9,
            "collectives": {"all-reduce": {"count": 4, "bytes": 4096}}}
    with open(os.path.join(d, "MULTICHIP_r01.json"), "w") as fh:
        json.dump({"n_devices": 8, "ok": True,
                   "tail": json.dumps(hist) + "\n"}, fh)
    out = io.StringIO()
    rc = bench_gate.gate_records(
        [{"metric": bench_gate.MULTICHIP_METRIC, "value": 0.5}],
        history_dir=d, metric=bench_gate.MULTICHIP_METRIC, out=out)
    assert rc == 1
    lines = [json.loads(l) for l in out.getvalue().splitlines()]
    cl = [l for l in lines if l["metric"] == "bench_gate_comm"][0]
    assert "no collective inventory in this run" in cl["detail"]


def test_repo_gate_multichip_comm_history_present():
    """The checked-in MULTICHIP history now carries at least one round
    with the scaling metric line in its tail (the empty-tail fix), so
    repo_gate's multichip lane has something to gate against."""
    hist = bench_gate.load_history(REPO)
    assert bench_gate.MULTICHIP_METRIC in hist, \
        "no MULTICHIP round in repo history carries the metric line"
