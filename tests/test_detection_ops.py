"""Detection / spatial / fork op tests against NumPy oracles
(mirrors reference tests/python/unittest/test_operator.py style)."""
import math

import numpy as np
import jax.numpy as jnp
import pytest

import mxnet_tpu as mx
from mxnet_tpu.ops.registry import get_op


def run_op(name, params, *inputs):
    outs = get_op(name).fcompute(params, *(jnp.asarray(i) for i in inputs))
    return [np.asarray(o) for o in outs]


def test_multibox_prior_matches_reference_layout():
    data = np.zeros((1, 3, 2, 3), np.float32)  # H=2, W=3
    sizes, ratios = (0.5, 0.25), (1.0, 2.0)
    (out,) = run_op("_contrib_MultiBoxPrior",
                    {"sizes": sizes, "ratios": ratios}, data)
    h, w = 2, 3
    na = len(sizes) - 1 + len(ratios)
    assert out.shape == (1, h * w * na, 4)
    # oracle: loop exactly as multibox_prior.cc:43-70
    want = []
    for r in range(h):
        cy = (r + 0.5) / h
        for c in range(w):
            cx = (c + 0.5) / w
            for s in sizes:
                ww, hh = s * h / w / 2, s / 2
                want.append([cx - ww, cy - hh, cx + ww, cy + hh])
            for rt in ratios[1:]:
                sr = math.sqrt(rt)
                ww, hh = sizes[0] * h / w * sr / 2, sizes[0] / sr / 2
                want.append([cx - ww, cy - hh, cx + ww, cy + hh])
    np.testing.assert_allclose(out[0], np.asarray(want), rtol=1e-5, atol=1e-6)


def test_multibox_target_simple_match():
    # two anchors, one gt that clearly matches anchor 0
    anchors = np.asarray([[[0.0, 0.0, 0.5, 0.5], [0.5, 0.5, 1.0, 1.0]]],
                         np.float32)
    label = np.asarray([[[1.0, 0.05, 0.05, 0.45, 0.45],
                         [-1, -1, -1, -1, -1]]], np.float32)
    cls_pred = np.zeros((1, 3, 2), np.float32)
    loc_t, loc_m, cls_t = run_op("_contrib_MultiBoxTarget", {},
                                 anchors, label, cls_pred)
    assert cls_t.shape == (1, 2)
    assert cls_t[0, 0] == 2.0        # class 1 shifted +1
    assert cls_t[0, 1] == 0.0        # background
    assert loc_m[0, :4].sum() == 4.0 and loc_m[0, 4:].sum() == 0.0
    # encoding oracle for anchor 0
    ax, ay, aw, ah = 0.25, 0.25, 0.5, 0.5
    gx, gy, gw, gh = 0.25, 0.25, 0.4, 0.4
    want = [(gx - ax) / aw / 0.1, (gy - ay) / ah / 0.1,
            math.log(gw / aw) / 0.2, math.log(gh / ah) / 0.2]
    np.testing.assert_allclose(loc_t[0, :4], want, rtol=1e-4, atol=1e-5)


def test_multibox_target_no_gt_all_background():
    anchors = np.random.RandomState(0).rand(1, 5, 4).astype(np.float32)
    label = -np.ones((2, 3, 5), np.float32)
    cls_pred = np.zeros((2, 4, 5), np.float32)
    loc_t, loc_m, cls_t = run_op("_contrib_MultiBoxTarget", {},
                                 anchors, label, cls_pred)
    assert (cls_t == 0).all() and (loc_m == 0).all() and (loc_t == 0).all()


def test_multibox_detection_decode_and_nms():
    anchors = np.asarray([[[0.1, 0.1, 0.3, 0.3],
                           [0.11, 0.11, 0.31, 0.31],
                           [0.6, 0.6, 0.9, 0.9]]], np.float32)
    # zero loc_pred => boxes == anchors
    loc_pred = np.zeros((1, 12), np.float32)
    # cls_prob (B, C=2, A): background + 1 class
    cls_prob = np.asarray([[[0.1, 0.2, 0.3],
                            [0.9, 0.8, 0.7]]], np.float32)
    (out,) = run_op("_contrib_MultiBoxDetection",
                    {"nms_threshold": 0.5}, cls_prob, loc_pred, anchors)
    assert out.shape == (1, 3, 6)
    ids = out[0, :, 0]
    # anchor 0 (score .9) kept, anchor 1 suppressed (iou~.8), anchor 2 kept
    assert ids[0] == 0.0 and ids[1] == -1.0 and ids[2] == 0.0
    np.testing.assert_allclose(out[0, 0, 2:], [0.1, 0.1, 0.3, 0.3],
                               atol=1e-5)


def test_proposal_shapes_and_validity():
    rng = np.random.RandomState(0)
    B, A, H, W = 1, 12, 4, 4  # 4 scales x 3 ratios
    cls_prob = rng.rand(B, 2 * A, H, W).astype(np.float32)
    bbox_pred = (rng.randn(B, 4 * A, H, W) * 0.1).astype(np.float32)
    im_info = np.asarray([[64.0, 64.0, 1.0]], np.float32)
    (rois,) = run_op("_contrib_Proposal",
                     {"rpn_post_nms_top_n": 8, "rpn_pre_nms_top_n": 50,
                      "feature_stride": 16}, cls_prob, bbox_pred, im_info)
    assert rois.shape == (8, 5)
    assert (rois[:, 0] == 0).all()
    assert (rois[:, 1:] >= 0).all() and (rois[:, [1, 3]] <= 64).all()


def test_bilinear_sampler_identity():
    rng = np.random.RandomState(1)
    data = rng.randn(2, 3, 5, 7).astype(np.float32)
    ys, xs = np.meshgrid(np.linspace(-1, 1, 5), np.linspace(-1, 1, 7),
                         indexing="ij")
    grid = np.stack([xs, ys])[None].repeat(2, 0).astype(np.float32)
    (out,) = run_op("BilinearSampler", {}, data, grid)
    np.testing.assert_allclose(out, data, rtol=1e-5, atol=1e-5)


def test_spatial_transformer_identity_affine():
    rng = np.random.RandomState(2)
    data = rng.randn(1, 2, 6, 6).astype(np.float32)
    theta = np.asarray([[1, 0, 0, 0, 1, 0]], np.float32)
    (out,) = run_op("SpatialTransformer", {"target_shape": (6, 6)},
                    data, theta)
    np.testing.assert_allclose(out, data, rtol=1e-5, atol=1e-5)


def test_grid_generator_warp_zero_flow_is_identity_grid():
    flow = np.zeros((1, 2, 4, 5), np.float32)
    (grid,) = run_op("GridGenerator", {"transform_type": "warp"}, flow)
    ys, xs = np.meshgrid(np.linspace(-1, 1, 4), np.linspace(-1, 1, 5),
                         indexing="ij")
    np.testing.assert_allclose(grid[0, 0], xs, atol=1e-6)
    np.testing.assert_allclose(grid[0, 1], ys, atol=1e-6)


def test_correlation_zero_displacement_is_mean_product():
    rng = np.random.RandomState(3)
    a = rng.randn(1, 4, 6, 6).astype(np.float32)
    (out,) = run_op("Correlation",
                    {"kernel_size": 1, "max_displacement": 1, "stride1": 1,
                     "stride2": 1, "pad_size": 1}, a, a)
    assert out.shape[1] == 9  # 3x3 displacements
    # center channel (index 4) at interior = mean over C of a*a
    want = (a * a).mean(axis=1)
    np.testing.assert_allclose(out[0, 4], want[0], rtol=1e-4, atol=1e-5)


def test_deformable_conv_zero_offset_matches_conv():
    rng = np.random.RandomState(4)
    data = rng.randn(1, 3, 7, 7).astype(np.float32)
    weight = rng.randn(5, 3, 3, 3).astype(np.float32)
    offset = np.zeros((1, 2 * 9, 5, 5), np.float32)
    (out,) = run_op("_contrib_DeformableConvolution",
                    {"kernel": (3, 3), "num_filter": 5, "no_bias": True},
                    data, offset, weight)
    # oracle: plain valid conv
    import jax
    want = jax.lax.conv_general_dilated(
        jnp.asarray(data), jnp.asarray(weight), (1, 1), "VALID",
        dimension_numbers=("NCHW", "OIHW", "NCHW"))
    np.testing.assert_allclose(out, np.asarray(want), rtol=1e-3, atol=1e-4)


def test_lsoftmax_eval_is_linear_train_reduces_target_logit():
    rng = np.random.RandomState(5)
    x = rng.randn(4, 8).astype(np.float32)
    w = rng.randn(6, 8).astype(np.float32)
    y = np.asarray([0, 1, 2, 3], np.float32)
    (out_eval,) = run_op("LSoftmax", {"num_hidden": 6}, x, w, y)
    np.testing.assert_allclose(out_eval, x @ w.T, rtol=1e-5, atol=1e-5)
    (out_tr,) = run_op("LSoftmax",
                       {"num_hidden": 6, "is_train": True, "margin": 2,
                        "beta": 0.0}, x, w, y)
    # margin penalises the target logit (never increases it)
    for i, yi in enumerate(y.astype(int)):
        assert out_tr[i, yi] <= out_eval[i, yi] + 1e-5
        # non-target logits untouched
        mask = np.ones(6, bool); mask[yi] = False
        np.testing.assert_allclose(out_tr[i, mask], out_eval[i, mask],
                                   rtol=1e-5, atol=1e-5)
    # oracle for sample 0: psi(theta) = 2cos^2 - 1 (m=2), k from table
    xn = np.linalg.norm(x[0]); wn = np.linalg.norm(w[0])
    cos_t = (x[0] @ w[0]) / (xn * wn)
    k = 1 if cos_t < math.cos(math.pi / 2) else 0
    cos_mt = 2 * cos_t ** 2 - 1
    want = ((-1) ** k * cos_mt - 2 * k) * xn * wn
    np.testing.assert_allclose(out_tr[0, 0], want, rtol=1e-4, atol=1e-4)


def test_weighted_l1_and_multi_logistic_grads():
    import jax
    rng = np.random.RandomState(6)
    data = jnp.asarray(rng.randn(3, 4).astype(np.float32))
    label = jnp.asarray((rng.rand(3, 4) > 0.5).astype(np.float32))
    f = get_op("weighted_l1").fcompute
    out = f({}, data, label)[0]
    np.testing.assert_allclose(np.asarray(out), np.asarray(data))
    g = jax.grad(lambda d: jnp.sum(f({}, d, label)[0]))(data)
    want = np.sign(np.asarray(data) - np.asarray(label)) * (
        np.asarray(label) > 0)
    np.testing.assert_allclose(np.asarray(g), want)

    f2 = get_op("multi_logistic").fcompute
    out2 = f2({}, data, label)[0]
    np.testing.assert_allclose(np.asarray(out2),
                               1 / (1 + np.exp(-np.asarray(data))),
                               rtol=1e-5)
    g2 = jax.grad(lambda d: jnp.sum(f2({}, d, label)[0]))(data)
    np.testing.assert_allclose(np.asarray(g2),
                               np.asarray(out2) - np.asarray(label),
                               rtol=1e-5, atol=1e-6)


def test_ball_query_matches_reference_loop():
    rng = np.random.RandomState(7)
    xyz = rng.rand(2, 20, 3).astype(np.float32)
    query = rng.rand(2, 4, 3).astype(np.float32)
    r, ns = 0.4, 5
    (idx,) = run_op("_contrib_BallQuery", {"radius": r, "nsample": ns},
                    xyz, query)
    # oracle: reference ball_query-inl.h loop
    for b in range(2):
        for m in range(4):
            want = np.zeros(ns, np.int64)
            cnt = 0
            for k in range(20):
                if ((xyz[b, k] - query[b, m]) ** 2).sum() < r * r:
                    if cnt == 0:
                        want[:] = k
                    want[cnt] = k
                    cnt += 1
                    if cnt >= ns:
                        break
            np.testing.assert_array_equal(idx[b, m], want)


def test_farthest_point_sampling():
    # 4 corners + center: FPS from corner 0 picks far corners first
    pts = np.asarray([[[0, 0, 0], [10, 10, 0], [10, 0, 0], [0, 10, 0],
                       [5, 5, 0]]], np.float32)
    (idx,) = run_op("_contrib_FarthestPointSampling", {"npoints": 4}, pts)
    assert idx[0, 0] == 0 and idx[0, 1] == 1
    assert set(idx[0, 2:].tolist()) == {2, 3}


def test_lsoftmax_train_flag_via_invoke():
    # the margin must engage through the real nd path under train_mode
    rng = np.random.RandomState(8)
    x = mx.nd.array(rng.randn(3, 6).astype(np.float32))
    w = mx.nd.array(rng.randn(4, 6).astype(np.float32))
    y = mx.nd.array(np.asarray([0, 1, 2], np.float32))
    out_eval = mx.nd.LSoftmax(x, w, y, num_hidden=4).asnumpy()
    with mx.autograd.train_mode():
        out_tr = mx.nd.LSoftmax(x, w, y, num_hidden=4, beta=0.0).asnumpy()
    assert not np.allclose(out_eval, out_tr)


def test_deformable_conv_grouped():
    rng = np.random.RandomState(9)
    data = rng.randn(1, 4, 5, 5).astype(np.float32)
    weight = rng.randn(6, 2, 3, 3).astype(np.float32)  # num_group=2
    offset = np.zeros((1, 18, 3, 3), np.float32)
    (out,) = run_op("_contrib_DeformableConvolution",
                    {"kernel": (3, 3), "num_filter": 6, "num_group": 2,
                     "no_bias": True}, data, offset, weight)
    import jax
    want = jax.lax.conv_general_dilated(
        jnp.asarray(data), jnp.asarray(weight), (1, 1), "VALID",
        dimension_numbers=("NCHW", "OIHW", "NCHW"), feature_group_count=2)
    np.testing.assert_allclose(out, np.asarray(want), rtol=1e-3, atol=1e-4)


def test_correlation1d_kernel3_window_and_shape():
    rng = np.random.RandomState(10)
    a = rng.randn(1, 2, 6, 8).astype(np.float32)
    b = rng.randn(1, 2, 6, 8).astype(np.float32)
    (out,) = run_op("Correlation1D",
                    {"kernel_size": 3, "max_displacement": 1, "stride1": 1,
                     "stride2": 1, "pad_size": 2}, a, b)
    # channels = 2d+1 = 3; height shrinks by 2*kr = 2
    assert out.shape == (1, 3, 4, out.shape[3])
    # oracle at zero displacement, interior position (y=1 center -> rows
    # 0..2), x center c: mean over 3x3 window and channels of a*b
    kr, border = 1, 2
    pa = np.pad(a, ((0, 0), (0, 0), (0, 0), (2, 2)))
    pb = np.pad(b, ((0, 0), (0, 0), (0, 0), (2, 2)))
    y, xo = 0, 0
    yc, xc = y + kr, xo + border
    want = (pa[0, :, yc - 1:yc + 2, xc - 1:xc + 2]
            * pb[0, :, yc - 1:yc + 2, xc - 1:xc + 2]).sum() / (9 * 2)
    np.testing.assert_allclose(out[0, 1, y, xo], want, rtol=1e-4)


def test_multibox_detection_nonzero_background_id():
    anchors = np.asarray([[[0.1, 0.1, 0.3, 0.3]]], np.float32)
    loc_pred = np.zeros((1, 4), np.float32)
    # 3 classes, background is class 2; class 0 wins with 0.9
    cls_prob = np.asarray([[[0.9], [0.05], [0.05]]], np.float32)
    (out,) = run_op("_contrib_MultiBoxDetection",
                    {"background_id": 2}, cls_prob, loc_pred, anchors)
    assert out[0, 0, 0] == 0.0          # class 0 keeps id 0
    assert out[0, 0, 1] == np.float32(0.9)


# ---------------------------------------------------------------------------
# Fork RCNN target ops
# ---------------------------------------------------------------------------

def _pt_inputs():
    rng = np.random.RandomState(7)
    B, R, G = 2, 40, 4
    gt = np.zeros((B, G, 5), np.float32)
    for b in range(B):
        for g in range(G - 1):  # last row padding (-1)
            x1, y1 = rng.uniform(0, 60, 2)
            gt[b, g] = [x1, y1, x1 + rng.uniform(10, 40),
                        y1 + rng.uniform(10, 40), rng.randint(1, 4)]
        gt[b, G - 1, 4] = -1
    rois = np.zeros((B, R, 5), np.float32)
    for b in range(B):
        for r in range(R):
            if r < R // 2:  # half jittered around a gt box → fg candidates
                g = rng.randint(0, G - 1)
                jit = rng.uniform(-3, 3, 4)
                rois[b, r] = [b, *(gt[b, g, :4] + jit)]
            else:
                x1, y1 = rng.uniform(0, 80, 2)
                rois[b, r] = [b, x1, y1, x1 + rng.uniform(5, 30),
                              y1 + rng.uniform(5, 30)]
    return rois, gt


def test_proposal_target_shapes_and_semantics():
    import jax
    rois, gt = _pt_inputs()
    params = {"num_classes": 4, "batch_images": 2, "batch_rois": 32,
              "fg_thresh": 0.5, "bg_thresh_hi": 0.5, "bg_thresh_lo": 0.0,
              "proposal_without_gt": True,
              "_rng_key": jax.random.PRNGKey(0)}
    out, label, tgt, wt = run_op("ProposalTarget", params, rois, gt)
    assert out.shape == (32, 5) and label.shape == (32,)
    assert tgt.shape == (32, 16) and wt.shape == (32, 16)
    # batch index column is the image id
    assert set(out[:16, 0]) == {0.0} and set(out[16:, 0]) == {1.0}
    # fg fraction cap: at most 8 fg per image
    for img in range(2):
        lab = label[img * 16:(img + 1) * 16]
        nfg = int((lab > 0).sum())
        assert 0 < nfg <= 8
        # fg rows come first
        assert all(lab[:nfg] > 0) and all(lab[nfg:] == 0)
    # targets/weights nonzero exactly in the labelled class columns
    for i in range(32):
        cls = int(label[i])
        nz = wt[i].reshape(4, 4)
        if cls > 0:
            assert np.all(nz[cls] == 1.0)
            nz_other = np.delete(nz, cls, axis=0)
            assert np.all(nz_other == 0.0)
        else:
            assert np.all(nz == 0.0)
    # every output roi is one of the input rois of its image
    for img in range(2):
        pool = {tuple(np.round(r, 3)) for r in rois[img]}
        for r in out[img * 16:(img + 1) * 16]:
            assert tuple(np.round(r, 3)) in pool


def test_proposal_target_regression_oracle():
    """Check the bbox-target math on a deterministic 1-roi case."""
    import jax
    rois = np.array([[[0, 10, 10, 29, 29]]], np.float32)
    gt = np.array([[[12, 8, 33, 31, 2]]], np.float32)
    params = {"num_classes": 3, "batch_images": 1, "batch_rois": 1,
              "fg_thresh": 0.3, "bg_thresh_hi": 0.3, "bg_thresh_lo": 0.0,
              "proposal_without_gt": True, "fg_fraction": 1.0,
              "bbox_mean": (0, 0, 0, 0), "bbox_std": (1, 1, 1, 1),
              "_rng_key": jax.random.PRNGKey(1)}
    out, label, tgt, wt = run_op("ProposalTarget", params, rois, gt)
    assert label[0] == 2.0
    ew = eh = 20.0
    ecx, ecy = 19.5, 19.5
    gw, gh = 22.0, 24.0
    gcx, gcy = 22.5, 19.5
    want = [(gcx - ecx) / ew, (gcy - ecy) / eh,
            math.log(gw / ew), math.log(gh / eh)]
    np.testing.assert_allclose(tgt[0, 8:12], want, rtol=1e-5, atol=1e-6)
    assert np.all(tgt[0, :8] == 0) and np.all(tgt[0, 12:] == 0)


def test_proposal_mask_target_rasterizes_rectangle():
    import jax
    # one roi exactly covering a square gt whose polygon is the left half
    rois = np.array([[[0, 0, 0, 15, 15]]], np.float32)
    gt = np.array([[[0, 0, 15, 15, 1]]], np.float32)
    # poly: category 1, 1 segment, 8 coords: rectangle x in [0,8), y in [0,16)
    poly = np.zeros((1, 1, 16), np.float32)
    poly[0, 0, :3] = [1, 1, 8]
    poly[0, 0, 3:11] = [0, 0, 8, 0, 8, 16, 0, 16]
    params = {"num_classes": 2, "batch_images": 1, "img_rois": 1,
              "poly_len": 16, "mask_size": 8, "fg_fraction": 1.0,
              "fg_thresh": 0.5, "bg_thresh_hi": 0.5, "bg_thresh_lo": 0.0,
              "proposal_without_gt": True,
              "_rng_key": jax.random.PRNGKey(0)}
    out, label, tgt, wt, mask = run_op("ProposalMaskTarget", params,
                                       rois, gt, poly)
    assert mask.shape == (1, 2, 8, 8)
    assert label[0] == 1.0
    # roi w=h=15 → scale 8/15; poly x<8 maps to mask x < 8*8/15 ≈ 4.27
    # → columns 0..3 inside, 4..7 outside; full y range
    np.testing.assert_array_equal(mask[0, 1, :, :4], 1.0)
    np.testing.assert_array_equal(mask[0, 1, :, 4:], 0.0)
    # background channel untouched
    np.testing.assert_array_equal(mask[0, 0], -1.0)


def test_proposal_mask_target_bg_rows_minus1():
    import jax
    rois, gt = _pt_inputs()
    poly = np.zeros((2, 4, 20), np.float32)
    poly[:, :, 0] = gt[:, :, 4]  # category
    poly[:, :, 1] = 1
    poly[:, :, 2] = 8
    for b in range(2):
        for g in range(4):
            x1, y1, x2, y2 = gt[b, g, :4]
            poly[b, g, 3:11] = [x1, y1, x2, y1, x2, y2, x1, y2]
    params = {"num_classes": 4, "batch_images": 2, "img_rois": 16,
              "poly_len": 20, "mask_size": 4, "fg_thresh": 0.5,
              "bg_thresh_hi": 0.5, "bg_thresh_lo": 0.0,
              "proposal_without_gt": True,
              "_rng_key": jax.random.PRNGKey(3)}
    out, label, tgt, wt, mask = run_op("ProposalMaskTarget", params,
                                       rois, gt, poly)
    assert mask.shape == (8, 4, 4, 4)  # 2 imgs * 16*0.25 fg slots
    for img in range(2):
        lab = label[img * 16:(img + 1) * 16]
        nfg = int((lab > 0).sum())
        m = mask[img * 4:(img + 1) * 4]
        for j in range(4):
            if j < nfg:
                cls = int(lab[j])
                assert np.all(np.isin(m[j, cls], [0.0, 1.0]))
            else:
                assert np.all(m[j] == -1.0)


def test_post_detection_weighted_nms():
    import jax
    B, N, C = 1, 6, 3
    rois = np.zeros((B * N, 5), np.float32)
    # two clusters of overlapping boxes + identity deltas
    base = [[0, 0, 10, 10], [1, 1, 11, 11], [0.5, 0, 10.5, 10],
            [40, 40, 60, 60], [42, 41, 61, 62], [80, 0, 90, 10]]
    for i, b in enumerate(base):
        rois[i, 1:] = b
    deltas = np.zeros((B, N, 4 * C), np.float32)
    scores = np.zeros((B, N, C), np.float32)
    scores[0, :, 1] = [0.97, 0.96, 0.95, 0.0, 0.0, 0.2]
    scores[0, :, 2] = [0.0, 0.0, 0.0, 0.98, 0.96, 0.3]
    scores[0, :, 0] = 1.0 - scores[0].sum(-1)
    im_info = np.array([[100, 100, 1]], np.float32)
    params = {"thresh": 0.9, "nms_thresh_lo": 0.3, "nms_thresh_hi": 0.5,
              "_is_train": False}
    boxes, out_rois = run_op("PostDetection", params, rois, scores,
                             deltas, im_info)
    assert boxes.shape == (B, N, 6) and out_rois.shape == (B * N, 5)
    kept = boxes[0][np.any(boxes[0] != 0, axis=-1)]
    # the two clusters collapse to one detection each (scores > 0.9
    # after enhancement); the weak lone box (0.2/0.3) is below thresh
    assert kept.shape[0] == 2
    assert kept[0, 4] >= 0.9 and kept[0, 5] in (1.0, 2.0)
    cls2 = kept[kept[:, 5] == 2.0]
    assert len(cls2) == 1 and 39 < cls2[0, 0] < 62
    # rois output mirrors box coords with batch index 0
    nz = out_rois[np.any(out_rois[:, 1:] != 0, axis=-1)]
    np.testing.assert_allclose(nz[:, 1:], kept[:, :4], rtol=1e-5)


def test_post_detection_train_mode_raises():
    with pytest.raises(ValueError):
        run_op("PostDetection", {"_is_train": True},
               np.zeros((2, 5), np.float32), np.zeros((1, 2, 2), np.float32),
               np.zeros((1, 2, 8), np.float32), np.ones((1, 3), np.float32))


def test_proposal_target_ohem_selects_hardest():
    """OHEM: fg/bg picked by classification loss, not randomly.

    The reference DECLARES ohem on ProposalTarget but its branch is
    LOG(FATAL) "OHEM not Implemented." (proposal_target-inl.h:133) — this
    capability exceeds it; oracle is a numpy top-k by -log p.
    """
    import jax
    # 1 image, 8 rois: 4 clear fg (IoU 1 with the gt), 4 clear bg
    gt = np.array([[[10, 10, 40, 40, 2]]], np.float32)
    rois = np.zeros((1, 8, 5), np.float32)
    for i in range(4):
        rois[0, i, 1:] = [10, 10, 40, 40]          # fg (IoU 1.0)
    for i in range(4, 8):
        rois[0, i, 1:] = [60 + i, 60, 80 + i, 80]  # bg (IoU 0)
    # predicted probs: fg rois 0..3 have DESCENDING p[gt class] => roi 3
    # is hardest; bg rois 4..7 have ASCENDING p[background] => roi 4 is
    # hardest background
    C = 3
    score = np.full((1, 8, C), 0.01, np.float32)
    score[0, :4, 2] = [0.9, 0.7, 0.5, 0.1]
    score[0, 4:, 0] = [0.2, 0.4, 0.6, 0.9]
    params = {"num_classes": C, "batch_images": 1, "batch_rois": 4,
              "fg_thresh": 0.5, "bg_thresh_hi": 0.5, "bg_thresh_lo": 0.0,
              "fg_fraction": 0.5, "ohem": True,
              "proposal_without_gt": True,
              "_rng_key": jax.random.PRNGKey(0)}
    out, label, tgt, wt = run_op("ProposalTarget", params, rois, gt,
                                 score)
    # 2 fg slots: hardest fg are rois 3 (p=0.1) and 2 (p=0.5)
    fg_rows = out[label > 0]
    assert fg_rows.shape[0] == 2
    np.testing.assert_allclose(fg_rows[:, 1:], [[10, 10, 40, 40]] * 2)
    # 2 bg slots: hardest bg are rois 4 (p0=0.2) and 5 (p0=0.4)
    bg_rows = out[label == 0]
    got_x1 = sorted(bg_rows[:, 1].tolist())
    assert got_x1 == [64.0, 65.0], got_x1
    # determinism: same inputs, same selection (no RNG in the ranking)
    out2, label2, _, _ = run_op("ProposalTarget", params, rois, gt, score)
    np.testing.assert_array_equal(out, out2)


def test_proposal_target_ohem_needs_scores():
    import jax
    with pytest.raises(mx.base.MXNetError, match="cls_prob"):
        run_op("ProposalTarget",
               {"num_classes": 3, "batch_images": 1, "batch_rois": 4,
                "fg_thresh": 0.5, "bg_thresh_hi": 0.5, "bg_thresh_lo": 0.0,
                "ohem": True, "_rng_key": jax.random.PRNGKey(0)},
               np.zeros((1, 4, 5), np.float32),
               np.zeros((1, 1, 5), np.float32))
