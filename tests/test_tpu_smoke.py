"""Real-TPU smoke lane (reference pattern: tests/python/gpu/
test_operator_gpu.py re-runs the op suite on the accelerator).

Run with:  MXNET_TEST_TPU=1 python -m pytest tests/ -m tpu -q
(Needs sole ownership of the single-client tunnel chip; first compiles take
tens of seconds each.)

Covers the TPU-only behaviors that round-1 proved CPU testing cannot catch:
flash-attention block tuning, the fused Pallas LSTM dispatch, bf16 conv
gradients, engine fencing through the relay, and a short real-training
convergence check.
"""
import time

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd

pytestmark = pytest.mark.tpu


def _tpu_ctx():
    if not mx.context.num_tpus():
        pytest.skip("no TPU visible")
    return mx.tpu()


def test_flash_attention_matches_dense_oracle():
    ctx = _tpu_ctx()
    rng = np.random.RandomState(0)
    B, T, H, D = 2, 512, 4, 64
    q, k, v = (rng.randn(B, T, H, D).astype("f") * 0.1 for _ in range(3))
    for causal in (False, True):
        out = mx.nd.contrib.flash_attention(
            mx.nd.array(q, ctx=ctx), mx.nd.array(k, ctx=ctx),
            mx.nd.array(v, ctx=ctx), causal=causal).asnumpy()
        s = np.einsum("bqhd,bkhd->bhqk", q, k) / np.sqrt(D)
        if causal:
            mask = np.tril(np.ones((T, T), bool))
            s = np.where(mask[None, None], s, -1e30)
        p = np.exp(s - s.max(-1, keepdims=True))
        p /= p.sum(-1, keepdims=True)
        ref = np.einsum("bhqk,bkhd->bqhd", p, v)
        np.testing.assert_allclose(out, ref, rtol=2e-2, atol=2e-3)


def test_fused_lstm_forward_backward():
    ctx = _tpu_ctx()
    rng = np.random.RandomState(1)
    T, B, I, H = 32, 16, 32, 64
    x = mx.nd.array(rng.randn(T, B, I).astype("f") * 0.1, ctx=ctx)
    from mxnet_tpu.ops.nn import rnn_param_size
    psize = rnn_param_size(1, I, H, False, "lstm")
    params = mx.nd.array(rng.randn(psize).astype("f") * 0.1, ctx=ctx)
    state = mx.nd.zeros((1, B, H), ctx=ctx)
    cell = mx.nd.zeros((1, B, H), ctx=ctx)
    x.attach_grad()
    params.attach_grad()
    with autograd.record():
        out = mx.nd.RNN(x, params, state, cell, mode="lstm", state_size=H,
                        num_layers=1)
    out.backward()
    # CPU oracle: identical op on the cpu context (lax.scan path)
    xc = mx.nd.array(x.asnumpy())
    pc = mx.nd.array(params.asnumpy())
    ref = mx.nd.RNN(xc, pc, mx.nd.zeros((1, B, H)), mx.nd.zeros((1, B, H)),
                    mode="lstm", state_size=H, num_layers=1).asnumpy()
    np.testing.assert_allclose(out.asnumpy(), ref, rtol=2e-2, atol=2e-3)
    assert np.isfinite(x.grad.asnumpy()).all()
    assert np.abs(params.grad.asnumpy()).sum() > 0


def test_bf16_conv_gradients():
    ctx = _tpu_ctx()
    rng = np.random.RandomState(2)
    x = mx.nd.array(rng.randn(4, 8, 16, 16).astype("f"),
                    ctx=ctx).astype("bfloat16")
    w = mx.nd.array(rng.randn(16, 8, 3, 3).astype("f") * 0.1,
                    ctx=ctx).astype("bfloat16")
    x.attach_grad()
    w.attach_grad()
    with autograd.record():
        y = mx.nd.Convolution(x, w, kernel=(3, 3), num_filter=16,
                              pad=(1, 1), no_bias=True)
    y.backward()
    gx, gw = x.grad.asnumpy(), w.grad.asnumpy()
    assert gx.dtype == np.dtype("bfloat16") or np.isfinite(
        gx.astype("f")).all()
    assert np.isfinite(gx.astype("f")).all() and np.abs(gx).astype("f").sum() > 0
    assert np.isfinite(gw.astype("f")).all() and np.abs(gw).astype("f").sum() > 0


def test_stem_s2d_rewrite_on_chip_matches_cpu():
    """The space-to-depth stem rewrite engages on TPU (ctx gate) — its
    output must match the plain conv computed on CPU."""
    ctx = _tpu_ctx()
    rng = np.random.RandomState(3)
    x = rng.randn(2, 3, 64, 64).astype("f")
    w = rng.randn(16, 3, 7, 7).astype("f") * 0.1
    out_tpu = mx.nd.Convolution(
        mx.nd.array(x, ctx=ctx), mx.nd.array(w, ctx=ctx), kernel=(7, 7),
        num_filter=16, stride=(2, 2), pad=(3, 3), no_bias=True).asnumpy()
    out_cpu = mx.nd.Convolution(
        mx.nd.array(x), mx.nd.array(w), kernel=(7, 7), num_filter=16,
        stride=(2, 2), pad=(3, 3), no_bias=True).asnumpy()
    # MXU f32 convs run at bf16-mantissa precision by default — tolerance
    # reflects the hardware, not the rewrite (exact equivalence is proven
    # in test_operator.py::test_space_to_depth_conv_rewrite_matches_direct)
    np.testing.assert_allclose(out_tpu, out_cpu, rtol=3e-2, atol=3e-2)


def test_waitall_fences_on_relay():
    """Engine::WaitForAll must actually wait: dispatch ~a second of chained
    device work, then observe waitall blocking for it (block_until_ready
    alone is a fast-path no-op through the relay)."""
    ctx = _tpu_ctx()
    import jax
    import jax.numpy as jnp
    from jax import lax

    n = 4096
    a = mx.nd.random.uniform(shape=(n, n), ctx=ctx).astype("bfloat16")

    @jax.jit
    def burn(x):
        def body(i, acc):
            return jnp.tanh(acc @ x * 1e-3)
        return lax.fori_loop(0, 60, body, x)

    warm = burn(a._data)
    float(np.asarray(warm[0, 0].astype(jnp.float32)))  # compile + settle
    t0 = time.time()
    out = burn(a._data)
    dispatch_t = time.time() - t0
    res = mx.nd.NDArray(out, ctx=ctx)
    t0 = time.time()
    mx.nd.waitall()
    wait_t = time.time() - t0
    t0 = time.time()
    _ = float(np.asarray(out[0, 0].astype(jnp.float32)))
    read_t = time.time() - t0
    # dispatch returns promptly; waitall absorbs the device time; the
    # subsequent read finds the result already complete
    assert dispatch_t < wait_t + read_t + 1.0
    assert wait_t > read_t, (dispatch_t, wait_t, read_t)
    del res


def test_mlp_trains_on_chip():
    ctx = _tpu_ctx()
    rng = np.random.RandomState(4)
    X = rng.randn(512, 32).astype("f")
    w = rng.randn(32, 4).astype("f")
    y = X.dot(w).argmax(1).astype("f")
    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data, num_hidden=64, name="fc1")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.FullyConnected(net, num_hidden=4, name="fc2")
    net = mx.sym.SoftmaxOutput(net, name="softmax")
    it = mx.io.NDArrayIter(X, y, batch_size=128, shuffle=True,
                           label_name="softmax_label")
    mod = mx.mod.Module(net, context=ctx)
    mod.fit(it, num_epoch=10, optimizer="sgd",
            optimizer_params={"learning_rate": 0.2, "momentum": 0.9})
    it.reset()
    acc = dict(mod.score(it, "acc"))["accuracy"]
    assert acc > 0.9, acc


def test_step_scan_trains_on_chip():
    """Round-3 scanned multi-batch train step: K fused steps in ONE
    dispatch on the real chip, loss decreasing."""
    ctx = _tpu_ctx()
    rng = np.random.RandomState(0)
    X = rng.randn(128, 16).astype("f")
    W = rng.randn(16, 4).astype("f")
    y = X.dot(W).argmax(1).astype("f")
    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data, num_hidden=32, name="fc1")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.FullyConnected(net, num_hidden=4, name="fc2")
    net = mx.sym.SoftmaxOutput(net, name="softmax")
    mod = mx.mod.Module(net, context=ctx)
    it = mx.io.NDArrayIter(X, y, batch_size=32, label_name="softmax_label")
    mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label)
    np.random.seed(0)
    mod.init_params(initializer=mx.init.Xavier())
    mod.init_optimizer(optimizer="sgd",
                       optimizer_params={"learning_rate": 0.5})
    batches = list(it)
    out = mod._step_scan(batches)          # 4 steps, one dispatch
    assert out is not False
    first = mod.get_outputs()[0].asnumpy()
    for _ in range(5):
        mod._step_scan(batches)
    it.reset()
    m = mx.metric.Accuracy()
    mod.score(it, m)
    assert np.isfinite(first).all()
    assert m.get()[1] > 0.9, m.get()


def test_sparse_row_update_on_chip():
    """O(nnz) lazy row update executes on the chip: touched rows move,
    untouched rows bit-identical, compiled operand rows == padded nnz."""
    from mxnet_tpu.ndarray import sparse
    from mxnet_tpu import optimizer as opt_mod
    ctx = _tpu_ctx()
    rows = 200_000
    w = mx.nd.ones((rows, 8), ctx=ctx)
    idx = np.array([1, 77, 4096, 199_999])
    g = sparse.row_sparse_array((np.full((4, 8), 2.0, "f"), idx),
                                shape=(rows, 8))
    opt = opt_mod.SGD(learning_rate=0.25, momentum=0.9, rescale_grad=1.0)
    state = opt.create_state(0, w)
    opt_mod._SPARSE_ROW_JIT.clear()
    opt.update(0, w, g, state)
    (kind, _, _, bucket, _), = list(opt_mod._SPARSE_ROW_JIT)
    assert kind == "sgd_mom" and bucket == 4
    out = w.asnumpy()
    np.testing.assert_allclose(out[idx], 0.5)
    np.testing.assert_allclose(out[[0, 5, 100_000]], 1.0)


def test_core_op_consistency_vs_cpu():
    """The reference re-runs the op suite on the accelerator and compares
    against CPU (tests/python/gpu/test_operator_gpu.py:check_consistency).
    Sweep the hot op families fwd+bwd on the chip vs the CPU oracle via
    the shared test_utils.check_consistency harness.

    Tolerances are bf16-grade: XLA's default TPU conv precision routes f32
    convolutions through bf16 MXU passes (the same allowance the
    reference's harness gives fp16)."""
    from mxnet_tpu.test_utils import check_consistency
    ctx = _tpu_ctx()
    rng = np.random.RandomState(0)

    data = mx.sym.Variable("data")
    w = mx.sym.Variable("w")
    cases = [
        ("conv3x3", mx.sym.Convolution(data, kernel=(3, 3), pad=(1, 1),
                                       num_filter=8, name="c"),
         {"data": (2, 3, 12, 12)}, None),
        ("fc", mx.sym.FullyConnected(data, num_hidden=16, name="f"),
         {"data": (4, 10)}, None),
        ("bn", mx.sym.BatchNorm(data, fix_gamma=False, name="b"),
         {"data": (4, 6, 8, 8)}, None),
        ("pool", mx.sym.Pooling(data, kernel=(2, 2), stride=(2, 2),
                                pool_type="max"),
         {"data": (2, 4, 8, 8)}, None),
        # sliced so the all-ones head gradient is non-uniform over the
        # softmax output — the full-softmax VJP of ones is identically 0
        ("softmax", mx.sym.slice_axis(mx.sym.softmax(data, axis=-1),
                                      axis=1, begin=0, end=3),
         {"data": (4, 11)}, None),
        ("dot", mx.sym.dot(data, w), {"data": (8, 8), "w": (8, 8)}, None),
        ("tanh", mx.sym.tanh(data), {"data": (3, 7)}, None),
        ("layernorm", mx.sym.LayerNorm(data, mx.sym.Variable("g"),
                                       mx.sym.Variable("be")),
         {"data": (4, 16), "g": (16,), "be": (16,)}, None),
        ("deconv", mx.sym.Deconvolution(data, kernel=(4, 4), stride=(2, 2),
                                        pad=(1, 1), num_filter=4,
                                        name="d"),
         {"data": (2, 3, 8, 8)}, None),
        ("embed", mx.sym.Embedding(data, w, input_dim=50, output_dim=8),
         {"data": (4, 6), "w": (50, 8)},
         {"data": rng.randint(0, 50, (4, 6)).astype("f")}),
    ]
    for name, sym, shapes, arg_params in cases:
        try:
            check_consistency(
                sym, [dict(ctx=mx.cpu(), **shapes), dict(ctx=ctx, **shapes)],
                tol=5e-2, arg_params=arg_params)
        except AssertionError as e:
            raise AssertionError("%s: %s" % (name, e))


def test_predict_api_on_chip():
    """The predict path's accelerator mapping (c_predict_api dev_type=2 ->
    mx.tpu()): create via the C-boundary helper, forward on the real
    chip, outputs match a CPU predictor (reference c_predict_api.cc maps
    dev_type 2 to GPU the same way)."""
    ctx = _tpu_ctx()
    assert ctx is not None
    rng = np.random.RandomState(0)
    data = mx.sym.var("data")
    net = mx.sym.FullyConnected(data, num_hidden=8, name="fc1")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.FullyConnected(net, num_hidden=3, name="fc2")
    net = mx.sym.SoftmaxOutput(net, name="softmax")
    params = {
        "arg:fc1_weight": mx.nd.array(rng.randn(8, 5).astype(np.float32)),
        "arg:fc1_bias": mx.nd.array(rng.randn(8).astype(np.float32)),
        "arg:fc2_weight": mx.nd.array(rng.randn(3, 8).astype(np.float32)),
        "arg:fc2_bias": mx.nd.array(rng.randn(3).astype(np.float32)),
    }
    import tempfile, os as _os
    with tempfile.NamedTemporaryFile(suffix=".params", delete=False) as f:
        path = f.name
    mx.nd.save(path, params)
    with open(path, "rb") as fh:
        payload = fh.read()
    _os.unlink(path)
    x = rng.randn(4, 5).astype(np.float32)

    from mxnet_tpu.predict import Predictor, _c_create
    tpu_pred = _c_create(net.tojson(), payload, 2, 0, ["data"],
                         [(4, 5)], [])
    assert tpu_pred._ctx.device_type == "tpu"
    tpu_pred.forward(data=x)
    got = tpu_pred.get_output(0)

    with Predictor(net.tojson(), payload, ctx=mx.cpu(),
                   input_shapes={"data": (4, 5)}) as cpu_pred:
        cpu_pred.forward(data=x)
        expect = cpu_pred.get_output(0)
    # bf16-precision MXU matmuls on chip vs f32 CPU: same tolerance as
    # the other cpu-vs-tpu sweeps in this lane
    np.testing.assert_allclose(got, expect, rtol=2e-2, atol=2e-3)


def test_group2ctx_spans_tpu_and_cpu():
    """Round-5 (r4 VERDICT weak #4): a grouped executor whose segments
    straddle the REAL chip and host CPU — exercises actual device_put
    edges between XLA devices, one train step + parity vs ungrouped.

    Reference pattern: example/model-parallel/lstm places layer groups on
    different GPUs; here group 'a' computes on tpu(0) and group 'b' on
    cpu(0), so every cross-group edge is a real host<->device transfer.
    """
    ctx = _tpu_ctx()
    rng = np.random.RandomState(7)
    X = rng.randn(64, 16).astype("f")
    y = (X.sum(axis=1) > 0).astype("f")

    def build():
        data = mx.sym.Variable("data")
        with mx.AttrScope(ctx_group="a"):
            h = mx.sym.FullyConnected(data, num_hidden=32, name="fc1")
            h = mx.sym.Activation(h, act_type="relu")
        with mx.AttrScope(ctx_group="b"):
            out = mx.sym.FullyConnected(h, num_hidden=2, name="fc2")
        return mx.sym.SoftmaxOutput(out, name="softmax")

    def train(g2c, context):
        it = mx.io.NDArrayIter(X, y, batch_size=32,
                               label_name="softmax_label")
        mod = mx.mod.Module(build(), context=context, group2ctxs=g2c)
        np.random.seed(11)
        mod.fit(it, num_epoch=4, optimizer="sgd",
                initializer=mx.init.Xavier(),
                optimizer_params={"learning_rate": 0.3})
        it.reset()
        probs = mod.predict(it).asnumpy()
        it.reset()
        acc = dict(mod.score(it, "acc"))["accuracy"]
        return probs, acc

    grouped, acc_g = train([{"a": ctx, "b": mx.cpu(0)}], ctx)
    plain, acc_p = train(None, ctx)
    assert acc_g > 0.9, acc_g
    # same seed, same data: the split-device run must match the
    # single-device run to float tolerance (transfers are value-exact;
    # fp reassociation across backends allows small drift)
    np.testing.assert_allclose(grouped, plain, rtol=2e-2, atol=2e-2)
    assert abs(acc_g - acc_p) < 0.05


def test_conv1x1_s2_dgrad_kernel_on_chip():
    """The Pallas strided-1x1 dgrad kernel (env-gated off by default —
    measured negative end-to-end, see docs/perf/
    resnet50_train_attribution.md) must stay CORRECT on real hardware:
    Mosaic lowering (i32 index maps, double-buffered VMEM budget) is
    exactly what CPU interpret mode cannot exercise."""
    _tpu_ctx()
    import jax
    import jax.numpy as jnp
    from mxnet_tpu.ops.conv_kernels import conv1x1_s2_dgrad

    rng = np.random.RandomState(0)
    for N, Ho, K, C in ((16, 28, 512, 256), (16, 7, 256, 128)):
        dy = jnp.asarray(rng.randn(N, Ho, Ho, K), jnp.bfloat16)
        w2 = jnp.asarray(rng.randn(K, C), jnp.bfloat16)
        got = np.asarray(conv1x1_s2_dgrad(dy, w2, 2 * Ho, 2 * Ho),
                         np.float32)
        want = np.einsum("nhwk,kc->nhwc", np.asarray(dy, np.float32),
                         np.asarray(w2, np.float32))
        np.testing.assert_allclose(got[:, ::2, ::2, :], want,
                                   rtol=5e-2, atol=5e-1)
        assert (got[:, 1::2] == 0).all() and (got[:, :, 1::2] == 0).all()


def test_ctrain_api_trains_on_chip():
    """The MXT* train C-ABI path mapped onto the REAL chip (dev_type=2 ->
    mx.tpu()): bind, init, step through mxnet_tpu.ctrain — the same
    delegation target src/c_train_api.cc calls — and verify training
    actually descends on TPU."""
    ctx = _tpu_ctx()
    assert ctx is not None
    from mxnet_tpu.ctrain import CTrainer

    rng = np.random.RandomState(2)
    d = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(d, num_hidden=32, name="fc1")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.FullyConnected(net, num_hidden=4, name="fc2")
    net = mx.sym.SoftmaxOutput(net, name="softmax")

    B, D = 64, 16
    centers = rng.randn(4, D) * 3.0
    tr = CTrainer(net.tojson(), 2, 0, ["data"], ["softmax_label"])
    assert tr._ctx.device_type == "tpu"
    tr.bind(["data", "softmax_label"], [(B, D), (B,)])
    tr.init_params("xavier", 3)
    tr.init_optimizer("sgd", {"learning_rate": "0.2", "momentum": "0.9"})

    losses = []
    for step in range(12):
        y = rng.randint(0, 4, B)
        x = (centers[y] + rng.randn(B, D) * 0.5).astype(np.float32)
        tr.step(["data", "softmax_label"],
                [x.tobytes(), y.astype(np.float32).tobytes()])
        probs = np.frombuffer(tr.output_bytes(0),
                              np.float32).reshape(B, 4)
        p = probs[np.arange(B), y]
        losses.append(float(-np.log(np.maximum(p, 1e-12)).mean()))
    assert losses[-1] < losses[0] * 0.2, losses
