"""Gluon multi-device data parallelism (reference gluon trainer.py +
utils.split_and_load): net.initialize(ctx=[...]) replicates parameters over
a 'dp' mesh, split_and_load places the batch sharded over it, and the
classic record/backward/Trainer.step loop runs as ONE SPMD program — the
N-device run must reproduce the 1-device trajectory."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon
from mxnet_tpu.gluon import nn


def _data(n=256, d=16, k=3, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, d).astype(np.float32)
    W = rng.randn(d, k).astype(np.float32)
    y = X.dot(W).argmax(axis=1).astype(np.float32)
    return X, y


def _train(ctxs, epochs=4, hybridize=True):
    X, y = _data()
    net = nn.HybridSequential()
    net.add(nn.Dense(16, activation="relu"), nn.Dense(3))
    np.random.seed(11)
    net.initialize(mx.init.Xavier(), ctx=ctxs)
    if hybridize:
        net.hybridize()
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.5, "momentum": 0.9})
    losses = []
    bs = 32
    for _ in range(epochs):
        ep = 0.0
        for i in range(0, len(X), bs):
            xb, yb = X[i:i + bs], y[i:i + bs]
            xs = gluon.utils.split_and_load(mx.nd.array(xb), ctxs)
            ys = gluon.utils.split_and_load(mx.nd.array(yb), ctxs)
            with autograd.record():
                ls = [loss_fn(net(xi), yi) for xi, yi in zip(xs, ys)]
            for l in ls:
                l.backward()
            trainer.step(bs)
            ep += sum(float(l.mean().asnumpy()) for l in ls) / len(ls)
        losses.append(ep)
    params = [p.data().asnumpy() for _, p in
              sorted(net.collect_params().items())]
    return losses, params


def test_gluon_dp_matches_single_device():
    l1, p1 = _train([mx.cpu(0)])
    l8, p8 = _train([mx.cpu(i) for i in range(8)])
    np.testing.assert_allclose(l8, l1, rtol=1e-3)
    for a, b in zip(p8, p1):
        np.testing.assert_allclose(a, b, rtol=2e-4, atol=2e-5)
    assert l8[-1] < l8[0]  # improving (equivalence is the real assertion)


def test_gluon_dp_eager_mode():
    l8, _ = _train([mx.cpu(i) for i in range(8)], epochs=2, hybridize=False)
    assert np.isfinite(l8).all()


def test_split_and_load_shards_batch():
    ctxs = [mx.cpu(i) for i in range(8)]
    xs = gluon.utils.split_and_load(mx.nd.ones((32, 4)), ctxs)
    assert len(xs) == 1 and xs[0].shape == (32, 4)
    assert len(xs[0]._data.sharding.device_set) == 8


def test_split_and_load_uneven_falls_back():
    ctxs = [mx.cpu(i) for i in range(8)]
    xs = gluon.utils.split_and_load(mx.nd.ones((12, 4)), ctxs,
                                    even_split=False)
    assert len(xs) == 8  # reference-style per-device slices


def test_parameter_list_ctx_and_reset():
    ctxs = [mx.cpu(i) for i in range(8)]
    p = gluon.Parameter("test_weight", shape=(4, 4))
    p.initialize(ctx=ctxs)
    assert p.list_ctx() == ctxs
    assert len(p.data()._data.sharding.device_set) == 8
    p.reset_ctx(mx.cpu(0))
    assert p.list_ctx() == [mx.cpu(0)]
    assert len(p.data()._data.sharding.device_set) == 1


def test_save_load_roundtrip_multi_ctx(tmp_path):
    ctxs = [mx.cpu(i) for i in range(8)]
    net = nn.Dense(3, in_units=4)
    net.initialize(ctx=ctxs)
    f = str(tmp_path / "net.params")
    net.save_params(f)
    net2 = nn.Dense(3, in_units=4)
    net2.load_params(f, ctx=ctxs)
    np.testing.assert_allclose(net2.weight.data().asnumpy(),
                               net.weight.data().asnumpy())
    assert len(net2.weight.data()._data.sharding.device_set) == 8


def test_split_and_load_reference_contract():
    """sharded=False restores the reference contract exactly:
    len(result) == len(ctx_list), slice i on ctx_list[i] (advisor r3)."""
    ctxs = [mx.cpu(i) for i in range(8)]
    x = mx.nd.array(np.arange(32 * 4, dtype=np.float32).reshape(32, 4))
    xs = gluon.utils.split_and_load(x, ctxs, sharded=False)
    assert len(xs) == 8
    for i, (xi, ctx) in enumerate(zip(xs, ctxs)):
        assert xi.shape == (4, 4)
        assert xi.context == ctx
        np.testing.assert_array_equal(xi.asnumpy(),
                                      x.asnumpy()[i * 4:(i + 1) * 4])
    # sharded=True on an unshardable batch is a loud error, not silence
    with pytest.raises(ValueError, match="sharded=True"):
        gluon.utils.split_and_load(mx.nd.ones((12, 4)), ctxs, sharded=True)
