"""Sharded checkpoint save/restore on the virtual 8-device mesh
(parallel/checkpoint.py): per-shard write, reshard-on-restore, NDArray
trees, and round-trip through a Module's SPMD parameters."""
import numpy as np
import pytest
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

import mxnet_tpu as mx
from mxnet_tpu.parallel import save_sharded, load_sharded, abstract_like


@pytest.fixture
def mesh():
    devs = jax.devices()
    if len(devs) < 8:
        pytest.skip("needs 8 devices")
    return Mesh(np.asarray(devs[:8]).reshape(4, 2), ("dp", "tp"))


def test_save_restore_same_sharding(tmp_path, mesh):
    w = jax.device_put(jnp.arange(32.0).reshape(8, 4),
                       NamedSharding(mesh, P(None, "tp")))
    tree = {"w": w, "b": jnp.full((3,), 2.5)}
    path = str(tmp_path / "ck")
    save_sharded(path, tree)
    out = load_sharded(path, abstract_like(tree))
    assert np.allclose(np.asarray(out["w"]), np.arange(32.0).reshape(8, 4))
    assert out["w"].sharding.spec == P(None, "tp")
    assert np.allclose(np.asarray(out["b"]), 2.5)


def test_restore_resharded(tmp_path, mesh):
    """Save sharded on tp, restore sharded on dp — the cross-topology
    resume the single-host .params path cannot express."""
    w = jax.device_put(jnp.arange(64.0).reshape(8, 8),
                       NamedSharding(mesh, P(None, "tp")))
    path = str(tmp_path / "ck")
    save_sharded(path, {"w": w})
    target = abstract_like({"w": w},
                           {"w": NamedSharding(mesh, P("dp", None))})
    out = load_sharded(path, target)
    assert out["w"].sharding.spec == P("dp", None)
    assert np.allclose(np.asarray(out["w"]), np.arange(64.0).reshape(8, 8))


def test_ndarray_tree_roundtrip(tmp_path):
    a = mx.nd.array(np.arange(6, dtype=np.float32).reshape(2, 3))
    path = str(tmp_path / "ck")
    save_sharded(path, {"a": a})
    out = load_sharded(path, abstract_like({"a": a}))
    assert np.allclose(np.asarray(out["a"]), a.asnumpy())


def test_module_spmd_params_roundtrip(tmp_path, mesh):
    """A dp-SPMD Module's parameter dict checkpoints and restores with
    shardings intact; restored values land back via set_params."""
    ctxs = [mx.cpu(i) for i in range(8)]
    data = mx.sym.var("data")
    net = mx.sym.FullyConnected(data, num_hidden=4, name="fc")
    net = mx.sym.SoftmaxOutput(net, name="softmax")
    mod = mx.mod.Module(net, context=ctxs)
    mod.bind(data_shapes=[("data", (16, 6))],
             label_shapes=[("softmax_label", (16,))])
    mod.init_params(initializer=mx.init.Xavier())
    arg_params, aux_params = mod.get_params()
    path = str(tmp_path / "ck")
    tree = {"arg": dict(arg_params), "aux": dict(aux_params)}
    save_sharded(path, tree)
    out = load_sharded(path, abstract_like(tree))
    for name, arr in arg_params.items():
        assert np.allclose(np.asarray(out["arg"][name]), arr.asnumpy())
    mod2 = mx.mod.Module(net, context=ctxs)
    mod2.bind(data_shapes=[("data", (16, 6))],
              label_shapes=[("softmax_label", (16,))])
    mod2.init_params(initializer=mx.init.Zero())
    mod2.set_params({k: mx.nd.array(np.asarray(v))
                     for k, v in out["arg"].items()},
                    {k: mx.nd.array(np.asarray(v))
                     for k, v in out["aux"].items()}, allow_missing=True)
    a2, _ = mod2.get_params()
    for name, arr in arg_params.items():
        assert np.allclose(a2[name].asnumpy(), arr.asnumpy())
