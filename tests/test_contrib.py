"""contrib package tests: text vocab/embedding (reference
tests/python/unittest/test_contrib_text.py strategy), legacy autograd,
DataLoaderIter, onnx gating."""
import os
from collections import Counter

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.contrib import text


def test_count_tokens_from_str():
    source_str = " Life is great! \n life is good . \n"
    counter = text.utils.count_tokens_from_str(source_str, to_lower=True)
    assert counter["life"] == 2 and counter["is"] == 2
    assert counter["great!"] == 1


def test_vocabulary_indexing():
    counter = Counter(["a", "b", "b", "c", "c", "c", "some_word$"])
    v = text.vocab.Vocabulary(counter, most_freq_count=None, min_freq=1,
                              unknown_token="<unk>",
                              reserved_tokens=["<pad>"])
    assert len(v) == 6
    assert v.token_to_idx["<unk>"] == 0
    assert v.token_to_idx["<pad>"] == 1
    # by decreasing frequency
    assert v.idx_to_token[2] == "c"
    assert v.to_indices("c") == 2
    assert v.to_indices(["c", "unknown!"]) == [2, 0]
    assert v.to_tokens([0, 2]) == ["<unk>", "c"]
    with pytest.raises(ValueError):
        v.to_tokens(100)
    # most_freq_count / min_freq thresholds
    v2 = text.vocab.Vocabulary(counter, most_freq_count=2, min_freq=2)
    assert len(v2) == 3  # unk + c + b


def test_custom_embedding_and_lookup(tmp_path):
    path = tmp_path / "emb.txt"
    path.write_text("a 0.1 0.2 0.3\nb 1.0 2.0 3.0\n<unk> 9 9 9\n")
    emb = text.embedding.CustomEmbedding(str(path))
    assert emb.vec_len == 3
    np.testing.assert_allclose(emb.get_vecs_by_tokens("b").asnumpy(),
                               [1, 2, 3])
    # unknown token vector loaded from the file
    np.testing.assert_allclose(emb.get_vecs_by_tokens("zzz").asnumpy(),
                               [9, 9, 9])
    vecs = emb.get_vecs_by_tokens(["a", "b"])
    assert vecs.shape == (2, 3)
    assert "a" in emb and "zzz" not in emb
    emb.update_token_vectors("a", mx.nd.array(np.array([7., 8., 9.], "f")))
    np.testing.assert_allclose(emb.get_vecs_by_tokens("a").asnumpy(),
                               [7, 8, 9])


def test_embedding_with_vocabulary(tmp_path):
    path = tmp_path / "emb.txt"
    path.write_text("a 1 1\nb 2 2\nc 3 3\n")
    counter = Counter(["a", "c", "c", "d"])
    v = text.vocab.Vocabulary(counter)
    emb = text.embedding.CustomEmbedding(str(path), vocabulary=v)
    assert len(emb) == len(v)
    assert emb.idx_to_vec.shape == (len(v), 2)
    # c indexed within vocab; d missing from file -> zeros
    np.testing.assert_allclose(
        emb.get_vecs_by_tokens("c").asnumpy(), [3, 3])
    np.testing.assert_allclose(
        emb.get_vecs_by_tokens("d").asnumpy(), [0, 0])


def test_composite_embedding(tmp_path):
    p1 = tmp_path / "e1.txt"
    p1.write_text("a 1 1\nb 2 2\n")
    p2 = tmp_path / "e2.txt"
    p2.write_text("a 10 11\nc 12 13\n")
    v = text.vocab.Vocabulary(Counter(["a", "b", "c"]))
    comp = text.embedding.CompositeEmbedding(
        v, [text.embedding.CustomEmbedding(str(p1)),
            text.embedding.CustomEmbedding(str(p2))])
    assert comp.vec_len == 4
    np.testing.assert_allclose(
        comp.get_vecs_by_tokens("a").asnumpy(), [1, 1, 10, 11])
    np.testing.assert_allclose(
        comp.get_vecs_by_tokens("b").asnumpy()[:2], [2, 2])


def test_embedding_registry():
    names = text.embedding.get_pretrained_file_names()
    assert "glove" in names and "fasttext" in names
    assert "glove.6B.50d.txt" in \
        text.embedding.get_pretrained_file_names("glove")
    with pytest.raises(KeyError):
        text.embedding.create("not_an_embedding")
    # air-gapped: missing pretrained file raises informative IOError
    with pytest.raises(IOError):
        text.embedding.create("glove",
                              pretrained_file_name="glove.6B.50d.txt",
                              embedding_root="/nonexistent")


def test_contrib_autograd_grad_and_loss():
    from mxnet_tpu.contrib import autograd as cag

    @cag.grad_and_loss
    def f(x):
        return x * x

    x = mx.nd.array(np.array([1., 2., 3.], "f"))
    grads, out = f(x)
    np.testing.assert_allclose(grads[0].asnumpy(), [2, 4, 6])
    np.testing.assert_allclose(out.asnumpy(), [1, 4, 9])

    g = cag.grad(lambda x: mx.nd.sum(x * 3))
    np.testing.assert_allclose(g(x)[0].asnumpy(), 3.0)


def test_contrib_autograd_sections():
    from mxnet_tpu.contrib import autograd as cag
    x = mx.nd.ones((2,))
    x.attach_grad()
    with cag.train_section():
        y = x * 2
    y.backward()
    np.testing.assert_allclose(x.grad.asnumpy(), 2.0)


def test_dataloader_iter():
    from mxnet_tpu import gluon
    from mxnet_tpu.contrib.io import DataLoaderIter
    X = np.random.rand(20, 4).astype("f")
    y = np.arange(20, dtype="f")
    ds = gluon.data.ArrayDataset(X, y)
    loader = gluon.data.DataLoader(ds, batch_size=5)
    it = DataLoaderIter(loader)
    assert it.batch_size == 5
    n = 0
    for batch in it:
        n += 1
        assert batch.data[0].shape == (5, 4)
    assert n == 4


def test_onnx_gated():
    with pytest.raises(ImportError, match="onnx"):
        mx.contrib.onnx.import_model("/tmp/nonexistent.onnx")


def test_onnx_translations_no_onnx_needed():
    """The ONNX node translators are pure Symbol builders — exercise them
    directly (asymmetric pads now insert an explicit Pad node instead of
    raising; reference importer refuses them)."""
    import importlib
    om = importlib.import_module("mxnet_tpu.contrib.onnx.import_model")
    import numpy as np

    class StubProto:
        _params = {}

    x = mx.sym.Variable("x")
    w = mx.sym.Variable("w")
    StubProto._params = {"w": mx.nd.ones((2, 3, 3, 3))}

    # asymmetric pads: explicit Pad + zero conv padding
    conv = om._CONVERT_MAP["Conv"](
        {"kernel_shape": (3, 3), "pads": (1, 0, 0, 1)}, [x, w], StubProto)
    out = conv.eval(x=mx.nd.ones((1, 3, 8, 8)),
                    w=mx.nd.ones((2, 3, 3, 3)))[0]
    assert out.shape == (1, 2, 7, 7)  # (8+1+0-3+1, 8+0+1-3+1)

    # Gather / Slice / Split
    g = om._CONVERT_MAP["Gather"]({"axis": 0}, [x, mx.sym.Variable("idx")],
                                  StubProto)
    got = g.eval(x=mx.nd.array(np.arange(12).reshape(4, 3)),
                 idx=mx.nd.array([2.0, 0.0]))[0]
    np.testing.assert_allclose(got.asnumpy()[0], [6, 7, 8])

    s = om._CONVERT_MAP["Slice"]({"starts": (1,), "ends": (3,),
                                  "axes": (0,)}, [x], StubProto)
    got = s.eval(x=mx.nd.array(np.arange(5, dtype="f")))[0]
    np.testing.assert_allclose(got.asnumpy(), [1, 2])

    outs = om._CONVERT_MAP["Split"]({"axis": 1, "split": (2, 2)},
                                    [x], StubProto)
    assert len(outs) == 2
    got = outs[1].eval(x=mx.nd.array(np.arange(8, dtype="f")
                                     .reshape(2, 4)))[0]
    np.testing.assert_allclose(got.asnumpy(), [[2, 3], [6, 7]])

    # HardSigmoid / Softplus / elementwise unary
    hs = om._CONVERT_MAP["HardSigmoid"]({}, [x], StubProto)
    got = hs.eval(x=mx.nd.array([-10.0, 0.0, 10.0]))[0]
    np.testing.assert_allclose(got.asnumpy(), [0.0, 0.5, 1.0])
    for name, fn in [("Exp", np.exp), ("Sqrt", np.sqrt), ("Abs", np.abs)]:
        sym_ = om._CONVERT_MAP[name]({}, [x], StubProto)
        got = sym_.eval(x=mx.nd.array([1.0, 4.0]))[0]
        np.testing.assert_allclose(got.asnumpy(), fn([1.0, 4.0]),
                                   rtol=1e-6)


def test_onnx_conv_transpose_and_gather_semantics():
    """ConvTranspose pads CROP the output (not pad the input); Gather
    wraps negative indices (review r3 findings)."""
    import importlib
    om = importlib.import_module("mxnet_tpu.contrib.onnx.import_model")
    import numpy as np

    x = mx.sym.Variable("x")
    w = mx.sym.Variable("w")

    class P:
        _params = {"w": mx.nd.ones((3, 2, 3, 3))}

    # symmetric pads: out = stride*(in-1) + k - 2p = 2*3+3-2 = 7
    ct = om._CONVERT_MAP["ConvTranspose"](
        {"kernel_shape": (3, 3), "strides": (2, 2), "pads": (1, 1, 1, 1)},
        [x, w], P)
    out = ct.eval(x=mx.nd.ones((1, 3, 4, 4)), w=mx.nd.ones((3, 2, 3, 3)))[0]
    assert out.shape == (1, 2, 7, 7), out.shape

    # asymmetric pads crop per-edge: full out 9, crop (1,0),(0,1) -> 8x8
    ct = om._CONVERT_MAP["ConvTranspose"](
        {"kernel_shape": (3, 3), "strides": (2, 2), "pads": (1, 0, 0, 1)},
        [x, w], P)
    out = ct.eval(x=mx.nd.ones((1, 3, 4, 4)), w=mx.nd.ones((3, 2, 3, 3)))[0]
    assert out.shape == (1, 2, 8, 8), out.shape

    # Gather with negative index wraps to the end
    g = om._CONVERT_MAP["Gather"]({"axis": 0}, [x, mx.sym.Variable("i")], P)
    got = g.eval(x=mx.nd.array(np.arange(4, dtype="f")),
                 i=mx.nd.array([-1.0]))[0]
    np.testing.assert_allclose(got.asnumpy(), [3.0])


def test_onnx_pooling_pad_semantics():
    """ONNX pooling padding excludes padded cells: MaxPool pads are -inf,
    AveragePool (count_include_pad=0, the default) excludes them from the
    divisor (advisor r3 finding: zero pre-padding silently changed
    numerics)."""
    import importlib
    om = importlib.import_module("mxnet_tpu.contrib.onnx.import_model")
    import numpy as np

    x = mx.sym.Variable("x")

    class P:
        _params = {}

    # MaxPool over an all-negative input with asymmetric pads: a zero-pad
    # implementation would return 0 at the padded border
    mp = om._CONVERT_MAP["MaxPool"](
        {"kernel_shape": (2, 2), "strides": (1, 1), "pads": (1, 0, 0, 1)},
        [x], P)
    out = mp.eval(x=mx.nd.full((1, 1, 4, 4), -2.0))[0].asnumpy()
    assert out.shape == (1, 1, 4, 4), out.shape
    np.testing.assert_allclose(out, -2.0)

    # AveragePool default (count_include_pad=0): ones stay ones at borders
    ap = om._CONVERT_MAP["AveragePool"](
        {"kernel_shape": (3, 3), "strides": (1, 1), "pads": (1, 1, 1, 1)},
        [x], P)
    out = ap.eval(x=mx.nd.ones((1, 1, 4, 4)))[0].asnumpy()
    assert out.shape == (1, 1, 4, 4)
    np.testing.assert_allclose(out, 1.0)

    # count_include_pad=1 dilutes the corner by the padded window size
    ap1 = om._CONVERT_MAP["AveragePool"](
        {"kernel_shape": (2, 2), "pads": (1, 1, 0, 0),
         "count_include_pad": 1}, [x], P)
    out = ap1.eval(x=mx.nd.ones((1, 1, 4, 4)))[0].asnumpy()
    np.testing.assert_allclose(out[0, 0, 0, 0], 0.25)  # 1 real cell / 4
    np.testing.assert_allclose(out[0, 0, 1, 1], 1.0)

    # ceil_mode=1 -> 'full' pooling convention output size
    mpc = om._CONVERT_MAP["MaxPool"](
        {"kernel_shape": (2, 2), "strides": (2, 2), "ceil_mode": 1},
        [x], P)
    out = mpc.eval(x=mx.nd.ones((1, 1, 5, 5)))[0]
    assert out.shape == (1, 1, 3, 3), out.shape


def test_onnx_grouped_conv_transpose_channels():
    """Grouped ConvTranspose: weight is (C, M/group, kH, kW), so the output
    channel count is shape[1]*group (advisor r3 finding)."""
    import importlib
    om = importlib.import_module("mxnet_tpu.contrib.onnx.import_model")

    x = mx.sym.Variable("x")
    w = mx.sym.Variable("w")

    class P:
        _params = {"w": mx.nd.ones((4, 2, 3, 3))}

    ct = om._CONVERT_MAP["ConvTranspose"](
        {"kernel_shape": (3, 3), "group": 2}, [x, w], P)
    out = ct.eval(x=mx.nd.ones((1, 4, 5, 5)), w=mx.nd.ones((4, 2, 3, 3)))[0]
    assert out.shape == (1, 4, 7, 7), out.shape
