"""Step-time anatomy profiler (`mxnet_tpu/stepprof.py`): taxonomy
completeness (shares sum to 1), the overlap estimator on a synthetic
async workload, verdict classification fixtures for every bottleneck
class, prefetch queue telemetry, the Speedometer phase summary, the
report CLI, bench_gate's pre-diagnosed phase deltas, a chrome-trace
round-trip of the phase spans through ``tools/merge_traces.py``, and a
launched 2-process straggler run where ``MXNET_CHAOS heartbeat.delay``
makes one host provably slow.
"""
import io as _io
import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import stepprof, telemetry

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

import launchutil  # noqa: E402


@pytest.fixture
def fresh():
    """Clean registry + a reset process profiler; verbose layer off."""
    telemetry.reset()
    stepprof.reset()
    stepprof.disable()
    yield
    stepprof.disable()
    stepprof.reset()
    telemetry.reset()


# ---------------------------------------------------------------------------
# Taxonomy completeness
# ---------------------------------------------------------------------------

def test_phase_taxonomy_shares_sum_to_one(fresh):
    prof = stepprof.StepProfiler(window=64)
    # a step where every taxonomy phase appears, plus untiled residual
    prof.record_step({"data_wait": 0.010, "h2d": 0.005, "dispatch": 0.020,
                      "device_compute": 0.050, "sync": 0.008,
                      "opt_update": 0.004}, wall=0.100)
    for basis in ("p50", "total"):
        sh = prof.shares(basis=basis)
        assert set(sh) == set(stepprof.PHASES) | {stepprof.PHASE_OTHER}
        assert sum(sh.values()) == pytest.approx(1.0, abs=1e-9)
    # the residual bucket is wall minus the tiled phases
    tot = prof.totals()
    assert tot[stepprof.PHASE_OTHER] == pytest.approx(0.003)
    # an unknown phase name is a programming error, not a new bucket
    with pytest.raises(ValueError, match="unknown phase"):
        prof.record_step({"gpu_stuff": 1.0}, wall=1.0)
    with pytest.raises(ValueError, match="unknown phase"):
        prof.phase("not_a_phase")


def test_step_and_phase_ctx_feed_histograms_and_records(fresh):
    with stepprof.step(batches=3) as sp:
        with stepprof.phase("data_wait"):
            time.sleep(0.002)
        with stepprof.phase("dispatch") as ph:
            time.sleep(0.001)
        sp["note"] = "x"
    assert ph.seconds >= 0.001
    st = stepprof.profiler.step_stats()
    assert st["steps"] == 1 and st["batches"] == 3
    assert st["mean_step_seconds"] >= 0.003
    tot = stepprof.totals()
    assert tot["data_wait"] >= 0.002 and tot["dispatch"] >= 0.001
    # telemetry histograms exist under the step_* naming
    for name in ("step_seconds", "step_data_wait_seconds",
                 "step_dispatch_seconds"):
        h = telemetry.get_metric(name)
        assert h is not None and h.count == 1, name
    # phases outside an open step still feed histograms, not records
    with stepprof.phase("sync"):
        pass
    assert telemetry.get_metric("step_sync_seconds").count == 1
    assert stepprof.profiler.step_stats()["steps"] == 1


# ---------------------------------------------------------------------------
# Overlap estimator (synthetic async workload)
# ---------------------------------------------------------------------------

def test_overlap_estimator_synthetic_async(fresh):
    prof = stepprof.StepProfiler(window=64)
    # sampled-sync steps measure TRUE device time: 100 ms per step
    for _ in range(4):
        prof.record_step({"dispatch": 0.005, "device_compute": 0.100},
                         wall=0.108, synced=True)
    # async steady state: the host blocks 40 ms on the readback while
    # 60 ms of device time hid under data_wait — the estimator must
    # surface those hidden 60 ms
    for _ in range(8):
        prof.record_step({"data_wait": 0.060, "dispatch": 0.010,
                          "device_compute": 0.040}, wall=0.115)
    ov = prof.overlap()
    assert ov["steps"] == 8   # synced steps are the estimate, not the view
    assert ov["device_busy_est"] == pytest.approx(0.100, rel=0.01)
    assert ov["device_visible"] == pytest.approx(0.040, rel=0.01)
    assert ov["overlap_seconds"] == pytest.approx(0.060, rel=0.05)
    assert ov["hidden_fraction"] == pytest.approx(0.60, rel=0.05)


def test_overlap_without_samples_is_none(fresh):
    prof = stepprof.StepProfiler(window=8)
    prof.record_step({"data_wait": 0.01, "device_compute": 0.02},
                     wall=0.04)
    ov = prof.overlap()
    assert ov["device_busy_est"] is None
    assert ov["hidden_fraction"] is None
    assert ov["host_busy"] is not None


def test_note_device_sample_marks_step_and_gauges(fresh):
    with stepprof.step():
        with stepprof.phase("device_compute", synced=True):
            pass
        stepprof.note_device_sample(0.05, batches=5,
                                    flops_per_batch=1e9)
    ov = stepprof.overlap()
    # 0.05 s over 5 batches -> 0.01 s/batch entered the estimator
    assert ov["device_busy_est"] == pytest.approx(0.01)
    g = telemetry.get_metric("step_device_flops_per_second")
    assert g is not None and g.value == pytest.approx(1e9 * 5 / 0.05)


# ---------------------------------------------------------------------------
# Verdict classification fixtures
# ---------------------------------------------------------------------------

def _shares(**kv):
    base = {p: 0.0 for p in stepprof.PHASES + (stepprof.PHASE_OTHER,)}
    base.update(kv)
    return base


@pytest.mark.parametrize("shares,expect", [
    (_shares(data_wait=0.5, h2d=0.2, device_compute=0.3), "input-bound"),
    (_shares(dispatch=0.45, other=0.15, device_compute=0.4),
     "dispatch-bound"),
    (_shares(sync=0.6, device_compute=0.3, data_wait=0.1), "sync-bound"),
    (_shares(device_compute=0.7, opt_update=0.1, dispatch=0.2),
     "compute-bound"),
])
def test_verdict_classes(shares, expect):
    verdict, hint = stepprof.classify(shares)
    assert verdict == expect
    assert hint and "unknown" not in verdict


def test_verdict_unknown_on_empty():
    assert stepprof.classify({})[0] == "unknown"
    assert stepprof.classify(_shares())[0] == "unknown"
    assert stepprof.verdict()[0] in (
        "unknown", "input-bound", "dispatch-bound", "sync-bound",
        "compute-bound")


def test_verdict_hints_refined_by_extras():
    disp = _shares(dispatch=0.8, device_compute=0.2)
    v, hint = stepprof.classify(disp, retraces=7)
    assert v == "dispatch-bound" and "retraces" in hint \
        and "bucket" in hint
    v, hint = stepprof.classify(disp, fused=False)
    assert "not fused" in hint
    comp = _shares(device_compute=0.9, dispatch=0.1)
    v, hint = stepprof.classify(comp, donated=False)
    assert v == "compute-bound" and "donation is OFF" in hint


# ---------------------------------------------------------------------------
# Module.fit wiring: shares from a real (CPU) fit loop
# ---------------------------------------------------------------------------

def _tiny_fit(epochs=2, **fit_kw):
    data = mx.sym.var("data")
    fc = mx.sym.FullyConnected(data, num_hidden=4, name="fc")
    net = mx.sym.SoftmaxOutput(fc, name="softmax")
    x = np.random.RandomState(0).uniform(size=(64, 10)).astype(np.float32)
    y = np.zeros(64, dtype=np.float32)
    it = mx.io.NDArrayIter(x, y, batch_size=16)
    mod = mx.mod.Module(net, context=mx.cpu())
    mod.fit(it, num_epoch=epochs, eval_metric="acc", **fit_kw)
    return mod


def test_fit_records_taxonomy_and_consistent_verdict(fresh):
    _tiny_fit()
    st = stepprof.profiler.step_stats()
    assert st["steps"] == 8 and st["batches"] == 8
    sh = stepprof.shares()
    assert sum(sh.values()) == pytest.approx(1.0, abs=0.05)
    verdict, _ = stepprof.verdict()
    assert verdict != "unknown"
    # the verdict names the dominant phase group
    groups = {v: sum(sh.get(p, 0.0) for p in g)
              for v, g in stepprof.VERDICT_GROUPS.items()}
    assert verdict == max(groups, key=lambda v: groups[v])


def test_fit_sampled_sync_feeds_overlap(fresh):
    stepprof.enable(sync_every=2)
    try:
        _tiny_fit(epochs=1)
    finally:
        stepprof.disable()
    ov = stepprof.overlap()
    assert ov["device_busy_est"] is not None  # samples were taken
    h = telemetry.get_metric("step_device_compute_seconds")
    assert h is not None and h.count >= 4


def test_gluon_trainer_loop_populates_steps(fresh):
    """The gluon path has no fit loop, so `Trainer.step` itself must
    record steps (ImplicitStepper): shares/verdict work, and the step
    wall reaches back over the user's fwd/bwd between calls."""
    from mxnet_tpu import gluon, autograd
    net = gluon.nn.Dense(3)
    net.initialize(ctx=mx.cpu())
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1})
    x = mx.nd.ones((8, 4))
    for _ in range(4):
        with autograd.record():
            out = net(x)
            loss = (out * out).sum()
        loss.backward()
        time.sleep(0.002)   # "user fwd/bwd time" between step() calls
        trainer.step(8)
    st = stepprof.profiler.step_stats()
    assert st["steps"] == 4
    # steps 2..4 stretch back over the 2 ms of user work
    assert st["wall_total_seconds"] >= 3 * 0.002
    sh = stepprof.shares()
    assert sum(sh.values()) == pytest.approx(1.0, abs=1e-6)
    assert sh["opt_update"] > 0
    assert stepprof.verdict()[0] != "unknown"


def test_implicit_stepper_noop_inside_explicit_step(fresh):
    stepper = stepprof.ImplicitStepper()
    with stepprof.step():
        with stepper.bracket():
            with stepprof.phase("opt_update"):
                pass
    assert stepprof.profiler.step_stats()["steps"] == 1  # no double count


def test_implicit_stepper_failed_step_not_recorded(fresh):
    stepper = stepprof.ImplicitStepper()
    with pytest.raises(RuntimeError, match="boom"):
        with stepper.bracket():
            raise RuntimeError("boom")
    # matching an explicit step: an aborted step leaves no record to
    # skew shares / mean_step_seconds / straggler snapshots
    assert stepprof.profiler.step_stats()["steps"] == 0
    with stepper.bracket():
        pass
    assert stepprof.profiler.step_stats()["steps"] == 1


def test_implicit_stepper_carries_prestep_phases(fresh):
    stepper = stepprof.ImplicitStepper()
    stepper.carry_phase("h2d", 0.5)
    with pytest.raises(ValueError):
        stepper.carry_phase("nope", 1.0)
    with stepper.bracket():
        pass
    tot = stepprof.totals()
    assert tot["h2d"] == pytest.approx(0.5)  # reached the step record


# ---------------------------------------------------------------------------
# Prefetch telemetry (ROADMAP item 4 satellite)
# ---------------------------------------------------------------------------

def test_prefetch_queue_depth_and_wait_series(fresh):
    x = np.arange(80, dtype=np.float32).reshape(20, 4)
    base = mx.io.NDArrayIter(x, np.zeros(20, np.float32), batch_size=4)
    it = mx.io.PrefetchingIter(base)
    # give the producer a beat to fill the queue, then read the gauge
    time.sleep(0.1)
    g = telemetry.get_metric("prefetch_queue_depth")
    assert g is not None and 0 <= g.read() <= 2
    n = sum(1 for _ in it)
    assert n == 5
    cons = telemetry.get_metric("prefetch_wait_seconds", side="consumer")
    prod = telemetry.get_metric("prefetch_wait_seconds", side="producer")
    assert cons is not None and cons.count >= 5
    assert prod is not None and prod.count >= 5
    # the gauge holds a weakref: a dropped iterator degrades the scrape
    # to the pushed value instead of keeping the queue alive
    del it, base
    import gc
    gc.collect()
    assert g.read() == 0.0


# ---------------------------------------------------------------------------
# Speedometer phase summary (gated by MXNET_STEPPROF)
# ---------------------------------------------------------------------------

def test_speedometer_phase_suffix_gated(fresh):
    sp = mx.callback.Speedometer(batch_size=16, frequent=4)
    sp._mark()
    with stepprof.step():
        with stepprof.phase("data_wait"):
            time.sleep(0.002)
        with stepprof.phase("device_compute"):
            time.sleep(0.004)
    assert sp._phase_suffix() == ""     # disabled: no suffix
    stepprof.enable()
    try:
        suffix = sp._phase_suffix()
        assert "data" in suffix and "compute" in suffix and "%" in suffix
        sp._mark()
        assert sp._phase_suffix() == ""  # nothing advanced since mark
    finally:
        stepprof.disable()


# ---------------------------------------------------------------------------
# Report: sources, CLI, bench_gate phase deltas
# ---------------------------------------------------------------------------

def test_report_from_bench_json_and_prom(fresh, tmp_path):
    doc = {"metric": "train_phase_breakdown",
           "phases": {"data_wait": 0.55, "h2d": 0.1, "dispatch": 0.1,
                      "device_compute": 0.2, "sync": 0.05},
           "verdict": "input-bound"}
    p = tmp_path / "bench_stepprof.json"
    p.write_text(json.dumps(doc))
    out = _io.StringIO()
    rc = stepprof.report(str(p), out=out)
    text = out.getvalue()
    assert rc == 0
    assert "verdict: input-bound" in text and "PrefetchingIter" in text
    rec = json.loads(text.strip().splitlines()[-1])
    assert rec["metric"] == "stepprof_report"
    assert rec["verdict"] == "input-bound"
    # .prom round trip: feed histograms, snapshot, report from the file
    prof = stepprof.profiler
    for _ in range(3):
        prof.record_step({"sync": 0.08, "device_compute": 0.01,
                          "dispatch": 0.01}, wall=0.11)
    prom = str(tmp_path / "metrics.prom")
    telemetry.write_snapshot(prom)
    out = _io.StringIO()
    assert stepprof.report(prom, out=out) == 0
    assert "verdict: sync-bound" in out.getvalue()


def test_report_cli_subprocess(tmp_path):
    doc = {"phases": {"dispatch": 0.7, "device_compute": 0.2,
                      "other": 0.1}}
    p = tmp_path / "run.json"
    p.write_text(json.dumps(doc))
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
    env.pop("MXNET_TELEMETRY_DIR", None)
    r = subprocess.run(
        [sys.executable, "-m", "mxnet_tpu.stepprof", "report", str(p),
         "--json"],
        capture_output=True, text=True, timeout=launchutil.LAUNCH_TIMEOUT,
        env=env, cwd=str(tmp_path))
    assert r.returncode == 0, r.stdout + r.stderr
    rec = json.loads(r.stdout.strip().splitlines()[-1])
    assert rec["verdict"] == "dispatch-bound"


def test_bench_gate_prints_phase_deltas_on_regression(fresh, tmp_path):
    sys.path.insert(0, os.path.join(REPO, "tools"))
    import bench_gate
    good_phases = {"data_wait": 0.05, "dispatch": 0.1,
                   "device_compute": 0.85}
    bad_phases = {"data_wait": 0.45, "dispatch": 0.1,
                  "device_compute": 0.45}
    (tmp_path / "BENCH_r01.json").write_text(json.dumps({
        "parsed": {"metric": bench_gate.TRAIN_METRIC, "value": 100.0,
                   "phases": good_phases}}))
    run = [{"metric": bench_gate.TRAIN_METRIC, "value": 70.0,
            "phases": bad_phases, "verdict": "input-bound"}]
    out = _io.StringIO()
    rc = bench_gate.gate_records(run, history_dir=str(tmp_path), out=out)
    assert rc == 1
    lines = [json.loads(l) for l in out.getvalue().splitlines()]
    gate = [l for l in lines if l["metric"] == "bench_gate"][0]
    assert gate["status"] == "fail"
    ph = [l for l in lines if l["metric"] == "bench_gate_phases"][0]
    assert ph["delta"]["data_wait"] == pytest.approx(0.40)
    assert "data_wait +40%" in ph["detail"]
    # a pass prints no phase line
    out = _io.StringIO()
    assert bench_gate.gate_records(
        [{"metric": bench_gate.TRAIN_METRIC, "value": 99.0}],
        history_dir=str(tmp_path), out=out) == 0
    assert "bench_gate_phases" not in out.getvalue()


# ---------------------------------------------------------------------------
# Cross-host merge + straggler detection (in-process)
# ---------------------------------------------------------------------------

def _host_snapshot(tmp_path, host, step_seconds, steps=20):
    prof = stepprof.StepProfiler(window=64)
    for _ in range(steps):
        prof.record_step({"dispatch": step_seconds}, wall=step_seconds)
    telemetry.set_host_id(host)
    try:
        path = prof.write_host_snapshot(dir=str(tmp_path), force=True)
    finally:
        telemetry.set_host_id(0)
    assert path and os.path.exists(path)
    return path


def test_straggler_detection_and_unskewed(fresh, tmp_path):
    _host_snapshot(tmp_path, 0, 0.010)
    _host_snapshot(tmp_path, 1, 0.050)
    res = stepprof.detect_stragglers(str(tmp_path))
    assert set(res["hosts"]) == {0, 1}
    assert res["straggler_host"] == 1
    assert res["skew_seconds"] == pytest.approx(0.040, rel=0.01)
    assert telemetry.get_metric("step_skew_seconds").value == \
        pytest.approx(0.040, rel=0.01)
    assert telemetry.get_metric("straggler_host").value == 1
    # unskewed: equal hosts accuse nobody
    for f in os.listdir(tmp_path):
        os.remove(os.path.join(tmp_path, f))
    _host_snapshot(tmp_path, 0, 0.020)
    _host_snapshot(tmp_path, 1, 0.0201)
    res = stepprof.detect_stragglers(str(tmp_path))
    assert res["straggler_host"] == -1
    assert abs(res["skew_seconds"]) < 0.001


def test_merge_keeps_freshest_per_host_and_skips_garbage(fresh, tmp_path):
    _host_snapshot(tmp_path, 0, 0.010)
    (tmp_path / "stepprof_host9_pid1.json").write_text("{torn")
    hosts = stepprof.merge_host_snapshots(str(tmp_path))
    assert set(hosts) == {0}
    assert stepprof.merge_host_snapshots(str(tmp_path / "missing")) == {}


# ---------------------------------------------------------------------------
# Chrome-trace round trip through tools/merge_traces.py
# ---------------------------------------------------------------------------

def test_phase_spans_round_trip_chrome_trace(fresh, tmp_path):
    teldir = str(tmp_path / "telemetry")
    telemetry.configure(teldir, snapshot_interval=0)
    try:
        with stepprof.step():
            with stepprof.phase("data_wait"):
                pass
            with stepprof.phase("dispatch"):
                pass
            with stepprof.phase("device_compute", via="update_metric"):
                pass
    finally:
        telemetry.configure(None)
    out = str(tmp_path / "trace.json")
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "merge_traces.py"),
         teldir, "-o", out],
        capture_output=True, text=True, timeout=60)
    assert r.returncode == 0, r.stderr
    names = [e["name"] for e in json.load(open(out))["traceEvents"]]
    for needle in ("step", "step.data_wait", "step.dispatch",
                   "step.device_compute"):
        assert needle in names, (needle, names)
    # phase slices are complete ("X") events with real durations
    evs = [e for e in json.load(open(out))["traceEvents"]
           if e["name"].startswith("step.")]
    assert all(e["ph"] == "X" and e["dur"] >= 0 for e in evs)


# ---------------------------------------------------------------------------
# Launched acceptance: a chaos-slowed host is named straggler
# ---------------------------------------------------------------------------

STRAGGLER_WORKER = r"""
import os, sys, time
rank, steps = int(sys.argv[1]), int(sys.argv[2])
from mxnet_tpu import stepprof, chaos, telemetry
assert telemetry.host_id() == rank
for i in range(steps):
    with stepprof.step():
        with stepprof.phase("dispatch"):
            time.sleep(0.002)
        extra = chaos.heartbeat_extra_delay()
        if extra:
            time.sleep(extra)   # the injected straggler stall
path = stepprof.write_host_snapshot(force=True)
assert path, "no telemetry dir configured?"
print("WORKER_OK", rank, flush=True)
"""


def _run_straggler_pair(tmp_path, tag, chaos_spec):
    teldir = str(tmp_path / ("telemetry_" + tag))
    os.makedirs(teldir)
    worker = tmp_path / "worker.py"
    worker.write_text(STRAGGLER_WORKER)
    procs = []
    for rank in range(2):
        env = dict(os.environ, JAX_PLATFORMS="cpu",
                   PYTHONPATH=REPO, MXNET_TELEMETRY_DIR=teldir,
                   MXNET_TELEMETRY_HOST=str(rank))
        env.pop("MXNET_CHAOS", None)
        if rank == 1 and chaos_spec:
            env["MXNET_CHAOS"] = chaos_spec
        procs.append(subprocess.Popen(
            [sys.executable, str(worker), str(rank), "20"], env=env,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True))
    for rank, (p, (out, _)) in enumerate(
            zip(procs, launchutil.communicate_all(procs))):
        assert p.returncode == 0, out[-3000:]
        assert "WORKER_OK %d" % rank in out, out[-3000:]
    return stepprof.detect_stragglers(teldir)


@pytest.mark.launched
@pytest.mark.timeout(180)
def test_launched_straggler_named_and_unskewed_clean(fresh, tmp_path):
    """Acceptance (ISSUE 6): a 2-process run where MXNET_CHAOS
    `heartbeat.delay` stalls every step of host 1 reports
    step_skew_seconds > 0 and names host 1 in straggler_host; the same
    pair without chaos reports skew ~= 0 and accuses nobody."""
    skewed = _run_straggler_pair(
        tmp_path, "skewed", "heartbeat.delay@0x100=0.05")
    assert skewed["straggler_host"] == 1, skewed
    assert skewed["skew_seconds"] > 0.02, skewed
    clean = _run_straggler_pair(tmp_path, "clean", None)
    assert clean["straggler_host"] == -1, clean
    assert abs(clean["skew_seconds"]) < 0.01, clean
