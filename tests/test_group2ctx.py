"""group2ctx model parallelism (reference symbol.py:1280 simple_bind
group2ctx + PlaceDevice pass graph_executor.cc:406 + the worked
example/model-parallel/lstm): ops carrying a ctx_group attribute run on
their group's device, parameters live with their group, transfers happen
at group edges, and the math matches the single-device run exactly."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.group_exec import GroupedGraph, groups_in_symbol


def _grouped_mlp():
    """Two FC layers pinned to two groups (the reference LSTM example's
    per-layer `with mx.AttrScope(ctx_group='layer%d')` pattern)."""
    data = mx.sym.Variable("data")
    with mx.AttrScope(ctx_group="dev1"):
        fc1 = mx.sym.FullyConnected(data, num_hidden=16, name="fc1")
        act1 = mx.sym.Activation(fc1, act_type="relu")
    with mx.AttrScope(ctx_group="dev2"):
        fc2 = mx.sym.FullyConnected(act1, num_hidden=3, name="fc2")
    return mx.sym.SoftmaxOutput(fc2, name="softmax")


def _devices():
    import jax
    return jax.devices("cpu")


def test_groups_detected():
    net = _grouped_mlp()
    assert groups_in_symbol(net) == {"dev1", "dev2"}


def test_simple_bind_places_params_per_group():
    net = _grouped_mlp()
    devs = _devices()
    g2c = {"dev1": mx.cpu(1), "dev2": mx.cpu(2)}
    exe = mx.executor.Executor.simple_bind(net, mx.cpu(0), group2ctx=g2c,
                                  data=(8, 10),
                                  softmax_label=(8,))
    assert exe._grouped is not None
    # params live on their group's device
    assert exe.arg_dict["fc1_weight"]._data.device == devs[1]
    assert exe.arg_dict["fc1_bias"]._data.device == devs[1]
    assert exe.arg_dict["fc2_weight"]._data.device == devs[2]
    # data feeds the first grouped segment
    assert exe.arg_dict["data"]._data.device == devs[1]
    # at least two segments on distinct devices
    seg_devs = [s.device for s in exe._grouped.segments]
    assert len(set(seg_devs)) >= 2


def test_grouped_forward_matches_single_device():
    net = _grouped_mlp()
    rng = np.random.RandomState(0)
    vals = {
        "data": rng.randn(8, 10).astype(np.float32),
        "fc1_weight": rng.randn(16, 10).astype(np.float32) * 0.1,
        "fc1_bias": np.zeros(16, np.float32),
        "fc2_weight": rng.randn(3, 16).astype(np.float32) * 0.1,
        "fc2_bias": np.zeros(3, np.float32),
        "softmax_label": rng.randint(0, 3, 8).astype(np.float32),
    }

    def run(group2ctx):
        exe = mx.executor.Executor.simple_bind(
            net, mx.cpu(0), group2ctx=group2ctx,
            data=(8, 10), softmax_label=(8,))
        for k, v in vals.items():
            exe.arg_dict[k][:] = v
        exe.forward(is_train=False)
        return exe.outputs[0].asnumpy()

    ref = run(None)
    got = run({"dev1": mx.cpu(1), "dev2": mx.cpu(2)})
    np.testing.assert_allclose(got, ref, rtol=1e-6, atol=1e-6)


def test_grouped_backward_matches_single_device():
    net = _grouped_mlp()
    rng = np.random.RandomState(1)
    vals = {
        "data": rng.randn(8, 10).astype(np.float32),
        "fc1_weight": rng.randn(16, 10).astype(np.float32) * 0.1,
        "fc1_bias": np.zeros(16, np.float32),
        "fc2_weight": rng.randn(3, 16).astype(np.float32) * 0.1,
        "fc2_bias": np.zeros(3, np.float32),
        "softmax_label": rng.randint(0, 3, 8).astype(np.float32),
    }

    def run(group2ctx):
        exe = mx.executor.Executor.simple_bind(
            net, mx.cpu(0), group2ctx=group2ctx, grad_req="write",
            data=(8, 10), softmax_label=(8,))
        for k, v in vals.items():
            exe.arg_dict[k][:] = v
        exe.forward(is_train=True)
        exe.backward()
        return {k: g.asnumpy() for k, g in exe.grad_dict.items()
                if g is not None}

    ref = run(None)
    got = run({"dev1": mx.cpu(1), "dev2": mx.cpu(2)})
    assert set(got) == set(ref)
    for k in ref:
        np.testing.assert_allclose(got[k], ref[k], rtol=1e-5, atol=1e-6,
                                   err_msg=k)
    # grads live on the group device of their parameter
    devs = _devices()
    exe = mx.executor.Executor.simple_bind(
        net, mx.cpu(0), group2ctx={"dev1": mx.cpu(1), "dev2": mx.cpu(2)},
        grad_req="write", data=(8, 10), softmax_label=(8,))
    for k, v in vals.items():
        exe.arg_dict[k][:] = v
    exe.forward(is_train=True)
    exe.backward()
    assert exe.grad_dict["fc1_weight"]._data.device == devs[1]
    assert exe.grad_dict["fc2_weight"]._data.device == devs[2]


def test_unknown_group_raises():
    net = _grouped_mlp()
    with pytest.raises(mx.MXNetError, match="ctx_group 'dev2'"):
        GroupedGraph(net, mx.cpu(0), {"dev1": mx.cpu(1)})


def test_module_group2ctxs_trains_model_parallel_lstm():
    """The reference model-parallel pattern end-to-end: a stacked LSTM
    with each layer in its own ctx_group (example/model-parallel/lstm's
    group structure), trained through Module(group2ctxs=...) on distinct
    virtual devices — must converge like the ungrouped run."""
    T, B, D, H = 6, 16, 8, 16
    rng = np.random.RandomState(3)
    X = rng.randn(64, T, D).astype(np.float32)
    y = (X.sum(axis=(1, 2)) > 0).astype(np.float32)

    def build():
        data = mx.sym.Variable("data")
        cur = data
        for layer, grp in ((0, "l0"), (1, "l1")):
            with mx.AttrScope(ctx_group=grp):
                cell = mx.rnn.LSTMCell(num_hidden=H,
                                       prefix="lstm%d_" % layer)
                outputs, _ = cell.unroll(T, inputs=cur, layout="NTC",
                                         merge_outputs=True)
                cur = outputs
        with mx.AttrScope(ctx_group="l1"):
            last = mx.sym.slice_axis(cur, axis=1, begin=T - 1, end=T)
            last = mx.sym.reshape(last, shape=(-1, H))
            fc = mx.sym.FullyConnected(last, num_hidden=2, name="out_fc")
        return mx.sym.SoftmaxOutput(fc, name="softmax")

    def train(g2c):
        it = mx.io.NDArrayIter(X, y, batch_size=B,
                               label_name="softmax_label")
        mod = mx.mod.Module(build(), context=mx.cpu(0), group2ctxs=g2c)
        np.random.seed(5)
        mod.fit(it, num_epoch=6, optimizer="sgd",
                initializer=mx.init.Xavier(),
                optimizer_params={"learning_rate": 0.5})
        it.reset()
        m = mx.metric.Accuracy()
        mod.score(it, m)
        return m.get()[1]

    acc_grouped = train({"l0": mx.cpu(1), "l1": mx.cpu(2)})
    assert acc_grouped > 0.9, acc_grouped
    acc_plain = train(None)
    # same trajectory modulo float reassociation across devices
    assert abs(acc_grouped - acc_plain) < 0.1, (acc_grouped, acc_plain)
