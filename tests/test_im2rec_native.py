"""Native im2rec CLI (reference tools/im2rec.cc): build it, pack images,
read the .rec/.idx back through the framework's record IO."""
import os
import subprocess
import sys

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.image.codec import imencode

SRC = os.path.join(os.path.dirname(__file__), os.pardir, "src")


@pytest.mark.skipif(not os.path.exists(os.path.join(SRC, "Makefile")),
                    reason="native sources not present")
def test_native_im2rec_roundtrip(tmp_path):
    build = subprocess.run(["make", "-C", SRC, "tools/im2rec"],
                           capture_output=True, text=True)
    assert build.returncode == 0, build.stderr

    imgs = tmp_path / "imgs"
    imgs.mkdir()
    rng = np.random.RandomState(0)
    with open(tmp_path / "data.lst", "w") as lst:
        for i in range(5):
            img = (rng.rand(20, 24, 3) * 255).astype("u1")
            (imgs / ("i%d.jpg" % i)).write_bytes(imencode(img, quality=95))
            lst.write("%d\t%d\timgs/i%d.jpg\n" % (i, i % 3, i))

    r = subprocess.run(
        [os.path.join(SRC, "tools", "im2rec"), str(tmp_path / "data.lst"),
         str(tmp_path), str(tmp_path / "out"), "--resize", "16"],
        capture_output=True, text=True)
    assert r.returncode == 0, r.stderr
    assert "wrote 5 records (0 errors)" in r.stdout

    rec = mx.recordio.MXIndexedRecordIO(str(tmp_path / "out.idx"),
                                        str(tmp_path / "out.rec"), "r")
    for i in range(5):
        header, img = mx.recordio.unpack_img(rec.read_idx(i))
        assert header.id == i
        assert float(header.label) == i % 3
        assert min(img.shape[:2]) == 16
