"""Build and run the native C++ unit tests (the reference's tests/cpp
suite analog — tests/cpp/{engine,storage,operator} there run under
googletest; src/tests/native_tests.cc is a self-contained CHECK harness
over the libmxtpu C API)."""
import os
import subprocess

import pytest

SRC = os.path.join(os.path.dirname(__file__), os.pardir, "src")


@pytest.mark.skipif(not os.path.exists(os.path.join(SRC, "Makefile")),
                    reason="native sources not present")
def test_native_cpp_suite():
    build = subprocess.run(["make", "-C", SRC, "tests/native_tests"],
                           capture_output=True, text=True)
    assert build.returncode == 0, build.stderr
    run = subprocess.run([os.path.join(SRC, "tests", "native_tests")],
                         capture_output=True, text=True)
    assert run.returncode == 0, run.stdout + run.stderr
    assert "checks passed" in run.stdout
