"""Build and run the native C++ unit tests (the reference's tests/cpp
suite analog — tests/cpp/{engine,storage,operator} there run under
googletest; src/tests/native_tests.cc is a self-contained CHECK harness
over the libmxtpu C API)."""
import os
import subprocess

import pytest

SRC = os.path.join(os.path.dirname(__file__), os.pardir, "src")


@pytest.mark.skipif(not os.path.exists(os.path.join(SRC, "Makefile")),
                    reason="native sources not present")
def test_native_cpp_suite():
    build = subprocess.run(["make", "-C", SRC, "tests/native_tests"],
                           capture_output=True, text=True)
    assert build.returncode == 0, build.stderr
    run = subprocess.run([os.path.join(SRC, "tests", "native_tests")],
                         capture_output=True, text=True)
    assert run.returncode == 0, run.stdout + run.stderr
    assert "checks passed" in run.stdout


def test_ndlist_cross_language_roundtrip(tmp_path):
    """The native NDList reader/writer is byte-compatible with the Python
    .params serializer in BOTH directions (reference c_predict_api
    MXNDListCreate over NDArray::Save files)."""
    import ctypes
    import numpy as np
    import mxnet_tpu as mx
    from mxnet_tpu._native import lib as _lib_fn
    lib = _lib_fn()
    if lib is None:
        import pytest
        pytest.skip("native library not built")

    # Python writes -> C reads
    f = str(tmp_path / "py.params")
    w = np.arange(12, dtype=np.float32).reshape(3, 4) * 0.5
    ids = np.array([3, 1, 4], np.int64)
    mx.nd.save(f, {"arg:w": mx.nd.array(w),
                   "ids": mx.nd.array(ids, dtype=np.int64)})
    h = ctypes.c_void_p()
    count = ctypes.c_size_t()
    assert lib.MXTNDListCreateFromFile(
        f.encode(), ctypes.byref(h), ctypes.byref(count)) == 0
    assert count.value == 2
    name = ctypes.c_char_p()
    data = ctypes.c_void_p()
    shape = ctypes.POINTER(ctypes.c_int64)()
    ndim = ctypes.c_uint32()
    flag = ctypes.c_int()
    got = {}
    for i in range(2):
        assert lib.MXTNDListGet(h, i, ctypes.byref(name),
                                ctypes.byref(data), ctypes.byref(shape),
                                ctypes.byref(ndim),
                                ctypes.byref(flag)) == 0
        shp = tuple(shape[d] for d in range(ndim.value))
        nbytes = int(np.prod(shp)) * (4 if flag.value == 0 else 8)
        raw = ctypes.string_at(data, nbytes)
        got[name.value.decode()] = (shp, flag.value, raw)
    assert got["arg:w"][0] == (3, 4) and got["arg:w"][1] == 0
    np.testing.assert_array_equal(
        np.frombuffer(got["arg:w"][2], np.float32).reshape(3, 4), w)
    assert got["ids"][1] == 6
    np.testing.assert_array_equal(
        np.frombuffer(got["ids"][2], np.int64), ids)
    assert lib.MXTNDListFree(h) == 0

    # C writes -> Python loads
    f2 = str(tmp_path / "c.params")
    names = (ctypes.c_char_p * 1)(b"bias")
    arr = np.array([1.0, -2.5], np.float32)
    datas = (ctypes.c_void_p * 1)(arr.ctypes.data)
    shp_arr = (ctypes.c_int64 * 1)(2)
    shapes = (ctypes.POINTER(ctypes.c_int64) * 1)(shp_arr)
    ndims = (ctypes.c_uint32 * 1)(1)
    flags = (ctypes.c_int * 1)(0)
    assert lib.MXTNDListSave(f2.encode(), 1, names, datas, shapes, ndims,
                             flags) == 0
    loaded = mx.nd.load(f2)
    np.testing.assert_array_equal(loaded["bias"].asnumpy(), arr)


def test_ndlist_rejects_corrupt_files(tmp_path):
    """Crafted corruption must produce clean errors, not out-of-bounds
    reads: huge name length, huge ndim, negative dims (review r3)."""
    import ctypes
    import struct
    import numpy as np
    import mxnet_tpu as mx
    from mxnet_tpu._native import lib as _lib_fn
    lib = _lib_fn()
    if lib is None:
        import pytest
        pytest.skip("native library not built")

    f = str(tmp_path / "ok.params")
    mx.nd.save(f, {"w": mx.nd.array(np.ones((2, 2), np.float32))})
    good = open(f, "rb").read()

    def parse(buf):
        h = ctypes.c_void_p()
        count = ctypes.c_size_t()
        rc = lib.MXTNDListCreate(buf, len(buf), ctypes.byref(h),
                                 ctypes.byref(count))
        if rc == 0:
            lib.MXTNDListFree(h)
        return rc

    assert parse(good) == 0
    # name length field is the last 12..4 bytes region: set to huge
    corrupt = bytearray(good)
    corrupt[-9:-1] = struct.pack("<Q", 2 ** 63)[0:8]
    assert parse(bytes(corrupt)) != 0
    # huge ndim in the record header (offset: 24 list hdr + 4 magic + 4
    # stype)
    corrupt = bytearray(good)
    corrupt[32:36] = struct.pack("<I", 0xFFFFFFF0)
    assert parse(bytes(corrupt)) != 0
    # negative dim
    corrupt = bytearray(good)
    corrupt[36:44] = struct.pack("<q", -2)
    assert parse(bytes(corrupt)) != 0
    # truncated payload
    assert parse(good[:-6]) != 0


def test_ndlist_bf16_roundtrip(tmp_path):
    """bf16 .params (dtype flag 12, this framework's serializer extension)
    must round-trip through the native C API (advisor r3: DTypeSize
    rejected flag 12, so native code couldn't read checkpoints the Python
    side writes for bf16 models)."""
    import ctypes
    import numpy as np
    import mxnet_tpu as mx
    from mxnet_tpu._native import lib as _lib_fn
    lib = _lib_fn()
    if lib is None:
        import pytest
        pytest.skip("native library not built")

    f = str(tmp_path / "bf16.params")
    w = mx.nd.array(np.arange(6, dtype=np.float32).reshape(2, 3),
                    dtype="bfloat16")
    mx.nd.save(f, {"w": w})

    h = ctypes.c_void_p()
    count = ctypes.c_size_t()
    assert lib.MXTNDListCreateFromFile(
        f.encode(), ctypes.byref(h), ctypes.byref(count)) == 0
    assert count.value == 1
    name = ctypes.c_char_p()
    data = ctypes.c_void_p()
    shape = ctypes.POINTER(ctypes.c_int64)()
    ndim = ctypes.c_uint32()
    flag = ctypes.c_int()
    assert lib.MXTNDListGet(h, 0, ctypes.byref(name), ctypes.byref(data),
                            ctypes.byref(shape), ctypes.byref(ndim),
                            ctypes.byref(flag)) == 0
    assert flag.value == 12
    raw = ctypes.string_at(data, 2 * 3 * 2)
    assert lib.MXTNDListFree(h) == 0

    # C writes the same bf16 payload back; Python must load it as bf16
    f2 = str(tmp_path / "c_bf16.params")
    names = (ctypes.c_char_p * 1)(b"w")
    buf = ctypes.create_string_buffer(raw, len(raw))
    datas = (ctypes.c_void_p * 1)(ctypes.addressof(buf))
    shp_arr = (ctypes.c_int64 * 2)(2, 3)
    shapes = (ctypes.POINTER(ctypes.c_int64) * 1)(shp_arr)
    ndims = (ctypes.c_uint32 * 1)(2)
    flags = (ctypes.c_int * 1)(12)
    assert lib.MXTNDListSave(f2.encode(), 1, names, datas, shapes, ndims,
                             flags) == 0
    loaded = mx.nd.load(f2)["w"]
    assert str(loaded.dtype) == "bfloat16"
    np.testing.assert_array_equal(loaded.asnumpy().astype(np.float32),
                                  np.arange(6, dtype=np.float32).reshape(2, 3))


def test_c_predict_api_end_to_end(tmp_path):
    """Reference-style C deployment: export a trained symbol+params from
    Python, run the compiled MXPred* client (src/tests/predict_demo.c)
    against them, and check its outputs equal the Python Predictor's
    (reference include/mxnet/c_predict_api.h flow)."""
    import struct
    import sys
    import numpy as np
    import mxnet_tpu as mx

    build = subprocess.run(["make", "-C", SRC, "tests/predict_demo"],
                           capture_output=True, text=True)
    assert build.returncode == 0, build.stderr

    # tiny model: 2-layer MLP, deterministic params
    rng = np.random.RandomState(0)
    data = mx.sym.var("data")
    net = mx.sym.FullyConnected(data, num_hidden=8, name="fc1")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.FullyConnected(net, num_hidden=3, name="fc2")
    net = mx.sym.SoftmaxOutput(net, name="softmax")
    params = {
        "arg:fc1_weight": mx.nd.array(rng.randn(8, 5).astype(np.float32)),
        "arg:fc1_bias": mx.nd.array(rng.randn(8).astype(np.float32)),
        "arg:fc2_weight": mx.nd.array(rng.randn(3, 8).astype(np.float32)),
        "arg:fc2_bias": mx.nd.array(rng.randn(3).astype(np.float32)),
    }
    sym_path = str(tmp_path / "model-symbol.json")
    param_path = str(tmp_path / "model.params")
    net.save(sym_path)
    mx.nd.save(param_path, params)

    x = rng.randn(4, 5).astype(np.float32)

    from mxnet_tpu.predict import Predictor
    with Predictor(open(sym_path).read(), param_path,
                   input_shapes={"data": (4, 5)}) as pred:
        pred.forward(data=x)
        expect = pred.get_output(0)

    env = dict(os.environ)
    env["PYTHONPATH"] = os.pathsep.join(
        [os.path.join(SRC, os.pardir)] + sys.path)
    env.setdefault("JAX_PLATFORMS", "cpu")
    env["PALLAS_AXON_POOL_IPS"] = ""
    run = subprocess.run(
        [os.path.join(SRC, "tests", "predict_demo"), sym_path, param_path,
         "data", "4", "5"],
        input=x.tobytes(), capture_output=True, env=env, timeout=420)
    assert run.returncode == 0, run.stderr.decode()[-2000:]
    got = np.array([[float(v) for v in line.split()]
                    for line in run.stdout.decode().strip().splitlines()])
    assert got.shape == expect.shape
    assert np.allclose(got, expect, rtol=1e-4, atol=1e-5), (got, expect)

    # ADVICE r4: a weight name must NOT be settable through set_input —
    # the reference c_predict_api rejects keys that aren't declared
    # inputs (a typo would otherwise silently overwrite the weight)
    import pytest
    with Predictor(open(sym_path).read(), param_path,
                   input_shapes={"data": (4, 5)}) as pred:
        with pytest.raises(mx.base.MXNetError, match="no input named"):
            pred.set_input("fc1_weight", np.zeros((8, 5), np.float32))

    # bad CLI arguments must error out, not crash (ADVICE r4)
    bad = subprocess.run(
        [os.path.join(SRC, "tests", "predict_demo"), sym_path, param_path,
         "data", "0", "xyz"],
        input=b"", capture_output=True, env=env, timeout=60)
    assert bad.returncode == 2
    assert b"bad batch/dim" in bad.stderr
