"""Storage manager + resource manager + predict API tests.

Mirrors the reference's tests/cpp/storage/storage_test.cc (alloc/free/pool
reuse), the resource attachment semantics of src/resource.cc, and the
predict-API usage pattern of example/image-classification/predict-cpp.
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import resource, storage


class TestStorage:
    def test_alloc_free_roundtrip(self):
        h = storage.alloc(1000, mx.cpu())
        assert h.size == 1000
        assert h.dptr.nbytes == 1000
        h.dptr[:] = 7
        storage.free(h)
        assert h.dptr is None

    def test_pool_reuse(self):
        storage.release_all()
        before = storage.pool_stats()
        h1 = storage.alloc(5000, mx.cpu())
        storage.free(h1)
        h2 = storage.alloc(5000, mx.cpu())  # same size class -> pool hit
        after = storage.pool_stats()
        assert after["pool_hits"] == before["pool_hits"] + 1
        storage.free(h2)

    def test_size_classes_round_pow2(self):
        h = storage.alloc(5000, mx.cpu())
        assert h._block.nbytes == 8192
        storage.free(h)
        tiny = storage.alloc(3, mx.cpu())
        assert tiny._block.nbytes == 4096  # 4KB floor
        storage.free(tiny)

    def test_release_all_empties_pool(self):
        h = storage.alloc(4096, mx.cpu())
        storage.free(h)
        storage.release_all()
        assert storage.pool_stats()["cached_blocks"] == 0

    def test_double_free_is_noop(self):
        h = storage.alloc(64, mx.cpu())
        storage.free(h)
        storage.free(h)  # no raise

    def test_direct_free_bypasses_pool(self):
        storage.release_all()
        h = storage.alloc(4096, mx.cpu())
        storage.direct_free(h)
        assert storage.pool_stats()["cached_blocks"] == 0

    def test_device_alloc_rejected(self):
        with pytest.raises(mx.MXNetError):
            storage.alloc(10, mx.tpu(0))

    def test_device_memory_info_host_is_zero(self):
        assert storage.device_memory_info(mx.cpu()) == (0, 0)


class TestResource:
    def test_temp_space_grows_and_reuses(self):
        r = resource.request(resource.ResourceRequest.kTempSpace, mx.cpu())
        a = r.get_space((4, 5))
        assert a.shape == (4, 5) and a.dtype == np.float32
        b = r.get_space((2, 2))     # smaller: same backing block
        assert b.shape == (2, 2)
        c = r.get_space((100, 100))  # bigger: regrow
        assert c.shape == (100, 100)

    def test_rng_streams_independent(self):
        r1 = resource.request(resource.ResourceRequest.kRandom, mx.cpu())
        r2 = resource.request(resource.ResourceRequest.kRandom, mx.cpu())
        k1, k2 = r1.next_key(), r2.next_key()
        # distinct resources (round-robin pool of 2) give distinct keys
        assert not np.array_equal(np.asarray(k1), np.asarray(k2))

    def test_parallel_random_vector(self):
        r = resource.request(resource.ResourceRequest.kParallelRandom,
                             mx.cpu())
        ks = r.parallel_keys(4)
        assert len(ks) == 4

    def test_type_mismatch_raises(self):
        r = resource.request(resource.ResourceRequest.kTempSpace, mx.cpu())
        with pytest.raises(mx.MXNetError):
            r.next_key()
        r2 = resource.request(resource.ResourceRequest.kRandom, mx.cpu())
        with pytest.raises(mx.MXNetError):
            r2.get_space((2,))

    def test_seed_makes_stream_reproducible(self):
        r = resource.request(resource.ResourceRequest.kRandom, mx.cpu())
        r.seed(42)
        a = np.asarray(r.next_key())
        r.seed(42)
        b = np.asarray(r.next_key())
        assert np.array_equal(a, b)


class TestPredictor:
    def _mlp(self):
        data = mx.sym.var("data")
        w1 = mx.sym.var("fc1_weight")
        b1 = mx.sym.var("fc1_bias")
        h = mx.sym.FullyConnected(data, weight=w1, bias=b1, num_hidden=8,
                                  name="fc1")
        act = mx.sym.Activation(h, act_type="relu", name="relu1")
        out = mx.sym.FullyConnected(act, num_hidden=3, name="fc2")
        return mx.sym.SoftmaxOutput(out, name="softmax")

    def _params_bytes(self, sym, tmp_path):
        rng = np.random.RandomState(0)
        shapes, _, _ = sym.infer_shape(data=(2, 10))
        args = sym.list_arguments()
        params = {}
        for name, shp in zip(args, shapes):
            if name in ("data", "softmax_label"):
                continue
            params["arg:" + name] = mx.nd.array(
                rng.uniform(-1, 1, shp).astype(np.float32))
        f = str(tmp_path / "m.params")
        mx.nd.save(f, params)
        return open(f, "rb").read(), params

    def test_create_forward_get_output(self, tmp_path):
        sym = self._mlp()
        blob, params = self._params_bytes(sym, tmp_path)
        pred = mx.Predictor(sym.tojson(), blob, mx.cpu(),
                            input_shapes={"data": (2, 10)})
        x = np.random.RandomState(1).rand(2, 10).astype(np.float32)
        pred.forward(data=x)
        out = pred.get_output(0)
        assert out.shape == (2, 3)
        np.testing.assert_allclose(out.sum(axis=1), np.ones(2), rtol=1e-5)

    def test_matches_executor(self, tmp_path):
        sym = self._mlp()
        blob, params = self._params_bytes(sym, tmp_path)
        pred = mx.Predictor(sym.tojson(), blob, mx.cpu(),
                            input_shapes={"data": (4, 10)})
        x = np.random.RandomState(2).rand(4, 10).astype(np.float32)
        pred.forward(data=x)
        got = pred.get_output(0)

        ex = sym.simple_bind(mx.cpu(), grad_req="null", data=(4, 10))
        for k, v in params.items():
            ex.arg_dict[k.split(":", 1)[1]][:] = v
        ex.arg_dict["data"][:] = x
        want = ex.forward(is_train=False)[0].asnumpy()
        np.testing.assert_allclose(got, want, rtol=1e-5)

    def test_reshape_shares_params(self, tmp_path):
        sym = self._mlp()
        blob, _ = self._params_bytes(sym, tmp_path)
        pred = mx.Predictor(sym.tojson(), blob, mx.cpu(),
                            input_shapes={"data": (2, 10)})
        pred.reshape({"data": (6, 10)})
        x = np.zeros((6, 10), np.float32)
        pred.forward(data=x)
        assert pred.get_output(0).shape == (6, 3)

    def test_partial_out(self, tmp_path):
        sym = self._mlp()
        blob, _ = self._params_bytes(sym, tmp_path)
        pred = mx.Predictor(sym.tojson(), blob, mx.cpu(),
                            input_shapes={"data": (2, 10)},
                            output_names=["relu1"])
        pred.forward(data=np.ones((2, 10), np.float32))
        assert pred.get_output(0).shape == (2, 8)

    def test_bad_input_name_and_shape(self, tmp_path):
        sym = self._mlp()
        blob, _ = self._params_bytes(sym, tmp_path)
        pred = mx.Predictor(sym.tojson(), blob, mx.cpu(),
                            input_shapes={"data": (2, 10)})
        with pytest.raises(mx.MXNetError):
            pred.set_input("nope", np.zeros((2, 10), np.float32))
        with pytest.raises(mx.MXNetError):
            pred.set_input("data", np.zeros((3, 10), np.float32))

    def test_load_frombuffer_roundtrip(self, tmp_path):
        a = mx.nd.array(np.arange(6, dtype=np.float32).reshape(2, 3))
        f = str(tmp_path / "x.params")
        mx.nd.save(f, {"w": a})
        loaded = mx.nd.load_frombuffer(open(f, "rb").read())
        np.testing.assert_array_equal(loaded["w"].asnumpy(), a.asnumpy())
