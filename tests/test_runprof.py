"""Run anatomy (`mxnet_tpu/runprof.py`): the goodput/badput ledger
(taxonomy tiles the run wall), training-health sentinels (non-finite
values, step-time spikes, loss plateau/divergence) with flight-recorder
dumps, lost-work accounting across restarts, the report CLI with
per-host goodput skew, the bench_gate goodput gate with its state-
seconds delta line, zero-compile instrumentation proof, and a launched
chaos kill-and-resume run whose ledger shows measured recovery +
checkpoint_restore + lost-work badput.
"""
import io as _io
import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import runprof, stepprof, telemetry, xla_stats

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

import launchutil  # noqa: E402


@pytest.fixture
def fresh():
    """Clean registry + reset run ledger and step profiler."""
    telemetry.reset()
    stepprof.reset()
    runprof.reset()
    yield
    runprof.reset()
    stepprof.reset()
    telemetry.reset()


# ---------------------------------------------------------------------------
# Ledger: taxonomy tiles the run wall
# ---------------------------------------------------------------------------

def test_taxonomy_tiles_run_wall(fresh):
    led = runprof.RunLedger(window=32)
    time.sleep(0.03)                       # -> init
    led.note_state("compile", 0.0)         # zero-cost note is fine
    for _ in range(6):
        t0 = time.perf_counter()
        time.sleep(0.008)
        led.note_step({"data_wait": 0.002},
                      time.perf_counter() - t0)
    time.sleep(0.02)                       # -> idle
    snap = led.snapshot()
    assert set(snap["states"]) == set(runprof.RUN_STATES)
    total = sum(snap["states"].values())
    wall = snap["run_wall_seconds"]
    assert total == pytest.approx(wall, rel=0.10)
    assert snap["states"]["init"] >= 0.02
    assert snap["states"]["idle"] >= 0.01
    assert snap["states"]["train_productive"] > 0
    assert snap["states"]["input_stall"] > 0
    assert 0 < snap["goodput_fraction"] < 1


def test_first_step_compile_does_not_deflate_init(fresh):
    """Compile paid INSIDE the first train step happens after the
    step's front edge: it must not be subtracted from the derived init
    residual (a minutes-long first compile would otherwise misfile the
    whole startup period as idle and flip the verdict)."""
    led = runprof.RunLedger(window=32)
    time.sleep(0.05)                  # true init
    t0 = time.perf_counter()
    time.sleep(0.03)                  # "compile inside the first step"
    dur = time.perf_counter() - t0
    led.note_state("compile", dur)
    led.note_step({}, dur)            # the step wall covers the compile
    snap = led.snapshot()
    assert snap["states"]["init"] >= 0.04
    assert snap["states"]["idle"] <= 0.02


def test_explicit_state_validation(fresh):
    led = runprof.RunLedger()
    with pytest.raises(ValueError, match="derived"):
        led.note_state("idle", 1.0)
    with pytest.raises(ValueError, match="taxonomy"):
        led.note_state("bogus", 1.0)


def test_state_counters_and_goodput_gauge(fresh):
    runprof.note_state("checkpoint_save", 0.001)
    c = telemetry.get_metric("run_state_seconds", state="checkpoint_save")
    assert c is not None and c.value == pytest.approx(0.001)
    time.sleep(0.02)   # un-tiled wall -> derived init grows
    snap = runprof.snapshot()
    g = telemetry.get_metric("run_goodput_fraction")
    assert g is not None
    assert g.read() == pytest.approx(snap["goodput_fraction"], abs=0.05)
    # derived counters published monotonically by snapshot()
    init_c = telemetry.get_metric("run_state_seconds", state="init")
    assert init_c is not None and init_c.value > 0
    v1 = init_c.value
    time.sleep(0.01)
    runprof.snapshot()
    assert init_c.value > v1


def test_run_state_spans_land_in_event_log(fresh, tmp_path):
    telemetry.configure(str(tmp_path))
    try:
        runprof.note_state("checkpoint_save", 0.05, step=3)
        path = os.path.join(
            str(tmp_path),
            "events_host%d_pid%d.jsonl" % (telemetry.host_id(),
                                           os.getpid()))
        events = telemetry.read_events(path)
    finally:
        telemetry.configure(None)
    spans = [e for e in events if e.get("name") == "run.checkpoint_save"]
    assert spans and spans[0]["ph"] == "X"
    assert spans[0]["dur"] == pytest.approx(0.05)
    assert spans[0]["args"]["step"] == 3


def test_disabled_is_noop(fresh, monkeypatch):
    monkeypatch.setenv("MXNET_RUNPROF", "0")
    runprof.note_state("compile", 1.0)
    runprof.note_step({}, 1.0)
    runprof.observe_metric("loss", float("nan"))
    assert runprof.state_seconds("compile") == 0.0
    assert not runprof.should_check()
    assert telemetry.get_metric("run_anomalies_total",
                                kind="nonfinite_loss") is None


# ---------------------------------------------------------------------------
# Sentinels
# ---------------------------------------------------------------------------

def test_nonfinite_loss_sentinel_dumps_flight_recorder(fresh, tmp_path):
    telemetry.configure(str(tmp_path))
    try:
        runprof.observe_metric("cross-entropy-loss", float("nan"))
    finally:
        telemetry.configure(None)
    c = telemetry.get_metric("run_anomalies_total", kind="nonfinite_loss")
    assert c is not None and c.value == 1
    dump = os.path.join(str(tmp_path),
                        "flightrecorder-host%d.json" % telemetry.host_id())
    assert os.path.exists(dump)
    doc = json.load(open(dump))
    assert doc["reason"] == "runprof.nonfinite_loss"
    snap = runprof.snapshot()
    assert snap["anomaly_counts"] == {"nonfinite_loss": 1}
    assert snap["anomalies"][-1]["kind"] == "nonfinite_loss"


def test_nonfinite_metric_vs_loss_kinds(fresh):
    runprof.observe_metric("accuracy", float("inf"))
    runprof.observe_metric("perplexity", float("nan"))
    assert telemetry.get_metric("run_anomalies_total",
                                kind="nonfinite_metric").value == 1
    assert telemetry.get_metric("run_anomalies_total",
                                kind="nonfinite_loss").value == 1


def test_halt_env_raises_after_counting(fresh, monkeypatch):
    monkeypatch.setenv("MXNET_RUNPROF_HALT", "1")
    with pytest.raises(runprof.RunHealthError, match="nonfinite_loss"):
        runprof.observe_metric("loss", float("nan"))
    c = telemetry.get_metric("run_anomalies_total", kind="nonfinite_loss")
    assert c is not None and c.value == 1   # counted before the halt


def test_halt_inside_step_fn_propagates_not_recovers(fresh, monkeypatch):
    """A sentinel halt raised INSIDE an elastic step_fn is a verdict,
    not a worker failure: it must escape the recover/exit machinery
    instead of burning the restart budget re-tripping itself."""
    monkeypatch.setenv("MXNET_RUNPROF_HALT", "1")
    import jax.numpy as jnp
    from mxnet_tpu.parallel import elastic

    def step_fn(state, step):
        if step == 1:
            runprof.note_anomaly("test_halt", dump=False)
        return state

    t = elastic.ElasticTrainer(step_fn, {"w": jnp.zeros(2)},
                               dead_node_timeout=None,
                               on_failure="recover")
    with pytest.raises(runprof.RunHealthError):
        t.run(3)
    assert t.restarts_used == 0   # no recovery cycle was entered


def test_step_time_spike_sentinel(fresh):
    led = runprof.RunLedger(window=32)
    for _ in range(10):
        led.note_step({}, 0.01)
    led.note_step({}, 0.5)   # > 4x the 0.01 median
    snap = led.snapshot()
    assert snap["anomaly_counts"].get("step_time_spike") == 1
    # steady steps never accuse anyone
    led2 = runprof.RunLedger(window=32)
    for _ in range(20):
        led2.note_step({}, 0.01)
    assert "step_time_spike" not in led2.snapshot()["anomaly_counts"]


def test_loss_divergence_sentinel(fresh):
    led = runprof.RunLedger(window=16)
    for v in [1.0, 0.8, 0.6, 0.5, 0.5, 0.5, 0.5, 0.5]:
        led.observe_metric("loss", v)
    for v in [1.2, 1.5, 2.0, 2.5, 3.0, 3.5, 4.0, 4.5]:
        led.observe_metric("loss", v)
    assert led.snapshot()["anomaly_counts"].get("loss_divergence") == 1


def test_loss_windows_are_per_metric(fresh):
    """Two healthy loss-like metrics at different scales must not read
    their interleaving as a divergence."""
    led = runprof.RunLedger(window=16)
    for i in range(16):
        led.observe_metric("nll-loss", 2.3 - 0.01 * i)
        led.observe_metric("perplexity", 10.0 - 0.05 * i)
    assert led.snapshot()["anomaly_counts"] == {}


def test_loss_plateau_sentinel(fresh):
    led = runprof.RunLedger(window=16)
    for _ in range(16):
        led.observe_metric("loss", 0.7)
    assert led.snapshot()["anomaly_counts"].get("loss_plateau") == 1
    # a healthily-declining loss trips neither heuristic
    led2 = runprof.RunLedger(window=16)
    for i in range(16):
        led2.observe_metric("loss", 1.0 - 0.05 * i)
    assert led2.snapshot()["anomaly_counts"] == {}


def test_clip_global_norm_counts_nonfinite(fresh):
    from mxnet_tpu.gluon.utils import clip_global_norm
    a = mx.nd.array(np.array([np.inf, 1.0], dtype=np.float32))
    with pytest.warns(UserWarning, match="nan or inf"):
        clip_global_norm([a], 1.0)
    assert telemetry.get_metric("grad_nonfinite_total").value == 1
    assert telemetry.get_metric("run_anomalies_total",
                                kind="nonfinite_grad_norm").value == 1
    # a finite norm counts nothing
    b = mx.nd.array(np.ones(4, dtype=np.float32))
    clip_global_norm([b], 1.0)
    assert telemetry.get_metric("grad_nonfinite_total").value == 1


def test_monitor_nan_count_stat_and_routing(fresh):
    from mxnet_tpu import monitor as monitor_mod
    bad = mx.nd.array(np.array([np.nan, 1.0, np.inf], dtype=np.float32))
    assert float(monitor_mod.nan_count(bad).asscalar()) == 2.0
    ok = mx.nd.array(np.ones(3, dtype=np.float32))
    assert float(monitor_mod.nan_count(ok).asscalar()) == 0.0
    # a Monitor using nan_count routes nonzero counts into the sentinel
    m = monitor_mod.Monitor(1, stat_func=monitor_mod.nan_count)
    m.activated = True
    m.queue = [(0, "fc_weight", monitor_mod.nan_count(bad))]
    res = m.toc()
    assert len(res) == 1
    assert telemetry.get_metric("run_anomalies_total",
                                kind="nonfinite_tensor").value == 1
    # the default value stat routes a non-finite result the same way
    m2 = monitor_mod.Monitor(1)
    m2.activated = True
    m2.queue = [(0, "fc_weight", m2.stat_func(bad))]
    m2.toc()
    assert telemetry.get_metric("run_anomalies_total",
                                kind="nonfinite_tensor").value == 2


def test_fit_loop_sampled_health_check(fresh, monkeypatch):
    monkeypatch.setenv("MXNET_RUNPROF_CHECK_EVERY", "2")
    data = mx.sym.var("data")
    fc = mx.sym.FullyConnected(data, num_hidden=4, name="fc")
    net = mx.sym.SoftmaxOutput(fc, name="softmax")
    x = np.random.RandomState(0).uniform(size=(64, 10)).astype(np.float32)
    y = np.zeros(64, dtype=np.float32)
    it = mx.io.NDArrayIter(x, y, batch_size=16)
    mod = mx.mod.Module(net, context=mx.cpu())
    mod.fit(it, num_epoch=2, eval_metric="acc")
    snap = runprof.snapshot()
    # the fit trained: productive seconds recorded, goodput sane, and a
    # healthy accuracy metric tripped nothing
    assert snap["states"]["train_productive"] > 0
    assert 0 < snap["goodput_fraction"] <= 1
    assert snap["anomaly_counts"] == {}
    assert snap["steps"] == 8


# ---------------------------------------------------------------------------
# Compile / checkpoint / recovery states + zero-compile instrumentation
# ---------------------------------------------------------------------------

def test_compile_feeds_ledger_and_instrumentation_is_free(fresh):
    import jax.numpy as jnp
    from mxnet_tpu import compiled
    compiled.reset()
    prog = compiled.tracked_jit(lambda v: v + 1, "runprof.test")
    prog(jnp.ones((4,), jnp.float32))
    assert runprof.state_seconds("compile") > 0
    c = telemetry.get_metric("run_state_seconds", state="compile")
    assert c is not None and c.value > 0
    # exercising the whole runprof surface compiles NOTHING
    before = xla_stats.compile_counts()
    for _ in range(16):
        runprof.note_step({"data_wait": 0.001}, 0.01)
    runprof.note_state("checkpoint_save", 0.01)
    runprof.observe_metric("loss", 0.5)
    runprof.snapshot()
    buf = _io.StringIO()
    runprof.report(out=buf)
    assert xla_stats.compile_counts() == before


def test_checkpointer_feeds_save_restore_states(fresh, tmp_path):
    import jax.numpy as jnp
    from mxnet_tpu.parallel.checkpoint import abstract_like
    from mxnet_tpu.parallel.elastic import ElasticCheckpointer
    tree = {"w": jnp.zeros((4,), jnp.float32)}
    ck = ElasticCheckpointer(str(tmp_path / "ck"))
    ck.save(1, tree)
    assert runprof.state_seconds("checkpoint_save") > 0
    step, _ = ck.restore(abstract_like(tree))
    assert step == 1
    assert runprof.state_seconds("checkpoint_restore") > 0


def test_elastic_trainer_feeds_productive_and_recovery(fresh, tmp_path):
    import jax.numpy as jnp
    from mxnet_tpu.parallel import elastic
    from mxnet_tpu.parallel.retry import RetryPolicy
    failed = {"done": False}

    def step_fn(state, step):
        if step == 2 and not failed["done"]:
            failed["done"] = True
            raise RuntimeError("boom")
        time.sleep(0.005)
        return {"w": state["w"] + 1.0}

    t = elastic.ElasticTrainer(
        step_fn, {"w": jnp.zeros(2)}, ckpt_dir=str(tmp_path / "ck"),
        ckpt_every=2, on_failure="recover", dead_node_timeout=None,
        retry_policy=RetryPolicy(max_attempts=3, base_delay=0.01,
                                 max_delay=0.05))
    out = t.run(4)
    assert float(np.asarray(out["w"])[0]) == 4.0
    assert runprof.state_seconds("train_productive") >= 4 * 0.005
    assert runprof.state_seconds("checkpoint_save") > 0
    assert runprof.state_seconds("recovery") > 0
    # the recover cycle restored from step 2: restore booked separately
    assert runprof.state_seconds("checkpoint_restore") > 0
    snap = runprof.snapshot()
    assert snap["goodput_fraction"] < 1


# ---------------------------------------------------------------------------
# Lost work across restarts
# ---------------------------------------------------------------------------

def _write_progress(dir, host, pid, step, avg, scope=None):
    path = os.path.join(str(dir),
                        "runprof_progress_host%d_pid%d.json" % (host, pid))
    with open(path, "w") as fh:
        json.dump({"step": step, "avg_step_seconds": avg,
                   "scope": scope, "updated": time.time()}, fh)


def test_note_resume_books_lost_work(fresh, tmp_path):
    _write_progress(tmp_path, telemetry.host_id(), 99991, 12, 0.5)
    _write_progress(tmp_path, telemetry.host_id(), 99992, 9, 0.5)
    lost = runprof.note_resume(7, dir=str(tmp_path))
    assert lost == 5    # highest marker (12) minus the checkpoint (7)
    assert telemetry.get_metric("run_lost_steps_total").value == 5
    assert telemetry.get_metric("run_lost_work_seconds").value == \
        pytest.approx(2.5)
    snap = runprof.snapshot()
    assert snap["lost_steps"] == 5
    assert snap["lost_work_seconds"] == pytest.approx(2.5)
    assert snap["resumed_from"] == 7
    # the in-memory high-water clamps to the resumed step: the dead
    # crash point must not be re-persisted and re-booked next recovery
    assert snap["progress_step"] == 7
    # the markers were consumed at the resume that booked them: a
    # second resume from the same checkpoint cannot double-book
    assert runprof.note_resume(7, dir=str(tmp_path)) == 0
    assert telemetry.get_metric("run_lost_steps_total").value == 5


def test_note_progress_persists_marker(fresh, tmp_path):
    telemetry.configure(str(tmp_path))
    try:
        runprof.note_progress(3, step_seconds=0.1)
        # throttled: rapid-fire progress inside the 0.2s window lags...
        for s in range(4, 9):
            runprof.note_progress(s, step_seconds=0.1)
        # ...until the exit-path flush writes the high-water mark NOW
        runprof.flush_progress()
    finally:
        telemetry.configure(None)
    fns = [fn for fn in os.listdir(str(tmp_path))
           if fn.startswith("runprof_progress_host")]
    assert len(fns) == 1
    doc = json.load(open(os.path.join(str(tmp_path), fns[0])))
    assert doc["step"] == 8
    assert doc["avg_step_seconds"] == pytest.approx(0.1)
    # a marker without a mean prices lost steps at zero, not wrongly
    _write_progress(tmp_path, telemetry.host_id(), 77001, 20, None)
    assert runprof.note_resume(15, dir=str(tmp_path)) == 5
    assert telemetry.get_metric("run_lost_steps_total").value == 5
    assert telemetry.get_metric("run_lost_work_seconds") is None
    # an OTHER run's marker (different scope) in the same telemetry dir
    # is invisible to this run's resume — and left on disk for its owner
    _write_progress(tmp_path, telemetry.host_id(), 77002, 40, 0.5,
                    scope="/ck/other-run")
    assert runprof.note_resume(15, dir=str(tmp_path),
                               scope="/ck/this-run") == 0
    assert telemetry.get_metric("run_lost_steps_total").value == 5
    assert len(os.listdir(str(tmp_path))) == 1   # other marker survives


# ---------------------------------------------------------------------------
# Verdicts
# ---------------------------------------------------------------------------

def _states(**kv):
    st = {s: 0.0 for s in runprof.RUN_STATES}
    st.update(kv)
    return st


@pytest.mark.parametrize("states,expect", [
    (_states(train_productive=9.5, idle=0.5), "healthy"),
    (_states(train_productive=2.0, compile=6.0), "compile-heavy"),
    (_states(train_productive=2.0, checkpoint_save=5.0),
     "checkpoint-heavy"),
    (_states(train_productive=2.0, checkpoint_restore=5.0),
     "checkpoint-heavy"),
    (_states(train_productive=2.0, recovery=5.0), "recovery-heavy"),
    (_states(train_productive=2.0, input_stall=5.0), "input-bound"),
    (_states(train_productive=2.0, idle=5.0), "idle-heavy"),
    (_states(train_productive=1.0, init=5.0), "init-heavy"),
])
def test_verdict_classes(states, expect):
    verdict, hint = runprof.classify(states)
    assert verdict == expect
    assert hint == runprof.HINTS[expect]


def test_verdict_unknown_and_anomaly_hint():
    assert runprof.classify({})[0] == "unknown"
    v, hint = runprof.classify(_states(train_productive=10.0),
                               anomaly_counts={"nonfinite_loss": 2})
    assert v == "healthy"
    assert "nonfinite_loss x2" in hint and "flight-recorder" in hint


# ---------------------------------------------------------------------------
# Snapshots, merge, skew, report
# ---------------------------------------------------------------------------

def _host_snapshot(dir, host, pid, productive, wall, lost=0,
                   anomalies=None, incarnation=0):
    doc = {"host": host, "pid": pid, "updated": time.time(),
           "incarnation": incarnation,
           "run_wall_seconds": wall, "steps": 10,
           "lost_steps": lost, "lost_work_seconds": lost * 0.2,
           "anomaly_counts": anomalies or {}, "anomalies": [],
           "states": _states(train_productive=productive,
                             idle=wall - productive),
           "goodput_fraction": productive / wall}
    with open(os.path.join(str(dir), "runprof_i%d_host%d_pid%d.json"
                           % (incarnation, host, pid)), "w") as fh:
        json.dump(doc, fh)


def test_merge_keeps_every_incarnation_and_skew(fresh, tmp_path):
    # host 0: a crashed incarnation and its replacement REUSING the pid
    # (the k8s pid-1 case) — the incarnation in filename + key keeps
    # both snapshots
    _host_snapshot(tmp_path, 0, 100, productive=4.0, wall=5.0)
    _host_snapshot(tmp_path, 0, 100, productive=4.0, wall=5.0, lost=2,
                   incarnation=1)
    # host 1: one slow incarnation
    _host_snapshot(tmp_path, 1, 200, productive=2.0, wall=5.0,
                   anomalies={"step_time_spike": 1})
    # torn file from a killed writer is skipped, not fatal
    with open(os.path.join(str(tmp_path),
                           "runprof_host9_pid9.json"), "w") as fh:
        fh.write("{torn")
    # a non-training snapshot (the supervise() launcher) contributes
    # its recovery badput but NOT its wall/init — a launcher that sat
    # idle all run must not deflate merged goodput into init-heavy
    sup = {"host": 0, "pid": 999, "updated": time.time(),
           "incarnation": 0, "run_wall_seconds": 60.0, "steps": 0,
           "lost_steps": 0, "lost_work_seconds": 0.0,
           "anomaly_counts": {}, "anomalies": [],
           "states": _states(recovery=1.5, init=58.5),
           "goodput_fraction": 0.0}
    with open(os.path.join(str(tmp_path),
                           "runprof_i0_host0_pid999.json"), "w") as fh:
        json.dump(sup, fh)
    merged = runprof.merge_host_snapshots(str(tmp_path))
    assert set(merged) == {(0, 100, 0), (0, 100, 1), (1, 200, 0),
                           (0, 999, 0)}
    agg = runprof.aggregate(merged.values())
    assert agg["lost_steps"] == 2
    assert agg["run_wall_seconds"] == pytest.approx(15.0)
    assert agg["goodput_fraction"] == pytest.approx(10.0 / 15.0)
    assert agg["states"]["recovery"] == pytest.approx(1.5)
    assert agg["states"]["init"] == pytest.approx(0.0)
    skew = runprof.goodput_by_host(merged)
    assert skew["slowest"] == 1
    assert skew["skew"] == pytest.approx(0.8 - 0.4)
    g = telemetry.get_metric("run_goodput_skew")
    assert g is not None and g.read() == pytest.approx(0.4)


def test_report_renders_waterfall_lost_work_and_skew(fresh, tmp_path):
    _host_snapshot(tmp_path, 0, 100, productive=4.0, wall=5.0, lost=3,
                   anomalies={"nonfinite_loss": 1})
    _host_snapshot(tmp_path, 1, 200, productive=2.0, wall=5.0)
    buf = _io.StringIO()
    rc = runprof.report(str(tmp_path), out=buf)
    text = buf.getvalue()
    assert rc == 0
    assert "train_productive" in text and "lost work: 3 step(s)" in text
    assert "nonfinite_loss x1" in text
    assert "hosts: 2" in text and "slowest host 1" in text
    rec = json.loads(text.strip().splitlines()[-1])
    assert rec["metric"] == "runprof_report"
    assert rec["lost_steps"] == 3
    assert rec["goodput_fraction"] == pytest.approx(0.6)
    assert rec["goodput_skew"] == pytest.approx(0.4)
    assert rec["slowest_host"] == 1


def test_report_single_snapshot_file_and_empty_dir(fresh, tmp_path):
    _host_snapshot(tmp_path, 0, 100, productive=1.0, wall=10.0)
    path = os.path.join(str(tmp_path), "runprof_i0_host0_pid100.json")
    buf = _io.StringIO()
    assert runprof.report(path, out=buf) == 0
    rec = json.loads(buf.getvalue().strip().splitlines()[-1])
    assert rec["verdict"] == "idle-heavy"
    empty = tmp_path / "empty"
    empty.mkdir()
    buf = _io.StringIO()
    assert runprof.report(str(empty), out=buf) == 1


def test_report_cli_subprocess(tmp_path):
    _host_snapshot(tmp_path, 0, 100, productive=9.0, wall=10.0)
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
    proc = subprocess.Popen(
        [sys.executable, "-m", "mxnet_tpu.runprof", "report",
         str(tmp_path), "--json"],
        cwd=REPO, env=env, stdout=subprocess.PIPE,
        stderr=subprocess.PIPE, text=True)
    out, err = launchutil.communicate(proc)
    assert proc.returncode == 0, out + err
    rec = json.loads(out.strip().splitlines()[-1])
    assert rec["metric"] == "runprof_report"
    assert rec["verdict"] == "healthy"


# ---------------------------------------------------------------------------
# Speedometer goodput suffix (gated by MXNET_STEPPROF)
# ---------------------------------------------------------------------------

def test_speedometer_goodput_suffix_gated(fresh):
    sp = mx.callback.Speedometer(batch_size=16, frequent=4)
    sp._mark()
    t0 = time.perf_counter()
    time.sleep(0.02)
    runprof.note_step({}, time.perf_counter() - t0)
    assert sp._runprof_suffix() == ""     # disabled: no suffix
    stepprof.enable()
    try:
        suffix = sp._runprof_suffix()
        assert suffix.startswith("\tgoodput ") and suffix.endswith("%")
        sp._mark()
        assert sp._runprof_suffix() == ""  # nothing advanced since mark
    finally:
        stepprof.disable()


# ---------------------------------------------------------------------------
# bench_gate: the goodput gate + state-seconds delta line
# ---------------------------------------------------------------------------

def test_bench_gate_goodput_regression_prints_state_deltas(fresh,
                                                           tmp_path):
    sys.path.insert(0, os.path.join(REPO, "tools"))
    import bench_gate
    hist = {"parsed": {
        "metric": bench_gate.TRAIN_METRIC, "value": 2800.0,
        "goodput_fraction": 0.95,
        "run_states": {"train_productive": 9.5, "compile": 0.2}}}
    with open(str(tmp_path / "BENCH_r01.json"), "w") as fh:
        json.dump(hist, fh)
    run = [{"metric": bench_gate.TRAIN_METRIC, "value": 2800.0,
            "goodput_fraction": 0.6,
            "run_states": {"train_productive": 6.0, "compile": 0.2,
                           "checkpoint_save": 3.5}}]
    buf = _io.StringIO()
    rc = bench_gate.gate_records(run, history_dir=str(tmp_path),
                                 metric=bench_gate.GOODPUT_METRIC,
                                 out=buf)
    assert rc == 1
    lines = [json.loads(l) for l in buf.getvalue().splitlines()]
    assert lines[0]["status"] == "fail"
    states = [l for l in lines if l["metric"] == "bench_gate_states"]
    assert states and "checkpoint_save +3.500s" in states[0]["detail"]
    # a non-regressed run passes
    ok = [{"metric": bench_gate.TRAIN_METRIC, "value": 2800.0,
           "goodput_fraction": 0.93}]
    buf = _io.StringIO()
    assert bench_gate.gate_records(ok, history_dir=str(tmp_path),
                                   metric=bench_gate.GOODPUT_METRIC,
                                   out=buf) == 0


def test_repo_gate_picks_up_goodput_records(fresh, tmp_path):
    sys.path.insert(0, os.path.join(REPO, "tools"))
    import bench_gate
    # no history for the goodput metric -> lenient skip, exit 0
    run = [{"metric": bench_gate.TRAIN_METRIC, "value": 2800.0,
            "goodput_fraction": 0.9}]
    buf = _io.StringIO()
    rc = bench_gate.gate_records(run, history_dir=str(tmp_path),
                                 metric=bench_gate.GOODPUT_METRIC,
                                 out=buf)
    assert rc == 0
    assert json.loads(buf.getvalue().splitlines()[0])["status"] == "skip"


# ---------------------------------------------------------------------------
# launched: chaos kill-and-resume leaves a priced badput ledger
# ---------------------------------------------------------------------------

RUNPROF_WORKER = r"""
import json, os, sys, time
coord, rank, ckdir, tdir = sys.argv[1], int(sys.argv[2]), sys.argv[3], \
    sys.argv[4]
os.environ["MXNET_TELEMETRY_DIR"] = tdir
restart = int(os.environ.get("MXNET_ELASTIC_RESTART", "0"))
if restart == 0 and rank == 1:
    # incarnation 0 only: rank 1 dies mid-run, strictly after the
    # step-5 checkpoint committed (chaos armed via env before import)
    os.environ["MXNET_CHAOS"] = "worker.death@8"
import numpy as np
import mxnet_tpu as mx
from mxnet_tpu import runprof
from mxnet_tpu.parallel import dist, elastic
import jax.numpy as jnp

dist.init(coord, 2, rank, recoverable=True)
dist.stop_heartbeat(); dist.start_heartbeat(interval=0.1)

def step_fn(state, step):
    time.sleep(0.25)
    return {"w": state["w"] + 1.0}

t = elastic.ElasticTrainer(step_fn, {"w": jnp.zeros(4)}, ckpt_dir=ckdir,
                           ckpt_every=5, on_failure="exit",
                           dead_node_timeout=1.0, watchdog_interval=0.25)
out = t.run(12)
print("RESUMED_FROM", t.resumed_from, flush=True)
print("FINAL", float(np.asarray(out["w"])[0]), flush=True)
runprof.write_host_snapshot(force=True)
print("RUNPROF", json.dumps(runprof.snapshot()), flush=True)
dist.stop_heartbeat()
os._exit(0)  # skip jax's shutdown barrier (peer histories differ)
"""


@pytest.mark.launched
@pytest.mark.timeout(180)
def test_launched_chaos_kill_and_resume_prices_badput(fresh, tmp_path):
    """Acceptance: a launched 2-process elastic run loses a worker to
    chaos, the supervisor relaunches the pod, and the run-anatomy
    ledger prices it: nonzero checkpoint_restore badput and lost-work
    steps in the worker snapshots, recovery badput in the supervisor's
    ledger, goodput < 1, all consistent with the merged waterfall."""
    from mxnet_tpu.parallel import elastic
    from mxnet_tpu.parallel.retry import RetryPolicy
    worker = tmp_path / "worker.py"
    worker.write_text(RUNPROF_WORKER)
    ckdir = str(tmp_path / "ck")
    tdir = str(tmp_path / "telemetry")
    env = dict(os.environ, JAX_PLATFORMS="cpu", PALLAS_AXON_POOL_IPS="",
               PYTHONPATH=REPO)
    restarts, log_dir = elastic.supervise(
        lambda rank, restart, coord: [sys.executable, str(worker), coord,
                                      str(rank), ckdir, tdir],
        nprocs=2, max_restarts=2, env=env,
        log_dir=str(tmp_path / "logs"), round_timeout=120,
        policy=RetryPolicy(max_attempts=3, base_delay=0.2, max_delay=1.0))
    assert restarts >= 1   # incarnation 0 really did lose the worker

    # the supervisor's own ledger booked the relaunch backoff
    assert runprof.state_seconds("recovery") > 0

    for r in range(2):
        out = open(os.path.join(log_dir,
                                "r%d_rank%d.log" % (restarts, r))).read()
        assert "RESUMED_FROM 5" in out, out
        assert "FINAL 12.0" in out, out
        line = [l for l in out.splitlines()
                if l.startswith("RUNPROF ")][-1]
        snap = json.loads(line[len("RUNPROF "):])
        # the resumed incarnation restored a checkpoint and re-executed
        # the steps the dead incarnation had already trained past it
        assert snap["states"]["checkpoint_restore"] > 0, snap
        assert snap["lost_steps"] >= 1, snap
        assert snap["lost_work_seconds"] > 0, snap
        assert snap["states"]["train_productive"] > 0, snap
        assert 0 < snap["goodput_fraction"] < 1, snap

    # merged report over the telemetry dir: both hosts' snapshots (plus
    # the supervisor's, written here so its recovery badput is in the
    # same waterfall), consistent with the per-worker ledgers
    runprof.write_host_snapshot(dir=tdir, force=True)
    merged = runprof.merge_host_snapshots(tdir)
    assert len(merged) >= 3
    buf = _io.StringIO()
    rc = runprof.report(tdir, out=buf)
    text = buf.getvalue()
    assert rc == 0, text
    rec = json.loads(text.strip().splitlines()[-1])
    assert rec["lost_steps"] >= 2          # both ranks re-did work
    assert rec["states"]["checkpoint_restore"] > 0
    assert rec["states"]["recovery"] > 0
    assert rec["goodput_fraction"] < 1
    assert "hosts: " in text               # goodput skew line rendered
