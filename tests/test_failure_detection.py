"""Failure detection (reference include/mxnet/kvstore.h:338 + ps-lite
heartbeats, van.cc): each process heartbeats into the jax.distributed
coordinator KV store; `kv.get_num_dead_node(timeout)` counts stale peers.

Launched test: two jax.distributed CPU processes — one exits early
(simulated death) and the survivor must observe exactly one dead node."""
import os
import subprocess
import sys

import pytest

import launchutil

pytestmark = pytest.mark.launched

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

SURVIVOR = r"""
import sys, time
import jax
jax.distributed.initialize(sys.argv[1], 2, 0)
from mxnet_tpu.parallel import dist
dist._initialized = True
dist.start_heartbeat(interval=0.2)
import mxnet_tpu as mx
kv = mx.kv.create("dist_sync")
# wait for the peer's first heartbeat
deadline = time.time() + 30
while kv.get_num_dead_node(timeout=60) != 0:
    if time.time() > deadline:
        print("PEER NEVER BEAT"); sys.exit(2)
    time.sleep(0.2)
print("ALL ALIVE", flush=True)
# peer exits after ~1s; its beat goes stale
deadline = time.time() + 30
while kv.get_num_dead_node(timeout=1.0) != 1:
    if time.time() > deadline:
        print("NEVER SAW DEATH", kv.get_num_dead_node(timeout=1.0))
        sys.exit(3)
    time.sleep(0.3)
print("DEAD NODES 1", flush=True)
import os
os._exit(0)  # skip jax's shutdown barrier (it would fail: peer is dead)
"""

VICTIM = r"""
import sys, time
import jax
jax.distributed.initialize(sys.argv[1], 2, 1)
from mxnet_tpu.parallel import dist
dist._initialized = True
dist.start_heartbeat(interval=0.2)
time.sleep(1.0)
import os
os._exit(0)  # die without cleanup, like a crashed worker
"""


_free_port = launchutil.free_port


@pytest.mark.timeout(180)
def test_dead_worker_detected(tmp_path):
    coord = "127.0.0.1:%d" % _free_port()
    env = dict(os.environ, JAX_PLATFORMS="cpu", PALLAS_AXON_POOL_IPS="",
               PYTHONPATH=REPO)
    (tmp_path / "survivor.py").write_text(SURVIVOR)
    (tmp_path / "victim.py").write_text(VICTIM)
    survivor = subprocess.Popen(
        [sys.executable, str(tmp_path / "survivor.py"), coord],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
    victim = subprocess.Popen(
        [sys.executable, str(tmp_path / "victim.py"), coord],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
    out, _ = launchutil.communicate(survivor, timeout=150)
    try:
        victim.wait(timeout=30)
    except subprocess.TimeoutExpired:
        victim.kill()
    assert survivor.returncode == 0, out
    assert "ALL ALIVE" in out and "DEAD NODES 1" in out, out
