"""Monitor, visualization and test_utils harness coverage (reference
tests: test_monitor.py, print_summary usage, check_consistency from
test_utils.py:1207)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import test_utils as tu


def _mlp():
    data = mx.sym.Variable("data")
    fc1 = mx.sym.FullyConnected(data, num_hidden=8, name="fc1")
    act = mx.sym.Activation(fc1, act_type="relu", name="relu1")
    fc2 = mx.sym.FullyConnected(act, num_hidden=3, name="fc2")
    return mx.sym.SoftmaxOutput(fc2, name="sm")


def test_monitor_collects_stats():
    net = _mlp()
    mon = mx.monitor.Monitor(interval=1, pattern=".*fc.*")
    ex = net.simple_bind(mx.cpu(), data=(4, 10))
    mon.install(ex)
    for arr in ex.arg_arrays:
        arr[:] = np.random.RandomState(0).rand(*arr.shape).astype("f")
    mon.tic()
    ex.forward()
    stats = mon.toc()
    assert stats, "monitor should capture fc tensors"
    names = [n for _, n, _ in stats]
    assert any("fc1" in n for n in names)
    assert not any("relu" in n for n in names)  # pattern filtered


def test_print_summary(capsys):
    net = _mlp()
    mx.visualization.print_summary(net, shape={"data": (1, 10)})
    out = capsys.readouterr().out
    assert "fc1" in out and "Total params" in out


def test_check_symbolic_forward_backward():
    a = mx.sym.Variable("a")
    out = 2 * a
    x = np.random.RandomState(1).rand(3, 4).astype("f")
    tu.check_symbolic_forward(out, [x], [2 * x])
    tu.check_symbolic_backward(out, [x], [np.ones_like(x)],
                               [2 * np.ones_like(x)])


def test_check_numeric_gradient():
    a = mx.sym.Variable("a")
    out = mx.sym.sum(a * a)
    x = np.random.RandomState(2).rand(4).astype("f")
    tu.check_numeric_gradient(out, [x])


def test_check_consistency_across_dtypes():
    """The reference's kernel-parity harness: same symbol under several
    ctx/dtype combos, outputs cross-checked (test_utils.py:1207)."""
    sym = mx.sym.FullyConnected(mx.sym.Variable("data"), num_hidden=4,
                                name="fc")
    ctx_list = [
        {"ctx": mx.cpu(), "data": (2, 6), "type_dict": {"data": np.float32}},
        {"ctx": mx.cpu(), "data": (2, 6), "type_dict": {"data": np.float64}},
    ]
    tu.check_consistency(sym, ctx_list)
