"""Parametrized sweep over elemwise/broadcast/reduce op families vs
NumPy oracles — the bulk-coverage strategy of the reference's
test_operator.py (5,773 LoC) in parametrized form."""
import numpy as np
import pytest

import mxnet_tpu as mx

RNG = np.random.RandomState(0)
POS = RNG.rand(3, 4).astype("f") + 0.5          # strictly positive
ANY = RNG.randn(3, 4).astype("f")
UNIT = (RNG.rand(3, 4).astype("f") - 0.5) * 1.8  # in (-0.9, 0.9)

UNARY = [
    ("abs", ANY, np.abs), ("sign", ANY, np.sign),
    ("square", ANY, np.square), ("sqrt", POS, np.sqrt),
    ("rsqrt", POS, lambda x: 1 / np.sqrt(x)),
    ("cbrt", POS, np.cbrt), ("exp", UNIT, np.exp),
    ("log", POS, np.log), ("log2", POS, np.log2),
    ("log10", POS, np.log10), ("log1p", POS, np.log1p),
    ("expm1", UNIT, np.expm1), ("sin", ANY, np.sin),
    ("cos", ANY, np.cos), ("tan", UNIT, np.tan),
    ("arcsin", UNIT, np.arcsin), ("arccos", UNIT, np.arccos),
    ("arctan", ANY, np.arctan), ("sinh", UNIT, np.sinh),
    ("cosh", UNIT, np.cosh), ("tanh", ANY, np.tanh),
    ("arcsinh", ANY, np.arcsinh),
    ("arccosh", POS + 1.0, np.arccosh),
    ("arctanh", UNIT * 0.9, np.arctanh),
    ("floor", ANY * 3, np.floor), ("ceil", ANY * 3, np.ceil),
    ("round", ANY * 3, lambda x: np.round(x)),
    ("trunc", ANY * 3, np.trunc),
    ("fix", ANY * 3, np.fix),
    ("negative", ANY, np.negative),
    ("reciprocal", POS, np.reciprocal),
    ("relu", ANY, lambda x: np.maximum(x, 0)),
    ("sigmoid", ANY, lambda x: 1 / (1 + np.exp(-x))),
    ("softsign", ANY, lambda x: x / (1 + np.abs(x))),
    ("gamma", POS, None),    # checked for finiteness only
    ("gammaln", POS, None),
    ("degrees", ANY, np.degrees), ("radians", ANY, np.radians),
]


@pytest.mark.parametrize("name,x,oracle", UNARY,
                         ids=[u[0] for u in UNARY])
def test_unary_vs_numpy(name, x, oracle):
    fn = getattr(mx.nd, name)
    out = fn(mx.nd.array(x)).asnumpy()
    if oracle is None:
        assert np.isfinite(out).all()
        return
    np.testing.assert_allclose(out, oracle(x), rtol=2e-5, atol=1e-6)


BINARY = [
    ("broadcast_add", np.add), ("broadcast_sub", np.subtract),
    ("broadcast_mul", np.multiply), ("broadcast_div", np.divide),
    ("broadcast_maximum", np.maximum), ("broadcast_minimum", np.minimum),
    ("broadcast_power", None),
    ("broadcast_hypot", np.hypot),
    ("broadcast_mod", None),
    ("broadcast_equal", lambda a, b: (a == b).astype("f")),
    ("broadcast_not_equal", lambda a, b: (a != b).astype("f")),
    ("broadcast_greater", lambda a, b: (a > b).astype("f")),
    ("broadcast_lesser", lambda a, b: (a < b).astype("f")),
]


@pytest.mark.parametrize("name,oracle", BINARY, ids=[b[0] for b in BINARY])
def test_binary_broadcast_vs_numpy(name, oracle):
    a = RNG.rand(3, 1, 4).astype("f") + 0.5
    b = RNG.rand(1, 2, 4).astype("f") + 0.5
    fn = getattr(mx.nd, name)
    out = fn(mx.nd.array(a), mx.nd.array(b)).asnumpy()
    if name == "broadcast_power":
        oracle = np.power
    if name == "broadcast_mod":
        oracle = np.mod
    np.testing.assert_allclose(out, oracle(a, b), rtol=2e-5, atol=1e-6)


REDUCE = [("sum", np.sum), ("mean", np.mean), ("max", np.max),
          ("min", np.min), ("prod", np.prod),
          ("nansum", np.nansum), ("nanprod", np.nanprod)]


@pytest.mark.parametrize("name,oracle", REDUCE, ids=[r[0] for r in REDUCE])
@pytest.mark.parametrize("axis", [None, 0, 1, (0, 1)])
@pytest.mark.parametrize("keepdims", [False, True])
def test_reduce_vs_numpy(name, oracle, axis, keepdims):
    x = (RNG.rand(3, 4).astype("f") + 0.2)
    fn = getattr(mx.nd, name)
    out = fn(mx.nd.array(x), axis=axis, keepdims=keepdims).asnumpy()
    want = oracle(x, axis=axis, keepdims=keepdims)
    np.testing.assert_allclose(np.squeeze(out) if not keepdims else out,
                               np.squeeze(want) if not keepdims else want,
                               rtol=2e-5)


def test_scalar_op_family():
    x = ANY
    nd = mx.nd.array(x)
    np.testing.assert_allclose((nd + 2).asnumpy(), x + 2, rtol=1e-6)
    np.testing.assert_allclose((2 - nd).asnumpy(), 2 - x, rtol=1e-6)
    np.testing.assert_allclose((nd * 3).asnumpy(), x * 3, rtol=1e-6)
    np.testing.assert_allclose((3 / (nd + 10)).asnumpy(), 3 / (x + 10),
                               rtol=1e-5)
    np.testing.assert_allclose((nd ** 2).asnumpy(), x ** 2, rtol=1e-5)
    np.testing.assert_allclose(mx.nd.maximum(nd, 0.1).asnumpy(),
                               np.maximum(x, 0.1), rtol=1e-6)


def test_profiler_writes_trace(tmp_path):
    import os
    mx.profiler.set_config(filename=str(tmp_path / "prof.json"))
    mx.profiler.set_state("run")
    (mx.nd.ones((32, 32)) @ mx.nd.ones((32, 32))).asnumpy()
    mx.profiler.set_state("stop")
    trace_dir = str(tmp_path / "prof_trace")
    found = []
    for root, _, files in os.walk(trace_dir):
        found.extend(files)
    assert found, "profiler produced no trace files"


def test_symbol_scalar_maximum_minimum():
    a = mx.sym.Variable("a")
    ex = mx.sym.maximum(a, 0.5).bind(
        mx.cpu(), {"a": mx.nd.array(np.array([[0.2, 0.8]], "f"))})
    np.testing.assert_allclose(ex.forward()[0].asnumpy(), [[0.5, 0.8]])
    ex2 = mx.sym.minimum(0.5, a).bind(
        mx.cpu(), {"a": mx.nd.array(np.array([[0.2, 0.8]], "f"))})
    np.testing.assert_allclose(ex2.forward()[0].asnumpy(), [[0.2, 0.5]])


# ---------------------------------------------------------------------------
# dtype sweep: the reference op suite exercises ops across dtypes
# (test_operator.py's check_consistency dtype lists); this sweeps the
# dtype-generic families over ints and half-precision floats, asserting
# BOTH values and output dtype (a silent upcast is a bug even when the
# numbers match).
# ---------------------------------------------------------------------------
DTYPES = ["int32", "int64", "float16", "float64"]


def _mk(dtype, lo=1, hi=7, shape=(3, 4), seed=3):
    r = np.random.RandomState(seed)
    if np.issubdtype(np.dtype(dtype), np.integer):
        return r.randint(lo, hi, shape).astype(dtype)
    return (r.rand(*shape) * (hi - lo) + lo).astype(dtype)


@pytest.mark.parametrize("dtype", DTYPES)
def test_dtype_unary_sweep(dtype):
    x = _mk(dtype)
    for name, oracle in [("abs", np.abs), ("negative", np.negative),
                         ("square", np.square), ("sign", np.sign)]:
        out = getattr(mx.nd, name)(mx.nd.array(x, dtype=dtype))
        assert str(out.dtype.name if hasattr(out.dtype, "name")
                   else out.dtype) == dtype, (name, out.dtype)
        tol = 1e-2 if dtype == "float16" else 1e-6
        np.testing.assert_allclose(out.asnumpy().astype("f8"),
                                   oracle(x).astype("f8"), rtol=tol)


@pytest.mark.parametrize("dtype", DTYPES)
def test_dtype_binary_and_reduce_sweep(dtype):
    a, b = _mk(dtype, seed=4), _mk(dtype, seed=5)
    for name, oracle in [("broadcast_add", np.add),
                         ("broadcast_mul", np.multiply),
                         ("broadcast_maximum", np.maximum),
                         ("broadcast_minimum", np.minimum)]:
        out = getattr(mx.nd, name)(mx.nd.array(a, dtype=dtype),
                                   mx.nd.array(b, dtype=dtype))
        assert np.dtype(str(out.dtype)) == np.dtype(dtype), (name, out.dtype)
        tol = 1e-2 if dtype == "float16" else 1e-6
        np.testing.assert_allclose(out.asnumpy().astype("f8"),
                                   oracle(a, b).astype("f8"), rtol=tol)
    # reductions: sum/max/min keep dtype; argmax returns f32 indices
    # (reference convention)
    arr = mx.nd.array(a, dtype=dtype)
    np.testing.assert_allclose(mx.nd.sum(arr, axis=1).asnumpy()
                               .astype("f8"),
                               a.sum(axis=1).astype("f8"),
                               rtol=1e-2 if dtype == "float16" else 1e-6)
    np.testing.assert_allclose(mx.nd.max(arr, axis=0).asnumpy()
                               .astype("f8"),
                               a.max(axis=0).astype("f8"), rtol=1e-6)
    am = mx.nd.argmax(arr, axis=1).asnumpy()
    np.testing.assert_array_equal(am.astype("i8"), a.argmax(axis=1))
