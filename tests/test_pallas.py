"""Pallas kernel tests (interpret mode on CPU).

Oracle is dense JAX math, mirroring how the reference cross-checks cuDNN
kernels against CPU (`tests/python/gpu/test_operator_gpu.py`
check_consistency).
"""
import numpy as np
import jax
import jax.numpy as jnp
import pytest

from mxnet_tpu.ops.pallas_kernels import flash_attention, fused_lstm
from mxnet_tpu.parallel.ring_attention import local_attention


def _qkv(b=2, t=64, h=2, d=16, seed=0):
    rng = np.random.RandomState(seed)
    mk = lambda: jnp.asarray(rng.randn(b, t, h, d).astype(np.float32))
    return mk(), mk(), mk()


@pytest.mark.parametrize("causal", [False, True])
def test_flash_attention_matches_dense(causal):
    q, k, v = _qkv()
    got = flash_attention(q, k, v, causal=causal, block_q=16, block_k=16)
    want = local_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_flash_attention_ragged_blocks():
    # T not a multiple of the block size exercises the tail-padding mask
    q, k, v = _qkv(t=40)
    got = flash_attention(q, k, v, causal=True, block_q=16, block_k=16)
    want = local_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_flash_attention_grad():
    q, k, v = _qkv(t=32)
    f = lambda q, k, v: jnp.sum(
        flash_attention(q, k, v, causal=True, block_q=16, block_k=16) ** 2)
    fd = lambda q, k, v: jnp.sum(local_attention(q, k, v, causal=True) ** 2)
    g = jax.grad(f, argnums=(0, 1, 2))(q, k, v)
    gw = jax.grad(fd, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g, gw):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-4)


def _lstm_ref(x, h0, c0, wx, wh, b):
    hs = []
    h, c = h0, c0
    hid = wh.shape[0]
    for t in range(x.shape[0]):
        gates = x[t] @ wx + h @ wh + b
        i = jax.nn.sigmoid(gates[:, :hid])
        f = jax.nn.sigmoid(gates[:, hid:2 * hid])
        g = jnp.tanh(gates[:, 2 * hid:3 * hid])
        o = jax.nn.sigmoid(gates[:, 3 * hid:])
        c = f * c + i * g
        h = o * jnp.tanh(c)
        hs.append(h)
    return jnp.stack(hs), h, c


def test_fused_lstm_matches_scan():
    rng = np.random.RandomState(1)
    t, bs, inp, hid = 5, 4, 6, 8
    x = jnp.asarray(rng.randn(t, bs, inp).astype(np.float32))
    h0 = jnp.asarray(rng.randn(bs, hid).astype(np.float32))
    c0 = jnp.asarray(rng.randn(bs, hid).astype(np.float32))
    wx = jnp.asarray(rng.randn(inp, 4 * hid).astype(np.float32) * 0.1)
    wh = jnp.asarray(rng.randn(hid, 4 * hid).astype(np.float32) * 0.1)
    b = jnp.asarray(rng.randn(4 * hid).astype(np.float32) * 0.1)
    hseq, hn, cn = fused_lstm(x, h0, c0, wx, wh, b)
    hseq_w, hn_w, cn_w = _lstm_ref(x, h0, c0, wx, wh, b)
    np.testing.assert_allclose(np.asarray(hseq), np.asarray(hseq_w),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(hn), np.asarray(hn_w),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(cn), np.asarray(cn_w),
                               rtol=1e-5, atol=1e-5)


def test_rtc_pallas_module():
    import mxnet_tpu as mx
    from mxnet_tpu import rtc

    mod = rtc.PallasModule("""
def axpy(x_ref, y_ref, out_ref):
    out_ref[:] = 2.0 * x_ref[:] + y_ref[:]
""")
    k = mod.get_kernel("axpy")
    x = mx.nd.array(np.arange(8, dtype=np.float32))
    y = mx.nd.ones((8,))
    out = k.launch((x, y), out_shapes=[((8,), "float32")])
    np.testing.assert_allclose(out.asnumpy(),
                               2 * np.arange(8, dtype=np.float32) + 1)
    with pytest.raises(ValueError):
        mod.get_kernel("missing")


def test_fused_lstm_grad():
    rng = np.random.RandomState(2)
    t, bs, inp, hid = 3, 2, 4, 5
    args = (jnp.asarray(rng.randn(t, bs, inp).astype(np.float32)),
            jnp.zeros((bs, hid), jnp.float32),
            jnp.zeros((bs, hid), jnp.float32),
            jnp.asarray(rng.randn(inp, 4 * hid).astype(np.float32) * 0.1),
            jnp.asarray(rng.randn(hid, 4 * hid).astype(np.float32) * 0.1),
            jnp.zeros((4 * hid,), jnp.float32))
    loss = lambda *a: jnp.sum(fused_lstm(*a)[0] ** 2)
    from mxnet_tpu.ops.pallas_kernels import _lstm_scan_ref
    loss_ref = lambda *a: jnp.sum(_lstm_scan_ref(*a)[0] ** 2)
    g = jax.grad(loss, argnums=tuple(range(6)))(*args)
    gw = jax.grad(loss_ref, argnums=tuple(range(6)))(*args)
    for a, b in zip(g, gw):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-4)
