"""Device & compiler observability (`mxnet_tpu/xla_stats.py`): compile
accounting with the retrace explainer, the memory ledger /
`profiler._device_memory_lines` zeros-on-CPU contract, MFU goodput, the
bench regression gate, and the crash flight recorder (including the
launched chaos-kill acceptance test)."""
import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import telemetry, xla_stats

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))

import launchutil  # noqa: E402
import bench_gate  # noqa: E402


@pytest.fixture
def fresh(tmp_path):
    telemetry.reset()
    xla_stats.reset()
    telemetry.configure(str(tmp_path / "telemetry"), snapshot_interval=0)
    yield str(tmp_path / "telemetry")
    telemetry.configure(None)
    telemetry.reset()
    xla_stats.reset()


def _fc_module(batch=4, for_training=False):
    data = mx.sym.var("data")
    fc = mx.sym.FullyConnected(data, num_hidden=4, name="fc")
    mod = mx.mod.Module(fc, label_names=None, context=mx.cpu())
    mod.bind(data_shapes=[("data", (batch, 10))],
             for_training=for_training)
    mod.init_params()
    return mod


# ---------------------------------------------------------------------------
# Compile accounting (tentpole 1)
# ---------------------------------------------------------------------------

def test_one_compile_then_cache_hits(fresh):
    """Repeated Module.forward with a FIXED shape is exactly one XLA
    compile; every later call is a cache hit and no retrace fires."""
    mod = _fc_module()
    batch = mx.io.DataBatch(data=[mx.nd.ones((4, 10))], label=None)
    for _ in range(4):
        mod.forward(batch, is_train=False)
    site = dict(site="executor.forward")
    assert telemetry.get_metric("jit_compiles_total", **site).value == 1
    assert telemetry.get_metric("jit_cache_hits_total", **site).value == 3
    retr = telemetry.get_metric("jit_retraces_total", **site)
    assert retr is None or retr.value == 0
    # compile wall time landed in the per-site histogram
    h = telemetry.get_metric("jit_compile_seconds", **site)
    assert h is not None and h.count == 1 and h.sum > 0


def test_retrace_explainer_names_changed_dimension(fresh):
    """A batch-shape change retraces, and the explainer names the input
    and the exact dimension that changed."""
    mod = _fc_module()
    mod.forward(mx.io.DataBatch(data=[mx.nd.ones((4, 10))], label=None),
                is_train=False)
    mod.forward(mx.io.DataBatch(data=[mx.nd.ones((8, 10))], label=None),
                is_train=False)
    site = dict(site="executor.forward")
    assert telemetry.get_metric("jit_retraces_total", **site).value == 1
    assert telemetry.get_metric("jit_compiles_total", **site).value == 2
    info = xla_stats.last_retrace()
    assert info is not None and info["site"] == "executor.forward"
    assert "'data'" in info["reason"]
    assert "dim 0" in info["reason"] and "4 -> 8" in info["reason"]
    # the unlabeled totals advanced too (what the Prometheus snapshot
    # acceptance reads)
    assert telemetry.counter("jit_retraces_total").value >= 1
    assert telemetry.counter("jit_compiles_total").value >= 2


def test_unrelated_models_do_not_cross_retrace(fresh):
    """Two independent models hitting the same jit site are separate
    lineages: the second model's first compile is a compile, NOT a
    retrace diffed against the first model's signature."""
    _fc_module().forward(
        mx.io.DataBatch(data=[mx.nd.ones((4, 10))], label=None),
        is_train=False)
    data = mx.sym.var("data")
    other = mx.sym.FullyConnected(data, num_hidden=7, name="other_fc")
    mod2 = mx.mod.Module(other, label_names=None, context=mx.cpu())
    mod2.bind(data_shapes=[("data", (2, 6))], for_training=False)
    mod2.init_params()
    mod2.forward(mx.io.DataBatch(data=[mx.nd.ones((2, 6))], label=None),
                 is_train=False)
    site = dict(site="executor.forward")
    assert telemetry.get_metric("jit_compiles_total", **site).value == 2
    retr = telemetry.get_metric("jit_retraces_total", **site)
    assert retr is None or retr.value == 0
    assert xla_stats.last_retrace() is None


def test_static_arg_and_dtype_changes_explained(fresh):
    """The explainer covers static-arg flips and dtype changes, not just
    shapes (executor.forward's is_train flag is static)."""
    mod = _fc_module(for_training=True)
    batch = mx.io.DataBatch(data=[mx.nd.ones((4, 10))], label=None)
    mod.forward(batch, is_train=False)
    mod.forward(batch, is_train=True)
    info = xla_stats.last_retrace()
    assert info["site"] == "executor.forward"
    assert "static" in info["reason"]
    assert "False" in info["reason"] and "True" in info["reason"]


def test_tracked_jit_inside_trace_falls_through(fresh):
    """A tracked function called under an outer trace (tracer inputs)
    must not try to AOT-dispatch — gluon's vjp path depends on this."""
    import jax
    import jax.numpy as jnp
    tj = xla_stats.tracked_jit(lambda x: x * 2, "test.site")
    out = jax.jit(lambda x: tj(x) + 1)(jnp.ones(3))
    np.testing.assert_allclose(np.asarray(out), 3.0)
    # the outer jit traced through: no tracked compile happened
    assert telemetry.get_metric("jit_compiles_total",
                                site="test.site") is None
    np.testing.assert_allclose(np.asarray(tj(jnp.ones(3))), 2.0)
    assert telemetry.get_metric("jit_compiles_total",
                                site="test.site").value == 1


def test_gluon_hybridize_compile_accounting(fresh):
    from mxnet_tpu.gluon import nn
    net = nn.Dense(3, in_units=5)
    net.initialize()
    net.hybridize()
    x = mx.nd.ones((2, 5))
    for _ in range(3):
        net(x)
    site = dict(site="gluon.hybrid_forward")
    assert telemetry.get_metric("jit_compiles_total", **site).value == 1
    assert telemetry.get_metric("jit_cache_hits_total", **site).value == 2


# ---------------------------------------------------------------------------
# Memory ledger (tentpole 2) + profiler satellite
# ---------------------------------------------------------------------------

def test_memory_ledger_params_and_activations(fresh):
    mod = _fc_module(for_training=True)
    led = xla_stats.ledger()
    # bind recorded the module's parameter and gradient bytes
    assert led[("fc", "params")] == (10 * 4 + 4) * 4
    assert led[("fc", "grads")] == (10 * 4 + 4) * 4
    # a compile records the executable's temp/output bytes under its site
    mod.forward(mx.io.DataBatch(data=[mx.nd.ones((4, 10))], label=None),
                is_train=False)
    led = xla_stats.ledger()
    assert ("executor.forward", "xla_output") in led
    # gauges exist for Prometheus
    assert telemetry.get_metric("memory_ledger_bytes", scope="fc",
                                section="params").value > 0
    report = xla_stats.memory_report()
    assert "params" in report and "fc" in report
    assert "Live device buffers" in report


def test_device_memory_zeros_on_cpu(fresh):
    """CPU backends have no memory_stats(): the ledger reports ZEROS per
    device (continuous Prometheus series), it does not skip or raise."""
    recs = xla_stats.device_memory()
    assert recs, "no devices reported"
    assert all(r["bytes_in_use"] == 0 and r["peak_bytes_in_use"] == 0
               for r in recs)
    for r in recs:
        g = telemetry.get_metric("hbm_bytes_in_use", device=r["device"])
        assert g is not None and g.value == 0
    from mxnet_tpu import profiler
    lines = profiler._device_memory_lines()
    assert lines and all("bytes_in_use=0" in l for l in lines)


def test_profiler_memory_section_includes_device_lines(fresh):
    from mxnet_tpu import profiler
    profiler.set_config(aggregate_stats=True, profile_memory=True)
    profiler.reset_stats()
    try:
        (mx.nd.ones((8, 8)) + 1).asnumpy()
        table = profiler.dumps()
        assert "Backend allocator (PJRT memory_stats)." in table
        assert "bytes_in_use=0" in table
    finally:
        profiler.set_config(aggregate_stats=False, profile_memory=False)
        profiler.reset_stats()


def test_optimizer_bytes_ledgered_after_update(fresh):
    data = mx.sym.var("data")
    fc = mx.sym.FullyConnected(data, num_hidden=4, name="fc")
    net = mx.sym.SoftmaxOutput(fc, name="softmax")
    x = np.random.RandomState(0).uniform(size=(32, 10)).astype(np.float32)
    y = np.zeros(32, dtype=np.float32)
    it = mx.io.NDArrayIter(x, y, batch_size=16)
    mod = mx.mod.Module(net, context=mx.cpu())
    mod.fit(it, num_epoch=1, eval_metric="acc",
            optimizer_params=(("learning_rate", 0.01),
                              ("momentum", 0.9)))
    led = xla_stats.ledger()
    key = (mod._ledger_scope(), "optimizer")
    assert key in led and led[key] > 0  # momentum buffers


# ---------------------------------------------------------------------------
# Goodput / MFU (tentpole 3)
# ---------------------------------------------------------------------------

def test_mfu_gauges_from_fit(fresh, monkeypatch):
    monkeypatch.setenv("MXNET_PEAK_FLOPS", "1e12")
    data = mx.sym.var("data")
    fc = mx.sym.FullyConnected(data, num_hidden=4, name="fc")
    net = mx.sym.SoftmaxOutput(fc, name="softmax")
    x = np.random.RandomState(0).uniform(size=(64, 10)).astype(np.float32)
    y = np.zeros(64, dtype=np.float32)
    it = mx.io.NDArrayIter(x, y, batch_size=16)
    mod = mx.mod.Module(net, context=mx.cpu())
    mod.fit(it, num_epoch=1, eval_metric="acc")
    assert xla_stats.flops_per_batch() > 0
    g = xla_stats.goodput(batches=8, elapsed=0.5)
    assert g is not None and g["model_flops_per_second"] > 0
    assert g["mfu"] == pytest.approx(
        g["model_flops_per_second"] / xla_stats.peak_flops_total())
    text = telemetry.dumps()
    assert "\nmfu " in text or "\nmfu{" in text
    assert "model_flops_per_second" in text
    assert telemetry.counter("model_flops_total").value > 0


def test_peak_flops_env_override_and_table(monkeypatch):
    monkeypatch.setenv("MXNET_PEAK_FLOPS", "2.5e13")
    assert xla_stats.peak_flops_per_device() == 2.5e13
    monkeypatch.delenv("MXNET_PEAK_FLOPS")
    # unknown device kind (cpu) -> 0, and mfu_of degrades to 0
    assert xla_stats.peak_flops_per_device() == 0.0
    assert xla_stats.mfu_of(1e12) == 0.0


def test_speedometer_goodput_suffix(fresh, monkeypatch):
    monkeypatch.setenv("MXNET_PEAK_FLOPS", "1e9")
    xla_stats.note_train_step(1000.0, batches=1)
    sp = mx.callback.Speedometer(batch_size=16, frequent=4)
    sp._mark()
    telemetry.counter("fit_batches_total").inc(100)
    telemetry.counter("fit_samples_total").inc(1600)
    time.sleep(0.02)
    suffix = sp._goodput_suffix()
    assert "mfu" in suffix and "model FLOP/s" in suffix
    # no FLOPs figure -> empty suffix, reference log format untouched
    xla_stats.reset()
    assert sp._goodput_suffix() == ""


# ---------------------------------------------------------------------------
# Monitor satellite
# ---------------------------------------------------------------------------

def test_monitor_install_dedupes_and_counts(fresh):
    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data, num_hidden=8, name="fc")
    exe = net.simple_bind(mx.cpu(), data=(4, 16))
    mon = mx.monitor.Monitor(interval=1, pattern=".*fc.*")
    for _ in range(3):   # repeated fit calls re-install the monitor
        mon.install(exe)
    assert len(mon.exes) == 1
    mon.tic()
    exe.forward()
    stats = mon.toc()
    assert stats
    c = telemetry.get_metric("monitor_stats_total")
    assert c is not None and c.value == len(stats)


# ---------------------------------------------------------------------------
# Bench gate satellite
# ---------------------------------------------------------------------------

def _write_history(d, value=100.0):
    rec = {"metric": bench_gate.TRAIN_METRIC, "value": value,
           "unit": "img/s"}
    with open(os.path.join(d, "BENCH_r01.json"), "w") as fh:
        json.dump({"n": 1, "parsed": rec,
                   "tail": json.dumps(rec) + "\n"}, fh)


def test_bench_gate_pass_and_fail(tmp_path):
    d = str(tmp_path)
    _write_history(d, 100.0)
    ok = [{"metric": bench_gate.TRAIN_METRIC, "value": 95.0}]
    bad = [{"metric": bench_gate.TRAIN_METRIC, "value": 80.0}]
    assert bench_gate.gate_records(ok, history_dir=d) == 0
    assert bench_gate.gate_records(bad, history_dir=d) == 1
    # threshold is honored
    assert bench_gate.gate_records(bad, history_dir=d,
                                   threshold=0.25) == 0
    # a cpu-platform run regressing vs accelerator history skips...
    cpu = [{"metric": bench_gate.TRAIN_METRIC, "value": 8.0,
            "platform": "cpu"}]
    assert bench_gate.gate_records(cpu, history_dir=d) == 0
    # ...unless strict
    assert bench_gate.gate_records(cpu, history_dir=d, strict=True) == 1


def test_bench_gate_missing_metric_or_history(tmp_path):
    d = str(tmp_path)
    # no history at all -> nothing to gate -> pass (strict fails)
    recs = [{"metric": bench_gate.TRAIN_METRIC, "value": 50.0}]
    assert bench_gate.gate_records(recs, history_dir=d) == 0
    assert bench_gate.gate_records(recs, history_dir=d, strict=True) == 1
    _write_history(d, 100.0)
    assert bench_gate.gate_records([], history_dir=d) == 0
    # infer-only runs gate the inference headline instead
    _write_history(d, 100.0)
    infer_hist = {"metric": bench_gate.INFER_METRIC, "value": 200.0}
    with open(os.path.join(d, "BENCH_r02.json"), "w") as fh:
        json.dump({"parsed": infer_hist}, fh)
    assert bench_gate.gate_records(
        [{"metric": bench_gate.INFER_METRIC, "value": 195.0}],
        history_dir=d) == 0
    assert bench_gate.gate_records(
        [{"metric": bench_gate.INFER_METRIC, "value": 100.0}],
        history_dir=d) == 1


def test_bench_gate_cli_reads_repo_history(tmp_path):
    """The CLI form the acceptance criterion runs: a fresh-run file at
    the recorded best passes against the repo's real BENCH_r*.json."""
    hist = bench_gate.load_history(REPO)
    assert bench_gate.TRAIN_METRIC in hist  # real rounds are parseable
    best = hist[bench_gate.TRAIN_METRIC][0][0]
    run = tmp_path / "run.jsonl"
    run.write_text("noise line\n" + json.dumps(
        {"metric": bench_gate.TRAIN_METRIC, "value": best, "unit": "img/s"})
        + "\n")
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "bench_gate.py"),
         str(run)], capture_output=True, text=True, timeout=60)
    assert r.returncode == 0, r.stdout + r.stderr
    assert '"status": "pass"' in r.stdout


# ---------------------------------------------------------------------------
# Flight recorder (tentpole 4)
# ---------------------------------------------------------------------------

def test_flight_recorder_ring_and_dump(fresh):
    telemetry.event("alpha", k=1)
    with telemetry.span("beta"):
        pass
    path = xla_stats.flight_recorder.dump(reason="unit")
    assert path and os.path.basename(path).startswith(
        "flightrecorder-host")
    doc = json.load(open(path))
    assert doc["reason"] == "unit" and doc["pid"] == os.getpid()
    names = [e["name"] for e in doc["events"]]
    assert "alpha" in names and "beta" in names
    assert isinstance(doc["metrics"], dict)
    assert telemetry.counter("flightrecorder_dumps_total").value == 1


def test_flight_recorder_ring_is_bounded(fresh):
    rec = xla_stats.FlightRecorder(maxlen=16)
    for i in range(100):
        rec.record({"name": "e%d" % i})
    evs = rec.events()
    assert len(evs) == 16 and evs[-1]["name"] == "e99"


def test_flight_recorder_records_without_telemetry_dir():
    telemetry.configure(None)
    telemetry.reset()
    xla_stats.reset()
    try:
        telemetry.event("quiet.crash.context")
        names = [e["name"] for e in xla_stats.flight_recorder.events()]
        assert "quiet.crash.context" in names
        # but with no dir configured a dump has nowhere to go
        env_dir = os.environ.pop("MXNET_TELEMETRY_DIR", None)
        try:
            assert xla_stats.flight_recorder.dump(reason="x") is None
        finally:
            if env_dir is not None:
                os.environ["MXNET_TELEMETRY_DIR"] = env_dir
    finally:
        xla_stats.reset()
        telemetry.reset()


def test_fit_exception_dumps_flight_recorder(fresh):
    data = mx.sym.var("data")
    fc = mx.sym.FullyConnected(data, num_hidden=4, name="fc")
    net = mx.sym.SoftmaxOutput(fc, name="softmax")
    x = np.zeros((32, 10), dtype=np.float32)
    y = np.zeros(32, dtype=np.float32)
    it = mx.io.NDArrayIter(x, y, batch_size=16)
    mod = mx.mod.Module(net, context=mx.cpu())

    boom = mx.callback.Speedometer(16, frequent=1)

    def exploding_callback(param):
        raise RuntimeError("injected callback failure")

    with pytest.raises(RuntimeError, match="injected callback failure"):
        mod.fit(it, num_epoch=1, eval_metric="acc",
                batch_end_callback=[boom, exploding_callback])
    path = os.path.join(fresh, "flightrecorder-host%d.json"
                        % telemetry.host_id())
    doc = json.load(open(path))
    assert doc["reason"] == "fit_exception"
    assert "injected callback failure" in doc["error"]


# ---------------------------------------------------------------------------
# Acceptance: launched chaos-kill run leaves a parseable flight record
# whose last event precedes (is) the injected fault
# ---------------------------------------------------------------------------

FLIGHT_WORKER = r"""
import sys
import jax.numpy as jnp
from mxnet_tpu import telemetry
from mxnet_tpu.parallel import elastic

def step_fn(state, step):
    telemetry.event("worker.step", i=step)
    return {"w": state["w"] + 1.0}

t = elastic.ElasticTrainer(step_fn, {"w": jnp.zeros(2)},
                           dead_node_timeout=None)
t.run(10)   # chaos worker.death@3 fires at the 4th step boundary
print("UNREACHABLE", flush=True)
"""


@pytest.mark.launched
@pytest.mark.timeout(120)
def test_launched_chaos_kill_leaves_flight_record(tmp_path):
    from mxnet_tpu import chaos
    worker = tmp_path / "worker.py"
    worker.write_text(FLIGHT_WORKER)
    teldir = str(tmp_path / "telemetry")
    env = dict(os.environ, JAX_PLATFORMS="cpu", PALLAS_AXON_POOL_IPS="",
               PYTHONPATH=REPO, MXNET_TELEMETRY_DIR=teldir,
               MXNET_CHAOS="worker.death@3")
    p = subprocess.Popen([sys.executable, str(worker)], env=env,
                         stdout=subprocess.PIPE,
                         stderr=subprocess.STDOUT, text=True)
    out, _ = launchutil.communicate(p)
    assert p.returncode == chaos.DEAD_EXIT_CODE, out[-4000:]
    assert "UNREACHABLE" not in out

    path = os.path.join(teldir, "flightrecorder-host0.json")
    assert os.path.exists(path), os.listdir(teldir)
    doc = json.load(open(path))
    assert doc["reason"] == "chaos.worker.death"
    events = doc["events"]
    assert events, "flight record carries no events"
    # the ring's last entry IS the injected fault; everything else
    # precedes it, and only steps 0..2 ran before the step-4 boundary
    last = events[-1]
    assert last["name"] == "chaos.injection"
    assert last["args"]["site"] == "worker.death"
    steps = [e["args"]["i"] for e in events if e["name"] == "worker.step"]
    assert steps == [0, 1, 2]
    assert all(e["mono"] <= last["mono"] for e in events)
    assert doc["dumped_mono"] >= last["mono"]
    # the post-mortem carries the registry too
    assert "chaos_injections_total" in doc["metrics"]
