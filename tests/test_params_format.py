"""Reference .params binary-format compatibility
(src/ndarray/ndarray.cc:1596,1792): byte-level container layout,
roundtrips, npz back-compat."""
import struct

import numpy as np
import pytest

import mxnet_tpu as mx


def test_dict_roundtrip(tmp_path):
    path = str(tmp_path / "m.params")
    rng = np.random.RandomState(0)
    data = {"arg:fc_weight": mx.nd.array(rng.randn(4, 3).astype("f")),
            "arg:fc_bias": mx.nd.array(rng.randn(4).astype("f")),
            "aux:bn_mean": mx.nd.array(rng.randn(4).astype("float64"))}
    mx.nd.save(path, data)
    loaded = mx.nd.load(path)
    assert sorted(loaded) == sorted(data)
    for k in data:
        np.testing.assert_allclose(loaded[k].asnumpy(), data[k].asnumpy())
        assert loaded[k].dtype == data[k].dtype


def test_list_roundtrip(tmp_path):
    path = str(tmp_path / "l.params")
    arrs = [mx.nd.ones((2, 2)), mx.nd.zeros((3,))]
    mx.nd.save(path, arrs)
    loaded = mx.nd.load(path)
    assert isinstance(loaded, list) and len(loaded) == 2
    np.testing.assert_allclose(loaded[0].asnumpy(), 1.0)


def test_exact_container_bytes(tmp_path):
    """Byte-level check against the reference writer's layout."""
    path = str(tmp_path / "b.params")
    arr = mx.nd.array(np.arange(6, dtype="f").reshape(2, 3))
    mx.nd.save(path, {"w": arr})
    raw = open(path, "rb").read()
    # container header
    assert struct.unpack("<QQ", raw[:16]) == (0x112, 0)
    assert struct.unpack("<Q", raw[16:24]) == (1,)   # one array
    # ndarray record: V2 magic, stype 0, ndim 2, dims 2,3
    off = 24
    assert struct.unpack("<I", raw[off:off + 4])[0] == 0xF993FAC9
    assert struct.unpack("<i", raw[off + 4:off + 8])[0] == 0
    assert struct.unpack("<I", raw[off + 8:off + 12])[0] == 2
    assert struct.unpack("<qq", raw[off + 12:off + 28]) == (2, 3)
    # context cpu(0), dtype flag 0 (float32)
    assert struct.unpack("<iii", raw[off + 28:off + 40]) == (1, 0, 0)
    payload = np.frombuffer(raw[off + 40:off + 40 + 24], "f")
    np.testing.assert_allclose(payload, np.arange(6, dtype="f"))
    # names
    noff = off + 40 + 24
    assert struct.unpack("<Q", raw[noff:noff + 8]) == (1,)
    ln = struct.unpack("<Q", raw[noff + 8:noff + 16])[0]
    assert raw[noff + 16:noff + 16 + ln] == b"w"


def test_reads_reference_written_v1(tmp_path):
    """Hand-build a V1-record file as old MXNet would write it."""
    path = str(tmp_path / "v1.params")
    arr = np.arange(4, dtype="f")
    with open(path, "wb") as f:
        f.write(struct.pack("<QQ", 0x112, 0))
        f.write(struct.pack("<Q", 1))
        f.write(struct.pack("<I", 0xF993FAC8))        # V1: no stype
        f.write(struct.pack("<I", 1))                 # ndim
        f.write(struct.pack("<q", 4))
        f.write(struct.pack("<ii", 1, 0))             # cpu(0)
        f.write(struct.pack("<i", 0))                 # float32
        f.write(arr.tobytes())
        f.write(struct.pack("<Q", 0))                 # no names
    loaded = mx.nd.load(path)
    np.testing.assert_allclose(loaded[0].asnumpy(), arr)


def test_npz_backcompat(tmp_path):
    """Files written by the earlier npz container still load."""
    path = str(tmp_path / "old.params")
    with open(path, "wb") as f:
        np.savez(f, w=np.ones((2, 2), "f"))
    loaded = mx.nd.load(path)
    np.testing.assert_allclose(loaded["w"].asnumpy(), 1.0)


def test_checkpoint_uses_reference_format(tmp_path):
    net = mx.sym.FullyConnected(mx.sym.Variable("data"), num_hidden=3,
                                name="ckfc")
    mod = mx.mod.Module(net, label_names=None)
    mod.bind([mx.io.DataDesc("data", (2, 5))], None)
    mod.init_params()
    prefix = str(tmp_path / "ckpt")
    mod.save_checkpoint(prefix, 1)
    raw = open(prefix + "-0001.params", "rb").read()
    assert struct.unpack("<Q", raw[:8])[0] == 0x112
    sym, arg, aux = mx.model.load_checkpoint(prefix, 1)
    assert "ckfc_weight" in arg


def test_unrepresentable_values_rejected(tmp_path):
    path = str(tmp_path / "bad.params")
    with pytest.raises(mx.MXNetError, match="0-dim"):
        mx.nd.save(path, [mx.nd.array(np.float32(1.0).reshape(()))])
    with pytest.raises(mx.MXNetError, match="bool"):
        mx.nd.save(path, [mx.nd.array(np.ones((2,), bool))])


def test_reference_style_symbol_json_loads():
    """JSON exactly as MXNet 1.2.1 serializes it (string attrs,
    node_row_ptr, heads) must load and bind (legacy_json_util parity)."""
    import json
    ref_json = json.dumps({
        "nodes": [
            {"op": "null", "name": "data", "inputs": []},
            {"op": "null", "name": "fc1_weight", "inputs": []},
            {"op": "null", "name": "fc1_bias", "inputs": []},
            {"op": "FullyConnected", "name": "fc1",
             "attrs": {"num_hidden": "8", "no_bias": "False"},
             "inputs": [[0, 0, 0], [1, 0, 0], [2, 0, 0]]},
            {"op": "Activation", "name": "relu1",
             "attrs": {"act_type": "relu"}, "inputs": [[3, 0, 0]]},
        ],
        "arg_nodes": [0, 1, 2],
        "node_row_ptr": [0, 1, 2, 3, 4, 5],
        "heads": [[4, 0, 0]],
        "attrs": {"mxnet_version": ["int", 10201]},
    })
    s = mx.sym.load_json(ref_json)
    assert s.list_arguments() == ["data", "fc1_weight", "fc1_bias"]
    ex = s.simple_bind(mx.cpu(), data=(2, 5))
    out = ex.forward()
    assert out[0].shape == (2, 8)
    # our own tojson emits the same container keys
    import json as _json
    j = _json.loads(s.tojson())
    assert {"nodes", "arg_nodes", "heads"} <= set(j.keys())
