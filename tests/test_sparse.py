"""Sparse NDArray suite (reference tests/python/unittest/
test_sparse_ndarray.py + test_sparse_operator.py): storage conversions,
sparse dot, retain, kvstore row-sparse flows, sparse optimizer ops."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.ndarray import sparse


DENSE = np.array([[0, 1, 0], [2, 0, 3], [0, 0, 0], [4, 0, 0]], "f")


def test_csr_creation_and_fields():
    csr = sparse.csr_matrix(DENSE)
    assert csr.stype == "csr"
    np.testing.assert_allclose(csr.todense().asnumpy(), DENSE)
    # scipy-style CSR fields
    np.testing.assert_allclose(csr.indptr.asnumpy(), [0, 1, 3, 3, 4])
    np.testing.assert_allclose(csr.indices.asnumpy(), [1, 0, 2, 0])
    np.testing.assert_allclose(csr.data.asnumpy(), [1, 2, 3, 4])


def test_row_sparse_creation_and_retain():
    rs = sparse.row_sparse_array(DENSE)
    assert rs.stype == "row_sparse"
    np.testing.assert_allclose(rs.indices.asnumpy(), [0, 1, 3])
    np.testing.assert_allclose(rs.todense().asnumpy(), DENSE)
    kept = rs.retain(mx.nd.array(np.array([1], "f")))
    out = kept.todense().asnumpy()
    np.testing.assert_allclose(out[1], DENSE[1])
    np.testing.assert_allclose(out[0], 0)


def test_cast_storage_roundtrip():
    dn = mx.nd.array(DENSE)
    for stype in ("csr", "row_sparse"):
        sp = sparse.cast_storage(dn, stype)
        assert sp.stype == stype
        back = sparse.cast_storage(sp, "default")
        np.testing.assert_allclose(back.asnumpy(), DENSE)


def test_sparse_dot():
    csr = sparse.csr_matrix(DENSE)
    rhs = np.random.RandomState(0).rand(3, 5).astype("f")
    out = sparse.dot(csr, mx.nd.array(rhs))
    np.testing.assert_allclose(out.asnumpy(), DENSE.dot(rhs), rtol=1e-5)


def test_sparse_zeros_and_tostype():
    z = sparse.zeros("row_sparse", (3, 4))
    assert z.stype == "row_sparse"
    np.testing.assert_allclose(z.todense().asnumpy(), 0)
    dn = mx.nd.array(DENSE)
    assert dn.tostype("csr").stype == "csr"
    assert dn.tostype("default") is dn or \
        np.allclose(dn.tostype("default").asnumpy(), DENSE)


def test_kvstore_rowsparse_push_and_pull():
    kv = mx.kv.create("local")
    kv.init("emb", mx.nd.zeros((4, 3)))
    kv.push("emb", sparse.row_sparse_array(DENSE))
    out = mx.nd.zeros((4, 3))
    kv.pull("emb", out=out)
    np.testing.assert_allclose(out.asnumpy(), DENSE)
    rid = mx.nd.array(np.array([1, 3], "f"))
    kv.row_sparse_pull("emb", out=out, row_ids=rid)
    got = out.asnumpy()
    np.testing.assert_allclose(got[1], DENSE[1])
    np.testing.assert_allclose(got[0], 0)


def test_embedding_grad_touches_only_used_rows():
    """The reference's row-sparse gradient semantics: rows not indexed
    get zero gradient (so sparse optimizers can skip them)."""
    w = mx.nd.array(np.random.RandomState(1).rand(10, 4).astype("f"))
    w.attach_grad()
    idx = mx.nd.array(np.array([2.0, 5.0, 2.0], "f"))
    with mx.autograd.record():
        out = mx.nd.Embedding(idx, w, input_dim=10, output_dim=4)
        loss = out.sum()
    loss.backward()
    g = w.grad.asnumpy()
    assert np.abs(g[2]).sum() > 0 and np.abs(g[5]).sum() > 0
    untouched = [i for i in range(10) if i not in (2, 5)]
    np.testing.assert_allclose(g[untouched], 0.0)
    # row 2 used twice accumulates
    np.testing.assert_allclose(g[2], 2.0)


def test_sparse_sgd_semantics():
    """lazy_update SGD: zero-grad rows keep their momentum untouched via
    the sparse adagrad/sgd row-skip convention."""
    w = mx.nd.ones((3, 2))
    g = mx.nd.array(np.array([[1, 1], [0, 0], [1, 1]], "f"))
    h = mx.nd.zeros((3, 2))
    new_w = mx.nd.sparse_adagrad_update(w, g, h, lr=0.5)
    nw = new_w.asnumpy()
    np.testing.assert_allclose(nw[1], 1.0)   # untouched row
    assert (nw[0] < 1.0).all() and (nw[2] < 1.0).all()
