"""Sparse NDArray suite (reference tests/python/unittest/
test_sparse_ndarray.py + test_sparse_operator.py): storage conversions,
sparse dot, retain, kvstore row-sparse flows, sparse optimizer ops."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.ndarray import sparse


DENSE = np.array([[0, 1, 0], [2, 0, 3], [0, 0, 0], [4, 0, 0]], "f")


def test_csr_creation_and_fields():
    csr = sparse.csr_matrix(DENSE)
    assert csr.stype == "csr"
    np.testing.assert_allclose(csr.todense().asnumpy(), DENSE)
    # scipy-style CSR fields
    np.testing.assert_allclose(csr.indptr.asnumpy(), [0, 1, 3, 3, 4])
    np.testing.assert_allclose(csr.indices.asnumpy(), [1, 0, 2, 0])
    np.testing.assert_allclose(csr.data.asnumpy(), [1, 2, 3, 4])


def test_row_sparse_creation_and_retain():
    rs = sparse.row_sparse_array(DENSE)
    assert rs.stype == "row_sparse"
    np.testing.assert_allclose(rs.indices.asnumpy(), [0, 1, 3])
    np.testing.assert_allclose(rs.todense().asnumpy(), DENSE)
    kept = rs.retain(mx.nd.array(np.array([1], "f")))
    out = kept.todense().asnumpy()
    np.testing.assert_allclose(out[1], DENSE[1])
    np.testing.assert_allclose(out[0], 0)


def test_cast_storage_roundtrip():
    dn = mx.nd.array(DENSE)
    for stype in ("csr", "row_sparse"):
        sp = sparse.cast_storage(dn, stype)
        assert sp.stype == stype
        back = sparse.cast_storage(sp, "default")
        np.testing.assert_allclose(back.asnumpy(), DENSE)


def test_sparse_dot():
    csr = sparse.csr_matrix(DENSE)
    rhs = np.random.RandomState(0).rand(3, 5).astype("f")
    out = sparse.dot(csr, mx.nd.array(rhs))
    np.testing.assert_allclose(out.asnumpy(), DENSE.dot(rhs), rtol=1e-5)


def test_sparse_zeros_and_tostype():
    z = sparse.zeros("row_sparse", (3, 4))
    assert z.stype == "row_sparse"
    np.testing.assert_allclose(z.todense().asnumpy(), 0)
    dn = mx.nd.array(DENSE)
    assert dn.tostype("csr").stype == "csr"
    assert dn.tostype("default") is dn or \
        np.allclose(dn.tostype("default").asnumpy(), DENSE)


def test_kvstore_rowsparse_push_and_pull():
    kv = mx.kv.create("local")
    kv.init("emb", mx.nd.zeros((4, 3)))
    kv.push("emb", sparse.row_sparse_array(DENSE))
    out = mx.nd.zeros((4, 3))
    kv.pull("emb", out=out)
    np.testing.assert_allclose(out.asnumpy(), DENSE)
    rid = mx.nd.array(np.array([1, 3], "f"))
    kv.row_sparse_pull("emb", out=out, row_ids=rid)
    got = out.asnumpy()
    np.testing.assert_allclose(got[1], DENSE[1])
    np.testing.assert_allclose(got[0], 0)


def test_embedding_grad_touches_only_used_rows():
    """The reference's row-sparse gradient semantics: rows not indexed
    get zero gradient (so sparse optimizers can skip them)."""
    w = mx.nd.array(np.random.RandomState(1).rand(10, 4).astype("f"))
    w.attach_grad()
    idx = mx.nd.array(np.array([2.0, 5.0, 2.0], "f"))
    with mx.autograd.record():
        out = mx.nd.Embedding(idx, w, input_dim=10, output_dim=4)
        loss = out.sum()
    loss.backward()
    g = w.grad.asnumpy()
    assert np.abs(g[2]).sum() > 0 and np.abs(g[5]).sum() > 0
    untouched = [i for i in range(10) if i not in (2, 5)]
    np.testing.assert_allclose(g[untouched], 0.0)
    # row 2 used twice accumulates
    np.testing.assert_allclose(g[2], 2.0)


def test_sparse_sgd_semantics():
    """lazy_update SGD: zero-grad rows keep their momentum untouched via
    the sparse adagrad/sgd row-skip convention."""
    w = mx.nd.ones((3, 2))
    g = mx.nd.array(np.array([[1, 1], [0, 0], [1, 1]], "f"))
    h = mx.nd.zeros((3, 2))
    new_w = mx.nd.sparse_adagrad_update(w, g, h, lr=0.5)
    nw = new_w.asnumpy()
    np.testing.assert_allclose(nw[1], 1.0)   # untouched row
    assert (nw[0] < 1.0).all() and (nw[2] < 1.0).all()


# ---------------------------------------------------------------------------
# Compact-storage economics (round-2 verdict #3): memory and update cost
# must scale with nnz, not the dense shape.
# ---------------------------------------------------------------------------

def test_compact_storage_never_densifies():
    """A (1M, 64) row-sparse array with 8 live rows stores 8 rows."""
    rows = 1_000_000
    vals = np.ones((8, 64), "f")
    idx = np.array([3, 77, 1000, 5000, 99999, 500000, 700000, 999999])
    rs = sparse.row_sparse_array((vals, idx), shape=(rows, 64))
    assert rs.has_compact() and rs.nnz == 8
    assert rs._dense is None  # no dense buffer was ever allocated
    kept = rs.retain(mx.nd.array(np.array([77.0, 500000.0], "f")))
    assert kept.nnz == 2 and kept._dense is None
    np.testing.assert_allclose(kept.data.asnumpy(), np.ones((2, 64)))
    # csr <-> rs conversions stay compact too
    z = sparse.zeros("row_sparse", (rows, 64))
    assert z.nnz == 0 and z._dense is None


def test_sparse_dot_stays_compact():
    csr = sparse.csr_matrix(DENSE)
    assert csr.has_compact()
    rhs = np.random.RandomState(0).rand(3, 5).astype("f")
    out = sparse.dot(csr, mx.nd.array(rhs))
    np.testing.assert_allclose(out.asnumpy(), DENSE.dot(rhs), rtol=1e-5)
    assert csr._dense is None  # the O(nnz) path never densified the lhs


def test_sparse_sgd_update_cost_scales_with_nnz():
    """The compiled sparse-update program's operand shapes are O(nnz): the
    jit cache key buckets on padded nnz, and a 1M-row weight update with
    nnz=8 compiles a bucket-8 program, not a 1M-row one."""
    from mxnet_tpu import optimizer as opt_mod
    rows = 1_000_000
    w = mx.nd.ones((rows, 4))
    vals = np.full((8, 4), 2.0, "f")
    idx = np.array([0, 5, 100, 1000, 65536, 99999, 500000, 999999])
    g = sparse.row_sparse_array((vals, idx), shape=(rows, 4))
    opt = opt_mod.SGD(learning_rate=0.5, momentum=0.9, rescale_grad=1.0)
    state = opt.create_state(0, w)
    opt_mod._SPARSE_ROW_JIT.clear()
    opt.update(0, w, g, state)
    keys = list(opt_mod._SPARSE_ROW_JIT)
    assert len(keys) == 1
    kind, shape, dtype, bucket, _ = keys[0]
    assert kind == "sgd_mom" and bucket == 8  # operand rows = nnz, not 1M
    out = w.asnumpy()
    np.testing.assert_allclose(out[idx], 1.0 - 0.5 * 2.0)  # touched rows
    untouched = [1, 4, 99, 12345, 999998]
    np.testing.assert_allclose(out[untouched], 1.0)
    # momentum state touched only on live rows
    st = state.asnumpy()
    np.testing.assert_allclose(st[idx], 2.0)
    np.testing.assert_allclose(st[untouched], 0.0)


def test_sparse_adam_matches_dense_on_live_rows():
    from mxnet_tpu import optimizer as opt_mod
    rng = np.random.RandomState(0)
    wv = rng.rand(50, 3).astype("f")
    gv = np.zeros((50, 3), "f")
    live = np.array([2, 7, 31])
    gv[live] = rng.rand(3, 3)

    # dense reference
    wd_ = mx.nd.array(wv)
    opt_d = opt_mod.Adam(learning_rate=0.1, rescale_grad=1.0,
                         lazy_update=False)
    st_d = opt_d.create_state(0, wd_)
    opt_d.update(0, wd_, mx.nd.array(gv), st_d)

    # sparse lazy path
    ws = mx.nd.array(wv)
    opt_s = opt_mod.Adam(learning_rate=0.1, rescale_grad=1.0)
    st_s = opt_s.create_state(0, ws)
    g_rs = sparse.row_sparse_array((gv[live], live), shape=(50, 3))
    opt_s.update(0, ws, g_rs, st_s)

    np.testing.assert_allclose(ws.asnumpy()[live], wd_.asnumpy()[live],
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(ws.asnumpy()[~np.isin(np.arange(50), live)],
                               wv[~np.isin(np.arange(50), live)])


def test_rowsparse_pull_moves_compact_payload():
    kv = mx.kv.create("local")
    big = np.zeros((10000, 16), "f")
    big[7] = 1.0
    big[42] = 2.0
    big[9999] = 3.0
    kv.init("emb", mx.nd.array(big))
    out = sparse.zeros("row_sparse", (10000, 16))
    rid = mx.nd.array(np.array([7, 9999], "f"))
    kv.row_sparse_pull("emb", out=out, row_ids=rid)
    assert out.has_compact() and out.nnz == 2  # only live rows moved
    assert out._dense is None
    np.testing.assert_allclose(out.data.asnumpy()[0], 1.0)
    np.testing.assert_allclose(out.data.asnumpy()[1], 3.0)


def test_push_merges_compact_rowsparse():
    kv = mx.kv.create("local")
    kv.init("w", mx.nd.zeros((100, 2)))
    a = sparse.row_sparse_array((np.ones((2, 2), "f"), np.array([1, 50])),
                                shape=(100, 2))
    b = sparse.row_sparse_array((np.ones((2, 2), "f"), np.array([50, 99])),
                                shape=(100, 2))
    kv.push("w", [a, b])
    out = mx.nd.zeros((100, 2))
    kv.pull("w", out=out)
    got = out.asnumpy()
    np.testing.assert_allclose(got[1], 1.0)
    np.testing.assert_allclose(got[50], 2.0)  # duplicate rows summed
    np.testing.assert_allclose(got[99], 1.0)
    np.testing.assert_allclose(got[0], 0.0)


def test_cast_storage_rs_csr_compact():
    rs = sparse.row_sparse_array(DENSE)
    csr = sparse.cast_storage(rs, "csr")
    np.testing.assert_allclose(csr.indptr.asnumpy(), [0, 1, 3, 3, 4])
    np.testing.assert_allclose(csr.indices.asnumpy(), [1, 0, 2, 0])
    np.testing.assert_allclose(csr.data.asnumpy(), [1, 2, 3, 4])
    back = sparse.cast_storage(csr, "row_sparse")
    np.testing.assert_allclose(back.indices.asnumpy(), [0, 1, 3])
    np.testing.assert_allclose(back.todense().asnumpy(), DENSE)


def test_dense_mutation_invalidates_compact():
    rs = sparse.row_sparse_array(DENSE)
    rs[:] = np.ones_like(DENSE)
    np.testing.assert_allclose(rs.todense().asnumpy(), 1.0)
    # compact form recomputed from the mutated dense, vectorized
    assert rs.nnz == 4
    np.testing.assert_allclose(rs.indices.asnumpy(), [0, 1, 2, 3])


def test_cast_storage_rs_csr_unsorted_indices():
    """Stored rows in arbitrary index order must land at their dense row
    ids in CSR (review r3 finding)."""
    vals = np.array([[1, 1], [2, 2]], "f")
    rs = sparse.row_sparse_array((vals, np.array([3, 0])), shape=(5, 2))
    csr = sparse.cast_storage(rs, "csr")
    np.testing.assert_allclose(csr.todense().asnumpy(),
                               rs.todense().asnumpy())
    np.testing.assert_allclose(csr.todense().asnumpy()[0], 2.0)
    np.testing.assert_allclose(csr.todense().asnumpy()[3], 1.0)


def test_sparse_dot_differentiable_under_record():
    """Under autograd.record() sparse.dot must produce real gradients
    (the compact fast path bypasses the tape, so recording falls back to
    the op dispatcher)."""
    csr = sparse.csr_matrix(DENSE)
    rhs = mx.nd.array(np.random.RandomState(0).rand(3, 5).astype("f"))
    rhs.attach_grad()
    with mx.autograd.record():
        out = sparse.dot(csr, rhs)
        loss = out.sum()
    loss.backward()
    g = rhs.grad.asnumpy()
    # d(sum(A@R))/dR = A^T @ ones
    want = DENSE.T.dot(np.ones((4, 5), "f"))
    np.testing.assert_allclose(g, want, rtol=1e-5)


def test_sparse_dot_under_record_never_densifies(monkeypatch):
    """Training-path economics (reference dot-inl.h FComputeEx fwd :1032 +
    bwd :1074): under record, forward AND backward must run over the
    compact payload — densifying the CSR lhs anywhere raises here."""
    csr = sparse.csr_matrix(DENSE)

    def boom(self):
        raise AssertionError("CSR lhs was densified")

    monkeypatch.setattr(sparse.CSRNDArray, "_materialize", boom)

    rhs = mx.nd.array(np.random.RandomState(1).rand(3, 5).astype("f"))
    rhs.attach_grad()
    with mx.autograd.record():
        out = sparse.dot(csr, rhs)
        loss = out.sum()
    loss.backward()
    np.testing.assert_allclose(rhs.grad.asnumpy(),
                               DENSE.T.dot(np.ones((4, 5), "f")), rtol=1e-5)

    # csr.T x dense: same economics, transposed
    rhs_t = mx.nd.array(np.random.RandomState(2).rand(4, 5).astype("f"))
    rhs_t.attach_grad()
    with mx.autograd.record():
        out = sparse.dot(csr, rhs_t, transpose_a=True)
        loss = (out * out).sum()
    loss.backward()
    out_np = DENSE.T.dot(rhs_t.asnumpy())
    want = DENSE.dot(2 * out_np)
    np.testing.assert_allclose(rhs_t.grad.asnumpy(), want, rtol=1e-5)
