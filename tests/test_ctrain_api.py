"""MXT* train C ABI: a C++ host process trains a model end-to-end and
its loss curve matches the Python Module path exactly.

Reference parity: cpp-package/example/lenet.cpp trains over the C API
(include/mxnet/c_api.h); here cpp-package/example/mlp_train.cpp drives
src/c_train_api.cc, which delegates to the SAME Module._step program
Python uses — so parity is byte-marshalling plus determinism, verified
against a same-seed Python run.
"""
import os
import re
import subprocess
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SRC = os.path.join(REPO, "src")
BIN = os.path.join(REPO, "cpp-package", "example", "mlp_train")

N, D, CLASSES, EPOCHS, BATCH = 512, 16, 10, 8, 64


def _symbol_json():
    import mxnet_tpu as mx
    d = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(d, num_hidden=64, name="fc1")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.FullyConnected(net, num_hidden=CLASSES, name="fc2")
    return mx.sym.SoftmaxOutput(net, name="softmax")


def _data():
    rng = np.random.RandomState(3)
    centers = rng.randn(CLASSES, D) * 3.0
    y = rng.randint(0, CLASSES, N)
    x = centers[y] + rng.randn(N, D) * 0.6
    return x.astype(np.float32), y.astype(np.float32)


def _python_curve(sym, x, y):
    """Same training loop through the Python Module path, same seed."""
    import mxnet_tpu as mx
    mod = mx.mod.Module(sym)
    mod.bind(data_shapes=[("data", (BATCH, D))],
             label_shapes=[("softmax_label", (BATCH,))])
    mx.random.seed(7)  # same point CTrainer.init_params seeds
    np.random.seed(7)  # initializers draw from the numpy global RNG
    mod.init_params(initializer=mx.init.Xavier())
    mod.init_optimizer(optimizer="sgd",
                       optimizer_params={"learning_rate": 0.1,
                                         "momentum": 0.9})
    losses = []
    from mxnet_tpu.io import DataBatch
    for _ in range(EPOCHS):
        total = 0.0
        for b in range(N // BATCH):
            xb = x[b * BATCH:(b + 1) * BATCH]
            yb = y[b * BATCH:(b + 1) * BATCH]
            mod._step(DataBatch(data=[mx.nd.array(xb)],
                                label=[mx.nd.array(yb)]))
            probs = mod.get_outputs()[0].asnumpy()
            p = probs[np.arange(BATCH), yb.astype(int)]
            total += float(-np.log(np.maximum(p, 1e-12)).sum())
        losses.append(total / N)
    return losses


def test_cpp_trains_to_95pct_and_matches_python(tmp_path):
    build = subprocess.run(["make", "-C", SRC, "cpp_example"],
                           capture_output=True, text=True)
    assert build.returncode == 0, build.stderr

    import mxnet_tpu as mx
    sym = _symbol_json()
    x, y = _data()
    sym_path = str(tmp_path / "mlp-symbol.json")
    sym.save(sym_path)
    data_path = str(tmp_path / "data.bin")
    with open(data_path, "wb") as f:
        f.write(x.tobytes())
        f.write(y.tobytes())

    env = dict(os.environ)
    env["PYTHONPATH"] = os.pathsep.join([REPO] + sys.path)
    env.setdefault("JAX_PLATFORMS", "cpu")
    env["PALLAS_AXON_POOL_IPS"] = ""
    run = subprocess.run(
        [BIN, sym_path, data_path, str(N), str(D), str(CLASSES),
         str(EPOCHS), str(BATCH), "1"],
        capture_output=True, text=True, env=env, timeout=600)
    assert run.returncode == 0, run.stdout + run.stderr[-2000:]
    assert "FINAL acc" in run.stdout
    final = float(re.search(r"FINAL acc ([\d.]+)", run.stdout).group(1))
    assert final > 0.95, run.stdout

    cpp_losses = [float(m) for m in
                  re.findall(r"epoch \d+ loss ([\d.]+)", run.stdout)]
    assert len(cpp_losses) == EPOCHS
    # loss must actually go down (training happened)
    assert cpp_losses[-1] < cpp_losses[0] * 0.5

    py_losses = _python_curve(sym, x, y)
    np.testing.assert_allclose(cpp_losses, py_losses, rtol=1e-4,
                               atol=1e-5)


def test_cpp_checkpoint_roundtrip(tmp_path):
    """SaveCheckpoint from the C ABI writes a Python-loadable .params."""
    pytest.importorskip("mxnet_tpu")
    import mxnet_tpu as mx
    from mxnet_tpu.ctrain import CTrainer

    sym = _symbol_json()
    x, y = _data()
    tr = CTrainer(sym.tojson(), 1, 0, ["data"], ["softmax_label"])
    tr.bind(["data", "softmax_label"], [(BATCH, D), (BATCH,)])
    tr.init_params("xavier", 7)
    tr.init_optimizer("sgd", {"learning_rate": "0.1"})
    tr.step(["data", "softmax_label"],
            [x[:BATCH].tobytes(), y[:BATCH].tobytes()])
    prefix = str(tmp_path / "model")
    tr.save_checkpoint(prefix, 1)
    params = mx.nd.load(prefix + "-0001.params")
    assert any(k.endswith("fc1_weight") for k in params)

    # and load back through the C-ABI helper path
    tr2 = CTrainer(sym.tojson(), 1, 0, ["data"], ["softmax_label"])
    tr2.bind(["data", "softmax_label"], [(BATCH, D), (BATCH,)])
    tr2.init_params("zeros", 0)
    tr2.load_params(prefix + "-0001.params")
    tr2.forward(["data"], [x[:BATCH].tobytes()])
    tr.forward(["data"], [x[:BATCH].tobytes()])
    np.testing.assert_allclose(
        np.frombuffer(tr2.output_bytes(0), np.float32),
        np.frombuffer(tr.output_bytes(0), np.float32), rtol=1e-5)
