"""Test configuration: run the suite on a virtual 8-device CPU mesh.

Mirrors the reference CI trick of testing distributed semantics on one
machine (`ci/docker/runtime_functions.sh:551`): multi-chip sharding tests
use --xla_force_host_platform_device_count=8 host devices.

Must run before jax initializes any backend: forces the cpu platform and
drops the axon TPU plugin registration (tests never touch the real chip).
"""
import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

try:
    # sitecustomize may have imported jax already (axon TPU plugin), so the
    # env var alone is too late — update the live config before any backend
    # initializes.
    import jax
    jax.config.update("jax_platforms", "cpu")
    from jax._src import xla_bridge as _xb
    _xb._backend_factories.pop("axon", None)
except Exception:
    pass

import numpy as np
import pytest


@pytest.fixture(autouse=True)
def _seed():
    np.random.seed(0)
    import mxnet_tpu as mx
    mx.random.seed(0)
    yield
