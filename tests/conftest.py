"""Test configuration: run the suite on a virtual 8-device CPU mesh.

Mirrors the reference CI trick of testing distributed semantics on one
machine (`ci/docker/runtime_functions.sh:551`): multi-chip sharding tests
use --xla_force_host_platform_device_count=8 host devices.

Must run before jax initializes any backend: forces the cpu platform and
drops the axon TPU plugin registration (tests never touch the real chip).

On-TPU lane (the reference's GPU re-run pattern,
tests/python/gpu/test_operator_gpu.py): set ``MXNET_TEST_TPU=1`` to keep
the real accelerator visible and run the ``tpu``-marked smoke tests:

    MXNET_TEST_TPU=1 python -m pytest tests/ -m tpu -q

Without the env var, ``tpu``-marked tests are skipped and everything else
runs on the virtual CPU mesh as before. The TPU lane assumes sole ownership
of the (single-client) chip — stop other TPU processes first.
"""
import os

TPU_LANE = os.environ.get("MXNET_TEST_TPU", "") == "1"

if not TPU_LANE:
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    if "host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()

    try:
        # sitecustomize may have imported jax already (axon TPU plugin), so
        # the env var alone is too late — update the live config before any
        # backend initializes.
        import jax
        jax.config.update("jax_platforms", "cpu")
        from jax._src import xla_bridge as _xb
        _xb._backend_factories.pop("axon", None)
    except Exception:
        pass

import numpy as np
import pytest


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "tpu: smoke tests that run on the real TPU chip "
        "(enabled with MXNET_TEST_TPU=1, select with -m tpu)")
    config.addinivalue_line(
        "markers", "launched: spawns multi-process worker subprocesses "
        "(coordinator/PS/elastic tests); all subprocess waits go through "
        "tests/launchutil.py with explicit timeouts so a hung coordinator "
        "can never wedge the tier-1 lane; deselect with -m 'not launched'")
    config.addinivalue_line(
        "markers", "timeout(seconds): documented wall-clock budget of a "
        "launched test; enforcement is the subprocess timeouts inside "
        "(tests/launchutil.py), not a runner plugin")


def pytest_collection_modifyitems(config, items):
    if TPU_LANE:
        return
    skip_tpu = pytest.mark.skip(
        reason="real-TPU lane disabled (set MXNET_TEST_TPU=1 and run -m tpu)")
    for item in items:
        if "tpu" in item.keywords:
            item.add_marker(skip_tpu)


@pytest.fixture(autouse=True)
def _seed():
    np.random.seed(0)
    import mxnet_tpu as mx
    mx.random.seed(0)
    yield


@pytest.fixture(autouse=True)
def _chaos_disarm():
    """No chaos trigger armed in one test may leak into the next."""
    yield
    from mxnet_tpu import chaos
    chaos.clear()
