"""Examples smoke tests (tiny shapes, CPU) — each BASELINE.json config's
script must run end-to-end and learn on its synthetic data."""
import os
import subprocess
import sys

import pytest

EXAMPLES = os.path.join(os.path.dirname(__file__), "..", "examples")


def _run(rel, *args, timeout=420):
    env = dict(os.environ,
               PYTHONPATH=os.path.join(EXAMPLES, ".."))
    return subprocess.run(
        [sys.executable, os.path.join(EXAMPLES, rel)] + list(args),
        capture_output=True, text=True, env=env, timeout=timeout)


def test_train_mnist_mlp():
    r = _run("image-classification/train_mnist.py", "--num-epochs", "4",
             "--num-examples", "600", "--batch-size", "50")
    assert r.returncode == 0, r.stderr[-2000:]
    assert "final validation" in r.stdout


def test_word_lm():
    r = _run("rnn/word_lm/train.py", "--num-epochs", "1",
             "--max-sentences", "300", "--batch-size", "25",
             "--num-hidden", "32", "--num-embed", "16",
             "--data", "/nonexistent")
    assert r.returncode == 0, r.stderr[-2000:]
    assert "final train perplexity" in r.stdout


def test_ssd():
    r = _run("ssd/train_ssd.py", "--num-batches", "30", "--batch-size", "8")
    assert r.returncode == 0, r.stderr[-2000:]
    assert "detections kept after NMS" in r.stdout


def test_factorization_machine():
    r = _run("sparse/factorization_machine/train.py", "--num-epochs", "15",
             "--num-examples", "2400", "--num-features", "200",
             "--lr", "0.01")
    assert r.returncode == 0, r.stderr[-2000:]
    acc = float(r.stdout.strip().split()[-1])
    assert acc > 0.6, r.stdout


def test_wide_deep():
    r = _run("sparse/wide_deep/train.py", "--num-epochs", "6",
             "--num-examples", "1200", "--num-sparse", "400")
    assert r.returncode == 0, r.stderr[-2000:]
    acc = float(r.stdout.strip().split()[-1])
    assert acc > 0.7, r.stdout


def test_model_parallel_lstm():
    r = _run("model-parallel/lstm_sharded.py", "--steps", "3",
             "--seq-len", "8", "--batch-size", "2", "--num-hidden", "32")
    assert r.returncode == 0, r.stderr[-2000:]
    assert "sharded LSTM train OK" in r.stdout


def test_model_parallel_lstm_group2ctx():
    """Reference example/model-parallel/lstm pattern: per-layer ctx_group
    + Module(group2ctxs=...) on distinct virtual devices."""
    r = _run("model-parallel/lstm_group2ctx.py", "--num-epoch", "2",
             "--samples", "128", "--seq-len", "6", "--num-hidden", "24")
    assert r.returncode == 0, r.stderr[-2000:]
    assert "next-token accuracy" in r.stdout
    assert "TFRT_CPU_1" in r.stdout  # layer 1 really lives elsewhere


def test_gluon_resnet_tiny():
    r = _run("gluon/train_resnet50.py", "--model", "resnet18_v1",
             "--batch-size", "2", "--image-size", "32",
             "--num-classes", "10", "--num-batches", "2", "--ctx", "cpu")
    assert r.returncode == 0, r.stderr[-2000:]
    assert "img/s" in r.stdout


def test_pipeline_mlp():
    r = _run("model-parallel/pipeline_mlp.py", "--steps", "10",
             "--micro-batches", "4", "--micro-size", "2", "--hidden", "8")
    assert r.returncode == 0, r.stderr[-2000:]
    assert "pipeline training OK" in r.stdout


def test_moe_example():
    r = _run("moe/train_moe.py", "--steps", "10", "--tokens", "32",
             "--dim", "8")
    assert r.returncode == 0, r.stderr[-2000:]
    assert "MoE training OK" in r.stdout


def test_faster_rcnn():
    r = _run("rcnn/train_faster_rcnn.py", "--num-steps", "20")
    assert r.returncode == 0, r.stderr[-2000:]
    assert "FASTER-RCNN FLOW OK" in r.stdout


def test_deformable_rcnn():
    r = _run("rcnn/train_faster_rcnn.py", "--num-steps", "15",
             "--deformable")
    assert r.returncode == 0, r.stderr[-2000:]
    assert "FASTER-RCNN FLOW OK" in r.stdout


def test_faster_rcnn_ohem():
    """Hardest-first ROI sampling (round 5; the reference LOG(FATAL)s
    on ohem=True — proposal_target-inl.h:133)."""
    r = _run("rcnn/train_faster_rcnn.py", "--num-steps", "15", "--ohem")
    assert r.returncode == 0, r.stderr[-2000:]
    assert "FASTER-RCNN FLOW OK" in r.stdout


def test_faster_rcnn_ohem_deformable():
    """OHEM scoring must ride the SAME pooling path the deformable head
    trains on (a separate ROIPooling scoring pass pinned the deferred
    Dense to the wrong width — review-caught crash)."""
    r = _run("rcnn/train_faster_rcnn.py", "--num-steps", "10", "--ohem",
             "--deformable")
    assert r.returncode == 0, r.stderr[-2000:]
    assert "FASTER-RCNN FLOW OK" in r.stdout


def test_adversary_fgsm():
    r = _run("adversary/fgsm_mnist.py", "--num-examples", "600",
             "--num-epochs", "3")
    assert r.returncode == 0, r.stderr[-2000:]
    assert "adversarial accuracy" in r.stdout


def test_autoencoder():
    r = _run("autoencoder/train_autoencoder.py", "--num-examples", "600",
             "--num-epochs", "12")
    assert r.returncode == 0, r.stderr[-2000:]
    assert "final reconstruction loss" in r.stdout


def test_gan():
    r = _run("gan/train_gan.py", "--num-iters", "250")
    assert r.returncode == 0, r.stderr[-2000:]
    assert "sample mean" in r.stdout


def test_multitask():
    r = _run("multi-task/train_multitask.py", "--num-examples", "800",
             "--num-epochs", "5")
    assert r.returncode == 0, r.stderr[-2000:]
    assert "parity accuracy" in r.stdout


def test_svm_mnist():
    r = _run("svm_mnist/train_svm.py", "--num-examples", "800",
             "--num-epochs", "6")
    assert r.returncode == 0, r.stderr[-2000:]
    assert "final svm accuracy" in r.stdout


def test_long_context_ring_lm():
    r = _run("long-context/train_long_lm.py", "--seq-len", "256",
             "--steps", "20", "--dim", "32", "--layers", "1")
    assert r.returncode == 0, r.stderr[-2000:]
    assert "LONG-CONTEXT TRAINING OK" in r.stdout


def test_cnn_text_classification():
    r = _run("cnn_text_classification/train_cnn_text.py",
             "--num-examples", "1000", "--num-epochs", "4")
    assert r.returncode == 0, r.stderr[-2000:]
    assert "final text-cnn accuracy" in r.stdout


def test_recommender_mf():
    r = _run("recommenders/train_mf.py")
    assert r.returncode == 0, r.stderr[-2000:]
    assert "final test mse" in r.stdout


def test_quantization_example():
    r = _run("quantization/quantize_mlp.py", "--num-examples", "1200",
             "--num-epochs", "5")
    assert r.returncode == 0, r.stderr[-2000:]
    assert "int8 accuracy" in r.stdout


def test_ctc_ocr():
    r = _run("ctc/train_ctc_ocr.py", "--num-examples", "800",
             "--num-epochs", "25", timeout=1200)
    assert r.returncode == 0, r.stderr[-2000:]
    assert "sequence accuracy" in r.stdout


def test_vae():
    r = _run("vae/train_vae.py", "--num-examples", "1000",
             "--num-epochs", "15")
    assert r.returncode == 0, r.stderr[-2000:]
    assert "VAE TRAINING OK" in r.stdout


def test_bi_lstm_sort():
    r = _run("bi-lstm-sort/train_sort.py", "--num-examples", "2000",
             "--num-epochs", "20", timeout=900)
    assert r.returncode == 0, r.stderr[-2000:]
    assert "token accuracy" in r.stdout


def test_nce_loss():
    r = _run("nce-loss/train_nce.py", timeout=600)
    assert r.returncode == 0, r.stderr[-2000:]
    assert "rank-1 accuracy" in r.stdout


def test_neural_style():
    r = _run("neural-style/neural_style.py", "--size", "32", "--iters", "40")
    assert r.returncode == 0, r.stdout[-1500:] + r.stderr[-1500:]
    assert "NEURAL STYLE OK" in r.stdout


def test_fcn_segmentation():
    r = _run("fcn-xs/train_fcn.py", "--num-examples", "32",
             "--num-epochs", "10", timeout=600)
    assert r.returncode == 0, r.stdout[-1500:] + r.stderr[-1500:]
    assert "FCN SEGMENTATION OK" in r.stdout


def test_speech_recognition_ctc():
    r = _run("speech_recognition/train_am.py", timeout=1500)
    assert r.returncode == 0, r.stdout[-1500:] + r.stderr[-1500:]
    assert "SPEECH AM OK" in r.stdout


def test_parallel_actor_critic():
    r = _run("reinforcement-learning/parallel_actor_critic.py",
             "--updates", "400", timeout=900)
    assert r.returncode == 0, r.stdout[-1500:] + r.stderr[-1500:]
    assert "PARALLEL ACTOR-CRITIC OK" in r.stdout


def test_stochastic_depth():
    r = _run("stochastic-depth/train_sd.py", "--num-epochs", "8",
             timeout=900)
    assert r.returncode == 0, r.stdout[-1500:] + r.stderr[-1500:]
    assert "STOCHASTIC DEPTH OK" in r.stdout


def test_numpy_ops_custom_softmax():
    r = _run("numpy-ops/custom_softmax.py", "--num-epochs", "8")
    assert r.returncode == 0, r.stdout[-1500:] + r.stderr[-1500:]
    assert "CUSTOM NUMPY OP OK" in r.stdout


def test_profiler_example():
    r = _run("profiler/profiler_example.py")
    assert r.returncode == 0, r.stdout[-1500:] + r.stderr[-1500:]
    assert "PROFILER EXAMPLE OK" in r.stdout


def test_captcha_multihead():
    r = _run("captcha/train_captcha.py", "--num-epochs", "6", timeout=600)
    assert r.returncode == 0, r.stdout[-1500:] + r.stderr[-1500:]
    assert "CAPTCHA OK" in r.stdout


def test_lstnet_forecast():
    r = _run("multivariate_time_series/train_lstnet.py", timeout=900)
    assert r.returncode == 0, r.stdout[-1500:] + r.stderr[-1500:]
    assert "LSTNET FORECAST OK" in r.stdout


def test_sgld_posterior():
    r = _run("bayesian-methods/sgld_regression.py", timeout=900)
    assert r.returncode == 0, r.stdout[-1500:] + r.stderr[-1500:]
    assert "SGLD OK" in r.stdout


def test_dsd_training():
    r = _run("dsd/train_dsd.py", timeout=900)
    assert r.returncode == 0, r.stdout[-1500:] + r.stderr[-1500:]
    assert "DSD OK" in r.stdout


def test_rnn_time_major():
    r = _run("rnn-time-major/readme_bench.py", "--steps", "10", timeout=900)
    assert r.returncode == 0, r.stdout[-1500:] + r.stderr[-1500:]
    assert "RNN TIME-MAJOR OK" in r.stdout


def test_module_walkthrough():
    r = _run("module/mod_walkthrough.py", timeout=600)
    assert r.returncode == 0, r.stdout[-1500:] + r.stderr[-1500:]
    assert "MODULE WALKTHROUGH OK" in r.stdout


def test_python_howto():
    r = _run("python-howto/data_and_ops.py", timeout=600)
    assert r.returncode == 0, r.stdout[-1500:] + r.stderr[-1500:]
    assert "PYTHON HOWTO OK" in r.stdout


def test_memcost_remat():
    r = _run("memcost/memonger_demo.py", timeout=600)
    assert r.returncode == 0, r.stdout[-1500:] + r.stderr[-1500:]
    assert "MEMCOST REMAT OK" in r.stdout


def test_onnx_roundtrip_example():
    r = _run("onnx/roundtrip.py", timeout=600)
    assert r.returncode == 0, r.stdout[-1500:] + r.stderr[-1500:]
    assert "ONNX EXAMPLE OK" in r.stdout


def test_capsnet_routing():
    r = _run("capsnet/train_capsnet.py", "--num-epochs", "6", timeout=1200)
    assert r.returncode == 0, r.stdout[-1500:] + r.stderr[-1500:]
    assert "CAPSNET OK" in r.stdout


def test_deep_embedded_clustering():
    r = _run("deep-embedded-clustering/dec.py", timeout=900)
    assert r.returncode == 0, r.stdout[-1500:] + r.stderr[-1500:]
    assert "DEC OK" in r.stdout


def test_sparse_embedding_end2end():
    # shrunk table: below the 500k gate for the wall-clock assert, which
    # is machine-load sensitive (the O(nnz) guarantee is asserted
    # deterministically in tests/test_sparse.py)
    r = _run("sparse/sparse_embedding/train.py", "--rows", "100000",
             "--steps", "80", timeout=900)
    assert r.returncode == 0, r.stdout[-1500:] + r.stderr[-1500:]
    assert "SPARSE EMBEDDING OK" in r.stdout


def test_kaggle_pipeline():
    r = _run("kaggle-ndsb1/train_predict_submit.py", "--num-train", "300",
             "--num-epochs", "6", timeout=900)
    assert r.returncode == 0, r.stdout[-1500:] + r.stderr[-1500:]
    assert "KAGGLE PIPELINE OK" in r.stdout


def test_chinese_text_cnn():
    r = _run("cnn_chinese_text_classification/text_cnn_zh.py",
             "--num-examples", "800", "--num-epochs", "4", timeout=900)
    assert r.returncode == 0, r.stdout[-1500:] + r.stderr[-1500:]
    assert "final chinese text-cnn accuracy" in r.stdout
    acc = float(r.stdout.rsplit("accuracy:", 1)[1])
    assert acc > 0.8, acc


def test_kaggle_ndsb2():
    r = _run("kaggle-ndsb2/train_ndsb2.py", "--num-examples", "200",
             "--num-epochs", "6", timeout=900)
    assert r.returncode == 0, r.stdout[-1500:] + r.stderr[-1500:]
    assert "final NDSB2 val CRPS" in r.stdout


def test_adversarial_vae():
    r = _run("mxnet_adversarial_vae/vaegan.py", "--num-examples", "512",
             "--num-epochs", "6", "--batch-size", "32", timeout=900)
    assert r.returncode == 0, r.stdout[-1500:] + r.stderr[-1500:]
    assert "final VAE-GAN pixel recon MSE" in r.stdout


def test_utils_get_data():
    sys.path.insert(0, EXAMPLES)
    try:
        from utils import get_mnist_iterator, get_cifar10_iterator
        train, val = get_mnist_iterator(25, num_train=100, num_val=50)
        b = next(iter(train))
        assert b.data[0].shape == (25, 1, 28, 28)
        ctrain, _ = get_cifar10_iterator(20, num_train=60, num_val=20)
        cb = next(iter(ctrain))
        assert cb.data[0].shape == (20, 3, 32, 32)
    finally:
        sys.path.remove(EXAMPLES)
