"""mx.rnn symbolic cell tests, modelled on the reference's
tests/python/unittest/test_rnn.py strategy: shape-check unrolled graphs,
fused-vs-unfused numerical consistency, weight pack/unpack round trips,
BucketSentenceIter semantics."""
import numpy as np
import pytest

import mxnet_tpu as mx


def _unroll_shapes(cell, T=3, B=2, I=10, **unroll_kw):
    inputs = [mx.sym.Variable("t%d_data" % i) for i in range(T)]
    outputs, _ = cell.unroll(T, inputs, **unroll_kw)
    outputs = mx.sym.Group(outputs) if isinstance(outputs, list) else outputs
    shapes = {"t%d_data" % i: (B, I) for i in range(T)}
    _, out_shapes, _ = outputs.infer_shape(**shapes)
    return outputs, out_shapes


def test_rnn_cell_unroll_shapes():
    cell = mx.rnn.RNNCell(100, prefix="rnn_")
    outputs, out_shapes = _unroll_shapes(cell, T=3, B=2, I=10)
    assert sorted(cell.params._params.keys()) == [
        "rnn_h2h_bias", "rnn_h2h_weight", "rnn_i2h_bias", "rnn_i2h_weight"]
    assert out_shapes == [(2, 100)] * 3


def test_lstm_cell_unroll_shapes():
    cell = mx.rnn.LSTMCell(100, prefix="lstm_")
    outputs, out_shapes = _unroll_shapes(cell, T=3, B=2, I=10)
    assert sorted(cell.params._params.keys()) == [
        "lstm_h2h_bias", "lstm_h2h_weight", "lstm_i2h_bias",
        "lstm_i2h_weight"]
    assert out_shapes == [(2, 100)] * 3


def test_gru_cell_unroll_shapes():
    cell = mx.rnn.GRUCell(100, prefix="gru_")
    _, out_shapes = _unroll_shapes(cell, T=3, B=2, I=10)
    assert out_shapes == [(2, 100)] * 3


def test_stacked_and_bidirectional():
    cell = mx.rnn.SequentialRNNCell()
    cell.add(mx.rnn.LSTMCell(16, prefix="l0_"))
    cell.add(mx.rnn.LSTMCell(16, prefix="l1_"))
    _, out_shapes = _unroll_shapes(cell, T=3, B=2, I=8)
    assert out_shapes == [(2, 16)] * 3

    bi = mx.rnn.BidirectionalCell(
        mx.rnn.LSTMCell(16, prefix="bl_"), mx.rnn.LSTMCell(16, prefix="br_"))
    _, out_shapes = _unroll_shapes(bi, T=3, B=2, I=8)
    assert out_shapes == [(2, 32)] * 3


def test_residual_zoneout_dropout():
    base = mx.rnn.RNNCell(8, prefix="res_")
    cell = mx.rnn.ResidualCell(base)
    _, out_shapes = _unroll_shapes(cell, T=2, B=2, I=8)
    assert out_shapes == [(2, 8)] * 2

    cell = mx.rnn.ZoneoutCell(mx.rnn.RNNCell(8, prefix="zo_"), 0.3, 0.3)
    _, out_shapes = _unroll_shapes(cell, T=2, B=2, I=8)
    assert out_shapes == [(2, 8)] * 2

    cell = mx.rnn.SequentialRNNCell()
    cell.add(mx.rnn.RNNCell(8, prefix="d0_"))
    cell.add(mx.rnn.DropoutCell(0.5))
    _, out_shapes = _unroll_shapes(cell, T=2, B=2, I=8)
    assert out_shapes == [(2, 8)] * 2


def test_fused_unroll_shapes_and_states():
    cell = mx.rnn.FusedRNNCell(50, num_layers=2, mode="lstm", prefix="f_",
                               get_next_state=True)
    inputs = mx.sym.Variable("data")
    outputs, states = cell.unroll(4, inputs, layout="NTC",
                                  merge_outputs=True)
    _, out_shapes, _ = mx.sym.Group([outputs] + states).infer_shape(
        data=(2, 4, 10))
    assert out_shapes[0] == (2, 4, 50)
    assert out_shapes[1] == (2, 2, 50)  # h: (L, B, H)
    assert out_shapes[2] == (2, 2, 50)  # c


def test_fused_vs_unfused_consistency():
    """Fused RNN op output == explicitly unrolled unfused cells with the
    same (packed/unpacked) weights — the reference's fused/unfused parity
    check (test_rnn.py test_unfuse)."""
    T, B, I, H = 5, 3, 4, 6
    rng = np.random.RandomState(0)
    fused = mx.rnn.FusedRNNCell(H, num_layers=1, mode="lstm", prefix="f_")
    data = mx.sym.Variable("data")
    fo, _ = fused.unroll(T, data, layout="NTC", merge_outputs=True)

    x = rng.randn(B, T, I).astype("f")
    nparam = sum(p.size for p in [
        np.zeros((4 * H, I)), np.zeros((4 * H, H)),
        np.zeros(4 * H), np.zeros(4 * H)])
    flat = rng.randn(nparam).astype("f") * 0.1
    ex = fo.simple_bind(ctx=mx.cpu(), data=(B, T, I))
    args = dict(zip(fo.list_arguments(), ex.arg_arrays))
    args["data"][:] = x
    args["f_parameters"][:] = flat
    fused_out = ex.forward()[0].asnumpy()

    # unpack the flat vector and run the unfused stack
    arg_dict = fused.unpack_weights({"f_parameters": mx.nd.array(flat)})
    stack = fused.unfuse()
    so, _ = stack.unroll(T, data, layout="NTC", merge_outputs=True)
    ex2 = so.simple_bind(ctx=mx.cpu(), data=(B, T, I))
    args2 = dict(zip(so.list_arguments(), ex2.arg_arrays))
    args2["data"][:] = x
    for k, v in arg_dict.items():
        if k in args2:
            args2[k][:] = v.asnumpy() if hasattr(v, "asnumpy") else v
    unfused_out = ex2.forward()[0].asnumpy()
    np.testing.assert_allclose(fused_out, unfused_out, rtol=1e-4, atol=1e-5)


def test_pack_unpack_roundtrip():
    cell = mx.rnn.FusedRNNCell(8, num_layers=2, mode="gru", prefix="g_",
                               bidirectional=True)
    n = mx.ops.nn.rnn_param_size(2, 5, 8, True, "gru")
    flat = mx.nd.array(np.random.RandomState(1).randn(n).astype("f"))
    unpacked = cell.unpack_weights({"g_parameters": flat})
    assert "g_parameters" not in unpacked
    assert "g_l0_i2h_weight" in unpacked and "g_r1_h2h_bias" in unpacked
    assert unpacked["g_l0_i2h_weight"].shape == (3 * 8, 5)
    repacked = cell.pack_weights(unpacked)
    np.testing.assert_allclose(repacked["g_parameters"].asnumpy(),
                               flat.asnumpy(), rtol=1e-6)


def test_unfused_pack_unpack_roundtrip():
    cell = mx.rnn.LSTMCell(4, prefix="lstm_")
    rng = np.random.RandomState(2)
    args = {"lstm_i2h_weight": mx.nd.array(rng.randn(16, 3).astype("f")),
            "lstm_i2h_bias": mx.nd.array(rng.randn(16).astype("f")),
            "lstm_h2h_weight": mx.nd.array(rng.randn(16, 4).astype("f")),
            "lstm_h2h_bias": mx.nd.array(rng.randn(16).astype("f"))}
    unpacked = cell.unpack_weights(dict(args))
    assert "lstm_i2h_i_weight" in unpacked
    assert unpacked["lstm_i2h_f_weight"].shape == (4, 3)
    repacked = cell.pack_weights(unpacked)
    for k in args:
        np.testing.assert_allclose(repacked[k].asnumpy(), args[k].asnumpy())


def test_encode_sentences_and_bucket_iter():
    sentences = [["a", "b", "c"], ["a", "c"], ["b", "c", "a"],
                 ["a", "b"], ["c"], ["a", "b", "c"]]
    enc, vocab = mx.rnn.encode_sentences(sentences, start_label=1)
    assert len(vocab) == 4  # 3 tokens + invalid key
    assert all(all(isinstance(t, int) for t in s) for s in enc)

    it = mx.rnn.BucketSentenceIter(enc, batch_size=2, buckets=[2, 3],
                                   invalid_label=-1)
    seen = 0
    for batch in it:
        seen += 1
        assert batch.bucket_key in (2, 3)
        assert batch.data[0].shape == (2, batch.bucket_key)
        d = batch.data[0].asnumpy()
        l = batch.label[0].asnumpy()
        # label is data shifted one step left
        np.testing.assert_allclose(l[:, :-1], d[:, 1:])
    assert seen >= 2


def test_conv_cells_shapes():
    cell = mx.rnn.ConvLSTMCell(input_shape=(3, 8, 8), num_hidden=5,
                               prefix="cl_")
    inputs = [mx.sym.Variable("t%d_data" % i) for i in range(2)]
    outputs, _ = cell.unroll(2, inputs)
    outputs = mx.sym.Group(outputs)
    _, out_shapes, _ = outputs.infer_shape(
        t0_data=(1, 3, 8, 8), t1_data=(1, 3, 8, 8))
    assert out_shapes == [(1, 5, 8, 8)] * 2


def test_dropout_cell_merged_unroll():
    cell = mx.rnn.DropoutCell(0.5)
    outputs, states = cell.unroll(3, mx.sym.Variable("data"),
                                  merge_outputs=True)
    assert isinstance(outputs, mx.sym.Symbol)
    assert states == []
    _, out_shapes, _ = outputs.infer_shape(data=(2, 3, 4))
    assert out_shapes == [(2, 3, 4)]


def test_unfused_bidirectional_stack_unrolls():
    stack = mx.rnn.FusedRNNCell(4, num_layers=2, mode="lstm",
                                bidirectional=True, prefix="fb_").unfuse()
    inputs = [mx.sym.Variable("t%d_data" % i) for i in range(3)]
    outputs, _ = stack.unroll(3, inputs)
    outputs = mx.sym.Group(outputs)
    _, out_shapes, _ = outputs.infer_shape(
        **{"t%d_data" % i: (2, 5) for i in range(3)})
    assert out_shapes == [(2, 8)] * 3  # 2 directions x 4 hidden


def test_bucket_iter_empty_bucket():
    it = mx.rnn.BucketSentenceIter([[1, 2], [2, 1], [1, 2]], batch_size=2,
                                   buckets=[2, 5], invalid_label=-1)
    batches = list(it)
    assert all(b.bucket_key == 2 for b in batches)


def test_gluon_contrib_conv_cells():
    from mxnet_tpu.gluon import contrib as gcontrib
    for cls, dims, nst in [(gcontrib.rnn.Conv1DRNNCell, 1, 1),
                           (gcontrib.rnn.Conv2DLSTMCell, 2, 2),
                           (gcontrib.rnn.Conv3DGRUCell, 3, 1)]:
        spatial = (6,) * dims
        cell = cls(input_shape=(3,) + spatial, hidden_channels=4,
                   i2h_kernel=3, h2h_kernel=3, i2h_pad=1)
        cell.initialize()
        x = mx.nd.random.uniform(shape=(2, 3) + spatial)
        states = cell.begin_state(batch_size=2)
        assert len(states) == nst
        out, new_states = cell(x, states)
        assert out.shape == (2, 4) + spatial
        assert len(new_states) == nst
        # unroll a short sequence
        seq = mx.nd.random.uniform(shape=(2, 3, 3) + spatial)
        outs, _ = cell.unroll(3, seq, layout="NTC", merge_outputs=False)
        assert len(outs) == 3


def test_gluon_variational_dropout_cell():
    from mxnet_tpu.gluon import contrib as gcontrib
    from mxnet_tpu.gluon import rnn as grnn
    base = grnn.LSTMCell(8, input_size=5)
    cell = gcontrib.rnn.VariationalDropoutCell(base, drop_inputs=0.3,
                                               drop_outputs=0.3)
    cell.initialize()
    x = mx.nd.random.uniform(shape=(4, 6, 5))
    with mx.autograd.record():  # masks active in train mode
        outs, _ = cell.unroll(6, x, layout="NTC", merge_outputs=True)
    assert outs.shape == (4, 6, 8)


def test_gluon_lstmp_cell():
    from mxnet_tpu.gluon import contrib as gcontrib
    cell = gcontrib.rnn.LSTMPCell(16, projection_size=8, input_size=4)
    cell.initialize()
    x = mx.nd.random.uniform(shape=(2, 4))
    states = cell.begin_state(batch_size=2)
    out, new_states = cell(x, states)
    assert out.shape == (2, 8)           # projected
    assert new_states[1].shape == (2, 16)  # cell state full-size


def test_rnn_fused_lstm_dispatch_matches_scan():
    """The TPU fused-LSTM fast path's wiring (weight transposes, bias sum,
    reverse flip) must match the lax.scan path; forced through the Pallas
    interpreter since CI has no chip."""
    import numpy as np
    from mxnet_tpu.ops import nn as nn_ops

    rng = np.random.RandomState(0)
    T, B, I, H = 12, 4, 8, 16
    x = mx.nd.array(rng.randn(T, B, I).astype("f"))
    # bidirectional: two directions' worth of packed weights
    w = mx.nd.array(rng.randn(2 * ((I * 4 * H) + (H * 4 * H) + 8 * H))
                    .astype("f") * 0.1)
    h0 = mx.nd.zeros((2, B, H))
    c0 = mx.nd.zeros((2, B, H))

    def run():
        return mx.nd.RNN(x, w, h0, c0, state_size=H, num_layers=1,
                         mode="lstm", bidirectional=True).asnumpy()

    scan_out = run()
    saved = nn_ops._fused_lstm_ok
    nn_ops._fused_lstm_ok = lambda *a: True   # force the fused path
    try:
        fused_out = run()
    finally:
        nn_ops._fused_lstm_ok = saved
    np.testing.assert_allclose(fused_out, scan_out, rtol=1e-4, atol=1e-5)
