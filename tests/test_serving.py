"""Serving subsystem (`mxnet_tpu/serving/`): bucketing math, the
dynamic-batching engine (correctness, compile accounting, deadlines,
shedding, chaos-driven worker death + respawn, drain/shutdown), the
HTTP front end, and a launched end-to-end CLI server test."""
import http.client
import json
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import chaos, telemetry, xla_stats
from mxnet_tpu.serving import (EngineConfig, InferenceEngine,
                               RequestRejected, batching, reqtrace,
                               serve)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

import launchutil  # noqa: E402

IN_DIM = 12


def _mlp():
    data = mx.sym.Variable("data")
    fc1 = mx.sym.FullyConnected(data, num_hidden=8, name="fc1")
    act = mx.sym.Activation(fc1, act_type="relu", name="relu1")
    return mx.sym.FullyConnected(act, num_hidden=3, name="fc2")


def _init_params(net):
    exe = net.simple_bind(mx.cpu(), data=(2, IN_DIM))
    rng = np.random.RandomState(0)
    params = {}
    for name, arr in exe.arg_dict.items():
        if name == "data":
            continue
        arr[:] = (rng.randn(*arr.shape) * 0.1).astype(np.float32)
        params[name] = arr
    return params


def _np_forward(params, x):
    """Numpy reference — deliberately NOT an executor, so correctness
    checks add zero XLA compiles to the process (the compile-accounting
    assertions depend on that)."""
    h = x @ params["fc1_weight"].asnumpy().T \
        + params["fc1_bias"].asnumpy()
    h = np.maximum(h, 0.0)
    return h @ params["fc2_weight"].asnumpy().T \
        + params["fc2_bias"].asnumpy()


@pytest.fixture(scope="module")
def net():
    return _mlp()


@pytest.fixture(scope="module")
def params(net):
    return _init_params(net)


@pytest.fixture
def make_engine(net, params):
    engines = []

    def make(**cfg_kwargs):
        cfg = EngineConfig(**cfg_kwargs)
        eng = InferenceEngine(net.tojson(), dict(params),
                              {"data": (IN_DIM,)}, config=cfg)
        engines.append(eng)
        return eng

    yield make
    for eng in engines:
        eng.shutdown(drain=False)


def _x(n, seed=0):
    return np.random.RandomState(seed).rand(n, IN_DIM).astype(np.float32)


# ---------------------------------------------------------------------------
# bucketing math
# ---------------------------------------------------------------------------

def test_bucket_sizes():
    assert batching.bucket_sizes(1) == [1]
    assert batching.bucket_sizes(8) == [1, 2, 4, 8]
    assert batching.bucket_sizes(6) == [1, 2, 4, 6]
    assert batching.bucket_sizes(17) == [1, 2, 4, 8, 16, 17]
    with pytest.raises(ValueError):
        batching.bucket_sizes(0)


def test_pick_bucket():
    buckets = [1, 2, 4, 8]
    assert [batching.pick_bucket(n, buckets)
            for n in (1, 2, 3, 4, 5, 8)] == [1, 2, 4, 4, 8, 8]
    with pytest.raises(ValueError):
        batching.pick_bucket(9, buckets)


def test_pad_and_split_rows():
    arr = np.arange(6, dtype=np.float32).reshape(3, 2)
    assert batching.pad_rows(arr, 3) is arr        # full: no copy
    padded = batching.pad_rows(arr, 8)
    assert padded.shape == (8, 2)
    np.testing.assert_array_equal(padded[3:], np.tile(arr[-1], (5, 1)))
    with pytest.raises(ValueError):
        batching.pad_rows(arr, 2)
    parts = batching.split_rows(padded, [1, 2])    # pad rows dropped
    assert [p.shape[0] for p in parts] == [1, 2]
    np.testing.assert_array_equal(np.concatenate(parts), arr)


def test_engine_config_env(monkeypatch):
    monkeypatch.setenv("MXNET_SERVING_MAX_BATCH", "16")
    monkeypatch.setenv("MXNET_SERVING_MAX_DELAY_MS", "7.5")
    monkeypatch.setenv("MXNET_SERVING_QUEUE_DEPTH", "9")
    cfg = EngineConfig()
    assert (cfg.max_batch_size, cfg.max_batch_delay_ms,
            cfg.max_queue) == (16, 7.5, 9)
    # explicit args win over env
    assert EngineConfig(max_batch_size=4).max_batch_size == 4
    monkeypatch.setenv("MXNET_SERVING_MAX_BATCH", "junk")
    assert EngineConfig().max_batch_size == 8   # bad env -> default


# ---------------------------------------------------------------------------
# engine semantics
# ---------------------------------------------------------------------------

def test_engine_outputs_match_reference(make_engine, params):
    eng = make_engine(max_batch_size=4, max_batch_delay_ms=1.0)
    assert eng.buckets == [1, 2, 4]
    assert eng.warmup_compiles >= len(eng.buckets)
    for n in (1, 2, 3, 4):
        x = _x(n, seed=n)
        out = eng.predict({"data": x}, timeout=30)
        assert len(out) == 1 and out[0].shape == (n, 3)
        np.testing.assert_allclose(out[0], _np_forward(params, x),
                                   atol=1e-5)


def test_request_validation(make_engine):
    eng = make_engine(max_batch_size=4)
    with pytest.raises(mx.MXNetError, match="unknown 'datum'"):
        eng.submit({"datum": _x(1)})
    with pytest.raises(mx.MXNetError, match="missing 'data'"):
        eng.submit({})
    with pytest.raises(mx.MXNetError, match=r"must be \(n,\)"):
        eng.submit({"data": np.zeros((2, IN_DIM + 1), np.float32)})
    with pytest.raises(mx.MXNetError, match="at least one row"):
        eng.submit({"data": np.zeros((0, IN_DIM), np.float32)})
    with pytest.raises(mx.MXNetError, match="exceeds max_batch_size"):
        eng.submit({"data": _x(5)})


def test_concurrent_load_no_cold_compiles(make_engine, params):
    """THE acceptance test: >= 8 client threads, mixed request sizes,
    every response correct, the engine performs ZERO compiles after
    warm-up (all signatures bucket-bounded and pre-compiled) while the
    cache-hit counter does the serving — and every completed request's
    phase anatomy tiles its wall latency (sum of spans within 10%)."""
    reqtrace.reset()
    eng = make_engine(max_batch_size=8, max_batch_delay_ms=2.0,
                      max_queue=256)
    hits_before = xla_stats.compile_counts()["cache_hits"]

    def ok_count():
        m = telemetry.get_metric("serving_requests_total", status="ok")
        return m.value if m else 0.0

    def batch_count():
        entry = telemetry.snapshot().get("serving_batches_total")
        if not entry:
            return 0.0
        return sum(s["value"] for s in entry["series"] if s["labels"])

    ok_before = ok_count()
    batches_before = batch_count()
    n_threads, per_thread = 8, 20
    errors = []

    def client(cid):
        rng = np.random.RandomState(cid)
        for i in range(per_thread):
            n = 1 + (cid + i) % 5          # mixed sizes 1..5
            x = rng.rand(n, IN_DIM).astype(np.float32)
            try:
                out = eng.predict({"data": x}, timeout=60)
                np.testing.assert_allclose(
                    out[0], _np_forward(params, x), atol=1e-5)
            except Exception as exc:   # noqa: BLE001
                errors.append((cid, i, exc))

    threads = [threading.Thread(target=client, args=(c,))
               for c in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(120)
    assert not errors, errors[:3]
    assert eng.cold_compiles() == 0        # zero compiles under load
    assert xla_stats.compile_counts()["cache_hits"] > hits_before
    assert ok_count() - ok_before == n_threads * per_thread
    # batching actually batched: fewer dispatches than requests served
    batches = batch_count() - batches_before
    assert 0 < batches < n_threads * per_thread

    # request anatomy: every completed request decomposed into the full
    # taxonomy, and the phase spans tile its measured wall latency
    # (telescoping boundaries -> within 10% is the loose public bound)
    recs = [r for r in reqtrace.tracer.records() if r["status"] == "ok"]
    assert len(recs) >= n_threads * per_thread
    for rec in recs:
        assert set(rec["phases"]) == set(reqtrace.PHASES)
        assert abs(sum(rec["phases"].values()) - rec["total"]) \
            <= 0.1 * max(rec["total"], 1e-9), rec
        assert rec["bucket"] in eng.buckets
        assert rec["batch"] is not None
    # pad accounting saw every dispatched batch
    pad = reqtrace.tracer.pad.snapshot()
    assert sum(b["batches"] for b in pad["buckets"].values()) \
        >= batches
    assert 0.0 <= pad["waste_ratio"] < 1.0
    # SLO: everything completed well under the default 250ms target
    slo = eng.stats()["slo"]
    assert slo["bad_total"] == 0
    assert slo["good_total"] >= n_threads * per_thread


def test_deadline_expired_at_submit(make_engine):
    eng = make_engine(max_batch_size=2)
    with pytest.raises(RequestRejected) as ei:
        eng.submit({"data": _x(1)}, deadline_ms=-5)
    assert ei.value.status == "expired"


def test_deadline_expires_while_queued(make_engine):
    eng = make_engine(max_batch_size=2, max_batch_delay_ms=0.0,
                      max_queue=8)
    # first batch stalls in the worker for 0.5 s; the second request's
    # 100 ms deadline passes while it waits behind it
    with chaos.armed("serving.slow_request", value="0.5"):
        f1 = eng.submit({"data": _x(1)})
        f2 = eng.submit({"data": _x(1)}, deadline_ms=100)
        with pytest.raises(RequestRejected) as ei:
            f2.result(timeout=30)
        assert ei.value.status == "expired"
        f1.result(timeout=30)   # the slow one still completes
    m = telemetry.get_metric("serving_requests_total", status="expired")
    assert m is not None and m.value >= 1


def test_load_shedding(make_engine):
    """Backpressure surfaces as RequestRejected(shed), not unbounded
    queueing: with stalled workers and a depth-2 queue, a flood of
    submissions mostly sheds, and everything that was accepted still
    completes."""
    eng = make_engine(max_batch_size=2, max_batch_delay_ms=0.0,
                      max_queue=2)
    shed_before = telemetry.counter("serving_requests_total",
                                    status="shed").value
    chaos.arm("serving.slow_request", times=100, value="0.2")
    futs, shed = [], 0
    for i in range(30):
        try:
            futs.append(eng.submit({"data": _x(1, seed=i)}))
        except RequestRejected as exc:
            assert exc.status == "shed"
            assert "retry" in str(exc)
            shed += 1
    assert shed > 0
    assert len(futs) >= 2          # bounded queue admitted some
    chaos.clear("serving.slow_request")
    for f in futs:
        assert f.result(timeout=60)[0].shape == (1, 3)
    delta = telemetry.counter("serving_requests_total",
                              status="shed").value - shed_before
    assert delta == shed


def test_worker_death_fails_inflight_and_respawns(make_engine, tmp_path,
                                                  monkeypatch):
    """Chaos serving.worker_death: ONLY the in-flight batch fails, the
    worker respawns, later requests succeed, and the crash leaves a
    flight-recorder post-mortem."""
    monkeypatch.setenv("MXNET_TELEMETRY_DIR", str(tmp_path))
    eng = make_engine(max_batch_size=2, max_batch_delay_ms=0.0)
    with chaos.armed("serving.worker_death"):
        fut = eng.submit({"data": _x(1)})
        with pytest.raises(mx.MXNetError, match="worker died mid-batch"):
            fut.result(timeout=30)
    assert chaos.fired("serving.worker_death") == 1
    # the respawned worker serves the NEXT request fine
    out = eng.predict({"data": _x(2)}, timeout=30)
    assert out[0].shape == (2, 3)
    assert telemetry.get_metric("serving_worker_deaths_total",
                                replica="0").value >= 1
    assert telemetry.counter("serving_worker_respawns_total").value >= 1
    rec = os.path.join(str(tmp_path), "flightrecorder-host%d.json"
                       % telemetry.host_id())
    assert os.path.exists(rec)
    doc = json.load(open(rec))
    assert doc["reason"] == "serving.worker_death"


def test_cancelled_future_does_not_kill_engine(make_engine):
    """A client cancelling a queued Future must not crash the batcher
    or worker when they later try to resolve it — the engine keeps
    serving and the request counts as ``cancelled``."""
    eng = make_engine(max_batch_size=2, max_batch_delay_ms=0.0,
                      max_queue=8)
    with chaos.armed("serving.slow_request", value="0.3"):
        f1 = eng.submit({"data": _x(1)})      # occupies the worker
        f2 = eng.submit({"data": _x(2, seed=1)})
        assert f2.cancel()                    # client walks away
        assert f1.result(timeout=30)[0].shape == (1, 3)
    # the threads that resolved the cancelled future are still alive
    out = eng.predict({"data": _x(1, seed=2)}, timeout=30)
    assert out[0].shape == (1, 3)
    m = telemetry.get_metric("serving_requests_total",
                             status="cancelled")
    assert m is not None and m.value >= 1


def test_drain_serves_out_then_rejects(make_engine):
    eng = make_engine(max_batch_size=2, max_batch_delay_ms=0.0,
                      max_queue=16)
    chaos.arm("serving.slow_request", value="0.2")
    futs = [eng.submit({"data": _x(1, seed=i)}) for i in range(3)]
    chaos.clear("serving.slow_request")
    assert eng.drain(timeout=60)
    for f in futs:
        assert f.result(timeout=1)[0].shape == (1, 3)   # already done
    with pytest.raises(RequestRejected) as ei:
        eng.submit({"data": _x(1)})
    assert ei.value.status == "closed"
    eng.shutdown()   # idempotent after drain


def test_shutdown_without_drain_fails_queued(make_engine):
    eng = make_engine(max_batch_size=2, max_batch_delay_ms=0.0,
                      max_queue=16)
    chaos.arm("serving.slow_request", times=20, value="0.3")
    futs = [eng.submit({"data": _x(1, seed=i)}) for i in range(6)]
    eng.shutdown(drain=False)
    statuses = set()
    for f in futs:
        try:
            f.result(timeout=30)
            statuses.add("ok")
        except RequestRejected as exc:
            statuses.add(exc.status)
    # whatever was already in flight may finish; the rest got "closed"
    assert "closed" in statuses
    assert statuses <= {"ok", "closed"}


# ---------------------------------------------------------------------------
# request anatomy: tail attribution + trace propagation
# ---------------------------------------------------------------------------

def test_report_names_queue_delay_under_load(make_engine):
    """Synthetic queue-delay fixture: a worker stalled by chaos makes
    requests tail in queue_wait/batch_wait, and the report CLI names
    that dominant p99 phase and says queue-bound."""
    import io
    reqtrace.reset()
    eng = make_engine(max_batch_size=2, max_batch_delay_ms=0.0,
                      max_queue=64)
    # one warm request so the head of the window is fast
    for i in range(10):
        eng.predict({"data": _x(1, seed=i)}, timeout=30)
    # the stall: each batch sleeps 50ms, so later submissions queue
    chaos.arm("serving.slow_request", times=10, value="0.05")
    futs = [eng.submit({"data": _x(1, seed=100 + i)}) for i in range(8)]
    for f in futs:
        f.result(timeout=60)
    chaos.clear("serving.slow_request")
    out = io.StringIO()
    assert reqtrace.report(out=out) == 0
    text = out.getvalue()
    machine = json.loads(text.strip().splitlines()[-1])
    assert machine["verdict"] == "queue-bound", text
    assert machine["dominant_p99_phase"] in ("queue_wait", "batch_wait")
    assert ("dominant p99 phase: %s" % machine["dominant_p99_phase"]) \
        in text
    # zero cold compiles even through the chaos-stalled tail
    assert eng.cold_compiles() == 0


def test_engine_propagates_rid_and_rejections_carry_it(make_engine):
    eng = make_engine(max_batch_size=2, max_batch_delay_ms=0.0)
    reqtrace.reset()
    eng.predict({"data": _x(1)}, timeout=30, rid="my-trace-1")
    recs = reqtrace.tracer.records()
    assert [r["rid"] for r in recs] == ["my-trace-1"]
    with pytest.raises(RequestRejected) as ei:
        eng.submit({"data": _x(1)}, deadline_ms=-5, rid="dead-1")
    assert ei.value.rid == "dead-1"
    assert reqtrace.tracer.counts().get("expired", 0) >= 1


def test_http_trace_propagation_end_to_end(make_engine, tmp_path):
    """THE propagation test: X-Request-Id in -> the engine's
    serving.request span lands in the telemetry JSONL with that id,
    the serving.batch span links it in args.rids, and the response
    echoes the header back."""
    telemetry.configure(str(tmp_path))
    try:
        eng = make_engine(max_batch_size=4, max_batch_delay_ms=1.0)
        srv = serve(eng, port=0)
        rid = "e2e-trace-42"
        try:
            conn = http.client.HTTPConnection("127.0.0.1", srv.port,
                                              timeout=30)
            body = json.dumps({"inputs": {"data": _x(2).tolist()}})
            conn.request("POST", "/predict", body,
                         {"Content-Type": "application/json",
                          "X-Request-Id": rid})
            resp = conn.getresponse()
            raw = resp.read()
            assert resp.status == 200, raw
            assert resp.getheader("X-Request-Id") == rid
            conn.close()

            # error responses carry the trace id too
            conn = http.client.HTTPConnection("127.0.0.1", srv.port,
                                              timeout=30)
            conn.request("POST", "/predict",
                         json.dumps({"inputs": {"datum": [[0.0]]}}),
                         {"Content-Type": "application/json",
                          "X-Request-Id": "bad-input-7"})
            resp = conn.getresponse()
            doc = json.loads(resp.read())
            assert resp.status == 400
            assert doc["request_id"] == "bad-input-7"
            conn.close()
        finally:
            srv.stop()
        telemetry.flush()
        events = []
        for fn in os.listdir(str(tmp_path)):
            if fn.endswith(".jsonl"):
                events.extend(telemetry.read_events(
                    os.path.join(str(tmp_path), fn)))
        req_spans = [e for e in events if e["name"] == "serving.request"
                     and e["args"].get("rid") == rid]
        assert len(req_spans) == 1, [e["name"] for e in events][:20]
        span = req_spans[0]
        assert span["ph"] == "X"
        assert span["args"]["status"] == "ok"
        phases = span["args"]["phases"]
        assert set(phases) == set(reqtrace.PHASES)
        assert abs(sum(phases.values()) - span["dur"]) \
            <= 0.1 * span["dur"] + 1e-6
        batch_spans = [e for e in events if e["name"] == "serving.batch"
                       and rid in (e["args"].get("rids") or [])]
        assert len(batch_spans) == 1
        assert batch_spans[0]["args"]["batch"] == span["args"]["batch"]
        # per-route metrics counted both requests
        m = telemetry.get_metric("serving_http_requests_total",
                                 route="/predict", code="200")
        assert m is not None and m.value >= 1
        m = telemetry.get_metric("serving_http_requests_total",
                                 route="/predict", code="400")
        assert m is not None and m.value >= 1
    finally:
        telemetry.configure(None)


def test_healthz_reports_saturation(make_engine):
    eng = make_engine(max_batch_size=2)
    srv = serve(eng, port=0)
    try:
        code, _, raw = _http(srv.port, "GET", "/healthz")
        doc = json.loads(raw)
        assert code == 200
        # the load-balancer saturation triple: queue depth, in-flight,
        # SLO burn rate per window
        assert "queue_depth" in doc and "pending" in doc
        assert set(doc["slo"]["burn_rate"]) \
            == {str(w) for w in eng._slo.windows}
        assert doc["slo"]["target_ms"] == eng._slo.target_ms
    finally:
        srv.stop()


def test_metrics_exposes_anatomy_series(make_engine):
    eng = make_engine(max_batch_size=4, max_batch_delay_ms=0.0)
    srv = serve(eng, port=0)
    try:
        eng.predict({"data": _x(3)}, timeout=30)
        code, _, raw = _http(srv.port, "GET", "/metrics")
        text = raw.decode()
        assert code == 200
        for series in ("serving_req_phase_seconds",
                       "serving_pad_waste_ratio",
                       "serving_bucket_occupancy",
                       "serving_slo_burn_rate",
                       "serving_slo_target_ms",
                       "serving_http_seconds"):
            assert series in text, series
        assert 'phase="queue_wait"' in text
        assert 'phase="device_compute"' in text
    finally:
        srv.stop()


# ---------------------------------------------------------------------------
# HTTP front end
# ---------------------------------------------------------------------------

def _http(port, method, path, body=None):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
    try:
        conn.request(method, path,
                     json.dumps(body) if body is not None else None,
                     {"Content-Type": "application/json"})
        resp = conn.getresponse()
        raw = resp.read()
        return resp.status, resp.getheader("Content-Type"), raw
    finally:
        conn.close()


def test_http_server(make_engine, params):
    eng = make_engine(max_batch_size=4, max_batch_delay_ms=1.0)
    srv = serve(eng, port=0, allow_shutdown=True)
    try:
        x = _x(3, seed=7)
        code, ctype, raw = _http(srv.port, "POST", "/predict",
                                 {"inputs": {"data": x.tolist()}})
        assert code == 200 and ctype == "application/json"
        doc = json.loads(raw)
        assert doc["shapes"] == [[3, 3]]
        np.testing.assert_allclose(np.asarray(doc["outputs"][0]),
                                   _np_forward(params, x), atol=1e-4)

        code, _, raw = _http(srv.port, "GET", "/healthz")
        assert code == 200 and json.loads(raw)["status"] == "ok"

        code, ctype, raw = _http(srv.port, "GET", "/metrics")
        text = raw.decode()
        assert code == 200 and ctype.startswith("text/plain")
        for series in ("serving_requests_total", "serving_total_seconds",
                       "serving_queue_wait_seconds",
                       "serving_compute_seconds", "jit_compiles_total"):
            assert series in text, series

        # error mapping: bad JSON -> 400, unknown input -> 400,
        # missing body -> 400, bad route -> 404
        assert _http(srv.port, "POST", "/predict",
                     {"inputs": {"datum": [[0.0] * IN_DIM]}})[0] == 400
        assert _http(srv.port, "POST", "/predict", {"nope": 1})[0] == 400
        assert _http(srv.port, "GET", "/nothere")[0] == 404

        # deadline already expired -> 504 (Gateway Timeout semantics)
        code, _, raw = _http(srv.port, "POST", "/predict",
                             {"inputs": {"data": x.tolist()},
                              "deadline_ms": -1})
        assert code == 504 and json.loads(raw)["status"] == "expired"
    finally:
        srv.stop()
    # stop() drained the engine: health gone, submits rejected
    with pytest.raises(RequestRejected):
        eng.submit({"data": _x(1)})


# ---------------------------------------------------------------------------
# launched: the CLI server end-to-end over a real socket
# ---------------------------------------------------------------------------

@pytest.mark.launched
@pytest.mark.timeout(150)
def test_launched_cli_server(net, params, tmp_path):
    sym_path = str(tmp_path / "net.json")
    with open(sym_path, "w") as fh:
        fh.write(net.tojson())
    params_path = str(tmp_path / "net.params")
    mx.nd.save(params_path,
               {"arg:%s" % k: v for k, v in params.items()})

    env = dict(os.environ, JAX_PLATFORMS="cpu", PALLAS_AXON_POOL_IPS="",
               PYTHONPATH=REPO, MXNET_SERVING_MAX_BATCH="4")
    proc = subprocess.Popen(
        [sys.executable, "-m", "mxnet_tpu.serving.server",
         "--symbol", sym_path, "--params", params_path,
         "--input", "data:%d" % IN_DIM, "--port", "0",
         "--allow-shutdown"],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True)
    try:
        # the SERVING line prints once every bucket is warm-compiled
        deadline = time.monotonic() + launchutil.LAUNCH_TIMEOUT
        line = ""
        while time.monotonic() < deadline:
            line = proc.stdout.readline()
            if line.startswith("SERVING ") or not line:
                break
        assert line.startswith("SERVING "), line
        info = json.loads(line[len("SERVING "):])
        port = info["port"]
        assert info["buckets"] == [1, 2, 4]
        assert info["warmup_compiles"] >= 3

        x = _x(3, seed=9)
        code, _, raw = _http(port, "POST", "/predict",
                             {"inputs": {"data": x.tolist()}})
        assert code == 200
        np.testing.assert_allclose(
            np.asarray(json.loads(raw)["outputs"][0]),
            _np_forward(params, x), atol=1e-4)

        code, _, raw = _http(port, "GET", "/metrics")
        text = raw.decode()
        assert code == 200
        assert 'serving_requests_total{status="ok"} 1' in text
        assert "serving_total_seconds" in text

        assert _http(port, "POST", "/shutdown")[0] == 200
        out, _ = launchutil.communicate(proc)
        assert proc.returncode == 0, out[-4000:]
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait()
