"""True dist_async (reference src/kvstore/kvstore_dist_server.h:282-294):
update-on-push with no global barrier — a slow worker must not block fast
ones — plus heartbeat-based failure detection and SSP staleness bounds.

Launched test: worker subprocesses connect to an in-test async PS over TCP
(`parallel/ps_async`), the ps-lite analog."""
import os
import subprocess
import sys
import time

import numpy as np
import pytest

import launchutil
from mxnet_tpu.parallel import ps_async

pytestmark = pytest.mark.launched

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

WORKER = r"""
import os, sys, time
import numpy as np
import mxnet_tpu as mx

rank = int(sys.argv[1])
n_push = int(sys.argv[2])
sleep_s = float(sys.argv[3])

kv = mx.kv.create("dist_async")
w = mx.nd.ones((4,))
kv.init("w", w)
kv.set_optimizer(mx.optimizer.SGD(learning_rate=0.1, rescale_grad=1.0))
t0 = time.time()
for i in range(n_push):
    g = mx.nd.ones((4,))
    kv.push("w", g)
    kv.pull("w", out=w)
    if sleep_s:
        time.sleep(sleep_s)
print("WORKER %d DONE %.3f" % (rank, time.time() - t0), flush=True)
"""


def _spawn_worker(tmp_path, rank, n_push, sleep_s, port, extra_env=None):
    script = tmp_path / ("worker%d.py" % rank)
    script.write_text(WORKER)
    env = dict(os.environ,
               JAX_PLATFORMS="cpu", PALLAS_AXON_POOL_IPS="",
               PYTHONPATH=REPO,
               MXNET_PS_HOST="127.0.0.1", MXNET_PS_PORT=str(port),
               MXNET_PS_RANK=str(rank), MXNET_PS_NUM_WORKERS="2")
    env.update(extra_env or {})
    return subprocess.Popen(
        [sys.executable, str(script), str(rank), str(n_push), str(sleep_s)],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True)


def test_async_server_updates_on_push():
    srv, (host, port) = ps_async.serve_forever()
    try:
        c = ps_async.AsyncPSClient((host, port), rank=0)
        c.init("w", np.ones(3, np.float32))
        # no optimizer: pushes assign
        c.push("w", np.full(3, 7.0, np.float32))
        np.testing.assert_allclose(c.pull("w"), 7.0)
        # with optimizer: update-on-receive
        from mxnet_tpu.optimizer import SGD
        c.set_optimizer(SGD(learning_rate=0.5, rescale_grad=1.0))
        c.push("w", np.ones(3, np.float32))
        np.testing.assert_allclose(c.pull("w"), 6.5)
        c.close()
    finally:
        srv.shutdown()


def test_async_slow_worker_does_not_block_fast(tmp_path):
    """Fast worker completes its pushes while the slow one is still
    sleeping — impossible under BSP where every push barriers."""
    srv, (host, port) = ps_async.serve_forever()
    try:
        fast = _spawn_worker(tmp_path, 0, 20, 0.0, port)
        slow = _spawn_worker(tmp_path, 1, 3, 1.5, port)
        out_fast, _ = launchutil.communicate(fast, timeout=120)
        assert fast.returncode == 0, out_fast
        assert "DONE" in out_fast
        # the worker-reported push-loop time excludes the ~15s process
        # startup: 20 pushes must finish well under the slow worker's
        # >=4.5s of sleep — impossible if pushes barriered across workers
        fast_loop = float(out_fast.split("DONE")[1].split()[0])
        assert fast_loop < 4.0, (fast_loop, out_fast)
        out_slow, _ = launchutil.communicate(slow, timeout=120)
        assert slow.returncode == 0, out_slow
        slow_loop = float(out_slow.split("DONE")[1].split()[0])
        assert slow_loop >= 4.5  # it really was sleeping through its loop
        # both workers' updates landed on the same key
        c = ps_async.AsyncPSClient((host, port), rank=9)
        val = c.pull("w")
        assert np.isfinite(val).all()
        c.close()
    finally:
        srv.shutdown()


def test_async_heartbeat_failure_detection():
    srv, (host, port) = ps_async.serve_forever()
    try:
        a = ps_async.AsyncPSClient((host, port), rank=0)
        b = ps_async.AsyncPSClient((host, port), rank=1)
        a.heartbeat()
        b.heartbeat()
        assert a.num_dead_node(timeout=60) == 0
        time.sleep(0.3)
        a.heartbeat()  # b goes silent
        assert a.num_dead_node(timeout=0.2) == 1  # b exceeded the timeout
        assert a.num_dead_node(timeout=60) == 0
    finally:
        srv.shutdown()


def test_async_staleness_bound_blocks_runaway_worker():
    """SSP: with staleness S=2, a worker 3 pushes ahead blocks until the
    laggard catches up."""
    srv, (host, port) = ps_async.serve_forever(staleness=2)
    try:
        a = ps_async.AsyncPSClient((host, port), rank=0)
        b = ps_async.AsyncPSClient((host, port), rank=1)
        a.init("w", np.zeros(2, np.float32))
        b_pushed = []

        a.push("w", np.ones(2, np.float32))  # both have pushed once; a=1
        b.push("w", np.ones(2, np.float32))  # b=1
        a.push("w", np.ones(2, np.float32))  # a=2
        a.push("w", np.ones(2, np.float32))  # a=3, b=1: a is 2 ahead (=S ok)

        import threading
        done = threading.Event()

        def runaway():
            a.push("w", np.ones(2, np.float32))  # would be 3 ahead: blocks
            done.set()

        t = threading.Thread(target=runaway, daemon=True)
        t.start()
        assert not done.wait(timeout=0.8)  # blocked by the SSP bound
        b.push("w", np.ones(2, np.float32))  # laggard catches up (b=2)
        assert done.wait(timeout=10)  # unblocked
    finally:
        srv.shutdown()


MODULE_WORKER = r"""
import os, sys, time
import numpy as np
import mxnet_tpu as mx

rank = int(sys.argv[1])
epochs = int(sys.argv[2])
np.random.seed(42)  # same data/init on both workers
rng = np.random.RandomState(0)
X = rng.randn(128, 10).astype(np.float32)
W = rng.randn(10, 3).astype(np.float32)
y = X.dot(W).argmax(1).astype(np.float32)
it = mx.io.NDArrayIter(X, y, batch_size=32, label_name="softmax_label")
data = mx.sym.Variable("data")
net = mx.sym.FullyConnected(data, num_hidden=16, name="fc1")
net = mx.sym.Activation(net, act_type="relu")
net = mx.sym.FullyConnected(net, num_hidden=3, name="fc2")
net = mx.sym.SoftmaxOutput(net, name="softmax")
mod = mx.mod.Module(net, context=mx.cpu())
mod.fit(it, num_epoch=epochs, kvstore="dist_async", optimizer="sgd",
        initializer=mx.init.Xavier(),
        optimizer_params={"learning_rate": 0.2, "rescale_grad": 1.0 / 32})
it.reset()
m = mx.metric.Accuracy()
mod.score(it, m)
print("WORKER %d ACC %.3f" % (rank, m.get()[1]), flush=True)
"""


def test_module_fit_against_async_ps(tmp_path):
    """Module.fit(kvstore='dist_async') trains end-to-end against the
    async parameter server: two workers, server-side SGD updates, both
    reach high accuracy on the shared model."""
    srv, (host, port) = ps_async.serve_forever()
    try:
        script = tmp_path / "mw.py"
        script.write_text(MODULE_WORKER)
        procs = []
        for rank in range(2):
            env = dict(os.environ, JAX_PLATFORMS="cpu",
                       PALLAS_AXON_POOL_IPS="", PYTHONPATH=REPO,
                       MXNET_PS_HOST="127.0.0.1", MXNET_PS_PORT=str(port),
                       MXNET_PS_RANK=str(rank), MXNET_PS_NUM_WORKERS="2")
            procs.append(subprocess.Popen(
                [sys.executable, str(script), str(rank), "12"],
                env=env, stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT, text=True))
        accs = []
        for p in procs:
            out, _ = launchutil.communicate(p, timeout=240)
            assert p.returncode == 0, out
            accs.append(float(out.split("ACC")[1].split()[0]))
        assert all(a > 0.9 for a in accs), (accs,)
    finally:
        srv.shutdown()


def test_wire_format_is_not_executable():
    """The PS wire is JSON header + raw numpy bytes (reference ps-lite
    moves raw SArray<char>, not executable objects). pickle must be gone:
    a malicious frame can, at worst, fail dtype/shape validation — it can
    never run code (advisor r3 medium finding)."""
    import io
    import pickle
    import socket as socket_mod

    src = open(os.path.join(REPO, "mxnet_tpu", "parallel",
                            "ps_async.py")).read()
    assert "import pickle" not in src, "ps_async.py must not use pickle"

    # a pickle bomb sent to the server must be rejected, not executed
    srv, (host, port) = ps_async.serve_forever()
    try:
        class Boom:
            def __reduce__(self):
                return (print, ("EXECUTED",))
        evil = pickle.dumps(Boom())
        s = socket_mod.create_connection((host, port), timeout=10)
        import struct
        s.sendall(struct.pack("<Q", len(evil)) + evil)
        # server drops the connection (bad frame), no crash, still serves
        s.close()
        c = ps_async.AsyncPSClient((host, port), rank=0)
        c.init("x", np.ones(2, np.float32))
        np.testing.assert_allclose(c.pull("x"), 1.0)
        c.close()
    finally:
        srv.shutdown()

    # set_optimizer ships a registry name + scalar attrs, not an object
    name, attrs = ps_async.optimizer_spec(
        __import__("mxnet_tpu").optimizer.SGD(learning_rate=0.25))
    assert name == "sgd"
    assert all(isinstance(v, (int, float, bool, str, type(None)))
               for v in attrs.values())
    o = ps_async.optimizer_from_spec(name, attrs)
    assert type(o).__name__ == "SGD"
    with pytest.raises(ValueError):
        ps_async.optimizer_from_spec("os.system", {})


def test_wire_rejects_exotic_dtype():
    srv, (host, port) = ps_async.serve_forever()
    try:
        c = ps_async.AsyncPSClient((host, port), rank=0)
        with pytest.raises(ValueError, match="not allowed"):
            c.init("o", np.array([object()], dtype=object))
        c.close()
    finally:
        srv.shutdown()


def test_push_pull_throughput_25m_params():
    """Measured wire throughput for a 25M-param (100 MB fp32) push+pull —
    the raw-buffer frames must sustain real bandwidth (the old
    pickled-object path serialized through Python on every hop). Floor is
    conservative for loaded CI hosts; the printed number is the record."""
    srv, (host, port) = ps_async.serve_forever()
    try:
        c = ps_async.AsyncPSClient((host, port), rank=0)
        w = np.zeros(25_000_000, np.float32)
        c.init("big", w)
        g = np.ones_like(w)
        t0 = time.time()
        reps = 3
        for _ in range(reps):
            c.push("big", g)
            out = c.pull("big")
        dt = time.time() - t0
        mb = reps * 2 * w.nbytes / 1e6
        rate = mb / dt
        print("async PS push+pull: %.0f MB in %.2fs = %.0f MB/s"
              % (mb, dt, rate), flush=True)
        assert out.shape == w.shape
        assert rate > 50, "throughput %.0f MB/s is implausibly low" % rate
        c.close()
    finally:
        srv.shutdown()


def test_async_four_workers_one_straggler(tmp_path):
    """Round-5 scale-out: 4 async workers, one straggler — the three
    fast workers finish while the straggler sleeps (no barrier at any
    fan-in width), and every worker's updates land on the shared key."""
    srv, (host, port) = ps_async.serve_forever()
    try:
        extra = {"MXNET_PS_NUM_WORKERS": "4"}
        fast = [_spawn_worker(tmp_path, r, 20, 0.0, port, extra)
                for r in range(3)]
        slow = _spawn_worker(tmp_path, 3, 3, 1.5, port, extra)
        for p in fast:
            out, _ = launchutil.communicate(p, timeout=180)
            assert p.returncode == 0, out
            loop = float(out.split("DONE")[1].split()[0])
            assert loop < 4.0, (loop, out)
        out_slow, _ = launchutil.communicate(slow, timeout=180)
        assert slow.returncode == 0, out_slow
        assert float(out_slow.split("DONE")[1].split()[0]) >= 4.5
        c = ps_async.AsyncPSClient((host, port), rank=9)
        val = c.pull("w")
        assert np.isfinite(val).all()
        c.close()
    finally:
        srv.shutdown()
