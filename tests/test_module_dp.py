"""Single-process multi-device data parallelism through Module.

Reference `python/mxnet/module/executor_group.py:129,289,330`:
`Module(context=[gpu(0),gpu(1),...])` slices every batch across the bound
devices and reduces gradients. Here the same API binds ONE SPMD executor
over a 'dp' mesh (inputs batch-sharded, params replicated, gradient psum
in-program), so an N-device run must reproduce the 1-device loss/parameter
trajectory exactly (same global batch, same reductions, same RNG stream).

Runs on the 8 virtual CPU devices the conftest forces."""
import numpy as np
import pytest

import mxnet_tpu as mx


def _make_data(n=256, d=20, k=3, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, d).astype(np.float32)
    W = rng.randn(d, k).astype(np.float32)
    y = X.dot(W).argmax(axis=1).astype(np.float32)
    return X, y


def _mlp(with_bn=False):
    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data, num_hidden=16, name="fc1")
    if with_bn:
        net = mx.sym.BatchNorm(net, name="bn1", fix_gamma=False)
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.FullyConnected(net, num_hidden=3, name="fc2")
    return mx.sym.SoftmaxOutput(net, name="softmax")


def _train(contexts, with_bn=False, optimizer="sgd",
           opt_params=(("learning_rate", 0.5), ("momentum", 0.9)),
           epochs=6):
    X, y = _make_data()
    it = mx.io.NDArrayIter(X, y, batch_size=32, shuffle=False,
                           label_name="softmax_label")
    mod = mx.mod.Module(_mlp(with_bn), context=contexts)
    mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label)
    mx.random.seed(7)
    np.random.seed(7)  # initializers draw from numpy's global RNG
    mod.init_params(initializer=mx.init.Xavier(rnd_type="uniform",
                                               factor_type="avg",
                                               magnitude=2))
    mod.init_optimizer(optimizer=optimizer, optimizer_params=opt_params)
    metric = mx.metric.Accuracy()
    accs = []
    for _ in range(epochs):
        it.reset()
        metric.reset()
        for batch in it:
            mod._step(batch)
            mod.update_metric(metric, batch.label)
        accs.append(metric.get()[1])
    args, auxs = mod.get_params()
    return accs, {n: a.asnumpy() for n, a in args.items()}, \
        {n: a.asnumpy() for n, a in auxs.items()}


def test_dp_module_matches_single_device_trajectory():
    accs1, args1, _ = _train([mx.cpu(0)])
    ctxs = [mx.cpu(i) for i in range(8)]
    accs8, args8, _ = _train(ctxs)
    assert accs8 == pytest.approx(accs1, abs=1e-3)
    for name in args1:
        np.testing.assert_allclose(args8[name], args1[name],
                                   rtol=2e-4, atol=2e-5, err_msg=name)
    assert accs8[-1] > 0.8  # it actually learns (>0.9 covered by the
    # longer-horizon score test below; this lr/momentum setting oscillates)


def test_dp_module_batchnorm_cross_replica_stats():
    """BN over a dp-sharded batch must use GLOBAL batch statistics (the
    mean reduce spans the sharded axis), matching the single-device run —
    stronger than the reference's per-device BN."""
    accs1, args1, aux1 = _train([mx.cpu(0)], with_bn=True)
    accs8, args8, aux8 = _train([mx.cpu(i) for i in range(8)], with_bn=True)
    assert accs8 == pytest.approx(accs1, abs=1e-3)
    for name in aux1:  # moving_mean / moving_var match => global stats
        np.testing.assert_allclose(aux8[name], aux1[name],
                                   rtol=2e-4, atol=2e-5, err_msg=name)


def test_dp_module_adam_states_sharded_consistently():
    accs8, _, _ = _train([mx.cpu(i) for i in range(8)], optimizer="adam",
                         opt_params=(("learning_rate", 0.01),))
    assert accs8[-1] > 0.8


def test_dp_module_forward_outputs_global_batch():
    X, y = _make_data(n=64)
    it = mx.io.NDArrayIter(X, y, batch_size=64, label_name="softmax_label")
    mod = mx.mod.Module(_mlp(), context=[mx.cpu(i) for i in range(8)])
    mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label)
    mod.init_params()
    batch = next(it)
    mod.forward(batch, is_train=False)
    out = mod.get_outputs()[0]
    assert out.shape == (64, 3)
    probs = out.asnumpy()
    np.testing.assert_allclose(probs.sum(axis=1), 1.0, rtol=1e-5)


def test_dp_module_rejects_indivisible_batch():
    X, y = _make_data(n=60)
    it = mx.io.NDArrayIter(X, y, batch_size=30, label_name="softmax_label")
    mod = mx.mod.Module(_mlp(), context=[mx.cpu(i) for i in range(8)])
    with pytest.raises(Exception, match="divisible"):
        mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label)


def test_dp_module_score_and_predict():
    X, y = _make_data()
    it = mx.io.NDArrayIter(X, y, batch_size=32, label_name="softmax_label")
    mod = mx.mod.Module(_mlp(), context=[mx.cpu(i) for i in range(8)])
    mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label)
    np.random.seed(3)
    mod.init_params(initializer=mx.init.Xavier())
    mod.init_optimizer(optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.5),))
    for _ in range(5):
        it.reset()
        for batch in it:
            mod._step(batch)
    it.reset()
    metric = mx.metric.Accuracy()
    mod.score(it, metric)
    assert metric.get()[1] > 0.9


def _train_fit(ctxs, batches_per_dispatch, epochs=4):
    X, y = _make_data()
    it = mx.io.NDArrayIter(X, y, batch_size=32, shuffle=False,
                           label_name="softmax_label")
    mod = mx.mod.Module(_mlp(), context=ctxs)
    mx.random.seed(5)
    np.random.seed(5)
    import logging
    mod.fit(it, num_epoch=epochs, optimizer="sgd",
            initializer=mx.init.Xavier(),
            optimizer_params=(("learning_rate", 0.3), ("momentum", 0.9)),
            batches_per_dispatch=batches_per_dispatch)
    args, _ = mod.get_params()
    it.reset()
    metric = mx.metric.Accuracy()
    mod.score(it, metric)
    return metric.get()[1], {n: a.asnumpy() for n, a in args.items()}


def test_step_scan_matches_per_step():
    """fit(batches_per_dispatch=K) — K fused steps in one lax.scan dispatch
    — must reproduce the per-batch _step trajectory exactly."""
    acc1, p1 = _train_fit([mx.cpu(0)], 1)
    accK, pK = _train_fit([mx.cpu(0)], 4)
    assert accK == pytest.approx(acc1, abs=1e-3)
    for name in p1:
        np.testing.assert_allclose(pK[name], p1[name], rtol=1e-4,
                                   atol=1e-5, err_msg=name)


def test_step_scan_on_dp_mesh():
    """scan-of-steps composes with SPMD dp sharding."""
    acc1, p1 = _train_fit([mx.cpu(0)], 4)
    acc8, p8 = _train_fit([mx.cpu(i) for i in range(8)], 4)
    assert acc8 == pytest.approx(acc1, abs=2e-2)
    for name in p1:
        np.testing.assert_allclose(p8[name], p1[name], rtol=2e-3,
                                   atol=2e-4, err_msg=name)


def test_step_scan_metric_counts_every_batch():
    X, y = _make_data(n=96)
    it = mx.io.NDArrayIter(X, y, batch_size=32, label_name="softmax_label")
    mod = mx.mod.Module(_mlp(), context=[mx.cpu(0)])
    seen = []
    mod.fit(it, num_epoch=1, optimizer="sgd",
            initializer=mx.init.Xavier(),
            batches_per_dispatch=2,
            batch_end_callback=lambda p: seen.append(p.nbatch))
    assert seen == [0, 1, 2]  # 3 batches -> one scan(2) + one plain step


def test_dp_with_bf16_type_dict():
    """SPMD dp composes with bf16 binding (type_dict)."""
    X, y = _make_data()
    it = mx.io.NDArrayIter(X, y, batch_size=32, label_name="softmax_label")
    mod = mx.mod.Module(_mlp(), context=[mx.cpu(i) for i in range(8)])
    td = {"data": "bfloat16"}
    td.update({p_: "bfloat16" for p_ in mod._param_names})
    mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label,
             type_dict=td)
    np.random.seed(0)
    mod.init_params(initializer=mx.init.Xavier())
    mod.init_optimizer(optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.5),))
    for _ in range(3):
        it.reset()
        for batch in it:
            mod._step(batch)
    w = mod._exec.arg_dict["fc1_weight"]
    assert str(w.dtype) == "bfloat16"
    assert len(w._data.sharding.device_set) == 8
    out = mod.get_outputs()[0].asnumpy().astype(np.float32)
    assert np.isfinite(out).all()


def test_dp_with_bucketing_module():
    """BucketingModule shares the dp-sharded parameter arrays across
    bucket executors (shared_exec carries the shardings)."""
    ctxs = [mx.cpu(i) for i in range(8)]

    def sym_gen(seq_len):
        data = mx.sym.Variable("data")
        net = mx.sym.FullyConnected(
            mx.sym.Reshape(data, shape=(-1, 4)), num_hidden=8, name="fc1")
        net = mx.sym.Activation(net, act_type="relu")
        net = mx.sym.FullyConnected(net, num_hidden=3, name="fc2")
        return mx.sym.SoftmaxOutput(net, name="softmax"), ("data",), \
            ("softmax_label",)

    mod = mx.mod.BucketingModule(sym_gen, default_bucket_key=8,
                                 context=ctxs)
    mod.bind(data_shapes=[("data", (16, 8, 4))],
             label_shapes=[("softmax_label", (16 * 8,))])
    np.random.seed(0)
    mod.init_params(initializer=mx.init.Xavier())
    mod.init_optimizer(optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.1),))
    rng = np.random.RandomState(0)
    for key in (8, 4, 8):
        batch = mx.io.DataBatch(
            data=[mx.nd.array(rng.rand(16, key, 4).astype(np.float32))],
            label=[mx.nd.array((rng.rand(16 * key) * 3)
                               .astype(np.float32))],
            bucket_key=key,
            provide_data=[("data", (16, key, 4))],
            provide_label=[("softmax_label", (16 * key,))], pad=0)
        mod.forward_backward(batch)
        mod.update()
    w = mod._curr_module._exec.arg_dict["fc1_weight"]
    assert len(w._data.sharding.device_set) == 8  # stayed on the mesh
