"""Module API tests (modeled on reference test_module.py + tests/python/train).

Includes the end-to-end slice: Module.fit on a synthetic separable problem
must reach high accuracy (reference tests/python/train/test_mlp.py pattern).
"""
import logging

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.test_utils import assert_almost_equal


def _mlp_sym(num_hidden=32, num_classes=4):
    data = mx.sym.var("data")
    fc1 = mx.sym.FullyConnected(data, num_hidden=num_hidden, name="fc1")
    act1 = mx.sym.Activation(fc1, act_type="relu", name="relu1")
    fc2 = mx.sym.FullyConnected(act1, num_hidden=num_classes, name="fc2")
    return mx.sym.SoftmaxOutput(fc2, name="softmax")


def _synthetic_data(n=400, dim=10, classes=4, seed=0):
    rng = np.random.RandomState(seed)
    centers = rng.uniform(-3, 3, (classes, dim)).astype(np.float32)
    labels = rng.randint(0, classes, n)
    x = centers[labels] + rng.normal(0, 0.3, (n, dim)).astype(np.float32)
    return x.astype(np.float32), labels.astype(np.float32)


def test_module_bind_forward():
    net = _mlp_sym()
    mod = mx.mod.Module(net, context=mx.cpu())
    mod.bind(data_shapes=[("data", (8, 10))], label_shapes=[("softmax_label", (8,))])
    mod.init_params()
    batch = mx.io.DataBatch(data=[mx.nd.ones((8, 10))],
                            label=[mx.nd.zeros((8,))])
    mod.forward(batch, is_train=False)
    out = mod.get_outputs()[0]
    assert out.shape == (8, 4)
    assert_almost_equal(out.asnumpy().sum(1), np.ones(8), rtol=1e-4)


def test_module_fit_converges():
    x, y = _synthetic_data()
    train_iter = mx.io.NDArrayIter(x, y, batch_size=32, shuffle=True)
    val_iter = mx.io.NDArrayIter(x, y, batch_size=32)
    mod = mx.mod.Module(_mlp_sym(), context=mx.cpu())
    mod.fit(train_iter, eval_data=val_iter, optimizer="sgd",
            optimizer_params={"learning_rate": 0.5}, num_epoch=6,
            eval_metric="acc")
    score = mod.score(val_iter, "acc")
    assert score[0][1] > 0.95, "accuracy %f too low" % score[0][1]


def test_module_fit_adam_kvstore_device():
    x, y = _synthetic_data(seed=1)
    train_iter = mx.io.NDArrayIter(x, y, batch_size=25)
    mod = mx.mod.Module(_mlp_sym(), context=mx.cpu())
    mod.fit(train_iter, optimizer="adam",
            optimizer_params={"learning_rate": 0.05}, num_epoch=5,
            kvstore="device")
    score = mod.score(mx.io.NDArrayIter(x, y, batch_size=25), "acc")
    assert score[0][1] > 0.9


def test_module_predict_and_outputs():
    x, y = _synthetic_data(n=64)
    mod = mx.mod.Module(_mlp_sym(), context=mx.cpu())
    it = mx.io.NDArrayIter(x, y, batch_size=16)
    mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label)
    mod.init_params()
    pred = mod.predict(it)
    assert pred.shape == (64, 4)


def test_module_save_load_checkpoint(tmp_path):
    x, y = _synthetic_data(n=64)
    it = mx.io.NDArrayIter(x, y, batch_size=16)
    mod = mx.mod.Module(_mlp_sym(), context=mx.cpu())
    mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label)
    mod.init_params()
    prefix = str(tmp_path / "model")
    mod.save_checkpoint(prefix, 3)
    mod2 = mx.mod.Module.load(prefix, 3)
    mod2.bind(data_shapes=it.provide_data, label_shapes=it.provide_label)
    a1, _ = mod.get_params()
    a2, _ = mod2.get_params()
    for k in a1:
        assert_almost_equal(a1[k], a2[k].asnumpy())
    # predictions identical
    p1 = mod.predict(it).asnumpy()
    p2 = mod2.predict(it).asnumpy()
    assert_almost_equal(p1, p2, rtol=1e-5)


def test_module_get_set_params():
    mod = mx.mod.Module(_mlp_sym(), context=mx.cpu())
    mod.bind(data_shapes=[("data", (4, 10))],
             label_shapes=[("softmax_label", (4,))])
    mod.init_params()
    args, auxs = mod.get_params()
    args = {k: v.copy() for k, v in args.items()}
    args["fc1_bias"][:] = 7
    mod.set_params(args, auxs)
    new_args, _ = mod.get_params()
    assert (new_args["fc1_bias"].asnumpy() == 7).all()


def test_module_input_grads():
    mod = mx.mod.Module(_mlp_sym(), context=mx.cpu())
    mod.bind(data_shapes=[("data", (4, 10))],
             label_shapes=[("softmax_label", (4,))],
             inputs_need_grad=True)
    mod.init_params()
    batch = mx.io.DataBatch(data=[mx.nd.ones((4, 10))],
                            label=[mx.nd.zeros((4,))])
    mod.forward_backward(batch)
    ig = mod.get_input_grads()[0]
    assert ig.shape == (4, 10)
    assert np.abs(ig.asnumpy()).sum() > 0


def test_bucketing_module():
    def sym_gen(seq_len):
        data = mx.sym.var("data")
        fc = mx.sym.FullyConnected(data, num_hidden=8, name="fc")
        out = mx.sym.SoftmaxOutput(fc, name="softmax")
        return out, ("data",), ("softmax_label",)

    mod = mx.mod.BucketingModule(sym_gen, default_bucket_key=10,
                                 context=mx.cpu())
    mod.bind(data_shapes=[("data", (4, 10))],
             label_shapes=[("softmax_label", (4,))])
    mod.init_params()
    mod.init_optimizer(optimizer="sgd")
    for key, dim in [(10, 10), (5, 5), (10, 10)]:
        batch = mx.io.DataBatch(
            data=[mx.nd.ones((4, dim))], label=[mx.nd.zeros((4,))],
            bucket_key=key,
            provide_data=[mx.io.DataDesc("data", (4, dim))],
            provide_label=[mx.io.DataDesc("softmax_label", (4,))])
        mod.forward_backward(batch)
        mod.update()
    assert len(mod._buckets) == 2
    # parameters shared across buckets
    m10 = mod._buckets[10]
    m5 = mod._buckets[5]
    assert m10._exec.arg_dict["fc_bias"] is m5._exec.arg_dict["fc_bias"]


def test_sequential_module():
    net1 = mx.sym.FullyConnected(mx.sym.var("data"), num_hidden=8, name="fc1")
    net2 = mx.sym.SoftmaxOutput(mx.sym.FullyConnected(mx.sym.var("data"),
                                                      num_hidden=4, name="fc2"),
                                name="softmax")
    mod = mx.mod.SequentialModule()
    mod.add(mx.mod.Module(net1, label_names=None, context=mx.cpu()))
    mod.add(mx.mod.Module(net2, context=mx.cpu()), take_labels=True,
            auto_wiring=True)
    mod.bind(data_shapes=[("data", (4, 10))],
             label_shapes=[("softmax_label", (4,))])
    mod.init_params()
    batch = mx.io.DataBatch(data=[mx.nd.ones((4, 10))],
                            label=[mx.nd.zeros((4,))])
    mod.forward(batch, is_train=False)
    assert mod.get_outputs()[0].shape == (4, 4)


def test_module_fixed_params():
    mod = mx.mod.Module(_mlp_sym(), context=mx.cpu(),
                        fixed_param_names=["fc1_weight"])
    mod.bind(data_shapes=[("data", (4, 10))],
             label_shapes=[("softmax_label", (4,))])
    mod.init_params()
    mod.init_optimizer()
    w_before = mod._exec.arg_dict["fc1_weight"].asnumpy().copy()
    w2_before = mod._exec.arg_dict["fc2_weight"].asnumpy().copy()
    batch = mx.io.DataBatch(data=[mx.nd.ones((4, 10))],
                            label=[mx.nd.zeros((4,))])
    mod.forward_backward(batch)
    mod.update()
    assert_almost_equal(mod._exec.arg_dict["fc1_weight"], w_before)
    assert not np.allclose(mod._exec.arg_dict["fc2_weight"].asnumpy(), w2_before)


def test_feedforward_legacy():
    x, y = _synthetic_data(n=128)
    model = mx.FeedForward(_mlp_sym(), ctx=mx.cpu(), num_epoch=3,
                           numpy_batch_size=32,
                           optimizer_params={"learning_rate": 0.5})
    model.fit(x, y)
    pred = model.predict(x)
    assert pred.shape == (128, 4)


def test_python_loss_module():
    """PythonLossModule spliced after a Module inside SequentialModule
    (reference python_module.py pattern): custom python loss gradient
    drives the network."""
    import numpy as np

    x, y = _synthetic_data(n=300, dim=10, classes=4, seed=3)
    data = mx.sym.var("data")
    net = mx.sym.FullyConnected(data, num_hidden=4, name="fcout")

    def ce_grad(scores, labels):
        s = scores.asnumpy()
        p = np.exp(s - s.max(1, keepdims=True))
        p /= p.sum(1, keepdims=True)
        lab = labels.asnumpy().astype(int)
        p[np.arange(len(lab)), lab] -= 1.0
        return p / len(lab)

    seq = mx.mod.SequentialModule()
    seq.add(mx.mod.Module(net, label_names=[]))
    seq.add(mx.mod.PythonLossModule(grad_func=ce_grad), take_labels=True,
            auto_wiring=True)
    train = mx.io.NDArrayIter(x, y, batch_size=50, shuffle=True,
                              label_name="softmax_label")
    seq.bind(data_shapes=train.provide_data,
             label_shapes=train.provide_label, inputs_need_grad=False)
    seq.init_params(mx.init.Xavier())
    seq.init_optimizer(optimizer="sgd",
                       optimizer_params={"learning_rate": 0.5})
    for _ in range(25):
        train.reset()
        for batch in train:
            seq.forward(batch, is_train=True)
            seq.backward()
            seq.update()
    # accuracy via the first module's outputs
    train.reset()
    correct = total = 0
    for batch in train:
        seq.forward(batch, is_train=False)
        out = seq.get_outputs()[0].asnumpy()
        n = out.shape[0] - batch.pad
        correct += (out[:n].argmax(1) == batch.label[0].asnumpy()[:n]).sum()
        total += n
    assert correct / total > 0.9, correct / total


def test_step_scan_pack_small_matches_unpacked():
    """Module.scan_pack_small (flat-packed rank<=1 carries) must produce
    the same training trajectory as the plain scan."""
    import numpy as np

    def build():
        data = mx.sym.var("data")
        net = mx.sym.FullyConnected(data, num_hidden=8, name="fc1")
        net = mx.sym.BatchNorm(net, fix_gamma=False, name="bn")
        net = mx.sym.Activation(net, act_type="relu")
        net = mx.sym.FullyConnected(net, num_hidden=3, name="fc2")
        net = mx.sym.SoftmaxOutput(net, name="softmax")
        mod = mx.mod.Module(net, context=mx.cpu())
        mod.bind(data_shapes=[("data", (6, 4))],
                 label_shapes=[("softmax_label", (6,))])
        mod.init_params(initializer=mx.init.Xavier(rnd_type="uniform"))
        mod.init_optimizer(optimizer="sgd",
                           optimizer_params={"learning_rate": 0.1,
                                             "momentum": 0.9})
        return mod

    rng = np.random.RandomState(5)
    batches = [mx.io.DataBatch(
        data=[mx.nd.array(rng.randn(6, 4).astype(np.float32))],
        label=[mx.nd.array((rng.rand(6) * 3).astype(np.float32))])
        for _ in range(4)]

    ref = build()
    packed = build()
    a0, x0 = ref.get_params()  # same initial weights for both
    packed.set_params(a0, x0)
    out_ref = ref._step_scan(batches)
    assert out_ref is not False
    packed.scan_pack_small = True
    out_pk = packed._step_scan(batches)
    assert out_pk is not False
    for a, b in zip(out_pk, out_ref):
        assert np.allclose(a.asnumpy(), b.asnumpy(), rtol=1e-5, atol=1e-6)
    a_ref, aux_ref = ref.get_params()
    a_pk, aux_pk = packed.get_params()
    for name in a_ref:
        assert np.allclose(a_pk[name].asnumpy(), a_ref[name].asnumpy(),
                           rtol=1e-5, atol=1e-6), name
    for name in aux_ref:
        assert np.allclose(aux_pk[name].asnumpy(), aux_ref[name].asnumpy(),
                           rtol=1e-5, atol=1e-6), name
