"""Sequence/context parallelism tests on the 8-device CPU mesh.

The reference has no SP (SURVEY.md §2.8); oracle is dense local attention.
"""
import numpy as np
import jax
import jax.numpy as jnp
import pytest
from jax.sharding import PartitionSpec as P, NamedSharding

from mxnet_tpu.parallel import make_mesh
from mxnet_tpu.parallel.ring_attention import (
    ring_attention, ulysses_attention, local_attention, sequence_sharding)


def _qkv(b=2, t=32, h=4, d=8, seed=0):
    rng = np.random.RandomState(seed)
    mk = lambda: jnp.asarray(rng.randn(b, t, h, d).astype(np.float32))
    return mk(), mk(), mk()


@pytest.fixture(scope="module")
def sp_mesh():
    return make_mesh({"sp": 4})


def _shard(mesh, *xs):
    s = sequence_sharding(mesh)
    return tuple(jax.device_put(x, s) for x in xs)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_matches_dense(sp_mesh, causal):
    q, k, v = _qkv()
    want = local_attention(q, k, v, causal=causal)
    qs, ks, vs = _shard(sp_mesh, q, k, v)
    got = ring_attention(qs, ks, vs, mesh=sp_mesh, causal=causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_matches_dense(sp_mesh, causal):
    q, k, v = _qkv()
    want = local_attention(q, k, v, causal=causal)
    qs, ks, vs = _shard(sp_mesh, q, k, v)
    got = ulysses_attention(qs, ks, vs, mesh=sp_mesh, causal=causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_ring_attention_under_jit_keeps_sharding(sp_mesh):
    q, k, v = _qkv()
    qs, ks, vs = _shard(sp_mesh, q, k, v)

    @jax.jit
    def f(q, k, v):
        return ring_attention(q, k, v, mesh=sp_mesh, causal=True)

    out = f(qs, ks, vs)
    spec = out.sharding.spec
    assert tuple(spec)[:2] == (None, "sp")
    want = local_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_ring_attention_grads(sp_mesh):
    q, k, v = _qkv(t=16)

    def loss_ring(q, k, v):
        return jnp.sum(ring_attention(q, k, v, mesh=sp_mesh, causal=True) ** 2)

    def loss_dense(q, k, v):
        return jnp.sum(local_attention(q, k, v, causal=True) ** 2)

    qs, ks, vs = _shard(sp_mesh, q, k, v)
    g_ring = jax.grad(loss_ring, argnums=(0, 1, 2))(qs, ks, vs)
    g_dense = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for gr, gd in zip(g_ring, g_dense):
        np.testing.assert_allclose(np.asarray(gr), np.asarray(gd),
                                   rtol=1e-4, atol=1e-4)


def test_ulysses_rejects_indivisible_heads(sp_mesh):
    q, k, v = _qkv(h=3)
    with pytest.raises(ValueError):
        ulysses_attention(q, k, v, mesh=sp_mesh)
