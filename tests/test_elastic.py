"""Elastic training supervisor (`parallel/elastic.py`): commit-marked
step-numbered checkpoints with retention, resume-from-latest, chaos-driven
recovery (injected step failures, coordinator timeouts, torn checkpoint
writes), the fit(elastic=...) hook, and — launched — a 2-process run that
loses a worker mid-run and finishes after a supervised restart from the
last complete checkpoint."""
import os
import subprocess
import sys

import numpy as np
import pytest
import jax.numpy as jnp

import launchutil
import mxnet_tpu as mx
from mxnet_tpu import chaos
from mxnet_tpu.parallel import (ElasticCheckpointer, ElasticTrainer,
                                RetryPolicy, RetryError, abstract_like,
                                elastic, load_sharded)
from mxnet_tpu.parallel import retry as retry_mod

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture
def no_sleep(monkeypatch):
    sleeps = []
    monkeypatch.setattr(retry_mod, "_sleep", sleeps.append)
    return sleeps


def _count_step(state, step):
    return {"w": state["w"] + 1.0}


# ---------------------------------------------------------------------------
# checkpointer: commit marker, rotation, torn writes
# ---------------------------------------------------------------------------

def test_checkpointer_commit_and_restore(tmp_path):
    ck = ElasticCheckpointer(str(tmp_path / "ck"), keep_last=3)
    tree = {"w": jnp.arange(4.0)}
    ck.save(5, tree)
    assert ck.latest_step() == 5
    assert ck.is_complete(5)
    step, out = ck.restore(abstract_like(tree))
    assert step == 5
    np.testing.assert_allclose(np.asarray(out["w"]), np.arange(4.0))


def test_torn_checkpoint_never_restored(tmp_path):
    """chaos interrupts the write after the payload but before the COMMIT
    marker: the torn step is invisible to latest_step/restore and reaped
    once a newer commit lands."""
    ck = ElasticCheckpointer(str(tmp_path / "ck"), keep_last=3)
    tree = {"w": jnp.arange(4.0)}
    ck.save(5, tree)
    chaos.arm("checkpoint.interrupt")
    with pytest.raises(chaos.ChaosInterrupt):
        ck.save(10, {"w": jnp.arange(4.0) * 3})
    assert os.path.exists(ck.step_dir(10))  # payload landed...
    assert not ck.is_complete(10)           # ...but was never committed
    assert ck.latest_step() == 5
    with pytest.raises(ValueError, match="not committed"):
        ck.restore(abstract_like(tree), step=10)
    ck.save(11, tree)  # newer commit: retention reaps the torn dir
    assert not os.path.exists(ck.step_dir(10))


def test_retention_keeps_last_n(tmp_path):
    ck = ElasticCheckpointer(str(tmp_path / "ck"), keep_last=2)
    tree = {"w": jnp.zeros(3)}
    for s in (1, 2, 3, 4):
        ck.save(s, tree)
    assert ck.steps() == [3, 4]
    assert not os.path.exists(ck.step_dir(1))


def test_restore_with_no_checkpoint_raises(tmp_path):
    ck = ElasticCheckpointer(str(tmp_path / "empty"))
    with pytest.raises(FileNotFoundError, match="COMMIT"):
        ck.restore(abstract_like({"w": jnp.zeros(2)}))


# ---------------------------------------------------------------------------
# load_sharded error contract (satellite: no raw orbax tracebacks)
# ---------------------------------------------------------------------------

def test_load_sharded_missing_path_clear_error(tmp_path):
    tmpl = abstract_like({"w": jnp.zeros(2)})
    missing = str(tmp_path / "nope")
    with pytest.raises(FileNotFoundError, match="commit marker"):
        load_sharded(missing, tmpl)
    with pytest.raises(FileNotFoundError, match="nope"):
        load_sharded(missing, tmpl)


def test_load_sharded_torn_dir_clear_error(tmp_path):
    torn = tmp_path / "step_00000001" / "state"
    torn.mkdir(parents=True)
    (torn / "junk").write_text("not a checkpoint")
    with pytest.raises(ValueError, match="commit marker: absent"):
        load_sharded(str(torn), abstract_like({"w": jnp.zeros(2)}))


def test_local_backend_template_mismatch(tmp_path):
    ck = ElasticCheckpointer(str(tmp_path / "ck"), backend="local")
    ck.save(1, {"a": jnp.zeros(2), "b": jnp.zeros(3)})
    with pytest.raises(ValueError, match="2 saved leaves vs 3"):
        ck.restore(abstract_like({"a": jnp.zeros(2), "b": jnp.zeros(3),
                                  "c": jnp.zeros(4)}), step=1)
    with pytest.raises(ValueError, match="leaf shape"):
        ck.restore(abstract_like({"a": jnp.zeros(2), "b": jnp.zeros(9)}),
                   step=1)


# ---------------------------------------------------------------------------
# trainer: resume, recovery, retried liveness polls
# ---------------------------------------------------------------------------

def test_trainer_checkpoints_and_resumes(tmp_path, no_sleep):
    root = str(tmp_path / "ck")
    t = ElasticTrainer(_count_step, {"w": jnp.zeros(3)}, ckpt_dir=root,
                       ckpt_every=2, on_failure="recover")
    out = t.run(5)
    np.testing.assert_allclose(np.asarray(out["w"]), 5.0)
    assert t.ckpt.latest_step() == 5  # final save
    calls = []

    def counting(state, step):
        calls.append(step)
        return _count_step(state, step)

    t2 = ElasticTrainer(counting, {"w": jnp.zeros(3)}, ckpt_dir=root,
                        ckpt_every=2, on_failure="recover")
    marker_mtime = os.path.getmtime(
        os.path.join(t.ckpt.step_dir(5), "COMMIT"))
    out2 = t2.run(5)
    assert calls == [] and t2.resumed_from == 5  # nothing left to do
    np.testing.assert_allclose(np.asarray(out2["w"]), 5.0)
    # a no-op resume must not rewrite the existing commit
    assert os.path.getmtime(
        os.path.join(t2.ckpt.step_dir(5), "COMMIT")) == marker_mtime
    assert t2.ckpt.latest_step() == 5
    # resumed past num_steps: no mislabeled earlier-step commit either
    t3 = ElasticTrainer(counting, {"w": jnp.zeros(3)}, ckpt_dir=root,
                        ckpt_every=2, on_failure="recover")
    t3.run(3)
    assert calls == [] and not t3.ckpt.is_complete(3)


def test_trainer_recovers_from_step_failures_with_backoff(tmp_path,
                                                          no_sleep):
    chaos.arm("step.fail", after=3, times=2)
    t = ElasticTrainer(
        _count_step, {"w": jnp.zeros(2)}, ckpt_dir=str(tmp_path / "ck"),
        ckpt_every=2, max_restarts=3, on_failure="recover",
        retry_policy=RetryPolicy(max_attempts=4, base_delay=0.1,
                                 jitter=0.0))
    out = t.run(6)
    assert t.restarts_used == 2
    assert chaos.fired("step.fail") == 2
    # state came back from the step-2 checkpoint both times
    np.testing.assert_allclose(np.asarray(out["w"]), 6.0)
    # bounded exponential backoff between recoveries
    assert no_sleep == pytest.approx([0.1, 0.2])


def test_trainer_gives_up_after_max_restarts(no_sleep):
    chaos.arm("step.fail", times=100)
    t = ElasticTrainer(_count_step, {"w": jnp.zeros(2)}, max_restarts=2,
                       on_failure="recover",
                       retry_policy=RetryPolicy(max_attempts=3,
                                                base_delay=0.01))
    with pytest.raises(RetryError):
        t.run(4)
    assert t.restarts_used == 3  # 2 recoveries + the give-up attempt


def test_recover_refuses_blind_reattach(monkeypatch, no_sleep):
    """A distributed recover with no way to reach the coordinator again
    (no reinit_kwargs, no env) must fail loudly — a bare dist.init()
    would no-op the attach and leave failure detection silently dead."""
    monkeypatch.setattr(elastic, "_is_distributed", lambda: True)
    monkeypatch.delenv("MX_COORDINATOR", raising=False)
    monkeypatch.delenv("DMLC_NUM_WORKER", raising=False)
    chaos.arm("step.fail")
    t = ElasticTrainer(_count_step, {"w": jnp.zeros(2)}, max_restarts=2,
                       on_failure="recover")
    with pytest.raises(RetryError, match="re-attach"):
        t.run(2)


def test_coordinator_timeout_retried_with_backoff_not_fatal(no_sleep):
    """Acceptance: an injected coordinator timeout during the liveness
    poll is retried with growing backoff — attempt count asserted — and
    the run completes instead of crashing or triggering a recovery."""
    chaos.arm("coordinator.timeout", times=2)
    t = ElasticTrainer(_count_step, {"w": jnp.zeros(2)},
                       on_failure="recover")
    t.peer_policy = RetryPolicy(max_attempts=4, base_delay=0.1, jitter=0.0)
    out = t.run(1)
    assert t.peer_policy.last_attempts == 3  # 2 timeouts + 1 success
    assert chaos.fired("coordinator.timeout") == 2
    assert t.restarts_used == 0  # retried at the poll, not recovered
    assert no_sleep == pytest.approx([0.1, 0.2])  # backoff grew
    np.testing.assert_allclose(np.asarray(out["w"]), 1.0)


def test_kvstore_barrier_retries_coordinator_timeout(no_sleep):
    kv = mx.kv.create("dist_sync")
    chaos.arm("coordinator.timeout", times=2)
    kv._barrier_with_retry()
    assert kv._last_barrier_attempts == 3
    assert chaos.fired("coordinator.timeout") == 2
    assert len(no_sleep) == 2


def test_get_num_dead_node_unified_signature():
    from mxnet_tpu.kvstore import AsyncKVStore, KVStore
    # one implementation: the subclass overrides only the transport
    assert AsyncKVStore.get_num_dead_node is KVStore.get_num_dead_node
    kv = mx.kv.create("local")
    assert kv.get_num_dead_node() == 0
    # node_id accepted positionally and by name (reference-API parity),
    # but ignored
    assert kv.get_num_dead_node(3, 1) == 0
    assert mx.kv.create("dist_sync").get_num_dead_node(node_id=7,
                                                       timeout=1) == 0


def test_stop_heartbeat_reports_leaked_thread(caplog):
    from mxnet_tpu.parallel import dist
    assert dist.stop_heartbeat() is True  # no writer running: clean stop

    class Wedged:
        def join(self, timeout=None):
            pass

        def is_alive(self):
            return True

    import logging
    import threading
    dist._HB_THREAD = Wedged()
    dist._HB_STOP = threading.Event()
    with caplog.at_level(logging.WARNING):
        assert dist.stop_heartbeat() is False
    assert "did not stop" in caplog.text
    assert dist._HB_THREAD is None  # writer slot freed either way


def test_dist_shutdown_drops_device_caches():
    from mxnet_tpu.parallel import dist, mesh
    dist._AR_JIT[("probe",)] = object()
    dist._PMESH = object()
    mesh._DP_MESHES[("probe",)] = object()
    dist._initialized = True
    dist.shutdown()
    assert dist._AR_JIT == {}
    assert dist._PMESH is None
    assert mesh._DP_MESHES == {}
    assert not dist._initialized


# ---------------------------------------------------------------------------
# fit(elastic=...) hook
# ---------------------------------------------------------------------------

def _make_module():
    data = mx.sym.var("data")
    net = mx.sym.FullyConnected(data, num_hidden=4, name="fc")
    net = mx.sym.SoftmaxOutput(net, name="softmax")
    return mx.mod.Module(net, context=mx.cpu())


def _make_iter():
    rng = np.random.RandomState(0)
    X = rng.randn(64, 6).astype(np.float32)
    y = (rng.rand(64) * 4).astype(np.float32)
    return mx.io.NDArrayIter(X, y, batch_size=16,
                             label_name="softmax_label")


def test_fit_elastic_checkpoints_and_resumes(tmp_path):
    ckdir = str(tmp_path / "elastic")
    it = _make_iter()
    mod = _make_module()
    mod.fit(it, num_epoch=3, elastic=ckdir, initializer=mx.init.Xavier(),
            optimizer_params={"learning_rate": 0.05, "momentum": 0.9})
    ck = ElasticCheckpointer(ckdir)
    assert ck.latest_step() == 3
    # optimizer state (momentum) rides under the same commit marker
    assert os.path.exists(os.path.join(ck.step_dir(3), "opt_states"))
    a1, _ = mod.get_params()

    # a restarted run with the same dir fast-forwards past done epochs
    batches = []
    mod2 = _make_module()
    mod2.fit(_make_iter(), num_epoch=3, elastic=ckdir,
             initializer=mx.init.Zero(),
             batch_end_callback=lambda p: batches.append(p.nbatch))
    assert batches == []  # resumed at epoch 3 of 3: no training left
    a2, _ = mod2.get_params()
    for k in a1:  # and it carries the trained parameters, not Zero()
        np.testing.assert_allclose(a2[k].asnumpy(), a1[k].asnumpy())

    # extending the run resumes at 3 and trains 2 more epochs; a TUPLE
    # of user callbacks must survive the elastic callback append
    epochs_seen = []
    mod3 = _make_module()
    mod3.fit(_make_iter(), num_epoch=5,
             elastic={"path": ckdir, "keep_last": 2},
             initializer=mx.init.Zero(),
             epoch_end_callback=(lambda e, *a: epochs_seen.append(e),),
             optimizer_params={"learning_rate": 0.05, "momentum": 0.9})
    assert epochs_seen == [3, 4]
    assert ck.latest_step() == 5
    assert ck.steps() == [4, 5]  # keep_last=2 rotation

    # misconfiguration fails loudly, not by silent defaulting
    with pytest.raises(ValueError, match="elastic"):
        _make_module().fit(_make_iter(), num_epoch=1,
                           elastic={"path": ckdir, "keeplast": 10})


# ---------------------------------------------------------------------------
# host-side supervisor
# ---------------------------------------------------------------------------

def test_supervise_relaunches_until_round_succeeds(tmp_path, no_sleep):
    script = tmp_path / "w.py"
    script.write_text(
        "import os, sys\n"
        "r = int(os.environ['MXNET_ELASTIC_RESTART'])\n"
        "print('incarnation', r)\n"
        "sys.exit(0 if r >= 2 else 75)\n")
    restarts, log_dir = elastic.supervise(
        lambda rank, restart, coord: [sys.executable, str(script)],
        nprocs=2, max_restarts=3, log_dir=str(tmp_path / "logs"),
        round_timeout=60)
    assert restarts == 2
    out = open(os.path.join(log_dir, "r2_rank0.log")).read()
    assert "incarnation 2" in out


def test_supervise_gives_up_after_max_restarts(tmp_path, no_sleep):
    script = tmp_path / "w.py"
    script.write_text("import sys; sys.exit(1)\n")
    with pytest.raises(RetryError, match="all 2 rounds failed"):
        elastic.supervise(
            lambda rank, restart, coord: [sys.executable, str(script)],
            nprocs=1, max_restarts=1, log_dir=str(tmp_path / "logs"),
            round_timeout=60)


# ---------------------------------------------------------------------------
# launched: kill a worker mid-run, restart, resume from last commit
# ---------------------------------------------------------------------------

ELASTIC_WORKER = r"""
import os, sys, time
coord, rank, ckdir = sys.argv[1], int(sys.argv[2]), sys.argv[3]
restart = int(os.environ.get("MXNET_ELASTIC_RESTART", "0"))
if restart == 0 and rank == 1:
    # incarnation 0 only: rank 1 crashes at the top of step 7 — strictly
    # AFTER the step-5 checkpoint committed, mid-run (chaos armed via env
    # so it's live before any import)
    os.environ["MXNET_CHAOS"] = "worker.death@7"
import numpy as np
import mxnet_tpu as mx
from mxnet_tpu.parallel import dist, elastic
import jax.numpy as jnp

dist.init(coord, 2, rank, recoverable=True)
dist.stop_heartbeat(); dist.start_heartbeat(interval=0.1)

def step_fn(state, step):
    time.sleep(0.25)
    return {"w": state["w"] + 1.0}

t = elastic.ElasticTrainer(step_fn, {"w": jnp.zeros(4)}, ckpt_dir=ckdir,
                           ckpt_every=5, on_failure="exit",
                           dead_node_timeout=1.0, watchdog_interval=0.25)
out = t.run(20)
print("RESUMED_FROM", t.resumed_from, flush=True)
print("FINAL", float(np.asarray(out["w"])[0]), flush=True)
dist.stop_heartbeat()
os._exit(0)  # skip jax's shutdown barrier (peer histories differ)
"""


@pytest.mark.launched
@pytest.mark.timeout(180)
def test_kill_and_resume_finishes_training(tmp_path):
    """Acceptance: a launched 2-process elastic run loses a worker
    mid-run (chaos), the pod is torn down and relaunched by the
    supervisor, and the new incarnation restores from the last COMPLETE
    checkpoint and finishes all 20 steps.

    Determinism: commits need BOTH ranks at the host barrier, and rank 1
    dies at step 7, so step 5 is provably the last commit of incarnation
    0 no matter how far rank 0 raced ahead before the heartbeat watchdog
    (or the supervisor reacting to rank 1's exit) tore it down."""
    worker = tmp_path / "worker.py"
    worker.write_text(ELASTIC_WORKER)
    ckdir = str(tmp_path / "ck")
    env = dict(os.environ, JAX_PLATFORMS="cpu", PALLAS_AXON_POOL_IPS="",
               PYTHONPATH=REPO)
    restarts, log_dir = elastic.supervise(
        lambda rank, restart, coord: [sys.executable, str(worker), coord,
                                      str(rank), ckdir],
        nprocs=2, max_restarts=2, env=env,
        log_dir=str(tmp_path / "logs"), round_timeout=120,
        policy=RetryPolicy(max_attempts=3, base_delay=0.2, max_delay=1.0))
    assert restarts >= 1  # incarnation 0 really did lose the worker
    final = [open(os.path.join(log_dir,
                               "r%d_rank%d.log" % (restarts, r))).read()
             for r in range(2)]
    for out in final:
        assert "RESUMED_FROM 5" in out, out  # last complete checkpoint
        assert "FINAL 20.0" in out, out      # training finished
    # incarnation 0: rank 1 was chaos-killed, not a clean exit
    r0 = open(os.path.join(log_dir, "r0_rank1.log")).read()
    assert "chaos" in r0.lower() and "RESUMED_FROM" not in r0
