"""Shared harness for ``launched`` (multi-process subprocess) tests.

Every wait on a launched worker goes through here so a hung coordinator,
wedged collective, or dead PS can never hold a communicate() forever and
wedge the tier-1 lane: on expiry the subprocess tree member is killed and
the test fails with whatever output was captured. The per-test budget is
``MXNET_TEST_LAUNCH_TIMEOUT`` (seconds, default 150).
"""
import os
import subprocess

LAUNCH_TIMEOUT = float(os.environ.get("MXNET_TEST_LAUNCH_TIMEOUT", "150"))


def free_port():
    """An OS-assigned free TCP port for a test coordinator/PS."""
    import socket
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def communicate(proc, timeout=LAUNCH_TIMEOUT):
    """``proc.communicate`` that kills the process on expiry instead of
    wedging the lane; fails the test with the partial output."""
    try:
        return proc.communicate(timeout=timeout)
    except subprocess.TimeoutExpired:
        proc.kill()
        out, err = proc.communicate()
        raise AssertionError(
            "launched subprocess exceeded %.0fs and was killed.\n"
            "--- stdout ---\n%s\n--- stderr ---\n%s"
            % (timeout, out, err))


def communicate_all(procs, timeout=LAUNCH_TIMEOUT):
    """Collect (out, err) from every proc under ONE shared deadline;
    kills every straggler (and still-running peers) on expiry."""
    import time
    deadline = time.monotonic() + timeout
    results = []
    try:
        for p in procs:
            left = max(1.0, deadline - time.monotonic())
            results.append(communicate(p, timeout=left))
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    return results
