"""2-bit gradient compression tests.

Mirrors the semantics exercised by the reference's
`tests/nightly/dist_sync_kvstore.py` compressed push-pull checks and
`docs/faq/gradient_compression.md`: thresholding, error feedback
accumulation, wire-size ratio.
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.gradient_compression import GradientCompression


def test_quantize_dequantize_mapping():
    import jax.numpy as jnp
    gc = GradientCompression({"type": "2bit", "threshold": 0.5})
    g = jnp.asarray([0.6, -0.7, 0.1, -0.1, 0.0, 2.0, -2.0], jnp.float32)
    res = jnp.zeros_like(g)
    packed, new_res = gc.quantize(g, res)
    out = np.asarray(gc.dequantize(packed, g.shape, jnp.float32))
    # elements past +/-threshold send one threshold step; small ones send 0
    np.testing.assert_allclose(out, [0.5, -0.5, 0, 0, 0, 0.5, -0.5])
    # residual keeps what was not sent
    np.testing.assert_allclose(
        np.asarray(new_res), [0.1, -0.2, 0.1, -0.1, 0.0, 1.5, -1.5],
        rtol=1e-6, atol=1e-6)


def test_wire_size_is_16x_smaller():
    import jax.numpy as jnp
    gc = GradientCompression({"type": "2bit", "threshold": 0.5})
    g = jnp.zeros((1024,), jnp.float32)
    packed, _ = gc.quantize(g, g)
    assert packed.dtype == jnp.uint8
    assert packed.size == 1024 // 4  # 2 bits/elem: 16x vs float32 bytes


def test_error_feedback_accumulates():
    """Pushing a constant sub-threshold gradient must eventually deliver
    threshold steps at the right average rate (error feedback)."""
    import jax.numpy as jnp
    gc = GradientCompression({"type": "2bit", "threshold": 1.0})
    g = jnp.full((4,), 0.3, jnp.float32)
    res = jnp.zeros_like(g)
    delivered = np.zeros(4, np.float32)
    for _ in range(10):
        packed, res = gc.quantize(g, res)
        delivered += np.asarray(gc.dequantize(packed, g.shape, jnp.float32))
    # 10 pushes of 0.3 = 3.0 total; with threshold 1.0 exactly 3 steps sent
    np.testing.assert_allclose(delivered, 3.0)
    np.testing.assert_allclose(np.asarray(res), 0.0, atol=1e-5)


def test_kvstore_compressed_push_pull():
    kv = mx.kv.create("local")
    kv.set_gradient_compression({"type": "2bit", "threshold": 0.5})
    shape = (8, 4)
    kv.init("w", mx.nd.zeros(shape))
    big = mx.nd.ones(shape) * 0.9
    kv.push("w", big)
    out = mx.nd.zeros(shape)
    kv.pull("w", out=out)
    # one step of +0.5 lands; 0.4 stays in the residual
    np.testing.assert_allclose(out.asnumpy(), 0.5)
    kv.push("w", big)
    kv.pull("w", out=out)
    # 2-bit codes saturate at one threshold step per push; residual grows
    np.testing.assert_allclose(out.asnumpy(), 0.5)
    np.testing.assert_allclose(
        np.asarray(kv._gc._residuals["w"]), 0.8, rtol=1e-6)


def test_kvstore_compression_params_recorded():
    kv = mx.kv.create("local")
    kv.set_gradient_compression({"type": "2bit", "threshold": 2.0})
    assert kv._gc.threshold == 2.0
    with pytest.raises(ValueError):
        kv.set_gradient_compression({"type": "1bit"})


def test_compressed_wire_bytes_two_process(tmp_path):
    """2-process dist_sync with 2-bit compression: only the packed uint8
    codes cross the collective — transferred bytes ~= dense/16 (reference
    kvstore_dist.h:379 Quantize-before-ZPush) — and training semantics
    survive (error feedback keeps the sum drifting toward the true
    gradient)."""
    import os
    import re
    import subprocess
    import sys
    TOOLS = os.path.join(os.path.dirname(__file__), os.pardir, "tools")
    worker = tmp_path / "worker.py"
    worker.write_text(
        "import os\n"
        "os.environ.setdefault('PALLAS_AXON_POOL_IPS', '')\n"
        "import numpy as np\n"
        "import mxnet_tpu as mx\n"
        "from mxnet_tpu.parallel import dist\n"
        "dist.init()\n"
        "kv = mx.kv.create('dist_sync')\n"
        "kv.set_gradient_compression({'type': '2bit', 'threshold': 0.5})\n"
        "rank = kv.rank\n"
        "kv.init('w', mx.nd.zeros((64, 64)))\n"
        "g = mx.nd.ones((64, 64)) * (0.6 if rank == 0 else -0.6)\n"
        "kv.push('w', g)\n"
        "out = mx.nd.zeros((64, 64))\n"
        "kv.pull('w', out=out)\n"
        "# +0.5 (rank0, code 01) + -0.5 (rank1, code 10) = 0.0 stored\n"
        "np.testing.assert_allclose(out.asnumpy(), 0.0, atol=1e-6)\n"
        "wire = kv._last_wire_bytes\n"
        "dense = kv._last_dense_bytes\n"
        "assert wire * 15 <= dense, (wire, dense)\n"
        "print('WIRE %d DENSE %d RATIO %.1f OK' % (wire, dense,\n"
        "      dense / wire))\n"
        "# error feedback: residual 0.1 accumulates across pushes\n"
        "for _ in range(4):\n"
        "    kv.push('w', mx.nd.ones((64, 64)) * 0.3)\n"
        "kv.pull('w', out=out)\n"
        "assert abs(out.asnumpy().mean()) > 0.1\n"
        "print('GC DIST', rank, 'OK')\n")
    env = dict(os.environ, PYTHONPATH=os.path.join(TOOLS, os.pardir))
    env.pop("JAX_PLATFORMS", None)
    r = subprocess.run(
        [sys.executable, os.path.join(TOOLS, "launch.py"), "-n", "2",
         "--port", "9447", "--", sys.executable, str(worker)],
        capture_output=True, text=True, env=env, timeout=300)
    assert r.returncode == 0, r.stderr + r.stdout
    assert r.stdout.count("OK") == 4
    m = re.search(r"RATIO ([\d.]+)", r.stdout)
    assert float(m.group(1)) >= 15.0


def test_training_accuracy_with_compression():
    """Accuracy smoke (reference docs/faq/gradient_compression.md): a
    separable problem still trains to high accuracy through the
    quantized gradient path with a sane threshold."""
    rng = np.random.RandomState(0)
    protos = rng.rand(4, 16).astype("f") * 2
    y = rng.randint(0, 4, 600)
    X = protos[y] + rng.randn(600, 16).astype("f") * 0.1
    it = mx.io.NDArrayIter(X, y.astype("f"), 50, shuffle=True)
    data = mx.sym.var("data")
    net = mx.sym.FullyConnected(data, num_hidden=32, name="fc1")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.FullyConnected(net, num_hidden=4, name="fc2")
    net = mx.sym.SoftmaxOutput(net, name="softmax")
    kv = mx.kv.create("device")
    kv.set_gradient_compression({"type": "2bit", "threshold": 0.05})
    mod = mx.mod.Module(net)
    mod.fit(it, optimizer="sgd", initializer=mx.init.Xavier(),
            optimizer_params={"learning_rate": 0.5}, num_epoch=12,
            kvstore=kv)
    acc = dict(mod.score(mx.io.NDArrayIter(X, y.astype("f"), 50),
                         "acc"))["accuracy"]
    assert acc > 0.9, acc


def test_tpu_kvstore_roundtrip_error_bound_and_bytes_counter():
    """The `tpu` kvstore's compressed push path: (a) error feedback
    bounds the round-trip error — with per-push gradients bounded by the
    threshold, every element's residual (cumulative pushed minus
    cumulative delivered) stays within ONE threshold — and (b)
    `kvstore_compressed_bytes_total` counts the packed code bytes each
    push produced."""
    from mxnet_tpu import telemetry
    kv = mx.kv.create("tpu")
    thresh = 0.5
    kv.set_gradient_compression({"type": "2bit", "threshold": thresh})
    shape = (16, 8)
    rng = np.random.RandomState(3)
    kv.init(0, mx.nd.zeros(shape))
    kv._set_updater(lambda key, grad, stored: None)  # keep store inert

    c0 = telemetry.counter("kvstore_compressed_bytes_total").value
    pushed_total = np.zeros(shape, np.float32)
    pushes = 12
    for _ in range(pushes):
        # |g| <= threshold: the regime where the error-feedback residual
        # provably stays within one threshold step per element
        g = rng.uniform(-thresh, thresh, shape).astype(np.float32)
        pushed_total += g
        kv.push(0, mx.nd.array(g))
    # delivered = pushed - residual; the residual is the ONLY loss, and
    # error feedback keeps it within one threshold per element
    residual = np.asarray(kv._gc._residuals[0])
    np.testing.assert_array_less(np.abs(residual), thresh + 1e-6)
    c1 = telemetry.counter("kvstore_compressed_bytes_total").value
    packed_per_push = int(np.ceil(shape[0] * shape[1] / 4))  # 2-bit codes
    assert c1 - c0 == pushes * packed_per_push
    # the counted wire bytes are 16x smaller than the dense payload
    dense_per_push = shape[0] * shape[1] * 4
    assert (c1 - c0) * 16 == pushes * dense_per_push
