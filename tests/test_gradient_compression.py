"""2-bit gradient compression tests.

Mirrors the semantics exercised by the reference's
`tests/nightly/dist_sync_kvstore.py` compressed push-pull checks and
`docs/faq/gradient_compression.md`: thresholding, error feedback
accumulation, wire-size ratio.
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.gradient_compression import GradientCompression


def test_quantize_dequantize_mapping():
    import jax.numpy as jnp
    gc = GradientCompression({"type": "2bit", "threshold": 0.5})
    g = jnp.asarray([0.6, -0.7, 0.1, -0.1, 0.0, 2.0, -2.0], jnp.float32)
    res = jnp.zeros_like(g)
    packed, new_res = gc.quantize(g, res)
    out = np.asarray(gc.dequantize(packed, g.shape, jnp.float32))
    # elements past +/-threshold send one threshold step; small ones send 0
    np.testing.assert_allclose(out, [0.5, -0.5, 0, 0, 0, 0.5, -0.5])
    # residual keeps what was not sent
    np.testing.assert_allclose(
        np.asarray(new_res), [0.1, -0.2, 0.1, -0.1, 0.0, 1.5, -1.5],
        rtol=1e-6, atol=1e-6)


def test_wire_size_is_16x_smaller():
    import jax.numpy as jnp
    gc = GradientCompression({"type": "2bit", "threshold": 0.5})
    g = jnp.zeros((1024,), jnp.float32)
    packed, _ = gc.quantize(g, g)
    assert packed.dtype == jnp.uint8
    assert packed.size == 1024 // 4  # 2 bits/elem: 16x vs float32 bytes


def test_error_feedback_accumulates():
    """Pushing a constant sub-threshold gradient must eventually deliver
    threshold steps at the right average rate (error feedback)."""
    import jax.numpy as jnp
    gc = GradientCompression({"type": "2bit", "threshold": 1.0})
    g = jnp.full((4,), 0.3, jnp.float32)
    res = jnp.zeros_like(g)
    delivered = np.zeros(4, np.float32)
    for _ in range(10):
        packed, res = gc.quantize(g, res)
        delivered += np.asarray(gc.dequantize(packed, g.shape, jnp.float32))
    # 10 pushes of 0.3 = 3.0 total; with threshold 1.0 exactly 3 steps sent
    np.testing.assert_allclose(delivered, 3.0)
    np.testing.assert_allclose(np.asarray(res), 0.0, atol=1e-5)


def test_kvstore_compressed_push_pull():
    kv = mx.kv.create("local")
    kv.set_gradient_compression({"type": "2bit", "threshold": 0.5})
    shape = (8, 4)
    kv.init("w", mx.nd.zeros(shape))
    big = mx.nd.ones(shape) * 0.9
    kv.push("w", big)
    out = mx.nd.zeros(shape)
    kv.pull("w", out=out)
    # one step of +0.5 lands; 0.4 stays in the residual
    np.testing.assert_allclose(out.asnumpy(), 0.5)
    kv.push("w", big)
    kv.pull("w", out=out)
    # 2-bit codes saturate at one threshold step per push; residual grows
    np.testing.assert_allclose(out.asnumpy(), 0.5)
    np.testing.assert_allclose(
        np.asarray(kv._gc._residuals["w"]), 0.8, rtol=1e-6)


def test_kvstore_compression_params_recorded():
    kv = mx.kv.create("local")
    kv.set_gradient_compression({"type": "2bit", "threshold": 2.0})
    assert kv._gc.threshold == 2.0
    with pytest.raises(ValueError):
        kv.set_gradient_compression({"type": "1bit"})
