"""FusedApplier: one-dispatch optimizer application must be numerically
identical to the per-parameter update path."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import gluon, autograd
from mxnet_tpu.gluon import nn


def _make_pair(opt_name, opt_params):
    nets = []
    for _ in range(2):
        net = nn.HybridSequential()
        net.add(nn.Dense(8, activation="relu", in_units=4), nn.Dense(3, in_units=8))
        net.initialize(mx.init.Xavier())
        nets.append(net)
    # identical initial weights
    src = nets[0].collect_params()
    dst = nets[1].collect_params()
    for (kn, ps), (kd, pd) in zip(src.items(), dst.items()):
        pd.set_data(ps.data())
    trainers = [gluon.Trainer(n.collect_params(), opt_name, dict(opt_params))
                for n in nets]
    return nets, trainers


def _run(net, trainer, steps, force_per_param=False):
    if force_per_param:
        trainer._fused = False
    rng = np.random.RandomState(0)
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    for s in range(steps):
        x = mx.nd.array(rng.randn(6, 4).astype("f"))
        y = mx.nd.array(rng.randint(0, 3, 6).astype("f"))
        with autograd.record():
            loss = loss_fn(net(x), y)
        loss.backward()
        trainer.step(6)
    return {k: v.data().asnumpy() for k, v in net.collect_params().items()}


@pytest.mark.parametrize("opt_name,opt_params", [
    ("sgd", {"learning_rate": 0.1, "momentum": 0.9, "wd": 1e-4}),
    ("sgd", {"learning_rate": 0.05}),
    ("adam", {"learning_rate": 0.01, "wd": 1e-4}),
])
def test_fused_matches_per_param(opt_name, opt_params):
    nets, trainers = _make_pair(opt_name, opt_params)
    fused = _run(nets[0], trainers[0], steps=5)
    assert trainers[0]._fused, "fused path should have engaged"
    ref = _run(nets[1], trainers[1], steps=5, force_per_param=True)
    for (kf, vf), (kr, vr) in zip(fused.items(), ref.items()):
        np.testing.assert_allclose(vf, vr, rtol=1e-6, atol=1e-7,
                                   err_msg="%s vs %s" % (kf, kr))


def test_fused_with_lr_scheduler_no_retrace_explosion():
    sched = mx.lr_scheduler.FactorScheduler(step=2, factor=0.5)
    nets, _ = _make_pair("sgd", {"learning_rate": 0.1})
    net = nets[0]
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1, "lr_scheduler": sched,
                             "momentum": 0.9})
    _run(net, trainer, steps=6)
    assert trainer._fused
    # lr changed across steps but the jit cache holds ONE entry
    assert len(trainer._fused._jit_cache) == 1
    assert trainer.learning_rate < 0.1


def test_fused_states_serializable(tmp_path):
    nets, trainers = _make_pair("adam", {"learning_rate": 0.01})
    _run(nets[0], trainers[0], steps=3)
    fname = str(tmp_path / "states")
    trainers[0].save_states(fname)
    trainers[0].load_states(fname)
    _run(nets[0], trainers[0], steps=1)


def test_unsupported_optimizer_falls_back():
    nets, _ = _make_pair("sgd", {"learning_rate": 0.1})
    net = nets[0]
    trainer = gluon.Trainer(net.collect_params(), "rmsprop",
                            {"learning_rate": 0.01})
    _run(net, trainer, steps=2)
    assert trainer._fused is False
