"""Symbol shape/type inference (reference
tests/python/unittest/test_infer_shape.py): full and partial inference,
chained layers, error propagation."""
import numpy as np
import pytest

import mxnet_tpu as mx


def test_mlp_infer_shape():
    data = mx.sym.Variable("data")
    out = mx.sym.FullyConnected(data, name="fc1", num_hidden=30)
    out = mx.sym.Activation(out, act_type="relu")
    out = mx.sym.FullyConnected(out, name="fc2", num_hidden=10)
    arg_shapes, out_shapes, _ = out.infer_shape(data=(100, 50))
    args = dict(zip(out.list_arguments(), arg_shapes))
    assert out_shapes == [(100, 10)]
    assert args["fc1_weight"] == (30, 50)
    assert args["fc1_bias"] == (30,)
    assert args["fc2_weight"] == (10, 30)


def test_partial_infer():
    data = mx.sym.Variable("data")
    fc = mx.sym.FullyConnected(data, name="fc", num_hidden=4)
    # without data shape, partial inference must not raise and must
    # report the output as unknown rather than inventing a shape
    arg_shapes, out_shapes, _ = fc.infer_shape_partial()
    assert out_shapes[0] is None


def test_conv_pool_chain():
    data = mx.sym.Variable("data")
    c = mx.sym.Convolution(data, kernel=(3, 3), num_filter=8, pad=(1, 1))
    p = mx.sym.Pooling(c, kernel=(2, 2), stride=(2, 2), pool_type="max")
    _, out_shapes, _ = p.infer_shape(data=(2, 3, 32, 32))
    assert out_shapes == [(2, 8, 16, 16)]


def test_broadcast_and_elemwise():
    a = mx.sym.Variable("a")
    b = mx.sym.Variable("b")
    out = mx.sym.broadcast_add(a, b)
    _, out_shapes, _ = out.infer_shape(a=(2, 1, 4), b=(1, 3, 4))
    assert out_shapes == [(2, 3, 4)]


def test_incompatible_shapes_raise():
    a = mx.sym.Variable("a")
    b = mx.sym.Variable("b")
    out = mx.sym.elemwise_add(a, b)
    with pytest.raises(Exception):
        out.infer_shape(a=(2, 3), b=(4, 5))


def test_infer_type():
    a = mx.sym.Variable("a")
    out = mx.sym.FullyConnected(a, num_hidden=3)
    arg_types, out_types, _ = out.infer_type(a=np.float32)
    assert all(t == np.dtype(np.float32) for t in arg_types)
    assert out_types[0] == np.dtype(np.float32)


def test_reshape_and_transpose_shapes():
    d = mx.sym.Variable("d")
    r = mx.sym.Reshape(d, shape=(0, -1))
    _, out_shapes, _ = r.infer_shape(d=(4, 3, 5))
    assert out_shapes == [(4, 15)]
    t = mx.sym.transpose(d, axes=(2, 0, 1))
    _, out_shapes, _ = t.infer_shape(d=(4, 3, 5))
    assert out_shapes == [(5, 4, 3)]


def test_grouped_symbol_shapes():
    a = mx.sym.Variable("a")
    g = mx.sym.Group([a * 2, a + 1])
    _, out_shapes, _ = g.infer_shape(a=(7,))
    assert out_shapes == [(7,), (7,)]
