"""Symbol + Executor tests (modeled on reference test_symbol.py /
test_executor.py / test_infer_shape.py)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.test_utils import assert_almost_equal


def _mlp():
    data = mx.sym.var("data")
    fc1 = mx.sym.FullyConnected(data, num_hidden=16, name="fc1")
    act1 = mx.sym.Activation(fc1, act_type="relu", name="relu1")
    fc2 = mx.sym.FullyConnected(act1, num_hidden=10, name="fc2")
    return mx.sym.SoftmaxOutput(fc2, mx.sym.var("softmax_label"), name="softmax")


def test_compose_and_listing():
    net = _mlp()
    args = net.list_arguments()
    assert args == ["data", "fc1_weight", "fc1_bias", "fc2_weight", "fc2_bias",
                    "softmax_label"]
    assert net.list_outputs() == ["softmax_output"]
    internals = net.get_internals()
    assert "fc1_output" in internals.list_outputs()


def test_infer_shape():
    net = _mlp()
    arg_shapes, out_shapes, aux_shapes = net.infer_shape(data=(32, 100))
    shapes = dict(zip(net.list_arguments(), arg_shapes))
    assert shapes["fc1_weight"] == (16, 100)
    assert shapes["fc1_bias"] == (16,)
    assert shapes["fc2_weight"] == (10, 16)
    assert out_shapes == [(32, 10)]
    assert aux_shapes == []


def test_infer_shape_conv_bn():
    data = mx.sym.var("data")
    conv = mx.sym.Convolution(data, kernel=(3, 3), num_filter=8, name="conv")
    bn = mx.sym.BatchNorm(conv, name="bn")
    arg_shapes, out_shapes, aux_shapes = bn.infer_shape(data=(2, 3, 10, 10))
    shapes = dict(zip(bn.list_arguments(), arg_shapes))
    assert shapes["conv_weight"] == (8, 3, 3, 3)
    assert shapes["bn_gamma"] == (8,)
    assert out_shapes[0] == (2, 8, 8, 8)
    assert bn.list_auxiliary_states() == ["bn_moving_mean", "bn_moving_var"]
    assert aux_shapes == [(8,), (8,)]


def test_symbol_arith_and_eval():
    a = mx.sym.var("a")
    b = mx.sym.var("b")
    c = 2 * a + b ** 2 - 1
    out = c.eval(a=mx.nd.array([1.0, 2.0]), b=mx.nd.array([3.0, 4.0]))
    assert_almost_equal(out[0], np.array([10.0, 19.0], np.float32))


def test_json_roundtrip():
    net = _mlp()
    js = net.tojson()
    net2 = mx.sym.load_json(js)
    assert net2.list_arguments() == net.list_arguments()
    assert net2.list_outputs() == net.list_outputs()
    _, out_shapes, _ = net2.infer_shape(data=(8, 50))
    assert out_shapes == [(8, 10)]


def test_group_and_slicing():
    a = mx.sym.var("a")
    b = mx.sym.var("b")
    g = mx.sym.Group([a + b, a * b])
    assert len(g.list_outputs()) == 2
    first = g[0]
    assert len(first.list_outputs()) == 1


def test_simple_bind_forward_backward():
    net = _mlp()
    exe = net.simple_bind(mx.cpu(), data=(4, 20), softmax_label=(4,))
    for name, arr in exe.arg_dict.items():
        if name not in ("data", "softmax_label"):
            arr[:] = np.random.uniform(-0.1, 0.1, arr.shape)
    x = np.random.uniform(size=(4, 20)).astype(np.float32)
    y = np.array([1, 3, 5, 7], np.float32)
    exe.forward(is_train=True, data=x, softmax_label=y)
    out = exe.outputs[0].asnumpy()
    assert out.shape == (4, 10)
    assert_almost_equal(out.sum(1), np.ones(4), rtol=1e-4)
    exe.backward()
    gw = exe.grad_dict["fc2_weight"].asnumpy()
    assert np.abs(gw).sum() > 0


def test_grad_req_add_and_null():
    x_np = np.random.uniform(size=(3, 4)).astype(np.float32)
    data = mx.sym.var("data")
    w = mx.sym.var("w")
    out = mx.sym.broadcast_mul(data, w)
    exe = out.bind(mx.cpu(), {"data": mx.nd.array(x_np), "w": mx.nd.ones((3, 4))},
                   args_grad={"w": mx.nd.zeros((3, 4))},
                   grad_req={"data": "null", "w": "add"})
    exe.forward(is_train=True)
    exe.backward(mx.nd.ones((3, 4)))
    exe.forward(is_train=True)
    exe.backward(mx.nd.ones((3, 4)))
    assert_almost_equal(exe.grad_dict["w"], 2 * x_np, rtol=1e-5)
    assert exe.grad_dict.get("data") is None


def test_executor_bn_aux_update():
    data = mx.sym.var("data")
    bn = mx.sym.BatchNorm(data, name="bn", momentum=0.5)
    exe = bn.simple_bind(mx.cpu(), data=(8, 4))
    exe.aux_dict["bn_moving_var"][:] = 1.0
    x = np.random.normal(3.0, 2.0, (8, 4)).astype(np.float32)
    exe.forward(is_train=True, data=x)
    mm = exe.aux_dict["bn_moving_mean"].asnumpy()
    assert_almost_equal(mm, 0.5 * x.mean(0), rtol=1e-3)
    # eval-mode forward must not touch aux
    exe.forward(is_train=False, data=x)
    assert_almost_equal(exe.aux_dict["bn_moving_mean"], mm)


def test_shared_exec_reshape():
    net = _mlp()
    exe = net.simple_bind(mx.cpu(), data=(8, 20), softmax_label=(8,))
    exe2 = exe.reshape(data=(4, 20), softmax_label=(4,))
    assert exe2.arg_dict["fc1_weight"] is exe.arg_dict["fc1_weight"]
    assert exe2.arg_dict["data"].shape == (4, 20)


def test_monitor_callback():
    data = mx.sym.var("data")
    out = mx.sym.relu(data, name="act")
    exe = out.bind(mx.cpu(), {"data": mx.nd.array([-1.0, 2.0])})
    seen = []
    exe.set_monitor_callback(lambda name, arr: seen.append(name))
    exe.forward()
    assert any("act" in n for n in seen)


def test_attr_scope_and_var_attrs():
    with mx.AttrScope(ctx_group="dev1"):
        a = mx.sym.var("a")
        b = mx.sym.FullyConnected(a, num_hidden=4, name="fc")
    assert a.attr("ctx_group") == "dev1"
    assert b.attr_dict()["fc"]["ctx_group"] == "dev1"
    v = mx.sym.var("w", shape=(3, 3), lr_mult=2.0)
    assert v.attr("__lr_mult__") == "2.0"
