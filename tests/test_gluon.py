"""Gluon block suite (reference tests/python/unittest/test_gluon.py):
Parameter/ParameterDict, SymbolBlock, HybridBlock export/import,
save/load params, Trainer with lr scheduling, losses."""
import os

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import gluon, autograd
from mxnet_tpu.gluon import nn


def test_parameter_basic():
    # name must match an initializer pattern (reference raises
    # "Unknown initialization pattern" for unmatched bare names too)
    p = gluon.Parameter("dense0_weight", shape=(3, 4))
    p.initialize(init=mx.init.Xavier())
    assert p.data().shape == (3, 4)
    assert p.grad() is not None or True
    p.set_data(mx.nd.ones((3, 4)))
    np.testing.assert_allclose(p.data().asnumpy(), 1.0)


def test_dense_and_sequential():
    net = nn.HybridSequential()
    net.add(nn.Dense(8, activation="relu"), nn.Dense(3))
    net.initialize()
    x = mx.nd.random.uniform(shape=(4, 6))
    out = net(x)
    assert out.shape == (4, 3)
    net.hybridize()
    out2 = net(x)
    np.testing.assert_allclose(out.asnumpy(), out2.asnumpy(), rtol=1e-5,
                               atol=1e-6)


def test_save_load_params(tmp_path):
    net = nn.HybridSequential(prefix="slp_")
    with net.name_scope():
        net.add(nn.Dense(5), nn.Dense(2))
    net.initialize()
    x = mx.nd.random.uniform(shape=(2, 3))
    want = net(x).asnumpy()
    path = str(tmp_path / "p.params")
    net.save_params(path)

    net2 = nn.HybridSequential(prefix="slp_")
    with net2.name_scope():
        net2.add(nn.Dense(5), nn.Dense(2))
    net2.load_params(path)
    np.testing.assert_allclose(net2(x).asnumpy(), want, rtol=1e-6)


def test_hybrid_export_symbolblock(tmp_path):
    net = nn.HybridSequential(prefix="exp_")
    with net.name_scope():
        net.add(nn.Dense(4, activation="tanh"), nn.Dense(2))
    net.initialize()
    net.hybridize()
    x = mx.nd.random.uniform(shape=(3, 5))
    want = net(x).asnumpy()
    prefix = str(tmp_path / "model")
    net.export(prefix)
    assert os.path.exists(prefix + "-symbol.json")
    assert os.path.exists(prefix + "-0000.params")

    sb = gluon.SymbolBlock.imports(prefix + "-symbol.json", ["data"],
                                   prefix + "-0000.params")
    got = sb(x).asnumpy()
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_trainer_with_scheduler():
    net = nn.Dense(1)
    net.initialize()
    sched = mx.lr_scheduler.FactorScheduler(step=2, factor=0.5)
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 1.0, "lr_scheduler": sched})
    x = mx.nd.ones((2, 3))
    for i in range(4):
        with autograd.record():
            loss = net(x).sum()
        loss.backward()
        trainer.step(2)
    assert trainer.learning_rate < 1.0


def test_losses():
    pred = mx.nd.array(np.random.RandomState(0).randn(4, 3).astype("f"))
    label = mx.nd.array(np.array([0, 1, 2, 1], "f"))
    l = gluon.loss.SoftmaxCrossEntropyLoss()(pred, label)
    assert l.shape == (4,)
    l1 = gluon.loss.L1Loss()(pred, mx.nd.zeros((4, 3)))
    np.testing.assert_allclose(l1.asnumpy(),
                               np.abs(pred.asnumpy()).mean(axis=1),
                               rtol=1e-5)
    l2 = gluon.loss.L2Loss()(pred, mx.nd.zeros((4, 3)))
    np.testing.assert_allclose(l2.asnumpy(),
                               (pred.asnumpy() ** 2).mean(axis=1) / 2,
                               rtol=1e-5)
    sig = gluon.loss.SigmoidBinaryCrossEntropyLoss()
    lb = sig(pred, mx.nd.ones((4, 3)))
    assert (lb.asnumpy() > 0).all()


def test_block_grad_flow_and_collect():
    net = nn.HybridSequential()
    net.add(nn.Dense(4), nn.Dense(1))
    net.initialize()
    params = net.collect_params()
    assert len(params) == 4  # 2 weights + 2 biases
    x = mx.nd.ones((2, 3))
    with autograd.record():
        y = net(x).sum()
    y.backward()
    for p in params.values():
        assert np.isfinite(p.grad().asnumpy()).all()


def test_constant_and_embedding():
    emb = nn.Embedding(10, 4)
    emb.initialize()
    idx = mx.nd.array(np.array([1, 3], "f"))
    out = emb(idx)
    assert out.shape == (2, 4)


def test_hybridize_remat_matches_plain():
    """hybridize(remat=True) rematerializes activations (jax.checkpoint,
    the MXNET_BACKWARD_DO_MIRROR analog) without changing results."""
    rng = np.random.RandomState(7)
    x = mx.nd.array(rng.randn(4, 6).astype("f"))

    results = []
    for remat in (False, True):
        net = nn.HybridSequential()
        net.add(nn.Dense(8, activation="tanh", in_units=6),
                nn.Dense(3, in_units=8))
        net.initialize(mx.init.Xavier(rnd_type="gaussian"))
        # identical weights across both nets
        if not results:
            saved = {k: v.data().asnumpy()
                     for k, v in net.collect_params().items()}
            order = list(net.collect_params().keys())
        else:
            for k, v in zip(order, net.collect_params().values()):
                v.set_data(mx.nd.array(saved[k]))
        net.hybridize(remat=remat)
        xc = x.copy()
        xc.attach_grad()
        with autograd.record():
            y = net(xc).sum()
        y.backward()
        results.append((float(y.asnumpy()), xc.grad.asnumpy()))
    np.testing.assert_allclose(results[0][0], results[1][0], rtol=1e-5)
    np.testing.assert_allclose(results[0][1], results[1][1], rtol=1e-5)


def test_contrib_concurrent():
    from mxnet_tpu.gluon import contrib as gc
    c = gc.nn.Concurrent(axis=1)
    c.add(nn.Dense(3), nn.Dense(4))
    c.initialize()
    out = c(mx.nd.ones((2, 5)))
    assert out.shape == (2, 7)


def test_contrib_interval_sampler_and_wikitext(tmp_path):
    from mxnet_tpu.gluon import contrib as gc
    assert list(gc.data.IntervalSampler(13, interval=3)) == \
        [0, 3, 6, 9, 12, 1, 4, 7, 10, 2, 5, 8, 11]
    assert list(gc.data.IntervalSampler(13, interval=3, rollover=False)) \
        == [0, 3, 6, 9, 12]
    # WikiText from a local file
    (tmp_path / "wiki.train.tokens").write_text(
        " hello world foo \n bar hello baz qux \n" * 20)
    ds = gc.data.WikiText2(root=str(tmp_path), segment="train", seq_len=5)
    assert len(ds) > 10
    data, label = ds[0]
    assert data.shape == (5,) and label.shape == (5,)
    # label is data shifted by one in the token stream
    np.testing.assert_allclose(label.asnumpy()[:-1], data.asnumpy()[1:])
    with pytest.raises(IOError):
        gc.data.WikiText103(root=str(tmp_path / "nope"))


def test_hybridized_batchnorm_updates_moving_stats():
    """Round-3 fix: under hybridize() the BN moving-stats updates happen on
    tracers; the cached program must surface them as aux outputs and commit
    them back, or eval (global stats) silently uses the INITIAL stats."""
    np.random.seed(0)
    net = nn.HybridSequential()
    net.add(nn.BatchNorm())
    net.initialize()
    net.hybridize()
    x = mx.nd.array(np.random.rand(16, 4).astype(np.float32) * 5 + 10)
    with mx.autograd.record():
        net(x)
    bn = list(net._children.values())[0]
    mean = bn.running_mean.data().asnumpy()
    var = bn.running_var.data().asnumpy()
    # one momentum-0.9 update from (0, 1) toward the batch stats
    assert np.abs(mean).max() > 0.5, mean   # moved off the init value
    assert np.abs(var - 1.0).max() > 0.1, var
    # eager reference produces the same stats
    net2 = nn.HybridSequential()
    net2.add(nn.BatchNorm())
    net2.initialize()
    with mx.autograd.record():
        net2(x)
    bn2 = list(net2._children.values())[0]
    np.testing.assert_allclose(mean, bn2.running_mean.data().asnumpy(),
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(var, bn2.running_var.data().asnumpy(),
                               rtol=1e-5, atol=1e-6)


def test_hybridized_nested_deferred_bn_updates_stats():
    """Review r3: a deferred-init BN CHILD called via __call__ inside a
    parent's hybrid_forward must still commit moving stats — the parent's
    warmup aux-suppression must not leak into the child's jit trace."""

    class Wrapper(gluon.HybridBlock):
        def __init__(self, **kw):
            super().__init__(**kw)
            with self.name_scope():
                self.bn = nn.BatchNorm()  # in_channels deferred

        def hybrid_forward(self, F, x):
            return self.bn(x)

    np.random.seed(1)
    net = Wrapper()
    net.initialize()
    net.hybridize()
    x = mx.nd.array(np.random.rand(16, 4).astype(np.float32) * 5 + 10)
    with mx.autograd.record():
        net(x)
        net(x)
    mean = net.bn.running_mean.data().asnumpy()
    assert np.abs(mean).max() > 0.5, mean  # stats moved off init
