"""Model zoo structural tests (reference
tests/python/gpu/test_gluon_model_zoo_gpu.py runs forwards; here we check
construction, forward shapes, param counts, and hybridize consistency on
the cheap models)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.gluon.model_zoo import vision


def _param_count(net):
    # exclude BN running stats (aux states, grad_req='null') to match the
    # usual trainable-parameter counts
    return sum(int(np.prod(p.shape)) for p in net.collect_params().values()
               if getattr(p, "grad_req", "write") != "null")


def test_get_model_registry_has_new_families():
    for name in ["densenet121", "densenet169", "densenet201", "densenet161",
                 "inceptionv3"]:
        net = vision.get_model(name)
        assert net is not None


def test_densenet121_forward_and_param_count():
    net = vision.densenet121()
    net.initialize()
    out = net(mx.nd.zeros((2, 3, 224, 224)))
    assert out.shape == (2, 1000)
    # torchvision densenet121 = 7,978,856 params
    assert abs(_param_count(net) - 7_978_856) < 20_000


def test_inception_v3_forward_and_param_count():
    net = vision.inception_v3()
    net.initialize()
    out = net(mx.nd.zeros((1, 3, 299, 299)))
    assert out.shape == (1, 1000)
    # reference gluon inception v3 (no aux head) ~= 23.8M params
    assert 23_000_000 < _param_count(net) < 25_000_000


def test_densenet_hybridize_matches_eager():
    net = vision.densenet121(classes=10)
    net.initialize()
    x = mx.nd.array(np.random.RandomState(0).randn(1, 3, 224, 224)
                    .astype(np.float32))
    eager = net(x).asnumpy()
    net.hybridize()
    hybrid = net(x).asnumpy()
    np.testing.assert_allclose(eager, hybrid, rtol=1e-4, atol=1e-4)


def test_hybrid_concurrent_and_identity():
    from mxnet_tpu.gluon.contrib.nn import HybridConcurrent, Identity
    block = HybridConcurrent(axis=1)
    block.add(Identity())
    block.add(Identity())
    block.initialize()
    x = mx.nd.ones((2, 3, 4, 4))
    out = block(x)
    assert out.shape == (2, 6, 4, 4)
