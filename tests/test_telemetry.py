"""Unified runtime telemetry (`mxnet_tpu/telemetry.py`): registry
semantics, histogram quantiles, JSONL event log + chrome-trace export,
Prometheus exposition, multi-host merge, and the hot-path wire-ins
(kvstore, retry, elastic checkpoints, Module.fit phases, Speedometer).

The launched acceptance test at the bottom runs a 2-process elastic run
with chaos enabled and asserts — not demonstrates — that per-host JSONL
logs merge into one chrome trace and that `telemetry.dumps()` carries
nonzero kvstore/retry/checkpoint/chaos series on every host.

Also here: the `xplane.dumps` unit test on a synthetic hand-encoded
.xplane.pb, so the protobuf parser is no longer exercised only
end-to-end through a live jax trace.
"""
import json
import os
import re
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import telemetry

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

import launchutil  # noqa: E402


@pytest.fixture
def fresh(tmp_path):
    """Clean registry + event log routed to a tmp dir (no snapshot
    thread); always unconfigured afterwards."""
    telemetry.reset()
    d = str(tmp_path / "telemetry")
    telemetry.configure(d, snapshot_interval=0)
    yield d
    telemetry.configure(None)
    telemetry.reset()


# ---------------------------------------------------------------------------
# Registry semantics
# ---------------------------------------------------------------------------

def test_counter_gauge_identity_and_labels(fresh):
    c = telemetry.counter("reqs_total", "requests", route="a")
    c.inc()
    c.inc(2.5)
    assert c.value == 3.5
    # same (name, labels) -> same object; different labels -> new series
    assert telemetry.counter("reqs_total", route="a") is c
    other = telemetry.counter("reqs_total", route="b")
    assert other is not c and other.value == 0
    with pytest.raises(ValueError, match="only go up"):
        c.inc(-1)
    g = telemetry.gauge("depth")
    g.set(7)
    g.inc()
    g.dec(3)
    assert g.value == 5
    # a name cannot change kind
    with pytest.raises(ValueError, match="already registered"):
        telemetry.gauge("reqs_total")
    # lookup without creation
    assert telemetry.get_metric("reqs_total", route="a") is c
    assert telemetry.get_metric("reqs_total", route="zzz") is None


def test_counter_thread_safety(fresh):
    c = telemetry.counter("mt_total")

    def worker():
        for _ in range(1000):
            c.inc()

    threads = [threading.Thread(target=worker) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert c.value == 8000


def test_histogram_quantiles_and_bounded_reservoir(fresh):
    h = telemetry.histogram("lat_seconds")
    for v in range(1, 1001):
        h.observe(float(v))
    assert h.count == 1000 and h.sum == 500500.0
    assert h.min == 1.0 and h.max == 1000.0
    assert abs(h.quantile(0.5) - 500) < 30
    assert abs(h.quantile(0.95) - 950) < 30
    assert abs(h.quantile(0.99) - 990) < 30
    # bounded: a small reservoir keeps exact count/sum but caps samples
    small = telemetry.histogram("small_seconds", reservoir=64)
    for v in range(10000):
        small.observe(float(v))
    assert small.count == 10000
    assert len(small._samples) == 64
    assert 2000 < small.quantile(0.5) < 8000  # unbiased-ish median
    assert telemetry.histogram("lat_seconds") is h  # identity
    with pytest.raises(ValueError):
        h.quantile(1.5)


def test_prometheus_dumps_format(fresh):
    telemetry.counter("a_total", "things done", kind='we"ird\nlabel').inc(3)
    telemetry.gauge("b").set(1.5)
    telemetry.histogram("c_seconds").observe(0.25)
    text = telemetry.dumps()
    assert "# HELP a_total things done" in text
    assert "# TYPE a_total counter" in text
    # label value escaped: quote and newline must not break the line
    assert 'a_total{kind="we\\"ird\\nlabel"} 3' in text
    assert "# TYPE b gauge" in text and "\nb 1.5" in text
    assert "# TYPE c_seconds summary" in text
    assert 'c_seconds{quantile="0.5"} 0.25' in text
    assert "c_seconds_sum 0.25" in text
    assert "c_seconds_count 1" in text
    snap = telemetry.snapshot()
    assert snap["c_seconds"]["series"][0]["p99"] == 0.25


# ---------------------------------------------------------------------------
# Spans, JSONL event log, chrome-trace export
# ---------------------------------------------------------------------------

def test_span_feeds_histogram_without_event_log():
    telemetry.reset()
    try:
        assert telemetry.configured_dir() is None
        with telemetry.span("quiet.region"):
            pass
        h = telemetry.get_metric("quiet_region_seconds")
        assert h is not None and h.count == 1
    finally:
        telemetry.reset()


def test_jsonl_chrome_trace_round_trip(fresh):
    with telemetry.span("outer", step=3) as sp:
        sp["extra"] = "yes"
        time.sleep(0.01)
    telemetry.event("marker", reason="because")
    telemetry.flush()
    files = [f for f in os.listdir(fresh) if f.endswith(".jsonl")]
    assert len(files) == 1
    events = telemetry.read_events(os.path.join(fresh, files[0]))
    span_ev = [e for e in events if e["name"] == "outer"][0]
    assert span_ev["ph"] == "X"
    assert span_ev["dur"] >= 0.01
    assert span_ev["args"] == {"step": 3, "extra": "yes"}
    for key in ("ts", "mono", "pid", "host", "tid"):
        assert key in span_ev
    inst = [e for e in events if e["name"] == "marker"][0]
    assert inst["ph"] == "i" and inst["args"]["reason"] == "because"
    # registry side: the span duration landed in a histogram
    assert telemetry.get_metric("outer_seconds").count == 1

    out = os.path.join(fresh, "trace.json")
    trace = telemetry.merge(fresh, out=out)
    with open(out) as fh:
        assert json.load(fh) == trace
    tev = trace["traceEvents"]
    x = [e for e in tev if e.get("ph") == "X"][0]
    assert x["name"] == "outer" and x["dur"] >= 0.01 * 1e6
    assert x["ts"] == span_ev["ts"] * 1e6
    assert any(e.get("ph") == "M" and e["name"] == "process_name"
               for e in tev)
    # a torn trailing line (killed writer) is skipped, not fatal
    with open(os.path.join(fresh, files[0]), "a") as fh:
        fh.write('{"name": "torn')
    assert len(telemetry.read_events(os.path.join(fresh, files[0]))) \
        == len(events)


def test_span_records_error_attr(fresh):
    with pytest.raises(RuntimeError):
        with telemetry.span("failing"):
            raise RuntimeError("boom")
    telemetry.flush()
    files = [f for f in os.listdir(fresh) if f.endswith(".jsonl")]
    ev = [e for e in telemetry.read_events(os.path.join(fresh, files[0]))
          if e["name"] == "failing"][0]
    assert "RuntimeError: boom" in ev["args"]["error"]


def test_multi_host_merge_one_timeline(fresh, tmp_path):
    """Events from different hosts land on distinct trace-process rows
    of ONE wall-clock-ordered timeline (the multi-host story)."""
    d = str(tmp_path / "multihost")
    os.makedirs(d)
    t0 = 1000.0
    for host, offs in ((0, 0.0), (1, 0.005)):
        with open(os.path.join(d, "events_host%d_pid%d.jsonl"
                               % (host, 100 + host)), "w") as fh:
            for i in range(3):
                fh.write(json.dumps({
                    "name": "step", "ph": "X", "ts": t0 + offs + i * 0.1,
                    "dur": 0.05, "pid": 100 + host, "host": host,
                    "tid": 1, "args": {"i": i}}) + "\n")
    trace = telemetry.merge(d)
    tev = trace["traceEvents"]
    metas = [e for e in tev if e.get("ph") == "M"]
    assert sorted(e["args"]["name"] for e in metas) == \
        ["host0/pid100", "host1/pid101"]
    xs = [e for e in tev if e.get("ph") == "X"]
    assert len(xs) == 6 and len({e["pid"] for e in xs}) == 2
    # one timeline: globally sorted by wall clock
    assert [e["ts"] for e in xs] == sorted(e["ts"] for e in xs)


def test_snapshot_file_and_periodic_writer(tmp_path):
    telemetry.reset()
    d = str(tmp_path / "snap")
    try:
        telemetry.configure(d, snapshot_interval=0.05)
        telemetry.counter("snap_total").inc(5)
        deadline = time.time() + 5
        path = os.path.join(
            d, "metrics_host%d_pid%d.prom"
            % (telemetry.host_id(), os.getpid()))
        while time.time() < deadline:
            if os.path.exists(path) and "snap_total 5" in open(path).read():
                break
            time.sleep(0.02)
        assert "snap_total 5" in open(path).read()
    finally:
        telemetry.configure(None)
        telemetry.reset()


# ---------------------------------------------------------------------------
# Hot-path wire-ins
# ---------------------------------------------------------------------------

def test_kvstore_push_pull_series(fresh):
    kv = mx.kv.create("local")
    kv.init("w", mx.nd.zeros((16, 16)))
    for _ in range(3):
        kv.push("w", mx.nd.ones((16, 16)))
    out = mx.nd.zeros((16, 16))
    kv.pull("w", out=out)
    assert telemetry.counter("kvstore_push_total").value == 3
    assert telemetry.counter("kvstore_pull_total").value == 1
    nbytes = 16 * 16 * 4
    assert telemetry.counter("kvstore_push_bytes_total").value == 3 * nbytes
    assert telemetry.counter("kvstore_pull_bytes_total").value == nbytes
    h = telemetry.get_metric("kvstore_push_seconds")
    assert h.count == 3 and h.sum > 0
    # spans landed in the event log too
    telemetry.flush()
    files = [f for f in os.listdir(fresh) if f.endswith(".jsonl")]
    names = [e["name"] for e in
             telemetry.read_events(os.path.join(fresh, files[0]))]
    assert names.count("kvstore.push") == 3


def test_retry_attempts_counted(fresh):
    from mxnet_tpu.parallel import retry

    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] < 3:
            raise TimeoutError("transient")
        return "ok"

    policy = retry.RetryPolicy(max_attempts=5, base_delay=0.0,
                               max_delay=0.0)
    assert retry.retry_call(flaky, policy=policy,
                            describe="flaky thing") == "ok"
    c = telemetry.get_metric("retry_attempts_total", call="flaky thing")
    assert c is not None and c.value == 2
    with pytest.raises(retry.RetryError):
        retry.retry_call(lambda: (_ for _ in ()).throw(TimeoutError("x")),
                         policy=retry.RetryPolicy(max_attempts=2,
                                                  base_delay=0.0),
                         describe="doomed thing")
    assert telemetry.get_metric("retry_exhausted_total",
                                call="doomed thing").value == 1


def test_elastic_checkpoint_durations(fresh, tmp_path):
    from mxnet_tpu.parallel import elastic

    ck = elastic.ElasticCheckpointer(str(tmp_path / "ck"), keep_last=2)
    tree = {"w": np.arange(8, dtype=np.float32)}
    ck.save(1, tree)
    ck.save(2, tree)
    from mxnet_tpu.parallel.checkpoint import abstract_like
    step, out = ck.restore(abstract_like(tree))
    assert step == 2
    np.testing.assert_allclose(np.asarray(out["w"]), tree["w"])
    assert telemetry.get_metric("elastic_checkpoint_save_seconds").count == 2
    assert telemetry.get_metric(
        "elastic_checkpoint_restore_seconds").count == 1


def test_fit_phase_series_and_speedometer(fresh):
    data = mx.sym.var("data")
    fc = mx.sym.FullyConnected(data, num_hidden=4, name="fc")
    net = mx.sym.SoftmaxOutput(fc, name="softmax")
    x = np.random.RandomState(0).uniform(size=(64, 10)).astype(np.float32)
    y = np.zeros(64, dtype=np.float32)
    it = mx.io.NDArrayIter(x, y, batch_size=16)
    mod = mx.mod.Module(net, context=mx.cpu())
    mod.fit(it, num_epoch=2, eval_metric="acc")
    assert telemetry.counter("fit_batches_total").value == 8
    assert telemetry.counter("fit_samples_total").value == 128
    # PR 6: the ad-hoc fit.* spans became the stepprof taxonomy
    for phase in ("data_wait", "h2d", "dispatch", "device_compute"):
        h = telemetry.get_metric("step_%s_seconds" % phase)
        assert h is not None and h.count >= 8, phase
    assert telemetry.get_metric("step_seconds").count >= 8
    # Speedometer reads samples/sec from the registry, not local math
    sp = mx.callback.Speedometer(batch_size=16, frequent=4)
    sp._mark()
    telemetry.counter("fit_samples_total").inc(1000)
    time.sleep(0.05)
    speed = sp._speed()
    assert 1000 / 0.05 * 0.2 < speed < 1000 / 0.05 * 1.2
    # outside an instrumented loop the reference arithmetic kicks in
    sp2 = mx.callback.Speedometer(batch_size=16, frequent=4)
    sp2._mark()
    time.sleep(0.01)
    assert sp2._speed() == pytest.approx(
        4 * 16 / (time.time() - sp2.tic), rel=0.8)


def test_op_dispatch_series_via_profiler_hook(fresh):
    from mxnet_tpu import profiler
    profiler.set_config(aggregate_stats=True, profile_memory=False)
    profiler.reset_stats()
    try:
        a = mx.nd.ones((8, 8))
        (a + a).asnumpy()
        series = [(k, lab) for (k, lab) in telemetry._metrics
                  if k == "op_dispatch_seconds"]
        assert series, "no op_dispatch series recorded"
        assert all(dict(lab).get("op") for _k, lab in series)
    finally:
        profiler.set_config(aggregate_stats=False)
        profiler.reset_stats()


# ---------------------------------------------------------------------------
# xplane.dumps on a synthetic trace (parser no longer only tested e2e)
# ---------------------------------------------------------------------------

def _pb_varint(n):
    out = b""
    while True:
        b7 = n & 0x7F
        n >>= 7
        if n:
            out += bytes([b7 | 0x80])
        else:
            return out + bytes([b7])


def _pb_key(field, wire):
    return _pb_varint((field << 3) | wire)


def _pb_vi(field, value):
    return _pb_key(field, 0) + _pb_varint(value)


def _pb_ld(field, payload):
    if isinstance(payload, str):
        payload = payload.encode()
    return _pb_key(field, 2) + _pb_varint(len(payload)) + payload


def _synthetic_xplane(path):
    """Hand-encode an XSpace: one device plane, one 'XLA Ops' line, three
    events over two op metadatas (fusion.1 x2, copy.2 x1), one string
    stat (hlo_category) via stat-metadata interning."""
    stat_meta = _pb_ld(5, _pb_ld(2, _pb_vi(1, 7) + _pb_ld(2, "hlo_category")))
    em1 = _pb_ld(4, _pb_ld(2, _pb_vi(1, 1) + _pb_ld(2, "fusion.1")))
    em2 = _pb_ld(4, _pb_ld(2, _pb_vi(1, 2) + _pb_ld(2, "copy.2")))
    stat = _pb_ld(4, _pb_vi(1, 7) + _pb_ld(5, "convolution"))
    ev1 = _pb_ld(4, _pb_vi(1, 1) + _pb_vi(2, 0) + _pb_vi(3, 2_000_000)
                 + stat)
    ev2 = _pb_ld(4, _pb_vi(1, 1) + _pb_vi(2, 5_000_000)
                 + _pb_vi(3, 4_000_000))
    ev3 = _pb_ld(4, _pb_vi(1, 2) + _pb_vi(2, 9_000_000)
                 + _pb_vi(3, 1_000_000))
    line = _pb_ld(3, _pb_ld(11, "XLA Ops") + _pb_vi(3, 123) + ev1 + ev2
                  + ev3)
    plane = _pb_ld(1, _pb_ld(2, "/device:TPU:0") + stat_meta + em1 + em2
                   + line)
    with open(path, "wb") as fh:
        fh.write(plane)
    return path


def test_xplane_dumps_on_synthetic_trace(tmp_path):
    from mxnet_tpu import xplane

    path = _synthetic_xplane(str(tmp_path / "synthetic.xplane.pb"))
    planes = xplane.parse_xspace(path)
    assert len(planes) == 1 and planes[0].name == "/device:TPU:0"
    (line,) = planes[0].lines
    assert line.name == "XLA Ops" and len(line.events) == 3
    assert line.events[0].stats["hlo_category"] == "convolution"

    table = xplane.op_table(path, by="op")
    assert table["fusion"]["count"] == 2
    assert table["fusion"]["total_ps"] == 6_000_000
    assert table["fusion"]["min_ps"] == 2_000_000
    assert table["copy"]["count"] == 1

    by_inst = xplane.op_table(path, by="instance")
    assert set(by_inst) == {"fusion.1", "copy.2"}
    by_cat = xplane.op_table(path, by="category")
    assert by_cat["convolution"]["count"] == 1  # interned stat resolved

    text = xplane.dumps(path, top=10)
    assert "fusion" in text and "copy" in text
    fusion_line = [l for l in text.splitlines()
                   if l.startswith("fusion")][0]
    assert int(fusion_line.split()[1]) == 2
    assert "TOTAL" in text


# ---------------------------------------------------------------------------
# Acceptance: 2-process launched elastic run with chaos -> per-host JSONL
# merged into one chrome trace; dumps() nonzero on every required series
# ---------------------------------------------------------------------------

TELEMETRY_WORKER = r"""
import os, sys
coord, rank, ckdir = sys.argv[1], int(sys.argv[2]), sys.argv[3]
import numpy as np
import jax.numpy as jnp
import mxnet_tpu as mx
from mxnet_tpu import telemetry
from mxnet_tpu.parallel import dist, elastic

# MXNET_CHAOS armed coordinator.timeout@0x1 at import: the FIRST attach
# attempt times out, the retry layer backs off and reconnects
dist.init(coord, 2, rank)
assert telemetry.host_id() == rank

kv = mx.kv.create("local")  # per-host traffic (no CPU collectives)
kv.init("w", mx.nd.zeros((8, 8)))
kv.push("w", mx.nd.ones((8, 8)))
out = mx.nd.zeros((8, 8))
kv.pull("w", out=out)

def step_fn(state, step):
    return {"w": state["w"] + 1.0}

t = elastic.ElasticTrainer(step_fn, {"w": jnp.zeros(4)}, ckpt_dir=ckdir,
                           ckpt_every=2, dead_node_timeout=None)
res = t.run(4)
assert float(np.asarray(res["w"])[0]) == 4.0

text = telemetry.dumps()
for needle, pat in (
        ("kvstore_push_total", r"kvstore_push_total 1"),
        ("kvstore_pull_total", r"kvstore_pull_total 1"),
        ("kvstore_push_bytes_total", r"kvstore_push_bytes_total 256"),
        ("retry_attempts", r'retry_attempts_total\{call="jax.distributed.initialize"\} 1'),
        ("checkpoint saves", r"elastic_checkpoint_save_seconds_count 2"),
        ("chaos injections", r'chaos_injections_total\{site="coordinator.timeout"\} 1'),
):
    import re as _re
    assert _re.search(pat, text), (needle, text)
print("SERIES_OK", rank, flush=True)
telemetry.flush()
dist.stop_heartbeat()
os._exit(0)  # skip jax shutdown barrier
"""


@pytest.mark.launched
@pytest.mark.timeout(180)
def test_launched_two_host_elastic_chaos_telemetry(tmp_path):
    """Acceptance (ISSUE 2): a 2-process launched elastic run with chaos
    enabled produces per-host JSONL event logs that `telemetry.merge()`
    combines into one chrome-trace file, and every host's
    `telemetry.dumps()` shows nonzero kvstore push/pull, retry,
    checkpoint-duration, and chaos-injection series."""
    worker = tmp_path / "worker.py"
    worker.write_text(TELEMETRY_WORKER)
    teldir = str(tmp_path / "telemetry")
    ckdir = str(tmp_path / "ck")
    coord = "127.0.0.1:%d" % launchutil.free_port()
    procs = []
    for rank in range(2):
        env = dict(os.environ, JAX_PLATFORMS="cpu", PALLAS_AXON_POOL_IPS="",
                   PYTHONPATH=REPO, MXNET_TELEMETRY_DIR=teldir,
                   MXNET_TELEMETRY_HOST=str(rank),
                   MXNET_CHAOS="coordinator.timeout@0x1")
        procs.append(subprocess.Popen(
            [sys.executable, str(worker), coord, str(rank), ckdir],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True))
    results = launchutil.communicate_all(procs)
    for rank, (p, (out, _)) in enumerate(zip(procs, results)):
        assert p.returncode == 0, out[-4000:]
        assert "SERIES_OK %d" % rank in out, out[-4000:]

    # one JSONL event log and one .prom snapshot per host
    jsonls = sorted(f for f in os.listdir(teldir) if f.endswith(".jsonl"))
    assert len(jsonls) == 2, jsonls
    assert {re.match(r"events_host(\d+)_", f).group(1)
            for f in jsonls} == {"0", "1"}
    proms = [f for f in os.listdir(teldir) if f.endswith(".prom")]
    assert len(proms) == 2, proms
    for f in proms:
        assert "elastic_checkpoint_save_seconds_count 2" \
            in open(os.path.join(teldir, f)).read()

    # merge stitches both hosts into ONE chrome trace
    out_path = str(tmp_path / "merged_trace.json")
    trace = telemetry.merge(teldir, out=out_path)
    tev = json.load(open(out_path))["traceEvents"]
    assert tev == trace["traceEvents"]
    metas = {e["args"]["name"] for e in tev if e.get("ph") == "M"}
    assert len(metas) == 2  # two host rows on one timeline
    names = [e["name"] for e in tev]
    assert names.count("elastic.checkpoint.save") == 4  # 2 hosts x 2 saves
    assert "chaos.injection" in names and "retry" in names
    assert "kvstore.push" in names and "dist.init" in names
    # and the CLI produces the same artifact
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "merge_traces.py"),
         teldir, "-o", str(tmp_path / "cli_trace.json")],
        capture_output=True, text=True, timeout=60)
    assert r.returncode == 0, r.stderr
    assert "2 process(es)" in r.stdout
