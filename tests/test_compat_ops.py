"""Tests for registry-completeness ops: ROIAlign, ThreeNN, bipartite
matching, SigmoidCrossEntropy, legacy Crop, sparse/scatter/image compat
ops — numpy oracles follow the reference kernels."""
import math

import numpy as np
import pytest

import mxnet_tpu as mx


def test_roi_align_vs_oracle():
    rng = np.random.RandomState(0)
    data = rng.randn(2, 3, 10, 12).astype("f")
    rois = np.array([[0, 1, 1, 8, 7], [1, 0, 0, 11, 9],
                     [-1, 0, 0, 4, 4]], "f")
    scale, P = 0.5, 2
    out = mx.nd.contrib.ROIAlign_v2(
        mx.nd.array(data), mx.nd.array(rois), spatial_scale=scale,
        pooled_size=(P, P)).asnumpy()

    def bilinear(plane, h, w):
        H, W = plane.shape
        y0 = min(max(int(math.floor(h)), 0), H - 1)
        y1 = min(max(int(math.ceil(h)), 0), H - 1)
        x0 = min(max(int(math.floor(w)), 0), W - 1)
        x1 = min(max(int(math.ceil(w)), 0), W - 1)
        a = 0.5 if y0 == y1 else h - y0
        b = 0.5 if x0 == x1 else w - x0
        return (plane[y0, x0] * (1 - a) * (1 - b)
                + plane[y1, x0] * a * (1 - b)
                + plane[y0, x1] * (1 - a) * b
                + plane[y1, x1] * a * b)

    # oracle for roi 0, channel 0: 2x2 samples at 1/3, 2/3 of each bin
    n, c = 0, 0
    sw, sh, ew, eh = rois[n, 1] * scale, rois[n, 2] * scale, \
        rois[n, 3] * scale, rois[n, 4] * scale
    bh, bw = (eh - sh) / P, (ew - sw) / P
    for ph in range(P):
        for pw in range(P):
            hs = min(max(ph * bh + sh, 0), 10 - 1)
            he = min(max((ph + 1) * bh + sh, 0), 10 - 1)
            ws = min(max(pw * bw + sw, 0), 12 - 1)
            we = min(max((pw + 1) * bw + sw, 0), 12 - 1)
            vals = [bilinear(data[0, 0], hs + (he - hs) * fh,
                             ws + (we - ws) * fw)
                    for fh in (1 / 3, 2 / 3) for fw in (1 / 3, 2 / 3)]
            np.testing.assert_allclose(out[n, c, ph, pw], max(vals),
                                       rtol=1e-5)
    # negative batch index -> zeros
    np.testing.assert_allclose(out[2], 0.0)


def test_three_nn():
    rng = np.random.RandomState(1)
    unknown = rng.randn(2, 5, 3).astype("f")
    known = rng.randn(2, 7, 3).astype("f")
    dist, idx = mx.nd.contrib.ThreeNN(mx.nd.array(unknown),
                                      mx.nd.array(known))
    dist, idx = dist.asnumpy(), idx.asnumpy().astype(int)
    for b in range(2):
        for n in range(5):
            d = np.sqrt(((unknown[b, n] - known[b]) ** 2).sum(-1))
            order = np.argsort(d)[:3]
            np.testing.assert_allclose(dist[b, n], d[order], rtol=1e-5)
            assert set(idx[b, n]) == set(order)


def test_bipartite_matching():
    score = np.array([[[0.5, 0.6], [0.8, 0.9], [0.4, 0.1]]], "f")
    rm, cm = mx.nd.contrib.bipartite_matching(mx.nd.array(score),
                                              threshold=0.2)
    # greedy: (1,1)=0.9 first, then (0,0)=0.5 (0.8 col taken... row1 taken)
    np.testing.assert_allclose(rm.asnumpy(), [[0, 1, -1]])
    np.testing.assert_allclose(cm.asnumpy(), [[0, 1]])
    # threshold cuts low scores
    rm2, _ = mx.nd.contrib.bipartite_matching(mx.nd.array(score),
                                              threshold=0.7)
    np.testing.assert_allclose(rm2.asnumpy(), [[-1, 1, -1]])


def test_sigmoid_cross_entropy():
    data = np.array([[0.5, -1.2], [2.0, 0.1]], "f")
    label = np.array([[1.0, 0.0], [-1.0, 1.0]], "f")
    out = mx.nd.contrib.SigmoidCrossEntropy(
        mx.nd.array(data), mx.nd.array(label)).asnumpy()

    def ce(x, t):
        return -x * (t - (x >= 0)) + np.log1p(np.exp(x - 2 * x * (x >= 0)))
    # row 0: both valid
    want0 = (ce(0.5, 1.0) + ce(-1.2, 0.0)) / (2 + 1e-5)
    np.testing.assert_allclose(out[0], want0, rtol=1e-5)
    # row 1: first element ignored (-1 label)
    want1 = ce(0.1, 1.0) / (1 + 1e-5)
    np.testing.assert_allclose(out[1], want1, rtol=1e-5)


def test_legacy_crop():
    x = mx.nd.array(np.arange(2 * 3 * 6 * 8, dtype="f").reshape(2, 3, 6, 8))
    out = mx.nd.Crop(x, h_w=(4, 4), offset=(1, 2), num_args=1)
    assert out.shape == (2, 3, 4, 4)
    np.testing.assert_allclose(out.asnumpy(),
                               x.asnumpy()[:, :, 1:5, 2:6])
    like = mx.nd.zeros((2, 3, 3, 3))
    out2 = mx.nd.Crop(x, like, num_args=2, center_crop=True)
    assert out2.shape == (2, 3, 3, 3)


def test_sparse_compat_ops():
    x = mx.nd.array(np.arange(12, dtype="f").reshape(4, 3))
    kept = mx.nd.sparse_retain(x, mx.nd.array(np.array([1, 3], "f")))
    got = kept.asnumpy()
    np.testing.assert_allclose(got[0], 0)
    np.testing.assert_allclose(got[1], x.asnumpy()[1])
    sq = mx.nd._square_sum(x, axis=1)
    np.testing.assert_allclose(sq.asnumpy(), (x.asnumpy() ** 2).sum(1))


def test_sparse_adagrad_update():
    w = mx.nd.ones((3, 2))
    g = mx.nd.array(np.array([[1, 1], [0, 0], [2, 2]], "f"))
    h = mx.nd.zeros((3, 2))
    new_w = mx.nd.sparse_adagrad_update(w, g, h, lr=0.1)
    nw, nh = new_w.asnumpy(), h.asnumpy()  # history mutated in place
    np.testing.assert_allclose(nh[1], 0.0)       # untouched row
    np.testing.assert_allclose(nw[1], 1.0)
    assert nw[0, 0] < 1.0 and nh[0, 0] == 1.0


def test_image_ops():
    img = mx.nd.array((np.arange(2 * 3 * 4 * 3) % 255)
                      .reshape(2, 3, 4, 3).astype("uint8"))
    t = mx.nd.image_to_tensor(img)
    assert t.shape == (2, 3, 3, 4)
    assert float(t.asnumpy().max()) <= 1.0
    norm = mx.nd.image_normalize(t, mean=(0.5, 0.5, 0.5),
                                 std=(0.5, 0.5, 0.5))
    np.testing.assert_allclose(norm.asnumpy(),
                               (t.asnumpy() - 0.5) / 0.5, rtol=1e-6)


def test_negative_binomial_samplers():
    k = mx.nd.array(np.array([5.0, 20.0], "f"))
    p = mx.nd.array(np.array([0.5, 0.5], "f"))
    s = mx.nd._sample_negative_binomial(k, p, shape=(2000,))
    m = s.asnumpy().mean(axis=1)
    # mean = k(1-p)/p
    np.testing.assert_allclose(m, [5.0, 20.0], rtol=0.25)
    mu = mx.nd.array(np.array([4.0], "f"))
    alpha = mx.nd.array(np.array([0.25], "f"))
    s2 = mx.nd._sample_generalized_negative_binomial(mu, alpha,
                                                     shape=(2000,))
    np.testing.assert_allclose(s2.asnumpy().mean(), 4.0, rtol=0.25)


def test_slice_assign():
    x = mx.nd.zeros((4, 4))
    r = mx.nd.ones((2, 2))
    out = mx.nd._slice_assign(x, r, begin=(1, 1), end=(3, 3))
    got = out.asnumpy()
    assert got[1:3, 1:3].sum() == 4 and got.sum() == 4


def test_kl_sparse_reg_identity_and_aux():
    x = mx.nd.array(np.random.RandomState(2).rand(8, 4).astype("f"))
    aux = mx.nd.zeros((4,))
    out = mx.nd.IdentityAttachKLSparseReg(x, aux)
    np.testing.assert_allclose(out.asnumpy(), x.asnumpy())


def test_v1_aliases_exist():
    for name in ["Convolution_v1", "Pooling_v1", "CuDNNBatchNorm",
                 "ROIPooling_v1", "_copyto", "_grad_add", "cast_storage",
                 "_CrossDeviceCopy", "_contrib_SparseEmbedding"]:
        assert mx.ops.has_op(name), name


def test_kl_sparse_reg_penalty_rides_gradient():
    x = mx.nd.array(np.random.RandomState(3).rand(8, 4).astype("f"))
    aux = mx.nd.full((4,), 0.1)
    x.attach_grad()
    with mx.autograd.record():
        y = mx.nd.IdentityAttachKLSparseReg(
            x, aux, sparseness_target=0.1, penalty=0.01)
        loss = y.sum()
    loss.backward()
    g = x.grad.asnumpy()
    # d(sum)/dx = 1 + penalty * (-rho/rho_hat + (1-rho)/(1-rho_hat)),
    # with rho_hat the momentum-UPDATED moving average (training mode),
    # no 1/N factor (identity_attach_KL_sparse_reg-inl.h:108)
    rho, penalty, momentum = 0.1, 0.01, 0.9
    rho_hat = momentum * 0.1 + (1 - momentum) * x.asnumpy().mean(axis=0)
    want = 1.0 + penalty * (-rho / rho_hat + (1 - rho) / (1 - rho_hat))
    np.testing.assert_allclose(g, np.broadcast_to(want, g.shape),
                               rtol=1e-5)
