"""SPMD sharded training on a named mesh (`parallel/spmd.py` +
`mxnet_tpu/compiled.py`).

Runs on the forced 8-device CPU mesh from conftest
(``XLA_FLAGS=--xla_force_host_platform_device_count=8``). Covers: policy
spec construction, batch sharding along the 'data' axis, DP-vs-FSDP
(and tensor) numerical parity with the single-device fused step,
donation decisions, zero retraces after warmup via
``xla_stats.compile_counts()``, the in-program gradient sync replacing
the ``kvstore='tpu'`` post-step device sync, the FSDP per-shard memory
ledger win, the scaling-efficiency bench record + gate wiring, and the
"exactly one compiled-program implementation" structural assertion.
"""
import json
import os
import re
import sys

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import compiled, telemetry, xla_stats
from mxnet_tpu.parallel import spmd

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))

import bench_gate  # noqa: E402


def _mlp():
    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data, num_hidden=16, name="fc1")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.FullyConnected(net, num_hidden=4, name="fc2")
    return mx.sym.SoftmaxOutput(net, name="softmax")


def _make_data(n=256, d=20, k=3, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, d).astype(np.float32)
    W = rng.randn(d, k).astype(np.float32)
    y = X.dot(W).argmax(axis=1).astype(np.float32)
    return X, y


# ---------------------------------------------------------------------------
# Policy / mesh construction
# ---------------------------------------------------------------------------

def test_policy_spec_construction():
    from jax.sharding import PartitionSpec as P
    dp = spmd.make_policy("data_parallel")
    assert dp.mesh.axis_names == ("data",) and dp.data_size == 8
    assert dp.batch_spec() == P("data")
    assert dp.param_spec("w", (16, 8)) == P()

    fsdp = spmd.make_policy("fsdp")
    # largest dim divisible by 8 shards on 'data'
    assert fsdp.param_spec("w", (16, 8)) == P("data")
    assert fsdp.param_spec("w", (4, 24)) == P(None, "data")
    assert fsdp.param_spec("b", (16,)) == P("data")
    # nothing divisible -> replicated
    assert fsdp.param_spec("b", (3,)) == P()
    assert fsdp.param_spec("s", ()) == P()

    tp = spmd.make_policy("tensor", model_axis=2)
    assert tp.mesh.axis_names == ("data", "model")
    assert tp.data_size == 4 and tp.model_size == 2
    # output-unit (dim 0) sharding for FC-layout weights and biases
    assert tp.param_spec("fc_weight", (16, 8)) == P("model")
    assert tp.param_spec("fc_bias", (16,)) == P("model")
    # model-indivisible dim 0 falls back to the fsdp rule on 'data'
    assert tp.param_spec("odd", (3, 8)) == P(None, "data")

    with pytest.raises(ValueError, match="not one of|unknown"):
        spmd.make_policy("zeRO")
    with pytest.raises(ValueError, match="divisible"):
        dp.check_batch("data", (12, 4))


def test_named_mesh_cached_and_validated():
    import jax
    from mxnet_tpu.parallel.mesh import named_mesh
    devs = jax.devices()
    m1 = named_mesh(devs, {"data": 8})
    m2 = named_mesh(devs, {"data": 8})
    assert m1 is m2  # one Mesh object per layout (jit cache stability)
    with pytest.raises(ValueError, match="need 6 devices"):
        named_mesh(devs, {"data": 3, "model": 2})
    with pytest.raises(ValueError, match="duplicate"):
        named_mesh([devs[0], devs[0]], {"data": 2})


def test_resolve_forms():
    p = spmd.make_policy("fsdp")
    assert spmd.resolve(p) is p
    assert spmd.resolve("fsdp").name == "fsdp"
    d = spmd.resolve({"policy": "tensor", "model_axis": 4})
    assert d.name == "tensor" and d.model_size == 4
    with pytest.raises(ValueError, match="'policy' key"):
        spmd.resolve({"model_axis": 2})
    with pytest.raises(TypeError):
        spmd.resolve(42)


# ---------------------------------------------------------------------------
# Module binding: batch + param placement
# ---------------------------------------------------------------------------

def test_module_bind_places_batch_and_params():
    from jax.sharding import PartitionSpec as P
    mod = mx.mod.Module(_mlp(), context=mx.cpu())
    mod.bind(data_shapes=[("data", (32, 20))],
             label_shapes=[("softmax_label", (32,))], spmd="fsdp")
    assert mod._spmd is not None and mod._spmd.name == "fsdp"
    # inputs shard along 'data'; params shard per policy
    assert mod._exec.arg_dict["data"]._data.sharding.spec == P("data")
    w = mod._exec.arg_dict["fc1_weight"]._data
    assert w.sharding.spec == P("data")
    assert len(w.sharding.device_set) == 8
    # gradient buffers inherit the parameter placement
    g = mod._exec.grad_dict["fc1_weight"]._data
    assert g.sharding.spec == P("data")


def test_module_env_default_policy(monkeypatch):
    monkeypatch.setenv("MXNET_SPMD", "fsdp")
    mod = mx.mod.Module(_mlp(), context=[mx.cpu(i) for i in range(8)])
    mod.bind(data_shapes=[("data", (32, 20))],
             label_shapes=[("softmax_label", (32,))])
    assert mod._spmd.name == "fsdp"
    monkeypatch.setenv("MXNET_SPMD", "bogus")
    mod2 = mx.mod.Module(_mlp(), context=[mx.cpu(i) for i in range(8)])
    with pytest.raises(Exception, match="MXNET_SPMD"):
        mod2.bind(data_shapes=[("data", (32, 20))],
                  label_shapes=[("softmax_label", (32,))])


def test_module_rejects_indivisible_batch():
    mod = mx.mod.Module(_mlp(), context=mx.cpu())
    with pytest.raises(Exception, match="divisible"):
        mod.bind(data_shapes=[("data", (30, 20))],
                 label_shapes=[("softmax_label", (30,))], spmd="fsdp")


# ---------------------------------------------------------------------------
# Numerical parity: single-device fused step vs DP vs FSDP vs tensor
# ---------------------------------------------------------------------------

def _train(spmd_arg, epochs=4, kvstore="tpu"):
    X, y = _make_data()
    it = mx.io.NDArrayIter(X, y, batch_size=32, shuffle=False,
                           label_name="softmax_label")
    mod = mx.mod.Module(_mlp(), context=mx.cpu())
    mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label,
             spmd=spmd_arg)
    mx.random.seed(7)
    np.random.seed(7)
    mod.init_params(initializer=mx.init.Xavier(rnd_type="uniform",
                                               factor_type="avg",
                                               magnitude=2))
    mod.init_optimizer(kvstore=kvstore, optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.5),
                                         ("momentum", 0.9)))
    metric = mx.metric.Accuracy()
    accs = []
    for _ in range(epochs):
        it.reset()
        metric.reset()
        for batch in it:
            mod._step(batch)
            mod.update_metric(metric, batch.label)
        accs.append(metric.get()[1])
    args, _ = mod.get_params()
    return accs, {n: a.asnumpy() for n, a in args.items()}, mod


def test_dp_and_fsdp_match_single_device_fused_step():
    accs1, args1, _ = _train(None)          # single-device fused step
    accs_dp, args_dp, _ = _train("data_parallel")
    accs_fs, args_fs, _ = _train("fsdp")
    assert accs_dp == pytest.approx(accs1, abs=1e-3)
    assert accs_fs == pytest.approx(accs1, abs=1e-3)
    for name in args1:
        np.testing.assert_allclose(args_dp[name], args1[name],
                                   rtol=2e-4, atol=2e-5, err_msg=name)
        np.testing.assert_allclose(args_fs[name], args1[name],
                                   rtol=2e-4, atol=2e-5, err_msg=name)
    assert accs1[-1] > 0.8  # and it actually learns


def test_tensor_policy_matches_single_device():
    accs1, args1, _ = _train(None, epochs=3)
    accs_tp, args_tp, mod = _train({"policy": "tensor", "model_axis": 2},
                                   epochs=3)
    assert mod._spmd.model_size == 2
    assert accs_tp == pytest.approx(accs1, abs=1e-3)
    for name in args1:
        np.testing.assert_allclose(args_tp[name], args1[name],
                                   rtol=5e-4, atol=5e-5, err_msg=name)


# ---------------------------------------------------------------------------
# Gradient sync lives INSIDE the compiled step (kvstore='tpu')
# ---------------------------------------------------------------------------

def test_kvstore_tpu_has_no_post_step_sync():
    push0 = telemetry.counter("kvstore_push_total").value
    pull0 = telemetry.counter("kvstore_pull_total").value
    _, _, mod = _train("fsdp", epochs=2, kvstore="tpu")
    # no kvstore was even created: the in-program collective subsumed it
    assert mod._kvstore is None and not mod._update_on_kvstore
    assert telemetry.counter("kvstore_push_total").value == push0
    assert telemetry.counter("kvstore_pull_total").value == pull0


# ---------------------------------------------------------------------------
# Zero retraces / cold compiles at steady state
# ---------------------------------------------------------------------------

def test_zero_retraces_after_warmup():
    X, y = _make_data(n=128)
    it = mx.io.NDArrayIter(X, y, batch_size=32, shuffle=False,
                           label_name="softmax_label")
    mod = mx.mod.Module(_mlp(), context=mx.cpu())
    mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label,
             spmd="fsdp")
    mod.init_params()
    mod.init_optimizer(kvstore="tpu", optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.1),))
    batches = list(it)
    mod._step(batches[0])   # warmup: the one compile
    c0 = xla_stats.compile_counts()
    for _ in range(3):
        for b in batches:
            mod._step(b)
    c1 = xla_stats.compile_counts()
    assert c1["compiles"] == c0["compiles"], "cold compile at steady state"
    assert c1["retraces"] == c0["retraces"], "retrace at steady state"
    assert c1["cache_hits"] > c0["cache_hits"]


def test_compiled_program_warmup_prepopulates_cache():
    import jax.numpy as jnp
    prog = compiled.tracked_jit(lambda x: x * 2, "spmd.test.warmup")
    prog.warmup(jnp.ones(4))
    c0 = xla_stats.compile_counts()
    out = prog(jnp.ones(4))
    np.testing.assert_allclose(np.asarray(out), 2.0)
    c1 = xla_stats.compile_counts()
    assert c1["compiles"] == c0["compiles"]          # no new compile
    assert c1["cache_hits"] == c0["cache_hits"] + 1  # served from cache


# ---------------------------------------------------------------------------
# Donation decisions
# ---------------------------------------------------------------------------

class _FakeAccel:
    device_type = "tpu"


def test_donation_decision(monkeypatch):
    # accelerators donate, CPU backends don't (no donation support)
    assert compiled.donate_argnums_for(_FakeAccel(), (0, 7)) == (0, 7)
    assert compiled.donate_argnums_for(mx.cpu(), (0, 7)) == ()
    # MXNET_SPMD_DONATE=0 revokes only the SPMD-unlocked param donation;
    # the legacy device decision is untouched by it
    assert compiled.spmd_donate_enabled()
    monkeypatch.setenv("MXNET_SPMD_DONATE", "0")
    assert not compiled.spmd_donate_enabled()
    assert compiled.donate_argnums_for(_FakeAccel(), (7,)) == (7,)


def test_spmd_fused_step_donates_params_on_accelerators(monkeypatch):
    """An EXPLICITLY selected SPMD policy frees the old param + optimizer
    buffers via donate_argnums (grad_args is arg 0, state_vals arg 7);
    the implicit multi-device default keeps the legacy guarantee (params
    never donated — user code may hold views). Asserted through the
    decision the plan applies — on the CPU test mesh the set is
    stripped to ()."""
    _, _, mod = _train("fsdp", epochs=1)
    assert mod._fused_plan is not False
    assert mod._spmd_explicit  # spmd= was passed
    step_fn = mod._fused_plan[3]
    assert step_fn.donate_argnums == ()  # CPU: stripped by the decision
    # the compiled program carries the policy (mesh-scoped dispatch)
    assert step_fn.policy is mod._spmd
    # the decision itself, on an accelerator, donates params + states
    assert compiled.donate_argnums_for(_FakeAccel(), (0, 7)) == (0, 7)
    # a multi-device context WITHOUT spmd= keeps params un-donated
    X, y = _make_data(n=64)
    it = mx.io.NDArrayIter(X, y, batch_size=32, label_name="softmax_label")
    mod2 = mx.mod.Module(_mlp(), context=[mx.cpu(i) for i in range(8)])
    mod2.bind(data_shapes=it.provide_data, label_shapes=it.provide_label)
    assert mod2._spmd is not None and not mod2._spmd_explicit


# ---------------------------------------------------------------------------
# FSDP memory win: per-shard ledger under a single-device budget
# ---------------------------------------------------------------------------

def test_fsdp_fits_model_past_single_device_budget():
    """A model whose REPLICATED param+optimizer bytes exceed a (synthetic)
    single-device budget trains under the fsdp policy, and the per-shard
    ledger proves the memory win: each device holds ~1/8 of the state."""
    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data, num_hidden=512, name="big1")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.FullyConnected(net, num_hidden=8, name="big2")
    net = mx.sym.SoftmaxOutput(net, name="softmax")
    X = np.random.RandomState(0).randn(64, 256).astype(np.float32)
    y = (np.random.RandomState(1).rand(64) * 8).astype(np.float32)
    it = mx.io.NDArrayIter(X, y, batch_size=32, label_name="softmax_label")

    mod = mx.mod.Module(net, context=mx.cpu())
    mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label,
             spmd="fsdp")
    mod.init_params()
    mod.init_optimizer(kvstore="tpu", optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.1),
                                         ("momentum", 0.9)))
    for batch in it:
        mod._step(batch)

    params_global = xla_stats.tree_bytes(
        [mod._exec.arg_dict[n] for n in mod._param_names])
    led = xla_stats.ledger()
    scope = mod._ledger_scope()
    shard_params = led[(scope, "params")]
    shard_opt = led[(scope, "optimizer")]
    # momentum state mirrors the params: replicated footprint is 2x
    replicated_total = 2 * params_global
    budget = replicated_total // 2   # a device that CANNOT hold it all
    assert replicated_total > budget
    assert shard_params + shard_opt < budget, \
        "per-shard bytes do not fit the budget the replicated state blew"
    # the dominant (512, 256) weight shards 8 ways; small params stay
    # replicated, so the shard total sits well under a quarter of global
    assert shard_params < params_global / 4
    out = mod.get_outputs()[0].asnumpy()
    assert np.isfinite(out).all()


def test_tree_shard_bytes_replicated_equals_global():
    import jax
    import jax.numpy as jnp
    arrs = [jnp.zeros((16, 8), jnp.float32), jnp.zeros((5,), jnp.float32)]
    assert xla_stats.tree_shard_bytes(arrs) == xla_stats.tree_bytes(arrs)
    pol = spmd.make_policy("fsdp")
    sharded = jax.device_put(jnp.zeros((16, 8), jnp.float32),
                             pol.param_sharding("w", (16, 8)))
    assert xla_stats.tree_shard_bytes([sharded]) == sharded.nbytes // 8


# ---------------------------------------------------------------------------
# Gluon Trainer spmd
# ---------------------------------------------------------------------------

def test_gluon_trainer_spmd_places_params():
    from jax.sharding import PartitionSpec as P
    from mxnet_tpu.gluon import nn, Trainer
    net = nn.Dense(16, in_units=24)
    net.initialize()
    net.hybridize()
    trainer = Trainer(net.collect_params(), "sgd",
                      {"learning_rate": 0.1}, spmd="fsdp")
    w = net.weight.data()._data
    # weight (16, 24): the largest divisible dim (24, dim 1) shards
    assert w.sharding.spec == P(None, "data")
    assert len(w.sharding.device_set) == 8
    from mxnet_tpu import autograd
    x = trainer.place_batch(mx.nd.ones((8, 24)))
    assert x._data.sharding.spec == P("data")
    with autograd.record():
        out = net(x)
        loss = out.sum()
    loss.backward()
    trainer.step(batch_size=8)
    assert np.isfinite(net.weight.data().asnumpy()).all()
    # per-shard ledger recorded under this trainer's own scope
    led = xla_stats.ledger()
    scope = trainer._ledger_scope
    assert scope.startswith("gluon_trainer")
    assert led[(scope, "params")] > 0
    assert led[(scope, "params")] < xla_stats.tree_bytes(
        [p.data() for p in net.collect_params().values()])


def test_rng_chain_advances_for_sharded_anchors():
    """A policy-sharded param used as the RNG placement anchor must
    advance the SAME per-mesh replicated chain every call — reading one
    cache entry while writing another would freeze the key (identical
    dropout masks every step)."""
    import jax
    import jax.numpy as jnp
    from mxnet_tpu import random as mxrand
    pol = spmd.make_policy("fsdp")
    anchor = jax.device_put(jnp.zeros((16, 8), jnp.float32),
                            pol.param_sharding("w", (16, 8)))
    k1 = np.asarray(mxrand.next_key_like(anchor))
    k2 = np.asarray(mxrand.next_key_like(anchor))
    k3 = np.asarray(mxrand.next_key_like(anchor))
    assert not np.array_equal(k1, k2) and not np.array_equal(k2, k3)
    # a replicated anchor over the same mesh continues the same chain
    repl = jax.device_put(jnp.zeros((8,), jnp.float32), pol.replicated())
    k4 = np.asarray(mxrand.next_key_like(repl))
    assert not np.array_equal(k3, k4)


# ---------------------------------------------------------------------------
# Scaling-efficiency record + gate wiring
# ---------------------------------------------------------------------------

def test_scaling_efficiency_record():
    sys.path.insert(0, REPO)
    import __graft_entry__ as graft
    rec = graft.scaling_efficiency_record(8, batch_per_device=8, steps=2)
    assert rec["metric"] == "multichip_scaling_efficiency"
    assert rec["n_devices"] == 8 and rec["unit"] == "ratio"
    assert rec["value"] > 0 and rec["one_device_rate"] > 0


def test_multichip_gate_direction_and_history(tmp_path):
    d = str(tmp_path)
    hist_line = json.dumps({"metric": bench_gate.MULTICHIP_METRIC,
                            "value": 0.9, "n_devices": 8})
    with open(os.path.join(d, "MULTICHIP_r01.json"), "w") as fh:
        json.dump({"n_devices": 8, "ok": True, "tail": hist_line + "\n"},
                  fh)
    hist = bench_gate.load_history(d)
    assert bench_gate.MULTICHIP_METRIC in hist  # MULTICHIP rounds parse
    ok = [{"metric": bench_gate.MULTICHIP_METRIC, "value": 0.85}]
    bad = [{"metric": bench_gate.MULTICHIP_METRIC, "value": 0.5}]
    assert bench_gate.gate_records(
        ok, history_dir=d, metric=bench_gate.MULTICHIP_METRIC) == 0
    assert bench_gate.gate_records(
        bad, history_dir=d, metric=bench_gate.MULTICHIP_METRIC) == 1


def test_repo_gate_picks_up_multichip(tmp_path, monkeypatch, capsys):
    """repo_gate --bench gates the scaling metric when MULTICHIP records
    are present in the run output."""
    import repo_gate
    run = tmp_path / "run.jsonl"
    run.write_text(json.dumps({"metric": bench_gate.MULTICHIP_METRIC,
                               "value": 0.8}) + "\n")
    rc = repo_gate.main(["--bench", str(run)])
    out = capsys.readouterr().out
    # analysis gate ran, and the multichip metric was gated (skip or
    # pass against repo history — older MULTICHIP rounds carry no tail)
    assert '"mxanalyze_gate"' in out
    assert out.count('"bench_gate"') >= 2  # train headline + multichip
    assert rc == 0


# ---------------------------------------------------------------------------
# Structural: exactly ONE compiled-program implementation
# ---------------------------------------------------------------------------

def test_single_compiled_program_layer():
    """The acceptance grep: the signature->executable cache / AOT warmup
    machinery exists once (mxnet_tpu/compiled.py); the five former
    tracked_jit call sites are thin clients of it, and xla_stats only
    aliases the names."""
    root = os.path.join(REPO, "mxnet_tpu")
    impl_re = re.compile(
        r"^\s*(?:class\s+(?:CompiledProgram|TrackedJit)\b"
        r"|def\s+_compile_entry\b)", re.M)
    owners = []
    for dirpath, _dirs, files in os.walk(root):
        if "__pycache__" in dirpath:
            continue
        for fn in files:
            if not fn.endswith(".py"):
                continue
            path = os.path.join(dirpath, fn)
            src = open(path, encoding="utf-8").read()
            if impl_re.search(src):
                owners.append(os.path.relpath(path, REPO))
    assert owners == ["mxnet_tpu/compiled.py"], \
        "compiled-program machinery leaked outside compiled.py: %s" % owners

    # the five client call sites all go through mxnet_tpu.compiled
    clients = ["mxnet_tpu/executor.py", "mxnet_tpu/module/module.py",
               "mxnet_tpu/gluon/block.py",
               "mxnet_tpu/parallel/data_parallel.py"]
    for rel in clients:
        src = open(os.path.join(REPO, rel), encoding="utf-8").read()
        assert "compiled" in src and "xla_stats.tracked_jit" not in src, \
            "%s is not a CompiledProgram client" % rel

    # xla_stats only aliases: its tracked_jit body delegates to compiled
    xs = open(os.path.join(REPO, "mxnet_tpu/xla_stats.py"),
              encoding="utf-8").read()
    assert "compiled.tracked_jit" in xs
    assert "self._fn.lower(" not in xs  # no AOT machinery left behind
