"""Operator tests (modeled on reference tests/python/unittest/test_operator.py):
NumPy-oracle forward checks + numeric-gradient backward checks."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.test_utils import (assert_almost_equal, check_numeric_gradient,
                                  check_symbolic_forward)


def test_fully_connected():
    x = np.random.uniform(size=(4, 10)).astype(np.float32)
    w = np.random.uniform(size=(5, 10)).astype(np.float32)
    b = np.random.uniform(size=(5,)).astype(np.float32)
    out = mx.nd.FullyConnected(mx.nd.array(x), mx.nd.array(w), mx.nd.array(b),
                               num_hidden=5)
    assert_almost_equal(out, x @ w.T + b, rtol=1e-4)
    data = mx.sym.var("data")
    weight = mx.sym.var("weight")
    bias = mx.sym.var("bias")
    fc = mx.sym.FullyConnected(data, weight, bias, num_hidden=5)
    check_symbolic_forward(fc, {"data": x, "weight": w, "bias": b},
                           [x @ w.T + b], rtol=1e-4)
    check_numeric_gradient(fc, {"data": x, "weight": w, "bias": b},
                           numeric_eps=1e-2, rtol=5e-2, atol=1e-3)


def test_convolution_forward():
    # oracle: direct conv computed via numpy
    x = np.random.uniform(-1, 1, (2, 3, 7, 7)).astype(np.float32)
    w = np.random.uniform(-1, 1, (4, 3, 3, 3)).astype(np.float32)
    b = np.zeros(4, np.float32)
    out = mx.nd.Convolution(mx.nd.array(x), mx.nd.array(w), mx.nd.array(b),
                            kernel=(3, 3), num_filter=4).asnumpy()
    assert out.shape == (2, 4, 5, 5)
    ref = np.zeros_like(out)
    for n in range(2):
        for f in range(4):
            for i in range(5):
                for j in range(5):
                    ref[n, f, i, j] = (x[n, :, i:i + 3, j:j + 3] * w[f]).sum()
    assert_almost_equal(out, ref, rtol=1e-3, atol=1e-4)


def test_convolution_options():
    x = np.random.uniform(-1, 1, (2, 4, 8, 8)).astype(np.float32)
    # stride + pad
    out = mx.nd.Convolution(mx.nd.array(x),
                            mx.nd.array(np.random.uniform(-1, 1, (6, 4, 3, 3)).astype(np.float32)),
                            kernel=(3, 3), num_filter=6, stride=(2, 2),
                            pad=(1, 1), no_bias=True)
    assert out.shape == (2, 6, 4, 4)
    # dilate
    out = mx.nd.Convolution(mx.nd.array(x),
                            mx.nd.array(np.random.uniform(-1, 1, (6, 4, 3, 3)).astype(np.float32)),
                            kernel=(3, 3), num_filter=6, dilate=(2, 2), no_bias=True)
    assert out.shape == (2, 6, 4, 4)
    # grouped
    out = mx.nd.Convolution(mx.nd.array(x),
                            mx.nd.array(np.random.uniform(-1, 1, (4, 2, 3, 3)).astype(np.float32)),
                            kernel=(3, 3), num_filter=4, num_group=2, no_bias=True)
    assert out.shape == (2, 4, 6, 6)
    # 1D and 3D
    out = mx.nd.Convolution(mx.nd.ones((2, 3, 10)),
                            mx.nd.ones((4, 3, 3)), kernel=(3,), num_filter=4,
                            no_bias=True)
    assert out.shape == (2, 4, 8)
    out = mx.nd.Convolution(mx.nd.ones((1, 2, 5, 5, 5)),
                            mx.nd.ones((3, 2, 2, 2, 2)), kernel=(2, 2, 2),
                            num_filter=3, no_bias=True)
    assert out.shape == (1, 3, 4, 4, 4)


def test_deconvolution():
    x = mx.nd.ones((1, 2, 4, 4))
    w = mx.nd.ones((2, 3, 3, 3))
    out = mx.nd.Deconvolution(x, w, kernel=(3, 3), num_filter=3, no_bias=True)
    assert out.shape == (1, 3, 6, 6)
    out2 = mx.nd.Deconvolution(x, w, kernel=(3, 3), num_filter=3,
                               stride=(2, 2), pad=(1, 1), adj=(1, 1),
                               no_bias=True)
    assert out2.shape == (1, 3, 8, 8)
    # deconv(conv) roundtrip shape: (i-1)*s - 2p + k + adj
    data = mx.sym.var("data")
    dec = mx.sym.Deconvolution(data, mx.sym.var("w"), kernel=(3, 3),
                               num_filter=3, no_bias=True)
    x_np = np.random.uniform(size=(1, 2, 4, 4)).astype(np.float32)
    w_np = np.random.uniform(size=(2, 3, 3, 3)).astype(np.float32)
    check_numeric_gradient(dec, {"data": x_np, "w": w_np}, numeric_eps=1e-2,
                           rtol=5e-2, atol=1e-3)


def test_pooling():
    x_np = np.random.uniform(size=(2, 3, 6, 6)).astype(np.float32)
    x = mx.nd.array(x_np)
    out = mx.nd.Pooling(x, kernel=(2, 2), stride=(2, 2), pool_type="max")
    ref = x_np.reshape(2, 3, 3, 2, 3, 2).max(axis=(3, 5))
    assert_almost_equal(out, ref)
    out = mx.nd.Pooling(x, kernel=(2, 2), stride=(2, 2), pool_type="avg")
    ref = x_np.reshape(2, 3, 3, 2, 3, 2).mean(axis=(3, 5))
    assert_almost_equal(out, ref, rtol=1e-5)
    gout = mx.nd.Pooling(x, global_pool=True, pool_type="max", kernel=(1, 1))
    assert_almost_equal(gout.squeeze(), x_np.max(axis=(2, 3)), rtol=1e-5)
    gavg = mx.nd.Pooling(x, global_pool=True, pool_type="avg", kernel=(1, 1))
    assert_almost_equal(gavg.squeeze(), x_np.mean(axis=(2, 3)), rtol=1e-5)


def test_activation_ops():
    x_np = np.random.uniform(-2, 2, (3, 4)).astype(np.float32)
    x = mx.nd.array(x_np)
    assert_almost_equal(mx.nd.Activation(x, act_type="relu"),
                        np.maximum(x_np, 0))
    assert_almost_equal(mx.nd.Activation(x, act_type="tanh"), np.tanh(x_np),
                        rtol=1e-4)
    assert_almost_equal(mx.nd.Activation(x, act_type="sigmoid"),
                        1 / (1 + np.exp(-x_np)), rtol=1e-4)
    assert_almost_equal(mx.nd.Activation(x, act_type="softrelu"),
                        np.log1p(np.exp(x_np)), rtol=1e-4)
    assert_almost_equal(mx.nd.LeakyReLU(x, act_type="leaky", slope=0.1),
                        np.where(x_np >= 0, x_np, 0.1 * x_np), rtol=1e-5)
    assert_almost_equal(mx.nd.LeakyReLU(x, act_type="elu", slope=1.0),
                        np.where(x_np >= 0, x_np, np.expm1(x_np)), rtol=1e-4)


def test_softmax_ops():
    x_np = np.random.uniform(-2, 2, (3, 5)).astype(np.float32)
    x = mx.nd.array(x_np)
    e = np.exp(x_np - x_np.max(1, keepdims=True))
    ref = e / e.sum(1, keepdims=True)
    assert_almost_equal(mx.nd.softmax(x), ref, rtol=1e-4)
    assert_almost_equal(mx.nd.log_softmax(x), np.log(ref), rtol=1e-3, atol=1e-5)
    assert_almost_equal(mx.nd.softmax(x, temperature=2.0),
                        np.exp(x_np / 2) / np.exp(x_np / 2).sum(1, keepdims=True),
                        rtol=1e-4)


def test_batchnorm():
    x_np = np.random.uniform(-1, 1, (4, 3, 5, 5)).astype(np.float32)
    gamma = np.random.uniform(0.5, 1.5, (3,)).astype(np.float32)
    beta = np.random.uniform(-0.5, 0.5, (3,)).astype(np.float32)
    mm = np.zeros(3, np.float32)
    mv = np.ones(3, np.float32)
    arrs = [mx.nd.array(v) for v in (x_np, gamma, beta, mm, mv)]
    with mx.autograd.train_mode():
        out = mx.nd.BatchNorm(*arrs, fix_gamma=False, eps=1e-5, momentum=0.9)
    out = out[0] if isinstance(out, list) else out
    mean = x_np.mean(axis=(0, 2, 3))
    var = x_np.var(axis=(0, 2, 3))
    ref = (x_np - mean.reshape(1, 3, 1, 1)) / np.sqrt(var.reshape(1, 3, 1, 1) + 1e-5)
    ref = ref * gamma.reshape(1, 3, 1, 1) + beta.reshape(1, 3, 1, 1)
    assert_almost_equal(out, ref, rtol=1e-3, atol=1e-4)
    # moving stats updated in place
    assert_almost_equal(arrs[3], 0.9 * mm + 0.1 * mean, rtol=1e-4)
    assert_almost_equal(arrs[4], 0.9 * mv + 0.1 * var, rtol=1e-4)
    # inference mode uses the moving stats
    out2 = mx.nd.BatchNorm(*arrs, fix_gamma=False, eps=1e-5)
    out2 = out2[0] if isinstance(out2, list) else out2
    cur_mm, cur_mv = arrs[3].asnumpy(), arrs[4].asnumpy()
    ref2 = (x_np - cur_mm.reshape(1, 3, 1, 1)) / np.sqrt(cur_mv.reshape(1, 3, 1, 1) + 1e-5)
    ref2 = ref2 * gamma.reshape(1, 3, 1, 1) + beta.reshape(1, 3, 1, 1)
    assert_almost_equal(out2, ref2, rtol=1e-3, atol=1e-4)


def test_layernorm():
    x_np = np.random.uniform(-1, 1, (4, 6)).astype(np.float32)
    g = np.random.uniform(0.5, 1.5, (6,)).astype(np.float32)
    b = np.random.uniform(-0.5, 0.5, (6,)).astype(np.float32)
    out = mx.nd.LayerNorm(mx.nd.array(x_np), mx.nd.array(g), mx.nd.array(b))
    out = out[0] if isinstance(out, list) else out
    mean = x_np.mean(-1, keepdims=True)
    std = x_np.std(-1, keepdims=True)
    ref = (x_np - mean) / np.sqrt(std ** 2 + 1e-5) * g + b
    assert_almost_equal(out, ref, rtol=1e-3, atol=1e-4)


def test_dropout():
    x = mx.nd.ones((100, 100))
    with mx.autograd.train_mode():
        out = mx.nd.Dropout(x, p=0.5)
    out = out[0] if isinstance(out, list) else out
    arr = out.asnumpy()
    frac = (arr == 0).mean()
    assert 0.35 < frac < 0.65
    nz = arr[arr != 0]
    assert_almost_equal(nz, np.full_like(nz, 2.0))
    # eval mode = identity
    out = mx.nd.Dropout(x, p=0.5)
    out = out[0] if isinstance(out, list) else out
    assert (out.asnumpy() == 1).all()


def test_embedding_op():
    w = np.random.uniform(size=(10, 4)).astype(np.float32)
    idx = np.array([1, 3, 5], np.float32)
    out = mx.nd.Embedding(mx.nd.array(idx), mx.nd.array(w), input_dim=10,
                          output_dim=4)
    assert_almost_equal(out, w[[1, 3, 5]])


def test_softmax_output_grad():
    x_np = np.random.uniform(-1, 1, (4, 5)).astype(np.float32)
    label_np = np.array([0, 2, 4, 1], np.float32)
    data = mx.sym.var("data")
    label = mx.sym.var("label")
    sym = mx.sym.SoftmaxOutput(data, label, name="softmax")
    exe = sym.bind(mx.cpu(), {"data": mx.nd.array(x_np), "label": mx.nd.array(label_np)},
                   args_grad={"data": mx.nd.zeros((4, 5))},
                   grad_req={"data": "write"})
    exe.forward(is_train=True)
    e = np.exp(x_np - x_np.max(1, keepdims=True))
    p = e / e.sum(1, keepdims=True)
    assert_almost_equal(exe.outputs[0], p, rtol=1e-4)
    exe.backward()
    oh = np.eye(5, dtype=np.float32)[label_np.astype(int)]
    assert_almost_equal(exe.grad_dict["data"], p - oh, rtol=1e-4)


def test_regression_outputs():
    x_np = np.random.uniform(-1, 1, (4, 3)).astype(np.float32)
    y_np = np.random.uniform(-1, 1, (4, 3)).astype(np.float32)
    data, label = mx.sym.var("data"), mx.sym.var("label")
    lin = mx.sym.LinearRegressionOutput(data, label)
    exe = lin.bind(mx.cpu(), {"data": mx.nd.array(x_np), "label": mx.nd.array(y_np)},
                   args_grad={"data": mx.nd.zeros((4, 3))},
                   grad_req={"data": "write"})
    exe.forward(is_train=True)
    assert_almost_equal(exe.outputs[0], x_np)
    exe.backward()
    assert_almost_equal(exe.grad_dict["data"], x_np - y_np, rtol=1e-5)
    log = mx.sym.LogisticRegressionOutput(data, label)
    exe = log.bind(mx.cpu(), {"data": mx.nd.array(x_np), "label": mx.nd.array(y_np)},
                   args_grad={"data": mx.nd.zeros((4, 3))},
                   grad_req={"data": "write"})
    exe.forward(is_train=True)
    sig = 1 / (1 + np.exp(-x_np))
    assert_almost_equal(exe.outputs[0], sig, rtol=1e-4)
    exe.backward()
    assert_almost_equal(exe.grad_dict["data"], sig - y_np, rtol=1e-4)


def test_sequence_ops():
    data = np.arange(24, dtype=np.float32).reshape(4, 3, 2)  # (T,B,C)
    seqlen = np.array([2, 3, 1], np.float32)
    out = mx.nd.SequenceMask(mx.nd.array(data), mx.nd.array(seqlen),
                             use_sequence_length=True, value=-1.0)
    ref = data.copy()
    for b, l in enumerate(seqlen.astype(int)):
        ref[l:, b, :] = -1
    assert_almost_equal(out, ref)
    last = mx.nd.SequenceLast(mx.nd.array(data), mx.nd.array(seqlen),
                              use_sequence_length=True)
    ref_last = np.stack([data[int(l) - 1, b] for b, l in enumerate(seqlen)])
    assert_almost_equal(last, ref_last)
    rev = mx.nd.SequenceReverse(mx.nd.array(data), mx.nd.array(seqlen),
                                use_sequence_length=True)
    ref_rev = data.copy()
    for b, l in enumerate(seqlen.astype(int)):
        ref_rev[:l, b] = data[:l, b][::-1]
    assert_almost_equal(rev, ref_rev)


def test_rnn_op_shapes():
    T, B, I, H = 5, 3, 4, 6
    from mxnet_tpu.ops.nn import rnn_param_size
    for mode, nstate in [("rnn_tanh", 1), ("gru", 1), ("lstm", 2)]:
        psize = rnn_param_size(2, I, H, False, mode)
        data = mx.nd.random.normal(shape=(T, B, I))
        params = mx.nd.random.normal(shape=(psize,)) * 0.1
        state = mx.nd.zeros((2, B, H))
        args = [data, params, state]
        if mode == "lstm":
            args.append(mx.nd.zeros((2, B, H)))
        outs = mx.nd.RNN(*args, state_size=H, num_layers=2, mode=mode,
                         state_outputs=True)
        assert outs[0].shape == (T, B, H)
        assert outs[1].shape == (2, B, H)
        if mode == "lstm":
            assert outs[2].shape == (2, B, H)
    # bidirectional
    psize = rnn_param_size(1, I, H, True, "lstm")
    outs = mx.nd.RNN(mx.nd.random.normal(shape=(T, B, I)),
                     mx.nd.random.normal(shape=(psize,)) * 0.1,
                     mx.nd.zeros((2, B, H)), mx.nd.zeros((2, B, H)),
                     state_size=H, num_layers=1, bidirectional=True,
                     mode="lstm", state_outputs=True)
    assert outs[0].shape == (T, B, 2 * H)


def test_lstm_vs_manual():
    """Fused RNN(lstm) matches a hand-rolled cell."""
    T, B, I, H = 3, 2, 4, 5
    from mxnet_tpu.ops.nn import rnn_param_size
    psize = rnn_param_size(1, I, H, False, "lstm")
    params = np.random.uniform(-0.5, 0.5, (psize,)).astype(np.float32)
    data = np.random.uniform(-1, 1, (T, B, I)).astype(np.float32)
    out = mx.nd.RNN(mx.nd.array(data), mx.nd.array(params),
                    mx.nd.zeros((1, B, H)), mx.nd.zeros((1, B, H)),
                    state_size=H, num_layers=1, mode="lstm",
                    state_outputs=False)
    w_i2h = params[:4 * H * I].reshape(4 * H, I)
    w_h2h = params[4 * H * I:4 * H * I + 4 * H * H].reshape(4 * H, H)
    b = params[4 * H * I + 4 * H * H:]
    b_i2h, b_h2h = b[:4 * H], b[4 * H:]

    def sig(v):
        return 1 / (1 + np.exp(-v))

    h = np.zeros((B, H), np.float32)
    c = np.zeros((B, H), np.float32)
    ys = []
    for t in range(T):
        g = data[t] @ w_i2h.T + b_i2h + h @ w_h2h.T + b_h2h
        i, f, gg, o = np.split(g, 4, axis=1)
        c = sig(f) * c + sig(i) * np.tanh(gg)
        h = sig(o) * np.tanh(c)
        ys.append(h)
    assert_almost_equal(out, np.stack(ys), rtol=1e-4, atol=1e-5)


def test_random_ops():
    mx.random.seed(42)
    u = mx.nd.random.uniform(0, 1, shape=(1000,))
    arr = u.asnumpy()
    assert 0 <= arr.min() and arr.max() <= 1
    assert abs(arr.mean() - 0.5) < 0.05
    n = mx.nd.random.normal(2.0, 3.0, shape=(2000,))
    assert abs(n.asnumpy().mean() - 2.0) < 0.3
    assert abs(n.asnumpy().std() - 3.0) < 0.3
    # seeding is reproducible
    mx.random.seed(7)
    a = mx.nd.random.uniform(shape=(5,)).asnumpy()
    mx.random.seed(7)
    b = mx.nd.random.uniform(shape=(5,)).asnumpy()
    assert_almost_equal(a, b)
    p = mx.nd.random.poisson(lam=4.0, shape=(2000,))
    assert abs(p.asnumpy().mean() - 4.0) < 0.3
    g = mx.nd.random.gamma(alpha=2.0, beta=2.0, shape=(2000,))
    assert abs(g.asnumpy().mean() - 4.0) < 0.5
    m = mx.nd.random.multinomial(mx.nd.array([0.0, 0.0, 1.0]), shape=8)
    assert (m.asnumpy() == 2).all()


def test_optimizer_update_ops():
    w = mx.nd.ones((4,))
    g = mx.nd.ones((4,)) * 0.5
    mx.nd.sgd_update(w, g, lr=0.1, out=w)
    assert_almost_equal(w, np.full(4, 0.95, np.float32), rtol=1e-5)
    mom = mx.nd.zeros((4,))
    mx.nd.sgd_mom_update(w, g, mom, lr=0.1, momentum=0.9, out=w)
    assert_almost_equal(w, np.full(4, 0.90, np.float32), rtol=1e-5)
    assert_almost_equal(mom, np.full(4, -0.05, np.float32), rtol=1e-4)
    mean, var = mx.nd.zeros((4,)), mx.nd.zeros((4,))
    w2 = mx.nd.ones((4,))
    mx.nd.adam_update(w2, g, mean, var, lr=0.01, out=w2)
    assert (w2.asnumpy() < 1).all()


def test_linalg_ops():
    a = np.random.uniform(size=(4, 4)).astype(np.float32)
    spd = a @ a.T + 4 * np.eye(4, dtype=np.float32)
    L = mx.nd.linalg_potrf(mx.nd.array(spd))
    assert_almost_equal(L.asnumpy() @ L.asnumpy().T, spd, rtol=1e-3, atol=1e-4)
    g = mx.nd.linalg_gemm2(mx.nd.array(a), mx.nd.array(a), transpose_b=True)
    assert_almost_equal(g, a @ a.T, rtol=1e-4)
    sld = mx.nd.linalg_sumlogdiag(mx.nd.array(spd))
    assert_almost_equal(sld, np.log(np.diag(spd)).sum(), rtol=1e-4)


def test_lrn():
    x = np.random.uniform(size=(2, 8, 4, 4)).astype(np.float32)
    out = mx.nd.LRN(mx.nd.array(x), nsize=5, alpha=1e-4, beta=0.75, knorm=2.0)
    half = 2
    ref = np.zeros_like(x)
    for c in range(8):
        lo, hi = max(0, c - half), min(8, c + half + 1)
        ssum = (x[:, lo:hi] ** 2).sum(axis=1)
        ref[:, c] = x[:, c] / np.power(2.0 + 1e-4 / 5 * ssum, 0.75)
    assert_almost_equal(out, ref, rtol=1e-4)


def test_box_ops():
    a = mx.nd.array([[0, 0, 2, 2], [1, 1, 3, 3]])
    b = mx.nd.array([[0, 0, 2, 2]])
    iou = mx.nd.box_iou(a, b)
    assert_almost_equal(iou, np.array([[1.0], [1.0 / 7.0]], np.float32), rtol=1e-4)
    dets = mx.nd.array([[[0, 0.9, 0, 0, 2, 2], [0, 0.8, 0.1, 0.1, 2, 2],
                         [1, 0.7, 5, 5, 7, 7]]])
    out = mx.nd.box_nms(dets, overlap_thresh=0.5)
    arr = out.asnumpy()[0]
    assert arr[0, 1] == pytest.approx(0.9)
    assert (arr[1] == -1).all()          # suppressed
    assert arr[2, 1] == pytest.approx(0.7)


def test_smooth_l1_where():
    x = np.array([-2.0, -0.5, 0.5, 2.0], np.float32)
    out = mx.nd.smooth_l1(mx.nd.array(x), scalar=1.0)
    ref = np.where(np.abs(x) < 1, 0.5 * x * x, np.abs(x) - 0.5)
    assert_almost_equal(out, ref)


def test_linalg_namespaces():
    import numpy as np
    A = mx.nd.array(np.array([[2.0, 1.0], [1.0, 2.0]], "f"))
    L = mx.nd.linalg.potrf(A)
    np.testing.assert_allclose(L.asnumpy() @ L.asnumpy().T, A.asnumpy(),
                               rtol=1e-5)
    out = mx.nd.linalg.gemm2(A, A)
    np.testing.assert_allclose(out.asnumpy(), A.asnumpy() @ A.asnumpy(),
                               rtol=1e-5)
    s = mx.sym.linalg.sumlogdiag(mx.sym.Variable("a"))
    _, o, _ = s.infer_shape(a=(3, 3))
    # deliberate delta vs reference: scalar () instead of (1,) — the
    # jnp.sum over the diagonal drops the axis (la_op.h keeps a 1-dim)
    assert o == [()]


def test_space_to_depth_conv_rewrite_matches_direct():
    """The TPU stem rewrite (_space_to_depth_conv) must be the EXACT same
    function as the stride-2 conv it replaces, gradients included."""
    import jax
    import jax.numpy as jnp
    from jax import lax
    from mxnet_tpu.ops.nn import _space_to_depth_conv

    rng = np.random.RandomState(0)
    for (C, k, pad, H) in [(3, 7, 3, 32), (1, 3, 1, 28), (4, 5, 2, 63),
                           (3, 8, 3, 64)]:
        x = jnp.asarray(rng.randn(2, C, H, H).astype(np.float32))
        w = jnp.asarray(rng.randn(8, C, k, k).astype(np.float32))
        dn = lax.conv_dimension_numbers(x.shape, w.shape,
                                        ("NCHW", "OIHW", "NCHW"))

        def f_ref(x, w):
            return lax.conv_general_dilated(
                x, w, (2, 2), [(pad, pad), (pad, pad)],
                dimension_numbers=dn).sum()

        def f_got(x, w):
            return _space_to_depth_conv(x, w, (pad, pad)).sum()

        ref = lax.conv_general_dilated(x, w, (2, 2), [(pad, pad), (pad, pad)],
                                       dimension_numbers=dn)
        got = _space_to_depth_conv(x, w, (pad, pad))
        assert ref.shape == got.shape
        assert float(jnp.abs(ref - got).max()) < 1e-4
        for a, b in zip(jax.grad(f_ref, (0, 1))(x, w),
                        jax.grad(f_got, (0, 1))(x, w)):
            assert float(jnp.abs(a - b).max()) < 1e-3


def test_batchnorm_backward_oracle():
    """BN training-mode backward against the analytic batch-norm gradient
    (reference batch_norm-inl.h BatchNormBackward). The custom-VJP fused
    backward (ops/nn.py _bn_train_core) must match for both NCHW and NHWC
    axes and with fix_gamma on/off."""
    rng = np.random.RandomState(7)
    N, C, H, W = 4, 5, 3, 6
    eps = 1e-3

    def oracle(x, g, dy, axis):
        red = tuple(i for i in range(x.ndim) if i != axis)
        bs = tuple(-1 if i == axis else 1 for i in range(x.ndim))
        n = np.prod([x.shape[i] for i in red]).astype(np.float64)
        m = x.mean(axis=red).reshape(bs)
        v = ((x - m) ** 2).mean(axis=red).reshape(bs)
        inv = 1.0 / np.sqrt(v + eps)
        xhat = (x - m) * inv
        sdy = dy.sum(axis=red).reshape(bs)
        sdyx = (dy * xhat).sum(axis=red).reshape(bs)
        dx = (g.reshape(bs) * inv) * (dy - sdy / n - xhat * sdyx / n)
        return dx, np.squeeze(sdyx), np.squeeze(sdy)

    for axis, shape in ((1, (N, C, H, W)), (3, (N, H, W, C))):
        for fix_gamma in (False, True):
            x_np = (rng.randn(*shape) * 2 + 1).astype(np.float64)
            g_np = (rng.rand(C) + 0.5).astype(np.float64)
            b_np = rng.randn(C).astype(np.float64)
            dy_np = rng.randn(*shape).astype(np.float64)

            x = mx.nd.array(x_np, dtype="float64")
            g = mx.nd.array(g_np, dtype="float64")
            b = mx.nd.array(b_np, dtype="float64")
            mm = mx.nd.zeros((C,), dtype="float64")
            mv = mx.nd.ones((C,), dtype="float64")
            for t in (x, g, b):
                t.attach_grad()
            with mx.autograd.record():
                y = mx.nd.BatchNorm(x, g, b, mm, mv, eps=eps, axis=axis,
                                    fix_gamma=fix_gamma)
                y = y[0] if isinstance(y, list) else y
                head = mx.nd.array(dy_np, dtype="float64")
                loss = (y * head).sum()
            loss.backward()

            g_eff = np.ones_like(g_np) if fix_gamma else g_np
            dx_o, dg_o, db_o = oracle(x_np, g_eff, dy_np, axis)
            # internal statistics accumulate in f32 -> f32-level tolerance
            np.testing.assert_allclose(x.grad.asnumpy(), dx_o,
                                       rtol=2e-4, atol=2e-4)
            np.testing.assert_allclose(b.grad.asnumpy(), db_o,
                                       rtol=2e-4, atol=2e-4)
            if fix_gamma:
                np.testing.assert_allclose(g.grad.asnumpy(), 0.0, atol=1e-7)
            else:
                np.testing.assert_allclose(g.grad.asnumpy(), dg_o,
                                           rtol=2e-4, atol=2e-4)


def test_pool_slices_matches_reduce_window():
    """MXNET_POOL_SLICES (slice-form strided max pool): forward exact,
    gradients match the reduce_window lowering away from ties."""
    import os
    import jax
    import jax.numpy as jnp
    from mxnet_tpu.ops import nn as nn_ops

    rng = np.random.RandomState(0)
    # distinct values => no ties, so both backward conventions agree
    x = jnp.asarray(rng.permutation(2 * 8 * 13 * 13).reshape(2, 8, 13, 13)
                    .astype(np.float32))
    params = {"kernel": (3, 3), "stride": (2, 2), "pad": (1, 1),
              "pool_type": "max"}

    def run(x):
        return nn_ops._pooling(params, x)[0]

    old = os.environ.get("MXNET_POOL_SLICES")
    try:
        os.environ["MXNET_POOL_SLICES"] = "0"
        want = run(x)
        gw = jax.grad(lambda v: jnp.sum(run(v) ** 2))(x)
        os.environ["MXNET_POOL_SLICES"] = "1"
        got = run(x)
        gg = jax.grad(lambda v: jnp.sum(run(v) ** 2))(x)
    finally:
        if old is None:
            os.environ.pop("MXNET_POOL_SLICES", None)
        else:
            os.environ["MXNET_POOL_SLICES"] = old
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
    np.testing.assert_allclose(np.asarray(gg), np.asarray(gw), rtol=1e-6)


def test_space_to_depth_conv_nhwc_matches_direct():
    """NHWC twin of the stem rewrite (round 5): exact same function as
    the stride-2 NHWC conv, gradients included."""
    import jax
    import jax.numpy as jnp
    from jax import lax
    from mxnet_tpu.ops.nn import _space_to_depth_conv_nhwc

    rng = np.random.RandomState(0)
    for (C, k, pad, H) in [(3, 7, 3, 32), (1, 3, 1, 28), (4, 5, 2, 63),
                           (3, 8, 3, 64)]:
        x = jnp.asarray(rng.randn(2, H, H, C).astype(np.float32))
        w = jnp.asarray(rng.randn(8, k, k, C).astype(np.float32))
        dn = lax.conv_dimension_numbers(x.shape, w.shape,
                                        ("NHWC", "OHWI", "NHWC"))

        def f_ref(x, w):
            return lax.conv_general_dilated(
                x, w, (2, 2), [(pad, pad), (pad, pad)],
                dimension_numbers=dn).sum()

        def f_got(x, w):
            return _space_to_depth_conv_nhwc(x, w, (pad, pad)).sum()

        ref = lax.conv_general_dilated(x, w, (2, 2), [(pad, pad), (pad, pad)],
                                       dimension_numbers=dn)
        got = _space_to_depth_conv_nhwc(x, w, (pad, pad))
        assert ref.shape == got.shape
        assert float(jnp.abs(ref - got).max()) < 1e-4
        for a, b in zip(jax.grad(f_ref, (0, 1))(x, w),
                        jax.grad(f_got, (0, 1))(x, w)):
            assert float(jnp.abs(a - b).max()) < 1e-3
