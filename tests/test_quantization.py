"""Int8 quantization tests (mirror reference
tests/python/quantization/test_quantization.py)."""
import numpy as np
import jax.numpy as jnp
import pytest

import mxnet_tpu as mx
from mxnet_tpu.ops.registry import get_op


def run_op(name, params, *inputs):
    outs = get_op(name).fcompute(params, *(jnp.asarray(i) for i in inputs))
    return [np.asarray(o) for o in outs]


def test_quantize_dequantize_roundtrip():
    rng = np.random.RandomState(0)
    x = (rng.randn(4, 8) * 3).astype(np.float32)
    mn, mx_ = np.float32(x.min()), np.float32(x.max())
    q, qmin, qmax = run_op("_contrib_quantize", {}, x, [mn], [mx_])
    assert q.dtype == np.int8
    (back,) = run_op("_contrib_dequantize", {}, q, qmin, qmax)
    scale = max(abs(mn), abs(mx_)) / 127.0
    np.testing.assert_allclose(back, x, atol=scale * 0.51)


def test_quantize_v2_calibrated_range():
    x = np.asarray([[-1.0, 0.5, 2.0]], np.float32)
    q, qmin, qmax = run_op("_contrib_quantize_v2",
                           {"min_calib_range": -4.0,
                            "max_calib_range": 4.0}, x)
    assert qmax[0] == 4.0
    np.testing.assert_array_equal(
        q, np.round(x / (4.0 / 127)).astype(np.int8))


def test_quantized_fc_matches_fp32():
    rng = np.random.RandomState(1)
    x = rng.randn(5, 16).astype(np.float32)
    w = rng.randn(8, 16).astype(np.float32)
    qx, xmin, xmax = run_op("_contrib_quantize_v2", {}, x)
    qw, wmin, wmax = run_op("_contrib_quantize_v2", {}, w)
    out, omin, omax = run_op("_contrib_quantized_fully_connected",
                             {"num_hidden": 8}, qx, qw,
                             xmin, xmax, wmin, wmax)
    assert out.dtype == np.int32
    (deq,) = run_op("_contrib_dequantize", {}, out, omin, omax)
    want = x @ w.T
    # int8 quantization error ~ 1% relative on the output scale
    assert np.abs(deq - want).max() < 0.05 * np.abs(want).max()


def test_requantize_calibrated():
    rng = np.random.RandomState(2)
    x = rng.randn(3, 4).astype(np.float32)
    qx, xmin, xmax = run_op("_contrib_quantize_v2", {}, x)
    qw, wmin, wmax = run_op("_contrib_quantize_v2", {}, x)
    out, omin, omax = run_op("_contrib_quantized_fully_connected",
                             {}, qx, qw, xmin, xmax, wmin, wmax)
    t = float(np.abs(x @ x.T).max())
    rq, rmin, rmax = run_op("_contrib_requantize",
                            {"min_calib_range": -t, "max_calib_range": t},
                            out, omin, omax)
    assert rq.dtype == np.int8 and rmax[0] == np.float32(t)
    (deq,) = run_op("_contrib_dequantize", {}, rq, rmin, rmax)
    np.testing.assert_allclose(deq, x @ x.T, atol=t / 127 * 1.5 + 0.02)


def _small_mlp():
    data = mx.sym.Variable("data")
    h = mx.sym.FullyConnected(data, num_hidden=16, name="fc1")
    h = mx.sym.Activation(h, act_type="relu", name="relu1")
    out = mx.sym.FullyConnected(h, num_hidden=4, name="fc2")
    return out


def _init_params(sym, data_shape):
    ex = sym.simple_bind(mx.cpu(), data=data_shape)
    rng = np.random.RandomState(3)
    args = {}
    for name, arr in ex.arg_dict.items():
        if name == "data":
            continue
        args[name] = mx.nd.array(
            (rng.randn(*arr.shape) * 0.3).astype(np.float32))
    return args


class _Batch:
    def __init__(self, data):
        self.data = data


@pytest.mark.parametrize("calib_mode", ["naive", "entropy"])
def test_quantize_model_end_to_end(calib_mode):
    from mxnet_tpu.contrib import quantization as qt
    sym = _small_mlp()
    args = _init_params(sym, (8, 32))
    rng = np.random.RandomState(4)
    calib = [_Batch([mx.nd.array(rng.randn(8, 32).astype(np.float32))])
             for _ in range(3)]
    qsym, qargs, qaux = qt.quantize_model(
        sym, args, {}, calib_mode=calib_mode, calib_data=calib,
        ctx=mx.cpu())
    # evaluate on a calibration batch: naive calibration clips values
    # beyond the calibrated range by design, so an uncovered random draw
    # can legitimately saturate (same behavior as the reference)
    xv = calib[0].data[0].asnumpy()
    # fp32 reference
    ex = sym.simple_bind(mx.cpu(), data=(8, 32))
    for k, v in args.items():
        v.copyto(ex.arg_dict[k])
    ex.forward(is_train=False, data=mx.nd.array(xv))
    want = ex.outputs[0].asnumpy()
    # int8
    qex = qsym.simple_bind(mx.cpu(), data=(8, 32))
    for k, v in qargs.items():
        if k in qex.arg_dict:
            v.copyto(qex.arg_dict[k])
    qex.forward(is_train=False, data=mx.nd.array(xv))
    got = qex.outputs[0].asnumpy()
    if calib_mode == "naive":
        rel = np.abs(got - want).max() / (np.abs(want).max() + 1e-9)
        assert rel < 0.1, "int8 output diverged: rel err %.4f" % rel
    else:
        # entropy calibration clips distribution tails ON PURPOSE, so
        # judge by the bulk error, not the max
        rel = np.abs(got - want).mean() / (np.abs(want).mean() + 1e-9)
        assert rel < 0.1, "int8 bulk error too high: %.4f" % rel


def test_quantize_model_excluded_layer_stays_fp32():
    from mxnet_tpu.contrib import quantization as qt
    sym = _small_mlp()
    args = _init_params(sym, (2, 32))
    qsym, _, _ = qt.quantize_model(sym, args, {}, calib_mode="none",
                                   excluded_sym_names=["fc2"])
    names = [n.op for n in qsym._topo_nodes() if not n.is_var()]
    assert "_contrib_quantized_fully_connected" in names
    assert "FullyConnected" in names  # fc2 untouched
