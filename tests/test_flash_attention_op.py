"""_contrib_flash_attention op: nd/symbol/grad integration (the kernel
itself is covered by tests/test_pallas.py; this is the registry surface)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd


def _oracle(q, k, v, causal):
    B, T, H, D = q.shape
    s = np.einsum("bqhd,bkhd->bhqk", q, k) / np.sqrt(D)
    if causal:
        mask = np.tril(np.ones((T, T), bool))
        s = np.where(mask[None, None], s, -1e30)
    p = np.exp(s - s.max(-1, keepdims=True))
    p /= p.sum(-1, keepdims=True)
    return np.einsum("bhqk,bkhd->bqhd", p, v)


@pytest.mark.parametrize("causal", [False, True])
def test_nd_matches_oracle(causal):
    rng = np.random.RandomState(0)
    q, k, v = (rng.randn(2, 16, 4, 8).astype("f") for _ in range(3))
    out = mx.nd.contrib.flash_attention(
        mx.nd.array(q), mx.nd.array(k), mx.nd.array(v), causal=causal)
    np.testing.assert_allclose(out.asnumpy(), _oracle(q, k, v, causal),
                               rtol=1e-4, atol=1e-5)


def test_gradient_flows():
    rng = np.random.RandomState(1)
    q = mx.nd.array(rng.randn(1, 8, 2, 8).astype("f"))
    k = mx.nd.array(rng.randn(1, 8, 2, 8).astype("f"))
    v = mx.nd.array(rng.randn(1, 8, 2, 8).astype("f"))
    for x in (q, k, v):
        x.attach_grad()
    with autograd.record():
        out = mx.nd.contrib.flash_attention(q, k, v, causal=True)
    out.backward()
    for x in (q, k, v):
        g = x.grad.asnumpy()
        assert np.isfinite(g).all() and np.abs(g).sum() > 0


def test_symbol_binds():
    rng = np.random.RandomState(2)
    qn, kn, vn = (rng.randn(2, 12, 2, 8).astype("f") for _ in range(3))
    sym = mx.sym.contrib.flash_attention(
        mx.sym.var("q"), mx.sym.var("k"), mx.sym.var("v"), causal=False)
    ex = sym.simple_bind(mx.cpu(), q=qn.shape, k=kn.shape, v=vn.shape)
    ex.arg_dict["q"][:] = qn
    ex.arg_dict["k"][:] = kn
    ex.arg_dict["v"][:] = vn
    out = ex.forward()[0].asnumpy()
    np.testing.assert_allclose(out, _oracle(qn, kn, vn, False),
                               rtol=1e-4, atol=1e-5)
