"""`mxnet_tpu/predict.py` (reference c_predict_api): create /
partial-out / keyword forward / reshape weight-sharing / the `_c_*`
native-boundary helpers / error paths."""
import os

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import predict as P
from mxnet_tpu.predict import Predictor

IN_DIM = 10


def _mlp():
    data = mx.sym.Variable("data")
    fc1 = mx.sym.FullyConnected(data, num_hidden=8, name="fc1")
    act = mx.sym.Activation(fc1, act_type="relu", name="relu1")
    return mx.sym.FullyConnected(act, num_hidden=3, name="fc2")


def _init_params(net, batch=4):
    """Random weights via a bound executor; returns {name: NDArray}."""
    exe = net.simple_bind(mx.cpu(), data=(batch, IN_DIM))
    rng = np.random.RandomState(0)
    params = {}
    for name, arr in exe.arg_dict.items():
        if name == "data":
            continue
        arr[:] = (rng.randn(*arr.shape) * 0.1).astype(np.float32)
        params[name] = arr
    return params


def _np_forward(params, x):
    h = x @ params["fc1_weight"].asnumpy().T + params["fc1_bias"].asnumpy()
    h = np.maximum(h, 0.0)
    return h @ params["fc2_weight"].asnumpy().T \
        + params["fc2_bias"].asnumpy()


@pytest.fixture(scope="module")
def net():
    return _mlp()


@pytest.fixture(scope="module")
def params(net):
    return _init_params(net)


def test_create_forward_get_output(net, params):
    pred = Predictor(net.tojson(), dict(params),
                     input_shapes={"data": (4, IN_DIM)})
    x = np.random.RandomState(1).rand(4, IN_DIM).astype(np.float32)
    pred.forward(data=x)
    out = pred.get_output(0)
    assert out.shape == (4, 3)
    np.testing.assert_allclose(out, _np_forward(params, x), atol=1e-5)
    assert pred.num_outputs == 1
    assert pred.get_output_shape(0) == (4, 3)


def test_create_from_params_file_and_bytes(net, params, tmp_path):
    path = str(tmp_path / "net.params")
    # reference .params container carries arg:/aux: prefixed names
    mx.nd.save(path, {"arg:%s" % k: v for k, v in params.items()})
    x = np.random.RandomState(2).rand(2, IN_DIM).astype(np.float32)
    want = _np_forward(params, x)

    for blob in (path, open(path, "rb").read()):
        pred = Predictor(net.tojson(), blob,
                         input_shapes={"data": (2, IN_DIM)})
        pred.forward(data=x)
        np.testing.assert_allclose(pred.get_output(0), want, atol=1e-5)


def test_partial_out(net, params):
    # MXPredCreatePartialOut: bind an internal layer as the output
    pred = Predictor(net.tojson(), dict(params),
                     input_shapes={"data": (4, IN_DIM)},
                     output_names=["fc1"])
    x = np.random.RandomState(3).rand(4, IN_DIM).astype(np.float32)
    pred.forward(data=x)
    out = pred.get_output(0)
    assert out.shape == (4, 8)
    w, b = params["fc1_weight"].asnumpy(), params["fc1_bias"].asnumpy()
    np.testing.assert_allclose(out, x @ w.T + b, atol=1e-5)


def test_set_input_checks(net, params):
    pred = Predictor(net.tojson(), dict(params),
                     input_shapes={"data": (4, IN_DIM)})
    with pytest.raises(mx.MXNetError, match="no input named"):
        pred.set_input("bogus", np.zeros((4, IN_DIM), np.float32))
    # a weight is NOT a settable input (reference rejects non-input keys)
    with pytest.raises(mx.MXNetError, match="no input named"):
        pred.set_input("fc1_weight", params["fc1_weight"].asnumpy())
    with pytest.raises(mx.MXNetError, match="use reshape"):
        pred.set_input("data", np.zeros((5, IN_DIM), np.float32))


def test_reshape_shares_weights(net, params):
    pred = Predictor(net.tojson(), dict(params),
                     input_shapes={"data": (4, IN_DIM)})
    held = pred._params
    pred.reshape({"data": (7, IN_DIM)})
    assert pred._params is held          # no reload of the blob
    x = np.random.RandomState(4).rand(7, IN_DIM).astype(np.float32)
    pred.forward(data=x)
    out = pred.get_output(0)
    assert out.shape == (7, 3)
    np.testing.assert_allclose(out, _np_forward(params, x), atol=1e-5)


def test_reshape_rejects_unknown_names(net, params):
    pred = Predictor(net.tojson(), dict(params),
                     input_shapes={"data": (4, IN_DIM)})
    with pytest.raises(mx.MXNetError, match=r"unknown input name.*'datum'"
                                            r".*valid inputs.*data"):
        pred.reshape({"datum": (4, IN_DIM)})
    # the typo did NOT corrupt the bound shapes
    pred.forward(data=np.zeros((4, IN_DIM), np.float32))
    assert pred.get_output(0).shape == (4, 3)


def test_sibling_shares_param_buffers(net, params):
    pred = Predictor(net.tojson(), dict(params),
                     input_shapes={"data": (4, IN_DIM)})
    sib = pred.sibling({"data": (2, IN_DIM)})
    assert sib._params is pred._params
    # the weight DEVICE buffers are the same NDArrays (shared_exec), so
    # N bucket-bound predictors cost one copy of the model
    for name in params:
        assert sib._exec.arg_dict[name] is pred._exec.arg_dict[name]
    # the original handle keeps its shapes
    assert pred._exec.arg_dict["data"].shape == (4, IN_DIM)
    x = np.random.RandomState(5).rand(2, IN_DIM).astype(np.float32)
    sib.forward(data=x)
    np.testing.assert_allclose(sib.get_output(0), _np_forward(params, x),
                               atol=1e-5)


def test_output_index_bounds(net, params):
    pred = Predictor(net.tojson(), dict(params),
                     input_shapes={"data": (4, IN_DIM)})
    pred.forward(data=np.zeros((4, IN_DIM), np.float32))
    for bad in (1, -1, 99):
        with pytest.raises(mx.MXNetError, match="out of range"):
            pred.get_output(bad)
        with pytest.raises(mx.MXNetError, match="out of range"):
            pred.get_output_shape(bad)


def test_aux_states_load(tmp_path):
    # BatchNorm carries aux states: the aux: prefix path must populate
    # moving_mean/moving_var, and inference must consume them
    data = mx.sym.Variable("data")
    bn = mx.sym.BatchNorm(data, name="bn", fix_gamma=False)
    exe = bn.simple_bind(mx.cpu(), data=(4, 6))
    params = {}
    for name, arr in exe.arg_dict.items():
        if name == "data":
            continue
        arr[:] = 1.0 if name.endswith("gamma") else 0.0
        params["arg:%s" % name] = arr
    mean = np.arange(6, dtype=np.float32)
    for name, arr in exe.aux_dict.items():
        arr[:] = mean if name.endswith("mean") else 1.0
        params["aux:%s" % name] = arr
    pred = Predictor(bn.tojson(), params, input_shapes={"data": (4, 6)})
    x = np.tile(mean, (4, 1))
    pred.forward(data=x)
    # (x - moving_mean) / sqrt(var + eps): exactly zero at x == mean
    np.testing.assert_allclose(pred.get_output(0), np.zeros((4, 6)),
                               atol=1e-4)


def test_c_boundary_helpers(net, params, tmp_path):
    path = str(tmp_path / "net.params")
    mx.nd.save(path, {"arg:%s" % k: v for k, v in params.items()})
    blob = open(path, "rb").read()
    pred = P._c_create(net.tojson(), blob, 1, 0, ["data"],
                       [(4, IN_DIM)], [])
    x = np.random.RandomState(6).rand(4, IN_DIM).astype(np.float32)
    P._c_set_input(pred, "data", memoryview(x.tobytes()), x.size)
    pred.forward()
    assert P._c_output_shape(pred, 0) == (4, 3)
    out = np.frombuffer(P._c_get_output_bytes(pred, 0),
                        dtype=np.float32).reshape(4, 3)
    np.testing.assert_allclose(out, _np_forward(params, x), atol=1e-5)

    with pytest.raises(mx.MXNetError, match="no input named"):
        P._c_set_input(pred, "nope", memoryview(x.tobytes()), x.size)
    with pytest.raises(mx.MXNetError, match="size"):
        P._c_set_input(pred, "data", memoryview(x.tobytes()), x.size - 1)

    # _c_reshape: NEW handle, shared weights, original keeps its shapes
    new = P._c_reshape(pred, ["data"], [(2, IN_DIM)])
    assert new is not pred and new._params is pred._params
    assert pred._exec.arg_dict["data"].shape == (4, IN_DIM)
    x2 = x[:2]
    new.forward(data=x2)
    np.testing.assert_allclose(new.get_output(0),
                               _np_forward(params, x2), atol=1e-5)


def test_context_manager_close(net, params):
    with Predictor(net.tojson(), dict(params),
                   input_shapes={"data": (2, IN_DIM)}) as pred:
        pred.forward(data=np.zeros((2, IN_DIM), np.float32))
        assert pred.get_output(0).shape == (2, 3)
    assert pred._exec is None   # MXPredFree
    with pytest.raises(mx.MXNetError, match="closed Predictor"):
        pred.sibling({"data": (2, IN_DIM)})
