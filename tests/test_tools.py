"""Tools tests: im2rec list+rec round trip, rec2idx, parse_log."""
import os
import re
import subprocess
import sys

import numpy as np
import pytest

import mxnet_tpu as mx

TOOLS = os.path.join(os.path.dirname(__file__), "..", "tools")
ENV = dict(os.environ, PYTHONPATH=os.path.join(TOOLS, ".."))


def _make_image_tree(root):
    from mxnet_tpu.image import codec
    rng = np.random.RandomState(0)
    for cls in ["cat", "dog"]:
        os.makedirs(os.path.join(root, cls), exist_ok=True)
        for i in range(3):
            img = (rng.rand(12, 14, 3) * 255).astype("uint8")
            buf = codec.imencode(img, ".jpg", quality=95)
            with open(os.path.join(root, cls, "%d.jpg" % i), "wb") as f:
                f.write(buf)


def _run(script, *args):
    return subprocess.run(
        [sys.executable, os.path.join(TOOLS, script)] + list(args),
        capture_output=True, text=True, env=ENV)


def test_im2rec_roundtrip(tmp_path):
    root = str(tmp_path / "imgs")
    _make_image_tree(root)
    prefix = str(tmp_path / "data")
    r = _run("im2rec.py", prefix, root, "--list", "--recursive")
    assert r.returncode == 0, r.stderr
    lst = prefix + ".lst"
    assert os.path.exists(lst)
    lines = open(lst).read().strip().split("\n")
    assert len(lines) == 6
    labels = {float(l.split("\t")[1]) for l in lines}
    assert labels == {0.0, 1.0}

    r = _run("im2rec.py", prefix, root)
    assert r.returncode == 0, r.stderr
    assert os.path.exists(prefix + ".rec") and os.path.exists(
        prefix + ".idx")

    # records decode back to images with matching labels
    from mxnet_tpu import recordio
    rec = recordio.MXIndexedRecordIO(prefix + ".idx", prefix + ".rec", "r")
    n = 0
    for line in lines:
        idx = int(line.split("\t")[0])
        header, img = recordio.unpack_img(rec.read_idx(idx))
        assert img.shape == (12, 14, 3)
        assert float(header.label) in (0.0, 1.0)
        n += 1
    assert n == 6
    rec.close()


def test_rec2idx(tmp_path):
    from mxnet_tpu import recordio
    rec_path = str(tmp_path / "x.rec")
    w = recordio.MXIndexedRecordIO(str(tmp_path / "orig.idx"), rec_path, "w")
    for i in range(5):
        w.write_idx(i, recordio.pack(
            recordio.IRHeader(0, float(i), i, 0), b"payload%d" % i))
    w.close()
    r = _run("rec2idx.py", rec_path, str(tmp_path / "rebuilt.idx"))
    assert r.returncode == 0, r.stderr
    orig = open(str(tmp_path / "orig.idx")).read()
    rebuilt = open(str(tmp_path / "rebuilt.idx")).read()
    assert orig == rebuilt


def test_parse_log(tmp_path):
    log = tmp_path / "train.log"
    log.write_text(
        "INFO Epoch[0] Train-accuracy=0.5\n"
        "INFO Epoch[0] Time cost=10.0\n"
        "INFO Epoch[0] Validation-accuracy=0.55\n"
        "INFO Epoch[1] Train-accuracy=0.8\n"
        "INFO Epoch[1] Time cost=9.0\n"
        "INFO Epoch[1] Validation-accuracy=0.75\n")
    r = _run("parse_log.py", str(log))
    assert r.returncode == 0, r.stderr
    assert "| epoch |" in r.stdout
    assert "0.800000" in r.stdout and "0.750000" in r.stdout
    r = _run("parse_log.py", str(log), "--format", "none")
    assert "train-accuracy" in r.stdout


def test_launch_local_spawns_workers(tmp_path):
    script = tmp_path / "worker.py"
    script.write_text(
        "import os\n"
        "print('rank', os.environ['DMLC_WORKER_ID'],"
        " 'of', os.environ['DMLC_NUM_WORKER'])\n")
    r = _run("launch.py", "-n", "2", sys.executable, str(script))
    assert r.returncode == 0, r.stderr


def test_launch_dist_sync_kvstore(tmp_path):
    """2-process dist_sync consistency over the local launcher — the
    reference's tests/nightly/dist_sync_kvstore.py trick of running the
    real transport on one machine (ci/docker/runtime_functions.sh:551)."""
    worker = tmp_path / "worker.py"
    worker.write_text(
        "import os\n"
        "os.environ.setdefault('PALLAS_AXON_POOL_IPS', '')\n"
        "import numpy as np\n"
        "import mxnet_tpu as mx\n"
        "from mxnet_tpu.parallel import dist\n"
        "dist.init()\n"
        "kv = mx.kv.create('dist_sync')\n"
        "rank, nw = kv.rank, kv.num_workers\n"
        "assert nw == 2, nw\n"
        "kv.init('w', mx.nd.zeros((3, 4)))\n"
        "kv.push('w', mx.nd.ones((3, 4)) * (rank + 1))\n"
        "out = mx.nd.zeros((3, 4))\n"
        "kv.pull('w', out=out)\n"
        "np.testing.assert_allclose(out.asnumpy(), 3.0)\n"
        "kv.barrier()\n"
        "rid = mx.nd.array(np.array([1], 'f'))\n"
        "kv.row_sparse_pull('w', out=out, row_ids=rid)\n"
        "np.testing.assert_allclose(out.asnumpy()[1], 3.0)\n"
        "np.testing.assert_allclose(out.asnumpy()[0], 0.0)\n"
        "print('DIST WORKER', rank, 'OK')\n")
    env = dict(os.environ, PYTHONPATH=os.path.join(TOOLS, ".."))
    env.pop("JAX_PLATFORMS", None)  # launcher pins cpu itself
    r = subprocess.run(
        [sys.executable, os.path.join(TOOLS, "launch.py"), "-n", "2",
         "--port", "9441", "--", sys.executable, str(worker)],
        capture_output=True, text=True, env=env, timeout=300)
    assert r.returncode == 0, r.stderr + r.stdout
    assert r.stdout.count("OK") == 2


def test_launch_dist_training_converges(tmp_path):
    """2-process data-parallel Module training over dist_sync — the
    reference's tests/nightly/dist_lenet.py convergence check run with
    the local launcher. Each worker fits its shard; synced params must
    classify the full set."""
    worker = tmp_path / "train_worker.py"
    worker.write_text(
        "import os\n"
        "os.environ.setdefault('PALLAS_AXON_POOL_IPS', '')\n"
        "import numpy as np\n"
        "import mxnet_tpu as mx\n"
        "from mxnet_tpu.parallel import dist\n"
        "dist.init()\n"
        "kv = mx.kv.create('dist_sync')\n"
        "rank, nw = kv.rank, kv.num_workers\n"
        "rng = np.random.RandomState(0)\n"
        "protos = rng.rand(4, 16).astype('f') * 2\n"
        "y = rng.randint(0, 4, 800)\n"
        "X = protos[y] + rng.randn(800, 16).astype('f') * 0.1\n"
        "sl = slice(rank * 400, (rank + 1) * 400)  # worker shard\n"
        "train = mx.io.NDArrayIter(X[sl], y[sl].astype('f'), 50,\n"
        "                          shuffle=True)\n"
        "data = mx.sym.var('data')\n"
        "net = mx.sym.FullyConnected(data, num_hidden=32, name='fc1')\n"
        "net = mx.sym.Activation(net, act_type='relu')\n"
        "net = mx.sym.FullyConnected(net, num_hidden=4, name='fc2')\n"
        "net = mx.sym.SoftmaxOutput(net, name='softmax')\n"
        "mod = mx.mod.Module(net)\n"
        "mod.fit(train, optimizer='sgd', initializer=mx.init.Xavier(),\n"
        "        optimizer_params={'learning_rate': 0.3}, num_epoch=6,\n"
        "        kvstore=kv)\n"
        "val = mx.io.NDArrayIter(X, y.astype('f'), 50)\n"
        "acc = dict(mod.score(val, 'acc'))['accuracy']\n"
        "assert acc > 0.9, acc\n"
        "# params must be identical across workers after sync training;\n"
        "# each worker prints a digest and the harness compares them\n"
        "arg_params, _ = mod.get_params()\n"
        "w = arg_params['fc1_weight'].asnumpy()\n"
        "digest = float(np.abs(w).sum())\n"
        "print('DIGEST %.6f' % digest)\n"
        "print('DIST TRAIN', rank, 'acc %.3f OK' % acc)\n")
    env = dict(os.environ, PYTHONPATH=os.path.join(TOOLS, ".."))
    env.pop("JAX_PLATFORMS", None)
    r = subprocess.run(
        [sys.executable, os.path.join(TOOLS, "launch.py"), "-n", "2",
         "--port", "9443", "--", sys.executable, str(worker)],
        capture_output=True, text=True, env=env, timeout=420)
    assert r.returncode == 0, r.stderr + r.stdout
    assert r.stdout.count("OK") == 2
    digests = re.findall(r"DIGEST ([0-9.]+)", r.stdout)
    assert len(digests) == 2 and digests[0] == digests[1], digests


def test_launch_dist_gluon_trainer_local_update(tmp_path):
    """2-process gluon Trainer with update_on_kvstore=False: gradients
    sync through the store while the updater runs locally — workers must
    still end bit-identical, which requires the rank-0 init broadcast +
    pull-after-init (reference Trainer._init_kvstore)."""
    worker = tmp_path / "gluon_worker.py"
    worker.write_text(
        "import os\n"
        "os.environ.setdefault('PALLAS_AXON_POOL_IPS', '')\n"
        "import numpy as np\n"
        "import mxnet_tpu as mx\n"
        "from mxnet_tpu import autograd, gluon\n"
        "from mxnet_tpu.parallel import dist\n"
        "dist.init()\n"
        "kv = mx.kv.create('dist_sync')\n"
        "rank = kv.rank\n"
        "rng = np.random.RandomState(100 + rank)  # divergent local init\n"
        "mx.random.seed(100 + rank)\n"
        "X = rng.rand(200, 8).astype('f')\n"
        "y = (X.sum(1) > 4).astype('f')\n"
        "net = gluon.nn.Dense(1)\n"
        "net.initialize(mx.init.Xavier())\n"
        "net(mx.nd.zeros((2, 8)))  # materialize (per-rank different!)\n"
        "tr = gluon.Trainer(net.collect_params(), 'sgd',\n"
        "                   {'learning_rate': 0.1}, kvstore=kv,\n"
        "                   update_on_kvstore=False)\n"
        "loss_fn = gluon.loss.SigmoidBinaryCrossEntropyLoss()\n"
        "for step in range(5):\n"
        "    i = step * 40\n"
        "    d = mx.nd.array(X[i:i+40]); l = mx.nd.array(y[i:i+40])\n"
        "    with autograd.record():\n"
        "        loss = loss_fn(net(d), l)\n"
        "    loss.backward()\n"
        "    tr.step(40)\n"
        "w = net.weight.data().asnumpy()\n"
        "print('DIGEST %.8f' % float(np.abs(w).sum()))\n"
        "print('GLUON DIST', rank, 'OK')\n")
    env = dict(os.environ, PYTHONPATH=os.path.join(TOOLS, ".."))
    env.pop("JAX_PLATFORMS", None)
    r = subprocess.run(
        [sys.executable, os.path.join(TOOLS, "launch.py"), "-n", "2",
         "--port", "9447", "--", sys.executable, str(worker)],
        capture_output=True, text=True, env=env, timeout=420)
    assert r.returncode == 0, r.stderr + r.stdout
    assert r.stdout.count("OK") == 2
    digests = re.findall(r"DIGEST ([0-9.]+)", r.stdout)
    assert len(digests) == 2 and digests[0] == digests[1], digests


def test_bench_all_emits_json_records(tmp_path):
    """tools/bench_all.py records a north-star config as a bench.py-style
    JSON line + combined file (VERDICT r3 #7: per-round regression
    record for the BASELINE.md configs)."""
    import json
    out = tmp_path / "rec.json"
    r = subprocess.run(
        [sys.executable,
         os.path.join(os.path.dirname(__file__), os.pardir, "tools",
                      "bench_all.py"),
         "--only", "sparse_fm", "--out", str(out)],
        capture_output=True, text=True, timeout=600,
        env={**os.environ, "JAX_PLATFORMS": "cpu",
             "PALLAS_AXON_POOL_IPS": ""})
    assert r.returncode == 0, r.stderr[-2000:]
    rec = json.loads(r.stdout.strip().splitlines()[-1])
    assert rec["metric"] == "sparse_fm_samples_per_sec"
    assert rec["value"] and rec["value"] > 0
    saved = json.loads(out.read_text())
    assert saved[0]["metric"] == rec["metric"]


def test_serve_bench_closed_loop(tmp_path):
    """serve_bench: closed loop against the demo engine emits the
    BENCH-style metric lines and they parse through bench_gate."""
    import json
    out = str(tmp_path / "serve.jsonl")
    r = _run("serve_bench.py", "--mode", "closed", "--clients", "2",
             "--requests", "3", "--sizes", "1,2", "--out", out)
    assert r.returncode == 0, r.stderr
    sys.path.insert(0, TOOLS)
    import bench_gate
    recs = bench_gate.parse_lines(open(out).read().splitlines())
    metrics = {rec["metric"]: rec for rec in recs}
    for name in ("serving_warmup_compiles", "serving_closed_rps",
                 "serving_closed_rows_per_sec", "serving_closed_p50_ms",
                 "serving_closed_p95_ms", "serving_closed_p99_ms",
                 "serving_cold_compiles"):
        assert name in metrics, (name, sorted(metrics))
    assert metrics["serving_closed_rps"]["value"] > 0
    assert metrics["serving_cold_compiles"]["value"] == 0
    # 2 clients x 3 requests, none rejected in an unloaded engine
    assert "serving_closed_shed_total" not in metrics
    # the p99 line carries the request anatomy (phase shares + verdict)
    # so a latency regression gates pre-diagnosed, TRAIN-style
    p99 = metrics["serving_closed_p99_ms"]
    assert p99.get("verdict")
    assert p99.get("phases") and abs(sum(p99["phases"].values()) - 1.0) \
        < 0.01
    assert metrics["serving_closed_pad_waste_ratio"]["value"] >= 0.0
