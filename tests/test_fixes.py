"""Regression tests for review findings: CTC loss math, positional attr
args, NDArrayIter roll_over, F1 averaging, PrefetchingIter depth."""
import itertools

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import gluon


def _brute_ctc(probs, label, blank):
    """-log p(label) by enumerating all alignment paths (probs: (T, C))."""
    T, C = probs.shape
    total = 0.0
    for path in itertools.product(range(C), repeat=T):
        # collapse: remove repeats, then blanks
        collapsed = [k for k, _ in itertools.groupby(path) if k != blank]
        if collapsed == list(label):
            p = 1.0
            for t, k in enumerate(path):
                p *= probs[t, k]
            total += p
    return -np.log(total)


@pytest.mark.parametrize("blank_label", ["first", "last"])
def test_ctc_loss_against_brute_force(blank_label):
    rng = np.random.RandomState(3)
    T, B, C = 4, 2, 3
    logits = rng.randn(T, B, C).astype("float32")
    probs = np.exp(logits) / np.exp(logits).sum(-1, keepdims=True)
    blank = 0 if blank_label == "first" else C - 1
    if blank_label == "first":
        labels = np.array([[1, 2], [2, 0]], "float32")  # 0 pads
        label_seqs = [[1, 2], [2]]
    else:
        labels = np.array([[0, 1], [1, -1]], "float32")  # -1 pads
        label_seqs = [[0, 1], [1]]
    out = mx.nd.CTCLoss(mx.nd.array(logits), mx.nd.array(labels),
                           blank_label=blank_label).asnumpy()
    for b in range(B):
        want = _brute_ctc(probs[:, b], label_seqs[b], blank)
        assert abs(out[b] - want) < 1e-4, (b, out[b], want)


def test_ctc_loss_data_and_label_lengths():
    rng = np.random.RandomState(0)
    T, B, C = 5, 2, 4
    logits = rng.randn(T, B, C).astype("float32")
    probs = np.exp(logits) / np.exp(logits).sum(-1, keepdims=True)
    labels = np.array([[1, 2, 3], [2, 2, 0]], "float32")
    data_len = np.array([4, 5], "float32")
    label_len = np.array([2, 2], "float32")
    out = mx.nd.CTCLoss(
        mx.nd.array(logits), mx.nd.array(labels),
        mx.nd.array(data_len), mx.nd.array(label_len),
        use_data_lengths=True, use_label_lengths=True).asnumpy()
    want0 = _brute_ctc(probs[:4, 0], [1, 2], 0)
    want1 = _brute_ctc(probs[:5, 1], [2, 2], 0)
    assert abs(out[0] - want0) < 1e-4
    assert abs(out[1] - want1) < 1e-4


def test_gluon_ctc_loss_ntc_layout():
    loss_fn = gluon.loss.CTCLoss()  # default NTC
    pred = mx.nd.random.uniform(shape=(2, 6, 5))
    label = mx.nd.array([[1, 2, -1, -1], [0, 1, 2, 3]])
    out = loss_fn(pred, label).asnumpy()
    assert out.shape == (2,)
    assert np.all(np.isfinite(out)) and np.all(out > 0)


def test_gluon_ctc_loss_label_lengths_used():
    loss_fn = gluon.loss.CTCLoss()
    pred = mx.nd.random.uniform(shape=(1, 6, 4))
    # label padded with 0 — a REAL class when blank is last; only
    # label_lengths distinguishes [1] from [1, 0, 0]
    label = mx.nd.array([[1, 0, 0]])
    short = loss_fn(pred, label, None, mx.nd.array([1])).asnumpy()
    full = loss_fn(pred, label).asnumpy()
    assert not np.allclose(short, full)


def test_swapaxes_positional():
    x = mx.nd.arange(6).reshape((2, 3))
    y = mx.nd.swapaxes(x, 0, 1)
    assert y.shape == (3, 2)


def test_ndarray_iter_roll_over():
    data = np.arange(10, dtype="float32").reshape(10, 1)
    it = mx.io.NDArrayIter(data, np.arange(10, dtype="float32"),
                           batch_size=4, last_batch_handle="roll_over")
    epoch1 = [b.data[0].asnumpy().ravel() for b in it]
    assert [len(b) for b in epoch1] == [4, 4]  # 2 leftover held back
    it.reset()
    # 2 held-back + 10 fresh = 12 samples -> 3 full batches
    epoch2 = [(b.data[0].asnumpy().ravel(), b.label[0].asnumpy()) for b in it]
    assert [len(d) for d, _ in epoch2] == [4, 4, 4]
    # first batch of epoch 2 starts with the held-back samples 8, 9,
    # and the labels roll with the data
    assert epoch2[0][0][0] == 8.0 and epoch2[0][0][1] == 9.0
    assert epoch2[0][1][0] == 8.0


def test_f1_macro_vs_micro():
    macro = mx.metric.F1(average="macro")
    micro = mx.metric.F1(average="micro")
    batches = [
        (np.array([1, 1, 1, 1]), np.array([1, 1, 1, 0])),
        (np.array([0, 1]), np.array([0, 0])),
    ]
    for label, pred in batches:
        pred_scores = np.eye(2)[pred]
        for m in (macro, micro):
            m.update([mx.nd.array(label)], [mx.nd.array(pred_scores)])
    # micro pools counts: tp=3, fp=0, fn=2 -> f1 = 6/8
    assert abs(micro.get()[1] - 2 * 3 / (2 * 3 + 0 + 2)) < 1e-6
    # macro averages per-batch f1: (6/7 + 0) / 2
    assert abs(macro.get()[1] - ((2 * 3 / (2 * 3 + 0 + 1)) + 0.0) / 2) < 1e-6
    assert macro.get()[1] != micro.get()[1]


def test_prefetching_iter_depth_survives_reset():
    base = mx.io.NDArrayIter(np.zeros((8, 2), "float32"), batch_size=2)
    it = mx.io.PrefetchingIter(base, depth=5)
    list(it)
    it.reset()
    assert it._queue.maxsize == 5
    assert len(list(it)) == 4


def test_topk_mask():
    x = mx.nd.array([[1.0, 3.0, 2.0]])
    mask = mx.nd.topk(x, k=2, ret_typ="mask").asnumpy()
    assert np.array_equal(mask, [[0, 1, 1]])


def test_topk_mask_axis0():
    x = mx.nd.array([[1.0, 3.0, 2.0], [5.0, 0.0, 4.0]])
    mask = mx.nd.topk(x, axis=0, k=1, ret_typ="mask").asnumpy()
    assert np.array_equal(mask, [[0, 1, 0], [1, 0, 1]])


def test_ctc_loss_empty_label():
    logits = np.zeros((3, 1, 2), "float32")  # uniform: p(blank)=0.5 per step
    out = mx.nd.CTCLoss(mx.nd.array(logits), mx.nd.array([[1.0]]),
                        mx.nd.array([3.0]), mx.nd.array([0.0]),
                        use_data_lengths=True, use_label_lengths=True).asnumpy()
    assert abs(out[0] - (-np.log(0.5 ** 3))) < 1e-4


def test_symbol_swapaxes_positional():
    s = mx.sym.var("x")
    y = mx.sym.swapaxes(s, 0, 1)
    ex = y.bind(mx.cpu(), {"x": mx.nd.ones((2, 3))})
    assert ex.forward()[0].shape == (3, 2)


def test_hard_reset_drops_roll_over_cache():
    data = np.arange(10, dtype="float32").reshape(10, 1)
    it = mx.io.NDArrayIter(data, batch_size=4, last_batch_handle="roll_over")
    list(it)  # leaves a 2-sample cache
    it.hard_reset()
    it.reset()
    first = next(it)
    assert first.data[0].asnumpy()[0, 0] == 0.0


def test_small_parity_modules():
    """kvstore_server/log/registry/libinfo exist with reference APIs."""
    import warnings
    import mxnet_tpu as mx
    assert mx.libinfo.find_lib_path(), "native lib should be discoverable"
    lg = mx.log.get_logger("parity_test", level=mx.log.INFO)
    lg.info("hello")

    class Base:
        pass

    class Impl(Base):
        pass
    reg = mx.registry.get_register_func(Base, "thing")
    reg(Impl)
    create = mx.registry.get_create_func(Base, "thing")
    assert isinstance(create("impl"), Impl)
    assert isinstance(create(Impl()), Impl)
    alias = mx.registry.get_alias_func(Base, "thing")
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        alias("impl2")(Impl)
    assert isinstance(create("impl2"), Impl)
    srv = mx.kvstore_server.KVStoreServer(mx.kv.create("local"))
    assert callable(srv._controller())


def test_batchnorm_variance_large_mean_stable():
    """ADVICE r2: E[x^2]-E[x]^2 cancels catastrophically for large-mean
    activations (first BN over 0-255 images); the centered two-pass form
    must match numpy's variance."""
    rng = np.random.RandomState(7)
    x = (rng.rand(4, 3, 8, 8) * 255.0).astype(np.float32) + 1e4
    data = mx.nd.array(x)
    gamma = mx.nd.ones((3,))
    beta = mx.nd.zeros((3,))
    mm = mx.nd.zeros((3,))
    mv = mx.nd.ones((3,))
    with mx.autograd.record(train_mode=True):
        out = mx.nd.BatchNorm(data, gamma, beta, mm, mv, fix_gamma=False,
                              eps=1e-5)
    got = out[0].asnumpy() if isinstance(out, list) else out.asnumpy()
    ref_mean = x.mean(axis=(0, 2, 3), keepdims=True)
    ref_var = x.var(axis=(0, 2, 3), keepdims=True)
    want = (x - ref_mean) / np.sqrt(ref_var + 1e-5)
    np.testing.assert_allclose(got, want, rtol=2e-3, atol=2e-3)


def test_create_graph_replay_uses_recorded_inputs():
    """ADVICE r2: grad(create_graph=True) must replay the forward on the
    RECORDED input buffers, not the current ones after in-place mutation."""
    x = mx.nd.array([2.0])
    x.attach_grad()
    with mx.autograd.record():
        y = x * x * x          # y = x^3, dy/dx = 3x^2 = 12 at x=2
    x[:] = 100.0               # mutate AFTER recording, BEFORE the replay
    gx = mx.autograd.grad(y, x, create_graph=True)
    np.testing.assert_allclose(gx.asnumpy(), [12.0], rtol=1e-6)


def test_legacy_misc_scheduler():
    """Deprecated mx.misc scheduler API (reference misc.py) keeps
    working for old user code."""
    import mxnet_tpu as mx
    s = mx.misc.FactorScheduler(step=10, factor=0.5)
    s.base_lr = 0.8
    assert abs(s(0) - 0.8) < 1e-9
    assert abs(s(10) - 0.4) < 1e-9
    assert abs(s(25) - 0.2) < 1e-9
    import pytest
    with pytest.raises(ValueError):
        mx.misc.FactorScheduler(step=0)


def test_get_logger_root_gets_formatter_and_replaces_handlers(tmp_path):
    """Satellite (PR 2): the root logger (name=None) gets the colored
    formatter like any named logger, and re-calling with a different
    filename REPLACES the old handler instead of stacking a second."""
    import logging
    from mxnet_tpu.log import _Formatter

    root = logging.getLogger()
    saved = list(root.handlers)
    try:
        root.handlers = []
        lg = mx.log.get_logger(level=mx.log.INFO)
        ours = [h for h in lg.handlers
                if isinstance(h.formatter, _Formatter)]
        assert len(ours) == 1  # root got the framework formatter
        assert mx.log.get_logger(level=mx.log.INFO) is lg
        assert len([h for h in lg.handlers
                    if isinstance(h.formatter, _Formatter)]) == 1
    finally:
        for h in list(root.handlers):
            root.removeHandler(h)
            h.close()
        root.handlers = saved
        root._mx_log_dest = ()

    f1, f2 = str(tmp_path / "a.log"), str(tmp_path / "b.log")
    lg = mx.log.get_logger("telemetry_fix_test", filename=f1,
                           level=mx.log.INFO)
    lg.info("to-a")
    # same destination: no new handler stacked
    mx.log.get_logger("telemetry_fix_test", filename=f1, level=mx.log.INFO)
    assert len(lg.handlers) == 1
    # NEW destination: handler replaced, old file stops receiving
    mx.log.get_logger("telemetry_fix_test", filename=f2, level=mx.log.INFO)
    assert len(lg.handlers) == 1
    lg.info("to-b")
    a, b = open(f1).read(), open(f2).read()
    assert "to-a" in a and "to-b" not in a
    assert "to-b" in b


def test_profiler_resume_without_config_is_a_noop(monkeypatch):
    """Satellite (PR 2): a bare resume() used to silently start a trace
    into the default directory; now it warns and starts nothing."""
    import warnings
    from mxnet_tpu import profiler

    started = []
    import jax
    monkeypatch.setattr(jax.profiler, "start_trace",
                        lambda d: started.append(d))
    monkeypatch.setitem(profiler._state, "configured", False)
    monkeypatch.setitem(profiler._state, "paused", False)
    monkeypatch.setitem(profiler._state, "running", False)
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        profiler.resume()
    assert started == []
    assert any("set_config" in str(x.message) for x in w)
    assert not profiler._state["running"]
    # after set_config, resume() is a legitimate start again
    profiler.set_config(filename=str("/tmp/_prof_fix_test"))
    profiler.resume()
    assert started and profiler._state["running"]
    monkeypatch.setitem(profiler._state, "running", False)
    monkeypatch.setitem(profiler._state, "configured", False)


def test_profiler_autostart_honors_aggregate_env(tmp_path):
    """Satellite (PR 2): MXNET_PROFILER_AUTOSTART=1 +
    MXNET_PROFILER_AGGREGATE=1 collects the aggregate table."""
    import os
    import subprocess
    import sys
    code = (
        "import mxnet_tpu as mx\n"
        "a = mx.nd.ones((8, 8))\n"
        "(a + a).asnumpy()\n"
        "mx.profiler.set_state('stop')\n"
        "t = mx.profiler.dumps()\n"
        "assert 'Profile Statistics.' in t, repr(t[:80])\n"
        "print('AGG_OK')\n")
    repo = os.path.dirname(os.path.dirname(os.path.abspath(mx.__file__)))
    env = dict(os.environ, JAX_PLATFORMS="cpu", PALLAS_AXON_POOL_IPS="",
               MXNET_PROFILER_AUTOSTART="1", MXNET_PROFILER_AGGREGATE="1",
               PYTHONPATH=repo)
    r = subprocess.run([sys.executable, "-c", code], cwd=str(tmp_path),
                       capture_output=True, text=True, env=env, timeout=120)
    assert r.returncode == 0, r.stderr[-2000:]
    assert "AGG_OK" in r.stdout
