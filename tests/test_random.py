"""RNG tests (reference tests/python/unittest/test_random.py strategy:
statistical moments, seed determinism, per-distribution sanity — bitwise
parity with the reference's mshadow RNG is deliberately not a goal,
SURVEY.md §7 hard part 7)."""
import numpy as np
import pytest

import mxnet_tpu as mx


def test_seed_determinism():
    mx.random.seed(42)
    a = mx.nd.random.uniform(shape=(100,)).asnumpy()
    mx.random.seed(42)
    b = mx.nd.random.uniform(shape=(100,)).asnumpy()
    np.testing.assert_allclose(a, b)
    c = mx.nd.random.uniform(shape=(100,)).asnumpy()
    assert not np.allclose(b, c)


def test_uniform_moments():
    mx.random.seed(0)
    x = mx.nd.random.uniform(low=2.0, high=4.0, shape=(40000,)).asnumpy()
    assert 2.0 <= x.min() and x.max() <= 4.0
    np.testing.assert_allclose(x.mean(), 3.0, atol=0.05)
    np.testing.assert_allclose(x.var(), 4.0 / 12.0, atol=0.05)


def test_normal_moments():
    mx.random.seed(1)
    x = mx.nd.random.normal(loc=1.5, scale=2.0, shape=(40000,)).asnumpy()
    np.testing.assert_allclose(x.mean(), 1.5, atol=0.06)
    np.testing.assert_allclose(x.std(), 2.0, atol=0.06)


def test_gamma_poisson_exponential():
    mx.random.seed(2)
    g = mx.nd.random.gamma(alpha=4.0, beta=0.5, shape=(40000,)).asnumpy()
    np.testing.assert_allclose(g.mean(), 4.0 * 0.5, rtol=0.05)
    p = mx.nd.random.poisson(lam=3.0, shape=(40000,)).asnumpy()
    np.testing.assert_allclose(p.mean(), 3.0, rtol=0.05)
    e = mx.nd.random.exponential(scale=2.0, shape=(40000,)).asnumpy()
    np.testing.assert_allclose(e.mean(), 2.0, rtol=0.05)


def test_multinomial_distribution():
    mx.random.seed(3)
    probs = mx.nd.array(np.array([[0.1, 0.2, 0.7]], "f"))
    draws = mx.nd.sample_multinomial(probs, shape=(20000,)).asnumpy().ravel()
    freq = np.bincount(draws.astype(int), minlength=3) / draws.size
    np.testing.assert_allclose(freq, [0.1, 0.2, 0.7], atol=0.02)


def test_randint_and_shuffle():
    mx.random.seed(4)
    r = mx.nd.random.randint(low=0, high=10, shape=(1000,)).asnumpy()
    assert r.min() >= 0 and r.max() <= 9
    x = mx.nd.array(np.arange(50, dtype="f"))
    s = mx.nd.shuffle(x).asnumpy()
    assert sorted(s.tolist()) == list(range(50))
    assert not np.allclose(s, np.arange(50))


def test_symbolic_random_in_executor():
    """random symbols inside a bound executor produce fresh draws per
    forward (the reference's RNG resource semantics)."""
    x = mx.sym.random_uniform(shape=(64,), name="r")
    ex = x.bind(mx.cpu(), {})
    a = ex.forward()[0].asnumpy()
    b = ex.forward()[0].asnumpy()
    assert not np.allclose(a, b)
