"""NDArray tests (modeled on reference tests/python/unittest/test_ndarray.py)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.test_utils import assert_almost_equal


def test_creation():
    x = mx.nd.zeros((3, 4))
    assert x.shape == (3, 4)
    assert x.dtype == np.float32
    assert x.size == 12
    y = mx.nd.ones((2,), dtype="int32")
    assert y.dtype == np.int32
    z = mx.nd.full((2, 2), 7)
    assert (z.asnumpy() == 7).all()
    a = mx.nd.array([[1, 2], [3, 4]])
    assert a.shape == (2, 2)
    assert_almost_equal(a, np.array([[1, 2], [3, 4]]))
    r = mx.nd.arange(0, 10, 2)
    assert_almost_equal(r, np.arange(0, 10, 2, dtype=np.float32))


def test_elementwise_arith():
    a_np = np.random.uniform(-1, 1, (4, 5)).astype(np.float32)
    b_np = np.random.uniform(-1, 1, (4, 5)).astype(np.float32)
    a, b = mx.nd.array(a_np), mx.nd.array(b_np)
    assert_almost_equal(a + b, a_np + b_np)
    assert_almost_equal(a - b, a_np - b_np)
    assert_almost_equal(a * b, a_np * b_np)
    assert_almost_equal(a / b, a_np / b_np, rtol=1e-4)
    assert_almost_equal(a + 2, a_np + 2)
    assert_almost_equal(2 - a, 2 - a_np)
    assert_almost_equal(a * 0.5, a_np * 0.5)
    assert_almost_equal(1.0 / (a + 3), 1.0 / (a_np + 3), rtol=1e-4)
    assert_almost_equal(-a, -a_np)
    assert_almost_equal(abs(a), np.abs(a_np))
    assert_almost_equal((a ** 2), a_np ** 2, rtol=1e-4)


def test_inplace_ops():
    a_np = np.ones((3, 3), np.float32)
    a = mx.nd.array(a_np)
    a += 2
    assert (a.asnumpy() == 3).all()
    a *= 2
    assert (a.asnumpy() == 6).all()
    a -= 1
    assert (a.asnumpy() == 5).all()
    a /= 5
    assert (a.asnumpy() == 1).all()


def test_broadcast():
    a = mx.nd.ones((3, 1))
    b = mx.nd.ones((1, 4)) * 2
    c = a + b
    assert c.shape == (3, 4)
    assert (c.asnumpy() == 3).all()
    d = mx.nd.broadcast_to(a, shape=(3, 5))
    assert d.shape == (3, 5)


def test_comparisons():
    a = mx.nd.array([1, 2, 3])
    b = mx.nd.array([3, 2, 1])
    assert_almost_equal(a == b, np.array([0, 1, 0], np.float32))
    assert_almost_equal(a > b, np.array([0, 0, 1], np.float32))
    assert_almost_equal(a <= b, np.array([1, 1, 0], np.float32))
    assert_almost_equal(a != 2, np.array([1, 0, 1], np.float32))


def test_indexing():
    a_np = np.arange(24, dtype=np.float32).reshape(4, 6)
    a = mx.nd.array(a_np)
    assert_almost_equal(a[1], a_np[1])
    assert_almost_equal(a[1:3], a_np[1:3])
    assert_almost_equal(a[1, 2:4], a_np[1, 2:4])
    assert a[2, 3].asscalar() == a_np[2, 3]
    a[0] = 100
    a_np[0] = 100
    assert_almost_equal(a, a_np)
    a[1:3, 0] = -1
    a_np[1:3, 0] = -1
    assert_almost_equal(a, a_np)
    a[:] = 0
    assert (a.asnumpy() == 0).all()


def test_reshape_transpose():
    a_np = np.arange(24, dtype=np.float32).reshape(2, 3, 4)
    a = mx.nd.array(a_np)
    assert a.reshape((6, 4)).shape == (6, 4)
    assert a.reshape((-1, 4)).shape == (6, 4)
    assert a.reshape((0, -1)).shape == (2, 12)
    assert a.reshape((-3, 4)).shape == (6, 4)
    assert a.reshape((2, -4, 3, 1, 4)).shape == (2, 3, 1, 4)
    assert_almost_equal(a.T, a_np.T)
    assert_almost_equal(a.transpose((2, 0, 1)), a_np.transpose(2, 0, 1))
    assert_almost_equal(a.swapaxes(0, 2), a_np.swapaxes(0, 2))
    assert a.flatten().shape == (2, 12)
    assert a.expand_dims(1).shape == (2, 1, 3, 4)
    assert mx.nd.ones((2, 1, 3)).squeeze(axis=1).shape == (2, 3)


def test_reductions():
    a_np = np.random.uniform(-1, 1, (3, 4, 5)).astype(np.float32)
    a = mx.nd.array(a_np)
    assert_almost_equal(a.sum(), a_np.sum(), rtol=1e-4)
    assert_almost_equal(a.sum(axis=1), a_np.sum(1), rtol=1e-4)
    assert_almost_equal(a.mean(axis=(0, 2)), a_np.mean((0, 2)), rtol=1e-4)
    assert_almost_equal(a.max(axis=2, keepdims=True), a_np.max(2, keepdims=True))
    assert_almost_equal(a.min(), a_np.min())
    assert_almost_equal(mx.nd.sum(a, axis=0, exclude=True),
                        a_np.sum(axis=(1, 2)), rtol=1e-4)
    assert_almost_equal(a.norm(), np.sqrt((a_np ** 2).sum()), rtol=1e-4)
    assert_almost_equal(a.argmax(axis=1), a_np.argmax(1).astype(np.float32))


def test_dot():
    a_np = np.random.uniform(size=(4, 5)).astype(np.float32)
    b_np = np.random.uniform(size=(5, 6)).astype(np.float32)
    a, b = mx.nd.array(a_np), mx.nd.array(b_np)
    assert_almost_equal(mx.nd.dot(a, b), a_np @ b_np, rtol=1e-4)
    assert_almost_equal(mx.nd.dot(a, a, transpose_b=True), a_np @ a_np.T, rtol=1e-4)
    bd_a = mx.nd.array(np.random.uniform(size=(3, 4, 5)).astype(np.float32))
    bd_b = mx.nd.array(np.random.uniform(size=(3, 5, 2)).astype(np.float32))
    assert_almost_equal(mx.nd.batch_dot(bd_a, bd_b),
                        np.matmul(bd_a.asnumpy(), bd_b.asnumpy()), rtol=1e-4)


def test_concat_split_stack():
    a = mx.nd.ones((2, 3))
    b = mx.nd.ones((2, 3)) * 2
    c = mx.nd.concat(a, b, dim=0)
    assert c.shape == (4, 3)
    c2 = mx.nd.Concat(a, b, dim=1)
    assert c2.shape == (2, 6)
    parts = mx.nd.split(c2, num_outputs=2, axis=1)
    assert len(parts) == 2 and parts[0].shape == (2, 3)
    s = mx.nd.stack(a, b, axis=0)
    assert s.shape == (2, 2, 3)


def test_take_one_hot():
    w = mx.nd.array(np.arange(12, dtype=np.float32).reshape(4, 3))
    idx = mx.nd.array([0, 2], dtype="int32")
    out = mx.nd.take(w, idx)
    assert_almost_equal(out, w.asnumpy()[[0, 2]])
    oh = mx.nd.one_hot(idx, 4)
    assert_almost_equal(oh, np.eye(4, dtype=np.float32)[[0, 2]])
    picked = mx.nd.pick(w, mx.nd.array([1, 0, 2, 1]), axis=1)
    assert_almost_equal(picked, np.array([1, 3, 8, 10], np.float32))


def test_sort_topk():
    a_np = np.random.uniform(size=(3, 8)).astype(np.float32)
    a = mx.nd.array(a_np)
    assert_almost_equal(mx.nd.sort(a), np.sort(a_np))
    assert_almost_equal(mx.nd.argsort(a), np.argsort(a_np).astype(np.float32))
    topk = mx.nd.topk(a, k=3)
    expect = np.argsort(-a_np)[:, :3].astype(np.float32)
    assert_almost_equal(topk, expect)
    vals = mx.nd.topk(a, k=2, ret_typ="value")
    assert_almost_equal(vals, -np.sort(-a_np)[:, :2])


def test_astype_copy_context():
    a = mx.nd.ones((2, 2))
    b = a.astype("float64")
    assert b.dtype == np.float64
    c = a.copy()
    c[:] = 5
    assert (a.asnumpy() == 1).all()
    d = a.as_in_context(mx.cpu(0))
    assert d.context.device_type == "cpu"
    a.wait_to_read()
    mx.nd.waitall()


def test_save_load(tmp_path):
    fname = str(tmp_path / "nd.npz")
    a = mx.nd.ones((2, 3))
    b = mx.nd.arange(0, 4)
    mx.nd.save(fname, [a, b])
    loaded = mx.nd.load(fname)
    assert len(loaded) == 2
    assert_almost_equal(loaded[0], a.asnumpy())
    assert_almost_equal(loaded[1], b.asnumpy())
    mx.nd.save(fname, {"w": a, "b": b})
    loaded = mx.nd.load(fname)
    assert set(loaded.keys()) == {"w", "b"}
    assert_almost_equal(loaded["w"], a.asnumpy())


def test_where_clip():
    cond = mx.nd.array([1, 0, 1])
    x = mx.nd.array([1, 2, 3])
    y = mx.nd.array([-1, -2, -3])
    assert_almost_equal(mx.nd.where(cond, x, y), np.array([1, -2, 3], np.float32))
    assert_almost_equal(x.clip(1.5, 2.5), np.array([1.5, 2, 2.5], np.float32))


def test_unary_math():
    a_np = np.random.uniform(0.5, 2, (3, 4)).astype(np.float32)
    a = mx.nd.array(a_np)
    for op, ref in [("sqrt", np.sqrt), ("exp", np.exp), ("log", np.log),
                    ("square", np.square), ("sin", np.sin), ("cos", np.cos),
                    ("tanh", np.tanh), ("sign", np.sign), ("floor", np.floor),
                    ("ceil", np.ceil), ("log1p", np.log1p)]:
        assert_almost_equal(getattr(mx.nd, op)(a), ref(a_np), rtol=1e-4,
                            names=(op, op + "_np"))
    assert_almost_equal(mx.nd.relu(mx.nd.array([-1, 2])), np.array([0, 2], np.float32))
    assert_almost_equal(mx.nd.sigmoid(mx.nd.zeros((2,))), np.full(2, 0.5, np.float32))


def test_iter_len_scalar():
    a = mx.nd.array([[1, 2], [3, 4], [5, 6]])
    assert len(a) == 3
    rows = list(a)
    assert len(rows) == 3
    assert_almost_equal(rows[1], np.array([3, 4], np.float32))
    s = mx.nd.array([42.0])
    assert s.asscalar() == 42.0
    assert float(s) == 42.0
    assert int(s) == 42
    assert bool(mx.nd.array([1.0]))


def test_sparse_basics():
    dense = np.array([[0, 1, 0], [2, 0, 3], [0, 0, 0]], np.float32)
    csr = mx.nd.sparse.csr_matrix(dense)
    assert csr.stype == "csr"
    assert_almost_equal(csr.todense(), dense)
    assert list(csr.indptr.asnumpy()) == [0, 1, 3, 3]
    rs = mx.nd.sparse.row_sparse_array(dense)
    assert rs.stype == "row_sparse"
    assert list(rs.indices.asnumpy()) == [0, 1]
    back = mx.nd.sparse.cast_storage(rs, "default")
    assert back.stype == "default"
    assert_almost_equal(back, dense)
    kept = rs.retain(mx.nd.array([0], dtype="int64"))
    expect = dense.copy()
    expect[1] = 0
    assert_almost_equal(kept.todense(), expect)
