"""Bounded exponential backoff with jitter (`parallel/retry.py`) — the
transient-failure layer under dist.init, coordinator KV ops, and
KVStore.barrier: max-attempts honored, geometric growth capped at
max_delay, jitter inside its declared bounds."""
import pytest

from mxnet_tpu.parallel import retry


@pytest.fixture
def no_sleep(monkeypatch):
    """Capture backoff delays instead of sleeping."""
    sleeps = []
    monkeypatch.setattr(retry, "_sleep", sleeps.append)
    return sleeps


def test_success_first_try(no_sleep):
    p = retry.RetryPolicy(max_attempts=5)
    assert retry.retry_call(lambda: 7, policy=p) == 7
    assert p.last_attempts == 1
    assert no_sleep == []


def test_max_attempts_honored(no_sleep):
    calls = []

    def boom():
        calls.append(1)
        raise RuntimeError("down")

    p = retry.RetryPolicy(max_attempts=4, base_delay=0.1, jitter=0.0)
    with pytest.raises(retry.RetryError) as ei:
        retry.retry_call(boom, policy=p)
    assert len(calls) == 4
    assert p.last_attempts == 4
    assert ei.value.attempts == 4
    assert isinstance(ei.value.__cause__, RuntimeError)
    assert len(no_sleep) == 3  # no sleep after the final failure


def test_recovers_midway(no_sleep):
    calls = []

    def flaky():
        calls.append(1)
        if len(calls) < 3:
            raise OSError("transient")
        return "ok"

    p = retry.RetryPolicy(max_attempts=5, base_delay=0.1, jitter=0.0)
    assert retry.retry_call(flaky, policy=p) == "ok"
    assert p.last_attempts == 3


def test_backoff_growth_and_cap(no_sleep):
    def boom():
        raise ValueError("x")

    p = retry.RetryPolicy(max_attempts=6, base_delay=0.1, multiplier=2.0,
                          max_delay=0.5, jitter=0.0)
    with pytest.raises(retry.RetryError):
        retry.retry_call(boom, policy=p)
    # geometric 0.1, 0.2, 0.4 then capped at max_delay
    assert no_sleep == pytest.approx([0.1, 0.2, 0.4, 0.5, 0.5])


def test_jitter_bounds():
    p = retry.RetryPolicy(base_delay=1.0, multiplier=2.0, max_delay=64.0,
                          jitter=0.5, seed=123)
    for attempt in range(1, 6):
        base = min(64.0, 1.0 * 2.0 ** (attempt - 1))
        samples = [p.delay_for(attempt) for _ in range(200)]
        assert all(base * 0.5 <= s <= base for s in samples)
        # jitter actually spreads the delays (not a constant)
        assert max(samples) - min(samples) > base * 0.3


def test_jitter_deterministic_with_seed():
    a = retry.RetryPolicy(jitter=0.5, seed=7)
    b = retry.RetryPolicy(jitter=0.5, seed=7)
    assert [a.delay_for(k) for k in range(1, 5)] == \
        [b.delay_for(k) for k in range(1, 5)]


def test_non_retryable_exception_propagates(no_sleep):
    def bad():
        raise KeyError("logic bug")

    p = retry.RetryPolicy(max_attempts=5, retry_on=(OSError,))
    with pytest.raises(KeyError):
        retry.retry_call(bad, policy=p)
    assert no_sleep == []  # never retried


def test_on_retry_hook_sees_each_failure(no_sleep):
    seen = []

    def boom():
        raise RuntimeError("x")

    p = retry.RetryPolicy(max_attempts=3, base_delay=0.1, jitter=0.0)
    with pytest.raises(retry.RetryError):
        retry.retry_call(boom, policy=p,
                         on_retry=lambda a, e, d: seen.append((a, d)))
    assert [a for a, _ in seen] == [1, 2]


def test_policy_from_env(monkeypatch):
    monkeypatch.setenv("MXNET_T_MAX_ATTEMPTS", "7")
    monkeypatch.setenv("MXNET_T_BASE_DELAY", "0.25")
    p = retry.RetryPolicy.from_env("MXNET_T", max_attempts=3,
                                   base_delay=1.0, max_delay=9.0)
    assert p.max_attempts == 7
    assert p.base_delay == 0.25
    assert p.max_delay == 9.0  # default kept where env is unset


def test_timeout_like_predicate(no_sleep):
    class XlaRuntimeError(Exception):  # stand-in for jaxlib's
        pass

    assert retry.timeout_like(TimeoutError("t"))
    assert retry.timeout_like(XlaRuntimeError("DEADLINE_EXCEEDED: barrier"))
    assert retry.timeout_like(XlaRuntimeError("UNAVAILABLE: conn reset"))
    assert not retry.timeout_like(XlaRuntimeError("INVALID_ARGUMENT"))
    assert not retry.timeout_like(RuntimeError("DEADLINE_EXCEEDED"))

    # as a retry_on predicate: coordinator-style RPC timeouts retry,
    # anything else propagates on the first attempt
    calls = []

    def flaky():
        calls.append(1)
        if len(calls) == 1:
            raise XlaRuntimeError("DEADLINE_EXCEEDED: deadline exceeded")
        return "ok"

    p = retry.RetryPolicy(max_attempts=3, base_delay=0.1, jitter=0.0)
    assert retry.retry_call(flaky, policy=p,
                            retry_on=retry.timeout_like) == "ok"
    assert p.last_attempts == 2

    def hard():
        raise XlaRuntimeError("INVALID_ARGUMENT: bad mesh")

    with pytest.raises(XlaRuntimeError):
        retry.retry_call(hard, policy=p, retry_on=retry.timeout_like)
    assert p.last_attempts == 1  # not retried


def test_policy_validation():
    with pytest.raises(ValueError):
        retry.RetryPolicy(max_attempts=0)
    with pytest.raises(ValueError):
        retry.RetryPolicy(jitter=1.5)
