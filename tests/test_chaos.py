"""Fault-injection registry (`mxnet_tpu/chaos.py`): deterministic
arming (Nth-poll triggers, counts), env-spec parsing for launched
workers, and the site hooks production code polls."""
import pytest

from mxnet_tpu import chaos


def test_unarmed_site_is_silent():
    assert chaos.fire("coordinator.timeout") is None
    assert chaos.fired("coordinator.timeout") == 0
    chaos.maybe_timeout("nothing armed")  # no raise


def test_unknown_site_rejected():
    with pytest.raises(ValueError, match="unknown chaos site"):
        chaos.arm("made.up")
    with pytest.raises(ValueError, match="unknown chaos site"):
        chaos.fire("made.up")


def test_deterministic_after_and_times():
    chaos.arm("step.fail", after=2, times=2)
    fires = [chaos.fire("step.fail") is not None for _ in range(6)]
    # polls 1-2 pass, 3-4 fire, 5-6 pass again (times exhausted)
    assert fires == [False, False, True, True, False, False]
    assert chaos.fired("step.fail") == 2


def test_value_payload_carried():
    chaos.arm("heartbeat.delay", value=2.5)
    assert chaos.heartbeat_extra_delay() == 2.5
    assert chaos.heartbeat_extra_delay() == 0.0  # disarmed after one hit


def test_armed_context_manager_disarms():
    with chaos.armed("coordinator.timeout", times=100):
        assert chaos.is_armed("coordinator.timeout")
        with pytest.raises(chaos.ChaosTimeout):
            chaos.maybe_timeout()
    assert not chaos.is_armed("coordinator.timeout")
    chaos.maybe_timeout()  # silent again


def test_env_spec_parsing():
    chaos.arm_from_env("step.fail@1x2, coordinator.timeout, "
                       "heartbeat.delay@0x1=1.5")
    assert chaos.is_armed("step.fail")
    assert chaos.is_armed("coordinator.timeout")
    assert chaos.heartbeat_extra_delay() == 1.5
    assert chaos.fire("step.fail") is None  # after=1: first poll passes
    assert chaos.fire("step.fail") is True
    assert chaos.fire("step.fail") is True
    assert chaos.fire("step.fail") is None  # x2 exhausted
    with pytest.raises(chaos.ChaosTimeout):
        chaos.maybe_timeout()


def test_env_spec_bad_entry_rejected():
    with pytest.raises(ValueError, match="bad MXNET_CHAOS entry"):
        chaos.arm_from_env("step.fail@@5")


def test_clear_single_site():
    chaos.arm("step.fail", times=10)
    chaos.arm("coordinator.timeout", times=10)
    chaos.clear("step.fail")
    assert not chaos.is_armed("step.fail")
    assert chaos.is_armed("coordinator.timeout")


def test_step_fail_raiser_names_step():
    chaos.arm("step.fail")
    with pytest.raises(chaos.ChaosError, match="step 42"):
        chaos.maybe_step_fail(42)


def test_checkpoint_interrupt_raiser():
    chaos.arm("checkpoint.interrupt")
    with pytest.raises(chaos.ChaosInterrupt, match="/tmp/ck"):
        chaos.maybe_interrupt_checkpoint("/tmp/ck")


def test_heartbeat_delay_injection_in_dist_writer():
    """The dist heartbeat thread polls heartbeat.delay each beat; armed
    delay stalls the write (observable: the poll consumes the trigger)."""
    from mxnet_tpu.parallel import dist  # noqa: F401  (site lives there)
    chaos.arm("heartbeat.delay", value=0.0)
    assert chaos.heartbeat_extra_delay() == 0.0
    assert chaos.fired("heartbeat.delay") == 1


def test_injections_counted_in_telemetry_registry():
    """Satellite (PR 2): every injection lands in
    `chaos_injections_total{site=...}` so tests assert EXACT counts from
    the metrics registry instead of scraping warning logs."""
    from mxnet_tpu import telemetry

    def count(site):
        m = telemetry.get_metric("chaos_injections_total", site=site)
        return m.value if m is not None else 0.0

    base_fail = count("step.fail")
    base_to = count("coordinator.timeout")
    chaos.arm("step.fail", after=1, times=3)
    fired = 0
    for _ in range(6):
        fired += chaos.fire("step.fail") is not None
    assert fired == 3
    # exact equality: registry delta == injections delivered == fired()
    assert count("step.fail") - base_fail == 3
    assert count("step.fail") - base_fail == chaos.fired("step.fail")
    # polls that did NOT inject must not count
    assert chaos.fire("step.fail") is None
    assert count("step.fail") - base_fail == 3
    # sites are independent series
    chaos.arm("coordinator.timeout")
    with pytest.raises(chaos.ChaosTimeout):
        chaos.maybe_timeout()
    assert count("coordinator.timeout") - base_to == 1
    assert count("step.fail") - base_fail == 3
