"""Graph-optimization passes: conv-bias->BN elision and 1x1-conv-as-dot.

The fold pass (executor._plan_conv_bias_bn_fold) removes the mathematically
-zero-gradient bias of a conv feeding a BatchNorm (the Gluon zoo's
BottleneckV1 pattern, reference gluon/model_zoo/vision/resnet.py:107,113);
the 1x1 rewrite (ops/nn._conv1x1_as_dot) lowers pointwise convs to
dot_general so their autodiff transposes are matmuls, not lhs-dilated
convolutions. Both must be numerically invisible to users.
"""
import os

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.test_utils import assert_almost_equal


def _bind_conv_bn(x, w, b, gamma, beta, mm, mv, layout="NCHW",
                  stride=(1, 1)):
    """conv(+bias)->BN->sum graph bound with grads (env read at bind)."""
    data = mx.sym.var("data")
    weight = mx.sym.var("weight")
    bias = mx.sym.var("bias")
    axis = 1 if layout == "NCHW" else 3
    conv = mx.sym.Convolution(data, weight, bias, kernel=(1, 1),
                              stride=stride, num_filter=w.shape[0],
                              layout=layout)
    bn = mx.sym.BatchNorm(conv, mx.sym.var("gamma"), mx.sym.var("beta"),
                          mx.sym.var("mm"), mx.sym.var("mv"),
                          fix_gamma=False, axis=axis, momentum=0.9)
    # nonlinear head — sum(bn) alone is constant in w AND b (normalized
    # outputs sum to N*H*W*beta), which would make every grad trivially 0
    out = mx.sym.sum(mx.sym.Activation(bn, act_type="relu"))
    return out.bind(
        mx.cpu(),
        args={"data": mx.nd.array(x), "weight": mx.nd.array(w),
              "bias": mx.nd.array(b), "gamma": mx.nd.array(gamma),
              "beta": mx.nd.array(beta)},
        args_grad={"data": mx.nd.zeros(x.shape),
                   "weight": mx.nd.zeros(w.shape),
                   "bias": mx.nd.zeros(b.shape),
                   "gamma": mx.nd.zeros(gamma.shape),
                   "beta": mx.nd.zeros(beta.shape)},
        aux_states={"mm": mx.nd.array(mm), "mv": mx.nd.array(mv)})


def _run_fold(monkeypatch, disabled, train=True):
    rng = np.random.RandomState(7)
    x = rng.uniform(-1, 1, (4, 3, 6, 6)).astype(np.float32)
    w = rng.uniform(-1, 1, (5, 3, 1, 1)).astype(np.float32)
    b = rng.uniform(-1, 1, (5,)).astype(np.float32)
    gamma = rng.uniform(0.5, 1.5, (5,)).astype(np.float32)
    beta = rng.uniform(-1, 1, (5,)).astype(np.float32)
    mm = rng.uniform(-0.5, 0.5, (5,)).astype(np.float32)
    mv = rng.uniform(0.5, 1.5, (5,)).astype(np.float32)
    if disabled:
        monkeypatch.setenv("MXNET_FOLD_CONV_BIAS_BN", "0")
    else:
        monkeypatch.delenv("MXNET_FOLD_CONV_BIAS_BN", raising=False)
    exe = _bind_conv_bn(x, w, b, gamma, beta, mm, mv)
    if train:
        exe.forward(is_train=True)
        exe.backward()
    else:
        exe.forward(is_train=False)
    return exe


@pytest.mark.parametrize("train", [True, False])
def test_conv_bias_bn_fold_matches_unfolded(monkeypatch, train):
    ref = _run_fold(monkeypatch, disabled=True, train=train)
    opt = _run_fold(monkeypatch, disabled=False, train=train)
    assert_almost_equal(opt.outputs[0], ref.outputs[0].asnumpy(),
                        rtol=1e-5, atol=1e-5)
    # running stats must track the x+b domain exactly like the reference
    for a, r in zip(opt.aux_arrays, ref.aux_arrays):
        assert_almost_equal(a, r.asnumpy(), rtol=1e-5, atol=1e-5)
    if train:
        names = opt._symbol.list_arguments()
        for name, ga, gr in zip(names, opt.grad_arrays, ref.grad_arrays):
            if name == "bias":
                # both are "mathematically zero + rounding": the unfolded
                # graph computes the zero through a full reduce (fp32 fuzz
                # ~1e-4), the folded graph short-circuits it
                assert np.all(np.abs(ga.asnumpy()) < 1e-3)
                assert np.all(np.abs(gr.asnumpy()) < 1e-3)
            else:
                # rounding order differs (stats of x vs x+b): fp32 noise
                assert_almost_equal(ga, gr.asnumpy(), rtol=1e-3, atol=1e-4)


def test_conv_bias_bn_fold_bias_grad_zero(monkeypatch):
    monkeypatch.delenv("MXNET_FOLD_CONV_BIAS_BN", raising=False)
    exe = _run_fold(monkeypatch, disabled=False, train=True)
    names = exe._symbol.list_arguments()
    gbias = exe.grad_arrays[names.index("bias")].asnumpy()
    assert np.all(gbias == 0.0)


def test_conv_bias_bn_fold_skips_shared_conv_output(monkeypatch):
    """Conv output consumed by BOTH a BN and a plain add: fold must not
    fire (the second consumer sees the biased activation)."""
    monkeypatch.delenv("MXNET_FOLD_CONV_BIAS_BN", raising=False)
    rng = np.random.RandomState(3)
    x = rng.uniform(-1, 1, (2, 3, 4, 4)).astype(np.float32)
    w = rng.uniform(-1, 1, (3, 3, 1, 1)).astype(np.float32)
    b = rng.uniform(-1, 1, (3,)).astype(np.float32)
    ones = np.ones((3,), np.float32)
    zeros = np.zeros((3,), np.float32)
    data = mx.sym.var("data")
    conv = mx.sym.Convolution(data, mx.sym.var("weight"), mx.sym.var("bias"),
                              kernel=(1, 1), num_filter=3)
    bn = mx.sym.BatchNorm(conv, mx.sym.var("gamma"), mx.sym.var("beta"),
                          mx.sym.var("mm"), mx.sym.var("mv"),
                          fix_gamma=False)
    out = mx.sym.sum(bn + conv)
    exe = out.bind(mx.cpu(),
                   args={"data": mx.nd.array(x), "weight": mx.nd.array(w),
                         "bias": mx.nd.array(b), "gamma": mx.nd.array(ones),
                         "beta": mx.nd.array(zeros)},
                   args_grad={n: mx.nd.zeros(s) for n, s in
                              [("data", x.shape), ("weight", w.shape),
                               ("bias", b.shape), ("gamma", (3,)),
                               ("beta", (3,))]},
                   aux_states={"mm": mx.nd.array(zeros),
                               "mv": mx.nd.array(ones)})
    exe.forward(is_train=True)
    exe.backward()
    names = exe._symbol.list_arguments()
    gbias = exe.grad_arrays[names.index("bias")].asnumpy()
    # the add branch gives the bias a REAL gradient: sum over N,H,W = 2*4*4
    assert_almost_equal(gbias, np.full((3,), 32.0), rtol=1e-4)


@pytest.mark.parametrize("train", [True, False])
def test_relu_pool_fold_matches_unfolded(monkeypatch, train):
    """relu folded into its sole-consumer maxpool: outputs and grads must
    match the explicit relu->maxpool graph."""
    rng = np.random.RandomState(21)
    x = rng.uniform(-2, 2, (2, 3, 10, 10)).astype(np.float32)
    head = rng.uniform(-1, 1, (2, 3, 5, 5)).astype(np.float32)
    data = mx.sym.var("data")
    net = mx.sym.Activation(data, act_type="relu")
    net = mx.sym.Pooling(net, kernel=(3, 3), stride=(2, 2), pad=(1, 1),
                         pool_type="max")

    def run():
        exe = net.bind(mx.cpu(), args={"data": mx.nd.array(x)},
                       args_grad={"data": mx.nd.zeros(x.shape)})
        exe.forward(is_train=train)
        if train:
            exe.backward(mx.nd.array(head))
        return (exe.outputs[0].asnumpy(),
                exe.grad_arrays[0].asnumpy() if train else None)

    monkeypatch.setenv("MXNET_FOLD_RELU_POOL", "0")
    out_ref, g_ref = run()
    monkeypatch.delenv("MXNET_FOLD_RELU_POOL", raising=False)
    out_opt, g_opt = run()
    assert_almost_equal(out_opt, out_ref, rtol=1e-6, atol=1e-7)
    assert (out_opt >= 0).all()
    if train:
        assert_almost_equal(g_opt, g_ref, rtol=1e-5, atol=1e-6)


def test_relu_pool_fold_skips_shared_relu():
    """relu consumed by maxpool AND another op must not fold."""
    rng = np.random.RandomState(4)
    x = rng.uniform(-2, 2, (2, 3, 8, 8)).astype(np.float32)
    data = mx.sym.var("data")
    act = mx.sym.Activation(data, act_type="relu")
    pool = mx.sym.Pooling(act, kernel=(2, 2), stride=(2, 2), pool_type="max")
    out = mx.sym.Group([pool, mx.sym.sum(act)])
    exe = out.bind(mx.cpu(), args={"data": mx.nd.array(x)})
    exe.forward(is_train=False)
    # the second output must see the REAL relu (nonnegative, elementwise)
    relu_sum = exe.outputs[1].asnumpy()
    assert_almost_equal(relu_sum, np.maximum(x, 0).sum(), rtol=1e-5)


@pytest.mark.parametrize("layout,stride", [
    ("NCHW", (1, 1)), ("NCHW", (2, 2)), ("NHWC", (1, 1)), ("NHWC", (2, 2)),
])
def test_conv1x1_as_dot_matches_conv(monkeypatch, layout, stride):
    rng = np.random.RandomState(11)
    if layout == "NCHW":
        x = rng.uniform(-1, 1, (2, 6, 8, 8)).astype(np.float32)
        w = rng.uniform(-1, 1, (4, 6, 1, 1)).astype(np.float32)
    else:
        x = rng.uniform(-1, 1, (2, 8, 8, 6)).astype(np.float32)
        w = rng.uniform(-1, 1, (4, 1, 1, 6)).astype(np.float32)

    def run():
        return mx.nd.Convolution(mx.nd.array(x), mx.nd.array(w),
                                 kernel=(1, 1), stride=stride, num_filter=4,
                                 no_bias=True, layout=layout).asnumpy()

    monkeypatch.setenv("MXNET_CONV1X1_DOT", "0")
    ref = run()
    monkeypatch.setenv("MXNET_CONV1X1_DOT", "all")
    opt = run()
    assert opt.shape == ref.shape
    assert_almost_equal(opt, ref, rtol=1e-4, atol=1e-5)


@pytest.mark.parametrize("layout", ["NCHW", "NHWC"])
def test_conv1x1_strided_custom_bwd(monkeypatch, layout):
    """Custom VJP for strided 1x1 convs: grads must match the autodiff
    transpose of the plain conv path."""
    from mxnet_tpu.test_utils import check_numeric_gradient
    rng = np.random.RandomState(13)
    if layout == "NCHW":
        x = rng.uniform(-1, 1, (2, 3, 7, 7)).astype(np.float32)
        w = rng.uniform(-1, 1, (4, 3, 1, 1)).astype(np.float32)
    else:
        x = rng.uniform(-1, 1, (2, 7, 7, 3)).astype(np.float32)
        w = rng.uniform(-1, 1, (4, 1, 1, 3)).astype(np.float32)
    conv = mx.sym.Convolution(mx.sym.var("data"), mx.sym.var("weight"),
                              kernel=(1, 1), stride=(2, 2), num_filter=4,
                              no_bias=True, layout=layout)
    out_shape = (2, 4, 4, 4) if layout == "NCHW" else (2, 4, 4, 4)
    head_np = rng.uniform(-1, 1, out_shape).astype(np.float32)

    def run_grads():
        exe = conv.bind(mx.cpu(),
                        args={"data": mx.nd.array(x), "weight": mx.nd.array(w)},
                        args_grad={"data": mx.nd.zeros(x.shape),
                                   "weight": mx.nd.zeros(w.shape)})
        exe.forward(is_train=True)
        exe.backward(mx.nd.array(head_np))
        return (exe.outputs[0].asnumpy(),
                [g.asnumpy() for g in exe.grad_arrays])

    monkeypatch.setenv("MXNET_CONV1X1_BWD", "0")
    out_ref, grads_ref = run_grads()
    monkeypatch.setenv("MXNET_CONV1X1_BWD", "1")
    out_opt, grads_opt = run_grads()
    assert_almost_equal(out_opt, out_ref, rtol=1e-5, atol=1e-6)
    for go, gr in zip(grads_opt, grads_ref):
        assert_almost_equal(go, gr, rtol=1e-4, atol=1e-5)
    check_numeric_gradient(conv, {"data": x, "weight": w},
                           numeric_eps=1e-2, rtol=5e-2, atol=1e-3)


def test_conv1x1_as_dot_gradients(monkeypatch):
    monkeypatch.setenv("MXNET_CONV1X1_DOT", "all")
    from mxnet_tpu.test_utils import check_numeric_gradient
    rng = np.random.RandomState(5)
    x = rng.uniform(-1, 1, (2, 3, 6, 6)).astype(np.float32)
    w = rng.uniform(-1, 1, (4, 3, 1, 1)).astype(np.float32)
    conv = mx.sym.Convolution(mx.sym.var("data"), mx.sym.var("weight"),
                              kernel=(1, 1), stride=(2, 2), num_filter=4,
                              no_bias=True)
    check_numeric_gradient(conv, {"data": x, "weight": w},
                           numeric_eps=1e-2, rtol=5e-2, atol=1e-3)
