"""mxanalyze static-analysis suite: per-rule trigger + suppression
fixtures, baseline round-trip, CLI gate conventions, and the tier-1
assertion that the real tree is clean against the checked-in baseline.

Pure AST analysis — no jax import, no device; everything here runs in
milliseconds.
"""
import json
import os
import subprocess
import sys
import textwrap

import pytest

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from tools.mxanalyze import analyze_paths          # noqa: E402
from tools.mxanalyze.baseline import (             # noqa: E402
    diff_baseline, load_baseline, save_baseline)


def _analyze(tmp_path, source, relpath="mod.py", doc=""):
    """Write one fixture file + env doc under tmp_path, analyze it."""
    path = tmp_path / relpath
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(textwrap.dedent(source))
    env_doc = tmp_path / "env_var.md"
    env_doc.write_text(doc)
    return analyze_paths([str(path)], root=str(tmp_path),
                         env_doc=str(env_doc))


def _rules(findings):
    return sorted({f.rule for f in findings})


# ---------------------------------------------------------------------------
# rule fixtures: each rule must trigger, and its suppression must hold
# ---------------------------------------------------------------------------

class TestJitPurity:
    def test_side_effects_in_jitted_fn(self, tmp_path):
        fs = _analyze(tmp_path, """
            import time
            import jax

            @jax.jit
            def step(x):
                t = time.time()
                print("tracing")
                return x + t
            """)
        msgs = [f.message for f in fs if f.rule == "jit-purity"]
        assert len(msgs) == 2, fs
        assert any("time.time" in m for m in msgs)
        assert any("print" in m for m in msgs)

    def test_wrap_call_and_global(self, tmp_path):
        fs = _analyze(tmp_path, """
            import random
            _hits = 0

            def impl(x):
                global _hits
                _hits += 1
                return x * random.random()

            import jax
            fwd = jax.jit(impl)
            """)
        msgs = [f.message for f in fs if f.rule == "jit-purity"]
        assert any("global" in m for m in msgs)
        assert any("random.random" in m for m in msgs)

    def test_closure_mutation_and_telemetry(self, tmp_path):
        fs = _analyze(tmp_path, """
            import jax
            from mxnet_tpu import telemetry
            _cache = {}

            def impl(x):
                telemetry.counter("steps").inc()
                _cache[1] = x
                return x

            fwd = jax.jit(impl)
            """)
        msgs = [f.message for f in fs if f.rule == "jit-purity"]
        assert any("telemetry" in m for m in msgs)
        assert any("_cache" in m for m in msgs)

    def test_pure_fn_and_suppression(self, tmp_path):
        fs = _analyze(tmp_path, """
            import time
            import jax

            @jax.jit
            def pure(x):
                return x * 2

            @jax.jit
            def blessed(x):
                # mxanalyze: allow(jit-purity): trace-time stamp is the point here
                t = time.time()
                return x + t
            """)
        assert not [f for f in fs if f.rule == "jit-purity"], fs


class TestRetraceHazard:
    def test_dynamic_static_argnums(self, tmp_path):
        fs = _analyze(tmp_path, """
            import jax

            def impl(x, n):
                return x

            nums = (1,)
            fwd = jax.jit(impl, static_argnums=tuple(nums))
            ok = jax.jit(impl, static_argnums=(1,))
            """)
        hits = [f for f in fs if f.rule == "retrace-hazard"]
        assert len(hits) == 1 and "static_argnums" in hits[0].message

    def test_taint_follows_execution_order(self, tmp_path):
        fs = _analyze(tmp_path, """
            import jax

            def impl(x, n):
                return x

            fwd = jax.jit(impl)

            def late_bind_is_clean(x, k):
                r = fwd(x, k)        # k untainted HERE
                k = x.shape[0]       # later rebinding must not leak back
                return r, k

            def rebind_after_call_still_flags(x):
                n = x.shape[0]
                r = fwd(x, n)        # tainted at the call site
                n = 0
                return r, n
            """)
        hits = [f for f in fs if f.rule == "retrace-hazard"]
        # exactly ONE finding: none from late_bind_is_clean (no
        # retroactive taint), one from rebind_after_call_still_flags
        # (the clearing rebind comes after the call)
        assert len(hits) == 1, fs
        assert "traced arg 1" in hits[0].message

    def test_decorator_wrap_site_reported_once(self, tmp_path):
        fs = _analyze(tmp_path, """
            import functools
            import jax

            ns = [1]

            @functools.partial(jax.jit, static_argnums=tuple(ns))
            def f(x, n):
                return x
            """)
        hits = [f for f in fs if f.rule == "retrace-hazard"]
        assert len(hits) == 1, fs   # one defect, ONE finding

    def test_shape_scalar_as_traced_arg(self, tmp_path):
        fs = _analyze(tmp_path, """
            import jax

            def impl(x, n):
                return x

            fwd = jax.jit(impl)

            def use(x):
                n = x.shape[0]
                return fwd(x, n)
            """)
        hits = [f for f in fs if f.rule == "retrace-hazard"]
        assert len(hits) == 1 and "traced arg 1" in hits[0].message

    def test_unhashable_static_value(self, tmp_path):
        fs = _analyze(tmp_path, """
            import jax

            def impl(x, cfg):
                return x

            fwd = jax.jit(impl, static_argnums=(1,))

            def use(x):
                return fwd(x, [1, 2])
            """)
        hits = [f for f in fs if f.rule == "retrace-hazard"]
        assert len(hits) == 1 and "unhashable" in hits[0].message

    def test_serving_unbucketed_shape(self, tmp_path):
        src = """
            from .batching import pad_rows, pick_bucket

            def bad(reqs, arr):
                rows = sum(r.n for r in reqs)
                return pad_rows(arr, rows)

            def good(reqs, arr, buckets):
                rows = sum(r.n for r in reqs)
                bucket = pick_bucket(rows, buckets)
                return pad_rows(arr, bucket)
            """
        fs = _analyze(tmp_path, src,
                      relpath="mxnet_tpu/serving/myengine.py")
        hits = [f for f in fs if f.rule == "retrace-hazard"]
        assert len(hits) == 1, fs
        assert "bucket ladder" in hits[0].message
        # identical code OUTSIDE serving/ is not the engine's contract
        fs2 = _analyze(tmp_path, src, relpath="mxnet_tpu/other.py")
        assert not [f for f in fs2 if f.rule == "retrace-hazard"]


class TestLockDiscipline:
    def test_mixed_guard_writes(self, tmp_path):
        fs = _analyze(tmp_path, """
            import threading
            _lock = threading.Lock()
            _state = {}

            def locked():
                with _lock:
                    _state["x"] = 1

            def unlocked():
                _state["x"] = 2
            """)
        hits = [f for f in fs if f.rule == "lock-discipline"]
        assert len(hits) == 1, fs
        assert "_state" in hits[0].message
        assert "without the lock" in hits[0].message

    def test_init_exempt_and_self_attrs(self, tmp_path):
        fs = _analyze(tmp_path, """
            import threading

            class Box:
                def __init__(self):
                    self._lock = threading.Lock()
                    self.n = 0          # construction: exempt

                def inc(self):
                    with self._lock:
                        self.n += 1

                def racy(self):
                    self.n = 5
            """)
        hits = [f for f in fs if f.rule == "lock-discipline"]
        assert len(hits) == 1 and "Box.n" in hits[0].message

    def test_order_inversion(self, tmp_path):
        fs = _analyze(tmp_path, """
            import threading
            a = threading.RLock()
            b = threading.RLock()

            def one():
                with a:
                    with b:
                        pass

            def two():
                with b:
                    with a:
                        pass
            """)
        hits = [f for f in fs if f.rule == "lock-discipline"]
        assert len(hits) == 1 and "inversion" in hits[0].message

    def test_nonreentrant_self_nesting(self, tmp_path):
        fs = _analyze(tmp_path, """
            import threading
            lk = threading.Lock()

            def f():
                with lk:
                    with lk:
                        pass
            """)
        hits = [f for f in fs if f.rule == "lock-discipline"]
        assert len(hits) == 1 and "self-deadlock" in hits[0].message

    def test_duplicate_stems_do_not_conflate(self, tmp_path):
        """Two modules both named util.py: a lock in one must not make
        same-named globals in the other look guarded (or vice versa)."""
        (tmp_path / "a").mkdir()
        (tmp_path / "b").mkdir()
        (tmp_path / "a" / "util.py").write_text(textwrap.dedent("""
            import threading
            _lock = threading.Lock()
            _cache = {}

            def locked():
                with _lock:
                    _cache["k"] = 1

            def unlocked():
                _cache["k"] = 2
            """))
        (tmp_path / "b" / "util.py").write_text(textwrap.dedent("""
            _cache = {}

            def lockfree():
                _cache["k"] = 3   # this module has NO locks: clean
            """))
        env_doc = tmp_path / "env_var.md"
        env_doc.write_text("")
        fs = analyze_paths([str(tmp_path / "a"), str(tmp_path / "b")],
                           root=str(tmp_path), env_doc=str(env_doc))
        hits = [f for f in fs if f.rule == "lock-discipline"]
        assert len(hits) == 1, fs
        assert hits[0].path == "a/util.py"

    def test_suppression(self, tmp_path):
        fs = _analyze(tmp_path, """
            import threading
            _lock = threading.Lock()
            _state = {}

            def locked():
                with _lock:
                    _state["x"] = 1

            def unlocked():
                # mxanalyze: allow(lock-discipline): single-threaded setup path
                _state["x"] = 2
            """)
        assert not [f for f in fs if f.rule == "lock-discipline"], fs


class TestSwallowedException:
    def test_silent_broad_handler(self, tmp_path):
        fs = _analyze(tmp_path, """
            def f():
                try:
                    risky()
                except Exception:
                    pass
            """)
        hits = [f for f in fs if f.rule == "swallowed-exception"]
        assert len(hits) == 1

    def test_logged_counted_raised_ok(self, tmp_path):
        fs = _analyze(tmp_path, """
            import logging
            from mxnet_tpu import telemetry

            def a():
                try:
                    risky()
                except Exception as exc:
                    logging.debug("boom %s", exc)

            def b():
                try:
                    risky()
                except Exception as exc:
                    telemetry.swallowed("test.site", exc)

            def c():
                try:
                    risky()
                except Exception:
                    raise RuntimeError("wrapped")

            def d():
                try:
                    risky()
                except ValueError:   # narrow: out of scope
                    pass
            """)
        assert not [f for f in fs if f.rule == "swallowed-exception"], fs

    def test_suppression_with_reason(self, tmp_path):
        fs = _analyze(tmp_path, """
            def f():
                try:
                    risky()
                # mxanalyze: allow(swallowed-exception): exit path, nothing can observe it
                except Exception:
                    pass
            """)
        assert not [f for f in fs if f.rule == "swallowed-exception"], fs

    def test_reasonless_suppression_rejected(self, tmp_path):
        fs = _analyze(tmp_path, """
            def f():
                try:
                    risky()
                # mxanalyze: allow(swallowed-exception)
                except Exception:
                    pass
            """)
        assert [f for f in fs if f.rule == "swallowed-exception"]
        assert [f for f in fs if f.rule == "bad-suppression"]


class TestEnvVarDrift:
    DOC = "| `MXNET_DOCUMENTED_KNOB` | `0` | A knob. |\n" \
          "| `MXNET_FAMILY_*` | - | Wildcard family. |\n"

    def test_undocumented_read_flagged(self, tmp_path):
        fs = _analyze(tmp_path, """
            import os
            A = os.environ.get("MXNET_DOCUMENTED_KNOB", "0")
            B = os.environ.get("MXNET_MYSTERY_KNOB", "0")
            C = os.getenv("MXNET_FAMILY_DEPTH")
            D = os.environ["MXNET_MYSTERY_SUBSCRIPT"]
            """, doc=self.DOC)
        hits = sorted(f.message.split()[2] for f in fs
                      if f.rule == "env-var-drift")
        assert hits == ["MXNET_MYSTERY_KNOB", "MXNET_MYSTERY_SUBSCRIPT"]

    def test_from_env_prefix_expansion(self, tmp_path):
        fs = _analyze(tmp_path, """
            from mxnet_tpu.parallel.retry import RetryPolicy
            p = RetryPolicy.from_env("MXNET_NEWLOOP", max_attempts=2)
            """, doc=self.DOC)
        names = sorted(f.message.split()[2] for f in fs
                       if f.rule == "env-var-drift")
        assert names == ["MXNET_NEWLOOP_BASE_DELAY",
                         "MXNET_NEWLOOP_MAX_ATTEMPTS",
                         "MXNET_NEWLOOP_MAX_DELAY"]

    def test_docstring_mention_is_not_a_read(self, tmp_path):
        fs = _analyze(tmp_path, '''
            """Talks about MXNET_IMAGINARY_KNOB but never reads it."""
            X = 1
            ''', doc=self.DOC)
        assert not [f for f in fs if f.rule == "env-var-drift"]


# ---------------------------------------------------------------------------
# baseline round-trip
# ---------------------------------------------------------------------------

class TestBaseline:
    SRC = """
        def f():
            try:
                risky()
            except Exception:
                pass
        """

    def test_roundtrip_then_new_then_stale(self, tmp_path):
        fs = _analyze(tmp_path, self.SRC)
        assert fs
        bl_path = tmp_path / "baseline.json"
        save_baseline(str(bl_path), fs)
        bl = load_baseline(str(bl_path))

        new, baselined, stale = diff_baseline(fs, bl)
        assert not new and not stale and len(baselined) == len(fs)

        # a SECOND identical handler in the same file exceeds the count
        fs2 = _analyze(tmp_path, self.SRC + """
        def g():
            try:
                risky()
            except Exception:
                pass
        """)
        new, baselined, stale = diff_baseline(fs2, bl)
        assert len(new) == 1 and not stale

        # fixing everything leaves the entry stale
        new, baselined, stale = diff_baseline([], bl)
        assert not new and sum(stale.values()) == len(fs)

    def test_fingerprint_is_line_independent(self, tmp_path):
        fs = _analyze(tmp_path, self.SRC)
        shifted = _analyze(tmp_path, "\n\n# padding\n\n"
                           + textwrap.dedent(self.SRC))
        assert [f.fingerprint() for f in fs] == \
            [f.fingerprint() for f in shifted]


# ---------------------------------------------------------------------------
# CLI: exit codes + BENCH-style gate line (bench_gate conventions)
# ---------------------------------------------------------------------------

def _run_cli(args, cwd=REPO):
    return subprocess.run(
        [sys.executable, "-m", "tools.mxanalyze"] + args,
        capture_output=True, text=True, cwd=cwd,
        env=dict(os.environ, PYTHONPATH=REPO))


class TestCLI:
    def _tmp_repo(self, tmp_path):
        bad = tmp_path / "bad.py"
        bad.write_text(textwrap.dedent("""
            def f():
                try:
                    risky()
                except Exception:
                    pass
            """))
        doc = tmp_path / "env.md"
        doc.write_text("")
        bl = tmp_path / "bl.json"
        return bad, doc, bl

    def test_violation_fails_then_baseline_passes(self, tmp_path):
        bad, doc, bl = self._tmp_repo(tmp_path)
        common = [str(bad), "--baseline", str(bl), "--env-doc", str(doc)]
        r = _run_cli(["--strict"] + common)
        assert r.returncode == 1, r.stdout + r.stderr
        gate = json.loads(r.stdout.strip().splitlines()[-1])
        assert gate["metric"] == "mxanalyze_gate"
        assert gate["status"] == "fail" and gate["new"] == 1

        r = _run_cli(["--update-baseline"] + common)
        assert r.returncode == 0

        r = _run_cli(["--strict"] + common)
        assert r.returncode == 0, r.stdout + r.stderr
        gate = json.loads(r.stdout.strip().splitlines()[-1])
        assert gate["status"] == "pass" and gate["baselined"] == 1

    def test_scoped_update_preserves_out_of_scope_entries(self, tmp_path):
        """--update-baseline over a subdir must not drop recorded debt
        for files outside that subdir."""
        sub_a, sub_b = tmp_path / "a", tmp_path / "b"
        sub_a.mkdir(), sub_b.mkdir()
        src = textwrap.dedent("""
            def f():
                try:
                    risky()
                except Exception:
                    pass
            """)
        (sub_a / "m.py").write_text(src)
        (sub_b / "m.py").write_text(src)
        doc = tmp_path / "env.md"
        doc.write_text("")
        bl = tmp_path / "bl.json"
        common = ["--baseline", str(bl), "--env-doc", str(doc)]
        r = _run_cli(["--update-baseline", str(sub_a), str(sub_b)]
                     + common)
        assert r.returncode == 0
        full = load_baseline(str(bl))
        assert len(full) == 2
        # a path-scoped --strict run must not call the unanalyzed b
        # entry stale
        r = _run_cli(["--strict", str(sub_a)] + common)
        assert r.returncode == 0, r.stdout + r.stderr
        # fix b's finding, scoped-update only b: a's entry must survive
        (sub_b / "m.py").write_text("def f():\n    return 1\n")
        r = _run_cli(["--update-baseline", str(sub_b)] + common)
        assert r.returncode == 0, r.stdout + r.stderr
        after = load_baseline(str(bl))
        assert len(after) == 1 and list(after)[0][1].endswith("a/m.py"), \
            dict(after)
        # and the full-tree gate still passes against the merged file
        r = _run_cli(["--strict", str(sub_a), str(sub_b)] + common)
        assert r.returncode == 0, r.stdout + r.stderr

    def test_corrupt_baseline_is_usage_error_not_gate_result(self,
                                                             tmp_path):
        bad, doc, bl = self._tmp_repo(tmp_path)
        bl.write_text("<<<<<<< conflict markers\n{not json")
        r = _run_cli([str(bad), "--baseline", str(bl), "--env-doc",
                      str(doc)])
        assert r.returncode == 2, (r.returncode, r.stdout, r.stderr)
        assert "not valid JSON" in r.stderr

    def test_nonexistent_path_is_an_error_not_a_pass(self, tmp_path):
        doc = tmp_path / "env.md"
        doc.write_text("")
        r = _run_cli([str(tmp_path / "no_such_dir"), "--env-doc",
                      str(doc)])
        assert r.returncode == 2, (r.returncode, r.stdout, r.stderr)
        assert "does not exist" in r.stderr

    def test_strict_fails_on_stale_entry(self, tmp_path):
        bad, doc, bl = self._tmp_repo(tmp_path)
        common = [str(bad), "--baseline", str(bl), "--env-doc", str(doc)]
        _run_cli(["--update-baseline"] + common)
        bad.write_text("def f():\n    return 1\n")   # finding fixed
        r = _run_cli(common)               # lenient: warn only
        assert r.returncode == 0
        r = _run_cli(["--strict"] + common)
        assert r.returncode == 1
        gate = json.loads(r.stdout.strip().splitlines()[-1])
        assert gate["stale"] == 1

    def test_one_violation_of_each_rule_fails(self, tmp_path):
        """The acceptance drill: each of the five rules, inserted fresh,
        flips the gate to non-zero on its own."""
        doc = tmp_path / "env.md"
        doc.write_text("")
        bl = tmp_path / "bl.json"   # absent: empty baseline
        snippets = {
            "jit-purity": """
                import time, jax
                @jax.jit
                def f(x):
                    return x + time.time()
                """,
            "retrace-hazard": """
                import jax
                def impl(x):
                    return x
                nums = [0]
                f = jax.jit(impl, static_argnums=tuple(nums))
                """,
            "lock-discipline": """
                import threading
                _lock = threading.Lock()
                _s = {}
                def a():
                    with _lock:
                        _s["k"] = 1
                def b():
                    _s["k"] = 2
                """,
            "swallowed-exception": """
                def f():
                    try:
                        risky()
                    except Exception:
                        pass
                """,
            "env-var-drift": """
                import os
                X = os.environ.get("MXNET_UNDOCUMENTED", "0")
                """,
        }
        for rule, src in snippets.items():
            p = tmp_path / ("%s.py" % rule.replace("-", "_"))
            p.write_text(textwrap.dedent(src))
            r = _run_cli(["--strict", str(p), "--baseline", str(bl),
                          "--env-doc", str(doc)])
            assert r.returncode == 1, (rule, r.stdout, r.stderr)
            assert rule in r.stdout, (rule, r.stdout)
            p.unlink()


# ---------------------------------------------------------------------------
# tier-1: the real tree is clean against the checked-in baseline
# ---------------------------------------------------------------------------

class TestRepoGate:
    def test_mxnet_tpu_clean_against_baseline(self):
        findings = analyze_paths(["mxnet_tpu"], root=REPO)
        bl = load_baseline(os.path.join(REPO, "tools", "mxanalyze",
                                        "baseline.json"))
        new, baselined, stale = diff_baseline(findings, bl)
        assert not new, "new findings:\n%s" % "\n".join(
            f.render() for f in new)
        assert not stale, "stale baseline entries (fixed findings — " \
            "run --update-baseline): %r" % stale

    def test_env_var_drift_is_zero_with_no_baseline_entries(self):
        findings = analyze_paths(["mxnet_tpu"], root=REPO)
        drift = [f for f in findings if f.rule == "env-var-drift"]
        assert not drift, "\n".join(f.render() for f in drift)
        bl = load_baseline(os.path.join(REPO, "tools", "mxanalyze",
                                        "baseline.json"))
        assert not [fp for fp in bl if fp[0] == "env-var-drift"]

    def test_repo_gate_cli(self):
        r = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools", "repo_gate.py")],
            capture_output=True, text=True, cwd=REPO,
            env=dict(os.environ, PYTHONPATH=REPO))
        assert r.returncode == 0, r.stdout + r.stderr
        gate = json.loads(r.stdout.strip().splitlines()[-1])
        assert gate["metric"] == "mxanalyze_gate"
        assert gate["status"] == "pass"

    def test_known_rules_registry(self):
        from tools.mxanalyze import RULES
        for rule in ("jit-purity", "retrace-hazard", "lock-discipline",
                     "swallowed-exception", "env-var-drift"):
            assert rule in RULES
