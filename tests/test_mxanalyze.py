"""mxanalyze static-analysis suite: per-rule trigger + suppression
fixtures, baseline round-trip, CLI gate conventions, and the tier-1
assertion that the real tree is clean against the checked-in baseline.

Pure AST analysis — no jax import, no device; everything here runs in
milliseconds.
"""
import json
import os
import subprocess
import sys
import textwrap

import pytest

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from tools.mxanalyze import analyze_paths          # noqa: E402
from tools.mxanalyze.baseline import (             # noqa: E402
    diff_baseline, load_baseline, save_baseline)


def _analyze(tmp_path, source, relpath="mod.py", doc=""):
    """Write one fixture file + env doc under tmp_path, analyze it."""
    path = tmp_path / relpath
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(textwrap.dedent(source))
    env_doc = tmp_path / "env_var.md"
    env_doc.write_text(doc)
    return analyze_paths([str(path)], root=str(tmp_path),
                         env_doc=str(env_doc))


def _rules(findings):
    return sorted({f.rule for f in findings})


# ---------------------------------------------------------------------------
# rule fixtures: each rule must trigger, and its suppression must hold
# ---------------------------------------------------------------------------

class TestJitPurity:
    def test_side_effects_in_jitted_fn(self, tmp_path):
        fs = _analyze(tmp_path, """
            import time
            import jax

            @jax.jit
            def step(x):
                t = time.time()
                print("tracing")
                return x + t
            """)
        msgs = [f.message for f in fs if f.rule == "jit-purity"]
        assert len(msgs) == 2, fs
        assert any("time.time" in m for m in msgs)
        assert any("print" in m for m in msgs)

    def test_wrap_call_and_global(self, tmp_path):
        fs = _analyze(tmp_path, """
            import random
            _hits = 0

            def impl(x):
                global _hits
                _hits += 1
                return x * random.random()

            import jax
            fwd = jax.jit(impl)
            """)
        msgs = [f.message for f in fs if f.rule == "jit-purity"]
        assert any("global" in m for m in msgs)
        assert any("random.random" in m for m in msgs)

    def test_closure_mutation_and_telemetry(self, tmp_path):
        fs = _analyze(tmp_path, """
            import jax
            from mxnet_tpu import telemetry
            _cache = {}

            def impl(x):
                telemetry.counter("steps").inc()
                _cache[1] = x
                return x

            fwd = jax.jit(impl)
            """)
        msgs = [f.message for f in fs if f.rule == "jit-purity"]
        assert any("telemetry" in m for m in msgs)
        assert any("_cache" in m for m in msgs)

    def test_pure_fn_and_suppression(self, tmp_path):
        fs = _analyze(tmp_path, """
            import time
            import jax

            @jax.jit
            def pure(x):
                return x * 2

            @jax.jit
            def blessed(x):
                # mxanalyze: allow(jit-purity): trace-time stamp is the point here
                t = time.time()
                return x + t
            """)
        assert not [f for f in fs if f.rule == "jit-purity"], fs


class TestRetraceHazard:
    def test_dynamic_static_argnums(self, tmp_path):
        fs = _analyze(tmp_path, """
            import jax

            def impl(x, n):
                return x

            nums = (1,)
            fwd = jax.jit(impl, static_argnums=tuple(nums))
            ok = jax.jit(impl, static_argnums=(1,))
            """)
        hits = [f for f in fs if f.rule == "retrace-hazard"]
        assert len(hits) == 1 and "static_argnums" in hits[0].message

    def test_taint_follows_execution_order(self, tmp_path):
        fs = _analyze(tmp_path, """
            import jax

            def impl(x, n):
                return x

            fwd = jax.jit(impl)

            def late_bind_is_clean(x, k):
                r = fwd(x, k)        # k untainted HERE
                k = x.shape[0]       # later rebinding must not leak back
                return r, k

            def rebind_after_call_still_flags(x):
                n = x.shape[0]
                r = fwd(x, n)        # tainted at the call site
                n = 0
                return r, n
            """)
        hits = [f for f in fs if f.rule == "retrace-hazard"]
        # exactly ONE finding: none from late_bind_is_clean (no
        # retroactive taint), one from rebind_after_call_still_flags
        # (the clearing rebind comes after the call)
        assert len(hits) == 1, fs
        assert "traced arg 1" in hits[0].message

    def test_decorator_wrap_site_reported_once(self, tmp_path):
        fs = _analyze(tmp_path, """
            import functools
            import jax

            ns = [1]

            @functools.partial(jax.jit, static_argnums=tuple(ns))
            def f(x, n):
                return x
            """)
        hits = [f for f in fs if f.rule == "retrace-hazard"]
        assert len(hits) == 1, fs   # one defect, ONE finding

    def test_shape_scalar_as_traced_arg(self, tmp_path):
        fs = _analyze(tmp_path, """
            import jax

            def impl(x, n):
                return x

            fwd = jax.jit(impl)

            def use(x):
                n = x.shape[0]
                return fwd(x, n)
            """)
        hits = [f for f in fs if f.rule == "retrace-hazard"]
        assert len(hits) == 1 and "traced arg 1" in hits[0].message

    def test_unhashable_static_value(self, tmp_path):
        fs = _analyze(tmp_path, """
            import jax

            def impl(x, cfg):
                return x

            fwd = jax.jit(impl, static_argnums=(1,))

            def use(x):
                return fwd(x, [1, 2])
            """)
        hits = [f for f in fs if f.rule == "retrace-hazard"]
        assert len(hits) == 1 and "unhashable" in hits[0].message

    def test_serving_unbucketed_shape(self, tmp_path):
        src = """
            from .batching import pad_rows, pick_bucket

            def bad(reqs, arr):
                rows = sum(r.n for r in reqs)
                return pad_rows(arr, rows)

            def good(reqs, arr, buckets):
                rows = sum(r.n for r in reqs)
                bucket = pick_bucket(rows, buckets)
                return pad_rows(arr, bucket)
            """
        fs = _analyze(tmp_path, src,
                      relpath="mxnet_tpu/serving/myengine.py")
        hits = [f for f in fs if f.rule == "retrace-hazard"]
        assert len(hits) == 1, fs
        assert "bucket ladder" in hits[0].message
        # identical code OUTSIDE serving/ is not the engine's contract
        fs2 = _analyze(tmp_path, src, relpath="mxnet_tpu/other.py")
        assert not [f for f in fs2 if f.rule == "retrace-hazard"]


class TestLockDiscipline:
    def test_mixed_guard_writes(self, tmp_path):
        fs = _analyze(tmp_path, """
            import threading
            _lock = threading.Lock()
            _state = {}

            def locked():
                with _lock:
                    _state["x"] = 1

            def unlocked():
                _state["x"] = 2
            """)
        hits = [f for f in fs if f.rule == "lock-discipline"]
        assert len(hits) == 1, fs
        assert "_state" in hits[0].message
        assert "without the lock" in hits[0].message

    def test_init_exempt_and_self_attrs(self, tmp_path):
        fs = _analyze(tmp_path, """
            import threading

            class Box:
                def __init__(self):
                    self._lock = threading.Lock()
                    self.n = 0          # construction: exempt

                def inc(self):
                    with self._lock:
                        self.n += 1

                def racy(self):
                    self.n = 5
            """)
        hits = [f for f in fs if f.rule == "lock-discipline"]
        assert len(hits) == 1 and "Box.n" in hits[0].message

    def test_order_inversion(self, tmp_path):
        fs = _analyze(tmp_path, """
            import threading
            a = threading.RLock()
            b = threading.RLock()

            def one():
                with a:
                    with b:
                        pass

            def two():
                with b:
                    with a:
                        pass
            """)
        hits = [f for f in fs if f.rule == "lock-discipline"]
        assert len(hits) == 1 and "inversion" in hits[0].message

    def test_nonreentrant_self_nesting(self, tmp_path):
        fs = _analyze(tmp_path, """
            import threading
            lk = threading.Lock()

            def f():
                with lk:
                    with lk:
                        pass
            """)
        hits = [f for f in fs if f.rule == "lock-discipline"]
        assert len(hits) == 1 and "self-deadlock" in hits[0].message

    def test_duplicate_stems_do_not_conflate(self, tmp_path):
        """Two modules both named util.py: a lock in one must not make
        same-named globals in the other look guarded (or vice versa)."""
        (tmp_path / "a").mkdir()
        (tmp_path / "b").mkdir()
        (tmp_path / "a" / "util.py").write_text(textwrap.dedent("""
            import threading
            _lock = threading.Lock()
            _cache = {}

            def locked():
                with _lock:
                    _cache["k"] = 1

            def unlocked():
                _cache["k"] = 2
            """))
        (tmp_path / "b" / "util.py").write_text(textwrap.dedent("""
            _cache = {}

            def lockfree():
                _cache["k"] = 3   # this module has NO locks: clean
            """))
        env_doc = tmp_path / "env_var.md"
        env_doc.write_text("")
        fs = analyze_paths([str(tmp_path / "a"), str(tmp_path / "b")],
                           root=str(tmp_path), env_doc=str(env_doc))
        hits = [f for f in fs if f.rule == "lock-discipline"]
        assert len(hits) == 1, fs
        assert hits[0].path == "a/util.py"

    def test_suppression(self, tmp_path):
        fs = _analyze(tmp_path, """
            import threading
            _lock = threading.Lock()
            _state = {}

            def locked():
                with _lock:
                    _state["x"] = 1

            def unlocked():
                # mxanalyze: allow(lock-discipline): single-threaded setup path
                _state["x"] = 2
            """)
        assert not [f for f in fs if f.rule == "lock-discipline"], fs


class TestSwallowedException:
    def test_silent_broad_handler(self, tmp_path):
        fs = _analyze(tmp_path, """
            def f():
                try:
                    risky()
                except Exception:
                    pass
            """)
        hits = [f for f in fs if f.rule == "swallowed-exception"]
        assert len(hits) == 1

    def test_logged_counted_raised_ok(self, tmp_path):
        fs = _analyze(tmp_path, """
            import logging
            from mxnet_tpu import telemetry

            def a():
                try:
                    risky()
                except Exception as exc:
                    logging.debug("boom %s", exc)

            def b():
                try:
                    risky()
                except Exception as exc:
                    telemetry.swallowed("test.site", exc)

            def c():
                try:
                    risky()
                except Exception:
                    raise RuntimeError("wrapped")

            def d():
                try:
                    risky()
                except ValueError:   # narrow: out of scope
                    pass
            """)
        assert not [f for f in fs if f.rule == "swallowed-exception"], fs

    def test_suppression_with_reason(self, tmp_path):
        fs = _analyze(tmp_path, """
            def f():
                try:
                    risky()
                # mxanalyze: allow(swallowed-exception): exit path, nothing can observe it
                except Exception:
                    pass
            """)
        assert not [f for f in fs if f.rule == "swallowed-exception"], fs

    def test_reasonless_suppression_rejected(self, tmp_path):
        fs = _analyze(tmp_path, """
            def f():
                try:
                    risky()
                # mxanalyze: allow(swallowed-exception)
                except Exception:
                    pass
            """)
        assert [f for f in fs if f.rule == "swallowed-exception"]
        assert [f for f in fs if f.rule == "bad-suppression"]


class TestEnvVarDrift:
    DOC = "| `MXNET_DOCUMENTED_KNOB` | `0` | A knob. |\n" \
          "| `MXNET_FAMILY_*` | - | Wildcard family. |\n"

    def test_undocumented_read_flagged(self, tmp_path):
        fs = _analyze(tmp_path, """
            import os
            A = os.environ.get("MXNET_DOCUMENTED_KNOB", "0")
            B = os.environ.get("MXNET_MYSTERY_KNOB", "0")
            C = os.getenv("MXNET_FAMILY_DEPTH")
            D = os.environ["MXNET_MYSTERY_SUBSCRIPT"]
            """, doc=self.DOC)
        hits = sorted(f.message.split()[2] for f in fs
                      if f.rule == "env-var-drift")
        assert hits == ["MXNET_MYSTERY_KNOB", "MXNET_MYSTERY_SUBSCRIPT"]

    def test_from_env_prefix_expansion(self, tmp_path):
        fs = _analyze(tmp_path, """
            from mxnet_tpu.parallel.retry import RetryPolicy
            p = RetryPolicy.from_env("MXNET_NEWLOOP", max_attempts=2)
            """, doc=self.DOC)
        names = sorted(f.message.split()[2] for f in fs
                       if f.rule == "env-var-drift")
        assert names == ["MXNET_NEWLOOP_BASE_DELAY",
                         "MXNET_NEWLOOP_MAX_ATTEMPTS",
                         "MXNET_NEWLOOP_MAX_DELAY"]

    def test_docstring_mention_is_not_a_read(self, tmp_path):
        fs = _analyze(tmp_path, '''
            """Talks about MXNET_IMAGINARY_KNOB but never reads it."""
            X = 1
            ''', doc=self.DOC)
        assert not [f for f in fs if f.rule == "env-var-drift"]


class TestHostSyncHazard:
    def test_asnumpy_in_hot_function_flags(self, tmp_path):
        fs = _analyze(tmp_path, """
            def predict(self, eval_data):
                for batch in eval_data:
                    out = self.forward(batch)
                    yield out.asnumpy()
            """, relpath="mxnet_tpu/module/mod.py")
        hits = [f for f in fs if f.rule == "host-sync-hazard"]
        assert len(hits) == 1 and ".asnumpy()" in hits[0].message

    def test_taint_flow_device_vs_host_values(self, tmp_path):
        """float() flags only when taint says the operand came off the
        device — and only for values tainted BEFORE the sink runs."""
        fs = _analyze(tmp_path, """
            import jax

            def impl(x):
                return x

            fwd = jax.jit(impl)

            def _step(self, batch, cfg):
                loss = fwd(batch)
                lr = float(cfg["lr"])     # host value: clean
                bad = float(loss)         # device value: flags
                loss = cfg["lr"]
                ok = float(loss)          # rebound to host value: clean
                return bad, lr, ok
            """, relpath="mxnet_tpu/module/mod.py")
        hits = [f for f in fs if f.rule == "host-sync-hazard"]
        assert len(hits) == 1, fs
        assert "float()" in hits[0].message

    def test_branch_on_device_value_flags(self, tmp_path):
        fs = _analyze(tmp_path, """
            import jax

            def impl(x):
                return x

            fwd = jax.jit(impl)

            def _step(self, batch):
                loss = fwd(batch)
                if loss > 10.0:
                    raise RuntimeError("diverged")
            """, relpath="mxnet_tpu/module/mod.py")
        hits = [f for f in fs if f.rule == "host-sync-hazard"]
        assert len(hits) == 1 and "branch" in hits[0].message

    def test_block_until_ready_needs_sync_sampling(self, tmp_path):
        fs = _analyze(tmp_path, """
            import jax
            from mxnet_tpu import stepprof

            def _step(self, out, out2):
                jax.block_until_ready(out)          # unsampled: flags
                if stepprof.should_sync():
                    jax.block_until_ready(out2)     # sampled: clean
            """, relpath="mxnet_tpu/module/mod.py")
        hits = [f for f in fs if f.rule == "host-sync-hazard"]
        assert len(hits) == 1, fs
        assert "block_until_ready" in hits[0].message

    def test_cold_functions_and_cold_modules_out_of_scope(self, tmp_path):
        src = """
            def helper(x):
                return x.asnumpy()
            """
        # a non-hot function in a hot module: out of scope
        fs = _analyze(tmp_path, src, relpath="mxnet_tpu/module/mod.py")
        assert not [f for f in fs if f.rule == "host-sync-hazard"]
        # a hot-named function in a cold module: out of scope
        fs = _analyze(tmp_path, """
            def update(self, labels, preds):
                return preds.asnumpy()
            """, relpath="mxnet_tpu/metric.py")
        assert not [f for f in fs if f.rule == "host-sync-hazard"]

    def test_suppression(self, tmp_path):
        fs = _analyze(tmp_path, """
            def predict(self, out):
                # mxanalyze: allow(host-sync-hazard): API returns numpy
                return out.asnumpy()
            """, relpath="mxnet_tpu/module/mod.py")
        assert not [f for f in fs if f.rule == "host-sync-hazard"], fs

    def test_flips_gate_against_empty_baseline(self, tmp_path):
        fs = _analyze(tmp_path, """
            def _step(self, out):
                return out.asnumpy()
            """, relpath="mxnet_tpu/module/mod.py")
        new, _, _ = diff_baseline(fs, {})
        assert [f for f in new if f.rule == "host-sync-hazard"]


class TestDispatchAmplification:
    def test_param_loop_inside_traced_fn(self, tmp_path):
        fs = _analyze(tmp_path, """
            import jax

            def step(grad_args, live_names):
                outs = []
                for k, name in enumerate(live_names):
                    outs.append(apply_one(grad_args[name]))
                return outs

            fn = jax.jit(step)
            """, relpath="mxnet_tpu/module/mod.py")
        hits = [f for f in fs if f.rule == "dispatch-amplification"]
        assert len(hits) == 1 and "unrolls" in hits[0].message

    def test_host_per_param_updater_loop(self, tmp_path):
        fs = _analyze(tmp_path, """
            def update(self):
                for i, param in enumerate(self._params):
                    self._updater(i, param.grad, param.data)
            """, relpath="mxnet_tpu/gluon/mytrainer.py")
        hits = [f for f in fs if f.rule == "dispatch-amplification"]
        assert len(hits) == 1 and "per-param" in hits[0].message

    def test_non_param_loops_clean(self, tmp_path):
        fs = _analyze(tmp_path, """
            import jax

            def step(xs, rows):
                total = 0
                for r in rows:          # not a param collection
                    total = total + r
                return total

            fn = jax.jit(step)

            def host_loop(batches):
                for b in batches:       # no updater call
                    consume(b)
            """, relpath="mxnet_tpu/module/mod.py")
        assert not [f for f in fs
                    if f.rule == "dispatch-amplification"], fs

    def test_suppression_and_baseline_roundtrip(self, tmp_path):
        fs = _analyze(tmp_path, """
            def update(self):
                for i, param in enumerate(self._params):
                    # mxanalyze: allow(dispatch-amplification): fallback path
                    self._updater(i, param.grad, param.data)
            """, relpath="mxnet_tpu/gluon/mytrainer.py")
        assert not [f for f in fs
                    if f.rule == "dispatch-amplification"], fs
        # unsuppressed finding round-trips through the baseline
        fs = _analyze(tmp_path, """
            def update(self):
                for i, param in enumerate(self._params):
                    self._updater(i, param.grad, param.data)
            """, relpath="mxnet_tpu/gluon/mytrainer.py")
        bl_path = tmp_path / "bl.json"
        save_baseline(str(bl_path), fs)
        new, baselined, stale = diff_baseline(
            fs, load_baseline(str(bl_path)))
        assert not new and not stale and baselined


class TestDonationHazard:
    def test_unrouted_donation_flags(self, tmp_path):
        fs = _analyze(tmp_path, """
            import jax

            def step(params, grads):
                return params

            fn = jax.jit(step, donate_argnums=(0,))
            """, relpath="mxnet_tpu/mymod.py")
        hits = [f for f in fs if f.rule == "donation-hazard"]
        assert len(hits) == 1
        assert "donate_argnums_for" in hits[0].message

    def test_routed_and_empty_are_clean(self, tmp_path):
        fs = _analyze(tmp_path, """
            import jax
            from mxnet_tpu.compiled import donate_argnums_for

            def step(params, grads):
                return params

            def build(ctx, donate_params):
                donate = donate_argnums_for(ctx, (0,)) \\
                    if donate_params else ()
                a = jax.jit(step, donate_argnums=donate)
                b = jax.jit(step, donate_argnums=())
                c = jax.jit(step,
                            donate_argnums=donate_argnums_for(ctx, (0,)))
                return a, b, c
            """, relpath="mxnet_tpu/mymod.py")
        assert not [f for f in fs if f.rule == "donation-hazard"], fs

    def test_use_after_donation_flags(self, tmp_path):
        fs = _analyze(tmp_path, """
            import jax
            from mxnet_tpu.compiled import donate_argnums_for

            def step(params, state):
                return params, state

            fn = jax.jit(step,
                         donate_argnums=donate_argnums_for(None, (1,)))

            def train(params, state):
                new_p, new_s = fn(params, state)
                note_bytes(state)        # old donated buffer: flags
                return new_p, new_s
            """, relpath="mxnet_tpu/mymod.py")
        hits = [f for f in fs if f.rule == "donation-hazard"]
        assert len(hits) == 1, fs
        assert "use after donation" in hits[0].message
        assert "'state'" in hits[0].message

    def test_read_before_call_and_rebinding_clean(self, tmp_path):
        fs = _analyze(tmp_path, """
            import jax
            from mxnet_tpu.compiled import donate_argnums_for

            def step(params, state):
                return params, state

            fn = jax.jit(step,
                         donate_argnums=donate_argnums_for(None, (1,)))

            def train(params, state):
                note_bytes(state)        # BEFORE the dispatch: clean
                new_p, state = fn(params, state)
                return new_p, state      # rebound to the output: clean
            """, relpath="mxnet_tpu/mymod.py")
        assert not [f for f in fs if f.rule == "donation-hazard"], fs

    def test_severity_is_error(self, tmp_path):
        fs = _analyze(tmp_path, """
            import jax

            def step(params):
                return params

            fn = jax.jit(step, donate_argnums=(0,))
            """, relpath="mxnet_tpu/mymod.py")
        hits = [f for f in fs if f.rule == "donation-hazard"]
        assert hits and all(f.severity == "error" for f in hits)


class TestShardingReachability:
    def test_dead_spec_flags_applied_spec_clean(self, tmp_path):
        fs = _analyze(tmp_path, """
            from jax.sharding import NamedSharding, PartitionSpec as P

            def place(mesh, x, y):
                spec = P("data")            # never applied: flags
                used = P("data", "model")
                return NamedSharding(mesh, used)
            """, relpath="mxnet_tpu/mymod.py")
        hits = [f for f in fs if f.rule == "sharding-reachability"]
        assert len(hits) == 1, fs
        assert "'spec'" in hits[0].message

    def test_dead_spec_suppression(self, tmp_path):
        fs = _analyze(tmp_path, """
            from jax.sharding import PartitionSpec as P

            def place(mesh):
                # mxanalyze: allow(sharding-reachability): doc example
                spec = P("data")
            """, relpath="mxnet_tpu/mymod.py")
        assert not [f for f in fs
                    if f.rule == "sharding-reachability"], fs

    def _project(self, tmp_path, frontend_src):
        (tmp_path / "mxnet_tpu" / "parallel").mkdir(parents=True)
        (tmp_path / "mxnet_tpu" / "parallel" / "zoo.py").write_text(
            textwrap.dedent("""
                __all__ = ["zoo_apply"]

                def zoo_apply(x):
                    return x
                """))
        (tmp_path / "mxnet_tpu" / "parallel" / "__init__.py").write_text(
            "from .zoo import zoo_apply\n")
        (tmp_path / "mxnet_tpu" / "frontend.py").write_text(
            textwrap.dedent(frontend_src))
        env_doc = tmp_path / "env_var.md"
        env_doc.write_text("")
        return analyze_paths([str(tmp_path / "mxnet_tpu")],
                             root=str(tmp_path), env_doc=str(env_doc))

    def test_dead_public_surface_flags(self, tmp_path):
        fs = self._project(tmp_path, """
            def fit(x):
                return x
            """)
        hits = [f for f in fs if f.rule == "sharding-reachability"]
        assert len(hits) == 1, fs
        assert "unreachable" in hits[0].message
        assert hits[0].path == "mxnet_tpu/parallel/zoo.py"

    def test_reached_surface_clean(self, tmp_path):
        fs = self._project(tmp_path, """
            from .parallel import zoo_apply

            def fit(x):
                return zoo_apply(x)
            """)
        assert not [f for f in fs
                    if f.rule == "sharding-reachability"], fs

    def test_no_frontend_in_scope_no_dead_surface(self, tmp_path):
        """A --changed-only-style run over just the parallel module must
        not call everything dead for lack of visible callers."""
        (tmp_path / "mxnet_tpu" / "parallel").mkdir(parents=True)
        p = tmp_path / "mxnet_tpu" / "parallel" / "zoo.py"
        p.write_text("__all__ = [\"zoo_apply\"]\n\n"
                     "def zoo_apply(x):\n    return x\n")
        env_doc = tmp_path / "env_var.md"
        env_doc.write_text("")
        fs = analyze_paths([str(p)], root=str(tmp_path),
                           env_doc=str(env_doc))
        assert not [f for f in fs
                    if f.rule == "sharding-reachability"], fs


# ---------------------------------------------------------------------------
# --profile: runtime verdicts escalate matching findings
# ---------------------------------------------------------------------------

class TestProfileVerdicts:
    def _snapshot_dir(self, tmp_path, stepprof=None, shardprof=None,
                      runprof=None):
        d = tmp_path / "telemetry"
        d.mkdir(exist_ok=True)
        if stepprof is not None:
            (d / "stepprof_host0_pid1.json").write_text(
                json.dumps(stepprof))
        if shardprof is not None:
            (d / "shardprof_host0_pid1.json").write_text(
                json.dumps(shardprof))
        if runprof is not None:
            (d / "runprof_i0_host0_pid1.json").write_text(
                json.dumps(runprof))
        return str(d)

    def test_read_verdicts_from_synthetic_snapshots(self, tmp_path):
        from tools.mxanalyze import profiles
        d = self._snapshot_dir(
            tmp_path,
            stepprof={"verdict": "dispatch-bound", "hint": "fuse"},
            shardprof={"audit": {"flagged": 3},
                       "comm": {"overlap_fraction": 0.1}},
            runprof={"states": {"train_productive": 5.0,
                                "compile": 20.0},
                     "goodput_fraction": 0.2})
        names = {v["verdict"] for v in profiles.read_verdicts(d)}
        assert names == {"dispatch-bound", "replicated-params",
                         "unoverlapped-comm", "compile-heavy"}

    def test_dispatch_verdict_escalates_step_path_finding(self, tmp_path):
        from tools.mxanalyze import profiles
        fs = _analyze(tmp_path, """
            import jax

            def step(grad_args, live_names):
                outs = []
                for k, name in enumerate(live_names):
                    outs.append(apply_one(grad_args[name]))
                return outs

            fn = jax.jit(step)
            """, relpath="mxnet_tpu/module/mod.py")
        d = self._snapshot_dir(
            tmp_path, stepprof={"verdict": "dispatch-bound"})
        verdicts = profiles.read_verdicts(d)
        escalated = profiles.escalate(fs, verdicts)
        hits = [f for f in escalated
                if f.rule == "dispatch-amplification"]
        assert hits, fs
        assert all(f.severity == "error" for f in hits)
        assert all(f.escalated == "dispatch-bound" for f in hits)
        assert all(f.to_dict()["escalated_by"] == "dispatch-bound"
                   for f in hits)

    def test_unrelated_verdict_escalates_nothing(self, tmp_path):
        from tools.mxanalyze import profiles
        fs = _analyze(tmp_path, """
            def predict(self, out):
                return out.asnumpy()
            """, relpath="mxnet_tpu/module/mod.py")
        d = self._snapshot_dir(
            tmp_path, stepprof={"verdict": "dispatch-bound"})
        assert profiles.escalate(fs, profiles.read_verdicts(d)) == []
        # ...but a sync-bound verdict matches the host-sync finding
        d2 = self._snapshot_dir(
            tmp_path, stepprof={"verdict": "sync-bound"})
        esc = profiles.escalate(fs, profiles.read_verdicts(d2))
        assert len(esc) == 1 and esc[0].rule == "host-sync-hazard"

    def test_healthy_runprof_yields_no_verdict(self, tmp_path):
        from tools.mxanalyze import profiles
        d = self._snapshot_dir(
            tmp_path,
            runprof={"states": {"train_productive": 95.0,
                                "compile": 2.0},
                     "goodput_fraction": 0.97})
        assert profiles.read_verdicts(d) == []

    def test_cli_profile_emits_perf_gate_line(self, tmp_path):
        d = self._snapshot_dir(
            tmp_path, stepprof={"verdict": "dispatch-bound"})
        doc = tmp_path / "env.md"
        doc.write_text("")
        clean = tmp_path / "clean.py"
        clean.write_text("X = 1\n")
        r = _run_cli([str(clean), "--profile", d, "--env-doc",
                      str(doc), "--baseline",
                      str(tmp_path / "bl.json")])
        assert r.returncode == 0, r.stdout + r.stderr
        lines = r.stdout.strip().splitlines()
        perf = json.loads(lines[-1])
        assert perf["metric"] == "mxanalyze_perf_gate"
        assert perf["status"] == "pass"
        assert perf["verdicts"] == ["dispatch-bound"]
        gate = json.loads(lines[-2])
        assert gate["metric"] == "mxanalyze_gate"

    def test_cli_profile_empty_dir(self, tmp_path):
        d = tmp_path / "empty"
        d.mkdir()
        doc = tmp_path / "env.md"
        doc.write_text("")
        clean = tmp_path / "clean.py"
        clean.write_text("X = 1\n")
        r = _run_cli([str(clean), "--profile", str(d), "--env-doc",
                      str(doc), "--baseline",
                      str(tmp_path / "bl.json")])
        assert r.returncode == 0, r.stdout + r.stderr
        perf = json.loads(r.stdout.strip().splitlines()[-1])
        assert perf["metric"] == "mxanalyze_perf_gate"
        assert "no profiler verdicts" in perf["detail"]


# ---------------------------------------------------------------------------
# --changed-only: git-scoped incremental runs
# ---------------------------------------------------------------------------

class TestChangedOnly:
    def _git(self, cwd, *args):
        return subprocess.run(["git", "-C", str(cwd)] + list(args),
                              capture_output=True, text=True, check=True)

    def test_changed_files_lists_modified_and_untracked(self, tmp_path):
        from tools.mxanalyze.cli import changed_files
        self._git(tmp_path, "init", "-q")
        self._git(tmp_path, "-c", "user.email=t@t", "-c", "user.name=t",
                  "commit", "--allow-empty", "-qm", "seed")
        (tmp_path / "pkg").mkdir()
        tracked = tmp_path / "pkg" / "a.py"
        tracked.write_text("X = 1\n")
        self._git(tmp_path, "add", "pkg/a.py")
        self._git(tmp_path, "-c", "user.email=t@t", "-c", "user.name=t",
                  "commit", "-qm", "add a")
        assert changed_files(str(tmp_path), ["pkg/"]) == []
        tracked.write_text("X = 2\n")                    # modified
        (tmp_path / "pkg" / "b.py").write_text("Y = 1\n")  # untracked
        (tmp_path / "pkg" / "c.txt").write_text("not py\n")
        (tmp_path / "other.py").write_text("Z = 1\n")    # out of scope
        assert changed_files(str(tmp_path), ["pkg/"]) == [
            "pkg/a.py", "pkg/b.py"]

    def test_cli_changed_only_smoke(self):
        """Same exit-code conventions on the real repo: the changed set
        (possibly empty) analyzes clean against the baseline."""
        r = _run_cli(["--changed-only", "--strict"])
        assert r.returncode == 0, r.stdout + r.stderr
        gate = json.loads(r.stdout.strip().splitlines()[-1])
        assert gate["metric"] == "mxanalyze_gate"
        assert gate["status"] == "pass"


# ---------------------------------------------------------------------------
# baseline round-trip
# ---------------------------------------------------------------------------

class TestBaseline:
    SRC = """
        def f():
            try:
                risky()
            except Exception:
                pass
        """

    def test_roundtrip_then_new_then_stale(self, tmp_path):
        fs = _analyze(tmp_path, self.SRC)
        assert fs
        bl_path = tmp_path / "baseline.json"
        save_baseline(str(bl_path), fs)
        bl = load_baseline(str(bl_path))

        new, baselined, stale = diff_baseline(fs, bl)
        assert not new and not stale and len(baselined) == len(fs)

        # a SECOND identical handler in the same file exceeds the count
        fs2 = _analyze(tmp_path, self.SRC + """
        def g():
            try:
                risky()
            except Exception:
                pass
        """)
        new, baselined, stale = diff_baseline(fs2, bl)
        assert len(new) == 1 and not stale

        # fixing everything leaves the entry stale
        new, baselined, stale = diff_baseline([], bl)
        assert not new and sum(stale.values()) == len(fs)

    def test_fingerprint_is_line_independent(self, tmp_path):
        fs = _analyze(tmp_path, self.SRC)
        shifted = _analyze(tmp_path, "\n\n# padding\n\n"
                           + textwrap.dedent(self.SRC))
        assert [f.fingerprint() for f in fs] == \
            [f.fingerprint() for f in shifted]


# ---------------------------------------------------------------------------
# CLI: exit codes + BENCH-style gate line (bench_gate conventions)
# ---------------------------------------------------------------------------

def _run_cli(args, cwd=REPO):
    return subprocess.run(
        [sys.executable, "-m", "tools.mxanalyze"] + args,
        capture_output=True, text=True, cwd=cwd,
        env=dict(os.environ, PYTHONPATH=REPO))


class TestCLI:
    def _tmp_repo(self, tmp_path):
        bad = tmp_path / "bad.py"
        bad.write_text(textwrap.dedent("""
            def f():
                try:
                    risky()
                except Exception:
                    pass
            """))
        doc = tmp_path / "env.md"
        doc.write_text("")
        bl = tmp_path / "bl.json"
        return bad, doc, bl

    def test_violation_fails_then_baseline_passes(self, tmp_path):
        bad, doc, bl = self._tmp_repo(tmp_path)
        common = [str(bad), "--baseline", str(bl), "--env-doc", str(doc)]
        r = _run_cli(["--strict"] + common)
        assert r.returncode == 1, r.stdout + r.stderr
        gate = json.loads(r.stdout.strip().splitlines()[-1])
        assert gate["metric"] == "mxanalyze_gate"
        assert gate["status"] == "fail" and gate["new"] == 1

        r = _run_cli(["--update-baseline"] + common)
        assert r.returncode == 0

        r = _run_cli(["--strict"] + common)
        assert r.returncode == 0, r.stdout + r.stderr
        gate = json.loads(r.stdout.strip().splitlines()[-1])
        assert gate["status"] == "pass" and gate["baselined"] == 1

    def test_scoped_update_preserves_out_of_scope_entries(self, tmp_path):
        """--update-baseline over a subdir must not drop recorded debt
        for files outside that subdir."""
        sub_a, sub_b = tmp_path / "a", tmp_path / "b"
        sub_a.mkdir(), sub_b.mkdir()
        src = textwrap.dedent("""
            def f():
                try:
                    risky()
                except Exception:
                    pass
            """)
        (sub_a / "m.py").write_text(src)
        (sub_b / "m.py").write_text(src)
        doc = tmp_path / "env.md"
        doc.write_text("")
        bl = tmp_path / "bl.json"
        common = ["--baseline", str(bl), "--env-doc", str(doc)]
        r = _run_cli(["--update-baseline", str(sub_a), str(sub_b)]
                     + common)
        assert r.returncode == 0
        full = load_baseline(str(bl))
        assert len(full) == 2
        # a path-scoped --strict run must not call the unanalyzed b
        # entry stale
        r = _run_cli(["--strict", str(sub_a)] + common)
        assert r.returncode == 0, r.stdout + r.stderr
        # fix b's finding, scoped-update only b: a's entry must survive
        (sub_b / "m.py").write_text("def f():\n    return 1\n")
        r = _run_cli(["--update-baseline", str(sub_b)] + common)
        assert r.returncode == 0, r.stdout + r.stderr
        after = load_baseline(str(bl))
        assert len(after) == 1 and list(after)[0][1].endswith("a/m.py"), \
            dict(after)
        # and the full-tree gate still passes against the merged file
        r = _run_cli(["--strict", str(sub_a), str(sub_b)] + common)
        assert r.returncode == 0, r.stdout + r.stderr

    def test_corrupt_baseline_is_usage_error_not_gate_result(self,
                                                             tmp_path):
        bad, doc, bl = self._tmp_repo(tmp_path)
        bl.write_text("<<<<<<< conflict markers\n{not json")
        r = _run_cli([str(bad), "--baseline", str(bl), "--env-doc",
                      str(doc)])
        assert r.returncode == 2, (r.returncode, r.stdout, r.stderr)
        assert "not valid JSON" in r.stderr

    def test_nonexistent_path_is_an_error_not_a_pass(self, tmp_path):
        doc = tmp_path / "env.md"
        doc.write_text("")
        r = _run_cli([str(tmp_path / "no_such_dir"), "--env-doc",
                      str(doc)])
        assert r.returncode == 2, (r.returncode, r.stdout, r.stderr)
        assert "does not exist" in r.stderr

    def test_strict_fails_on_stale_entry(self, tmp_path):
        bad, doc, bl = self._tmp_repo(tmp_path)
        common = [str(bad), "--baseline", str(bl), "--env-doc", str(doc)]
        _run_cli(["--update-baseline"] + common)
        bad.write_text("def f():\n    return 1\n")   # finding fixed
        r = _run_cli(common)               # lenient: warn only
        assert r.returncode == 0
        r = _run_cli(["--strict"] + common)
        assert r.returncode == 1
        gate = json.loads(r.stdout.strip().splitlines()[-1])
        assert gate["stale"] == 1

    def test_one_violation_of_each_rule_fails(self, tmp_path):
        """The acceptance drill: each of the five rules, inserted fresh,
        flips the gate to non-zero on its own."""
        doc = tmp_path / "env.md"
        doc.write_text("")
        bl = tmp_path / "bl.json"   # absent: empty baseline
        snippets = {
            "jit-purity": """
                import time, jax
                @jax.jit
                def f(x):
                    return x + time.time()
                """,
            "retrace-hazard": """
                import jax
                def impl(x):
                    return x
                nums = [0]
                f = jax.jit(impl, static_argnums=tuple(nums))
                """,
            "lock-discipline": """
                import threading
                _lock = threading.Lock()
                _s = {}
                def a():
                    with _lock:
                        _s["k"] = 1
                def b():
                    _s["k"] = 2
                """,
            "swallowed-exception": """
                def f():
                    try:
                        risky()
                    except Exception:
                        pass
                """,
            "env-var-drift": """
                import os
                X = os.environ.get("MXNET_UNDOCUMENTED", "0")
                """,
        }
        for rule, src in snippets.items():
            p = tmp_path / ("%s.py" % rule.replace("-", "_"))
            p.write_text(textwrap.dedent(src))
            r = _run_cli(["--strict", str(p), "--baseline", str(bl),
                          "--env-doc", str(doc)])
            assert r.returncode == 1, (rule, r.stdout, r.stderr)
            assert rule in r.stdout, (rule, r.stdout)
            p.unlink()


# ---------------------------------------------------------------------------
# tier-1: the real tree is clean against the checked-in baseline
# ---------------------------------------------------------------------------

class TestRepoGate:
    def test_mxnet_tpu_clean_against_baseline(self):
        findings = analyze_paths(["mxnet_tpu"], root=REPO)
        bl = load_baseline(os.path.join(REPO, "tools", "mxanalyze",
                                        "baseline.json"))
        new, baselined, stale = diff_baseline(findings, bl)
        assert not new, "new findings:\n%s" % "\n".join(
            f.render() for f in new)
        assert not stale, "stale baseline entries (fixed findings — " \
            "run --update-baseline): %r" % stale

    def test_env_var_drift_is_zero_with_no_baseline_entries(self):
        findings = analyze_paths(["mxnet_tpu"], root=REPO)
        drift = [f for f in findings if f.rule == "env-var-drift"]
        assert not drift, "\n".join(f.render() for f in drift)
        bl = load_baseline(os.path.join(REPO, "tools", "mxanalyze",
                                        "baseline.json"))
        assert not [fp for fp in bl if fp[0] == "env-var-drift"]

    def test_repo_gate_cli(self):
        r = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools", "repo_gate.py")],
            capture_output=True, text=True, cwd=REPO,
            env=dict(os.environ, PYTHONPATH=REPO))
        assert r.returncode == 0, r.stdout + r.stderr
        gate = json.loads(r.stdout.strip().splitlines()[-1])
        assert gate["metric"] == "mxanalyze_gate"
        assert gate["status"] == "pass"

    def test_known_rules_registry(self):
        from tools.mxanalyze import RULES
        for rule in ("jit-purity", "retrace-hazard", "lock-discipline",
                     "swallowed-exception", "env-var-drift",
                     "host-sync-hazard", "dispatch-amplification",
                     "donation-hazard", "sharding-reachability"):
            assert rule in RULES

    def test_all_passes_cover_all_rules(self):
        # every pass rule is registered; RULES additionally carries the
        # framework's synthetic rules (parse-error, bad-suppression)
        from tools.mxanalyze import RULES
        from tools.mxanalyze.passes import ALL_PASSES
        pass_rules = {p.rule for p in ALL_PASSES}
        assert pass_rules <= set(RULES)
        assert {"host-sync-hazard", "dispatch-amplification",
                "donation-hazard",
                "sharding-reachability"} <= pass_rules

    def test_bench_with_adjacent_snapshots_runs_perf_gate(self, tmp_path):
        """repo_gate --bench auto-runs mxanalyze --profile when
        telemetry snapshots sit next to the bench records."""
        bench = tmp_path / "run.jsonl"
        bench.write_text("")   # no records: bench gate skips, exit 0
        (tmp_path / "stepprof_host0_pid1.json").write_text(
            json.dumps({"verdict": "compute-bound", "hint": ""}))
        r = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools", "repo_gate.py"),
             "--bench", str(bench)],
            capture_output=True, text=True, cwd=REPO,
            env=dict(os.environ, PYTHONPATH=REPO))
        assert r.returncode == 0, r.stdout + r.stderr
        perf = [json.loads(ln) for ln in r.stdout.splitlines()
                if ln.startswith("{") and "mxanalyze_perf_gate" in ln]
        assert len(perf) == 1, r.stdout
        assert perf[0]["status"] == "pass"
        assert perf[0]["verdicts"] == ["compute-bound"]

    def test_bench_without_snapshots_skips_perf_gate(self, tmp_path):
        bench = tmp_path / "run.jsonl"
        bench.write_text("")
        r = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools", "repo_gate.py"),
             "--bench", str(bench)],
            capture_output=True, text=True, cwd=REPO,
            env=dict(os.environ, PYTHONPATH=REPO))
        assert r.returncode == 0, r.stdout + r.stderr
        assert "mxanalyze_perf_gate" not in r.stdout


# ---------------------------------------------------------------------------
# cross-thread-state: thread roots, unlocked shared writes, bare waits
# ---------------------------------------------------------------------------

class TestCrossThreadState:
    def test_unlocked_write_from_two_roots(self, tmp_path):
        fs = _analyze(tmp_path, """
            import threading

            class Pump:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._done = False
                    self._t = threading.Thread(target=self._run)

                def _run(self):
                    self._done = True

                def stop(self):
                    self._done = True
            """)
        msgs = [f.message for f in fs if f.rule == "cross-thread-state"]
        assert len(msgs) == 2, fs
        assert all("Pump._done" in m for m in msgs)
        assert all("Pump._run" in m and "main" in m for m in msgs)

    def test_locked_writes_are_clean(self, tmp_path):
        fs = _analyze(tmp_path, """
            import threading

            class Pump:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._done = False
                    self._t = threading.Thread(target=self._run)

                def _run(self):
                    with self._lock:
                        self._done = True

                def stop(self):
                    with self._lock:
                        self._done = True
            """)
        assert [f for f in fs if f.rule == "cross-thread-state"] == []

    def test_single_root_not_flagged(self, tmp_path):
        # worker-only writes: one root, nothing cross-thread
        fs = _analyze(tmp_path, """
            import threading

            class Pump:
                def __init__(self):
                    self._n = 0
                    self._t = threading.Thread(target=self._run)

                def _run(self):
                    self._n += 1
            """)
        assert [f for f in fs if f.rule == "cross-thread-state"] == []

    def test_module_function_target_and_global(self, tmp_path):
        fs = _analyze(tmp_path, """
            import threading
            _state = {}

            def worker():
                _state["k"] = 1

            def start():
                threading.Thread(target=worker).start()
                _state["k"] = 0
            """)
        msgs = [f.message for f in fs if f.rule == "cross-thread-state"]
        assert len(msgs) == 2, fs
        assert all("_state" in m and "worker" in m for m in msgs)

    def test_root_propagates_through_helper(self, tmp_path):
        # the worker loop writes via a helper: the helper inherits the
        # worker root and the main-path write still makes it 2 roots
        fs = _analyze(tmp_path, """
            import threading
            _state = {}

            def _bump():
                _state["k"] = 1

            def worker():
                _bump()

            def start():
                threading.Thread(target=worker).start()
                _state["k"] = 0
            """)
        msgs = [f.message for f in fs if f.rule == "cross-thread-state"]
        assert len(msgs) == 2, fs

    def test_thread_subclass_run_is_a_root(self, tmp_path):
        fs = _analyze(tmp_path, """
            import threading
            _hits = 0

            class W(threading.Thread):
                def run(self):
                    global _hits
                    _hits += 1

            def poke():
                global _hits
                _hits = 0
            """)
        msgs = [f.message for f in fs if f.rule == "cross-thread-state"]
        assert len(msgs) == 2, fs
        assert all("W.run" in m for m in msgs)

    def test_suppression_holds(self, tmp_path):
        fs = _analyze(tmp_path, """
            import threading
            _state = {}

            def worker():
                # mxanalyze: allow(cross-thread-state): handoff is ordered by the queue, single writer per key
                _state["k"] = 1

            def start():
                threading.Thread(target=worker).start()
                # mxanalyze: allow(cross-thread-state): runs before the thread starts
                _state["k"] = 0
            """)
        assert [f for f in fs if f.rule == "cross-thread-state"] == []

    def test_bare_condition_wait_flagged(self, tmp_path):
        fs = _analyze(tmp_path, """
            import threading

            class Q:
                def __init__(self):
                    self._cond = threading.Condition()
                    self._t = threading.Thread(target=self._run)

                def _run(self):
                    with self._cond:
                        self._cond.notify()

                def get(self):
                    with self._cond:
                        self._cond.wait()
            """)
        msgs = [f.message for f in fs if f.rule == "cross-thread-state"]
        assert len(msgs) == 1, fs
        assert "while" in msgs[0]

    def test_predicate_loop_and_wait_for_are_clean(self, tmp_path):
        fs = _analyze(tmp_path, """
            import threading

            class Q:
                def __init__(self):
                    self._cond = threading.Condition()
                    self._ready = False
                    self._t = threading.Thread(target=self._run)

                def _run(self):
                    with self._cond:
                        self._cond.notify()

                def get(self):
                    with self._cond:
                        while not self._ready:
                            self._cond.wait()

                def get2(self):
                    with self._cond:
                        self._cond.wait_for(lambda: self._ready)
            """)
        assert [f for f in fs if f.rule == "cross-thread-state"] == []

    def test_registered_lock_still_recognized(self, tmp_path):
        # threadsan.register wrapping must not blind the lock table
        fs = _analyze(tmp_path, """
            import threading
            from mxnet_tpu import threadsan

            class Pump:
                def __init__(self):
                    self._lock = threadsan.register(
                        "mod.Pump._lock", threading.Lock())
                    self._done = False
                    self._t = threading.Thread(target=self._run)

                def _run(self):
                    with self._lock:
                        self._done = True

                def stop(self):
                    with self._lock:
                        self._done = True
            """)
        assert [f for f in fs if f.rule == "cross-thread-state"] == []


# ---------------------------------------------------------------------------
# --witness: runtime lock-witness join
# ---------------------------------------------------------------------------

class TestWitnessJoin:
    def _witness_dir(self, tmp_path, doc):
        d = tmp_path / "telemetry"
        d.mkdir(exist_ok=True)
        (d / "threadsan_host0_pid1.json").write_text(json.dumps(doc))
        return str(d)

    def _doc(self, **over):
        doc = {"host": 0, "pid": 1, "updated": 1.0, "armed": True,
               "locks": {}, "edges": [], "reports": []}
        doc.update(over)
        return doc

    def test_deadlock_report_fails_threads_gate(self, tmp_path):
        d = self._witness_dir(tmp_path, self._doc(
            reports=[{"kind": "potential_deadlock",
                      "cycle": ["a.L", "b.L", "a.L"],
                      "locks": ["a.L", "b.L"], "stacks": {}}],
            locks={"a.L": {"acquires": 9, "contended": 3,
                           "wait_total": 0.5, "wait_max": 0.3,
                           "hold_total": 0.1, "hold_max": 0.05}}))
        doc = tmp_path / "env.md"
        doc.write_text("")
        clean = tmp_path / "clean.py"
        clean.write_text("X = 1\n")
        r = _run_cli([str(clean), "--witness", d, "--env-doc", str(doc),
                      "--baseline", str(tmp_path / "bl.json")])
        assert r.returncode == 1, r.stdout + r.stderr
        lines = r.stdout.strip().splitlines()
        gate = json.loads(lines[-1])
        assert gate["metric"] == "mxanalyze_threads_gate"
        assert gate["status"] == "fail" and gate["reports"] == 1
        # the failure detail names the worst contended lock
        assert "a.L" in gate["detail"]
        assert "potential_deadlock" in r.stdout

    def test_runtime_inversion_without_report_fails(self, tmp_path):
        d = self._witness_dir(tmp_path, self._doc(
            edges=[{"outer": "a.L", "inner": "b.L", "count": 2,
                    "site": "x.py:1 (f)"},
                   {"outer": "b.L", "inner": "a.L", "count": 1,
                    "site": "y.py:2 (g)"}]))
        doc = tmp_path / "env.md"
        doc.write_text("")
        clean = tmp_path / "clean.py"
        clean.write_text("X = 1\n")
        r = _run_cli([str(clean), "--witness", d, "--env-doc", str(doc),
                      "--baseline", str(tmp_path / "bl.json")])
        assert r.returncode == 1, r.stdout + r.stderr
        gate = json.loads(r.stdout.strip().splitlines()[-1])
        assert gate["inversions"] == 1
        assert "witness inversion" in r.stdout

    def test_clean_witness_passes(self, tmp_path):
        d = self._witness_dir(tmp_path, self._doc(
            edges=[{"outer": "a.L", "inner": "b.L", "count": 5,
                    "site": "x.py:1 (f)"}],
            locks={"a.L": {"acquires": 5, "contended": 0,
                           "wait_total": 0.0, "wait_max": 0.0,
                           "hold_total": 0.0, "hold_max": 0.0}}))
        doc = tmp_path / "env.md"
        doc.write_text("")
        clean = tmp_path / "clean.py"
        clean.write_text("X = 1\n")
        r = _run_cli([str(clean), "--witness", d, "--env-doc", str(doc),
                      "--baseline", str(tmp_path / "bl.json")])
        assert r.returncode == 0, r.stdout + r.stderr
        gate = json.loads(r.stdout.strip().splitlines()[-1])
        assert gate["metric"] == "mxanalyze_threads_gate"
        assert gate["status"] == "pass"

    def test_empty_dir_passes_with_note(self, tmp_path):
        d = tmp_path / "empty"
        d.mkdir()
        doc = tmp_path / "env.md"
        doc.write_text("")
        clean = tmp_path / "clean.py"
        clean.write_text("X = 1\n")
        r = _run_cli([str(clean), "--witness", str(d), "--env-doc",
                      str(doc), "--baseline", str(tmp_path / "bl.json")])
        assert r.returncode == 0, r.stdout + r.stderr
        gate = json.loads(r.stdout.strip().splitlines()[-1])
        assert "no witness files" in gate["detail"]

    def test_report_escalates_baselined_finding(self, tmp_path):
        from tools.mxanalyze import witness as wit
        src = tmp_path / "mxnet_tpu"
        src.mkdir()
        (src / "mod.py").write_text(textwrap.dedent("""
            import threading
            _state = {}

            def worker():
                _state["k"] = 1

            def start():
                threading.Thread(target=worker).start()
                _state["k"] = 0
            """))
        fs = analyze_paths([str(src)], root=str(tmp_path),
                           env_doc=str(tmp_path / "env.md"))
        target = [f for f in fs if f.rule == "cross-thread-state"]
        assert target, fs
        esc = wit.escalate(fs, [{"kind": "potential_deadlock",
                                 "cycle": ["a", "b", "a"]}])
        assert esc and all(f.escalated == "witness:potential_deadlock"
                           for f in esc)
        assert all(f.severity == "error" for f in esc)

    def test_freshest_doc_per_host_wins(self, tmp_path):
        from tools.mxanalyze import witness as wit
        d = tmp_path / "t"
        d.mkdir()
        (d / "threadsan_host0_pid1.json").write_text(json.dumps(
            self._doc(updated=1.0,
                      reports=[{"kind": "blocked_too_long",
                                "lock": "stale.L"}])))
        (d / "threadsan_host0_pid2.json").write_text(json.dumps(
            self._doc(updated=2.0, pid=2)))
        docs = wit.read(str(d))
        assert len(docs) == 1 and docs[0]["pid"] == 2
        assert wit.runtime_reports(docs) == []
