"""Thread sanitizer (TSan-lite) suite: the off path is zero-overhead
passthrough, the armed path witnesses acquisition-order cycles with
both stacks, wait/hold anatomy lands in telemetry histograms,
held-across-dispatch and blocked-too-long hazards are filed once, and
the witness round-trips through the per-host JSON transport into the
``python -m mxnet_tpu.threadsan report`` CLI.

Everything here is host-side threading — no device, no jax import
needed beyond what mxnet_tpu pulls in.
"""
import json
import os
import subprocess
import sys
import threading
import time

import pytest

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from mxnet_tpu import telemetry, threadsan  # noqa: E402


@pytest.fixture
def armed():
    """Arm the witness for locks registered inside the test, with clean
    state on both sides. Locks other modules registered at import time
    stay raw (arming is never retroactive)."""
    threadsan.arm()
    threadsan.reset()
    yield
    threadsan.reset()
    threadsan.disarm()


# ---------------------------------------------------------------------------
# zero-overhead contract (off)
# ---------------------------------------------------------------------------

class TestOffPath:
    def test_register_returns_same_object(self):
        threadsan.disarm()
        lk = threading.Lock()
        assert threadsan.register("t.off", lk) is lk
        rl = threading.RLock()
        assert threadsan.register("t.off_r", rl) is rl
        cv = threading.Condition()
        assert threadsan.register("t.off_c", cv) is cv
        assert threadsan.held_locks() == []
        assert threadsan.note_dispatch("t.site") is None

    def test_module_locks_are_raw_when_off(self):
        """With MXNET_THREADSAN unset, importing the project must leave
        the registered module locks as plain threading primitives —
        the exact objects their modules created."""
        env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
        env.pop("MXNET_THREADSAN", None)
        r = subprocess.run(
            [sys.executable, "-c",
             "from mxnet_tpu import telemetry, threadsan\n"
             "assert not threadsan.ARMED\n"
             "assert not isinstance(telemetry._lock,"
             " threadsan.LockWitness), type(telemetry._lock)\n"
             "print('RAW_OK')\n"],
            capture_output=True, text=True, env=env, cwd=REPO,
            timeout=120)
        assert r.returncode == 0, r.stdout + r.stderr
        assert "RAW_OK" in r.stdout

    def test_armed_boot_wraps_module_locks(self):
        env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO,
                   MXNET_THREADSAN="1")
        r = subprocess.run(
            [sys.executable, "-c",
             "from mxnet_tpu import telemetry, threadsan\n"
             "assert threadsan.ARMED\n"
             "assert isinstance(telemetry._lock, threadsan.LockWitness)\n"
             "with telemetry._lock:\n"
             "    pass\n"
             "print('WRAPPED_OK')\n"],
            capture_output=True, text=True, env=env, cwd=REPO,
            timeout=120)
        assert r.returncode == 0, r.stdout + r.stderr
        assert "WRAPPED_OK" in r.stdout


# ---------------------------------------------------------------------------
# deadlock witness
# ---------------------------------------------------------------------------

class TestDeadlockWitness:
    def test_ab_ba_cycle_detected_with_both_stacks(self, armed):
        A = threadsan.register("t.A", threading.Lock())
        B = threadsan.register("t.B", threading.Lock())

        def ab():
            with A:
                with B:
                    pass

        def ba():
            with B:
                with A:
                    pass

        # serial execution: no actual deadlock, but the opposing order
        # is exactly what the witness exists to catch
        t1 = threading.Thread(target=ab, name="t-ab")
        t1.start()
        t1.join()
        t2 = threading.Thread(target=ba, name="t-ba")
        t2.start()
        t2.join()

        snap = threadsan.snapshot()
        reports = [r for r in snap["reports"]
                   if r["kind"] == "potential_deadlock"]
        assert len(reports) == 1, snap["reports"]
        rep = reports[0]
        assert sorted(rep["locks"]) == ["t.A", "t.B"]
        # BOTH sides of the inversion carry a stack naming its thread
        stacks = rep["stacks"]
        assert "t.A -> t.B" in stacks and "t.B -> t.A" in stacks
        assert stacks["t.A -> t.B"]["thread"] == "t-ab"
        assert stacks["t.B -> t.A"]["thread"] == "t-ba"
        assert any("ab" in fr for fr in stacks["t.A -> t.B"]["stack"])
        assert any("ba" in fr for fr in stacks["t.B -> t.A"]["stack"])

    def test_consistent_order_stays_clean(self, armed):
        A = threadsan.register("t.A2", threading.Lock())
        B = threadsan.register("t.B2", threading.Lock())
        for _ in range(3):
            with A:
                with B:
                    pass
        snap = threadsan.snapshot()
        assert snap["reports"] == []
        assert any(e["outer"] == "t.A2" and e["inner"] == "t.B2"
                   and e["count"] == 3 for e in snap["edges"])

    def test_rlock_reentry_records_no_self_edge(self, armed):
        R = threadsan.register("t.R", threading.RLock())
        with R:
            with R:
                assert threadsan.held_locks() == ["t.R"]
        snap = threadsan.snapshot()
        assert snap["reports"] == []
        assert snap["edges"] == []
        assert snap["locks"]["t.R"]["acquires"] == 1


# ---------------------------------------------------------------------------
# wait/hold anatomy
# ---------------------------------------------------------------------------

class TestWaitHoldAnatomy:
    def test_contended_acquire_lands_in_stats_and_histograms(self, armed):
        L = threadsan.register("t.C", threading.Lock())
        wait_h = telemetry.histogram("lock_wait_seconds", lock="t.C")
        hold_h = telemetry.histogram("lock_hold_seconds", lock="t.C")
        wait_n0, hold_n0 = wait_h.count, hold_h.count
        cont0 = telemetry.counter("lock_contention_total",
                                  lock="t.C").value
        entered = threading.Event()

        def holder():
            with L:
                entered.set()
                time.sleep(0.2)

        t = threading.Thread(target=holder)
        t.start()
        entered.wait(5)
        with L:   # must contend against the 0.2s hold
            pass
        t.join()

        st = threadsan.snapshot()["locks"]["t.C"]
        assert st["acquires"] == 2
        assert st["contended"] >= 1
        assert st["wait_total"] >= 0.1
        assert st["wait_max"] <= st["wait_total"] + 1e-9
        assert st["hold_total"] >= 0.2
        assert wait_h.count >= wait_n0 + 2
        assert hold_h.count >= hold_n0 + 2
        assert telemetry.counter("lock_contention_total",
                                 lock="t.C").value >= cont0 + 1

    def test_condition_wait_brackets_hold(self, armed):
        cv = threadsan.register("t.CV", threading.Condition())
        state = {"ready": False}

        def waiter():
            with cv:
                while not state["ready"]:
                    cv.wait(5)

        t = threading.Thread(target=waiter)
        t.start()
        time.sleep(0.05)
        with cv:
            state["ready"] = True
            cv.notify_all()
        t.join(5)
        assert not t.is_alive()
        snap = threadsan.snapshot()
        assert [r for r in snap["reports"]
                if r["kind"] == "potential_deadlock"] == []
        # the waiter's wait() must not read as contention: the witness
        # answers the Condition's _is_owned probe instead of letting it
        # speculatively acquire
        st = snap["locks"]["t.CV"]
        assert st["acquires"] >= 3   # waiter enter + rewake + notifier


# ---------------------------------------------------------------------------
# held-across-dispatch + blocked-too-long
# ---------------------------------------------------------------------------

class TestHazards:
    def test_held_across_dispatch_reported_once(self, armed):
        L = threadsan.register("t.D", threading.Lock())
        with L:
            rep = threadsan.note_dispatch("test.site")
            assert rep is not None
            assert rep["locks"] == ["t.D"]
            assert rep["dispatch_kind"] == "dispatch"
            # same site + same lock set: filed once
            assert threadsan.note_dispatch("test.site") is None
        assert threadsan.note_dispatch("test.site2") is None  # not held
        reports = [r for r in threadsan.snapshot()["reports"]
                   if r["kind"] == "held_across_dispatch"]
        assert len(reports) == 1
        assert reports[0]["site"] == "test.site"

    def test_dispatch_ok_lock_is_exempt(self, armed):
        """A lock registered dispatch_ok=True (e.g. the compile lock,
        which serializes work that dispatches by design) files no
        held-across-dispatch report — but still records edges/stats."""
        OK = threadsan.register("t.OK", threading.Lock(),
                                dispatch_ok=True)
        L = threadsan.register("t.NotOK", threading.Lock())
        with OK:
            assert threadsan.note_dispatch("exempt.site") is None
            with L:
                rep = threadsan.note_dispatch("mixed.site")
                assert rep is not None
                # only the non-exempt lock is named
                assert rep["locks"] == ["t.NotOK"]
        assert threadsan.snapshot()["locks"]["t.OK"]["acquires"] == 1

    def test_blocked_too_long_files_report(self, armed, monkeypatch):
        monkeypatch.setenv("MXNET_THREADSAN_BLOCK_SECONDS", "0.1")
        L = threadsan.register("t.S", threading.Lock())
        entered = threading.Event()

        def holder():
            with L:
                entered.set()
                time.sleep(0.35)

        t = threading.Thread(target=holder)
        t.start()
        entered.wait(5)
        with L:
            pass
        t.join()
        reports = [r for r in threadsan.snapshot()["reports"]
                   if r["kind"] == "blocked_too_long"]
        assert len(reports) == 1, threadsan.snapshot()["reports"]
        assert reports[0]["lock"] == "t.S"
        assert reports[0]["waited_seconds"] >= 0.1


# ---------------------------------------------------------------------------
# witness transport + report CLI
# ---------------------------------------------------------------------------

class TestWitnessRoundTrip:
    def _populate_hazard(self):
        A = threadsan.register("t.WA", threading.Lock())
        B = threadsan.register("t.WB", threading.Lock())

        def ab():
            with A:
                with B:
                    pass

        def ba():
            with B:
                with A:
                    pass

        for fn in (ab, ba):
            t = threading.Thread(target=fn)
            t.start()
            t.join()

    def test_write_load_roundtrip(self, armed, tmp_path):
        self._populate_hazard()
        path = threadsan.write_witness(dir=str(tmp_path))
        assert path and os.path.basename(path).startswith(
            "threadsan_host")
        docs = threadsan.load_witness(str(tmp_path))
        assert len(docs) == 1
        doc = docs[0]
        assert doc["armed"] is True
        assert any(r["kind"] == "potential_deadlock"
                   for r in doc["reports"])
        assert {(e["outer"], e["inner"]) for e in doc["edges"]} == \
            {("t.WA", "t.WB"), ("t.WB", "t.WA")}
        # single-file load too
        assert threadsan.load_witness(path)[0]["pid"] == doc["pid"]

    def test_report_cli_flags_hazard(self, armed, tmp_path):
        self._populate_hazard()
        threadsan.write_witness(dir=str(tmp_path))
        r = subprocess.run(
            [sys.executable, "-m", "mxnet_tpu.threadsan", "report",
             str(tmp_path)],
            capture_output=True, text=True, cwd=REPO,
            env=dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu"),
            timeout=120)
        assert r.returncode == 1, r.stdout + r.stderr
        assert "potential_deadlock" in r.stdout
        assert "t.WA -> t.WB" in r.stdout
        assert "t.WB -> t.WA" in r.stdout
        assert "verdict:" in r.stdout

    def test_report_cli_clean_and_empty(self, armed, tmp_path):
        L = threadsan.register("t.Clean", threading.Lock())
        with L:
            pass
        threadsan.write_witness(dir=str(tmp_path))
        env = dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu")
        r = subprocess.run(
            [sys.executable, "-m", "mxnet_tpu.threadsan", "report",
             str(tmp_path)],
            capture_output=True, text=True, cwd=REPO, env=env,
            timeout=120)
        assert r.returncode == 0, r.stdout + r.stderr
        assert "clean" in r.stdout
        empty = tmp_path / "empty"
        empty.mkdir()
        r = subprocess.run(
            [sys.executable, "-m", "mxnet_tpu.threadsan", "report",
             str(empty)],
            capture_output=True, text=True, cwd=REPO, env=env,
            timeout=120)
        assert r.returncode == 2, r.stdout + r.stderr

    def test_threadsan_dir_overrides_telemetry_dir(self, armed, tmp_path,
                                                   monkeypatch):
        """MXNET_THREADSAN_DIR is a witness-only destination: it wins
        over the telemetry dir, so a harness can collect witnesses in a
        scratch dir while tests keep owning MXNET_TELEMETRY_DIR."""
        wit = tmp_path / "wit"
        tel = tmp_path / "tel"
        wit.mkdir()
        tel.mkdir()
        monkeypatch.setenv("MXNET_TELEMETRY_DIR", str(tel))
        monkeypatch.setenv("MXNET_THREADSAN_DIR", str(wit))
        with threadsan.register("t.Dir", threading.Lock()):
            pass
        path = threadsan.write_witness()
        assert path and os.path.dirname(path) == str(wit)
        assert os.listdir(str(tel)) == []
        assert threadsan.load_witness(str(wit))

    def test_snapshot_is_json_serializable(self, armed):
        self._populate_hazard()
        with threadsan.register("t.J", threading.Lock()):
            threadsan.note_dispatch("json.site")
        json.dumps(threadsan.snapshot())
