"""PSROIPooling / DeformablePSROIPooling vs scalar numpy oracles that
transcribe the reference CUDA kernel semantics (psroi_pooling.cu,
deformable_psroi_pooling.cu)."""
import math

import numpy as np

import mxnet_tpu as mx


def psroi_oracle(data, rois, scale, D, P, G):
    B, C, H, W = data.shape
    R = rois.shape[0]
    out = np.zeros((R, D, P, P), np.float32)
    for n in range(R):
        bi = int(rois[n, 0])
        sw = round(rois[n, 1]) * scale
        sh = round(rois[n, 2]) * scale
        ew = (round(rois[n, 3]) + 1.0) * scale
        eh = (round(rois[n, 4]) + 1.0) * scale
        rw, rh = max(ew - sw, 0.1), max(eh - sh, 0.1)
        bh, bw = rh / P, rw / P
        for ctop in range(D):
            for ph in range(P):
                for pw in range(P):
                    hs = min(max(int(math.floor(ph * bh + sh)), 0), H)
                    he = min(max(int(math.ceil((ph + 1) * bh + sh)), 0), H)
                    ws = min(max(int(math.floor(pw * bw + sw)), 0), W)
                    we = min(max(int(math.ceil((pw + 1) * bw + sw)), 0), W)
                    gw = min(max(int(pw * G / P), 0), G - 1)
                    gh = min(max(int(ph * G / P), 0), G - 1)
                    c = (ctop * G + gh) * G + gw
                    if he <= hs or we <= ws:
                        continue
                    region = data[bi, c, hs:he, ws:we]
                    out[n, ctop, ph, pw] = region.sum() / region.size
    return out


def bilinear(plane, w, h):
    H, W = plane.shape
    x0, y0 = int(math.floor(w)), int(math.floor(h))
    x1, y1 = min(x0 + 1, W - 1), min(y0 + 1, H - 1)
    fx, fy = w - x0, h - y0
    return (plane[y0, x0] * (1 - fx) * (1 - fy)
            + plane[y0, x1] * fx * (1 - fy)
            + plane[y1, x0] * (1 - fx) * fy
            + plane[y1, x1] * fx * fy)


def dpsroi_oracle(data, rois, trans, scale, D, P, G, part, S, std,
                  no_trans=False):
    B, C, H, W = data.shape
    R = rois.shape[0]
    ncls = 1 if no_trans else trans.shape[1] // 2
    cec = D // ncls
    out = np.zeros((R, D, P, P), np.float32)
    cnt = np.zeros((R, D, P, P), np.float32)
    for n in range(R):
        bi = int(rois[n, 0])
        sw = round(rois[n, 1]) * scale - 0.5
        sh = round(rois[n, 2]) * scale - 0.5
        ew = (round(rois[n, 3]) + 1.0) * scale - 0.5
        eh = (round(rois[n, 4]) + 1.0) * scale - 0.5
        rw, rh = max(ew - sw, 0.1), max(eh - sh, 0.1)
        bh, bw = rh / P, rw / P
        sbh, sbw = bh / S, bw / S
        for ctop in range(D):
            cls = ctop // cec
            for ph in range(P):
                for pw in range(P):
                    part_h = int(ph / P * part)
                    part_w = int(pw / P * part)
                    if no_trans:
                        tx = ty = 0.0
                    else:
                        tx = trans[n, cls * 2, part_h, part_w] * std
                        ty = trans[n, cls * 2 + 1, part_h, part_w] * std
                    wstart = pw * bw + sw + tx * rw
                    hstart = ph * bh + sh + ty * rh
                    gw = min(max(int(pw * G / P), 0), G - 1)
                    gh = min(max(int(ph * G / P), 0), G - 1)
                    c = (ctop * G + gh) * G + gw
                    s, k = 0.0, 0
                    for ih in range(S):
                        for iw in range(S):
                            w = wstart + iw * sbw
                            h = hstart + ih * sbh
                            if w < -0.5 or w > W - 0.5 or h < -0.5 \
                                    or h > H - 0.5:
                                continue
                            w = min(max(w, 0.0), W - 1.0)
                            h = min(max(h, 0.0), H - 1.0)
                            s += bilinear(data[bi, c], w, h)
                            k += 1
                    out[n, ctop, ph, pw] = 0.0 if k == 0 else s / k
                    cnt[n, ctop, ph, pw] = k
    return out, cnt


def test_psroi_pooling_vs_oracle():
    rng = np.random.RandomState(0)
    D, G, P = 3, 2, 2
    B, H, W = 2, 12, 16
    data = rng.randn(B, D * G * G, H, W).astype("f")
    rois = np.array([[0, 2, 3, 11, 9], [1, 0, 0, 15, 11],
                     [0, 5, 5, 6, 6], [1, 14, 10, 15, 11]], "f")
    want = psroi_oracle(data, rois, 0.5, D, P, G)
    got = mx.nd.contrib.PSROIPooling(
        mx.nd.array(data), mx.nd.array(rois), spatial_scale=0.5,
        output_dim=D, pooled_size=P, group_size=G).asnumpy()
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


def test_psroi_pooling_default_group_size():
    rng = np.random.RandomState(1)
    D, P = 2, 3
    data = rng.randn(1, D * P * P, 10, 10).astype("f")
    rois = np.array([[0, 1, 1, 8, 8]], "f")
    want = psroi_oracle(data, rois, 1.0, D, P, P)
    got = mx.nd.contrib.PSROIPooling(
        mx.nd.array(data), mx.nd.array(rois), spatial_scale=1.0,
        output_dim=D, pooled_size=P).asnumpy()
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


def test_deformable_psroi_no_trans():
    rng = np.random.RandomState(2)
    D, G, P, S = 2, 2, 2, 2
    data = rng.randn(2, D * G * G, 9, 11).astype("f")
    rois = np.array([[0, 1, 1, 8, 7], [1, 0, 2, 10, 8]], "f")
    want, wcnt = dpsroi_oracle(data, rois, None, 0.5, D, P, G, P, S, 0.0,
                               no_trans=True)
    got, cnt = mx.nd.contrib.DeformablePSROIPooling(
        mx.nd.array(data), mx.nd.array(rois), spatial_scale=0.5,
        output_dim=D, pooled_size=P, group_size=G, sample_per_part=S,
        no_trans=True)
    np.testing.assert_allclose(got.asnumpy(), want, rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(cnt.asnumpy(), wcnt)


def test_deformable_psroi_with_trans():
    rng = np.random.RandomState(3)
    D, G, P, S, part = 4, 2, 2, 3, 2
    ncls = 2
    data = rng.randn(2, D * G * G, 10, 12).astype("f")
    rois = np.array([[0, 2, 2, 9, 9], [1, 1, 0, 11, 8]], "f")
    trans = (rng.rand(2, ncls * 2, part, part).astype("f") - 0.5)
    want, _ = dpsroi_oracle(data, rois, trans, 0.5, D, P, G, part, S, 0.2)
    got = mx.nd.contrib.DeformablePSROIPooling(
        mx.nd.array(data), mx.nd.array(rois), mx.nd.array(trans),
        spatial_scale=0.5, output_dim=D, pooled_size=P, group_size=G,
        part_size=part, sample_per_part=S, trans_std=0.2)[0].asnumpy()
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


def test_deformable_psroi_symbol_and_grad():
    data = mx.sym.Variable("data")
    rois = mx.sym.Variable("rois")
    out = mx.sym.contrib.DeformablePSROIPooling(
        data, rois, spatial_scale=1.0, output_dim=2, pooled_size=2,
        group_size=2, no_trans=True)
    _, out_shapes, _ = out.infer_shape(data=(1, 8, 6, 6), rois=(3, 5))
    assert out_shapes[0] == (3, 2, 2, 2)
    assert out_shapes[1] == (3, 2, 2, 2)  # top_count

    # gradient flows to data through the bilinear samples
    x = mx.nd.array(np.random.RandomState(4).randn(1, 8, 6, 6).astype("f"))
    r = mx.nd.array(np.array([[0, 1, 1, 4, 4]], "float32"))
    x.attach_grad()
    with mx.autograd.record():
        y = mx.nd.contrib.DeformablePSROIPooling(
            x, r, spatial_scale=1.0, output_dim=2, pooled_size=2,
            group_size=2, no_trans=True)[0]
        loss = (y * y).sum()
    loss.backward()
    assert np.abs(x.grad.asnumpy()).sum() > 0


def test_no_trans_string_attr_from_json():
    """Symbol JSON serializes attrs as strings; "False" must parse false."""
    rng = np.random.RandomState(5)
    data = rng.randn(1, 8, 6, 6).astype("f")
    rois = np.array([[0, 1, 1, 4, 4]], "f")
    trans = (rng.rand(1, 2, 2, 2).astype("f") - 0.5)
    want = mx.nd.contrib.DeformablePSROIPooling(
        mx.nd.array(data), mx.nd.array(rois), mx.nd.array(trans),
        spatial_scale=1.0, output_dim=2, pooled_size=2, group_size=2,
        part_size=2, trans_std=0.3, no_trans=False)[0].asnumpy()
    import json
    d, r, t = (mx.sym.Variable(n) for n in ("data", "rois", "trans"))
    out = mx.sym.contrib.DeformablePSROIPooling(
        d, r, t, spatial_scale=1.0, output_dim=2, pooled_size=2,
        group_size=2, part_size=2, trans_std=0.3, no_trans=False)
    loaded = mx.sym.load_json(out.tojson())
    ex = loaded.bind(mx.cpu(), {"data": mx.nd.array(data),
                                "rois": mx.nd.array(rois),
                                "trans": mx.nd.array(trans)})
    got = ex.forward()[0].asnumpy()
    np.testing.assert_allclose(got, want, rtol=1e-5)
    # offsets actually applied (zero-trans result differs)
    no_tr = mx.nd.contrib.DeformablePSROIPooling(
        mx.nd.array(data), mx.nd.array(rois), mx.nd.array(trans),
        spatial_scale=1.0, output_dim=2, pooled_size=2, group_size=2,
        part_size=2, trans_std=0.3, no_trans=True)[0].asnumpy()
    assert np.abs(want - no_tr).max() > 1e-4
