"""Pretrained-zoo converter (tools/convert_zoo_params.py): reference-style
.params files load through vision.<model>(pretrained=True).

No egress exists to fetch the real zoo blobs (reference
model_store.py:70-105 downloads them), so the tests synthesize a
reference-FORMAT file — same byte container, same gluon naming, same
arg:/aux: prefixes a checkpoint-saved file carries — and assert the
converted model reproduces the source net's outputs exactly.
"""
import os
import subprocess
import sys

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.gluon.model_zoo import vision

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TOOL = os.path.join(REPO, "tools", "convert_zoo_params.py")


def _make_reference_style_file(tmp_path, prefixed=True):
    """Init a resnet18_v1 and save it the way reference checkpoints look:
    arg:/aux: key prefixes, NCHW OIHW weights, gluon-prefixed names."""
    net = vision.resnet18_v1()
    net.initialize(mx.init.Xavier())
    x = mx.nd.array(np.random.RandomState(0).rand(1, 3, 224, 224)
                    .astype(np.float32))
    want = net(x).asnumpy()
    blob = {}
    for name, p in net.collect_params().items():
        tag = "aux:" if "running" in name else "arg:"
        blob[(tag + name) if prefixed else name] = p.data()
    path = str(tmp_path / "resnet18_v1-0000.params")
    mx.nd.save(path, blob)
    return path, x, want


def _run_tool(src, out_dir, *extra):
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    env["PALLAS_AXON_POOL_IPS"] = ""
    r = subprocess.run(
        [sys.executable, TOOL, src, "--model", "resnet18_v1",
         "--out-dir", out_dir] + list(extra),
        capture_output=True, text=True, timeout=600, env=env)
    assert r.returncode == 0, r.stdout[-2000:] + r.stderr[-2000:]
    return r.stdout


def test_convert_and_pretrained_load(tmp_path):
    src, x, want = _make_reference_style_file(tmp_path)
    out_dir = str(tmp_path / "zoo")
    out = _run_tool(src, out_dir)
    assert "matched" in out
    net = vision.resnet18_v1(pretrained=True, root=out_dir)
    got = net(x).asnumpy()
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


def test_convert_nhwc_layout(tmp_path):
    src, x, want = _make_reference_style_file(tmp_path)
    out_dir = str(tmp_path / "zoo_nhwc")
    _run_tool(src, out_dir, "--layout", "NHWC")
    net = vision.resnet18_v1(pretrained=True, root=out_dir, layout="NHWC")
    x_nhwc = mx.nd.array(x.asnumpy().transpose(0, 2, 3, 1))
    got = net(x_nhwc).asnumpy()
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)


def test_pretrained_without_file_raises(tmp_path):
    with pytest.raises(mx.base.MXNetError, match="not found"):
        vision.resnet18_v1(pretrained=True, root=str(tmp_path / "empty"))
