"""Autograd tests (modeled on reference tests/python/unittest/test_autograd.py)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd as ag
from mxnet_tpu.test_utils import assert_almost_equal


def test_simple_grad():
    x = mx.nd.array([1.0, 2.0, 3.0])
    x.attach_grad()
    with ag.record():
        y = (x * x).sum()
    y.backward()
    assert_almost_equal(x.grad, 2 * x.asnumpy())


def test_chain_rule():
    x = mx.nd.array([[1.0, 2.0], [3.0, 4.0]])
    x.attach_grad()
    with ag.record():
        y = mx.nd.exp(mx.nd.log(x) * 2)  # = x^2
    y.backward()
    assert_almost_equal(x.grad, 2 * x.asnumpy(), rtol=1e-4)


def test_head_gradient():
    x = mx.nd.array([1.0, 2.0])
    x.attach_grad()
    with ag.record():
        y = x * 3
    y.backward(mx.nd.array([10.0, 100.0]))
    assert_almost_equal(x.grad, np.array([30.0, 300.0], np.float32))


def test_multiple_inputs_and_reuse():
    a = mx.nd.array([2.0])
    b = mx.nd.array([3.0])
    a.attach_grad()
    b.attach_grad()
    with ag.record():
        c = a * b + a  # dc/da = b + 1, dc/db = a
    c.backward()
    assert_almost_equal(a.grad, np.array([4.0], np.float32))
    assert_almost_equal(b.grad, np.array([2.0], np.float32))


def test_grad_add_req():
    x = mx.nd.array([1.0, 2.0])
    x.attach_grad(grad_req="add")
    for _ in range(3):
        with ag.record():
            y = x.sum()
        y.backward()
    assert_almost_equal(x.grad, np.full(2, 3.0, np.float32))


def test_detach_and_stop_gradient():
    x = mx.nd.array([2.0])
    x.attach_grad()
    with ag.record():
        y = x * x
        z = mx.nd.BlockGrad(y) + x
    z.backward()
    assert_almost_equal(x.grad, np.array([1.0], np.float32))


def test_is_recording_training():
    assert not ag.is_recording()
    assert not ag.is_training()
    with ag.record():
        assert ag.is_recording()
        assert ag.is_training()
        with ag.pause():
            assert not ag.is_recording()
    with ag.record(train_mode=False):
        assert ag.is_recording()
        assert not ag.is_training()
    with ag.train_mode():
        assert ag.is_training()
    with ag.predict_mode():
        assert not ag.is_training()


def test_no_tape_error():
    x = mx.nd.ones((2,))
    x.attach_grad()
    y = x * 2  # outside record
    with pytest.raises(mx.MXNetError):
        y.backward()


def test_grad_function():
    x2 = mx.nd.array([1.0, 2.0, 3.0])
    x2.attach_grad()
    with ag.record():
        y = mx.nd.sum(x2 * x2 * x2)
    grads = ag.grad(y, [x2])
    assert_almost_equal(grads[0], 3 * x2.asnumpy() ** 2, rtol=1e-4)


def test_custom_function():
    class Sigmoid(ag.Function):
        def forward(self, x):
            y = mx.nd.sigmoid(x)
            self.save_for_backward(y)
            return y

        def backward(self, dy):
            y, = self.saved_tensors
            return dy * y * (1 - y)

    f = Sigmoid()
    x = mx.nd.array([0.0, 1.0, -1.0])
    x.attach_grad()
    with ag.record():
        y = f(x)
    y.backward()
    s = 1 / (1 + np.exp(-x.asnumpy()))
    assert_almost_equal(x.grad, s * (1 - s), rtol=1e-4)


def test_nn_layer_grads():
    # conv + pooling + fc chained, numeric sanity via finite differences
    x_np = np.random.uniform(-1, 1, (2, 3, 8, 8)).astype(np.float32)
    w_np = np.random.uniform(-0.5, 0.5, (4, 3, 3, 3)).astype(np.float32)
    x = mx.nd.array(x_np)
    w = mx.nd.array(w_np)
    x.attach_grad()
    w.attach_grad()
    with ag.record():
        y = mx.nd.Convolution(x, w, kernel=(3, 3), num_filter=4, no_bias=True)
        z = mx.nd.relu(y).sum()
    z.backward()
    # finite diff on one weight element
    eps = 1e-2
    w_pert = w_np.copy()
    w_pert[0, 0, 0, 0] += eps
    z1 = np.maximum(
        mx.nd.Convolution(mx.nd.array(x_np), mx.nd.array(w_pert), kernel=(3, 3),
                          num_filter=4, no_bias=True).asnumpy(), 0).sum()
    w_pert[0, 0, 0, 0] -= 2 * eps
    z2 = np.maximum(
        mx.nd.Convolution(mx.nd.array(x_np), mx.nd.array(w_pert), kernel=(3, 3),
                          num_filter=4, no_bias=True).asnumpy(), 0).sum()
    fd = (z1 - z2) / (2 * eps)
    assert abs(w.grad.asnumpy()[0, 0, 0, 0] - fd) < 5e-2


def test_retain_graph():
    x = mx.nd.array([3.0])
    x.attach_grad()
    with ag.record():
        y = x * x
    y.backward(retain_graph=True)
    g1 = x.grad.asnumpy().copy()
    y.backward()
    assert_almost_equal(x.grad, g1)  # write req overwrites


def test_mark_variables():
    x = mx.nd.array([2.0])
    g = mx.nd.zeros((1,))
    ag.mark_variables([x], [g])
    with ag.record():
        y = x * 5
    y.backward()
    assert_almost_equal(g, np.array([5.0], np.float32))


def test_create_graph_second_order():
    # d/dx (3x^2)^2 path: y = x^3, dy = 3x^2 (taped), z = sum(dy^2) = 9x^4,
    # dz/dx = 36x^3
    x = mx.nd.array([1.0, 2.0, 3.0])
    x.attach_grad()
    with ag.record():
        y = x * x * x
        dy = ag.grad(y, x, create_graph=True)
        z = (dy * dy).sum()
    z.backward()
    xv = np.array([1.0, 2.0, 3.0])
    assert_almost_equal(dy, 3 * xv ** 2)
    assert_almost_equal(x.grad, 36 * xv ** 3)


def test_create_graph_third_order():
    # y = e^x sin x: y' = e^x(sin+cos), y'' = 2 e^x cos,
    # y''' = 2 e^x (cos - sin)
    x = mx.nd.array([0.5, -1.0])
    x.attach_grad()
    with ag.record():
        y = mx.nd.exp(x) * mx.nd.sin(x)
        g1 = ag.grad(y, x, create_graph=True)
        g2 = ag.grad(g1, x, create_graph=True)
        g3 = ag.grad(g2, x)
    xv = np.array([0.5, -1.0])
    assert np.allclose(g1.asnumpy(), np.exp(xv) * (np.sin(xv) + np.cos(xv)),
                       atol=1e-5)
    assert np.allclose(g2.asnumpy(), 2 * np.exp(xv) * np.cos(xv), atol=1e-5)
    assert np.allclose(g3.asnumpy(), 2 * np.exp(xv) * (np.cos(xv) - np.sin(xv)),
                       atol=1e-5)


def test_create_graph_matches_finite_differences():
    # gradient-penalty shape: d/dw ||d loss/d w||^2 vs central differences
    rng = np.random.RandomState(7)
    wv0 = rng.rand(4, 4).astype(np.float32)
    vv = rng.rand(4, 1).astype(np.float32)
    v = mx.nd.array(vv)

    def loss_grad_at(wv):
        wnd = mx.nd.array(wv)
        wnd.attach_grad()
        with ag.record():
            l = mx.nd.tanh(mx.nd.dot(wnd, v)).sum()
        l.backward()
        return wnd.grad.asnumpy()

    w = mx.nd.array(wv0)
    w.attach_grad()
    with ag.record():
        loss = mx.nd.tanh(mx.nd.dot(w, v)).sum()
        gw = ag.grad(loss, w, create_graph=True)
        gnorm = (gw * gw).sum()
    gnorm.backward()
    analytic = w.grad.asnumpy()
    eps = 1e-3
    num = np.zeros_like(analytic)
    for i in range(4):
        for j in range(4):
            wp = wv0.copy()
            wp[i, j] += eps
            wm = wv0.copy()
            wm[i, j] -= eps
            num[i, j] = ((loss_grad_at(wp) ** 2).sum()
                         - (loss_grad_at(wm) ** 2).sum()) / (2 * eps)
    assert np.abs(analytic - num).max() < 1e-2


def test_create_graph_second_order_after_mutation():
    """The replay node must snapshot record-time buffers too: second-order
    grads after in-place mutation must reflect the RECORDED values."""
    x = mx.nd.array([2.0])
    x.attach_grad()
    with ag.record():
        y = x * x * x          # dy/dx = 3x^2, d2y/dx2 = 6x
        g1 = ag.grad(y, x, create_graph=True)
    x[:] = 100.0               # mutate between the two grad calls
    g2 = ag.grad(g1, x)
    np.testing.assert_allclose(g2.asnumpy(), [12.0], rtol=1e-6)
