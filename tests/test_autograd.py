"""Autograd tests (modeled on reference tests/python/unittest/test_autograd.py)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd as ag
from mxnet_tpu.test_utils import assert_almost_equal


def test_simple_grad():
    x = mx.nd.array([1.0, 2.0, 3.0])
    x.attach_grad()
    with ag.record():
        y = (x * x).sum()
    y.backward()
    assert_almost_equal(x.grad, 2 * x.asnumpy())


def test_chain_rule():
    x = mx.nd.array([[1.0, 2.0], [3.0, 4.0]])
    x.attach_grad()
    with ag.record():
        y = mx.nd.exp(mx.nd.log(x) * 2)  # = x^2
    y.backward()
    assert_almost_equal(x.grad, 2 * x.asnumpy(), rtol=1e-4)


def test_head_gradient():
    x = mx.nd.array([1.0, 2.0])
    x.attach_grad()
    with ag.record():
        y = x * 3
    y.backward(mx.nd.array([10.0, 100.0]))
    assert_almost_equal(x.grad, np.array([30.0, 300.0], np.float32))


def test_multiple_inputs_and_reuse():
    a = mx.nd.array([2.0])
    b = mx.nd.array([3.0])
    a.attach_grad()
    b.attach_grad()
    with ag.record():
        c = a * b + a  # dc/da = b + 1, dc/db = a
    c.backward()
    assert_almost_equal(a.grad, np.array([4.0], np.float32))
    assert_almost_equal(b.grad, np.array([2.0], np.float32))


def test_grad_add_req():
    x = mx.nd.array([1.0, 2.0])
    x.attach_grad(grad_req="add")
    for _ in range(3):
        with ag.record():
            y = x.sum()
        y.backward()
    assert_almost_equal(x.grad, np.full(2, 3.0, np.float32))


def test_detach_and_stop_gradient():
    x = mx.nd.array([2.0])
    x.attach_grad()
    with ag.record():
        y = x * x
        z = mx.nd.BlockGrad(y) + x
    z.backward()
    assert_almost_equal(x.grad, np.array([1.0], np.float32))


def test_is_recording_training():
    assert not ag.is_recording()
    assert not ag.is_training()
    with ag.record():
        assert ag.is_recording()
        assert ag.is_training()
        with ag.pause():
            assert not ag.is_recording()
    with ag.record(train_mode=False):
        assert ag.is_recording()
        assert not ag.is_training()
    with ag.train_mode():
        assert ag.is_training()
    with ag.predict_mode():
        assert not ag.is_training()


def test_no_tape_error():
    x = mx.nd.ones((2,))
    x.attach_grad()
    y = x * 2  # outside record
    with pytest.raises(mx.MXNetError):
        y.backward()


def test_grad_function():
    x2 = mx.nd.array([1.0, 2.0, 3.0])
    x2.attach_grad()
    with ag.record():
        y = mx.nd.sum(x2 * x2 * x2)
    grads = ag.grad(y, [x2])
    assert_almost_equal(grads[0], 3 * x2.asnumpy() ** 2, rtol=1e-4)


def test_custom_function():
    class Sigmoid(ag.Function):
        def forward(self, x):
            y = mx.nd.sigmoid(x)
            self.save_for_backward(y)
            return y

        def backward(self, dy):
            y, = self.saved_tensors
            return dy * y * (1 - y)

    f = Sigmoid()
    x = mx.nd.array([0.0, 1.0, -1.0])
    x.attach_grad()
    with ag.record():
        y = f(x)
    y.backward()
    s = 1 / (1 + np.exp(-x.asnumpy()))
    assert_almost_equal(x.grad, s * (1 - s), rtol=1e-4)


def test_nn_layer_grads():
    # conv + pooling + fc chained, numeric sanity via finite differences
    x_np = np.random.uniform(-1, 1, (2, 3, 8, 8)).astype(np.float32)
    w_np = np.random.uniform(-0.5, 0.5, (4, 3, 3, 3)).astype(np.float32)
    x = mx.nd.array(x_np)
    w = mx.nd.array(w_np)
    x.attach_grad()
    w.attach_grad()
    with ag.record():
        y = mx.nd.Convolution(x, w, kernel=(3, 3), num_filter=4, no_bias=True)
        z = mx.nd.relu(y).sum()
    z.backward()
    # finite diff on one weight element
    eps = 1e-2
    w_pert = w_np.copy()
    w_pert[0, 0, 0, 0] += eps
    z1 = np.maximum(
        mx.nd.Convolution(mx.nd.array(x_np), mx.nd.array(w_pert), kernel=(3, 3),
                          num_filter=4, no_bias=True).asnumpy(), 0).sum()
    w_pert[0, 0, 0, 0] -= 2 * eps
    z2 = np.maximum(
        mx.nd.Convolution(mx.nd.array(x_np), mx.nd.array(w_pert), kernel=(3, 3),
                          num_filter=4, no_bias=True).asnumpy(), 0).sum()
    fd = (z1 - z2) / (2 * eps)
    assert abs(w.grad.asnumpy()[0, 0, 0, 0] - fd) < 5e-2


def test_retain_graph():
    x = mx.nd.array([3.0])
    x.attach_grad()
    with ag.record():
        y = x * x
    y.backward(retain_graph=True)
    g1 = x.grad.asnumpy().copy()
    y.backward()
    assert_almost_equal(x.grad, g1)  # write req overwrites


def test_mark_variables():
    x = mx.nd.array([2.0])
    g = mx.nd.zeros((1,))
    ag.mark_variables([x], [g])
    with ag.record():
        y = x * 5
    y.backward()
    assert_almost_equal(g, np.array([5.0], np.float32))
