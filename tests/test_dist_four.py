"""4-process distributed matrix: dist_sync + 2-bit compression + a dead
worker among four (reference CI runs multi-node semantics on one machine,
ci/docker/runtime_functions.sh:551-553; round-4 suites stopped at 2
processes).
"""
import os
import re
import subprocess
import sys

import pytest

import launchutil

pytestmark = pytest.mark.launched

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TOOLS = os.path.join(REPO, "tools")


_free_port = launchutil.free_port


def test_compressed_dist_sync_four_workers(tmp_path):
    """4 workers, 2-bit compressed allreduce: codes are the collective
    operand (wire ~ dense/16 on every rank) and the 4-way sum is right."""
    worker = tmp_path / "worker.py"
    worker.write_text(
        "import os\n"
        "os.environ.setdefault('PALLAS_AXON_POOL_IPS', '')\n"
        "import numpy as np\n"
        "import mxnet_tpu as mx\n"
        "from mxnet_tpu.parallel import dist\n"
        "dist.init()\n"
        "kv = mx.kv.create('dist_sync')\n"
        "assert kv.num_workers == 4, kv.num_workers\n"
        "kv.set_gradient_compression({'type': '2bit', 'threshold': 0.5})\n"
        "rank = kv.rank\n"
        "kv.init('w', mx.nd.zeros((64, 64)))\n"
        "# ranks 0,1 push +0.6; ranks 2,3 push -0.6 -> quantized sum 0\n"
        "g = mx.nd.ones((64, 64)) * (0.6 if rank < 2 else -0.6)\n"
        "kv.push('w', g)\n"
        "out = mx.nd.zeros((64, 64))\n"
        "kv.pull('w', out=out)\n"
        "np.testing.assert_allclose(out.asnumpy(), 0.0, atol=1e-6)\n"
        "wire = kv._last_wire_bytes\n"
        "dense = kv._last_dense_bytes\n"
        "assert wire * 15 <= dense, (wire, dense)\n"
        "print('WIRE4 %d DENSE %d RATIO %.1f OK' % (wire, dense,\n"
        "      dense / wire))\n"
        "# one-sided push: only rank 0 has signal; 4-way mean of the\n"
        "# quantized codes (+0.5, 0, 0, 0) keeps direction\n"
        "g2 = mx.nd.ones((64, 64)) * (0.7 if rank == 0 else 0.0)\n"
        "kv.push('w', g2)\n"
        "kv.pull('w', out=out)\n"
        "assert out.asnumpy().mean() > 0.0\n"
        "print('DIST4', rank, 'OK')\n")
    env = dict(os.environ, PYTHONPATH=REPO)
    env.pop("JAX_PLATFORMS", None)
    r = subprocess.run(
        [sys.executable, os.path.join(TOOLS, "launch.py"), "-n", "4",
         "--port", str(_free_port()), "--", sys.executable, str(worker)],
        capture_output=True, text=True, env=env, timeout=600)
    assert r.returncode == 0, r.stderr[-3000:] + r.stdout[-2000:]
    assert r.stdout.count("OK") == 8
    for m in re.finditer(r"RATIO ([\d.]+)", r.stdout):
        assert float(m.group(1)) >= 15.0


SURVIVOR = r"""
import sys, time
from mxnet_tpu.parallel import dist
# recoverable: a dying peer must surface through get_num_dead_node, not
# as a coordination-service error broadcast that aborts the survivors
dist.init(sys.argv[1], 4, int(sys.argv[2]), recoverable=True)
dist.stop_heartbeat(); dist.start_heartbeat(interval=0.2)
import mxnet_tpu as mx
kv = mx.kv.create("dist_sync")
deadline = time.time() + 60
while kv.get_num_dead_node(timeout=60) != 0:
    if time.time() > deadline:
        print("PEERS NEVER BEAT"); sys.exit(2)
    time.sleep(0.2)
print("ALL 4 ALIVE", flush=True)
deadline = time.time() + 60
while True:
    dead = kv.get_num_dead_node(timeout=1.0)
    if dead == 1:
        break
    if dead > 1 or time.time() > deadline:
        print("WRONG DEAD COUNT", dead); sys.exit(3)
    time.sleep(0.3)
# stability: the count must stay exactly 1 (three live peers keep beating)
time.sleep(1.0)
dead = kv.get_num_dead_node(timeout=1.0)
if dead != 1:
    print("UNSTABLE DEAD COUNT", dead); sys.exit(4)
print("DEAD NODES 1 OF 4", flush=True)
import os
os._exit(0)  # skip jax's shutdown barrier (one peer is gone)
"""

VICTIM = r"""
import sys, time
from mxnet_tpu.parallel import dist
dist.init(sys.argv[1], 4, int(sys.argv[2]), recoverable=True)
dist.stop_heartbeat(); dist.start_heartbeat(interval=0.2)
time.sleep(1.5)
import os
os._exit(0)  # die without cleanup, like a crashed worker
"""


def test_one_dead_of_four_detected(tmp_path):
    """Ranks 0-2 survive, rank 3 dies: survivors converge on
    get_num_dead_node() == 1 and hold it (no over-count).

    Platform caveat (jax 0.9): a client's abrupt death resets its
    PollForError stream and the coordination service may broadcast a
    fatal error that kills NON-coordinator clients before our heartbeat
    layer reports — even with the recoverable flag.  So the coordinator-
    side survivor (rank 0, hosts the service in-process) must fully
    observe the death; ranks 1-2 must either observe it or have been
    taken down by that documented service broadcast, nothing else."""
    coord = "127.0.0.1:%d" % _free_port()
    sv = tmp_path / "survivor.py"
    vc = tmp_path / "victim.py"
    sv.write_text(SURVIVOR)
    vc.write_text(VICTIM)
    env = dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu",
               PALLAS_AXON_POOL_IPS="")
    procs = [subprocess.Popen(
        [sys.executable, str(sv if rank < 3 else vc), coord, str(rank)],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True, env=env)
        for rank in range(4)]
    outs, errs = [], []
    for out, err in launchutil.communicate_all(procs, timeout=180):
        outs.append(out)
        errs.append(err)
    assert procs[0].returncode == 0, (outs[0], errs[0][-2000:])
    assert "ALL 4 ALIVE" in outs[0]
    assert "DEAD NODES 1 OF 4" in outs[0]
    observers = 0
    for rank in (1, 2):
        if procs[rank].returncode == 0:
            assert "DEAD NODES 1 OF 4" in outs[rank]
            observers += 1
        else:
            assert ("PollForError" in errs[rank]
                    or "Connection reset" in errs[rank]), (
                rank, outs[rank], errs[rank][-2000:])
    # the recoverable flag must keep the broadcast from killing EVERY
    # non-coordinator — at least one must live to report the count (a
    # full regression of recoverable init would fail here)
    assert observers >= 1, [p.returncode for p in procs]
