"""Pipeline (pp) and expert (ep) parallelism vs dense references on the
virtual 8-device mesh — new capabilities beyond the reference
(SURVEY.md §2.8 lists both as absent)."""
import numpy as np
import pytest
import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from mxnet_tpu.parallel.pipeline import pipeline_apply, stack_stage_params
from mxnet_tpu.parallel.moe import moe_apply, stack_expert_params


@pytest.fixture
def pp_mesh():
    devs = jax.devices()
    if len(devs) < 4:
        pytest.skip("needs 4 devices")
    return Mesh(np.asarray(devs[:4]), ("pp",))


def _stages(rng, n, D):
    return [{"w": jnp.asarray(rng.randn(D, D).astype("f") * 0.3),
             "b": jnp.asarray(rng.randn(D).astype("f") * 0.1)}
            for _ in range(n)]


def _stage_fn(p, h):
    return jnp.tanh(h @ p["w"] + p["b"])


def test_pipeline_matches_dense(pp_mesh):
    rng = np.random.RandomState(0)
    D = 6
    stages = _stages(rng, 4, D)
    stacked = stack_stage_params(stages)
    x = jnp.asarray(rng.randn(8, 3, D).astype("f"))
    with pp_mesh:
        out = pipeline_apply(_stage_fn, stacked, x, pp_mesh, "pp")
    ref = x
    for p in stages:
        ref = _stage_fn(p, ref)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_pipeline_grads_reach_every_stage(pp_mesh):
    rng = np.random.RandomState(1)
    D = 4
    stacked = stack_stage_params(_stages(rng, 4, D))
    x = jnp.asarray(rng.randn(6, 2, D).astype("f"))

    def loss(stacked, x):
        with pp_mesh:
            o = pipeline_apply(_stage_fn, stacked, x, pp_mesh, "pp")
        return jnp.mean(o * o)

    g = jax.grad(loss)(stacked, x)
    norms = np.abs(np.asarray(g["w"])).sum(axis=(1, 2))
    assert (norms > 0).all()


def _expert_fn(p, t):
    return jax.nn.relu(t @ p["w1"]) @ p["w2"]


@pytest.fixture
def ep_mesh():
    devs = jax.devices()
    if len(devs) < 4:
        pytest.skip("needs 4 devices")
    return Mesh(np.asarray(devs[:4]), ("ep",))


def test_moe_matches_dense_at_full_capacity(ep_mesh):
    rng = np.random.RandomState(2)
    N, D, E, K = 16, 8, 8, 2
    experts = [{"w1": jnp.asarray(rng.randn(D, 16).astype("f") * 0.3),
                "w2": jnp.asarray(rng.randn(16, D).astype("f") * 0.3)}
               for _ in range(E)]
    stacked = stack_expert_params(experts)
    gate_w = jnp.asarray(rng.randn(D, E).astype("f"))
    x = jnp.asarray(rng.randn(N, D).astype("f"))
    with ep_mesh:
        out = moe_apply(_expert_fn, stacked, gate_w, x, ep_mesh,
                        top_k=K, capacity_factor=8.0)
    gates = jax.nn.softmax(x @ gate_w, axis=-1)
    topv, topi = jax.lax.top_k(gates, K)
    ref = np.zeros_like(np.asarray(x))
    for n in range(N):
        for k in range(K):
            e = int(topi[n, k])
            ref[n] += float(topv[n, k]) * np.asarray(
                _expert_fn(experts[e], x[n:n + 1]))[0]
    np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-4, atol=1e-5)


def test_moe_capacity_drops_and_grads(ep_mesh):
    rng = np.random.RandomState(3)
    N, D, E, K = 16, 8, 8, 2
    stacked = stack_expert_params(
        [{"w1": jnp.asarray(rng.randn(D, 16).astype("f") * 0.3),
          "w2": jnp.asarray(rng.randn(16, D).astype("f") * 0.3)}
         for _ in range(E)])
    gate_w = jnp.asarray(rng.randn(D, E).astype("f"))
    x = jnp.asarray(rng.randn(N, D).astype("f"))

    def loss(stacked, gw, x):
        with ep_mesh:
            return jnp.mean(moe_apply(_expert_fn, stacked, gw, x, ep_mesh,
                                      top_k=K, capacity_factor=1.0) ** 2)

    l, g = jax.value_and_grad(loss)(stacked, gate_w, x)
    assert np.isfinite(float(l))
    assert all(np.isfinite(np.asarray(v)).all()
               for v in jax.tree_util.tree_leaves(g))


def test_shape_validation(pp_mesh):
    rng = np.random.RandomState(4)
    wrong = stack_stage_params(_stages(rng, 8, 4))  # 8 stages, 4 ranks
    x = jnp.ones((4, 2, 4))
    with pytest.raises(ValueError, match="leading axis"):
        with pp_mesh:
            pipeline_apply(_stage_fn, wrong, x, pp_mesh, "pp")
    experts = stack_expert_params(
        [{"w1": jnp.ones((4, 4)), "w2": jnp.ones((4, 4))}
         for _ in range(4)])
    gate_w = jnp.ones((4, 8))  # routes to 8 experts but only 4 stacked
    with pytest.raises(ValueError, match="leading axis"):
        with pp_mesh:
            moe_apply(_expert_fn, experts, gate_w, jnp.ones((4, 4)),
                      pp_mesh, axis="pp", top_k=2)


# --- user-facing *TrainStep front doors (VERDICT r3 next #6) --------------

def test_pipeline_train_step_front_door(pp_mesh):
    """PipelineTrainStep: loss decreases and every stage's params move."""
    from mxnet_tpu.parallel import PipelineTrainStep, sgd_update
    rng = np.random.RandomState(0)
    D = 4
    step = PipelineTrainStep(_stage_fn, lambda o: jnp.mean(o * o),
                             sgd_update(0.5), pp_mesh, "pp",
                             donate_params=False)
    stages = step.place_stages(_stages(rng, 4, D))
    xs = jnp.asarray(rng.randn(6, 2, D).astype("f"))
    l0, p1, _ = step(stages, None, xs)
    l1, p2, _ = step(p1, None, xs)
    assert np.isfinite(float(l0)) and np.isfinite(float(l1))
    assert float(l1) < float(l0)
    for a, b in zip(jax.tree_util.tree_leaves(stages),
                    jax.tree_util.tree_leaves(p1)):
        assert not np.allclose(np.asarray(a), np.asarray(b))


def test_moe_train_step_front_door(ep_mesh):
    """MoETrainStep: experts and gate both receive gradient."""
    from mxnet_tpu.parallel import MoETrainStep, sgd_update
    rng = np.random.RandomState(1)
    D, E = 4, 8
    step = MoETrainStep(lambda p, t: t @ p["w"],
                        lambda o: jnp.mean(o * o), sgd_update(0.5),
                        ep_mesh, "ep", top_k=2, donate_params=False)
    experts = step.place_experts(
        [{"w": jnp.asarray(rng.randn(D, D).astype("f") * 0.3)}
         for _ in range(E)])
    gate_w = jnp.asarray(rng.randn(D, E).astype("f") * 0.1)
    x = jnp.asarray(rng.randn(16, D).astype("f"))
    l0, (e1, g1), _ = step((experts, gate_w), None, x)
    assert np.isfinite(float(l0))
    assert not np.allclose(np.asarray(gate_w), np.asarray(g1))
    assert not np.allclose(
        np.asarray(jax.tree_util.tree_leaves(experts)[0]),
        np.asarray(jax.tree_util.tree_leaves(e1)[0]))


def test_sharded_train_step_tp_matches_single_device():
    """ShardedTrainStep with Megatron-style 2-way tp == unsharded math."""
    from mxnet_tpu.parallel import ShardedTrainStep, sgd_update
    from jax.sharding import NamedSharding, PartitionSpec as P
    devs = jax.devices()
    if len(devs) < 4:
        pytest.skip("needs 4 devices")
    mesh = Mesh(np.asarray(devs[:4]).reshape(2, 2), ("dp", "tp"))
    rng = np.random.RandomState(2)
    w1 = rng.randn(8, 16).astype("f") * 0.3     # (in, hidden)
    w2 = rng.randn(16, 4).astype("f") * 0.3     # (hidden, out)
    x = rng.randn(4, 8).astype("f")
    y = rng.randn(4, 4).astype("f")

    def loss_fn(params, x, y):
        h = jnp.tanh(x @ params["w1"])
        out = h @ params["w2"]
        return jnp.mean((out - y) ** 2)

    spec = {"w1": P(None, "tp"), "w2": P("tp", None)}
    step = ShardedTrainStep(loss_fn, sgd_update(0.1), mesh, spec,
                            donate_params=False)
    params = step.place_params({"w1": jnp.asarray(w1), "w2": jnp.asarray(w2)})
    xb, yb = step.place_batch(x, y)
    loss, new_params, _ = step(params, None, xb, yb)

    # single-device oracle
    import numpy as _np
    p0 = {"w1": jnp.asarray(w1), "w2": jnp.asarray(w2)}
    l_ref, g_ref = jax.value_and_grad(loss_fn)(p0, jnp.asarray(x),
                                               jnp.asarray(y))
    assert abs(float(loss) - float(l_ref)) < 1e-5
    for k in ("w1", "w2"):
        ref = _np.asarray(p0[k]) - 0.1 * _np.asarray(g_ref[k])
        assert _np.allclose(_np.asarray(new_params[k]), ref,
                            rtol=1e-4, atol=1e-5)
