"""Memory anatomy (`mxnet_tpu/memprof.py`): timeline scope attribution
sums to live bytes, the leak sentinel (synthetic growing buffers →
run_anomalies_total + flight-recorder dump), chaos-injected OOM
postmortem round-trip with the enriched re-raise, admission
accept/reject (incl. the serving engine's model-load gate and the
/healthz headroom triple), the report CLI with host-dir merge and
cross-host skew, and the zero-extra-compile proof."""
import io as _io
import json
import os
import time

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import chaos, compiled, memprof, runprof, telemetry, \
    xla_stats

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture
def fresh():
    """Clean registry + reset the memory tracker, ledger, and run
    ledger (the leak sentinel books its trips through runprof)."""
    telemetry.reset()
    xla_stats.reset()
    memprof.reset()
    runprof.reset()
    yield
    runprof.reset()
    memprof.reset()
    xla_stats.reset()
    telemetry.reset()


def _device_put(arr):
    import jax
    return jax.device_put(arr)


# ---------------------------------------------------------------------------
# HBM timeline: sampling, attribution invariant, gauges, throttling
# ---------------------------------------------------------------------------

def test_attribution_sums_to_live_bytes(fresh):
    keep = [_device_put(np.ones((64, 64), np.float32))
            for _ in range(3)]                     # ≥ 48 KiB live
    xla_stats.ledger_set("model", "params", 16384)
    xla_stats.ledger_set("trainer", "optimizer", 4096)
    rec = memprof.sample("test", force=True)
    assert rec is not None and rec["live_bytes"] >= 3 * 64 * 64 * 4
    att = memprof.attribution(rec["live_bytes"])
    resident = sum(att[s] for s in memprof.RESIDENT_SECTIONS)
    # the invariant the waterfall is built on: resident + residual
    # tile the live bytes exactly; nothing double-books
    assert resident + att["residual"] == rec["live_bytes"]
    assert att["params"] == 16384           # ledger-backed scope claimed
    assert att["optimizer"] == 4096
    assert set(att) == set(memprof.ATTRIBUTION_SCOPES)
    del keep


def test_sample_publishes_gauges_and_span(fresh, tmp_path):
    telemetry.configure(str(tmp_path))
    try:
        keep = _device_put(np.ones((32, 32), np.float32))
        rec = memprof.sample("unit", force=True)
        g = telemetry.get_metric("memory_bytes", device="all",
                                 scope="residual")
        assert g is not None and g.read() > 0
        dev = rec["devices"][0]["device"]
        in_use = telemetry.get_metric("memory_bytes", device=dev,
                                      scope="in_use")
        assert in_use is not None
        path = os.path.join(
            str(tmp_path), "events_host%d_pid%d.jsonl"
            % (telemetry.host_id(), os.getpid()))
        events = telemetry.read_events(path)
        spans = [e for e in events if e.get("name") == "mem.sample"]
        assert spans and spans[0]["args"]["site"] == "unit"
        del keep
    finally:
        telemetry.configure(None)


def test_sample_throttle(fresh, monkeypatch):
    monkeypatch.setenv("MXNET_MEMPROF_SAMPLE_EVERY", "4")
    tr = memprof.MemTracker()      # private: no gauges, no spans
    taken = [tr.sample("poll") for _ in range(8)]
    assert sum(1 for r in taken if r is not None) == 2   # polls 1 and 5
    monkeypatch.setenv("MXNET_MEMPROF_SAMPLE_EVERY", "0")
    tr2 = memprof.MemTracker()
    assert all(tr2.sample("poll") is None for _ in range(5))
    assert tr2.sample("poll", force=True) is not None   # force bypasses


def test_kill_switch_disables_everything(fresh, monkeypatch):
    monkeypatch.setenv("MXNET_MEMPROF", "0")
    monkeypatch.setenv("MXNET_MEM_LIMIT_BYTES", "1")
    assert memprof.sample("x", force=True) is None
    assert memprof.maybe_oom_error(
        RuntimeError("RESOURCE_EXHAUSTED: Out of memory")) is None
    dec = memprof.admit(1 << 40, what="disabled")    # admits: layer off
    assert dec["admitted"]
    with chaos.armed("memory.oom"):
        memprof.on_dispatch("test.site")             # no injection either


def test_device_memory_cpu_fallback(fresh):
    """Satellite (a): on CPU the PJRT allocator reports zeros —
    device_memory() must fall back to live-buffer sums per device."""
    keep = _device_put(np.ones((256, 256), np.float32))
    recs = xla_stats.device_memory()
    assert recs
    assert any(r["bytes_in_use"] > 0 for r in recs)
    assert all(r["peak_bytes_in_use"] >= r["bytes_in_use"] for r in recs)
    if all(r.get("estimated") for r in recs):      # CPU backend path
        total = sum(r["bytes_in_use"] for r in recs)
        assert total >= 256 * 256 * 4
    del keep


# ---------------------------------------------------------------------------
# leak sentinel
# ---------------------------------------------------------------------------

def test_leak_window_logic_unit(fresh, monkeypatch):
    """The sentinel's three gates, on synthetic ring entries: ledger-
    explained growth and non-monotonic growth do NOT trip."""
    monkeypatch.setenv("MXNET_MEMPROF_WINDOW", "4")
    step = memprof.MemTracker.LEAK_MIN_BYTES

    def fill(tr, live_seq, ledger_seq):
        for lv, ld in zip(live_seq, ledger_seq):
            tr._ring.append({"time": 0.0, "live_bytes": lv,
                             "ledger_bytes": ld, "census": {}})

    tr = memprof.MemTracker()
    fill(tr, [0, step, 2 * step, 3 * step], [0, step, 2 * step, 3 * step])
    assert tr._check_leak_locked() is None       # ledger explains it
    tr = memprof.MemTracker()
    fill(tr, [0, 2 * step, step, 3 * step], [0, 0, 0, 0])
    assert tr._check_leak_locked() is None       # not monotonic
    tr = memprof.MemTracker()
    fill(tr, [0, step, 2 * step, 3 * step], [0, 0, 0, 0])
    trip = tr._check_leak_locked()
    assert trip is not None and trip[0] == 3 * step
    assert tr._leak_trips == 1
    assert len(tr._ring) == 0                    # fresh window after trip


def test_leak_sentinel_trips_anomaly_and_flight_dump(
        fresh, tmp_path, monkeypatch):
    monkeypatch.setenv("MXNET_MEMPROF_WINDOW", "3")
    telemetry.configure(str(tmp_path))
    leaked = []
    try:
        for _ in range(12):
            # 64 KiB of fresh, never-released device buffers per sample
            leaked.append(_device_put(np.ones(16384, np.float32)))
            memprof.sample("leak-test", force=True)
            c = telemetry.get_metric("run_anomalies_total",
                                     kind="memory_leak")
            if c is not None and c.value >= 1:
                break
        assert c is not None and c.value >= 1
        snap = memprof.snapshot()
        assert snap["leak_trips"] >= 1
        detail = snap["last_leak"]["detail"]
        assert "top growers" in detail and "float32" in detail
        dump = os.path.join(str(tmp_path), "flightrecorder-host%d.json"
                            % telemetry.host_id())
        assert os.path.exists(dump)
        with open(dump) as fh:
            doc = json.load(fh)
        assert doc["reason"] == "runprof.memory_leak"
    finally:
        telemetry.configure(None)
        del leaked


# ---------------------------------------------------------------------------
# OOM forensics
# ---------------------------------------------------------------------------

def test_looks_like_oom_and_parse_requested_bytes():
    assert memprof.looks_like_oom(
        RuntimeError("RESOURCE_EXHAUSTED: Out of memory while trying "
                     "to allocate 40000000000 bytes."))
    assert memprof.looks_like_oom(ValueError("xla: Out of memory"))
    assert not memprof.looks_like_oom(TypeError("bad dtype"))
    assert memprof.parse_requested_bytes(
        "while trying to allocate 40000000000 bytes.") == 40000000000
    assert memprof.parse_requested_bytes(
        "Attempting to allocate 37.25G") == int(37.25 * (1 << 30))
    assert memprof.parse_requested_bytes(
        "allocation of 1,048,576 bytes failed") == 1048576
    assert memprof.parse_requested_bytes("no numbers here") is None


def test_maybe_oom_error_passthrough(fresh):
    assert memprof.maybe_oom_error(TypeError("not an oom")) is None
    already = memprof.DeviceOOMError("RESOURCE_EXHAUSTED: once")
    assert memprof.maybe_oom_error(already) is None   # no double-wrap


def test_chaos_oom_postmortem_roundtrip(fresh, tmp_path):
    """The acceptance path: a chaos-injected RESOURCE_EXHAUSTED at
    CompiledProgram dispatch produces the oomdump postmortem naming the
    dominant scope, and the re-raised DeviceOOMError carries the
    verdict line."""
    telemetry.configure(str(tmp_path))
    try:
        # make optimizer state the dominant resident scope so the
        # attribution waterfall has an unambiguous verdict to name
        # (a live device buffer must exist for any scope to claim it)
        keep = _device_put(np.ones((64, 64), np.float32))
        xla_stats.ledger_set("trainer", "optimizer", 1 << 60)
        f = compiled.tracked_jit(lambda x: x + 1.0, site="test.memoom")
        x = np.ones((4,), np.float32)
        np.testing.assert_allclose(np.asarray(f(x)), x + 1.0)
        with chaos.armed("memory.oom", value=12345678):
            with pytest.raises(memprof.DeviceOOMError) as ei:
                f(x)
        err = ei.value
        assert err.verdict == "oom-optimizer-heavy"
        assert err.requested_bytes == 12345678
        assert err.site == "test.memoom"
        assert err.hint and "donate" in err.hint
        assert "memprof: oom-optimizer-heavy" in str(err)
        assert "RESOURCE_EXHAUSTED" in str(err)
        assert isinstance(err.__cause__, RuntimeError)
        assert err.dump_path and os.path.exists(err.dump_path)
        assert os.path.basename(err.dump_path).startswith("oomdump_host")
        with open(err.dump_path) as fh:
            doc = json.load(fh)
        assert doc["requested_bytes"] == 12345678
        assert doc["dominant_scope"] == "optimizer"
        assert doc["site"] == "test.memoom"
        assert doc["attribution"]["optimizer"] > 0
        assert isinstance(doc["top_buffers"], list) and doc["top_buffers"]
        assert {"shape", "dtype", "nbytes",
                "sharding"} <= set(doc["top_buffers"][0])
        assert any(w["section"] == "optimizer" for w in doc["ledger"])
        c = telemetry.get_metric("oom_events_total")
        assert c is not None and c.value == 1
        # the sentinel chain also leaves a flight-recorder dump behind
        dump = os.path.join(str(tmp_path), "flightrecorder-host%d.json"
                            % telemetry.host_id())
        assert os.path.exists(dump)
        with open(dump) as fh:
            assert json.load(fh)["reason"] == "memprof.oom"
        # after the chaos trigger expires the program runs normally
        np.testing.assert_allclose(np.asarray(f(x)), x + 1.0)
        del keep
    finally:
        telemetry.configure(None)


# ---------------------------------------------------------------------------
# headroom + admission control
# ---------------------------------------------------------------------------

def test_admit_reject_bumps_counter(fresh, monkeypatch):
    monkeypatch.setenv("MXNET_MEM_LIMIT_BYTES", str(1 << 20))
    monkeypatch.setenv("MXNET_MEM_FRACTION", "0.5")
    with pytest.raises(memprof.MemoryAdmissionError) as ei:
        memprof.admit(1 << 30, what="test load")
    err = ei.value
    assert err.decision["admitted"] is False
    assert err.decision["projected_bytes"] == 1 << 30
    assert err.decision["limit_bytes"] == 1 << 20
    assert "test load" in str(err) and "fsdp" in str(err)
    c = telemetry.get_metric("admission_rejections_total")
    assert c is not None and c.value == 1
    # the /healthz triple reflects the rejection and the tiny budget
    h = memprof.health()
    assert h["admission_rejections_total"] == 1
    assert h["headroom_bytes"] is not None


def test_admit_accepts_without_limit(fresh, monkeypatch):
    monkeypatch.delenv("MXNET_MEM_LIMIT_BYTES", raising=False)
    dec = memprof.admit(123, what="small")
    assert dec["admitted"] and dec["projected_bytes"] == 123
    c = telemetry.get_metric("admission_rejections_total")
    assert c is None or c.value == 0    # registry was reset; no bump


def test_headroom_gauge_scrapes_live(fresh, monkeypatch):
    monkeypatch.setenv("MXNET_MEM_LIMIT_BYTES", str(1 << 40))
    rec = memprof.sample("headroom", force=True)
    dev = rec["devices"][0]["device"]
    g = telemetry.get_metric("memory_headroom_bytes", device=dev)
    assert g is not None
    assert 0 < g.read() <= (1 << 40) * memprof.mem_fraction()
    h = memprof.health()
    assert h["headroom_bytes"] > 0
    assert 0 <= h["peak_fraction"] < 1


IN_DIM = 12


def _mlp():
    data = mx.sym.Variable("data")
    fc1 = mx.sym.FullyConnected(data, num_hidden=8, name="fc1")
    act = mx.sym.Activation(fc1, act_type="relu", name="relu1")
    return mx.sym.FullyConnected(act, num_hidden=3, name="fc2")


def _init_params(net):
    exe = net.simple_bind(mx.cpu(), data=(2, IN_DIM))
    rng = np.random.RandomState(0)
    params = {}
    for name, arr in exe.arg_dict.items():
        if name == "data":
            continue
        arr[:] = (rng.randn(*arr.shape) * 0.1).astype(np.float32)
        params[name] = arr
    return params


def test_serving_admission_gate_and_healthz(fresh, monkeypatch):
    """Satellite (c): the engine consults memprof.admit before model
    load, and stats() (the /healthz payload) carries the headroom
    triple."""
    from mxnet_tpu.serving import EngineConfig, InferenceEngine
    net = _mlp()
    params = _init_params(net)
    monkeypatch.setenv("MXNET_MEM_LIMIT_BYTES", "1")   # 0.9-byte budget
    with pytest.raises(memprof.MemoryAdmissionError):
        InferenceEngine(net.tojson(), dict(params), {"data": (IN_DIM,)},
                        config=EngineConfig(), warmup=False)
    assert telemetry.get_metric("admission_rejections_total").value == 1
    monkeypatch.delenv("MXNET_MEM_LIMIT_BYTES")
    eng = InferenceEngine(net.tojson(), dict(params),
                          {"data": (IN_DIM,)}, config=EngineConfig(),
                          warmup=False)
    try:
        st = eng.stats()
        assert {"headroom_bytes", "peak_fraction",
                "admission_rejections_total"} <= set(st)
        assert st["admission_rejections_total"] == 1
    finally:
        eng.shutdown(drain=False)


# ---------------------------------------------------------------------------
# snapshots, merge, classify, report CLI
# ---------------------------------------------------------------------------

def test_host_snapshot_roundtrip(fresh, tmp_path):
    assert memprof.write_host_snapshot(dir=str(tmp_path)) is None  # empty
    memprof.sample("snap", force=True)
    path = memprof.write_host_snapshot(dir=str(tmp_path))
    assert path and os.path.basename(path).startswith("memprof_host")
    merged = memprof.merge_host_snapshots(str(tmp_path))
    assert list(merged) == [telemetry.host_id()]
    doc = merged[telemetry.host_id()]
    assert doc["samples"] >= 1 and doc["live_bytes"] >= 0
    assert set(doc["attribution"]) == set(memprof.ATTRIBUTION_SCOPES)
    assert doc["timeline"]


def test_classify_verdicts():
    v, hint = memprof.classify({"residual": 60, "params": 40})
    assert v == "activation-heavy" and "scan" in hint
    v, _ = memprof.classify({"optimizer": 45, "params": 55})
    assert v == "opt-heavy"
    v, _ = memprof.classify({"params": 80, "residual": 20})
    assert v == "healthy"
    v, _ = memprof.classify({})
    assert v == "unknown"
    v, _ = memprof.classify({"params": 80, "residual": 20}, leak_trips=2)
    assert v == "leaking"
    v, _ = memprof.classify({"params": 100000, "residual": 0},
                            live_bytes=100000, in_use=200000)
    assert v == "fragmented"
    assert set(memprof.HINTS) == set(memprof.VERDICTS)


def _snapshot_doc(host, peak, att, updated):
    return {"host": host, "pid": 1, "updated": updated, "samples": 4,
            "window": 16, "sample_every": 8,
            "peak_by_device": {"dev:%d" % host: peak},
            "limit_by_device": {}, "live_peak_bytes": peak,
            "leak_trips": 0, "last_leak": None, "oom_dumps": 0,
            "live_bytes": sum(att.get(s, 0)
                              for s in memprof.RESIDENT_SECTIONS
                              + ("residual",)),
            "attribution": att, "peak_hbm_bytes": peak,
            "timeline": [], "admission_rejections": 0}


def test_report_merges_hosts_with_skew(fresh, tmp_path):
    att0 = {"params": 600, "grads": 0, "aux": 0, "optimizer": 100,
            "residual": 300, "xla_temp": 0, "xla_output": 0}
    att1 = {"params": 500, "grads": 0, "aux": 0, "optimizer": 100,
            "residual": 200, "xla_temp": 0, "xla_output": 0}
    now = time.time()
    for host, peak, att in ((0, 100, att0), (1, 200, att1)):
        with open(os.path.join(str(tmp_path),
                               "memprof_host%d_pid1.json" % host),
                  "w") as fh:
            json.dump(_snapshot_doc(host, peak, att, now), fh)
    buf = _io.StringIO()
    assert memprof.report(str(tmp_path), out=buf) == 0
    text = buf.getvalue()
    assert "verdict: healthy" in text
    rec = json.loads(text.strip().splitlines()[-1])
    assert rec["metric"] == "memprof_report"
    assert rec["hosts"] == 2
    assert rec["peak_hbm_bytes"] == 200
    assert rec["peak_skew"] == pytest.approx(0.5)    # (200-100)/200
    assert rec["scopes"]["params"] == 1100           # summed across hosts
    assert rec["verdict"] == "healthy"


def test_report_no_data_exits_nonzero(fresh, tmp_path):
    buf = _io.StringIO()
    assert memprof.report(str(tmp_path), out=buf) == 1
    rec = json.loads(buf.getvalue().strip().splitlines()[-1])
    assert rec["metric"] == "memprof_report"
    assert rec["verdict"] == "unknown"


def test_report_cli_main(fresh, tmp_path, capsys):
    att = {"params": 100, "grads": 0, "aux": 0, "optimizer": 0,
           "residual": 10, "xla_temp": 0, "xla_output": 0}
    with open(os.path.join(str(tmp_path), "memprof_host0_pid1.json"),
              "w") as fh:
        json.dump(_snapshot_doc(0, 110, att, time.time()), fh)
    assert memprof.main(["report", str(tmp_path), "--json"]) == 0
    out = capsys.readouterr().out.strip()
    rec = json.loads(out.splitlines()[-1])
    assert rec["metric"] == "memprof_report"
    assert rec["verdict"] == "healthy"
    assert len(out.splitlines()) == 1                # --json: line only


def test_aggregate_handles_empty_and_single():
    assert memprof.aggregate([]) is None
    agg = memprof.aggregate([_snapshot_doc(0, 50, {"params": 10},
                                           time.time())])
    assert agg["hosts"] == 1 and agg["peak_skew"] == 0.0
    assert agg["peak_hbm_bytes"] == 50


# ---------------------------------------------------------------------------
# the zero-extra-compile proof
# ---------------------------------------------------------------------------

def test_instrumentation_adds_zero_compiles(fresh, monkeypatch):
    monkeypatch.delenv("MXNET_MEM_LIMIT_BYTES", raising=False)
    f = compiled.tracked_jit(lambda x: x * 2.0, site="test.memzc")
    x = np.ones((8,), np.float32)
    f(x)                                   # the one and only compile
    c0 = xla_stats.compile_counts()
    assert c0["compiles"] >= 1
    for _ in range(5):
        memprof.sample("proof", force=True)
    memprof.peak_hbm_bytes()
    memprof.health()
    memprof.admit(123, what="proof")
    memprof.snapshot()
    memprof.attribution()
    buf = _io.StringIO()
    memprof.report(out=buf)
    f(x)                                   # dispatch hook samples again
    c1 = xla_stats.compile_counts()
    assert c1["compiles"] == c0["compiles"]
    assert c1["cache_hits"] >= c0["cache_hits"] + 1
