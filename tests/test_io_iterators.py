"""IO iterator suite (reference tests/python/unittest/test_io.py):
CSVIter, LibSVMIter, MNISTIter, ImageDetRecordIter, NDArrayIter
last-batch modes."""
import gzip
import os
import struct
import time

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import recordio
from mxnet_tpu.image import codec


def test_csv_iter(tmp_path):
    data = np.random.RandomState(0).rand(20, 4).astype("f")
    labels = np.arange(20, dtype="f")
    dpath, lpath = tmp_path / "d.csv", tmp_path / "l.csv"
    np.savetxt(dpath, data, delimiter=",")
    np.savetxt(lpath, labels.reshape(-1, 1), delimiter=",")
    it = mx.io.CSVIter(data_csv=str(dpath), data_shape=(4,),
                       label_csv=str(lpath), batch_size=5)
    got = []
    for batch in it:
        got.append(batch.data[0].asnumpy())
    got = np.concatenate(got)
    np.testing.assert_allclose(got, data, rtol=1e-5)


def test_libsvm_iter(tmp_path):
    path = tmp_path / "d.svm"
    path.write_text("1 0:0.5 3:1.5\n0 1:2.0\n1 2:3.0 3:4.0\n0 0:1.0\n")
    it = mx.io.LibSVMIter(data_libsvm=str(path), data_shape=(4,),
                          batch_size=2)
    rows = []
    labels = []
    for batch in it:
        rows.append(batch.data[0].asnumpy())
        labels.append(batch.label[0].asnumpy())
    rows = np.concatenate(rows)
    labels = np.concatenate(labels)
    np.testing.assert_allclose(rows[0], [0.5, 0, 0, 1.5])
    np.testing.assert_allclose(rows[1], [0, 2.0, 0, 0])
    np.testing.assert_allclose(labels[:4], [1, 0, 1, 0])


def test_mnist_iter(tmp_path):
    """Synthesize idx-ubyte files in the MNIST format."""
    rng = np.random.RandomState(1)
    imgs = (rng.rand(10, 28, 28) * 255).astype(np.uint8)
    labs = rng.randint(0, 10, 10).astype(np.uint8)
    img_path = tmp_path / "images-idx3-ubyte"
    lab_path = tmp_path / "labels-idx1-ubyte"
    with open(img_path, "wb") as f:
        f.write(struct.pack(">iiii", 2051, 10, 28, 28))
        f.write(imgs.tobytes())
    with open(lab_path, "wb") as f:
        f.write(struct.pack(">ii", 2049, 10))
        f.write(labs.tobytes())
    it = mx.io.MNISTIter(image=str(img_path), label=str(lab_path),
                         batch_size=5, flat=False)
    batch = next(iter(it))
    assert batch.data[0].shape == (5, 1, 28, 28)
    # values normalized to [0, 1]
    assert float(batch.data[0].asnumpy().max()) <= 1.0


def test_image_det_record_iter(tmp_path):
    rng = np.random.RandomState(2)
    rec_path = str(tmp_path / "det.rec")
    w = recordio.MXIndexedRecordIO(str(tmp_path / "det.idx"), rec_path, "w")
    for i in range(6):
        img = (rng.rand(20, 24, 3) * 255).astype("uint8")
        nobj = 1 + i % 2
        label = [2, 5] + sum(
            ([float(i % 3), 0.1, 0.2, 0.6, 0.8] for _ in range(nobj)), [])
        header = recordio.IRHeader(0, np.asarray(label, "f"), i, 0)
        w.write_idx(i, recordio.pack_img(header, img))
    w.close()

    it = mx.io.ImageDetRecordIter(path_imgrec=rec_path,
                                  data_shape=(3, 16, 16), batch_size=3)
    seen = 0
    for b in it:
        seen += 1
        assert b.data[0].shape == (3, 3, 16, 16)
        lab = b.label[0].asnumpy()
        assert lab.shape == (3, 12)  # 2 header + 2 objs x 5
        assert (lab[:, 0] == 2).all() and (lab[:, 1] == 5).all()
        # first object's box is valid and normalized
        assert ((lab[:, 3:7] >= -1) & (lab[:, 3:7] <= 1)).all()
    assert seen == 2


def test_ndarray_iter_last_batch_modes():
    X = np.arange(25, dtype="f").reshape(25, 1)
    it = mx.io.NDArrayIter(X, batch_size=10, last_batch_handle="pad")
    batches = list(it)
    assert len(batches) == 3 and batches[-1].pad == 5
    it = mx.io.NDArrayIter(X, batch_size=10, last_batch_handle="discard")
    assert len(list(it)) == 2


def _write_det_rec(tmp_path, n=8):
    rng = np.random.RandomState(4)
    rec_path = str(tmp_path / "dd.rec")
    w = recordio.MXIndexedRecordIO(str(tmp_path / "dd.idx"), rec_path, "w")
    for i in range(n):
        img = (rng.rand(24, 28, 3) * 255).astype("uint8")
        nobj = 1 + i % 3
        label = [2, 5] + sum(
            ([float(i % 4), 0.2, 0.2, 0.7, 0.7] for _ in range(nobj)), [])
        header = recordio.IRHeader(0, np.asarray(label, "f"), i, 0)
        w.write_idx(i, recordio.pack_img(header, img))
    w.close()
    return rec_path


def test_image_det_iter(tmp_path):
    rec_path = _write_det_rec(tmp_path)
    it = mx.image.ImageDetIter(batch_size=4, data_shape=(3, 16, 16),
                               path_imgrec=rec_path)
    assert it.max_objects == 3 and it.object_width == 5
    batch = next(iter(it))
    assert batch.data[0].shape == (4, 3, 16, 16)
    lab = batch.label[0].asnumpy()
    assert lab.shape == (4, 3, 5)
    # first object valid, pads -1, coordinates normalized
    assert (lab[:, 0, 0] >= 0).all()
    assert ((lab[:, :, 1:] >= -1) & (lab[:, :, 1:] <= 1.0001)).all()


def test_det_augmenters_flip_and_crop():
    from mxnet_tpu.image import detection as det
    img = mx.nd.array((np.arange(3 * 8 * 8) % 255)
                      .reshape(8, 8, 3).astype("uint8"))
    label = np.array([[1, 0.1, 0.2, 0.5, 0.6]], "f")
    flip = det.DetHorizontalFlipAug(p=1.0)
    img2, lab2 = flip(img, label)
    np.testing.assert_allclose(lab2[0, 1], 0.5, atol=1e-6)
    np.testing.assert_allclose(lab2[0, 3], 0.9, atol=1e-6)
    crop = det.DetRandomCropAug(min_object_covered=0.5,
                                area_range=(0.5, 1.0))
    img3, lab3 = crop(img, label.copy())
    assert lab3.shape[1] == 5 and lab3.shape[0] >= 1
    assert (lab3[:, 1:] >= -1e-6).all() and (lab3[:, 1:] <= 1 + 1e-6).all()
    pad = det.DetRandomPadAug(area_range=(1.5, 2.0))
    img4, lab4 = pad(img, label.copy())
    a4 = img4.asnumpy()
    assert a4.shape[0] >= 8 and a4.shape[1] >= 8
    assert a4.shape[0] * a4.shape[1] > 64  # canvas expanded
    w4 = (lab4[0, 3] - lab4[0, 1]) * a4.shape[1]
    np.testing.assert_allclose(w4, 0.4 * 8, rtol=0.3)  # box pixels kept


def test_hue_and_gray_augmenters():
    from mxnet_tpu import image as img_mod
    rng = np.random.RandomState(5)
    src = mx.nd.array((rng.rand(6, 6, 3) * 255).astype("f"))
    gray = img_mod.RandomGrayAug(p=1.0)(src).asnumpy()
    # all channels equal after grayscale
    np.testing.assert_allclose(gray[..., 0], gray[..., 1], rtol=1e-5)
    hue = img_mod.HueJitterAug(hue=0.3)(src).asnumpy()
    assert hue.shape == src.shape
    augs = img_mod.CreateAugmenter((3, 6, 6), hue=0.2, rand_gray=0.5)
    assert any(isinstance(a, img_mod.HueJitterAug) for a in augs)
    assert any(isinstance(a, img_mod.RandomGrayAug) for a in augs)


# ---------------------------------------------------------------------------
# PrefetchingIter: error propagation + reset thread hygiene
# ---------------------------------------------------------------------------

class _ExplodingIter(mx.io.DataIter):
    """Yields `good` batches, then crashes mid-epoch."""

    def __init__(self, good=2, batch_size=4):
        super().__init__()
        self.good = good
        self.batch_size = batch_size
        self.provide_data = [mx.io.DataDesc("data", (batch_size, 2))]
        self.provide_label = []
        self._i = 0

    def reset(self):
        self._i = 0

    def next(self):
        if self._i >= self.good:
            raise RuntimeError("disk died mid-epoch")
        self._i += 1
        return mx.io.DataBatch(
            data=[mx.nd.zeros((self.batch_size, 2))], label=[], pad=0)


def test_prefetch_propagates_producer_error():
    """A crash of the wrapped iterator must surface in iter_next(), not
    masquerade as a clean end-of-epoch (silent data truncation)."""
    it = mx.io.PrefetchingIter(_ExplodingIter(good=2))
    assert it.iter_next()
    assert it.iter_next()
    with pytest.raises(RuntimeError, match="disk died mid-epoch"):
        it.iter_next()


def test_prefetch_error_cleared_by_reset():
    it = mx.io.PrefetchingIter(_ExplodingIter(good=1))
    assert it.iter_next()
    with pytest.raises(RuntimeError):
        it.iter_next()
    it.reset()
    assert it.iter_next()          # fresh epoch serves again
    with pytest.raises(RuntimeError):
        it.iter_next()


def test_prefetch_reset_joins_producer_thread():
    """reset() while the producer is blocked on a FULL queue: the
    stop-aware put lets it exit, the old thread is provably joined, and
    the restarted epoch is complete and in order."""
    X = np.arange(40, dtype=np.float32).reshape(20, 2)
    base = mx.io.NDArrayIter(X, batch_size=2, shuffle=False)
    it = mx.io.PrefetchingIter(base, depth=2)
    time.sleep(0.05)               # let the producer fill the queue
    for _ in range(3):
        old = it._thread
        it.reset()
        assert not old.is_alive()  # no leaked thread feeding a dead queue
    got = [b.data[0].asnumpy() for b in it]
    assert len(got) == 10
    np.testing.assert_array_equal(np.concatenate(got), X)
