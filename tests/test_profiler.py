"""Profiler aggregate statistics + memory profiling (reference
src/profiler/aggregate_stats.cc, storage_profiler.h) and the per-op perf
harness (reference test_utils.py:1133 check_speed,
tests/cpp/operator/coreop_perf.cc)."""
import os
import subprocess
import sys

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import profiler

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture
def agg():
    profiler.set_config(aggregate_stats=True, profile_memory=True)
    profiler.reset_stats()
    yield
    profiler.set_config(aggregate_stats=False, profile_memory=False)
    profiler.reset_stats()


def test_aggregate_stats_table(agg):
    a = mx.nd.ones((32, 32))
    for _ in range(3):
        b = mx.nd.dot(a, a)
    (b + 1).asnumpy()
    table = profiler.dumps()
    assert "Profile Statistics." in table
    assert "dot" in table
    # per-op count column is real
    line = [l for l in table.splitlines() if l.startswith("dot")][0]
    assert int(line.split()[1]) == 3
    # memory section present with positive byte counts
    assert "Memory allocations" in table
    mline = [l for l in table.splitlines()
             if l.startswith("dot") and l in table.split(
                 "Memory allocations")[1]]
    assert mline and int(mline[0].split()[2]) >= 3 * 32 * 32 * 4


def test_dumps_reset(agg):
    mx.nd.ones((4,)).asnumpy()
    (mx.nd.ones((4,)) * 2).asnumpy()
    assert profiler.dumps(reset=True) != ""
    assert profiler.dumps() == ""


def test_dumps_empty_when_disabled():
    profiler.set_config(aggregate_stats=False)
    profiler.reset_stats()
    mx.nd.ones((4,)).asnumpy()
    assert profiler.dumps() == ""


def test_executor_calls_aggregated(agg):
    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data, num_hidden=8, name="fc")
    exe = net.simple_bind(mx.cpu(), data=(4, 16))
    exe.forward(is_train=False)
    exe.forward_backward()
    table = profiler.dumps()
    assert "_executor_forward" in table
    assert "_executor_forward_backward" in table


def test_check_speed_returns_time():
    from mxnet_tpu.test_utils import check_speed
    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data, num_hidden=8, name="fc")
    sec = check_speed(net, ctx=mx.cpu(), N=3, data=(4, 16))
    assert 0 < sec < 10


def test_op_bench_harness_tiny():
    env = dict(os.environ, JAX_PLATFORMS="cpu", PALLAS_AXON_POOL_IPS="",
               PYTHONPATH=REPO)
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "perf", "op_bench.py"),
         "--preset", "tiny", "-N", "2"],
        env=env, capture_output=True, text=True, timeout=900)
    assert out.returncode == 0, out.stdout + out.stderr
    assert "Convolution3x3" in out.stdout
    assert "FAIL" not in out.stdout, out.stdout
    # one JSON line per op for regression diffing
    import json
    json_lines = [l for l in out.stdout.splitlines()
                  if l.startswith('{"metric": "op_us"')]
    assert len(json_lines) >= 10
    assert all(json.loads(l)["us_per_iter"] > 0 for l in json_lines)


# ---------------------------------------------------------------------------
# XPlane device-time attribution (reference engine-instrumented aggregate
# stats, src/profiler/aggregate_stats.cc + src/engine/threaded_engine.h:80)
# ---------------------------------------------------------------------------

def test_xplane_device_time_table(tmp_path):
    import jax
    import jax.numpy as jnp
    from mxnet_tpu import xplane

    logdir = str(tmp_path / "trace")
    jax.profiler.start_trace(logdir)
    f = jax.jit(lambda x, w: jnp.tanh(x @ w) @ w.T)
    w = jnp.ones((256, 256), jnp.float32)
    x = jnp.ones((128, 256), jnp.float32)
    for _ in range(4):
        x = f(x, w)
    jax.block_until_ready(x)
    jax.profiler.stop_trace()

    files = xplane.find_xplane_files(logdir)
    assert files, "trace capture produced no .xplane.pb"

    # the HLO execution line must show the matmul with nonzero device time
    table = xplane.op_table(logdir, line_filter="PjRtCpuClient")
    dots = [k for k in table if "dot" in k or "fusion" in k]
    assert dots, f"no dot/fusion op in table: {sorted(table)[:20]}"
    assert all(table[k]["total_ps"] > 0 for k in dots)

    # rendered table is non-empty and carries the share column
    txt = xplane.dumps(logdir, line_filter="PjRtCpuClient", top=10)
    assert "Total (ms)" in txt and "%" in txt

    # profiler front door
    out = profiler.device_dumps(logdir, line_filter="PjRtCpuClient")
    assert out == txt


def test_xplane_cli(tmp_path):
    import jax
    import jax.numpy as jnp

    logdir = str(tmp_path / "trace")
    jax.profiler.start_trace(logdir)
    jax.block_until_ready(jax.jit(lambda a: a @ a)(jnp.ones((64, 64))))
    jax.profiler.stop_trace()

    out = subprocess.run(
        [sys.executable, "-m", "mxnet_tpu.xplane", logdir, "--top", "5",
         "--json", str(tmp_path / "t.json")],
        capture_output=True, text=True,
        env={**os.environ, "JAX_PLATFORMS": "cpu", "PALLAS_AXON_POOL_IPS": ""},
        cwd=REPO, timeout=120)
    assert out.returncode == 0, out.stderr
    assert "TOTAL" in out.stdout
    assert (tmp_path / "t.json").exists()
