"""Custom Python operator tests (mirror reference
tests/python/unittest/test_operator.py::test_custom_op)."""
import numpy as np
import pytest

import mxnet_tpu as mx


@mx.operator.register("tsigmoid")
class TSigmoidProp(mx.operator.CustomOpProp):
    def __init__(self):
        super().__init__(need_top_grad=True)

    def list_arguments(self):
        return ["data"]

    def list_outputs(self):
        return ["output"]

    def infer_shape(self, in_shape):
        return in_shape, [in_shape[0]], []

    def create_operator(self, ctx, shapes, dtypes):
        return TSigmoid()


class TSigmoid(mx.operator.CustomOp):
    def forward(self, is_train, req, in_data, out_data, aux):
        x = in_data[0].asnumpy()
        self.assign(out_data[0], req[0], 1.0 / (1.0 + np.exp(-x)))

    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
        y = out_data[0].asnumpy()
        g = out_grad[0].asnumpy()
        self.assign(in_grad[0], req[0], g * y * (1.0 - y))


@mx.operator.register("tsplit2")
class TSplit2Prop(mx.operator.CustomOpProp):
    def list_arguments(self):
        return ["data"]

    def list_outputs(self):
        return ["a", "b"]

    def infer_shape(self, in_shape):
        return in_shape, [in_shape[0], in_shape[0]], []

    def create_operator(self, ctx, shapes, dtypes):
        return TSplit2()


class TSplit2(mx.operator.CustomOp):
    def forward(self, is_train, req, in_data, out_data, aux):
        x = in_data[0].asnumpy()
        self.assign(out_data[0], req[0], x * 2)
        self.assign(out_data[1], req[1], x + 1)

    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
        self.assign(in_grad[0], req[0],
                    out_grad[0].asnumpy() * 2 + out_grad[1].asnumpy())


def test_custom_forward_eager():
    x = mx.nd.array(np.asarray([[-1.0, 0.0, 2.0]], np.float32))
    y = mx.nd.Custom(x, op_type="tsigmoid")
    np.testing.assert_allclose(y.asnumpy(),
                               1 / (1 + np.exp(-x.asnumpy())), rtol=1e-6)


def test_custom_backward_autograd():
    x = mx.nd.array(np.asarray([[-1.0, 0.5, 2.0]], np.float32))
    x.attach_grad()
    with mx.autograd.record():
        y = mx.nd.Custom(x, op_type="tsigmoid")
        loss = y.sum()
    loss.backward()
    s = 1 / (1 + np.exp(-x.asnumpy()))
    np.testing.assert_allclose(x.grad.asnumpy(), s * (1 - s), rtol=1e-5)


def test_custom_multi_output():
    xv = np.arange(4, dtype=np.float32).reshape(2, 2)
    a, b = mx.nd.Custom(mx.nd.array(xv), op_type="tsplit2")
    np.testing.assert_allclose(a.asnumpy(), xv * 2)
    np.testing.assert_allclose(b.asnumpy(), xv + 1)


def test_custom_in_symbol_executor():
    data = mx.sym.Variable("data")
    out = mx.sym.Custom(data, op_type="tsigmoid", name="sig")
    ex = out.simple_bind(mx.cpu(), data=(2, 3))
    xv = np.random.RandomState(0).randn(2, 3).astype(np.float32)
    ex.forward(is_train=True, data=mx.nd.array(xv))
    np.testing.assert_allclose(ex.outputs[0].asnumpy(),
                               1 / (1 + np.exp(-xv)), rtol=1e-5)
    ex.backward(mx.nd.ones((2, 3)))
    s = 1 / (1 + np.exp(-xv))
    np.testing.assert_allclose(ex.grad_arrays[0].asnumpy(), s * (1 - s),
                               rtol=1e-5)


def test_custom_unregistered_raises():
    with pytest.raises(mx.MXNetError):
        mx.nd.Custom(mx.nd.ones((1,)), op_type="definitely_missing")
