"""Strided-1x1 conv dgrad Pallas kernel + custom-VJP conv paths.

Oracle is jax.vjp through the plain `lax.conv_general_dilated` lowering —
the same cross-check the reference applies to its cuDNN conv backward
(`tests/python/gpu/test_operator_gpu.py` check_consistency).
"""
import os

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from mxnet_tpu.ops.conv_kernels import conv1x1_s2_dgrad
from mxnet_tpu.ops import nn as nn_ops


def _xla_dgrad(dy, w2, H, W):
    """Oracle: vjp of the stride-2 NHWC 1x1 conv wrt its input."""
    N, Ho, Wo, K = dy.shape
    C = w2.shape[1]
    w4 = w2.reshape(K, 1, 1, C)
    x = jnp.zeros((N, H, W, C), dy.dtype)
    dn = jax.lax.conv_dimension_numbers(x.shape, w4.shape,
                                        ("NHWC", "OHWI", "NHWC"))
    f = lambda d: jax.lax.conv_general_dilated(
        d, w4, window_strides=(2, 2), padding=[(0, 0), (0, 0)],
        dimension_numbers=dn)
    _, vjp = jax.vjp(f, x)
    return vjp(dy)[0]


@pytest.mark.parametrize("shape", [
    (4, 4, 4, 256, 128),      # (N, Ho, Wo, K, C): tiny c3-entry-like
    (2, 7, 7, 256, 128),      # odd spatial extents, c5-downsample-like
    (8, 2, 2, 128, 256),      # bn-blocking exercised (N > picked bn)
])
@pytest.mark.parametrize("dtype", [np.float32, jnp.bfloat16])
def test_conv1x1_s2_dgrad_matches_xla(shape, dtype):
    N, Ho, Wo, K, C = shape
    rng = np.random.RandomState(0)
    dy = jnp.asarray(rng.randn(N, Ho, Wo, K), dtype)
    w2 = jnp.asarray(rng.randn(K, C), dtype)
    got = conv1x1_s2_dgrad(dy, w2, 2 * Ho, 2 * Wo)
    want = _xla_dgrad(dy, w2, 2 * Ho, 2 * Wo)
    tol = 1e-4 if dtype == np.float32 else 2e-2
    np.testing.assert_allclose(np.asarray(got, np.float32),
                               np.asarray(want, np.float32),
                               rtol=tol, atol=tol)
    # the interleave: odd rows/cols must be exactly zero
    g = np.asarray(got, np.float32)
    assert np.all(g[:, 1::2, :, :] == 0) and np.all(g[:, :, 1::2, :] == 0)


def _conv_op(params, data, weight):
    return nn_ops._convolution(params, data, weight)[0]


def _check_conv_gate(env, val, stride, shapes, tol=1e-4):
    """Gated conv path vs default XLA path: forward AND both gradients."""
    N, H, W, C, K = shapes
    rng = np.random.RandomState(1)
    x = jnp.asarray(rng.randn(N, H, W, C).astype(np.float32))
    w = jnp.asarray(rng.randn(K, 1, 1, C).astype(np.float32))
    params = {"kernel": (1, 1), "stride": stride, "no_bias": True,
              "layout": "NHWC", "num_filter": K}

    def loss(x, w):
        return jnp.sum(_conv_op(params, x, w) ** 2)

    old = os.environ.get(env)
    try:
        os.environ[env] = "0"
        want_y = _conv_op(params, x, w)
        want_g = jax.grad(loss, argnums=(0, 1))(x, w)
        os.environ[env] = val
        got_y = _conv_op(params, x, w)
        got_g = jax.grad(loss, argnums=(0, 1))(x, w)
    finally:
        if old is None:
            os.environ.pop(env, None)
        else:
            os.environ[env] = old
    np.testing.assert_allclose(np.asarray(got_y), np.asarray(want_y),
                               rtol=tol, atol=tol)
    for a, b in zip(got_g, want_g):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=tol, atol=tol)


def test_conv1x1_pallas_gate_grads_match():
    _check_conv_gate("MXNET_CONV1X1_PALLAS", "1", (2, 2),
                     (2, 8, 8, 128, 64), tol=2e-3)


def test_conv1x1_s1dot_gate_grads_match():
    _check_conv_gate("MXNET_CONV1X1_S1DOT", "64", (1, 1),
                     (2, 8, 8, 128, 64), tol=2e-3)


def test_conv1x1_pallas_gate_ineligible_shapes_fall_back():
    # C not lane-aligned: gate must decline (and still be correct)
    _check_conv_gate("MXNET_CONV1X1_PALLAS", "1", (2, 2),
                     (2, 8, 8, 96, 64), tol=2e-3)
